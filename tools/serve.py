"""HTTP serving front end over the pipelined decode executors.

Beyond-reference serving surface (the reference runtime is single-shot
batch inference; SURVEY.md §2.4): a stdlib-only JSON/HTTP server that
drives a `ContinuousBatcher` (wave executor) or a `StageWorkerExecutor`
(one worker thread pinned per pipeline stage) continuously — requests
admit as they arrive, share the pipeline, and prompt prefixes
registered once via /prefix are reused by any number of /generate
requests (prompt caching).

Endpoints (all JSON unless noted):
- GET  /healthz            -> {"ok", "model", "stages", "speculative",
                               "executor", "degraded": false | {"dead_rank",
                               "since_s", "retry_after"},
                               "stats": {tokens, active,
                               pending, prefixes,
                               degraded_entered_total,
                               failover_replays_total,
                               rejoined_ranks_total, last_dead_rank, ...;
                               stage mode adds per-worker
                               stage_steps/busy/queued}};
                               the degraded object carries a "phase"
                               ("degraded" | "healing");
                               HTTP 503 once a serving worker has died
- GET  /metrics            -> Prometheus text format (the observability
                              plane, docs/OBSERVABILITY.md): request count/
                              latency histogram, tokens served, per-edge
                              activation wire-byte counters, degraded/
                              failover counters — plus every monitoring
                              key's (instant|window|global) matrix as
                              gauges when a monitoring session is open,
                              and whatever the runtime's DCN hooks fed
                              into the shared registry (wire bytes,
                              negotiated edge bitwidths, heartbeats)
- POST /degraded {"degraded": bool, "dead_rank"?: n, "retry_after"?: s,
                  "healing"?: bool, "healed"?: bool, "rank"?: n}
                           -> {"degraded": bool} — the failover
                              orchestrator's hook: while degraded, new
                              work is answered 503 + Retry-After and
                              /healthz names the dead rank; an in-flight
                              request whose executor fails during the
                              window is replayed once after recovery.
                              Lifecycle (docs/FAULT_TOLERANCE.md): the
                              orchestrator posts {"degraded": true, ...}
                              at the death, {"degraded": true, "healing":
                              true} once the rank rejoins (window still
                              open, /healthz phase flips to "healing"),
                              and {"degraded": false, "healed": true,
                              "rank": n} when capacity is restored — that
                              last form clears the window AND counts the
                              rank on pipeedge_serve_rejoined_ranks_total
- POST /prefix   {"ids": [t0, t1, ...]}
                           -> {"prefix_id": "p0", "len": N}
- POST /generate {"ids": [[...], ...] | [...], "new_tokens": N,
                  "temperature"?: f, "top_k"?: n, "seed"?: n,
                  "eos_token"?: n, "prefix_id"?: "p0",
                  "stream"?: true, "speculative"?: true}
                           -> {"ids": [[prompt+continuation], ...]}
                              (suffix+continuation when prefix_id given)

With `"stream": true` the response is chunked `application/x-ndjson`:
one line per decode step `{"step": i, "tokens": [[...]]}` as the token
lands (raw picked tokens — post-eos rows are NOT yet masked), then a
final line `{"ids": ..., "first_token_ms": t, "steps": n}` carrying the
authoritative (eos-masked) result, identical to the non-streaming
response. First-token latency is measured server-side from request
receipt to the first step's readback.

Executors (`--executor`):
- `wave` (default): one worker thread ticks the batcher
  (`ContinuousBatcher`) — strict wave semantics, JAX async dispatch
  keeps every stage busy from a single host thread.
- `stage`: one worker thread PER pipeline stage
  (`StageWorkerExecutor`) — host-side dispatch of different stages
  overlaps, and the last stage's token picks / eos readbacks never
  stall earlier stages' dispatch. healthz reports per-worker stats.

Speculative requests (`"speculative": true`, needs --draft-model) run
greedy draft/verify rounds under a DEDICATED lock: they serialize with
each other (bounding draft+verify cache memory at one in-flight
speculative generation) but NOT with plain requests or result waits —
JAX dispatch is thread-safe, so the batcher keeps serving while a
speculative generation runs (round-4 advice).

Tokens are identical to solo `DecodePipeline.generate` runs with the
same settings — the executors' shared contract (tests/test_serve.py).

Usage: python tools/serve.py -m gpt2 [--port 8321] [--executor stage] ...
"""
import argparse
import json
import os
import queue as queue_mod
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pipeedge_tpu import telemetry  # noqa: E402
from pipeedge_tpu.telemetry import metrics as prom  # noqa: E402


class ServiceDegraded(RuntimeError):
    """The service is in a failover window (a backing stage died): new
    work should come back later instead of queueing into the hole."""

    def __init__(self, dead_rank, retry_after: float):
        where = f" (rank {dead_rank} dead)" if dead_rank is not None else ""
        super().__init__(
            f"service degraded during failover{where}; retry after "
            f"{retry_after:g}s")
        self.dead_rank = dead_rank
        self.retry_after = retry_after


class _Service:
    """Owns the pipeline + executor; HTTP handler threads submit requests
    and wait for (or stream) their results."""

    def __init__(self, pipe, max_active=None, max_prefixes=8, spec=None,
                 executor="wave", edge_itemsize=2):
        from collections import OrderedDict

        from pipeedge_tpu.parallel.batcher import (ContinuousBatcher,
                                                   StageWorkerExecutor)
        self.pipe = pipe
        self.spec = spec
        self.executor = executor
        self.cond = threading.Condition()
        # -- /metrics + healthz counters (one source of truth) ----------
        # the registry instruments below ARE the state: healthz's stats
        # read them back (stats()), so both surfaces always agree — even
        # across a _Service rebuild in the same process (get_or_create
        # returns the surviving instruments)
        self._edge_itemsize = int(edge_itemsize)
        self.m_requests = prom.REGISTRY.counter(
            "pipeedge_serve_requests_total",
            "generate requests by endpoint and outcome status")
        self.m_tokens = prom.REGISTRY.counter(
            "pipeedge_serve_tokens_total", "tokens generated (rows x steps)")
        self.m_latency = prom.REGISTRY.histogram(
            "pipeedge_serve_request_latency_seconds",
            "end-to-end generate latency (request receipt -> result)")
        self.m_degraded = prom.REGISTRY.counter(
            "pipeedge_serve_degraded_entered_total",
            "failover windows opened via POST /degraded")
        self.m_replays = prom.REGISTRY.counter(
            "pipeedge_serve_failover_replays_total",
            "in-flight requests replayed after a degraded window closed")
        self.m_rejoined = prom.REGISTRY.counter(
            "pipeedge_serve_rejoined_ranks_total",
            "degraded windows closed as HEALED (capacity restored by a "
            "rank rejoining), by rank")
        self.m_last_dead = prom.REGISTRY.gauge(
            "pipeedge_serve_last_dead_rank",
            "rank named by the most recent degraded window (-1 = none)")
        self.m_last_dead.set(-1)
        # distinct name from runtime.py's pipeedge_edge_wire_bytes_total
        # (measured DCN socket bytes, direction/peer labels): these are
        # estimated device-edge activation bytes — merging the two under
        # one family would let sum() silently add different quantities
        self.m_edge_bytes = prom.REGISTRY.counter(
            "pipeedge_serve_edge_wire_bytes_total",
            "per-edge activation bytes moved by completed requests "
            "(prefill + decode steps, estimated from shapes)")
        # the full per-edge matrix renders from the first scrape, not the
        # first request
        for i in range(len(pipe.stages) - 1):
            self.m_edge_bytes.declare(edge=f"{i}->{i + 1}")
        # speculative generations hold THIS lock, not self.cond: plain
        # requests and result waits proceed concurrently (the pipeline's
        # jitted programs are thread-safe; serializing speculative
        # requests with each other bounds their cache memory)
        self.spec_lock = threading.Lock()
        self.prefixes = OrderedDict()   # LRU-bounded: handles hold full
        self.spec_prefixes = OrderedDict()   # max_len KV buffers
        self.max_prefixes = max_prefixes
        self._next_rid = 0
        self._next_pid = 0
        self._stop = False
        self._dead: Optional[BaseException] = None
        # failover window (enter_degraded/exit_degraded): while set, new
        # work is refused with 503 + Retry-After and healthz reports the
        # dead rank; unlike `_dead` it is expected to clear
        self.degraded_info: Optional[dict] = None
        if executor == "stage":
            self.exec = StageWorkerExecutor(pipe, max_active=max_active)
            self.batcher = None
            self.worker = None
        elif executor == "wave":
            self.exec = None
            self.batcher = ContinuousBatcher(pipe, max_active=max_active)
            self.worker = threading.Thread(target=self._loop, daemon=True)
            self.worker.start()
        else:
            raise ValueError(f"unknown executor {executor!r} "
                             "(expected 'wave' or 'stage')")

    def _loop(self):
        while True:
            with self.cond:
                while not self._stop and not (
                        self.batcher.pending or self.batcher.active):
                    self.cond.wait()
                if self._stop:
                    return
                try:
                    self.batcher.tick()
                except BaseException as exc:   # noqa: BLE001 — a wedged
                    # worker would hang every waiter forever; record the
                    # failure so they raise instead
                    self._dead = exc
                    self.cond.notify_all()
                    raise
                if self.batcher.results:
                    self.cond.notify_all()

    @property
    def dead(self) -> Optional[BaseException]:
        if self._dead is not None:
            return self._dead
        return self.exec._dead if self.exec is not None else None

    def add_prefix(self, ids):
        with self.cond:
            self._check_admittable()
            # precompute BOTH handles before registering either, so a
            # draft-side failure cannot leave a half-registered prefix
            # (usable plainly, 400ing speculatively). The target handle
            # is shared — the draft model's K/V is the only extra state.
            target = self.pipe.precompute_prefix(ids)
            draft = (self.spec.draft.precompute_prefix(ids)
                     if self.spec is not None else None)
            pid = f"p{self._next_pid}"
            self._next_pid += 1
            self.prefixes[pid] = target
            if draft is not None:
                self.spec_prefixes[pid] = {"target": target,
                                           "draft": draft}
            while len(self.prefixes) > self.max_prefixes:
                old, _ = self.prefixes.popitem(last=False)  # evict oldest
                self.spec_prefixes.pop(old, None)
            return pid, target["len"]

    def _check_dead(self):
        dead = self.dead
        if dead is not None:
            raise RuntimeError(f"serving worker died: {dead!r}")

    # -- failover window ------------------------------------------------

    def enter_degraded(self, dead_rank=None, retry_after: float = 5.0):
        """Open a failover window: admission refuses new work with
        503 + Retry-After until `exit_degraded` (the orchestrator's signal
        that the backing pipeline recovered)."""
        with self.cond:
            self.degraded_info = {"dead_rank": dead_rank,
                                  "since": time.monotonic(),
                                  "retry_after": float(retry_after),
                                  "phase": "degraded"}
            self.cond.notify_all()
        self.m_degraded.inc()
        if dead_rank is not None:
            self.m_last_dead.set(int(dead_rank))

    def mark_healing(self):
        """The dead rank rejoined and the orchestrator is restoring the
        partition: the window stays open (new work still bounces with
        Retry-After — the heal lands at a round boundary, not instantly),
        but /healthz distinguishes `healing` from plain `degraded`. A
        no-op when no window is open (a stray healing signal must not
        resurrect a closed window)."""
        with self.cond:
            if self.degraded_info is not None:
                self.degraded_info["phase"] = "healing"
                self.cond.notify_all()

    def exit_degraded(self, healed: bool = False, rank=None):
        """Close the window. `healed=True` records the close as a
        capacity restoration (the orchestrator's {"degraded": false,
        "healed": true} form) on pipeedge_serve_rejoined_ranks_total —
        distinct from a plain manual clear."""
        with self.cond:
            was_open = self.degraded_info is not None
            self.degraded_info = None
            self.cond.notify_all()
        if healed and was_open:
            # unlabeled on purpose: healthz stats() reads the same series
            # back (value() is per-label-set); the healed rank stays
            # visible as last_dead_rank history
            self.m_rejoined.inc()

    def _check_admittable(self):
        deg = self.degraded_info
        if deg is not None:
            raise ServiceDegraded(deg["dead_rank"], deg["retry_after"])

    def _await_recovery(self) -> bool:
        """Block until the degraded window closes (True) or its retry
        budget runs out / the worker is truly dead (False). The replay
        gate for a request that was in flight when the failover began."""
        with self.cond:
            deg = self.degraded_info
            if deg is None:
                return False   # the failure was not a failover window
            deadline = time.monotonic() + 2 * deg["retry_after"]
            while self.degraded_info is not None:
                left = deadline - time.monotonic()
                if left <= 0 or self.dead is not None:
                    return False
                self.cond.wait(timeout=min(0.5, left))
            return True

    def generate_speculative(self, ids, new_tokens, prefix_id=None):
        """Greedy speculative decoding (token-identical to plain greedy;
        the draft only changes the dispatch count). Holds only the
        dedicated spec lock during the generation — concurrent plain
        requests keep flowing through the executor."""
        t0 = time.monotonic()
        try:
            out = self._generate_speculative_once(ids, new_tokens,
                                                  prefix_id)
        except ServiceDegraded:
            self.m_requests.inc(endpoint="/generate-speculative",
                                status="503")
            raise
        except BaseException:
            self.m_requests.inc(endpoint="/generate-speculative",
                                status="error")
            raise
        self.m_latency.observe(time.monotonic() - t0)
        self.m_requests.inc(endpoint="/generate-speculative", status="200")
        self.m_tokens.inc(len(ids) * int(new_tokens))
        self._account_edge_bytes(ids, int(new_tokens))
        return out

    def _generate_speculative_once(self, ids, new_tokens, prefix_id):
        import numpy as np
        if self.spec is None:
            raise KeyError("server started without --draft-model; "
                           "speculative generation unavailable")
        with self.cond:                     # resolve prefix briefly
            self._check_dead()
            self._check_admittable()
            prefix = None
            if prefix_id is not None:
                if prefix_id not in self.spec_prefixes:
                    raise KeyError(
                        f"unknown prefix_id {prefix_id!r} for speculative "
                        "generation (register via /prefix while the "
                        "draft model is configured)")
                self.prefixes.move_to_end(prefix_id)   # LRU touch
                prefix = self.spec_prefixes[prefix_id]
        with self.spec_lock, telemetry.span("serve", "speculative"):
            return np.asarray(self.spec.generate(ids, new_tokens,
                                                 prefix=prefix))

    def prevalidate(self, ids, new_tokens, kw) -> dict:
        """Resolve prefix_id and run the full admission validation WITHOUT
        submitting — the streaming path needs errors raised BEFORE the
        200/chunked headers commit (a status-checking client must see
        400, not a 200 whose body is an error line). Returns `kw` with
        the prefix handle resolved in place of prefix_id."""
        from pipeedge_tpu.parallel.batcher import _build_request
        kw = dict(kw)
        with self.cond:
            self._check_dead()
            self._check_admittable()
            self._resolve_prefix(kw)
        _build_request(self.pipe, "__prevalidate__", ids, new_tokens,
                       kw.get("temperature", 0.0), kw.get("top_k", 0),
                       kw.get("seed", 0), kw.get("eos_token"),
                       kw.get("pad_token"), kw.get("prefix"))
        return kw

    def _resolve_prefix(self, kw):
        pid = kw.pop("prefix_id", None)
        if pid is not None:
            if pid not in self.prefixes:
                raise KeyError(f"unknown prefix_id {pid!r} (evicted "
                               "or never registered)")
            self.prefixes.move_to_end(pid)     # LRU touch
            kw["prefix"] = self.prefixes[pid]

    def generate(self, ids, new_tokens, on_token=None, **kw):
        t0 = time.monotonic()
        try:
            with telemetry.span("serve", "generate"):
                out = self._generate_policied(ids, new_tokens, on_token, kw)
        except ServiceDegraded:
            self.m_requests.inc(endpoint="/generate", status="503")
            raise
        except BaseException:
            self.m_requests.inc(endpoint="/generate", status="error")
            raise
        self.m_latency.observe(time.monotonic() - t0)
        self.m_requests.inc(endpoint="/generate", status="200")
        self.m_tokens.inc(len(ids) * int(new_tokens))
        self._account_edge_bytes(ids, int(new_tokens))
        return out

    def _generate_policied(self, ids, new_tokens, on_token, kw):
        with self.cond:
            self._check_dead()
            self._check_admittable()   # degraded: 503 + Retry-After
        try:
            return self._generate_once(ids, new_tokens, on_token, kw)
        except ServiceDegraded:
            raise
        except RuntimeError:
            # the executor failed while a failover window was open: the
            # request was in flight when the stage died. Replay it once
            # after recovery instead of surfacing the transient — except
            # streamed requests, whose partial output cannot be unsent.
            if on_token is not None or not self._await_recovery():
                raise
            self.m_replays.inc()
            return self._generate_once(ids, new_tokens, on_token, kw)

    def _account_edge_bytes(self, ids, new_tokens: int) -> None:
        """Per-edge activation traffic of one completed request: every
        inter-stage boundary moves a [B, S, H] prefill payload plus a
        [B, 1, H] payload per decode step (host-driven device edges — the
        serving analogue of the DCN wire counters)."""
        n_edges = len(self.pipe.stages) - 1
        if n_edges <= 0:
            return
        hidden = getattr(self.pipe.cfg, "hidden_size", 0)
        prompt_len = max(len(r) for r in ids) if ids else 0
        per_edge = (len(ids) * (prompt_len + max(0, new_tokens - 1))
                    * hidden * self._edge_itemsize)
        for i in range(n_edges):
            self.m_edge_bytes.inc(per_edge, edge=f"{i}->{i + 1}")

    def _generate_once(self, ids, new_tokens, on_token, kw):
        if self.exec is not None:
            with self.cond:
                self._check_dead()
                self._resolve_prefix(kw)
                rid = self._next_rid
                self._next_rid += 1
            self.exec.submit(rid, ids, new_tokens, on_token=on_token, **kw)
            return self.exec.wait(rid)
        with self.cond:
            self._check_dead()
            self._resolve_prefix(kw)
            rid = self._next_rid
            self._next_rid += 1
            self.batcher.submit(rid, ids, new_tokens, on_token=on_token,
                                **kw)
            self.cond.notify_all()
            while rid not in self.batcher.results:
                self._check_dead()
                self.cond.wait()
            return self.batcher.results.pop(rid)

    def stats(self):
        """Lock-free best-effort snapshot for /healthz (GIL-atomic reads;
        momentary inconsistency is fine for health)."""
        if self.exec is not None:
            s = self.exec.snapshot()
            s["pending"] = 0          # admission blocks in submit threads
            s["prefixes"] = len(self.prefixes)
        else:
            s = dict(self.batcher.stats,
                     active=self.batcher.active,
                     pending=len(self.batcher.pending),
                     prefixes=len(self.prefixes))
        # degraded/failover history: read back from the SAME registry
        # instruments /metrics renders, so the two surfaces cannot diverge
        s["degraded_entered_total"] = int(self.m_degraded.value())
        s["failover_replays_total"] = int(self.m_replays.value())
        s["rejoined_ranks_total"] = int(self.m_rejoined.value())
        last = self.m_last_dead.value()
        s["last_dead_rank"] = (None if last is None or last < 0
                               else int(last))
        return s

    def stop(self):
        with self.cond:
            self._stop = True
            self.cond.notify_all()
        if self.exec is not None:
            self.exec.stop()


def make_handler(service, model_name):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"      # chunked transfer needs 1.1

        def log_message(self, *a):      # quiet server
            pass

        def _send(self, code, obj, headers=()):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for name, value in headers:
                self.send_header(name, value)
            self.end_headers()
            self.wfile.write(body)

        def _chunk(self, obj):
            data = json.dumps(obj).encode() + b"\n"
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            self.wfile.flush()

        def _stream_generate(self, ids, new_tokens, kw):
            """Chunked x-ndjson response: one line per decode step as the
            token lands, then the authoritative final line. The worker
            pushes DEVICE token arrays into a queue; the readback (the
            blocking part) happens here in the handler thread, so
            streaming never stalls the executor.

            A client that disconnects mid-stream (write fails) sets the
            request's `cancel` flag: the executor completes the request
            at its next pick instead of decoding to the cap, so dead
            requests free their admission slot / cache memory early
            (repeated disconnects could otherwise occupy every
            max_active slot with vanished clients)."""
            import numpy as np
            t0 = time.monotonic()
            # validate BEFORE headers commit: bad requests still 400
            # (raises into do_POST's error mapping); after this point
            # failures surface as a terminal {"error": ...} stream line
            kw = service.prevalidate(ids, new_tokens, kw)
            cancel = threading.Event()
            kw["cancel"] = cancel
            q = queue_mod.Queue()
            worker = threading.Thread(
                target=self._run_generate,
                args=(ids, new_tokens, kw, q), daemon=True)
            worker.start()
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            steps = 0
            first_ms = None
            while True:
                kind, payload = q.get()
                if kind in ("error", "result"):
                    final = ({"error": str(payload)} if kind == "error"
                             else {"ids": payload.tolist(),
                                   "first_token_ms": first_ms,
                                   "steps": steps})
                    if not cancel.is_set():
                        try:
                            self._chunk(final)
                        except OSError:
                            cancel.set()
                    break
                step, token = payload
                # the blocking device readback happens HERE, in the
                # handler thread — the executor worker only enqueued the
                # device array and moved on
                tok = np.asarray(token).tolist()
                if first_ms is None:
                    first_ms = round((time.monotonic() - t0) * 1e3, 3)
                if not cancel.is_set():
                    try:
                        self._chunk({"step": step, "tokens": tok})
                    except OSError:
                        # client went away: cancel the generation but keep
                        # draining the queue until the worker's terminal
                        # result/error (it completes early at its next
                        # pick, releasing the executor slot)
                        cancel.set()
                steps += 1
            if not cancel.is_set():
                try:
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                except OSError:
                    pass    # disconnect after the final line: nothing owed

        def _run_generate(self, ids, new_tokens, kw, q):
            try:
                out = service.generate(
                    ids, new_tokens,
                    on_token=lambda step, tok: q.put(("token", (step, tok))),
                    **kw)
                q.put(("result", out))
            except BaseException as exc:   # noqa: BLE001 — surfaced as a
                q.put(("error", exc))      # terminal stream line

        def do_GET(self):
            if self.path == "/metrics":
                import monitoring
                extra = prom.render_monitoring_snapshot(
                    monitoring.snapshot())
                body = prom.REGISTRY.render(extra=extra).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path == "/healthz":
                dead = service.dead is not None
                deg = service.degraded_info
                degraded = False
                if deg is not None:
                    degraded = {"dead_rank": deg["dead_rank"],
                                "since_s": round(time.monotonic()
                                                 - deg["since"], 3),
                                "retry_after": deg["retry_after"],
                                # "degraded" (hole open) vs "healing"
                                # (rank rejoined, restore in progress)
                                "phase": deg.get("phase", "degraded")}
                self._send(503 if dead else 200,
                           {"ok": not dead, "model": model_name,
                            "stages": len(service.pipe.stages),
                            "speculative": service.spec is not None,
                            "executor": service.executor,
                            "degraded": degraded,
                            "stats": service.stats()})
            else:
                self._send(404, {"error": "unknown path"})

        def do_POST(self):
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                if self.path == "/degraded":
                    # the failover orchestrator's switch (see module doc):
                    # degraded -> healing -> healed lifecycle
                    if req.get("degraded", True):
                        if req.get("healing"):
                            service.mark_healing()
                        else:
                            service.enter_degraded(
                                dead_rank=req.get("dead_rank"),
                                retry_after=float(req.get("retry_after",
                                                          5)))
                    else:
                        service.exit_degraded(
                            healed=bool(req.get("healed")),
                            rank=req.get("rank"))
                    self._send(200, {"degraded":
                                     service.degraded_info is not None})
                elif self.path == "/prefix":
                    pid, plen = service.add_prefix(req["ids"])
                    self._send(200, {"prefix_id": pid, "len": plen})
                elif self.path == "/generate":
                    ids = req["ids"]
                    if ids and not isinstance(ids[0], list):
                        ids = [ids]
                    if req.get("speculative"):
                        if req.get("temperature") or req.get("top_k") \
                                or req.get("eos_token") is not None \
                                or req.get("stream"):
                            raise ValueError(
                                "speculative generation is greedy-exact "
                                "whole-rounds; it does not compose with "
                                "sampling/eos/stream")
                        out = service.generate_speculative(
                            ids, int(req["new_tokens"]),
                            prefix_id=req.get("prefix_id"))
                        self._send(200, {"ids": out.tolist()})
                    else:
                        kw = dict(
                            temperature=float(req.get("temperature", 0.0)),
                            top_k=int(req.get("top_k", 0)),
                            seed=int(req.get("seed", 0)),
                            eos_token=req.get("eos_token"),
                            prefix_id=req.get("prefix_id"))
                        if req.get("stream"):
                            self._stream_generate(
                                ids, int(req["new_tokens"]), kw)
                        else:
                            out = service.generate(
                                ids, int(req["new_tokens"]), **kw)
                            self._send(200, {"ids": out.tolist()})
                else:
                    self._send(404, {"error": "unknown path"})
            except (KeyError, ValueError, TypeError, IndexError) as exc:
                self._send(400, {"error": str(exc)})
            except ServiceDegraded as exc:
                # a degraded window is transient by contract: tell the
                # client exactly when to come back instead of hanging it
                self._send(503, {"error": str(exc),
                                 "degraded": True,
                                 "dead_rank": exc.dead_rank},
                           headers=(("Retry-After",
                                     f"{exc.retry_after:g}"),))
            except RuntimeError as exc:
                self._send(503, {"error": str(exc)})

    return Handler


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-m", "--model-name", default="gpt2")
    p.add_argument("-pt", "--partition", default=None)
    p.add_argument("--max-len", default=1024, type=int)
    p.add_argument("-t", "--dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    p.add_argument("--kv-bits", default=0, type=int, choices=[0, 8])
    p.add_argument("--attend-floor", default=64, type=int)
    p.add_argument("--executor", default="wave", choices=["wave", "stage"],
                   help="wave: one thread ticks the batcher; stage: one "
                        "worker thread pinned per pipeline stage "
                        "(healthz reports per-worker stats)")
    p.add_argument("--draft-model", default=None,
                   help="enable speculative generation: requests with "
                        '"speculative": true run greedy draft/verify '
                        "rounds against this (smaller, same-vocabulary) "
                        "model — token-identical to plain greedy")
    p.add_argument("--gamma", default=4, type=int,
                   help="speculative draft lookahead per round")
    p.add_argument("--max-active", default=None, type=int)
    p.add_argument("--max-prefixes", default=8, type=int,
                   help="LRU bound on registered prompt prefixes (each "
                        "handle retains full max_len KV buffers)")
    p.add_argument("--port", default=8321, type=int)
    p.add_argument("--trace-spans", default=None, metavar="OUT",
                   help="record request/stage spans and write a Perfetto-"
                        "loadable trace JSON to OUT on shutdown "
                        "(tools/trace_report.py analyzes it)")
    args = p.parse_args()

    from pipeedge_tpu.utils import apply_env_platform
    apply_env_platform()
    import jax.numpy as jnp

    from pipeedge_tpu.parallel.decode import build_decode_pipeline

    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    partition = None
    if args.partition:
        nums = [int(x) for x in args.partition.split(",")]
        partition = list(zip(nums[::2], nums[1::2]))
    pipe = build_decode_pipeline(
        args.model_name, partition, max_len=args.max_len, dtype=dtype,
        cache_bits=args.kv_bits, attend_floor=args.attend_floor)
    spec = None
    if args.draft_model:
        if args.kv_bits:
            p.error("--draft-model does not compose with --kv-bits (int8 "
                    "span verification is not bit-identical to serial "
                    "int8 steps)")
        from pipeedge_tpu.parallel.speculative import SpeculativeDecoder
        d_pipe = build_decode_pipeline(
            args.draft_model, None, max_len=args.max_len, dtype=dtype,
            attend_floor=args.attend_floor)
        spec = SpeculativeDecoder(pipe, d_pipe, gamma=args.gamma)

    if args.trace_spans:
        telemetry.configure(rank=0)
        # SIGTERM must unwind through the finally below (the default
        # handler would kill the process before the trace is written)
        import signal
        signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))
    service = _Service(pipe, max_active=args.max_active,
                       max_prefixes=args.max_prefixes, spec=spec,
                       executor=args.executor,
                       edge_itemsize=2 if args.dtype == "bfloat16" else 4)
    server = ThreadingHTTPServer(("127.0.0.1", args.port),
                                 make_handler(service, args.model_name))
    print(f"serving {args.model_name} ({len(pipe.stages)} stages, "
          f"{args.executor} executor) on 127.0.0.1:{args.port}", flush=True)
    try:
        server.serve_forever()
    finally:
        service.stop()
        if args.trace_spans and telemetry.recorder() is not None:
            from pipeedge_tpu.telemetry import chrome_trace
            chrome_trace.dump_trace(telemetry.recorder().snapshot(),
                                    args.trace_spans)


if __name__ == "__main__":
    main()
