"""A/B the Pallas fused-attention kernel against XLA's einsum attention
on the current backend. Prints one JSON line with a row per sequence
length — the recorded evidence behind `_use_fused_attention`'s policy
(pipeedge_tpu/models/layers.py): XLA wins short sequences, the
flash-attention kernel wins long ones by keeping each query block's
scores resident in VMEM (HBM traffic O(S*D) instead of O(S^2)).

Usage: python tools/bench_attention.py [-s 512,2048,8192] [-b 1] [--heads 16]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time(fn, q, k, v, reps=25):
    """ms per call: chain the output back in as the next query (serializing
    executions on-device) and fence ONCE with a scalar readback —
    `block_until_ready` does not fence on tunneled TPU platforms
    (pipeedge_tpu/profiler.py), and a per-rep fence would add a fixed
    ~65 ms round trip to every measurement."""
    import jax.numpy as jnp
    fence = lambda x: float(jnp.sum(x.astype(jnp.float32)))
    fence(fn(q, k, v))                  # compile + warm (fence warmed too)
    o = fn(q, k, v)
    fence(o)
    tik = time.monotonic()
    o = q
    for _ in range(reps):
        o = fn(o, k, v)
    fence(o)
    return (time.monotonic() - tik) / reps * 1e3


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-s", "--seq-lens", default="512,2048,8192")
    p.add_argument("-b", "--batch", default=1, type=int)
    p.add_argument("--heads", default=16, type=int)
    p.add_argument("--head-dim", default=64, type=int)
    p.add_argument("--causal", action="store_true")
    args = p.parse_args()

    from pipeedge_tpu.utils import apply_env_platform, require_live_backend
    apply_env_platform()
    require_live_backend("fused_attention_speedup", unit="x")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pipeedge_tpu.ops.attention import (attention_is_supported,
                                            fused_attention)
    interpret = not attention_is_supported()   # CPU smoke runs interpret

    @jax.jit
    def xla_attend(q, k, v):
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(q.shape[-1]))
        if args.causal:
            s = q.shape[1]
            qp = jax.lax.broadcasted_iota(jnp.int32, (s, s), 0)
            kp = jax.lax.broadcasted_iota(jnp.int32, (s, s), 1)
            scores = jnp.where((kp <= qp)[None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", probs, v,
                          preferred_element_type=jnp.float32).astype(q.dtype)

    rows = {}
    rng = np.random.default_rng(0)
    for s in (int(x) for x in args.seq_lens.split(",")):
        shape = (args.batch, s, args.heads, args.head_dim)
        q, k, v = (jnp.asarray(rng.normal(size=shape), jnp.bfloat16)
                   for _ in range(3))
        xla_ms = _time(xla_attend, q, k, v)
        pallas_ms = _time(
            lambda q, k, v: fused_attention(q, k, v, causal=args.causal,
                                            interpret=interpret),
            q, k, v)
        rows[str(s)] = {"xla_ms": round(xla_ms, 3),
                        "pallas_ms": round(pallas_ms, 3),
                        "speedup": round(xla_ms / pallas_ms, 2)}
    longest = rows[max(rows, key=int)]
    print(json.dumps({
        "metric": "fused_attention_speedup",
        "value": longest["speedup"],
        "unit": "x (XLA/pallas at longest S)",
        "vs_baseline": None,
        "batch": args.batch, "heads": args.heads,
        "head_dim": args.head_dim, "causal": args.causal,
        "dtype": "bfloat16", "per_seq_len": rows,
        "device_kind": jax.devices()[0].device_kind,
    }))


if __name__ == "__main__":
    main()
