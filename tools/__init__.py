"""CLI tools; package marker so tests can import fixture recipes."""
