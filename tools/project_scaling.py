"""Profile-driven 8-stage scaling projection (round-5 verdict item 8).

Real multi-chip hardware is unavailable in this environment, so the
multi-chip throughput claim is projected from measured single-chip
inputs, all of them committed in-repo:

- per-sublayer forward times measured ON the real chip
  (profiles/tpu/device_types.yml, `make_tpu_profiles.sh` recipe);
- per-sublayer edge payload sizes (elements/sample) from the same
  profiling pass (profiles/tpu/models.yml);
- the partition chosen by the NATIVE scheduler (native/partition.cpp)
  for an N-device tpu-v5e fleet — the same binary/cost model users run.

Steady-state pipeline throughput is batch / max_stage_time. Two comm
scenarios bound the answer:
- `overlapped`: stage-edge transfers overlap the next microbatch's
  compute (the SPMD driver's ppermute rides ICI asynchronously inside
  one program — parallel/spmd.py), so t_stage = compute only;
- `serialized`: worst case, t_stage = compute + edge_in/bw — reported
  for BOTH the conservative 100 Gbps DCN planning number the committed
  device_types carry and a 1600 Gbps v5e ICI-class link.

The dryrun (`__graft_entry__.dryrun_multichip`) executes the actual
8-stage edge logic on a virtual mesh; this tool prices it with the
chip-measured numbers. Prints ONE JSON line; --markdown emits the
BASELINE.md section.
"""
import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PROFILE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "profiles", "tpu")
ICI_MBPS = 1_600_000          # v5e ICI class (public spec sheet, per chip)


def project(model_name: str, n_devices: int, batch: int,
            dtype: str = "bfloat16"):
    import yaml

    from pipeedge_tpu.sched.scheduler import sched_pipeline

    with open(os.path.join(PROFILE_DIR, "models.yml")) as f:
        models = yaml.safe_load(f)
    with open(os.path.join(PROFILE_DIR, "device_types.yml")) as f:
        dev_types = yaml.safe_load(f)
    entry = models[model_name]
    prof = next(p for p in dev_types["tpu-v5e"]["model_profiles"]
                [model_name] if p["dtype"] == dtype
                and p["batch_size"] == batch)
    times = prof["time_s"]
    out_elems = entry["parameters_out"]
    dcn_mbps = dev_types["tpu-v5e"]["bw_Mbps"]

    with tempfile.NamedTemporaryFile("w", suffix=".yml",
                                     delete=False) as f:
        yaml.safe_dump({"tpu-v5e": [f"tpu{i}" for i in
                                    range(n_devices)]}, f)
        dev_file = f.name
    try:
        sched = sched_pipeline(
            model_name, 0, 0, batch, dtype=dtype,
            models_file=os.path.join(PROFILE_DIR, "models.yml"),
            dev_types_file=os.path.join(PROFILE_DIR, "device_types.yml"),
            dev_file=dev_file)
    finally:
        os.unlink(dev_file)
    partition = [next(iter(st.values())) for st in sched]

    stages = []
    for l, r in partition:
        compute = sum(times[l - 1:r])
        edge_elems = out_elems[r - 1]           # elements per sample out
        edge_bytes = edge_elems * batch * (2 if dtype == "bfloat16"
                                           else 4)
        stages.append({"layers": [l, r],
                       "compute_ms": round(compute * 1e3, 3),
                       "edge_out_mb": round(edge_bytes / 1e6, 2)})
    total_ms = sum(s["compute_ms"] for s in stages)

    def throughput(comm_mbps=None):
        """batch / steady-state max stage time (comm serialized into the
        stage when a bandwidth is given, overlapped when None)."""
        worst = 0.0
        for i, s in enumerate(stages):
            t = s["compute_ms"]
            if comm_mbps is not None and i > 0:
                in_mb = stages[i - 1]["edge_out_mb"]
                t += in_mb * 8 / comm_mbps * 1e3     # MB over Mbit/s
            worst = max(worst, t)
        return batch / (worst / 1e3), worst

    single = batch / (total_ms / 1e3)
    tp_overlap, worst_overlap = throughput(None)
    tp_dcn, worst_dcn = throughput(dcn_mbps)
    tp_ici, worst_ici = throughput(ICI_MBPS)

    # ABSOLUTE projection, anchored to the fused-program measurement:
    # the per-sublayer profile times carry per-call dispatch granularity
    # (each sublayer its own program), so their sum (-> `single` above)
    # is far below the fused single-chip bench (BENCH_r04: one scanned
    # program). A stage executes ITS sublayers as one fused program too,
    # so the absolute stage time is better estimated as the measured
    # fused microbatch time x the stage's PROFILE-TIME SHARE (the
    # profiles' relative balance is the measured quantity the scheduler
    # optimizes), plus the explicit edge cost.
    fused = None
    bench_path = os.path.join(os.path.dirname(PROFILE_DIR), "..",
                              "BENCH_r04.json")
    if os.path.exists(bench_path):
        with open(bench_path) as f:
            rec = json.load(f)
        if "tail" in rec:       # driver record: the bench line is the
            for line in rec["tail"].splitlines():   # JSON in its tail
                if line.startswith("{\"metric\""):
                    rec = json.loads(line)
                    break
        if rec.get("metric") == "vit_large_images_per_sec_b8":
            fused_img = rec["value"]
            ubatch_ms = batch / fused_img * 1e3
            shares = [s["compute_ms"] / total_ms for s in stages]
            worst_share = max(shares)

            def fused_tp(comm_mbps):
                worst = 0.0
                for i, s in enumerate(stages):
                    t = ubatch_ms * (s["compute_ms"] / total_ms)
                    if comm_mbps is not None and i > 0:
                        t += stages[i - 1]["edge_out_mb"] * 8 \
                            / comm_mbps * 1e3
                    worst = max(worst, t)
                return round(batch / (worst / 1e3), 1), round(worst, 3)

            fused = {
                "anchor_img_per_sec": fused_img,
                "anchor_ubatch_ms": round(ubatch_ms, 3),
                "worst_stage_share": round(worst_share, 4),
                "overlapped_comm": dict(zip(
                    ("img_per_sec", "bottleneck_stage_ms"),
                    fused_tp(None))),
                "serialized_ici_1600gbps": dict(zip(
                    ("img_per_sec", "bottleneck_stage_ms"),
                    fused_tp(ICI_MBPS))),
                "serialized_dcn_100gbps": dict(zip(
                    ("img_per_sec", "bottleneck_stage_ms"),
                    fused_tp(dcn_mbps))),
            }
            for k in ("overlapped_comm", "serialized_ici_1600gbps",
                      "serialized_dcn_100gbps"):
                fused[k]["speedup_vs_single"] = round(
                    fused[k]["img_per_sec"] / fused_img, 2)
    return {
        "fused_anchor_projection": fused,
        "model": model_name, "n_devices": n_devices, "batch": batch,
        "dtype": dtype, "partition": partition, "stages": stages,
        "single_chip_img_per_sec": round(single, 1),
        "projected": {
            "overlapped_comm": {
                "img_per_sec": round(tp_overlap, 1),
                "bottleneck_stage_ms": worst_overlap,
                "speedup_vs_single": round(tp_overlap / single, 2)},
            "serialized_dcn_100gbps": {
                "img_per_sec": round(tp_dcn, 1),
                "bottleneck_stage_ms": round(worst_dcn, 3),
                "speedup_vs_single": round(tp_dcn / single, 2)},
            "serialized_ici_1600gbps": {
                "img_per_sec": round(tp_ici, 1),
                "bottleneck_stage_ms": round(worst_ici, 3),
                "speedup_vs_single": round(tp_ici / single, 2)},
        },
    }


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-m", "--model-name",
                   default="google/vit-large-patch16-224")
    p.add_argument("-n", "--n-devices", default=8, type=int)
    p.add_argument("-b", "--batch", default=8, type=int)
    p.add_argument("--markdown", action="store_true",
                   help="emit the BASELINE.md section instead of JSON")
    args = p.parse_args()
    r = project(args.model_name, args.n_devices, args.batch)
    if not args.markdown:
        print(json.dumps(r))
        return
    pr = r["projected"]
    fa = r["fused_anchor_projection"]
    print(f"""### Projected {r['n_devices']}-stage scaling ({r['model']}, b={r['batch']}, chip-measured inputs)

Relative balance from the committed per-sublayer chip profiles + the
native scheduler's partition; absolute throughput anchored to the fused
single-chip bench (profile times carry per-sublayer dispatch
granularity, so their sum under-states a fused stage program):

| scenario | img/s | vs 1 chip (fused) | bottleneck stage |
|---|---|---|---|
| single chip, fused program (BENCH_r04 anchor) | {fa['anchor_img_per_sec']} | 1.0x | {fa['anchor_ubatch_ms']} ms |
| {r['n_devices']}-stage, comm overlapped (SPMD ppermute) | {fa['overlapped_comm']['img_per_sec']} | {fa['overlapped_comm']['speedup_vs_single']}x | {fa['overlapped_comm']['bottleneck_stage_ms']} ms |
| {r['n_devices']}-stage, comm serialized @ ICI 1600 Gbps | {fa['serialized_ici_1600gbps']['img_per_sec']} | {fa['serialized_ici_1600gbps']['speedup_vs_single']}x | {fa['serialized_ici_1600gbps']['bottleneck_stage_ms']} ms |
| {r['n_devices']}-stage, comm serialized @ DCN 100 Gbps | {fa['serialized_dcn_100gbps']['img_per_sec']} | {fa['serialized_dcn_100gbps']['speedup_vs_single']}x | {fa['serialized_dcn_100gbps']['bottleneck_stage_ms']} ms |

Profile-granularity cross-check (per-sublayer times summed, no fusion
correction): {pr['overlapped_comm']['speedup_vs_single']}x overlapped /
{pr['serialized_dcn_100gbps']['speedup_vs_single']}x @ 100 Gbps — the
speedup is insensitive to the anchor because the scheduler's partition
is balanced to {max(s['compute_ms'] for s in r['stages'])} ms worst
stage over {r['n_devices']} stages.

Partition (native scheduler, committed chip profiles): {r['partition']}""")


if __name__ == "__main__":
    main()
