#!/usr/bin/env python
"""Offline int8 calibration: sweep a batch through a shard, emit the
scale sidecar next to the checkpoint.

    python tools/calibrate.py -m pipeedge/test-tiny-vit --batch 8 \
        --batches 2 --out /tmp/tiny.int8scales.npz

Prints one JSON line (the chaos_dcn idiom) with the per-tag alphas and
where the sidecar landed. Serve/bench paths load it back with
`utils.calibrate.quantize_compute_from_sidecar` and install the config
via `models.layers.set_quantize_compute` BEFORE building the model.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pipeedge_tpu.utils import apply_env_platform  # noqa: E402

apply_env_platform()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-m", "--model", default="pipeedge/test-tiny-vit")
    ap.add_argument("--model-file", default=None,
                    help="checkpoint npz (default: the registry's; the "
                         "sidecar lands next to it)")
    ap.add_argument("--layer-start", type=int, default=1)
    ap.add_argument("--layer-end", type=int, default=0,
                    help="0 = all layers")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--batches", type=int, default=2,
                    help="calibration batches swept through the shard")
    ap.add_argument("--bit", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None,
                    help="sidecar path (default: <model-file>.int8scales"
                         ".npz, or ./<model>.int8scales.npz without a "
                         "checkpoint)")
    args = ap.parse_args()

    import numpy as np

    from pipeedge_tpu.models import registry
    from pipeedge_tpu.utils import calibrate

    cfg = registry.get_model_config(args.model)
    layer_end = args.layer_end or registry.get_model_layers(args.model)
    rng = np.random.default_rng(args.seed)
    if cfg.model_type in ("vit", "deit"):
        batches = [np.asarray(rng.normal(size=(
            args.batch, cfg.num_channels, cfg.image_size, cfg.image_size)),
            np.float32) for _ in range(args.batches)]
    else:
        batches = [np.asarray(rng.integers(
            0, cfg.vocab_size, size=(args.batch, 16)), np.int64)
            for _ in range(args.batches)]

    alphas, wscales, stats = calibrate.calibrate_shard(
        args.model, args.model_file, args.layer_start, layer_end,
        batches, bit=args.bit)

    out = args.out
    if out is None:
        base = args.model_file or registry.get_model_entry(
            args.model).weights_file or args.model.replace("/", "_")
        out = calibrate.sidecar_path(base)
    calibrate.write_sidecar(out, alphas, wscales, meta={
        "model": args.model, "bit": args.bit, "batch": args.batch,
        "batches": args.batches, "seed": args.seed,
        "layers": [args.layer_start, layer_end]})

    print(json.dumps({
        "bench": "calibrate", "model": args.model, "sidecar": out,
        "bit": args.bit,
        "alphas": {t: round(a, 6) for t, a in sorted(alphas.items())},
        "amax": {t: round(s.amax, 6) for t, s in sorted(stats.items())},
        "weight_scale_tensors": len(wscales),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
