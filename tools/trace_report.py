"""Bubble attribution + latency report over a merged DCN trace.

Reads the Perfetto-loadable trace JSON a `--trace-spans OUT` run wrote
(runtime.py's merged fleet timeline) and emits ONE JSON line — the
chaos_dcn.py idiom — with:

- `bubble_pct`: mean per-stage idle share of the active window, plus the
  per-stage busy/idle split under `stages`
- `edges`: per-edge wire-time busy seconds + share of the window
- `mb_latency`: per-microbatch end-to-end p50/p95/p99 (ms) across ranks
- `failover`: detection -> recovery breakdown when a failover happened
- `span_overhead_pct`: the recorder's own measured hot-path tax (per-span
  cost measured live on this host x span count / window)

Examples:

  # trace a loopback fleet, then report on it
  python runtime.py 0 2 -c dcn ... --trace-spans /tmp/trace.json
  python tools/trace_report.py /tmp/trace.json

  # machine-checkable gate (CI smoke): fail unless spans were recorded
  python tools/trace_report.py /tmp/trace.json --require-spans
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pipeedge_tpu.telemetry import chrome_trace, report  # noqa: E402


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("trace", help="merged trace JSON from --trace-spans "
                                 "(Chrome trace-event format)")
    p.add_argument("--require-spans", action="store_true",
                   help="exit nonzero when the trace holds no spans or "
                        "no bubble/latency fields (the CI smoke gate)")
    p.add_argument("--indent", action="store_true",
                   help="pretty-print instead of the one-line record")
    args = p.parse_args()

    with open(args.trace, encoding="utf8") as f:
        doc = json.load(f)
    spans = chrome_trace.trace_to_spans(doc)
    record = report.analyze_spans(spans)
    record["trace"] = args.trace
    print(json.dumps(record, indent=2 if args.indent else None,
                     sort_keys=True))
    if args.require_spans:
        ok = (record.get("spans", 0) > 0
              and record.get("bubble_pct") is not None
              and record.get("mb_latency", {}).get("n", 0) > 0)
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
