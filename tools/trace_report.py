"""Bubble attribution + latency report over a merged DCN trace.

Reads the Perfetto-loadable trace JSON a `--trace-spans OUT` run wrote
(runtime.py's merged fleet timeline) and emits ONE JSON line — the
chaos_dcn.py idiom — with:

- `bubble_pct`: mean per-stage idle share of the active window, plus the
  per-stage busy/idle split under `stages`
- `edges`: per-edge wire-time busy seconds + share of the window
- `segments`: per-(category, name) duration p50/p95 — the dispatch vs
  transfer vs emit breakdown of a microbatch's end-to-end path
- `transport`: edges per negotiated tier (colocated / zerocopy /
  socket_v2, docs/DCN_WIRE.md) + the colocated hand-off's share of
  wire-busy time
- `collectives`: per-stage bits moved by quantized ICI collectives
  (`collective` spans, ops/qcollectives.py) beside the DCN-edge busy
  time — the view that distinguishes intra-stage (ICI psum/all_gather)
  traffic from inter-stage (DCN) traffic (docs/QUANT_COLLECTIVES.md)
- `mb_latency`: per-microbatch end-to-end p50/p95/p99 (ms) across ranks
- `serving`: when the trace came from a `tools/serve.py --trace-spans`
  run — admitted request count, per-class admission-wait p50/p95, sheds
  by class and reason, brownout transitions + max rung (docs/SERVING.md)
- `requests`: distinct traced request ids + the worst-N by end-to-end
  duration — the entry point into `--request` when nothing else named one
- `gray`: peer-health lifecycle transitions (suspect / quarantine /
  readmit / recovered / floor-held) per affected rank — the gray-failure
  CI smoke gates on exactly one quarantine under an injected straggler
  and ZERO on a clean run (docs/FAULT_TOLERANCE.md gray failures)
- `autoscale`: capacity-controller decision spans — plan / apply / held
  / flap_damped per direction with apply durations; the autoscale chaos
  CI gates on scale-up AND scale-down under a load ramp and ZERO
  decisions on a steady fleet (docs/FAULT_TOLERANCE.md autoscale)
- `failover`: detection -> recovery breakdown when a failover happened
- `span_overhead_pct`: the recorder's own measured hot-path tax (per-span
  cost measured live on this host x span count / window)

With `--request RID` the tool instead renders ONE request's causal
timeline (admit -> queue -> per-mb per-stage per-edge -> retire) with
its dominant stall named — docs/OBSERVABILITY.md request tracing. The
input may be a merged trace OR a flight-recorder postmortem bundle.

Examples:

  # trace a loopback fleet, then report on it
  python runtime.py 0 2 -c dcn ... --trace-spans /tmp/trace.json
  python tools/trace_report.py /tmp/trace.json

  # why was THIS request slow? (rid from a /generate response, a 504
  # body, a loadgen worst-N entry, or the report's requests.worst)
  python tools/trace_report.py /tmp/trace.json --request q17
  python tools/trace_report.py postmortems/postmortem-r0-0000-deadline.json \
      --request q17

  # machine-checkable gate (CI smoke): fail unless spans were recorded
  python tools/trace_report.py /tmp/trace.json --require-spans

  # convert the measured per-stage timings into a scheduler-consumable
  # profiler_results.yml (offline re-scheduling from live measurements:
  # feed it to profiler_results_to_device_types.py / sched/profiles.py)
  python tools/trace_report.py /tmp/trace.json \
      --emit-profiles live.yaml --partition 1,24,25,48 \
      --model google/vit-base-patch16-224 --profile-batch-size 8
"""
import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pipeedge_tpu.telemetry as telemetry  # noqa: E402
from pipeedge_tpu.telemetry import chrome_trace, feedback, report  # noqa: E402


def _emit_profiles(args, spans) -> None:
    """Measured per-stage service times -> profiler_results.yml
    (sched/profiles.py ingestion)."""
    from pipeedge_tpu.sched import profiles

    nums = [int(x) for x in args.partition.split(",")]
    if len(nums) % 2:
        raise SystemExit("--partition needs comma-separated layer PAIRS")
    partition = list(zip(nums[::2], nums[1::2]))
    est = feedback.stage_estimates(feedback.digest_from_spans(spans))
    problems = feedback.check_estimates(est, len(partition))
    if problems:
        raise SystemExit("--emit-profiles: trace measurements incomplete: "
                         + "; ".join(problems))
    record = profiles.results_from_measured(
        args.model, args.dtype, args.profile_batch_size,
        total_layers=partition[-1][1], partition=partition,
        # layer_s, NOT service_s: the per-microbatch emit/wire fixed cost
        # must not be baked into per-layer compute times — the offline
        # scheduler models comm separately (bw_Mbps x boundary elements)
        stage_times_s=[est[i].layer_s for i in range(len(partition))])
    profiles.save_measured_profiles(args.emit_profiles, record)
    print(f"emitted measured per-layer profiles for {len(partition)} "
          f"stage(s) -> {args.emit_profiles}", file=sys.stderr)


def _load_spans(path: str):
    """Span dicts from any input shape: a merged Chrome-trace JSON
    (`--trace-spans` output), a flight-recorder postmortem bundle
    (telemetry/flight.py — its `spans` slice is already span dicts), or
    a benchkit trajectory record/artifact whose serve block names the
    trace it produced (`serve.trace`) — so `trace_report BENCH_r06.json
    --request q17` resolves a record's p99 exemplar without the caller
    digging the trace path out by hand."""
    with open(path, encoding="utf8") as f:
        doc = json.load(f)
    if isinstance(doc, dict) and doc.get("bundle") == "pipeedge-postmortem":
        return list(doc.get("spans", ())), doc
    if isinstance(doc, dict) and str(doc.get("schema",
                                             "")).startswith("pipeedge-bench"):
        records = ([doc] if "scenario" in doc
                   else list(doc.get("records", ())))
        for rec in records:
            trace = (rec.get("serve") or {}).get("trace")
            if trace:
                if not os.path.isabs(trace):
                    trace = os.path.join(os.path.dirname(
                        os.path.abspath(path)), trace)
                with open(trace, encoding="utf8") as fh:
                    return chrome_trace.trace_to_spans(json.load(fh)), None
        raise SystemExit(f"{path} is a bench record but no scenario in "
                         "it carries a serve.trace path")
    return chrome_trace.trace_to_spans(doc), None


def _fetch_json(url: str, timeout: float) -> dict:
    """GET url -> parsed JSON; an HTTP error status with a JSON body
    (the router's 503 /healthz while unroutable) still parses."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf8"))
    except urllib.error.HTTPError as exc:
        return json.loads(exc.read().decode("utf8"))


def _fleet_targets(fleet_url: str, timeout: float) -> dict:
    """{name: base_url} of every process in the routed fleet: the router
    itself, plus whatever GET /fleet (the collector's target set —
    includes prefill workers) or, failing that, GET /healthz's fleet
    block reports."""
    targets = {"router": fleet_url}
    try:
        body = _fetch_json(f"{fleet_url}/fleet", timeout)
        for name, url in (body.get("targets") or {}).items():
            targets.setdefault(name, url)
    except (OSError, ValueError):
        pass
    if len(targets) == 1:
        body = _fetch_json(f"{fleet_url}/healthz", timeout)
        for name, rec in (body.get("fleet") or {}).items():
            url = (rec or {}).get("url")
            if url:
                targets.setdefault(name, url)
    return targets


def _collect_fleet(fleet_url: str, timeout: float = 5.0):
    """Federate span rings across the routed fleet: GET /debug/spans
    from every process, estimate each peer's monotonic-clock offset
    from the fetch's own (t0, t1, t2, t3) quadruple (telemetry.
    estimate_clock_offset), align onto the caller's timeline, and remap
    each process's span `rank` to a distinct per-process index (every
    serving process records rank 0 locally — without the remap two
    replicas would collapse into one lane). Returns (spans, processes).
    """
    fleet_url = fleet_url.rstrip("/")
    spans = []
    processes = {}
    for idx, (name, url) in enumerate(
            sorted(_fleet_targets(fleet_url, timeout).items())):
        proc = {"target": name, "url": url}
        try:
            t0 = time.monotonic_ns()
            body = _fetch_json(f"{url.rstrip('/')}/debug/spans", timeout)
            t3 = time.monotonic_ns()
            theta = telemetry.estimate_clock_offset(
                [(t0, int(body["t_recv_ns"]), int(body["t_send_ns"]), t3)])
            aligned = telemetry.align_spans(body.get("spans") or (), theta)
            for s in aligned:
                s["rank"] = idx
            spans.extend(aligned)
            proc.update({"ok": True, "pid": body.get("pid"),
                         "spans": len(aligned),
                         "dropped": body.get("dropped", 0),
                         "offset_ns": theta, "rtt_ns": t3 - t0})
        except (OSError, ValueError, KeyError, TypeError) as exc:
            proc.update({"ok": False, "error": str(exc)})
        processes[str(idx)] = proc
    return spans, processes


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("trace", nargs="?", default=None,
                   help="merged trace JSON from --trace-spans "
                        "(Chrome trace-event format), or a "
                        "flight-recorder postmortem bundle "
                        "(omit with --fleet)")
    p.add_argument("--fleet", metavar="URL", default=None,
                   help="federate LIVE span rings instead of reading a "
                        "trace file: GET /debug/spans from the router at "
                        "URL and every replica/prefill worker it knows, "
                        "clock-aligned onto one timeline (each drain "
                        "consumes the rings — a second run sees only "
                        "newer spans)")
    p.add_argument("--request", metavar="RID", default=None,
                   help="render ONE request's causal timeline (admit -> "
                        "queue -> per-mb per-stage per-edge -> retire) "
                        "with the dominant stall named, instead of the "
                        "fleet report; RID comes from a /generate "
                        "response, a loadgen worst-N entry, a 504 body, "
                        "or the report's requests.worst list. Exit 3 "
                        "when the trace holds no spans for RID.")
    p.add_argument("--require-spans", action="store_true",
                   help="exit nonzero when the trace holds no spans or "
                        "no bubble/latency fields (the CI smoke gate)")
    p.add_argument("--require-local-edges", action="store_true",
                   help="exit nonzero unless at least one edge negotiated "
                        "the colocated (on-device hand-off) transport tier "
                        "(the CI colocated-world gate)")
    p.add_argument("--indent", action="store_true",
                   help="pretty-print instead of the one-line record")
    p.add_argument("--emit-profiles", metavar="OUT.yaml", default=None,
                   help="also write the trace's measured per-stage service "
                        "times as a profiler_results.yml the scheduler "
                        "tooling ingests (requires --partition + --model)")
    p.add_argument("--partition", default=None,
                   help="the layer partition the traced run used, e.g. "
                        "'1,24,25,48' (--emit-profiles needs it to spread "
                        "stage times over layers)")
    p.add_argument("--model", default=None,
                   help="model name recorded in the emitted profiles")
    p.add_argument("--dtype", default="float32",
                   help="dtype key recorded in the emitted profiles")
    p.add_argument("--profile-batch-size", type=int, default=8,
                   help="batch-size key recorded in the emitted profiles "
                        "(the traced run's microbatch size)")
    args = p.parse_args()
    if args.emit_profiles and not (args.partition and args.model):
        p.error("--emit-profiles requires --partition and --model")
    if (args.trace is None) == (args.fleet is None):
        p.error("give a trace file OR --fleet URL (exactly one)")

    processes = None
    bundle = None
    if args.fleet is not None:
        spans, processes = _collect_fleet(args.fleet)
    else:
        spans, bundle = _load_spans(args.trace)
    source = args.fleet if args.fleet is not None else args.trace
    if args.request is not None:
        record = report.request_timeline(spans, args.request)
        record["trace"] = source
        if processes is not None:
            record["processes"] = processes
        if bundle is not None:
            record["bundle_trigger"] = bundle.get("trigger")
        print(json.dumps(record, indent=2 if args.indent else None,
                         sort_keys=True))
        return 0 if record.get("found") else 3
    record = report.analyze_spans(spans)
    record["trace"] = source
    if processes is not None:
        record["processes"] = processes
    print(json.dumps(record, indent=2 if args.indent else None,
                     sort_keys=True))
    if args.emit_profiles:
        _emit_profiles(args, spans)
    if args.require_local_edges:
        transport = record.get("transport", {})
        if not transport.get("local_edges", 0):
            print("trace_report: no edge negotiated the colocated "
                  "transport tier", file=sys.stderr)
            return 1
    if args.require_spans:
        ok = (record.get("spans", 0) > 0
              and record.get("bubble_pct") is not None
              and record.get("mb_latency", {}).get("n", 0) > 0)
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
