"""Generate an ansible playbook that launches the runtime across a fleet.

Parity with /root/reference/tools/create_playbook.py:23-39. Two fleet
shapes:

- `-c spmd|host`: single-controller — one task per controller host, each
  driving its whole slice (rank 0).
- `-c dcn`: one rank per host (the reference's deployment shape), with
  `--dcn-addrs` derived from the node list + `--port`.
"""
import argparse


def create_python_command(file_name, rank, world_size, partition, model_name,
                          batch_size, ubatch_size, comm, dcn_addrs=None):
    command = (f"python3 {file_name} {rank} {world_size} -m {model_name} "
               f"-pt {partition} -b {batch_size} -u {ubatch_size} -c {comm}")
    if dcn_addrs:
        command += f" --dcn-addrs {dcn_addrs}"
    print(command)
    return command


def create_shell_command(script, node_name, command, write_async=True,
                         task_name="runtime"):
    script.write(f"- hosts: {node_name}\n")
    script.write("  tasks:\n")
    script.write(f"    - name: {task_name}\n")
    script.write(f"      shell: {command}\n")
    if write_async:
        script.write("      async: 10000\n")
        script.write("      poll: 0\n")
    script.write("\n")


def create_script(script_name, node_list, file_name, world_size, partition,
                  model_name, batch_size, ubatch_size, comm, port=29600):
    with open(script_name, "w") as script:
        if comm == "dcn":
            # one rank per host (reference create_playbook.py:23-39); the
            # data rank (0) runs last/synchronously so ansible waits on it
            if world_size != len(node_list):
                raise ValueError(
                    f"dcn mode runs one rank per host: --world-size "
                    f"{world_size} != {len(node_list)} nodes")
            addrs = ",".join(f"{node}:{port}" for node in node_list)
            for idx in range(len(node_list) - 1, -1, -1):
                command = create_python_command(
                    file_name, idx, len(node_list), partition, model_name,
                    batch_size, ubatch_size, comm, dcn_addrs=addrs)
                create_shell_command(script, node_list[idx], command,
                                     write_async=idx != 0,
                                     task_name=f"runtime rank {idx}")
            return
        for idx, node in enumerate(node_list):
            command = create_python_command(file_name, 0, world_size, partition,
                                            model_name, batch_size, ubatch_size,
                                            comm)
            # last task runs synchronously so ansible waits for completion
            create_shell_command(script, node, command,
                                 write_async=idx != len(node_list) - 1)


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="Create ansible playbook yml script")
    parser.add_argument("-wz", "--world-size", type=int, required=True,
                        help="number of pipeline stages per controller")
    parser.add_argument("-f", "--file-name", type=str, default="runtime.py")
    parser.add_argument("-m", "--model-name", type=str,
                        default="google/vit-base-patch16-224")
    parser.add_argument("-pt", "--partition", type=str, default="1,48")
    parser.add_argument("-b", "--batch-size", default=64, type=int)
    parser.add_argument("-u", "--ubatch-size", default=8, type=int)
    parser.add_argument("-c", "--comm", default="spmd",
                        choices=["spmd", "host", "dcn"])
    parser.add_argument("-P", "--port", type=int, default=29600,
                        help="per-rank listener port (dcn mode)")
    parser.add_argument("-nz", "--nodes", type=str, required=True,
                        help="comma-delimited controller host names "
                             "(dcn: one rank per host, rank 0 = data rank)")
    parser.add_argument("-sn", "--script-name", default="playbook.yml")
    args = parser.parse_args()

    nodes = args.nodes.split(',')
    create_script(args.script_name, nodes, args.file_name, args.world_size,
                  args.partition, args.model_name, args.batch_size,
                  args.ubatch_size, args.comm, port=args.port)
