"""Pipeline-parallel training CLI over the SPMD pipeline.

The training counterpart of tools/generate.py (beyond-reference: the
upstream framework is inference-only). Builds the one-program pipelined
forward over a ('dp', 'stage') mesh, differentiates through it
(parallel/train.py), and runs an optimizer loop on synthetic data —
classification (ViT/DeiT: images + labels), BERT sequence
classification (token ids + class labels), or causal-LM (GPT-2/LLaMA/
Mistral families: next-token targets). Checkpoints the full training
state (params + optimizer + step) via Orbax and resumes from it.

Examples:
  python tools/train.py -m pipeedge/test-tiny-vit --steps 20 --platform cpu
  python tools/train.py -m gpt2 -pt 1,24,25,48 --dp 2 --steps 100 \\
      --optimizer adam --ckpt-dir /tmp/gpt2_train --remat
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("-m", "--model-name", default="pipeedge/test-tiny-vit")
    p.add_argument("-pt", "--partition", default=None,
                   help="comma-separated block-aligned layer bounds "
                        "(default: one stage)")
    p.add_argument("--dp", default=1, type=int,
                   help="data-parallel mesh axis (batch shards)")
    p.add_argument("--steps", default=10, type=int)
    p.add_argument("-b", "--batch", default=4, type=int)
    p.add_argument("-u", "--ubatches", default=4, type=int,
                   help="microbatches per step (the pipeline's fill depth)")
    p.add_argument("--seq-len", default=32, type=int,
                   help="sequence length for LM families")
    p.add_argument("--lr", default=1e-3, type=float)
    p.add_argument("--optimizer", default="sgd", choices=["sgd", "adam"])
    p.add_argument("-t", "--dtype", default="float32",
                   choices=["float32", "bfloat16"])
    p.add_argument("--remat", action="store_true",
                   help="per-block jax.checkpoint (trades a forward "
                        "recompute for ~model-depth less activation HBM)")
    p.add_argument("--mixed-precision", action="store_true",
                   help="bf16 compute over f32 master weights (requires "
                        "-t float32: those params ARE the masters)")
    p.add_argument("--ckpt-dir", default=None,
                   help="save the training state here every --ckpt-every "
                        "steps and resume from it when present")
    p.add_argument("--ckpt-every", default=0, type=int,
                   help="0 = only at the end")
    p.add_argument("--log-every", default=1, type=int)
    p.add_argument("--seed", default=0, type=int)
    p.add_argument("--platform", default=None,
                   help="force a jax platform (e.g. cpu)")
    args = p.parse_args()

    if args.mixed_precision and args.dtype != "float32":
        p.error("--mixed-precision keeps f32 master weights; use -t "
                "float32 (the bf16 cast is per-step, inside the program)")
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    from pipeedge_tpu.utils import apply_env_platform
    apply_env_platform()
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from pipeedge_tpu.models import ShardConfig, registry
    from pipeedge_tpu.parallel import spmd, train

    cfg = registry.get_model_config(args.model_name)
    total = registry.get_model_layers(args.model_name)
    if args.partition:
        nums = [int(x) for x in args.partition.split(",")]
        if len(nums) % 2:
            p.error(f"-pt needs an even count of layer bounds: {nums}")
        partition = list(zip(nums[::2], nums[1::2]))
        from pipeedge_tpu.parallel.decode import validate_partition
        try:
            validate_partition(partition, total)
        except ValueError as exc:
            p.error(f"-pt: {exc} ({args.model_name} has {total} sublayers)")
    else:
        partition = [(1, total)]
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    entry = registry.get_model_entry(args.model_name)
    family_mod = entry.family
    is_lm = cfg.model_type in ("gpt2", "llama")
    is_bert = cfg.model_type == "bert"
    if not is_lm and not is_bert and cfg.model_type not in ("vit", "deit"):
        p.error(f"training CLI covers classification (vit/deit), BERT "
                f"sequence classification, and LM (gpt2/llama) families; "
                f"got {cfg.model_type}")

    stage_params = [family_mod.init_params(
        cfg, ShardConfig(l, r, is_first=l == 1, is_last=r == total),
        dtype=dtype, seed=args.seed) for l, r in partition]
    n_stages = len(partition)
    need = n_stages * args.dp
    if len(jax.devices()) < need:
        p.error(f"{n_stages} stages x dp {args.dp} needs {need} devices, "
                f"have {len(jax.devices())}")
    mesh = spmd.make_pipeline_mesh(n_stages, dp=args.dp)
    pipe = spmd.build_spmd_pipeline(family_mod.FAMILY, cfg, partition,
                                    stage_params, mesh, remat=args.remat)

    rng = np.random.default_rng(args.seed)
    if is_lm:
        seq = min(args.seq_len + 1, cfg.max_position_embeddings)
        ids = jnp.asarray(rng.integers(
            0, cfg.vocab_size, size=(args.ubatches, args.batch, seq)),
            jnp.int32)
        inputs, labels = ids[..., :-1], ids[..., 1:]
    elif is_bert:
        seq = min(args.seq_len, cfg.max_position_embeddings)
        inputs = jnp.asarray(rng.integers(
            0, cfg.vocab_size, size=(args.ubatches, args.batch, seq)),
            jnp.int32)
        labels = jnp.asarray(rng.integers(
            0, max(cfg.num_labels, 1), size=(args.ubatches, args.batch)),
            jnp.int32)
    else:
        inputs = jnp.asarray(rng.normal(size=(
            args.ubatches, args.batch, 3, cfg.image_size, cfg.image_size)),
            dtype)
        labels = jnp.asarray(rng.integers(
            0, max(cfg.num_labels, 1), size=(args.ubatches, args.batch)),
            jnp.int32)

    opt = (optax.adam(args.lr) if args.optimizer == "adam"
           else optax.sgd(args.lr))
    step_fn, opt_state = train.make_train_step(
        pipe, opt, inputs, mixed_precision=args.mixed_precision)
    params, start = pipe.params, 0
    if args.ckpt_dir and os.path.isdir(args.ckpt_dir) \
            and os.listdir(args.ckpt_dir):   # a real checkpoint, not just
        params, opt_state, start = train.restore_train_state(  # a mkdir
            args.ckpt_dir, params, opt_state)
        print(f"resumed from {args.ckpt_dir} at step {start}", flush=True)

    tik = time.monotonic()
    loss = None
    for i in range(start, args.steps):
        params, opt_state, loss = step_fn(params, opt_state, inputs, labels)
        if args.log_every and (i + 1) % args.log_every == 0:
            print(f"step={i + 1} loss={float(loss):.4f}", flush=True)
        if args.ckpt_dir and args.ckpt_every \
                and (i + 1) % args.ckpt_every == 0:
            train.save_train_state(args.ckpt_dir, params, opt_state, i + 1)
    wall = time.monotonic() - tik
    done = max(args.steps - start, 0)
    if args.ckpt_dir and done:
        # never write a checkpoint whose step count moves BACKWARD (a
        # --steps below the restored step trains nothing and must not
        # relabel step-`start` state as something earlier)
        train.save_train_state(args.ckpt_dir, params, opt_state,
                               start + done)
    print(json.dumps({
        "steps": done,
        "final_loss": round(float(loss), 4) if loss is not None else None,
        "images_or_seqs_per_step": args.ubatches * args.batch,
        "wall_s": round(wall, 2),
        "steps_per_sec": round(done / wall, 3) if wall > 0 and done else None,
        "mesh": dict(mesh.shape), "remat": args.remat,
        "mixed_precision": args.mixed_precision,
        "ckpt": args.ckpt_dir}), flush=True)


if __name__ == "__main__":
    main()
