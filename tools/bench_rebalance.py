"""Closed-loop rebalancing A/B: frozen partition vs `--rebalance auto` on a
loopback DCN fleet with one artificially slow stage.

Launches two (optionally three) fleets of `runtime.py` ranks — one OS
process each, a dedicated data rank plus one rank per stage — with
chaos-delayed sends (`DCN_CHAOS=delay@1:MS`, pipeedge_tpu/comm/chaos.py)
armed in the victim rank's environment only:

1. **frozen**: the CLI partition runs every round unchanged (today's
   behavior — a mis-profiled or straggling stage bubbles the pipeline
   forever). The default partition deliberately overloads the victim
   stage, the drifted-profile scenario the closed loop exists for.
2. **auto**: the data rank re-solves the partition from the measured span
   digests and re-broadcasts it at a round boundary (docs/REBALANCE.md).
3. **balanced** (`--check-balanced`): auto mode, NO chaos, a partition
   sized to the measured per-stage costs — the zero-churn guard (a
   healthy fleet must record zero rebalance events).

Each run writes a merged span trace; `tools/trace_report.py` turns both
into pipeline-bubble percentages. The headline comparison is the LAST
round of each run — both warm, the auto run settled on its re-cut — so
startup compile noise doesn't pollute the A/B. Emits ONE JSON line
(chaos_dcn.py idiom) with both bubbles, the measured drop, and the
rebalance-event counts — the acceptance record for ISSUE 4.

Example (the CI quick-gate smoke):

  python tools/bench_rebalance.py --check-balanced
"""
import argparse
import json
import os
import re
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n):
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def run_fleet(args, tag, rebalance, chaos, partition=None):
    """One loopback fleet run; returns (data stdout+stderr, report dict,
    trace path, report path)."""
    addrs = ",".join(f"127.0.0.1:{p}" for p in _free_ports(args.world))
    # absolute: the ranks run with cwd=workdir and resolve --trace-spans
    # against it — a relative workdir would double up in the path
    workdir = os.path.abspath(os.path.join(args.workdir, tag))
    os.makedirs(workdir, exist_ok=True)
    trace = os.path.join(workdir, "trace.json")
    common = [sys.executable, os.path.join(REPO, "runtime.py")]
    # stages on dedicated ranks; rank 0 is data-only, so rebalancing never
    # fights the feed/results threads for the data rank's interpreter
    rank_order = ",".join(str(r) for r in range(1, args.world))
    opts = ["-c", "dcn", "--platform", "cpu", "-m", args.model,
            "-b", str(args.batch), "-u", str(args.ubatch),
            "-pt", partition or args.partition, "-r", rank_order,
            "--rounds", str(args.rounds),
            "--dcn-addrs", addrs, "--sched-timeout", str(args.sched_timeout),
            "--trace-spans", trace, "--rebalance", rebalance,
            "--rebalance-threshold", str(args.threshold),
            "--rebalance-confirm", str(args.confirm),
            "--rebalance-cooldown", str(args.cooldown)]
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               DCN_CONNECT_TIMEOUT="30")
    workers = []
    logs = []
    for r in range(1, args.world):
        wenv = dict(env, DCN_CHAOS=chaos) if (chaos and r == args.victim) \
            else env
        # file-backed worker logs: an unread PIPE fills after ~64 KiB of
        # rank logging and then BLOCKS the worker mid-round
        log = open(os.path.join(workdir, f"rank{r}.log"), "w",
                   encoding="utf8")
        logs.append(log)
        workers.append(subprocess.Popen(
            common + [str(r), str(args.world)] + opts, cwd=workdir,
            env=wenv, text=True, stdout=log, stderr=subprocess.STDOUT))
    try:
        data = subprocess.run(common + ["0", str(args.world)] + opts,
                              cwd=workdir, env=env, capture_output=True,
                              text=True, timeout=args.timeout)
    finally:
        for w in workers:
            try:
                w.wait(timeout=60)
            except subprocess.TimeoutExpired:
                w.kill()
        for log in logs:
            log.close()
    out = data.stdout + data.stderr
    with open(os.path.join(workdir, "data_rank.log"), "w",
              encoding="utf8") as f:
        f.write(out)
    if data.returncode != 0:
        raise SystemExit(f"{tag} fleet failed (rc={data.returncode}):\n"
                         + out[-4000:])
    rep = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         trace, "--require-spans"], capture_output=True, text=True,
        timeout=120)
    if rep.returncode != 0:
        raise SystemExit(f"{tag} trace_report failed:\n"
                         + rep.stdout + rep.stderr)
    report_path = os.path.join(workdir, "report.json")
    with open(report_path, "w", encoding="utf8") as f:
        f.write(rep.stdout)
    return out, json.loads(rep.stdout), trace, report_path


def _last_round_bubble(rep):
    rounds = [r for r in rep.get("rounds", ())
              if r.get("bubble_pct") is not None]
    return rounds[-1]["bubble_pct"] if rounds else rep.get("bubble_pct")


def _last_round_latency(out):
    lats = re.findall(r"^latency_sec=([0-9.]+)", out, re.M)
    return float(lats[-1]) if lats else None


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--world", type=int, default=4,
                   help="ranks: one data rank + world-1 stage ranks")
    p.add_argument("--victim", type=int, default=2,
                   help="stage rank whose sends the chaos delay slows")
    p.add_argument("--delay-ms", type=float, default=60.0,
                   help="per-send delay injected at the victim (the "
                        "artificial straggler)")
    p.add_argument("--model", default="pipeedge/test-tiny-vit")
    p.add_argument("--partition", default="1,1,2,7,8,8",
                   help="the frozen starting partition — deliberately "
                        "overloading the victim stage (it gets most of "
                        "the layers AND the slow link): the drifted-"
                        "profile scenario the closed loop exists for")
    p.add_argument("--batch", type=int, default=48)
    p.add_argument("--ubatch", type=int, default=4)
    p.add_argument("--rounds", type=int, default=5,
                   help="rounds per run: enough that even a re-plan "
                        "confirmed one window late leaves a warm settled "
                        "round to compare")
    p.add_argument("--threshold", type=float, default=0.10,
                   help="--rebalance-threshold (the runtime default: the "
                        "compound imbalance clears it with margin, window "
                        "noise does not)")
    p.add_argument("--confirm", type=int, default=1,
                   help="--rebalance-confirm: one confirming window "
                        "filters the compile-tainted first window without "
                        "giving up determinism")
    p.add_argument("--cooldown", type=int, default=10,
                   help="--rebalance-cooldown: larger than the run, so a "
                        "short A/B re-plans exactly once — a second "
                        "rebuild would eat its own win before the run "
                        "ends")
    p.add_argument("--check-balanced", action="store_true",
                   help="also run auto with NO chaos on a measured-"
                        "balanced partition and record that zero "
                        "rebalance events occurred (the no-churn guard)")
    p.add_argument("--balanced-partition", default="1,2,3,5,6,8",
                   help="partition for the --check-balanced run (sized to "
                        "the MEASURED per-stage costs: the embed-bearing "
                        "head stage is the immovable bottleneck)")
    p.add_argument("--workdir", default="bench_rebalance_runs")
    p.add_argument("--sched-timeout", type=float, default=120)
    p.add_argument("--timeout", type=float, default=420,
                   help="per-fleet wall clock limit (seconds)")
    args = p.parse_args()

    chaos = f"delay@1:{args.delay_ms:g}"
    frozen_out, frozen_rep, _, frozen_report = run_fleet(
        args, "frozen", "off", chaos)
    auto_out, auto_rep, _, auto_report = run_fleet(
        args, "auto", "auto", chaos)

    events = auto_rep.get("rebalance_events", 0)
    partitions = re.findall(r"rebalance_round=\d+ partition=(\S+)", auto_out)
    frozen_last = _last_round_bubble(frozen_rep)
    auto_last = _last_round_bubble(auto_rep)
    record = {
        "metric": "dcn_rebalance_last_round_bubble_pct",
        "world": args.world,
        "chaos": chaos,
        "rounds": args.rounds,
        "partition_frozen": args.partition,
        "partitions_rebalanced": partitions,
        # headline: LAST round of each run (both warm; auto settled)
        "bubble_pct_frozen": frozen_last,
        "bubble_pct_auto": auto_last,
        "bubble_drop_pct": (round(frozen_last - auto_last, 3)
                            if frozen_last is not None
                            and auto_last is not None else None),
        "last_round_latency_sec_frozen": _last_round_latency(frozen_out),
        "last_round_latency_sec_auto": _last_round_latency(auto_out),
        # whole-window numbers for reference (startup noise included)
        "bubble_pct_frozen_whole": frozen_rep.get("bubble_pct"),
        "bubble_pct_auto_whole": auto_rep.get("bubble_pct"),
        "rebalance_events": events,
        "rebalance_events_frozen": frozen_rep.get("rebalance_events", 0),
        "reports": {"frozen": frozen_report, "auto": auto_report},
    }
    if args.check_balanced:
        bal_out, bal_rep, _, bal_report = run_fleet(
            args, "balanced", "auto", None,
            partition=args.balanced_partition)
        record["rebalance_events_balanced"] = bal_rep.get(
            "rebalance_events", 0)
        record["balanced_churned"] = "rebalance_round=" in bal_out
        record["reports"]["balanced"] = bal_report
    record["rebalanced"] = events > 0
    record["improved"] = (record["bubble_drop_pct"] is not None
                          and record["bubble_drop_pct"] > 0)
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
