"""Size the nominal-MFU residual bucket by bucket (round-5 verdict #1).

docs/PERF.md claims the 197->~117 TFLOP/s gap on the ViT-Large headline
bench is structural, split across (a) f32 VPU numerics kept for parity,
(b) S=197 tile padding, and (c) head_dim=64 half-filling the MXU lanes
— but round 4 never SIZED the buckets. This harness measures each one
with interleaved same-session A/Bs over the ViT-L encoder block stack
(24 blocks, D=1024, I=4096 — where ~99% of the model FLOPs live):

- base:    S=197, exact f32 numerics, 16 heads x 64   (the parity path)
- fast:    S=197, fast numerics (model-dtype LN/softmax, tanh GeLU)
           -> sizes the f32-numerics bucket (an EQUIVALENT model up to
           the measured accuracy delta; bench.py records it)
- pad256:  S=256, exact numerics  -> sizes the S=197 tile-padding
           bucket (each variant is scored against its OWN analytic
           FLOPs, so the comparison is efficiency, not work)
- hd128:   S=197, exact, 8 heads x 128 -> sizes the head_dim=64 MXU
           lane-fill bucket (a COST PROBE: same FLOPs, different head
           geometry — not the same model, used only to price the shape)
- stacked: S=256, fast, 8 x 128 -> the combined ceiling

Rounds are interleaved (one timing per variant per round, repeated) so
session drift hits every variant equally — the chip timing discipline
from docs/PERF.md. Prints ONE JSON line with per-variant ms/TFLOPs/MFU
and the derived bucket attribution.
"""
import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-b", "--batch", default=8, type=int)
    p.add_argument("-l", "--layers", default=24, type=int)
    p.add_argument("-d", "--hidden", default=1024, type=int)
    p.add_argument("-i", "--inter", default=4096, type=int)
    p.add_argument("--chain", default=8, type=int,
                   help="full-stack passes chained per timing (one fence)")
    p.add_argument("--rounds", default=3, type=int,
                   help="interleaved timing rounds per variant")
    p.add_argument("--extra-seqs", default="",
                   help="comma-separated extra sequence lengths to probe "
                        "as exact-numerics variants (e.g. 200,208 — sizes "
                        "the small-pad end of the S=197 padding bucket)")
    args = p.parse_args()
    try:
        # fail BEFORE any chip compile: a malformed list after five warm
        # builds would waste the whole leased session
        extra_seqs = [int(s) for s in args.extra_seqs.split(",") if s]
    except ValueError:
        p.error(f"--extra-seqs must be comma-separated integers, got "
                f"{args.extra_seqs!r}")

    from pipeedge_tpu.utils import apply_env_platform, require_live_backend
    apply_env_platform()
    require_live_backend("mfu_bucket_base_tflops", unit="TFLOP/s")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pipeedge_tpu.benchkit.headline import (
        NOMINAL_BF16_PEAK, calibrate_peak_samples as
        _calibrate_peak_samples)
    from pipeedge_tpu.models.layers import (dense, gelu, layer_norm,
                                            self_attention,
                                            set_fast_numerics)

    d, inter, n_layers, batch = (args.hidden, args.inter, args.layers,
                                 args.batch)
    rng = np.random.default_rng(0)

    def make_params():
        def mat(m, n):
            return {"w": jnp.asarray(rng.normal(scale=0.02, size=(m, n)),
                                     jnp.bfloat16),
                    "b": jnp.zeros((n,), jnp.bfloat16)}

        def ln():
            return {"scale": jnp.ones((d,), jnp.float32),
                    "bias": jnp.zeros((d,), jnp.float32)}

        def block():
            return {"ln_before": ln(), "q": mat(d, d), "k": mat(d, d),
                    "v": mat(d, d), "attn_out": mat(d, d),
                    "ln_after": ln(), "mlp_up": mat(d, inter),
                    "mlp_down": mat(inter, d)}

        return jax.device_put(jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *[block() for _ in range(n_layers)]))

    params = make_params()

    def build(seq, heads, fast):
        """One jitted program: `chain` passes of the L-block ViT stack
        (the vit.py sublayer composition) with a scalar fence — built
        under the requested numerics mode (trace-time flag)."""
        def block(p, x):
            normed = layer_norm(p["ln_before"], x, 1e-12)
            ctx = self_attention(
                {"q": p["q"], "k": p["k"], "v": p["v"]}, normed, heads)
            x = dense(p["attn_out"], ctx) + x
            normed = layer_norm(p["ln_after"], x, 1e-12)
            return dense(p["mlp_down"], gelu(dense(p["mlp_up"],
                                                   normed))) + x

        set_fast_numerics(fast)
        try:
            @jax.jit
            def run(p, x):
                def one_pass(x, _):
                    def step(x, bp):
                        return block(bp, x), None

                    x, _ = jax.lax.scan(step, x, p)
                    # keep magnitudes bounded across chained passes
                    return x * jnp.asarray(0.5, x.dtype), None

                x, _ = jax.lax.scan(one_pass, x, None, length=args.chain)
                return jnp.sum(x.astype(jnp.float32))

            x0 = jax.device_put(jnp.asarray(
                rng.normal(size=(batch, seq, d)), jnp.bfloat16))
            float(run(params, x0))          # compile + warm (flag bound)
        finally:
            set_fast_numerics(False)
        return run, x0

    def flops_per_pass(seq):
        per_block = 8 * seq * d * d + 4 * seq * seq * d + 4 * seq * d * inter
        return n_layers * per_block * batch

    variants = {
        "base": build(197, 16, False),
        "fast_numerics": build(197, 16, True),
        "pad256": build(256, 16, False),
        "hd128": build(197, 8, False),
        "stacked": build(256, 8, True),
    }
    seqs = {"base": 197, "fast_numerics": 197, "pad256": 256,
            "hd128": 197, "stacked": 256}
    for s_extra in extra_seqs:
        if f"pad{s_extra}" in variants or s_extra == 197:
            continue     # already a built-in variant; skip the recompile
        variants[f"pad{s_extra}"] = build(s_extra, 16, False)
        seqs[f"pad{s_extra}"] = s_extra

    cal = _calibrate_peak_samples()
    device_kind = jax.devices()[0].device_kind
    nominal = NOMINAL_BF16_PEAK.get(device_kind)

    times = {k: [] for k in variants}
    for _ in range(args.rounds):            # interleaved rounds
        for name, (run, x0) in variants.items():
            tik = time.monotonic()
            float(run(params, x0))
            times[name].append((time.monotonic() - tik) / args.chain)

    out = {}
    for name in variants:
        t = statistics.median(times[name])
        fl = flops_per_pass(seqs[name])
        out[name] = {
            "pass_ms": round(t * 1e3, 3),
            "achieved_tflops": round(fl / t / 1e12, 1),
            "mfu_nominal": (round(fl / t / nominal, 3) if nominal
                            else None),
            "mfu_calibrated": round(fl / t / max(cal), 3),
        }

    base_tf = out["base"]["achieved_tflops"]
    attribution = {
        "f32_numerics_tflops": round(
            out["fast_numerics"]["achieved_tflops"] - base_tf, 1),
        "seq197_padding_tflops": round(
            out["pad256"]["achieved_tflops"] - base_tf, 1),
        "head_dim64_tflops": round(
            out["hd128"]["achieved_tflops"] - base_tf, 1),
        "stacked_all_tflops": round(
            out["stacked"]["achieved_tflops"] - base_tf, 1),
        "note": "each delta is that variant's achieved TFLOP/s minus "
                "base's, per its OWN analytic FLOPs (efficiency, not "
                "work); 'stacked' is all three at once — buckets need "
                "not sum to it (overheads overlap)",
    }

    print(json.dumps({
        "metric": "mfu_bucket_base_tflops",
        "value": base_tf,
        "unit": "TFLOP/s",
        "vs_baseline": None,
        "variants": out,
        "attribution": attribution,
        "calibration_samples_tflops": [round(s / 1e12, 1) for s in cal],
        "peak_nominal_tflops": (round(nominal / 1e12, 1) if nominal
                                else None),
        "config": {"batch": batch, "layers": n_layers, "hidden": d,
                   "inter": inter, "chain": args.chain,
                   "rounds": args.rounds},
        "device_kind": device_kind,
    }))


if __name__ == "__main__":
    main()
