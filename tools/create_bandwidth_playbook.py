"""Generate tc-qdisc bandwidth-throttling ansible playbooks for experiments.

Parity with /root/reference/tools/create_bandwidth_playbook.py:7-36: for each
requested bandwidth, emit add/delete playbooks per host group that shape the
NIC with a token bucket filter. Used to emulate constrained DCN links when
benchmarking adaptive quantization.
"""
import argparse


def tc_command(bandwidth, action, ifname="eth0"):
    command = (f"sudo tc qdisc {action} dev {ifname} root tbf "
               f"rate {bandwidth}mbit burst 32kbit latency 20ms")
    print(command)
    return command


def write_playbook(path, host_group, command):
    with open(path, "w") as script:
        script.write(f"- hosts: {host_group}\n")
        script.write("  tasks:\n")
        script.write("    - name: add bandwidth limitation\n")
        script.write(f"      shell: {command}\n\n")


def create_scripts(bandwidths, host_groups, ifname):
    for bw in bandwidths:
        for group in host_groups:
            write_playbook(f"bw_{bw}mbps_20ms_add_{group}.yml", group,
                           tc_command(bw, "add", ifname))
            write_playbook(f"bw_{bw}mbps_20ms_delete_{group}.yml", group,
                           tc_command(bw, "delete", ifname))


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="Create bandwidth-throttling ansible playbooks")
    parser.add_argument("-bd", "--bandwidth", type=str, required=True,
                        help="comma-delimited bandwidths in Mbps")
    parser.add_argument("-g", "--groups", type=str, default="m,n",
                        help="comma-delimited ansible host groups")
    parser.add_argument("-i", "--ifname", type=str, default="eth0")
    args = parser.parse_args()
    create_scripts(args.bandwidth.split(','), args.groups.split(','),
                   args.ifname)
