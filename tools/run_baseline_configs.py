"""Run the five BASELINE.md configs on the current backend and print one
JSON line per config.

Configs (BASELINE.md "Configs to reproduce"):
  1. ViT-Base single-rank
  2. ViT-Base 2-stage even partition (-pt 1,24,25,48)
  3. ViT-Large 4-stage, auto-partition from a TPU profile via sched-pipeline
  4. BERT-base CoLA 2-stage
  5. DeiT-Base 8-stage + adaptive int8 (QuantPipe)

On a single chip the host driver places every stage on the same device
(round-robin, parallel/pipeline.py:247), so multi-stage configs measure the
full pipeline machinery (stage hand-off, quant edges, adaptive policy) at
single-chip scale; datasets are synthetic under zero egress (the loaders
fall back when ImageNet/GLUE are absent, utils/data.py).

Usage: python tools/run_baseline_configs.py [-c N] [--platform cpu]
"""
import argparse
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CONFIGS = {
    1: {"desc": "vit-base single-rank",
        "args": ["0", "1", "-m", "google/vit-base-patch16-224",
                 "-b", "64", "-u", "8"]},
    2: {"desc": "vit-base 2-stage even partition",
        "args": ["0", "2", "-m", "google/vit-base-patch16-224",
                 "-b", "64", "-u", "8", "-pt", "1,24,25,48"]},
    3: {"desc": "vit-large 4-stage auto-partition (profiles/tpu)",
        "args": ["0", "4", "-m", "google/vit-large-patch16-224",
                 "-b", "64", "-u", "8",
                 "-sm", os.path.join(REPO, "profiles", "tpu", "models.yml"),
                 "-sdt", os.path.join(REPO, "profiles", "tpu",
                                      "device_types.yml"),
                 "-sd", os.path.join(REPO, "profiles", "tpu", "devices.yml"),
                 "-H", "tpu0,tpu1,tpu2,tpu3"]},
    4: {"desc": "bert-base CoLA 2-stage",
        "args": ["0", "2", "-m", "textattack/bert-base-uncased-CoLA",
                 "-b", "64", "-u", "8", "-pt", "1,24,25,48",
                 "--dataset-name", "CoLA"]},
    5: {"desc": "deit-base 8-stage + adaptive int8",
        "args": ["0", "8", "-m", "facebook/deit-base-distilled-patch16-224",
                 "-b", "64", "-u", "8",
                 "-pt", "1,6,7,12,13,18,19,24,25,30,31,36,37,42,43,48",
                 "-q", "8,8,8,8,8,8,8,0"],
        "env": {"ADAPTIVE_QUANT": "HEURISTIC", "SEND_CONSTRAINT": "1000"}},
}


def run_config(n: int, platform: str, dtype: str) -> dict:
    spec = CONFIGS[n]
    missing = [a for a in spec["args"]
               if a.endswith(".yml") and not os.path.exists(a)]
    if missing:
        return {"config": n, "desc": spec["desc"], "rc": "missing-profiles",
                "missing": missing,
                "hint": "generate the TPU profile fixtures first "
                        "(profiles/README.md)"}
    cmd = [sys.executable, os.path.join(REPO, "runtime.py")] + spec["args"] \
        + ["-t", dtype, "--measure-rounds", "2"]
    if platform:
        cmd += ["--platform", platform]
    # APPEND to PYTHONPATH, never replace: the TPU plugin registers via a
    # sitecustomize on the inherited PYTHONPATH (clobbering it leaves
    # JAX_PLATFORMS naming a backend no longer registered in the child)
    pypath = os.pathsep.join(
        p for p in (REPO, os.environ.get("PYTHONPATH")) if p)
    env = dict(os.environ, PYTHONPATH=pypath, **spec.get("env", {}))
    tik = time.monotonic()
    # Explicit-CPU runs get a watchdog; anything else (including the
    # implicit default, which resolves to the chip wherever the axon
    # plugin is installed) gets NONE — killing or abandoning a mid-RPC
    # TPU client wedges the single-tenant tunnel lease for hours
    # (docs/PERF.md), so chip runs wait however long backend init takes.
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, env=env,
            timeout=1800 if platform == "cpu" else None)
    except subprocess.TimeoutExpired:
        return {"config": n, "desc": spec["desc"], "rc": "timeout",
                "wall_s": round(time.monotonic() - tik, 1)}
    wall = time.monotonic() - tik
    result = {"config": n, "desc": spec["desc"], "rc": proc.returncode,
              "wall_s": round(wall, 1)}
    rounds = re.findall(r"round=(\d+) latency_sec=([0-9.]+) "
                        r"throughput_items_sec=([0-9.]+)", proc.stdout)
    match = re.search(r"latency_sec=([0-9.]+) throughput_items_sec=([0-9.]+)",
                      proc.stdout)
    if rounds:
        # round 0 = cold (XLA compiles included, the reference's
        # single-shot methodology); last round = warm steady state
        result["cold_latency_sec"] = float(rounds[0][1])
        result["cold_items_per_sec"] = float(rounds[0][2])
        result["latency_sec"] = float(rounds[-1][1])
        result["items_per_sec"] = float(rounds[-1][2])
    elif match:
        result["latency_sec"] = float(match.group(1))
        result["items_per_sec"] = float(match.group(2))
    else:
        result["tail"] = (proc.stdout + proc.stderr)[-400:]
    return result


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("-c", "--config", type=int, action="append",
                        choices=sorted(CONFIGS),
                        help="configs to run (default: all)")
    parser.add_argument("--platform", default=None,
                        help="force a jax platform (e.g. cpu)")
    parser.add_argument("-t", "--dtype", default="bfloat16",
                        choices=["bfloat16", "float32"])
    args = parser.parse_args()
    for n in args.config or sorted(CONFIGS):
        print(json.dumps(run_config(n, args.platform, args.dtype)),
              flush=True)


if __name__ == "__main__":
    main()
