"""Open-loop load generator for the serving plane (docs/SERVING.md).

Drives tools/serve.py's /generate with a per-class request mix at a
target aggregate arrival rate and reports ONE JSON line (the
chaos_dcn.py idiom) of per-class SLO attainment, goodput, and shed
accounting — the "proof under fire" for the admission/brownout plane:
at 5x sustained overload, interactive goodput should hold while the
excess converts to 503s with a dynamic Retry-After, not to collapse.

OPEN loop: arrivals are scheduled on the clock (every 1/qps seconds),
not gated on completions — the honest overload model. A server that
slows down does not slow the offered load down with it; requests the
client cannot even launch (in-flight cap, a safety valve) are counted
separately as `client_dropped` so a wedged server cannot silently look
like a polite one.

Each request carries its class and a deadline budget (`deadline_ms`,
defaulting to the class SLO): the server sheds it at admission, expires
it in queue, or cancels it mid-flight (HTTP 504) when the budget runs
out — every outcome lands in a distinct counter below.

Outcome taxonomy (per class and aggregate):
- `ok`        HTTP 200 within the class SLO (client-side wall time)
- `ok_late`   HTTP 200, but over the SLO (admitted yet too slow — the
              failure mode admission control exists to prevent)
- `shed`      HTTP 503 with `"shed": true` + Retry-After (admission)
- `degraded`  HTTP 503 with `"degraded": true` (failover window)
- `deadline`  HTTP 504 (expired MID-FLIGHT; cancelled at a decode step)
- `error`     anything else — handler exceptions, connection failures,
              malformed bodies; the CI smoke gates on error == 0

`slo_attainment` = ok / (ok + ok_late): of the requests the server chose
to serve, how many met their SLO. `goodput_rps` = ok / duration: the
rate of USEFUL work — the acceptance metric ("within 20% of the
uncontended value at 5x overload"). Shed requests hurt neither; that is
the point of shedding.

Capacity calibration: `--overload-factor F` first measures the server's
closed-loop sequential service rate for `--calibrate-s` seconds, then
offers F times it — "5x overload" stays 5x on any machine. `--qps`
skips calibration.

Examples:
  # calibrated 5x overload, default 70/20/10 mix, 2s SLOs
  python tools/loadgen.py --port 8321 --overload-factor 5 --duration 8

  # explicit rate + per-class mix/SLO
  python tools/loadgen.py --port 8321 --qps 40 --duration 10 \
      --mix interactive=0.5 --mix batch=0.3 --mix best_effort=0.2 \
      --slo interactive=1000 --slo batch=5000
"""
import argparse
import json
import os
import random
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pipeedge_tpu.serving import (REQUEST_CLASSES,  # noqa: E402
                                  parse_class_map)
from pipeedge_tpu.utils.threads import make_lock  # noqa: E402

DEFAULT_MIX = {"interactive": 0.7, "batch": 0.2, "best_effort": 0.1}
DEFAULT_SLO_MS = {"interactive": 2000.0, "batch": 10000.0,
                  "best_effort": 30000.0}
OUTCOMES = ("ok", "ok_late", "shed", "degraded", "deadline", "error")


def _post(url, obj, timeout):
    """POST JSON; returns (status, body-dict, retry_after | None)."""
    req = urllib.request.Request(
        url, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read()), None
    except urllib.error.HTTPError as exc:
        ra = exc.headers.get("Retry-After")
        try:
            body = json.loads(exc.read())
        except Exception:       # noqa: BLE001 — non-JSON error body
            body = {}
        return exc.code, body, None if ra is None else float(ra)


def calibrate(url, seconds, new_tokens, prompt_len, timeout, seed=0):
    """Closed-loop sequential service rate (requests/s): the capacity
    baseline `--overload-factor` multiplies. The first request is
    discarded as compile warmup. Distribution specs calibrate at their
    LONGEST length (capacity should not be flattered by short draws)."""
    rng = random.Random(seed)
    ids = [[rng.randrange(100) for _ in range(spec_max_len(prompt_len))]]
    body = {"ids": ids, "new_tokens": new_tokens, "class": "interactive"}
    status, _, _ = _post(url, body, timeout)          # warmup (compile)
    if status != 200:
        raise RuntimeError(f"calibration warmup failed: HTTP {status}")
    n = 0
    t0 = time.monotonic()
    while time.monotonic() - t0 < seconds:
        status, _, _ = _post(url, body, timeout)
        if status != 200:
            raise RuntimeError(f"calibration request failed: HTTP {status}")
        n += 1
    dt = time.monotonic() - t0
    if n == 0:
        raise RuntimeError(
            f"calibration made no complete request in {seconds}s")
    return n / dt


WORST_N = 5      # per-class worst-latency request ids kept in the report


def parse_prompt_spec(s):
    """`--prompt-len DIST` -> spec dict. Forms:

    - `N`                    fixed length N (the historical behavior)
    - `uniform:LO:HI`        length drawn per request from [LO, HI]
    - `shared:PFX:TOTAL[:POOL]`  every prompt is TOTAL tokens whose
      first PFX tokens are one of POOL (default 1) DETERMINISTIC shared
      prefixes (seed-derived, so reruns share the same prefixes) — the
      workload shape that exercises the server's prefix trie and
      long-context token-budget admission (docs/SERVING.md).

    Accepts an int/dict unchanged (the in-process callers)."""
    if isinstance(s, dict):
        return s
    if isinstance(s, int) or (isinstance(s, str) and s.isdigit()):
        n = int(s)
        if n < 1:
            raise ValueError("prompt length must be >= 1")
        return {"dist": "fixed", "len": n}
    parts = str(s).split(":")
    try:
        if parts[0] == "uniform" and len(parts) == 3:
            lo, hi = int(parts[1]), int(parts[2])
            if not 1 <= lo <= hi:
                raise ValueError
            return {"dist": "uniform", "lo": lo, "hi": hi}
        if parts[0] == "shared" and len(parts) in (3, 4):
            pfx, total = int(parts[1]), int(parts[2])
            pool = int(parts[3]) if len(parts) == 4 else 1
            if not (1 <= pfx < total and pool >= 1):
                raise ValueError
            return {"dist": "shared", "prefix": pfx, "total": total,
                    "pool": pool}
    except ValueError:
        pass
    raise ValueError(
        f"bad --prompt-len {s!r}: expected N, uniform:LO:HI, or "
        "shared:PFX:TOTAL[:POOL]")


def parse_burst_spec(s):
    """`--burst AT:N:LEN[:WINDOW]` -> spec dict (None passes through).

    At fraction AT of the run (0..1), N interactive requests with
    LEN-token prompts launch back-to-back — a seeded long-prompt spike
    riding an otherwise steady run. The spike's own outcomes/latencies
    report under `burst`; served steady-state requests launched inside
    the WINDOW seconds after the spike (default 2.0) report separately
    as `burst.during_ms` — the decode-latency-under-burst number the
    chunked-prefill A/B compares (docs/SERVING.md)."""
    if s is None or isinstance(s, dict):
        return s
    parts = str(s).split(":")
    try:
        if len(parts) in (3, 4):
            at, cnt, ln = float(parts[0]), int(parts[1]), int(parts[2])
            window = float(parts[3]) if len(parts) == 4 else 2.0
            if 0.0 <= at <= 1.0 and cnt >= 1 and ln >= 1 and window > 0:
                return {"at": at, "n": cnt, "len": ln,
                        "window_s": window}
    except ValueError:
        pass
    raise ValueError(
        f"bad --burst {s!r}: expected AT:N:LEN[:WINDOW_S] "
        "with AT a fraction in [0, 1]")


def spec_max_len(spec) -> int:
    """Longest prompt a spec can emit (capacity/calibration sizing)."""
    spec = parse_prompt_spec(spec)
    return {"fixed": spec.get("len"), "uniform": spec.get("hi"),
            "shared": spec.get("total")}[spec["dist"]]


def prompt_ids(spec, rng, base_seed: int):
    """One request's prompt token list under `spec`. Shared prefixes
    derive from `base_seed` + the drawn pool index ONLY — every request
    (and every rerun with the same seed) that draws pool index k gets
    byte-identical prefix tokens, which is what makes the server-side
    prefix-hit counters deterministic."""
    spec = parse_prompt_spec(spec)
    if spec["dist"] == "fixed":
        return [rng.randrange(100) for _ in range(spec["len"])]
    if spec["dist"] == "uniform":
        n = rng.randint(spec["lo"], spec["hi"])
        return [rng.randrange(100) for _ in range(n)]
    pool_idx = rng.randrange(spec["pool"])
    pfx_rng = random.Random(1_000_003 * base_seed + 7919 * pool_idx + 13)
    prefix = [pfx_rng.randrange(100) for _ in range(spec["prefix"])]
    suffix = [rng.randrange(100)
              for _ in range(spec["total"] - spec["prefix"])]
    return prefix + suffix


class _Stats:
    """Per-class outcome/latency accumulator (one lock, short holds)."""

    def __init__(self, classes, during_window=None):
        self._lock = make_lock("loadgen.stats")
        self.counts = {c: dict.fromkeys(OUTCOMES, 0) for c in classes}
        self.latencies = {c: [] for c in classes}     # ok + ok_late, ms
        # served latencies of requests LAUNCHED inside [lo, hi] seconds
        # from start — the burst spike's blast-radius window
        self.during_window = during_window
        self.during = []
        # per-class worst-N (latency_ms, rid) of served requests: the
        # cross-reference from a bench run into trace_report --request
        # and the flight recorder's postmortem bundles
        self.worst = {c: [] for c in classes}
        # request ids the server answered 504 (each one triggered a
        # postmortem bundle server-side)
        self.deadline_rids = []
        self.retry_after = []
        self.client_dropped = 0
        self.first_error = None

    def record(self, cls, outcome, latency_ms=None, retry_after=None,
               error=None, rid=None, offset=None):
        with self._lock:
            self.counts[cls][outcome] += 1
            if latency_ms is not None:
                self.latencies[cls].append(latency_ms)
                if (self.during_window is not None and offset is not None
                        and self.during_window[0] <= offset
                        <= self.during_window[1]):
                    self.during.append(latency_ms)
                if rid is not None:
                    w = self.worst[cls]
                    w.append((latency_ms, rid))
                    w.sort(reverse=True)
                    del w[WORST_N:]
            if outcome == "deadline" and rid is not None \
                    and len(self.deadline_rids) < WORST_N:
                self.deadline_rids.append(rid)
            if retry_after is not None:
                self.retry_after.append(retry_after)
            if error is not None and self.first_error is None:
                self.first_error = f"{cls}: {error}"

    def drop(self):
        with self._lock:
            self.client_dropped += 1


def _percentile(vals, q):
    if not vals:
        return None
    vals = sorted(vals)
    idx = min(len(vals) - 1, max(0, int(round(q / 100.0 * (len(vals) - 1)))))
    return round(vals[idx], 3)


def parse_ramp_spec(spec):
    """`ramp:LO:HI[:HOLD]` -> {"lo", "hi", "hold"}, or None when `spec`
    is a plain arrival-process name. LO/HI are the offered req/s at the
    ramp floor and plateau; HOLD is the plateau's share of the run in
    [0, 1) (default 1/3). The offered rate rises LO->HI over the first
    (1-HOLD)/2 of the run, holds at HI, then falls symmetrically back
    to LO — the autoscale test workload (scale up on the rise, hold
    through the plateau, scale back down on the fall)."""
    if spec is None or not str(spec).startswith("ramp:"):
        return None
    parts = str(spec).split(":")
    if len(parts) not in (3, 4):
        raise ValueError(f"bad ramp spec {spec!r} "
                         "(expected ramp:LO:HI[:HOLD])")
    try:
        lo, hi = float(parts[1]), float(parts[2])
        hold = float(parts[3]) if len(parts) == 4 else 1.0 / 3.0
    except ValueError:
        raise ValueError(f"bad ramp spec {spec!r}: LO/HI/HOLD must be "
                         "numbers") from None
    if lo <= 0 or hi < lo:
        raise ValueError(f"bad ramp spec {spec!r}: need 0 < LO <= HI")
    if not 0.0 <= hold < 1.0:
        raise ValueError(f"bad ramp spec {spec!r}: HOLD is the plateau "
                         "fraction of the run, in [0, 1)")
    return {"lo": lo, "hi": hi, "hold": hold}


def ramp_rate(t, duration_s, ramp):
    """Instantaneous offered rate (req/s) `t` seconds into a
    `ramp:LO:HI[:HOLD]` run: piecewise-linear rise -> hold -> fall."""
    edge = duration_s * (1.0 - ramp["hold"]) / 2.0
    lo, hi = ramp["lo"], ramp["hi"]
    if t < edge:                      # edge > 0 whenever this is reached
        return lo + (hi - lo) * (t / edge)
    if t <= duration_s - edge:
        return hi
    if t >= duration_s:
        return lo
    return hi - (hi - lo) * ((t - (duration_s - edge)) / edge)


def _ramp_offsets(duration_s, ramp):
    # Step `t += 1/rate(t)` across the run so the instantaneous spacing
    # tracks the piecewise-linear offered rate. Deterministic — the
    # ramp analogue of the uniform grid; `seed` still drives the class
    # draw and prompt sampling, so a run is reproducible end-to-end.
    offsets, t = [], 0.0
    while t < duration_s:
        offsets.append(t)
        t += 1.0 / ramp_rate(t, duration_s, ramp)
    return offsets


def arrival_offsets(n, qps, arrival="uniform", rng=None, duration_s=None):
    """Seconds-from-start launch time of each of `n` arrivals at mean
    rate `qps`. `uniform` is the fixed 1/qps grid (the historical
    behavior); `poisson` draws seeded exponential gaps — an open-loop
    memoryless arrival process whose bursts stress the admission queue
    harder than a metronome at the same mean rate. `ramp:LO:HI[:HOLD]`
    (see `parse_ramp_spec`) ignores `n`/`qps` and shapes the rate over
    `duration_s` instead — the arrival count falls out of the rate
    integral. Pure: same (n, qps, arrival, rng seed) -> same offsets,
    so a serve-recipe run is reproducible end-to-end (the bench-record
    contract)."""
    ramp = parse_ramp_spec(arrival)
    if ramp is not None:
        if duration_s is None or duration_s <= 0:
            raise ValueError("ramp arrival needs duration_s > 0")
        return _ramp_offsets(duration_s, ramp)
    if arrival == "uniform":
        return [i / qps for i in range(n)]
    if arrival != "poisson":
        raise ValueError(f"unknown arrival process {arrival!r} "
                         "(expected 'uniform', 'poisson' or "
                         "'ramp:LO:HI[:HOLD]')")
    if rng is None:
        rng = random.Random(0)
    offsets, t = [], 0.0
    for _ in range(n):
        offsets.append(t)
        t += rng.expovariate(qps)
    return offsets


def _one_request(url, cls, slo_ms, deadline_ms, new_tokens, prompt_spec,
                 timeout, stats, rng_seed, base_seed, offset=None):
    rng = random.Random(rng_seed)
    ids = [prompt_ids(prompt_spec, rng, base_seed)]
    body = {"ids": ids, "new_tokens": new_tokens, "class": cls}
    if deadline_ms is not None:
        body["deadline_ms"] = deadline_ms
    t0 = time.monotonic()
    try:
        status, resp, retry_after = _post(url, body, timeout)
    except Exception as exc:    # noqa: BLE001 — connection-level failure
        stats.record(cls, "error", error=repr(exc))
        return
    ms = (time.monotonic() - t0) * 1e3
    rid = resp.get("rid") if isinstance(resp, dict) else None
    if status == 200:
        outcome = "ok" if (slo_ms is None or ms <= slo_ms) else "ok_late"
        stats.record(cls, outcome, latency_ms=ms, rid=rid, offset=offset)
    elif status == 503 and resp.get("shed"):
        stats.record(cls, "shed", retry_after=retry_after, rid=rid)
    elif status == 503 and resp.get("degraded"):
        stats.record(cls, "degraded", retry_after=retry_after, rid=rid)
    elif status == 504 and resp.get("deadline_exceeded"):
        stats.record(cls, "deadline", rid=rid)
    else:
        stats.record(cls, "error",
                     error=f"HTTP {status}: {resp.get('error', resp)!r}")


def run_load(url, duration_s, qps, mix=None, slo_ms=None,
             deadline_from_slo=True, new_tokens=8, prompt_len=6,
             timeout=120.0, max_inflight=128, seed=0,
             arrival="uniform", burst=None):
    """Offer `qps` requests/s for `duration_s` with the per-class `mix`;
    return the report dict (see module doc for the outcome taxonomy).
    Importable — the overload acceptance test, the CI smoke, and the
    benchkit serve recipe all call this in-process instead of shelling
    out. `seed` drives EVERYTHING random end-to-end (arrival process,
    class draw, prompt token sampling) and rides the report. `burst`
    (see `parse_burst_spec`) injects a seeded mid-run long-prompt spike
    whose own outcomes — and the steady-state latencies inside its
    blast-radius window — report under the `burst` key."""
    mix = dict(DEFAULT_MIX if mix is None else mix)
    unknown = set(mix) - set(REQUEST_CLASSES)
    if unknown:
        raise ValueError(f"unknown classes in mix: {sorted(unknown)}")
    total_w = sum(mix.values())
    ramp = parse_ramp_spec(arrival)
    if total_w <= 0 or duration_s <= 0 \
            or (ramp is None and (qps is None or qps <= 0)):
        raise ValueError("mix weights, qps and duration must be > 0")
    prompt_spec = parse_prompt_spec(prompt_len)
    slo_ms = dict(DEFAULT_SLO_MS if slo_ms is None else slo_ms)
    burst = parse_burst_spec(burst)
    burst_at_s = None if burst is None else burst["at"] * duration_s
    classes = sorted(mix)
    weights = [mix[c] / total_w for c in classes]
    stats = _Stats(classes,
                   during_window=None if burst is None else
                   (burst_at_s, burst_at_s + burst["window_s"]))
    # the spike's own accounting stays OUT of the per-class stats: the
    # steady-state goodput/attainment/latency numbers must measure the
    # same offered load with and without --burst
    burst_stats = None if burst is None else _Stats(["interactive"])
    burst_threads = []

    def _fire_burst():
        # back-to-back, NOT semaphore-gated: the spike must hit the
        # server even when the client is at its in-flight cap
        for j in range(burst["n"]):
            def bwork(j=j):
                _one_request(url, "interactive",
                             slo_ms.get("interactive"),
                             slo_ms.get("interactive")
                             if deadline_from_slo else None,
                             new_tokens,
                             {"dist": "fixed", "len": burst["len"]},
                             timeout, burst_stats,
                             seed * 7907 + j, seed)
            t = threading.Thread(target=bwork, daemon=True)
            t.start()
            burst_threads.append(t)

    rng = random.Random(seed)
    inflight = threading.Semaphore(max_inflight)
    threads = []
    if ramp is not None:
        offsets = arrival_offsets(0, None, arrival, rng,
                                  duration_s=duration_s)
        n = len(offsets)
        qps = n / duration_s         # mean offered rate, for the report
    else:
        n = max(1, int(round(qps * duration_s)))
        offsets = arrival_offsets(n, qps, arrival, rng)
    t0 = time.monotonic()
    burst_fired = False
    for i in range(n):
        target = t0 + offsets[i]         # open loop: arrivals on the clock
        if burst is not None and not burst_fired \
                and target >= t0 + burst_at_s:
            # the spike launches ON its clock tick, not the next arrival
            bd = t0 + burst_at_s - time.monotonic()
            if bd > 0:
                time.sleep(bd)
            _fire_burst()
            burst_fired = True
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        cls = rng.choices(classes, weights=weights)[0]
        if not inflight.acquire(blocking=False):
            stats.drop()                 # safety valve, not backpressure
            continue
        cls_slo = slo_ms.get(cls)
        deadline = cls_slo if deadline_from_slo else None

        def work(cls=cls, cls_slo=cls_slo, deadline=deadline, i=i):
            try:
                _one_request(url, cls, cls_slo, deadline, new_tokens,
                             prompt_spec, timeout, stats,
                             seed * 100003 + i, seed, offset=offsets[i])
            finally:
                inflight.release()

        t = threading.Thread(target=work, daemon=True)
        t.start()
        threads.append(t)
    if burst is not None and not burst_fired:
        bd = t0 + burst_at_s - time.monotonic()
        if bd > 0:
            time.sleep(bd)
        _fire_burst()
    for t in threads:
        t.join(timeout=timeout)
    for t in burst_threads:
        t.join(timeout=timeout)
    wall = time.monotonic() - t0
    report = {"url": url, "duration_s": round(wall, 3),
              "offered_qps": round(qps, 3), "requests": n,
              "seed": seed, "arrival": arrival,
              "prompt_len": prompt_spec,
              "client_dropped": stats.client_dropped,
              "classes": {}, "totals": dict.fromkeys(OUTCOMES, 0)}
    if ramp is not None:
        # parsed spec echoed alongside the raw `arrival` string: the
        # autoscale CI job and bench recipe read the shape from here
        report["ramp"] = dict(ramp)
    all_lat = []
    for c in classes:
        counts = stats.counts[c]
        served = counts["ok"] + counts["ok_late"]
        sent = sum(counts.values())
        lat = stats.latencies[c]
        all_lat.extend(lat)
        report["classes"][c] = {
            **counts, "sent": sent,
            "slo_ms": slo_ms.get(c),
            "slo_attainment": (None if not served
                               else round(counts["ok"] / served, 4)),
            "goodput_rps": round(counts["ok"] / wall, 3),
            "latency_ms": {"p50": _percentile(lat, 50),
                           "p95": _percentile(lat, 95),
                           "p99": _percentile(lat, 99)},
            # worst-N served requests BY ID: feed one to
            # `trace_report --request` (or cross-reference it against
            # the server's postmortem bundles) to explain the tail
            "worst": [{"rid": rid, "ms": round(ms, 3)}
                      for ms, rid in stats.worst[c]],
        }
        for k in OUTCOMES:
            report["totals"][k] += counts[k]
    # aggregate served-latency percentiles: the serve recipe's
    # latency_ms block (per-class views stay under classes.*)
    report["latency_ms"] = {"p50": _percentile(all_lat, 50),
                            "p95": _percentile(all_lat, 95),
                            "p99": _percentile(all_lat, 99),
                            "n": len(all_lat)}
    ra = stats.retry_after
    report["retry_after"] = {
        "n": len(ra), "min": min(ra) if ra else None,
        "max": max(ra) if ra else None,
        "distinct": len({round(v, 3) for v in ra})}
    # 504'd request ids: each one triggered a deadline postmortem bundle
    # server-side — the bench-to-bundle cross-reference
    report["deadline_rids"] = stats.deadline_rids
    report["first_error"] = stats.first_error
    if burst is not None:
        bc = burst_stats.counts["interactive"]
        blat = burst_stats.latencies["interactive"]
        report["burst"] = {
            "at_s": round(burst_at_s, 3), "n": burst["n"],
            "prompt_len": burst["len"], "window_s": burst["window_s"],
            **{k: bc[k] for k in OUTCOMES},
            # the spike's own end-to-end latencies (long prompt + decode)
            "latency_ms": {"p50": _percentile(blat, 50),
                           "p95": _percentile(blat, 95),
                           "p99": _percentile(blat, 99)},
            # steady-state served latencies launched inside the blast-
            # radius window — THE burst-decode number the chunked-
            # prefill A/B compares
            "during_ms": {"p50": _percentile(stats.during, 50),
                          "p95": _percentile(stats.during, 95),
                          "p99": _percentile(stats.during, 99),
                          "n": len(stats.during)},
            "first_error": burst_stats.first_error,
        }
    return report


def merge_class_map(pairs, what, default):
    """CLI `CLASS=VALUE` pairs merged over `default` — shared with the
    benchkit serve recipe (raises ValueError on malformed pairs)."""
    return {**default, **parse_class_map(pairs, what)}


def _parse_class_map(pairs, what, default):
    try:
        return merge_class_map(pairs, what, default)
    except ValueError as exc:
        raise SystemExit(str(exc)) from None


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8321)
    p.add_argument("--duration", type=float, default=8.0,
                   help="seconds of offered load")
    rate = p.add_mutually_exclusive_group()
    rate.add_argument("--qps", type=float, default=None,
                      help="explicit aggregate arrival rate")
    rate.add_argument("--overload-factor", type=float, default=None,
                      help="offer FACTOR x the measured sequential "
                           "service rate (see --calibrate-s)")
    p.add_argument("--calibrate-s", type=float, default=3.0,
                   help="closed-loop capacity measurement window used by "
                        "--overload-factor")
    p.add_argument("--mix", action="append", metavar="CLASS=WEIGHT",
                   help=f"per-class arrival weight (default {DEFAULT_MIX})")
    p.add_argument("--slo", action="append", metavar="CLASS=MS",
                   help="per-class SLO (and deadline_ms budget; default "
                        f"{DEFAULT_SLO_MS})")
    p.add_argument("--no-deadline", action="store_true",
                   help="do not send deadline_ms (SLO still scored "
                        "client-side; the server never sheds on expiry)")
    p.add_argument("--new-tokens", type=int, default=8)
    p.add_argument("--prompt-len", default="6",
                   help="prompt-length distribution: N (fixed), "
                        "uniform:LO:HI, or shared:PFX:TOTAL[:POOL] "
                        "(POOL seed-deterministic shared prefixes — "
                        "exercises the server's prefix trie and "
                        "long-context admission); recorded in the "
                        "JSON line")
    p.add_argument("--timeout", type=float, default=120.0)
    p.add_argument("--max-inflight", type=int, default=128,
                   help="client-side thread cap (arrivals beyond it are "
                        "counted as client_dropped, not silently delayed)")
    p.add_argument("--seed", type=int, default=0,
                   help="drives the arrival process, class draw and "
                        "prompt sampling; recorded in the JSON line")
    p.add_argument("--arrival", default="uniform",
                   metavar="{uniform,poisson,ramp:LO:HI[:HOLD]}",
                   help="arrival process: fixed 1/qps grid, seeded "
                        "exponential gaps (bursty open-loop traffic), "
                        "or a piecewise-linear offered-load ramp "
                        "LO->HI->LO req/s with a HOLD-fraction plateau "
                        "(default 1/3) — the autoscale test workload; "
                        "a ramp sets the rate itself, so --qps/"
                        "--overload-factor must be omitted")
    p.add_argument("--burst", default=None, metavar="AT:N:LEN[:WINDOW]",
                   help="inject a seeded long-prompt spike: at fraction "
                        "AT of the run, N interactive requests with "
                        "LEN-token prompts launch back-to-back; the "
                        "spike's outcomes and the steady-state latency "
                        "inside the WINDOW-second blast radius (default "
                        "2.0) ride the JSON line under `burst`")
    p.add_argument("--indent", action="store_true",
                   help="pretty-print instead of the one-line record")
    args = p.parse_args()

    try:
        ramp = parse_ramp_spec(args.arrival)
    except ValueError as exc:
        p.error(str(exc))
    if ramp is None and args.qps is None and args.overload_factor is None:
        p.error("one of --qps / --overload-factor is required (unless "
                "--arrival ramp:LO:HI[:HOLD] sets the offered rate)")
    if ramp is not None and (args.qps is not None
                             or args.overload_factor is not None):
        p.error("--arrival ramp:... sets the offered rate itself; "
                "drop --qps / --overload-factor")

    url = f"http://{args.host}:{args.port}/generate"
    qps = args.qps
    calibrated = None
    if qps is None and args.overload_factor is not None:
        calibrated = calibrate(url, args.calibrate_s, args.new_tokens,
                               args.prompt_len, args.timeout,
                               seed=args.seed)
        qps = calibrated * args.overload_factor
        print(f"calibrated capacity {calibrated:.2f} req/s -> offering "
              f"{qps:.2f} req/s ({args.overload_factor:g}x)",
              file=sys.stderr)
    report = run_load(
        url, args.duration, qps,
        mix=_parse_class_map(args.mix, "--mix", DEFAULT_MIX),
        slo_ms=_parse_class_map(args.slo, "--slo", DEFAULT_SLO_MS),
        deadline_from_slo=not args.no_deadline,
        new_tokens=args.new_tokens, prompt_len=args.prompt_len,
        timeout=args.timeout, max_inflight=args.max_inflight,
        seed=args.seed, arrival=args.arrival, burst=args.burst)
    if calibrated is not None:
        report["calibrated_capacity_rps"] = round(calibrated, 3)
        report["overload_factor"] = args.overload_factor
    print(json.dumps(report, indent=2 if args.indent else None,
                     sort_keys=True))
    errors = report["totals"]["error"] \
        + report.get("burst", {}).get("error", 0)
    return 0 if errors == 0 else 2


if __name__ == "__main__":
    sys.exit(main())
