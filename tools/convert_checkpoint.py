"""Convert a reference-format npz weights archive into per-stage Orbax
checkpoints (one directory per pipeline stage), for `runtime.py --comm dcn
--stage-ckpt`: each rank then restores exactly its own stage shard.

Usage:
    python tools/convert_checkpoint.py -m MODEL -M weights.npz \
        -pt 1,24,25,48 -o ckpts/
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pipeedge_tpu.models import registry  # noqa: E402
from pipeedge_tpu.utils import checkpoint as ckpt  # noqa: E402

logging.basicConfig(stream=sys.stdout, level=logging.INFO,
                    format="%(message)s")
logger = logging.getLogger(__name__)


def main():
    parser = argparse.ArgumentParser(
        description="npz -> per-stage Orbax checkpoints",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-m", "--model-name", required=True,
                        choices=registry.get_model_names())
    parser.add_argument("-M", "--model-file", default=None,
                        help="npz weights (default: the model's default file)")
    parser.add_argument("-pt", "--partition", required=True,
                        help="comma-delimited layer pairs, e.g. '1,24,25,48'")
    parser.add_argument("-o", "--output-dir", required=True)
    args = parser.parse_args()

    nums = [int(x) for x in args.partition.split(",")]
    assert len(nums) % 2 == 0, "partition must be layer pairs"
    partition = list(zip(nums[::2], nums[1::2]))
    npz = args.model_file or registry.get_model_default_weights_file(
        args.model_name)
    dirs = ckpt.save_stage_checkpoints(args.model_name, npz,
                                       args.output_dir, partition)
    for i, d in enumerate(dirs):
        logger.info("stage %d [%d, %d] -> %s", i, *partition[i], d)


if __name__ == "__main__":
    from pipeedge_tpu.utils import apply_env_platform
    apply_env_platform()
    main()
