"""DCN edge microbenchmark: wire-byte compression and overlap efficiency.

Measures the two claims of the overlapped int8 wire path (the DCN data-path
rebuild) so they are recorded, not asserted:

1. **Wire bytes per microbatch** — a ViT-Large-shaped activation
   (ubatch 8 x 197 x 1024, fp32) encoded as a v2 wire frame at bit 0 / 8 /
   4: activation-payload bytes (what replaces the raw fp32 tensors on the
   socket; exactly 32/bit smaller) and total frame bytes (payload + the
   O(ubatch) scale/shift/shape metadata — the number the transport monitor
   hooks see). Also pushes both frame kinds through a real loopback
   `DistDcnContext` edge and reports edge bytes/sec and frames/sec, so the
   byte reduction is visible as wall-clock transfer gain.

2. **Overlap efficiency** — steady-state microbatch latency of a loopback
   `DcnPipelineStage` in the pre-overlap configuration (single-phase
   `work_cb`, queue depth 1: compute, device->host readback and send
   serialize) vs the overlapped configuration (dispatch/readback split,
   depth 2: readback drains on the send thread while the next microbatch's
   compute dispatches). Phase costs are modeled with fixed sleeps
   (dispatch ~= readback), so the ideal speedup is ~2x and the measured
   number is the threading machinery's real overlap efficiency; the
   depth-1 split variant is reported alongside to separate the split's
   contribution from the buffering's.

CPU-safe (JAX_PLATFORMS=cpu) — nothing here needs a TPU. Prints ONE JSON
line, BENCH-record style.

Usage: JAX_PLATFORMS=cpu python tools/bench_dcn_edge.py
"""
import json
import os
import queue
import socket
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

UBATCH_SHAPE = (8, 197, 1024)   # ViT-Large hidden-state microbatch (b=8)
N_FRAMES = 8                    # loopback transfer reps per frame kind
N_UBATCH = 24                   # stage-overlap stream length
WORK_MS = 20.0                  # modeled dispatch (compute) cost
DRAIN_MS = 20.0                 # modeled readback (D2H + encode) cost


def _free_port() -> int:
    with socket.create_server(("127.0.0.1", 0)) as s:
        return s.getsockname()[1]


def bench_wire_bytes():
    """Frame sizes + loopback transfer rate for fp32 vs int8 vs 4-bit."""
    import jax.numpy as jnp

    from pipeedge_tpu.comm import dcn, wire

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=UBATCH_SHAPE).astype(np.float32))
    frames = {}
    for bit in (0, 8, 4):
        parts = wire.wire_encode_device(x, bit).finalize()
        frames[bit] = {
            "parts": parts,
            "payload_bytes": wire.frame_payload_bytes(parts),
            "total_bytes": wire.frame_wire_bytes(parts),
        }

    # real loopback edge: bytes/sec at each bitwidth
    ctx = dcn.DistDcnContext(1, 0, [("127.0.0.1", _free_port())])
    ctx.init()
    rates = {}
    try:
        for bit, f in frames.items():
            ctx.send_tensors(0, f["parts"])     # warm the self-connection
            ctx.recv_tensors(0, timeout=30)

            def feed(parts=f["parts"]):         # stream, don't ping-pong:
                for _ in range(N_FRAMES):       # throughput, not latency
                    ctx.send_tensors(0, parts)

            import threading
            feeder = threading.Thread(target=feed, daemon=True)
            tik = time.monotonic()
            feeder.start()
            got = 0
            for _ in range(N_FRAMES):
                got += wire.frame_wire_bytes(ctx.recv_tensors(0, timeout=30))
            dt = time.monotonic() - tik
            feeder.join()
            rates[bit] = {"mbytes_per_sec": round(got / dt / 1e6, 1),
                          "frames_per_sec": round(N_FRAMES / dt, 1)}
    finally:
        ctx.shutdown()

    fp32 = frames[0]
    out = {"fp32_payload_bytes_per_ubatch": fp32["payload_bytes"],
           "fp32_total_bytes_per_ubatch": fp32["total_bytes"]}
    for bit in (8, 4):
        f = frames[bit]
        out[f"int{bit}_payload_bytes_per_ubatch"] = f["payload_bytes"]
        out[f"int{bit}_total_bytes_per_ubatch"] = f["total_bytes"]
        out[f"int{bit}_payload_reduction"] = round(
            fp32["payload_bytes"] / f["payload_bytes"], 3)
        out[f"int{bit}_total_reduction"] = round(
            fp32["total_bytes"] / f["total_bytes"], 3)
    out["loopback_edge"] = {f"bit{b}": r for b, r in rates.items()}
    return out


def bench_overlap():
    """Steady-state ubatch latency: serialized (pre-overlap) vs overlapped."""
    from pipeedge_tpu.comm import dcn

    ctx = dcn.DistDcnContext(1, 0, [("127.0.0.1", _free_port())])
    ctx.init()

    def run(depth, split):
        results = queue.Queue()

        def dispatch(ts):
            time.sleep(WORK_MS / 1e3)
            return ts

        def readback(ts):
            time.sleep(DRAIN_MS / 1e3)
            return ts

        if split:
            stage = dcn.DcnPipelineStage(
                ctx, None, None, dispatch_cb=dispatch, readback_cb=readback,
                depth=depth, results_cb=results.put)
        else:       # the pre-overlap contract: both phases on one thread
            stage = dcn.DcnPipelineStage(
                ctx, None, None, work_cb=lambda ts: readback(dispatch(ts)),
                depth=depth, results_cb=results.put)
        stage.start()
        try:
            tik = time.monotonic()
            for i in range(N_UBATCH):
                stage.enqueue_tensors([np.full((1,), i, np.int32)])
            outs = [results.get(timeout=120) for _ in range(N_UBATCH)]
            dt = time.monotonic() - tik
        finally:
            stage.stop()
        assert [int(o[0][0]) for o in outs] == list(range(N_UBATCH)), \
            "FIFO order violated"
        return dt / N_UBATCH * 1e3

    try:
        serialized = run(depth=1, split=False)
        split_d1 = run(depth=1, split=True)
        overlapped = run(depth=2, split=True)
    finally:
        ctx.shutdown()
    return {
        "modeled_work_ms": WORK_MS,
        "modeled_drain_ms": DRAIN_MS,
        "depth1_serialized_ubatch_ms": round(serialized, 2),
        "depth1_split_ubatch_ms": round(split_d1, 2),
        "depth2_overlapped_ubatch_ms": round(overlapped, 2),
        # serialized costs work+drain per ubatch; perfect overlap costs
        # max(work, drain) — efficiency 1.0 means the full phase overlap
        # was realized by the dispatch/readback split + depth-2 buffering
        "overlap_speedup": round(serialized / overlapped, 3),
        "overlap_efficiency": round(
            (serialized - overlapped) /
            (serialized - max(WORK_MS, DRAIN_MS)), 3),
    }


def main():
    record = {"metric": "dcn_edge_wire_and_overlap",
              "ubatch_shape": list(UBATCH_SHAPE)}
    record.update(bench_wire_bytes())
    record["overlap"] = bench_overlap()
    # headline: the two acceptance numbers
    record["value"] = record["int8_payload_reduction"]
    record["unit"] = "x fewer activation wire bytes at int8 vs fp32"
    print(json.dumps(record))


if __name__ == "__main__":
    main()
