"""DCN edge microbenchmark: wire-byte compression and overlap efficiency.

Measures the two claims of the overlapped int8 wire path (the DCN data-path
rebuild) so they are recorded, not asserted:

1. **Wire bytes per microbatch** — a ViT-Large-shaped activation
   (ubatch 8 x 197 x 1024, fp32) encoded as a v2 wire frame at bit 0 / 8 /
   4: activation-payload bytes (what replaces the raw fp32 tensors on the
   socket; exactly 32/bit smaller) and total frame bytes (payload + the
   O(ubatch) scale/shift/shape metadata — the number the transport monitor
   hooks see). Also pushes both frame kinds through a real loopback
   `DistDcnContext` edge and reports edge bytes/sec and frames/sec, so the
   byte reduction is visible as wall-clock transfer gain.

2. **Overlap efficiency** — steady-state microbatch latency of a loopback
   `DcnPipelineStage` in the pre-overlap configuration (single-phase
   `work_cb`, queue depth 1: compute, device->host readback and send
   serialize) vs the overlapped configuration (dispatch/readback split,
   depth 2: readback drains on the send thread while the next microbatch's
   compute dispatches). Phase costs are modeled with fixed sleeps
   (dispatch ~= readback), so the ideal speedup is ~2x and the measured
   number is the threading machinery's real overlap efficiency; the
   depth-1 split variant is reported alongside to separate the split's
   contribution from the buffering's.

3. **Transport-tier latency A/B** (`--latency`) — a loopback world-4
   fleet (data rank + one relay stage + two idle spares, the world-4
   shape a 1-stage schedule runs) streams ViT-shaped microbatches over
   each transport tier of docs/DCN_WIRE.md's selection matrix — legacy
   v2 socket, zero-copy socket (pooled recv), colocated hand-off — and
   reports, ONE JSON line per tier: individually-dispatched p50/p99
   end-to-end microbatch latency, the streamed steady-state ubatch time,
   and their ratio (the BENCH_r05 "10× gap" number; ROADMAP item 5's
   target is ratio ≤ 2 on the colocated path).

CPU-safe (JAX_PLATFORMS=cpu) — nothing here needs a TPU. Prints ONE JSON
line, BENCH-record style (one line per tier in --latency mode).

Usage: JAX_PLATFORMS=cpu python tools/bench_dcn_edge.py [--latency]
"""
import argparse
import json
import os
import queue
import socket
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pipeedge_tpu.telemetry.report import _percentile  # noqa: E402 - one
# percentile estimator across the latency benches and the span reports

UBATCH_SHAPE = (8, 197, 1024)   # ViT-Large hidden-state microbatch (b=8)
N_FRAMES = 8                    # loopback transfer reps per frame kind
N_UBATCH = 24                   # stage-overlap stream length
WORK_MS = 20.0                  # modeled dispatch (compute) cost
DRAIN_MS = 20.0                 # modeled readback (D2H + encode) cost


def _free_port() -> int:
    with socket.create_server(("127.0.0.1", 0)) as s:
        return s.getsockname()[1]


def bench_wire_bytes():
    """Frame sizes + loopback transfer rate for fp32 vs int8 vs 4-bit."""
    import jax.numpy as jnp

    from pipeedge_tpu.comm import dcn, wire

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=UBATCH_SHAPE).astype(np.float32))
    frames = {}
    for bit in (0, 8, 4):
        parts = wire.wire_encode_device(x, bit).finalize()
        frames[bit] = {
            "parts": parts,
            "payload_bytes": wire.frame_payload_bytes(parts),
            "total_bytes": wire.frame_wire_bytes(parts),
        }

    # real loopback edge: bytes/sec at each bitwidth
    ctx = dcn.DistDcnContext(1, 0, [("127.0.0.1", _free_port())])
    ctx.init()
    rates = {}
    try:
        for bit, f in frames.items():
            ctx.send_tensors(0, f["parts"])     # warm the self-connection
            ctx.recv_tensors(0, timeout=30)

            def feed(parts=f["parts"]):         # stream, don't ping-pong:
                for _ in range(N_FRAMES):       # throughput, not latency
                    ctx.send_tensors(0, parts)

            import threading
            feeder = threading.Thread(target=feed, daemon=True)
            tik = time.monotonic()
            feeder.start()
            got = 0
            for _ in range(N_FRAMES):
                got += wire.frame_wire_bytes(ctx.recv_tensors(0, timeout=30))
            dt = time.monotonic() - tik
            feeder.join()
            rates[bit] = {"mbytes_per_sec": round(got / dt / 1e6, 1),
                          "frames_per_sec": round(N_FRAMES / dt, 1)}
    finally:
        ctx.shutdown()

    fp32 = frames[0]
    out = {"fp32_payload_bytes_per_ubatch": fp32["payload_bytes"],
           "fp32_total_bytes_per_ubatch": fp32["total_bytes"]}
    for bit in (8, 4):
        f = frames[bit]
        out[f"int{bit}_payload_bytes_per_ubatch"] = f["payload_bytes"]
        out[f"int{bit}_total_bytes_per_ubatch"] = f["total_bytes"]
        out[f"int{bit}_payload_reduction"] = round(
            fp32["payload_bytes"] / f["payload_bytes"], 3)
        out[f"int{bit}_total_reduction"] = round(
            fp32["total_bytes"] / f["total_bytes"], 3)
    out["loopback_edge"] = {f"bit{b}": r for b, r in rates.items()}
    return out


def bench_overlap():
    """Steady-state ubatch latency: serialized (pre-overlap) vs overlapped."""
    from pipeedge_tpu.comm import dcn

    ctx = dcn.DistDcnContext(1, 0, [("127.0.0.1", _free_port())])
    ctx.init()

    def run(depth, split):
        results = queue.Queue()

        def dispatch(ts):
            time.sleep(WORK_MS / 1e3)
            return ts

        def readback(ts):
            time.sleep(DRAIN_MS / 1e3)
            return ts

        if split:
            stage = dcn.DcnPipelineStage(
                ctx, None, None, dispatch_cb=dispatch, readback_cb=readback,
                depth=depth, results_cb=results.put)
        else:       # the pre-overlap contract: both phases on one thread
            stage = dcn.DcnPipelineStage(
                ctx, None, None, work_cb=lambda ts: readback(dispatch(ts)),
                depth=depth, results_cb=results.put)
        stage.start()
        try:
            tik = time.monotonic()
            for i in range(N_UBATCH):
                stage.enqueue_tensors([np.full((1,), i, np.int32)])
            outs = [results.get(timeout=120) for _ in range(N_UBATCH)]
            dt = time.monotonic() - tik
        finally:
            stage.stop()
        assert [int(o[0][0]) for o in outs] == list(range(N_UBATCH)), \
            "FIFO order violated"
        return dt / N_UBATCH * 1e3

    try:
        serialized = run(depth=1, split=False)
        split_d1 = run(depth=1, split=True)
        overlapped = run(depth=2, split=True)
    finally:
        ctx.shutdown()
    return {
        "modeled_work_ms": WORK_MS,
        "modeled_drain_ms": DRAIN_MS,
        "depth1_serialized_ubatch_ms": round(serialized, 2),
        "depth1_split_ubatch_ms": round(split_d1, 2),
        "depth2_overlapped_ubatch_ms": round(overlapped, 2),
        # serialized costs work+drain per ubatch; perfect overlap costs
        # max(work, drain) — efficiency 1.0 means the full phase overlap
        # was realized by the dispatch/readback split + depth-2 buffering
        "overlap_speedup": round(serialized / overlapped, 3),
        "overlap_efficiency": round(
            (serialized - overlapped) /
            (serialized - max(WORK_MS, DRAIN_MS)), 3),
    }


# -- transport-tier latency A/B (--latency) ------------------------------

LAT_WORLD = 4                   # data rank + 1 relay stage + 2 idle spares
LAT_N_UBATCH = 24               # per-tier stream length
LAT_WORK_MS = 8.0               # modeled stage compute (ViT-L ubatch-ish,
#                                 BENCH_r05 steady ubatch = 8.15 ms)

# tier name -> env staging applied BEFORE the fleet's contexts exist
# (both knobs are read at context construction)
LAT_TIERS = (
    ("socket_v2", {"DCN_LOCAL_HANDOFF": "0", "DCN_RECV_POOL": "0"}),
    ("zerocopy", {"DCN_LOCAL_HANDOFF": "0", "DCN_RECV_POOL": "1"}),
    ("local", {"DCN_LOCAL_HANDOFF": "1", "DCN_RECV_POOL": "1"}),
)


def _free_ports(n):
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports



def bench_latency_tier(tier: str, env: dict) -> dict:
    """One tier's loopback world-4 run: data rank 0 streams microbatches
    to a relay stage on rank 1 (modeled compute LAT_WORK_MS), results come
    home to rank 0; ranks 2-3 idle. Individually-dispatched latency and
    streamed steady-state cadence per the BENCH latency method."""
    from pipeedge_tpu.comm import dcn

    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        addrs = [("127.0.0.1", p) for p in _free_ports(LAT_WORLD)]
        ctxs = [dcn.DistDcnContext(LAT_WORLD, r, addrs)
                for r in range(LAT_WORLD)]
        for c in ctxs:
            c.init()
    finally:
        for k, v in saved.items():
            (os.environ.pop(k, None) if v is None
             else os.environ.__setitem__(k, v))
    rng = np.random.default_rng(0)
    payload = [rng.normal(size=UBATCH_SHAPE).astype(np.float32)]

    def work(tensors):
        time.sleep(LAT_WORK_MS / 1e3)
        return tensors

    stage = dcn.DcnPipelineStage(ctxs[1], rank_src=0, rank_dst=0,
                                 work_cb=work,
                                 send_channel=dcn.CHANNEL_RESULTS)
    stage.start()
    try:
        # negotiate both directions of the relay edge (producer-side, the
        # runtime's round-build idiom), then verify the tier we got
        ctxs[0].negotiate_edge_path(1, timeout=10)
        ctxs[1].negotiate_edge_path(0, timeout=10)
        got = ctxs[0].edge_path(1)
        # warm the edge (dials + first-frame costs stay out of the stats)
        ctxs[0].send_tensors(1, payload)
        ctxs[0].recv_tensors(1, timeout=30, channel=dcn.CHANNEL_RESULTS)

        # individually dispatched: enqueue -> result home, fenced per mb
        lats = []
        for _ in range(LAT_N_UBATCH):
            tik = time.monotonic()
            ctxs[0].send_tensors(1, payload)
            ctxs[0].recv_tensors(1, timeout=30,
                                 channel=dcn.CHANNEL_RESULTS)
            lats.append(time.monotonic() - tik)

        # streamed: feeder thread keeps the stage busy; cadence = T/M
        def feed():
            for _ in range(LAT_N_UBATCH):
                ctxs[0].send_tensors(1, payload)

        feeder = threading.Thread(target=feed, daemon=True)
        tik = time.monotonic()
        feeder.start()
        for _ in range(LAT_N_UBATCH):
            ctxs[0].recv_tensors(1, timeout=30,
                                 channel=dcn.CHANNEL_RESULTS)
        steady_s = (time.monotonic() - tik) / LAT_N_UBATCH
        feeder.join()
    finally:
        stage.stop()
        for c in ctxs:
            c.shutdown()
    lats_sorted = sorted(lats)
    p50 = _percentile(lats_sorted, 50)
    return {
        "metric": "dcn_transport_latency",
        "path": tier,
        "path_negotiated": got,
        "world": LAT_WORLD,
        "ubatch_shape": list(UBATCH_SHAPE),
        "modeled_work_ms": LAT_WORK_MS,
        "n_ubatch": LAT_N_UBATCH,
        "p50_microbatch_latency_ms": round(p50 * 1e3, 2),
        "p99_microbatch_latency_ms": round(
            _percentile(lats_sorted, 99) * 1e3, 2),
        "steady_state_ubatch_ms": round(steady_s * 1e3, 2),
        # the ROADMAP item 5 headline: end-to-end p50 over steady cadence
        # (1.0 = transport adds nothing; BENCH_r05 measured ~10)
        "p50_over_steady": round(p50 / steady_s, 3) if steady_s else None,
        "throughput_frames_sec": round(1.0 / steady_s, 1) if steady_s
        else None,
    }


def bench_latency() -> int:
    """A/B all three tiers; one JSON line per tier (oldest tier first so
    the gap reads top-to-bottom)."""
    for tier, env in LAT_TIERS:
        print(json.dumps(bench_latency_tier(tier, env)), flush=True)
    return 0


# -- quantized ICI collectives A/B (--quant-collectives) ------------------

def bench_quant_collectives() -> dict:
    """Collective-level A/B for the quantized ICI plane
    (docs/QUANT_COLLECTIVES.md): exact `psum`/`all_gather` vs the
    EQuARX-style `qpsum`/`qall_gather` over a 2-device mesh on
    ViT-Large-shaped activations — per-collective max-abs error against
    the analytic bound, wire-byte reduction from the trace tally, and
    loopback wall time per call (CPU numbers measure the codec overhead
    only; the wire win is ICI-bound and shows on TPU meshes)."""
    from functools import partial

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from pipeedge_tpu.ops import qcollectives
    from pipeedge_tpu.utils import jax_compat

    devs = jax.devices()
    assert len(devs) >= 2, "main() forces a >=2-device host mesh"
    mesh = Mesh(np.asarray(devs[:2]), ("tp",))
    rng = np.random.default_rng(0)
    # two per-device psum addends, ViT-L row-parallel-output-shaped
    x = jnp.asarray(rng.normal(size=(2,) + UBATCH_SHAPE).astype(np.float32))
    exact_sum = np.asarray(x).sum(axis=0)
    shard_absrange = float(max(
        np.asarray(x)[i].max() - np.asarray(x)[i].min() for i in range(2)))

    out = {"metric": "quant_collectives_ici", "world": 2,
           "ubatch_shape": list(UBATCH_SHAPE)}
    for bit in (8, 4):
        qcollectives.reset_trace_tally()
        # offline bench: one jit per benched bitwidth, never a hot path
        fn = jax.jit(jax_compat.shard_map(  # pipelint: disable=PL301
            partial(qcollectives.qpsum, axis_name="tp", bit=bit),
            mesh=mesh, in_specs=P("tp"), out_specs=P("tp")))
        got = np.asarray(fn(x))            # compile + correctness sample
        err = float(np.abs(got - exact_sum[None]).max())
        bound = qcollectives.qpsum_error_bound(shard_absrange, bit, 2)
        reps = []
        for _ in range(N_FRAMES):
            tik = time.monotonic()
            np.asarray(fn(x))
            reps.append(time.monotonic() - tik)
        tally = qcollectives.trace_tally()[0]
        out[f"qpsum_int{bit}"] = {
            "max_abs_error": round(err, 6),
            "error_bound": round(bound, 6),
            "within_bound": err <= bound,
            "wire_bytes_per_device": tally["wire_bytes"],
            "raw_bytes_per_device": tally["raw_bytes"],
            "wire_reduction": round(
                tally["raw_bytes"] / tally["wire_bytes"], 3),
            "loopback_ms_per_call": round(
                sorted(reps)[len(reps) // 2] * 1e3, 2),
        }
    # exact psum reference timing (same mesh, same loopback)
    fn0 = jax.jit(jax_compat.shard_map(
        lambda t: jax.lax.psum(t, "tp"), mesh=mesh,
        in_specs=P("tp"), out_specs=P("tp")))
    np.asarray(fn0(x))
    reps = []
    for _ in range(N_FRAMES):
        tik = time.monotonic()
        np.asarray(fn0(x))
        reps.append(time.monotonic() - tik)
    out["exact_psum"] = {"loopback_ms_per_call": round(
        sorted(reps)[len(reps) // 2] * 1e3, 2)}
    out["value"] = out["qpsum_int8"]["wire_reduction"]
    out["unit"] = "x fewer ICI collective wire bytes at int8 vs fp32"
    return out


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--latency", action="store_true",
                   help="run the transport-tier latency A/B (one JSON "
                        "line per tier) instead of the wire/overlap bench")
    p.add_argument("--quant-collectives", action="store_true",
                   help="run the quantized ICI collectives A/B "
                        "(qpsum/qall_gather vs exact, one JSON line; "
                        "docs/QUANT_COLLECTIVES.md)")
    args = p.parse_args()
    if args.quant_collectives:
        # a >= 2-device mesh even on CPU-only hosts: force BEFORE the
        # first jax backend init (parse-once flag). This bench measures
        # codec numerics + bytes, so the virtual-CPU mesh is the point —
        # same idiom as the test suite's 8-device conftest.
        from pipeedge_tpu.utils import force_host_cpu_devices
        force_host_cpu_devices(2)
        print(json.dumps(bench_quant_collectives()))
        return
    if args.latency:
        sys.exit(bench_latency())
    record = {"metric": "dcn_edge_wire_and_overlap",
              "ubatch_shape": list(UBATCH_SHAPE)}
    record.update(bench_wire_bytes())
    record["overlap"] = bench_overlap()
    # headline: the two acceptance numbers
    record["value"] = record["int8_payload_reduction"]
    record["unit"] = "x fewer activation wire bytes at int8 vs fp32"
    print(json.dumps(record))


if __name__ == "__main__":
    main()
