import time
import jax, jax.numpy as jnp, numpy as np

for n, dt in [(4096, jnp.bfloat16), (8192, jnp.bfloat16), (8192, jnp.float32)]:
    a = jnp.asarray(np.random.default_rng(0).normal(size=(n, n)), dt)
    b = jnp.asarray(np.random.default_rng(1).normal(size=(n, n)), dt)
    @jax.jit
    def mm(a, b):
        c = a
        for _ in range(8):
            c = jnp.dot(c, b, preferred_element_type=jnp.float32).astype(dt)
        return jnp.sum(c.astype(jnp.float32))
    float(mm(a, b))
    best = float("inf")
    for _ in range(3):
        t0 = time.monotonic(); float(mm(a, b)); best = min(best, time.monotonic()-t0)
    tf = 8 * 2 * n**3 / best / 1e12
    print(f"{n} {dt.__name__}: {tf:.1f} TFLOP/s", flush=True)
