"""Decode benchmark: GPT-2 KV-cache generation throughput on this chip.

Prints ONE JSON line with the headline metric (tokens/sec at the largest
batch) plus the measured matrix VERDICT r2 item 3 asks for:
- tokens/sec + steady-state decode-step ms per batch size (default 1, 16)
- prefill latency (ms) for the prompt pass
- A/Bs at the largest batch: int8 KV cache on/off, chunked vs unchunked
  prefill — measured, not reasoned.

The reference has no decode subsystem (encoder-only model list,
single-shot batch runtime — SURVEY.md §2.4), so there is no reference
baseline: `vs_baseline` is null and the numbers stand as this
framework's own record (docs/DECODE.md keeps the history).

Method notes: generation runs through the same single-stage
DecodePipeline users get from tools/generate.py (compiled prefill + one
compiled decode-step program; steps dispatch asynchronously, the final
token concat fences). Steady-state step time is measured as
(t(N tokens) - t(N0 tokens)) / (N - N0), which cancels both the prefill
and the fixed dispatch/readback overhead of the tunneled platform.
Weights are random (zero egress); decode timing is weight-independent
(same matmul shapes, no data-dependent control flow).
"""
import argparse
import json
import time


def _time_once(pipe, ids, new_tokens, **kw):
    import numpy as np
    tik = time.monotonic()
    out = pipe.generate(ids, new_tokens, **kw)
    np.asarray(out)            # fence
    return time.monotonic() - tik


def bench_pipe(pipe, ids, new_tokens, prefill_ubatch=None, reps=5):
    """(tokens/sec, steady step ms, prefill ms) for one pipeline+batch.

    Step time = median over `reps` of INTERLEAVED (t(N) - t(N/2)) pairs,
    divided by the N/2 step difference. Both lengths are step-dominated
    (so prefill + the fixed dispatch overhead cancel in each pair) and
    back-to-back pairing + median kills the tunnel's slow drift and
    multi-hundred-ms outliers — min-of-reps on each length separately
    composed two different outlier floors and once produced a *negative*
    step time on chip."""
    if new_tokens < 2:
        raise ValueError("steady-state step estimation needs "
                         f"new_tokens >= 2, got {new_tokens}")
    kw = dict(prefill_ubatch=prefill_ubatch)
    n_half = max(1, new_tokens // 2)
    # warm with the FULL token budget so every attend bucket the timed
    # runs will cross is compiled up front
    pipe.generate(ids, new_tokens, **kw)
    deltas, fulls, halves = [], [], []
    for _ in range(reps):
        t_full = _time_once(pipe, ids, new_tokens, **kw)
        t_half = _time_once(pipe, ids, n_half, **kw)
        fulls.append(t_full)
        halves.append(t_half)
        deltas.append(t_full - t_half)
    import statistics
    step_s = statistics.median(deltas) / (new_tokens - n_half)
    batch = ids.shape[0]
    tok_per_sec = batch * new_tokens / min(fulls)
    # prefill latency ~= t_half minus its decode steps
    prefill_ms = max(0.0, (min(halves) - n_half * step_s)) * 1e3
    return tok_per_sec, step_s * 1e3, prefill_ms


def main():
    from pipeedge_tpu.utils import apply_env_platform, require_live_backend

    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("-m", "--model-name", default="gpt2")
    p.add_argument("--prompt-len", default=128, type=int)
    p.add_argument("--new-tokens", default=64, type=int)
    p.add_argument("--max-len", default=1024, type=int,
                   help="KV cache capacity; headroom past prompt+new is "
                        "what bucketed attend saves (serving allocates "
                        "for the longest request, not the current one)")
    p.add_argument("--batches", default="1,16",
                   help="comma-separated batch sizes; the largest carries "
                        "the headline metric and the A/Bs")
    p.add_argument("-t", "--dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    args = p.parse_args()
    batches = sorted(int(b) for b in args.batches.split(","))

    apply_env_platform()
    # lease-neutral wedge diagnostic: fail fast with an attributable JSON
    # record (same metric key the success record carries) instead of
    # hanging when the TPU tunnel lease is held
    require_live_backend(
        f"{args.model_name}_decode_tokens_per_sec_b{batches[-1]}",
        unit="tokens/sec")
    import jax.numpy as jnp
    import numpy as np

    from pipeedge_tpu.models import registry
    from pipeedge_tpu.parallel import decode

    cfg = registry.get_model_config(args.model_name)
    total = registry.get_model_layers(args.model_name)
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    max_len = max(args.max_len, args.prompt_len + args.new_tokens)
    if cfg.max_position_embeddings:  # clamp headroom to model capacity
        max_len = min(max_len, cfg.max_position_embeddings)
    decode.validate_capacity(cfg, max_len, args.prompt_len, args.new_tokens)

    _, params, _ = registry.module_shard_factory(
        args.model_name, None, 1, total, dtype=dtype, unroll=False)
    family = registry.get_model_entry(args.model_name).family.FAMILY

    def make_pipe(cache_bits=0, attend_floor=64):
        return decode.DecodePipeline(
            family, cfg, [(1, total)], [params], max_len=max_len,
            dtype=dtype, cache_bits=cache_bits, attend_floor=attend_floor)

    rng = np.random.default_rng(0)
    pipe = make_pipe()
    per_batch = {}
    for b in batches:
        ids = rng.integers(0, cfg.vocab_size, size=(b, args.prompt_len))
        tps, step_ms, prefill_ms = bench_pipe(pipe, ids, args.new_tokens)
        per_batch[b] = {"tokens_per_sec": round(tps, 1),
                        "decode_step_ms": round(step_ms, 3),
                        "prefill_ms": round(prefill_ms, 1)}

    b_big = batches[-1]
    ids_big = rng.integers(0, cfg.vocab_size, size=(b_big, args.prompt_len))

    # A/B: int8 KV cache (same prompt set, fresh pipeline)
    tps_int8, step_int8, _ = bench_pipe(make_pipe(cache_bits=8), ids_big,
                                        args.new_tokens)
    # A/B: chunked prefill (pipelines the prompt pass in batch chunks)
    chunk = max(1, b_big // 4)
    _, _, prefill_chunked = bench_pipe(pipe, ids_big, args.new_tokens,
                                       prefill_ubatch=chunk)
    # A/B: bucketed vs full-window decode-step attention (the default
    # pipe buckets at floor 64; the full pipe always attends max_len)
    tps_full, step_full, _ = bench_pipe(make_pipe(attend_floor=max_len),
                                        ids_big, args.new_tokens)

    # speculative-verify span efficiency: ONE extend() over a
    # (gamma+1)-token span vs gamma+1 serial decode steps — the
    # mechanical upper bound on speculative decoding's per-round win
    # (realized speedup scales with draft acceptance)
    span_k = 5

    def time_span():
        import numpy as _np
        _, caches = pipe._prefill(jnp.asarray(ids_big, jnp.int32))
        toks = jnp.asarray(rng.integers(0, cfg.vocab_size,
                                        size=(b_big, span_k)), jnp.int32)
        out, caches = pipe.extend(toks, caches, args.prompt_len)  # compile
        # warm the fence too: the argmax program would otherwise compile
        # inside the timed window
        _np.asarray(jnp.argmax(out.astype(jnp.float32), -1))
        out, caches = pipe.extend(toks, caches, args.prompt_len)
        _np.asarray(jnp.argmax(out.astype(jnp.float32), -1))
        # chain extends with ONE device-side-argmax fence at the end:
        # dispatch is async, so the fixed dispatch/readback round trip
        # amortizes away and the quotient is device time per span —
        # comparable to decode_step_ms, whose estimator cancels the same
        # overhead. (A per-rep fence measured RTT + device time: 72 ms
        # on the tunneled chip, ~4x the device cost.)
        reps = 7
        tik = time.monotonic()
        for _ in range(reps):
            out, caches = pipe.extend(toks, caches, args.prompt_len)
        _np.asarray(jnp.argmax(out.astype(jnp.float32), -1))
        return (time.monotonic() - tik) / reps * 1e3

    span_ms = time_span()
    serial_ms = span_k * per_batch[b_big]["decode_step_ms"]

    # prefix caching: monolithic b-row prefill vs shared-prefix reuse
    # (prefix prefilled once at B=1, suffixes run as one span). Single
    # device executes queued dispatches in order, so N un-chained calls
    # + one fence measure N x device time + one RTT, amortized.
    def time_prefix_reuse(suffix_len=8, reps=5):
        import numpy as _np
        from pipeedge_tpu.parallel.decode import _repeat_batch
        suffix_len = min(suffix_len, max(1, args.prompt_len // 2))
        p_len = args.prompt_len - suffix_len   # >= 1 by construction
        ids_full = jnp.asarray(ids_big, jnp.int32)
        fence = lambda x: _np.asarray(
            jnp.argmax(x[:, -1].astype(jnp.float32), -1))
        out, _ = pipe._prefill(ids_full)
        fence(out)                                  # warm monolithic
        tik = time.monotonic()
        for _ in range(reps):
            out, _ = pipe._prefill(ids_full)
        fence(out)
        mono_ms = (time.monotonic() - tik) / reps * 1e3
        handle = pipe.precompute_prefix(ids_full[:1, :p_len])
        out, _ = pipe.extend(  # warm the suffix span + tiled caches
            ids_full[:, p_len:],
            [_repeat_batch(c, b_big) for c in handle["caches"]], p_len)
        fence(out)
        tik = time.monotonic()
        for _ in range(reps):
            caches = [_repeat_batch(c, b_big) for c in handle["caches"]]
            out, _ = pipe.extend(ids_full[:, p_len:], caches, p_len)
        fence(out)
        reuse_ms = (time.monotonic() - tik) / reps * 1e3
        return p_len, suffix_len, mono_ms, reuse_ms

    import jax
    p_len, s_len, mono_ms, reuse_ms = time_prefix_reuse()

    print(json.dumps({
        "metric": f"{args.model_name}_decode_tokens_per_sec_b{b_big}",
        "value": per_batch[b_big]["tokens_per_sec"],
        "unit": "tokens/sec",
        "vs_baseline": None,     # the reference has no decode subsystem
        "per_batch": {str(b): v for b, v in per_batch.items()},
        "prompt_len": args.prompt_len,
        "new_tokens": args.new_tokens,
        "max_len": max_len,
        "dtype": args.dtype,
        "int8_kv": {"tokens_per_sec": round(tps_int8, 1),
                    "decode_step_ms": round(step_int8, 3)},
        "chunked_prefill_ms": round(prefill_chunked, 1),
        "whole_prefill_ms": per_batch[b_big]["prefill_ms"],
        "prefill_chunk": chunk,
        "full_window_attend": {"tokens_per_sec": round(tps_full, 1),
                               "decode_step_ms": round(step_full, 3)},
        "verify_span": {"k": span_k, "extend_ms": round(span_ms, 3),
                        "serial_ms": round(serial_ms, 3),
                        "speedup_bound": round(serial_ms / span_ms, 2)
                        if span_ms > 0 else None},
        "prefix_reuse": {"prefix_len": p_len, "suffix_len": s_len,
                         "monolithic_prefill_ms": round(mono_ms, 3),
                         "suffix_span_ms": round(reuse_ms, 3),
                         "speedup": round(mono_ms / reuse_ms, 2)
                         if reuse_ms > 0 else None},
        "device_kind": jax.devices()[0].device_kind,
    }))


if __name__ == "__main__":
    main()
