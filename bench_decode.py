"""Decode benchmark: GPT-2 KV-cache generation throughput on this chip.

Prints ONE JSON line with the headline metric (tokens/sec at the largest
batch) plus the measured matrix VERDICT r2 item 3 asks for:
- tokens/sec + steady-state decode-step ms per batch size (default 1, 16)
- prefill latency (ms) for the prompt pass
- A/Bs at the largest batch: int8 KV cache on/off, chunked vs unchunked
  prefill — measured, not reasoned.

The reference has no decode subsystem (encoder-only model list,
single-shot batch runtime — SURVEY.md §2.4), so there is no reference
baseline: `vs_baseline` is null and the numbers stand as this
framework's own record (docs/DECODE.md keeps the history).

Method notes: generation runs through the same single-stage
DecodePipeline users get from tools/generate.py (compiled prefill + one
compiled decode-step program; steps dispatch asynchronously, the final
token concat fences). Steady-state step time is measured as
(t(N tokens) - t(N0 tokens)) / (N - N0), which cancels both the prefill
and the fixed dispatch/readback overhead of the tunneled platform.
Weights are random (zero egress); decode timing is weight-independent
(same matmul shapes, no data-dependent control flow).
"""
import argparse
import json
import time


def _time_generate(pipe, ids, new_tokens, reps=3, **kw):
    import numpy as np
    best = float("inf")
    for _ in range(reps):
        tik = time.monotonic()
        out = pipe.generate(ids, new_tokens, **kw)
        np.asarray(out)            # fence
        best = min(best, time.monotonic() - tik)
    return best


def bench_pipe(pipe, ids, new_tokens, prefill_ubatch=None):
    """(tokens/sec, steady step ms, prefill ms) for one pipeline+batch."""
    kw = dict(prefill_ubatch=prefill_ubatch)
    n0 = max(2, new_tokens // 8)
    # warm with the FULL token budget so every attend bucket the timed
    # runs will cross is compiled up front (min-of-reps would drop a
    # compile-laden first rep anyway, but keep all reps meaningful)
    pipe.generate(ids, new_tokens, **kw)
    t_full = _time_generate(pipe, ids, new_tokens, **kw)
    t_n0 = _time_generate(pipe, ids, n0, **kw)
    step_s = (t_full - t_n0) / (new_tokens - n0)
    batch = ids.shape[0]
    tok_per_sec = batch * new_tokens / t_full
    # prefill latency ~= t_n0 minus its n0 decode steps
    prefill_ms = max(0.0, (t_n0 - n0 * step_s)) * 1e3
    return tok_per_sec, step_s * 1e3, prefill_ms


def main():
    from pipeedge_tpu.utils import apply_env_platform, require_live_backend

    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("-m", "--model-name", default="gpt2")
    p.add_argument("--prompt-len", default=128, type=int)
    p.add_argument("--new-tokens", default=64, type=int)
    p.add_argument("--batches", default="1,16",
                   help="comma-separated batch sizes; the largest carries "
                        "the headline metric and the A/Bs")
    p.add_argument("-t", "--dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    args = p.parse_args()
    batches = sorted(int(b) for b in args.batches.split(","))

    apply_env_platform()
    # lease-neutral wedge diagnostic: fail fast with an attributable JSON
    # record (same metric key the success record carries) instead of
    # hanging when the TPU tunnel lease is held
    require_live_backend(
        f"{args.model_name}_decode_tokens_per_sec_b{batches[-1]}",
        unit="tokens/sec")
    import jax.numpy as jnp
    import numpy as np

    from pipeedge_tpu.models import registry
    from pipeedge_tpu.parallel import decode

    cfg = registry.get_model_config(args.model_name)
    total = registry.get_model_layers(args.model_name)
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    max_len = args.prompt_len + args.new_tokens
    decode.validate_capacity(cfg, max_len, args.prompt_len, args.new_tokens)

    _, params, _ = registry.module_shard_factory(
        args.model_name, None, 1, total, dtype=dtype, unroll=False)
    family = registry.get_model_entry(args.model_name).family.FAMILY

    def make_pipe(cache_bits=0, attend_floor=64):
        return decode.DecodePipeline(
            family, cfg, [(1, total)], [params], max_len=max_len,
            dtype=dtype, cache_bits=cache_bits, attend_floor=attend_floor)

    rng = np.random.default_rng(0)
    pipe = make_pipe()
    per_batch = {}
    for b in batches:
        ids = rng.integers(0, cfg.vocab_size, size=(b, args.prompt_len))
        tps, step_ms, prefill_ms = bench_pipe(pipe, ids, args.new_tokens)
        per_batch[b] = {"tokens_per_sec": round(tps, 1),
                        "decode_step_ms": round(step_ms, 3),
                        "prefill_ms": round(prefill_ms, 1)}

    b_big = batches[-1]
    ids_big = rng.integers(0, cfg.vocab_size, size=(b_big, args.prompt_len))

    # A/B: int8 KV cache (same prompt set, fresh pipeline)
    tps_int8, step_int8, _ = bench_pipe(make_pipe(cache_bits=8), ids_big,
                                        args.new_tokens)
    # A/B: chunked prefill (pipelines the prompt pass in batch chunks)
    chunk = max(1, b_big // 4)
    _, _, prefill_chunked = bench_pipe(pipe, ids_big, args.new_tokens,
                                       prefill_ubatch=chunk)
    # A/B: bucketed vs full-window decode-step attention (the default
    # pipe buckets at floor 64; the full pipe always attends max_len)
    tps_full, step_full, _ = bench_pipe(make_pipe(attend_floor=max_len),
                                        ids_big, args.new_tokens)

    import jax
    print(json.dumps({
        "metric": f"{args.model_name}_decode_tokens_per_sec_b{b_big}",
        "value": per_batch[b_big]["tokens_per_sec"],
        "unit": "tokens/sec",
        "vs_baseline": None,     # the reference has no decode subsystem
        "per_batch": {str(b): v for b, v in per_batch.items()},
        "prompt_len": args.prompt_len,
        "new_tokens": args.new_tokens,
        "dtype": args.dtype,
        "int8_kv": {"tokens_per_sec": round(tps_int8, 1),
                    "decode_step_ms": round(step_int8, 3)},
        "chunked_prefill_ms": round(prefill_chunked, 1),
        "whole_prefill_ms": per_batch[b_big]["prefill_ms"],
        "prefill_chunk": chunk,
        "full_window_attend": {"tokens_per_sec": round(tps_full, 1),
                               "decode_step_ms": round(step_full, 3)},
        "device_kind": jax.devices()[0].device_kind,
    }))


if __name__ == "__main__":
    main()
