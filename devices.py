"""Common device configuration (parity with /root/reference/devices.py).

The reference keeps a global torch device and hook functions that shuttle
activations device<->CPU around every shard, because its transport is
CPU-side TCP (devices.py:8-24). Under JAX the transport IS on-device
(ppermute/device_put), so no shuttling hooks exist; this module only resolves
device specs ('tpu', 'cpu', 'tpu:1') to `jax.Device` handles.
"""
from typing import List, Optional

import jax

DEVICE: Optional[jax.Device] = None


def get_devices(spec: Optional[str] = None) -> List[jax.Device]:
    """Resolve a device spec to the jax devices to run on.

    None -> all default-backend devices; 'cpu'/'tpu' -> that platform's
    devices; 'tpu:1' -> single device by ordinal.
    """
    if spec is None:
        return jax.devices()
    if ':' in spec:
        platform, ordinal = spec.split(':', 1)
        return [jax.devices(platform)[int(ordinal)]]
    return jax.devices(spec)


def set_device(spec: Optional[str]) -> None:
    """Set the module-global default device (reference devices.py:6)."""
    global DEVICE  # pylint: disable=global-statement
    DEVICE = None if spec is None else get_devices(spec)[0]
