"""Save model weights files (parity with /root/reference/save_model_weights.py).

The reference downloads checkpoints from the network (Google storage /
HF hub / torch hub). Under zero egress this script instead converts from a
local HF cache when available, or (with --random) generates randomly-
initialized weights in the exact on-disk format the loaders expect — useful
for benchmarking and for exercising the real weights-file code path offline.
"""
import argparse
import logging
import os
import sys

import numpy as np

from pipeedge_tpu.models import registry

logging.basicConfig(stream=sys.stdout, level=logging.INFO, format='%(message)s')
logger = logging.getLogger(__name__)


def _hf_config_for(cfg):
    """Build the matching HF config from our local TransformerConfig."""
    kwargs = dict(hidden_size=cfg.hidden_size,
                  num_hidden_layers=cfg.num_hidden_layers,
                  num_attention_heads=cfg.num_attention_heads,
                  intermediate_size=cfg.intermediate_size)
    if cfg.model_type in ("vit", "deit"):
        kwargs.update(image_size=cfg.image_size, patch_size=cfg.patch_size,
                      num_labels=max(cfg.num_labels, 2))
        if cfg.model_type == "vit":
            from transformers import ViTConfig
            return ViTConfig(**kwargs)
        from transformers import DeiTConfig
        return DeiTConfig(**kwargs)
    if cfg.model_type == "gpt2":
        from transformers import GPT2Config
        return GPT2Config(n_embd=cfg.hidden_size,
                          n_layer=cfg.num_hidden_layers,
                          n_head=cfg.num_attention_heads,
                          n_inner=cfg.intermediate_size,
                          vocab_size=cfg.vocab_size,
                          n_positions=cfg.max_position_embeddings)
    if cfg.model_type == "llama":
        common = dict(kwargs, num_key_value_heads=cfg.kv_heads,
                      vocab_size=cfg.vocab_size,
                      max_position_embeddings=cfg.max_position_embeddings,
                      rms_norm_eps=cfg.layer_norm_eps,
                      rope_theta=cfg.rope_theta,
                      tie_word_embeddings=False)
        if cfg.sliding_window:
            from transformers import MistralConfig
            return MistralConfig(**common,
                                 sliding_window=cfg.sliding_window)
        from transformers import LlamaConfig
        return LlamaConfig(**common, attention_bias=False, mlp_bias=False)
    from transformers import BertConfig
    return BertConfig(**kwargs, vocab_size=cfg.vocab_size,
                      max_position_embeddings=cfg.max_position_embeddings,
                      num_labels=max(cfg.num_labels, 2))


def _hf_model(model_name: str, cfg, random_init: bool):
    """Instantiate the HF torch model: pretrained if cached, else random."""
    import torch
    if cfg.model_type == "vit":
        from transformers import ViTForImageClassification as Cls
    elif cfg.model_type == "deit":
        from transformers import DeiTForImageClassificationWithTeacher as Cls
    elif cfg.model_type == "gpt2":
        from transformers import GPT2LMHeadModel as Cls
    elif cfg.model_type == "llama":
        if cfg.sliding_window:
            from transformers import MistralForCausalLM as Cls
        else:
            from transformers import LlamaForCausalLM as Cls
    elif cfg.num_labels > 0:
        from transformers import BertForSequenceClassification as Cls
    else:
        from transformers import BertModel as Cls
    if random_init:
        torch.manual_seed(0)
        return Cls(_hf_config_for(cfg))
    return Cls.from_pretrained(model_name)


def save_weights(model_name: str, model_file: str, random_init: bool = False) -> None:
    """Convert an HF model to the reference npz format for `model_name`."""
    entry = registry.get_model_entry(model_name)
    cfg = entry.config
    if cfg.n_experts:
        # synthetic MoE family: no pretrained checkpoint exists to convert
        if not random_init:
            raise ValueError(f"{model_name} is a synthetic MoE model with "
                             "no pretrained checkpoint; pass --random")
        np.savez(model_file, **entry.family.moe_state_dict(cfg))
        return
    model = _hf_model(model_name, cfg, random_init)
    state_dict = {k: v.numpy() for k, v in model.state_dict().items()}
    if cfg.model_type in ("vit", "deit"):
        weights = entry.family.hf_to_npz_weights(state_dict, cfg)
    else:
        weights = state_dict  # BERT/GPT-2 native format IS the HF state dict
    np.savez(model_file, **weights)


if __name__ == "__main__":
    from pipeedge_tpu.utils import apply_env_platform
    apply_env_platform()  # tests run this CLI with JAX_PLATFORMS=cpu
    parser = argparse.ArgumentParser(description="Save model weights files")
    parser.add_argument("-m", "--model-name", action='append',
                        choices=registry.get_model_names(),
                        help="Model name (default: all models)")
    parser.add_argument("--random", action='store_true',
                        help="generate randomly-initialized weights (offline)")
    parser.add_argument("-o", "--output-dir", default=".",
                        help="directory to write the npz files into")
    args = parser.parse_args()

    os.makedirs(args.output_dir, exist_ok=True)
    model_names = registry.get_model_names() if args.model_name is None \
        else args.model_name
    for name in model_names:
        model_file = os.path.join(
            args.output_dir, registry.get_model_default_weights_file(name))
        if os.path.exists(model_file):
            logger.info('%s: weights file already exists: %s', name, model_file)
            continue
        logger.info('%s: saving weights file: %s', name, model_file)
        try:
            save_weights(name, model_file, random_init=args.random)
        except Exception as exc:
            logger.error('%s: failed (%s); pass --random for offline weights',
                         name, exc)
