"""Process-wide monitoring (facade over `pipeedge_tpu.monitoring`).

The application-facing surface the reference facade defines — one shared
`MonitorContext` per process, keys addable at runtime, iterations that may
start and finish on different calls or different threads — with the state
held by a single `_Session` object instead of a spread of module globals.
Module-level functions stay the API (every runtime/CLI imports them); they
delegate to the live session under a readers-writer lock, and every call is
a safe no-op when no session is open, so late worker threads can keep
reporting through a teardown.

Crash posture: `flush()` pushes all CSV logs to disk (called by the DCN
runtime on fleet abort and failover, before anything else happens), and
`finish()` is registered atexit so an exception exit still closes the logs
— post-mortem records survive the crash they are needed for.
"""
from contextlib import ExitStack, contextmanager
import atexit
import logging
import os
import threading
from typing import Optional, Union

from pipeedge_tpu.monitoring import MonitorContext, MonitorIterationContext
from pipeedge_tpu.utils.threads import RWLock, make_lock

ENV_CSV_FILE_MODE: str = "CSV_FILE_MODE"
_DEFAULT_CSV_MODE = 'w'  # fresh logs each run; CSV_FILE_MODE=x refuses to
# clobber an existing file, =a appends across runs

PRINT_FIELDS_INSTANT = True
PRINT_FIELDS_WINDOW = True
PRINT_FIELDS_GLOBAL = True

logger = logging.getLogger(__name__)

# metric name -> (context getter suffix, unit template); '{work}'/'{acc}'
# expand to the key's registered display units
_SCOPE_METRICS = (
    ("Time", "time_s", "sec"),
    ("Rate", "heartrate", "microbatches/sec"),
    ("Work", "work", "{work}"),
    ("Perf", "perf", "{work}/sec"),
    ("Energy", "energy_j", "Joules"),
    ("Power", "power_w", "Watts"),
    ("Acc", "accuracy", "{acc}"),
    ("Acc Rate", "accuracy_rate", "{acc}/sec"),
)


class _Session:
    """Everything one init()..finish() span owns: the shared context, the
    per-key report locks and display units, and the in-flight iteration
    contexts of every (thread, key) pair."""

    def __init__(self, ctx: MonitorContext):
        self.ctx = ctx
        self.key_locks = {}
        self.units = {}      # key -> (work unit, acc unit)
        self.inflight = {}   # (thread ident, key) -> MonitorIterationContext

    def register(self, key: str, work_type: str, acc_type: str) -> None:
        self.key_locks[key] = make_lock(f"monitoring.key[{key}]")
        self.units[key] = (work_type, acc_type)

    def begin(self, key: str) -> MonitorIterationContext:
        slot = (threading.get_ident(), key)
        if slot in self.inflight:
            raise KeyError(f"{key}: this thread already has an open "
                           "iteration")
        ictx = MonitorIterationContext()
        self.inflight[slot] = ictx
        return ictx

    def take(self, key: str) -> MonitorIterationContext:
        slot = (threading.get_ident(), key)
        try:
            return self.inflight.pop(slot)
        except KeyError:
            raise KeyError(f"{key}: no open iteration on this thread") \
                from None

    def log_scope(self, key: str, scope: str) -> None:
        work_u, acc_u = self.units[key]
        title = scope.capitalize()
        for name, getter, unit in _SCOPE_METRICS:
            value = getattr(self.ctx, f"get_{scope}_{getter}")(key=key)
            unit = unit.format(work=work_u, acc=acc_u)
            logger.info("%s: %s %s: %s %s", key, title, name, value, unit)


_session: Optional[_Session] = None
_session_lock = RWLock("monitoring.session")


def init(key: str, window_size: int, work_type: str = 'items',
         acc_type: str = 'acc') -> None:
    """Open the process-wide monitoring session with its first key."""
    global _session  # pylint: disable=global-statement
    from pipeedge_tpu.monitoring.energy import default_energy_source
    mode = os.getenv(ENV_CSV_FILE_MODE, _DEFAULT_CSV_MODE)
    with _session_lock.lock_write():
        ctx = MonitorContext(key=key, window_size=window_size,
                             log_name=f"{key}.csv", log_mode=mode,
                             energy_source=default_energy_source())
        logger.info("Monitoring energy source: %s", ctx.energy_source)
        ctx.open()
        _session = _Session(ctx)
        _session.register(key, work_type, acc_type)


def finish() -> None:
    """Log global stats, close the CSV logs, end the session."""
    global _session  # pylint: disable=global-statement
    with _session_lock.lock_write():
        if _session is None:
            return
        if PRINT_FIELDS_GLOBAL:
            for key in _session.ctx.keys():
                _session.log_scope(key, "global")
        _session.ctx.close()
        _session = None


def flush() -> None:
    """Force every key's buffered CSV rows to disk. The fleet-abort /
    failover hook: whatever happens next, the beat records up to this
    moment are on disk for the post-mortem."""
    with _session_lock.lock_read():
        if _session is not None:
            _session.ctx.flush()


def add_key(key: str, work_type: str = 'items', acc_type: str = 'acc') -> None:
    """Register another monitored key on the open session."""
    with _session_lock.lock_write():
        if _session is None:
            return
        _session.ctx.add_heartbeat(key=key, log_name=f"{key}.csv")
        _session.register(key, work_type, acc_type)


def snapshot() -> dict:
    """The full (instant|window|global) getter matrix for every registered
    key as one dict (`MonitorContext.snapshot`), with each key's report
    lock held for its read so concurrent beats never tear a row; `{}` when
    no session is open. The one-call read telemetry/metrics export uses
    instead of the per-key getters."""
    with _session_lock.lock_read():
        if _session is None:
            return {}
        # hold every report lock (deterministic order; every other path
        # takes at most one, so no deadlock) for one consistent read
        with ExitStack() as stack:
            for key in sorted(_session.key_locks, key=str):
                stack.enter_context(_session.key_locks[key])
            return _session.ctx.snapshot()


@contextmanager
def get_locked_context(key: str):
    """Yield the session's `MonitorContext` with `key`'s report lock held
    (synchronized metric reads); yields None when no session is open."""
    with _session_lock.lock_read():
        if _session is None or key not in _session.key_locks:
            yield None
            return
        with _session.key_locks[key]:
            yield _session.ctx


def iteration_start(key: str) -> None:
    """Open an iteration for this thread on `key`."""
    with _session_lock.lock_read():
        if _session is None:
            return
        with _session.key_locks[key]:
            _session.ctx.iteration_start(iter_ctx=_session.begin(key))


def iteration_reset(key: str) -> None:
    """Forget `key`'s last shared beat: the next start-less
    `iteration(..., safe=False)` stamps a fresh baseline instead of
    recording the idle gap since the previous beat (e.g. a DCN
    re-schedule round boundary) as one giant iteration."""
    with _session_lock.lock_read():
        if _session is None:
            return
        with _session.key_locks[key]:
            _session.ctx.iteration_reset(key=key)


def iteration_abort(key: str) -> None:
    """Discard this thread's open iteration without emitting a heartbeat
    (e.g. a transfer that failed mid-way); no-op if none was started."""
    with _session_lock.lock_read():
        if _session is None:
            return
        with _session.key_locks[key]:
            _session.inflight.pop((threading.get_ident(), key), None)


def iteration(key: str, work: int = 1, accuracy: Union[int, float] = 0,
              safe: bool = True) -> None:
    """Complete an iteration: emit the heartbeat + CSV row, log instant
    fields each beat and window fields at each window boundary. With
    `safe=False` a missing start is tolerated — the shared per-key beat
    baseline turns the call into a beat-to-beat measurement."""
    with _session_lock.lock_read():
        if _session is None:
            return
        with _session.key_locks[key]:
            ctx = _session.ctx
            try:
                ictx = _session.take(key)
            except KeyError:
                if safe:
                    raise
                ictx = None
            ctx.iteration(key=key, work=work, accuracy=accuracy,
                          iter_ctx=ictx)
            tag = ctx.get_tag(key=key)
            if tag > 0:
                if PRINT_FIELDS_INSTANT:
                    _session.log_scope(key, "instant")
                if PRINT_FIELDS_WINDOW and \
                        (tag + 1) % ctx.get_window_size(key=key) == 0:
                    _session.log_scope(key, "window")


# an exception exit (fleet abort) must still close the logs; finish() is
# idempotent, so an orderly main() calling it first costs nothing
atexit.register(finish)
