"""Global monitoring utilities (facade over `pipeedge_tpu.monitoring`).

Parity with /root/reference/monitoring.py: a module-global `MonitorContext`
behind an RWLock, per-thread iteration contexts keyed by thread ident (so
concurrent threads can measure the same key), per-key CSV files named
`<key>.csv` with mode from env `CSV_FILE_MODE`, instant metrics logged every
iteration and window metrics at each window boundary.
"""
from contextlib import contextmanager
import logging
import os
import threading
from typing import Union

from pipeedge_tpu.monitoring import MonitorContext, MonitorIterationContext
from pipeedge_tpu.utils.threads import RWLock

ENV_CSV_FILE_MODE: str = "CSV_FILE_MODE"
_CSV_FILE_MODE = 'w'  # NOTE: will overwrite existing files!

PRINT_FIELDS_INSTANT = True
PRINT_FIELDS_WINDOW = True
PRINT_FIELDS_GLOBAL = True

logger = logging.getLogger(__name__)

_monitor_ctx = None  # pylint: disable=invalid-name
_monitor_ctx_lock = RWLock()

# key: thread ident, value: dict (key: key, value: MonitorIterationContext)
_thr_ctx = {}
# per-key locks, only for reporting iterations
_locks = {}
# user-friendly field names
_work_types = {}
_acc_types = {}


def _log_scope(key, scope):
    ctx = _monitor_ctx
    get = lambda metric: getattr(ctx, f"get_{scope}_{metric}")(key=key)  # noqa: E731
    name = scope.capitalize()
    logger.info("%s: %s Time:     %s sec", key, name, get("time_s"))
    logger.info("%s: %s Rate:     %s microbatches/sec", key, name, get("heartrate"))
    logger.info("%s: %s Work:     %s %s", key, name, get("work"), _work_types[key])
    logger.info("%s: %s Perf:     %s %s/sec", key, name, get("perf"), _work_types[key])
    logger.info("%s: %s Energy:   %s Joules", key, name, get("energy_j"))
    logger.info("%s: %s Power:    %s Watts", key, name, get("power_w"))
    logger.info("%s: %s Acc:      %s %s", key, name, get("accuracy"), _acc_types[key])
    logger.info("%s: %s Acc Rate: %s %s/sec", key, name, get("accuracy_rate"),
                _acc_types[key])


def init(key: str, window_size: int, work_type: str = 'items',
         acc_type: str = 'acc') -> None:
    """Create the global monitoring context."""
    global _monitor_ctx  # pylint: disable=global-statement
    log_name = key + '.csv'
    log_mode = os.getenv(ENV_CSV_FILE_MODE, _CSV_FILE_MODE)
    from pipeedge_tpu.monitoring.energy import default_energy_source
    with _monitor_ctx_lock.lock_write():
        _monitor_ctx = MonitorContext(key=key, window_size=window_size,
                                      log_name=log_name, log_mode=log_mode,
                                      energy_source=default_energy_source())
        logger.info("Monitoring energy source: %s", _monitor_ctx.energy_source)
        _monitor_ctx.open()
        _locks[key] = threading.Lock()
        _work_types[key] = work_type
        _acc_types[key] = acc_type


def finish() -> None:
    """Log global stats and destroy the monitoring context."""
    global _monitor_ctx  # pylint: disable=global-statement
    with _monitor_ctx_lock.lock_write():
        if _monitor_ctx is None:
            return
        if PRINT_FIELDS_GLOBAL:
            for key in _monitor_ctx.keys():
                _log_scope(key, "global")
        _monitor_ctx.close()
        _monitor_ctx = None
        _thr_ctx.clear()
        _locks.clear()
        _work_types.clear()
        _acc_types.clear()


def add_key(key: str, work_type: str = 'items', acc_type: str = 'acc') -> None:
    """Add a new monitored key."""
    with _monitor_ctx_lock.lock_write():
        if _monitor_ctx is None:
            return
        _monitor_ctx.add_heartbeat(key=key, log_name=key + '.csv')
        _locks[key] = threading.Lock()
        _work_types[key] = work_type
        _acc_types[key] = acc_type


@contextmanager
def get_locked_context(key: str):
    """Yields the `MonitorContext` with a lock on `key` (use to synchronize
    retrieving metrics)."""
    with _monitor_ctx_lock.lock_read():
        with _locks[key]:
            yield _monitor_ctx


def _iter_ctx_push(key):
    ident = threading.get_ident()
    keymap = _thr_ctx.setdefault(ident, {})
    if key in keymap:
        raise KeyError(f"Thread iteration context already exists for key: {key}")
    keymap[key] = MonitorIterationContext()
    return keymap[key]


def _iter_ctx_pop(key):
    ident = threading.get_ident()
    iter_ctx = _thr_ctx[ident].pop(key)
    if len(_thr_ctx[ident]) == 0:
        del _thr_ctx[ident]
    return iter_ctx


def iteration_start(key: str) -> None:
    """Start an iteration."""
    with _monitor_ctx_lock.lock_read():
        if _monitor_ctx is None:
            return
        with _locks[key]:
            _monitor_ctx.iteration_start(iter_ctx=_iter_ctx_push(key))


def iteration_reset(key: str) -> None:
    """Forget `key`'s last shared beat: the next start-less
    `iteration(..., safe=False)` stamps a fresh baseline instead of
    recording the idle gap since the previous beat (e.g. a DCN
    re-schedule round boundary) as one giant iteration."""
    with _monitor_ctx_lock.lock_read():
        if _monitor_ctx is None:
            return
        with _locks[key]:
            _monitor_ctx.iteration_reset(key=key)


def iteration_abort(key: str) -> None:
    """Discard a started iteration without emitting a heartbeat (e.g. a
    transfer that failed mid-way); no-op if none was started."""
    with _monitor_ctx_lock.lock_read():
        if _monitor_ctx is None:
            return
        with _locks[key]:
            try:
                _iter_ctx_pop(key)
            except KeyError:
                pass


def iteration(key: str, work: int = 1, accuracy: Union[int, float] = 0,
              safe: bool = True) -> None:
    """Complete an iteration; logs instant metrics each beat and window
    metrics each window period."""
    with _monitor_ctx_lock.lock_read():
        if _monitor_ctx is None:
            return
        with _locks[key]:
            try:
                iter_ctx = _iter_ctx_pop(key)
            except KeyError:
                if safe:
                    raise KeyError(
                        f"No thread iteration context for key: {key}") from None
                iter_ctx = None
            _monitor_ctx.iteration(key=key, work=work, accuracy=accuracy,
                                   iter_ctx=iter_ctx)
            tag = _monitor_ctx.get_tag(key=key)
            if tag > 0:
                if PRINT_FIELDS_INSTANT:
                    _log_scope(key, "instant")
                if PRINT_FIELDS_WINDOW and \
                        (tag + 1) % _monitor_ctx.get_window_size(key=key) == 0:
                    _log_scope(key, "window")
