// miniyaml: a small, dependency-free YAML-subset reader/emitter.
//
// The image has no yaml-cpp, so the scheduler parses its three input files
// (models.yml / device_types.yml / devices.yml — formats documented in the
// reference's README_Scheduler.md:44-264 and emitted by PyYAML safe_dump)
// with this purpose-built parser. Supported subset, which covers everything
// PyYAML's default_flow_style=None emitter produces for those schemas:
//   - block mappings (nested by indentation), plain/quoted scalar keys
//   - block sequences ("- item", including "- key: value" map items)
//   - flow sequences "[a, b, c]", INCLUDING multi-line wrapped ones
//   - plain, single- and double-quoted scalars; comments; empty values (null)
// Not supported (not needed): anchors/aliases, tags, flow mappings,
// multi-line literal scalars.
#pragma once

#include <cctype>
#include <cstdlib>
#include <memory>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

namespace miniyaml {

class Node;
using NodePtr = std::shared_ptr<Node>;

class Node {
 public:
  enum class Kind { Null, Scalar, Seq, Map };

  Kind kind = Kind::Null;
  std::string scalar;
  std::vector<NodePtr> seq;
  std::vector<std::pair<std::string, NodePtr>> map;  // preserves file order

  bool is_null() const { return kind == Kind::Null; }
  bool is_scalar() const { return kind == Kind::Scalar; }
  bool is_seq() const { return kind == Kind::Seq; }
  bool is_map() const { return kind == Kind::Map; }

  const NodePtr find(const std::string &key) const {
    for (const auto &kv : map)
      if (kv.first == key) return kv.second;
    return nullptr;
  }
  bool has(const std::string &key) const { return find(key) != nullptr; }
  const Node &at(const std::string &key) const {
    auto n = find(key);
    if (!n) throw std::runtime_error("miniyaml: missing key: " + key);
    return *n;
  }

  long long as_int() const {
    if (!is_scalar()) throw std::runtime_error("miniyaml: not a scalar int");
    return std::strtoll(scalar.c_str(), nullptr, 10);
  }
  double as_double() const {
    if (!is_scalar()) throw std::runtime_error("miniyaml: not a scalar number");
    return std::strtod(scalar.c_str(), nullptr);
  }
  const std::string &as_string() const { return scalar; }

  std::vector<double> as_double_list() const {
    std::vector<double> out;
    for (const auto &n : seq) out.push_back(n->as_double());
    return out;
  }
  std::vector<long long> as_int_list() const {
    std::vector<long long> out;
    for (const auto &n : seq) out.push_back(n->as_int());
    return out;
  }
  std::vector<std::string> as_string_list() const {
    std::vector<std::string> out;
    for (const auto &n : seq) out.push_back(n->scalar);
    return out;
  }
};

namespace detail {

struct Line {
  int indent;
  std::string text;  // content with indent stripped, comments removed
};

inline std::string strip(const std::string &s) {
  size_t a = s.find_first_not_of(" \t\r\n");
  if (a == std::string::npos) return "";
  size_t b = s.find_last_not_of(" \t\r\n");
  return s.substr(a, b - a + 1);
}

inline std::string unquote(const std::string &s) {
  if (s.size() >= 2 && ((s.front() == '"' && s.back() == '"') ||
                        (s.front() == '\'' && s.back() == '\'')))
    return s.substr(1, s.size() - 2);
  return s;
}

// remove a trailing comment that is not inside quotes/brackets
inline std::string drop_comment(const std::string &s) {
  int depth = 0;
  char quote = 0;
  for (size_t i = 0; i < s.size(); ++i) {
    char c = s[i];
    if (quote) {
      if (c == quote) quote = 0;
    } else if (c == '"' || c == '\'') {
      quote = c;
    } else if (c == '[') {
      ++depth;
    } else if (c == ']') {
      --depth;
    } else if (c == '#' && depth == 0 && (i == 0 || s[i - 1] == ' ')) {
      return s.substr(0, i);
    }
  }
  return s;
}

inline int bracket_balance(const std::string &s) {
  int depth = 0;
  char quote = 0;
  for (char c : s) {
    if (quote) {
      if (c == quote) quote = 0;
    } else if (c == '"' || c == '\'') {
      quote = c;
    } else if (c == '[') {
      ++depth;
    } else if (c == ']') {
      --depth;
    }
  }
  return depth;
}

// Split the physical document into logical lines, merging wrapped flow
// sequences (PyYAML wraps long [a, b, ...] lists across lines).
inline std::vector<Line> logical_lines(const std::string &doc) {
  std::vector<Line> lines;
  size_t pos = 0;
  std::string pending;
  int pending_indent = 0;
  int balance = 0;
  while (pos <= doc.size()) {
    size_t eol = doc.find('\n', pos);
    std::string raw = doc.substr(pos, eol == std::string::npos ? std::string::npos
                                                               : eol - pos);
    pos = eol == std::string::npos ? doc.size() + 1 : eol + 1;
    std::string content = drop_comment(raw);
    if (balance > 0) {
      pending += " " + strip(content);
      balance += bracket_balance(content);
      if (balance <= 0) {
        lines.push_back({pending_indent, strip(pending)});
        pending.clear();
        balance = 0;
      }
      continue;
    }
    std::string stripped = strip(content);
    if (stripped.empty() || stripped == "---") continue;
    int indent = 0;
    while (indent < (int)content.size() && content[indent] == ' ') ++indent;
    int bal = bracket_balance(content);
    if (bal > 0) {
      pending = stripped;
      pending_indent = indent;
      balance = bal;
    } else {
      lines.push_back({indent, stripped});
    }
  }
  return lines;
}

inline NodePtr make_scalar(const std::string &s) {
  auto n = std::make_shared<Node>();
  std::string v = strip(s);
  if (v.empty() || v == "~" || v == "null") {
    n->kind = Node::Kind::Null;
  } else {
    n->kind = Node::Kind::Scalar;
    n->scalar = unquote(v);
  }
  return n;
}

inline NodePtr parse_flow_seq(const std::string &s) {
  auto n = std::make_shared<Node>();
  n->kind = Node::Kind::Seq;
  std::string body = strip(s);
  body = body.substr(1, body.size() - 2);  // strip [ ]
  std::string cur;
  int depth = 0;
  char quote = 0;
  for (char c : body) {
    if (quote) {
      cur += c;
      if (c == quote) quote = 0;
    } else if (c == '"' || c == '\'') {
      quote = c;
      cur += c;
    } else if (c == '[') {
      ++depth;
      cur += c;
    } else if (c == ']') {
      --depth;
      cur += c;
    } else if (c == ',' && depth == 0) {
      if (!strip(cur).empty()) n->seq.push_back(make_scalar(cur));
      cur.clear();
    } else {
      cur += c;
    }
  }
  if (!strip(cur).empty()) n->seq.push_back(make_scalar(cur));
  return n;
}

inline NodePtr parse_value_inline(const std::string &v) {
  std::string s = strip(v);
  if (!s.empty() && s.front() == '[') return parse_flow_seq(s);
  return make_scalar(s);
}

// find "key:" split point outside quotes/brackets
inline size_t key_split(const std::string &s) {
  char quote = 0;
  for (size_t i = 0; i < s.size(); ++i) {
    char c = s[i];
    if (quote) {
      if (c == quote) quote = 0;
    } else if (c == '"' || c == '\'') {
      quote = c;
    } else if (c == ':' && (i + 1 == s.size() || s[i + 1] == ' ')) {
      return i;
    }
  }
  return std::string::npos;
}

NodePtr parse_block(const std::vector<Line> &lines, size_t &idx, int indent);

// parse one "- ..." sequence item body (may be scalar, inline map entry, or
// nested block)
inline NodePtr parse_seq_item(const std::vector<Line> &lines, size_t &idx,
                              int item_indent, const std::string &rest) {
  std::string body = strip(rest);
  if (body.empty()) {  // nested block on following lines
    return parse_block(lines, idx, item_indent + 1);
  }
  size_t split = key_split(body);
  if (split == std::string::npos || body.front() == '[') {
    return parse_value_inline(body);
  }
  // "- key: value" starts a map; more keys may follow on deeper lines
  auto n = std::make_shared<Node>();
  n->kind = Node::Kind::Map;
  std::string key = unquote(strip(body.substr(0, split)));
  std::string val = body.substr(split + 1);
  if (strip(val).empty()) {
    n->map.emplace_back(key, parse_block(lines, idx, item_indent + 1));
  } else {
    n->map.emplace_back(key, parse_value_inline(val));
  }
  while (idx < lines.size() && lines[idx].indent > item_indent) {
    const Line &ln = lines[idx];
    size_t ksp = key_split(ln.text);
    if (ksp == std::string::npos) break;
    ++idx;
    std::string k2 = unquote(strip(ln.text.substr(0, ksp)));
    std::string v2 = ln.text.substr(ksp + 1);
    if (strip(v2).empty()) {
      n->map.emplace_back(k2, parse_block(lines, idx, ln.indent + 1));
    } else {
      n->map.emplace_back(k2, parse_value_inline(v2));
    }
  }
  return n;
}

inline NodePtr parse_block(const std::vector<Line> &lines, size_t &idx,
                           int min_indent) {
  if (idx >= lines.size() || lines[idx].indent < min_indent) {
    return std::make_shared<Node>();  // null
  }
  int indent = lines[idx].indent;
  if (lines[idx].text.rfind("- ", 0) == 0 || lines[idx].text == "-") {
    auto n = std::make_shared<Node>();
    n->kind = Node::Kind::Seq;
    while (idx < lines.size() && lines[idx].indent == indent &&
           (lines[idx].text.rfind("- ", 0) == 0 || lines[idx].text == "-")) {
      std::string rest = lines[idx].text == "-" ? "" : lines[idx].text.substr(2);
      ++idx;
      n->seq.push_back(parse_seq_item(lines, idx, indent, rest));
    }
    return n;
  }
  auto n = std::make_shared<Node>();
  n->kind = Node::Kind::Map;
  while (idx < lines.size() && lines[idx].indent == indent) {
    const Line &ln = lines[idx];
    if (ln.text.rfind("- ", 0) == 0 || ln.text == "-") break;
    size_t split = key_split(ln.text);
    if (split == std::string::npos)
      throw std::runtime_error("miniyaml: expected 'key:' at: " + ln.text);
    std::string key = unquote(strip(ln.text.substr(0, split)));
    std::string val = ln.text.substr(split + 1);
    ++idx;
    if (strip(val).empty()) {
      // YAML allows a block sequence value to sit at the SAME indent as its
      // key (PyYAML emits this); otherwise the value block must be deeper.
      if (idx < lines.size() && lines[idx].indent == indent &&
          (lines[idx].text.rfind("- ", 0) == 0 || lines[idx].text == "-")) {
        n->map.emplace_back(key, parse_block(lines, idx, indent));
      } else {
        n->map.emplace_back(key, parse_block(lines, idx, indent + 1));
      }
    } else {
      n->map.emplace_back(key, parse_value_inline(val));
    }
  }
  return n;
}

}  // namespace detail

inline NodePtr parse(const std::string &doc) {
  auto lines = detail::logical_lines(doc);
  size_t idx = 0;
  return detail::parse_block(lines, idx, 0);
}

}  // namespace miniyaml
