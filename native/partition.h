// Throughput-optimal pipeline partitioning over heterogeneous device types.
//
// TPU-native reimplementation of the reference's scheduler core
// (/root/reference/src-native/schedule.cpp:92-267, the Hu et al. DSD'22
// dynamic program), same algorithm and I/O contract, rebuilt with precomputed
// prefix sums so range compute-time and memory queries are O(1) instead of
// O(L) in the hot loop.
#pragma once

#include <cstddef>
#include <cstdint>
#include <map>
#include <string>
#include <vector>

namespace tpusched {

struct LayerProfile {
  std::uint64_t params_out;  // per-microbatch output element count
  double mem_mb;             // weight memory for this layer
};

struct DeviceKind {
  std::string name;
  double mem_mb;                    // usable device memory
  double bw_mbps;                   // link bandwidth (Mbit/s)
  std::vector<double> layer_time_s; // per-layer compute time profile
};

struct PartitionProblem {
  std::vector<LayerProfile> layers;
  std::uint64_t params_in = 0;       // first layer's input element count
  std::size_t dtype_bytes = 4;
  std::size_t ubatch_size = 8;
  std::size_t buffers_in = 2;        // in-flight + queue recv buffers
  std::size_t buffers_out = 2;       // in-flight + queue send buffers
  std::vector<DeviceKind> kinds;
  std::vector<std::size_t> kind_count;  // devices available per kind
};

struct StageAssignment {
  std::size_t kind_idx;   // index into PartitionProblem::kinds
  std::size_t layer_l;    // 1-based inclusive
  std::size_t layer_r;
};

// Minimize the pipeline bottleneck = max over stages of
// max(stage compute time, outbound edge comm time), subject to each stage
// fitting its device's memory (weights + in/out data buffers). Returns the
// stage list in layer order; empty if no feasible schedule exists.
std::vector<StageAssignment> plan_partition(const PartitionProblem &prob);

// Assign concrete hosts to a kind-level schedule, consuming each kind's host
// list in order (reference schedule.cpp:248-267).
struct HostStage {
  std::string host;
  std::size_t layer_l;
  std::size_t layer_r;
};
std::vector<HostStage> assign_hosts(
    const std::vector<StageAssignment> &stages,
    const std::vector<DeviceKind> &kinds,
    const std::map<std::string, std::vector<std::string>> &kind_hosts);

}  // namespace tpusched
