// sched-pipeline: CLI for the heterogeneous pipeline-partition scheduler.
//
// Drop-in contract parity with the reference binary
// (/root/reference/src-native/sched-pipeline.cpp:132-249): same flags,
// defaults, YAML input schemas (README_Scheduler.md:44-264) and YAML schedule
// output ("- host: [l, r]" per stage). TPU extension: bfloat16/float16
// dtypes are accepted (the reference only supports torch.float32,
// sched-pipeline.cpp:227-232); profiles describing TPU chips as device types
// work unchanged.
#include <getopt.h>

#include <cstdio>
#include <fstream>
#include <iostream>
#include <map>
#include <sstream>
#include <string>
#include <vector>

#include "miniyaml.h"
#include "partition.h"

using tpusched::DeviceKind;
using tpusched::HostStage;
using tpusched::LayerProfile;
using tpusched::PartitionProblem;

namespace {

constexpr const char *kDtypeDefault = "torch.float32";
constexpr std::size_t kBatchDefault = 8;
constexpr std::size_t kBuffersDefault = 2;  // in-flight + queue
constexpr const char *kModelDefault = "google/vit-base-patch16-224";

[[noreturn]] void usage(int code) {
  auto &os = code ? std::cerr : std::cout;
  os << "Usage: sched-pipeline [OPTION]...\n\n"
     << "Run the pipeline partition scheduling algorithm.\n\n"
     << "Options:\n"
     << "  -h, --help                 Print this message and exit\n"
     << "  -d, --dtype=NAME           Data type (default=" << kDtypeDefault << ")\n"
     << "  -b, --batch-size=N         Batch size (default=" << kBatchDefault << ")\n"
     << "  -i, --buffers-in=N         Inbound data buffers (default=" << kBuffersDefault << ")\n"
     << "  -o, --buffers-out=N        Outbound data buffers (default=" << kBuffersDefault << ")\n"
     << "  -m, --model-name=NAME      Model name (default=" << kModelDefault << ")\n"
     << "  -M, --models-file=PATH     Models YAML file (default=models.yml)\n"
     << "  -T, --dev-types-file=PATH  Device types YAML file (default=device_types.yml)\n"
     << "  -D, --dev-file=PATH        Devices YAML file (default=devices.yml)\n";
  std::exit(code);
}

miniyaml::NodePtr load_yaml_file(const std::string &path) {
  std::ifstream f(path);
  if (!f) {
    std::cerr << "Cannot open file: " << path << std::endl;
    std::exit(1);
  }
  std::stringstream ss;
  ss << f.rdbuf();
  return miniyaml::parse(ss.str());
}

// "torch.float32" and "float32" name the same dtype: reference-format YAML
// uses torch-style names, the TPU profiler writes bare jnp names — profiles
// must match either way.
std::string normalize_dtype(const std::string &dtype) {
  const std::string prefix = "torch.";
  if (dtype.rfind(prefix, 0) == 0) return dtype.substr(prefix.size());
  return dtype;
}

std::size_t dtype_bytes(const std::string &dtype) {
  // torch-style and bare names; bf16/f16 are the TPU-native additions
  static const std::map<std::string, std::size_t> sizes = {
      {"torch.float32", 4}, {"float32", 4},
      {"torch.bfloat16", 2}, {"bfloat16", 2},
      {"torch.float16", 2}, {"float16", 2},
  };
  auto it = sizes.find(dtype);
  if (it == sizes.end()) {
    std::cerr << "Unsupported dtype: " << dtype << std::endl;
    usage(1);
  }
  return it->second;
}

void load_model(PartitionProblem &prob, const miniyaml::Node &models,
                const std::string &model_name) {
  auto node = models.find(model_name);
  if (!node) {
    std::cerr << "Model not found: " << model_name << std::endl;
    std::exit(1);
  }
  std::size_t layers = (std::size_t)node->at("layers").as_int();
  auto params_out = node->at("parameters_out").as_int_list();
  auto mem_mb = node->at("mem_MB").as_double_list();
  if (params_out.size() < layers) {
    std::cerr << "Warning: Model parameters_out length " << params_out.size()
              << " < " << layers << ": block will be repeated" << std::endl;
  } else if (params_out.size() > layers) {
    std::cerr << "Model parameters_out length " << params_out.size() << " > "
              << layers << std::endl;
    std::exit(1);
  }
  if (mem_mb.size() != layers) {
    std::cerr << "Model mem_MB length " << mem_mb.size() << " != " << layers
              << std::endl;
    std::exit(1);
  }
  prob.params_in = (std::uint64_t)node->at("parameters_in").as_int();
  for (std::size_t i = 0; i < layers; ++i) {
    prob.layers.push_back(
        {(std::uint64_t)params_out[i % params_out.size()], mem_mb[i]});
  }
}

void load_device_types(PartitionProblem &prob, const miniyaml::Node &types,
                       const std::string &model_name, const std::string &dtype,
                       std::size_t batch_size) {
  if (!types.is_map()) {
    std::cerr << "No device types found" << std::endl;
    std::exit(1);
  }
  for (const auto &[name, dev] : types.map) {
    const miniyaml::NodePtr profiles = dev->find("model_profiles");
    const miniyaml::NodePtr model_prof =
        profiles ? profiles->find(model_name) : nullptr;
    if (!model_prof || !model_prof->is_seq()) {
      std::cerr << "Warning: Device type " << name
                << " doesn't support model: type will be skipped" << std::endl;
      continue;
    }
    const miniyaml::Node *match = nullptr;
    for (const auto &prof : model_prof->seq) {
      if (normalize_dtype(prof->at("dtype").as_string()) ==
              normalize_dtype(dtype) &&
          (std::size_t)prof->at("batch_size").as_int() == batch_size) {
        match = prof.get();
        break;  // first match, like the reverse auction's matcher
      }
    }
    if (!match) {
      std::cerr << "Warning: Device type " << name
                << " doesn't have matching profile: type will be skipped"
                << std::endl;
      continue;
    }
    prob.kinds.push_back({name, dev->at("mem_MB").as_double(),
                          dev->at("bw_Mbps").as_double(),
                          match->at("time_s").as_double_list()});
  }
}

std::map<std::string, std::vector<std::string>> load_devices(
    const miniyaml::Node &devices) {
  if (!devices.is_map()) {
    std::cerr << "No devices found" << std::endl;
    std::exit(1);
  }
  std::map<std::string, std::vector<std::string>> out;
  for (const auto &[name, hosts] : devices.map) {
    out[name] = hosts->as_string_list();
  }
  return out;
}

}  // namespace

int main(int argc, char **argv) {
  std::string dtype = kDtypeDefault;
  std::string model_name = kModelDefault;
  std::string models_file = "models.yml";
  std::string types_file = "device_types.yml";
  std::string devices_file = "devices.yml";
  PartitionProblem prob;
  prob.ubatch_size = kBatchDefault;
  prob.buffers_in = kBuffersDefault;
  prob.buffers_out = kBuffersDefault;

  static const char short_opts[] = "hd:b:i:o:m:M:T:D:";
  static const struct option long_opts[] = {
      {"help", no_argument, nullptr, 'h'},
      {"dtype", required_argument, nullptr, 'd'},
      {"batch-size", required_argument, nullptr, 'b'},
      {"buffers-in", required_argument, nullptr, 'i'},
      {"buffers-out", required_argument, nullptr, 'o'},
      {"model-name", required_argument, nullptr, 'm'},
      {"models-file", required_argument, nullptr, 'M'},
      {"dev-types-file", required_argument, nullptr, 'T'},
      {"dev-file", required_argument, nullptr, 'D'},
      {nullptr, 0, nullptr, 0}};
  int c;
  while ((c = getopt_long(argc, argv, short_opts, long_opts, nullptr)) != -1) {
    switch (c) {
      case 'h': usage(0);
      case 'd': dtype = optarg; break;
      case 'b':
        if (std::sscanf(optarg, "%zu", &prob.ubatch_size) != 1) usage(1);
        break;
      case 'i':
        if (std::sscanf(optarg, "%zu", &prob.buffers_in) != 1) usage(1);
        break;
      case 'o':
        if (std::sscanf(optarg, "%zu", &prob.buffers_out) != 1) usage(1);
        break;
      case 'm': model_name = optarg; break;
      case 'M': models_file = optarg; break;
      case 'T': types_file = optarg; break;
      case 'D': devices_file = optarg; break;
      default: usage(1);
    }
  }
  prob.dtype_bytes = dtype_bytes(dtype);

  load_model(prob, *load_yaml_file(models_file), model_name);
  load_device_types(prob, *load_yaml_file(types_file), model_name, dtype,
                    prob.ubatch_size);
  for (const auto &kind : prob.kinds) {
    if (kind.layer_time_s.size() != prob.layers.size()) {
      std::cerr << "Device: " << kind.name << ": model layer size ("
                << kind.layer_time_s.size() << ") != device time size ("
                << prob.layers.size() << ")" << std::endl;
      std::exit(1);
    }
  }
  auto kind_hosts = load_devices(*load_yaml_file(devices_file));
  for (auto &kind : prob.kinds) {
    auto it = kind_hosts.find(kind.name);
    prob.kind_count.push_back(it == kind_hosts.end() ? 0 : it->second.size());
  }

  auto stages = tpusched::plan_partition(prob);
  auto host_stages = tpusched::assign_hosts(stages, prob.kinds, kind_hosts);

  // YAML schedule: list of {host: [layer_l, layer_r]}
  for (const auto &s : host_stages) {
    std::cout << "- " << s.host << ": [" << s.layer_l << ", " << s.layer_r
              << "]" << std::endl;
  }
  return 0;
}
