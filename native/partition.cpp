#include "partition.h"

#include <algorithm>
#include <limits>

namespace tpusched {
namespace {

constexpr double kInfeasible = std::numeric_limits<double>::infinity();

// Packed DP index helpers over (layer 0..L, used-multiset S, next-kind u).
// S encodes per-kind used counts in mixed radix: digit u has radix
// count[u] + 1 and place value radix_place[u] (reference schedule.cpp:111-132).
struct Radix {
  std::vector<std::size_t> place;  // place value per kind
  std::size_t total = 1;           // product of all radices

  explicit Radix(const std::vector<std::size_t> &counts) {
    place.reserve(counts.size());
    for (std::size_t c : counts) {
      place.push_back(total);
      total *= c + 1;
    }
  }
  std::size_t digit(std::size_t S, std::size_t u,
                    const std::vector<std::size_t> &counts) const {
    return S / place[u] % (counts[u] + 1);
  }
};

}  // namespace

std::vector<StageAssignment> plan_partition(const PartitionProblem &prob) {
  const std::size_t L = prob.layers.size();
  const std::size_t K = prob.kinds.size();
  if (L == 0 || K == 0) return {};

  // O(1) range queries via prefix sums (the reference recomputes per-range
  // sums inside the DP loop, schedule.cpp:20-26, 54-60).
  std::vector<std::vector<double>> cum_time(K, std::vector<double>(L + 1, 0.0));
  for (std::size_t u = 0; u < K; ++u)
    for (std::size_t i = 0; i < L; ++i)
      cum_time[u][i + 1] = cum_time[u][i] + prob.kinds[u].layer_time_s[i];
  std::vector<double> cum_mem(L + 1, 0.0);
  for (std::size_t i = 0; i < L; ++i)
    cum_mem[i + 1] = cum_mem[i] + prob.layers[i].mem_mb;

  auto edge_bytes = [&](std::size_t layer_r) -> double {
    // bytes leaving layer_r (1-based), reference schedule.cpp:32-37
    return static_cast<double>(prob.layers[layer_r - 1].params_out) *
           prob.dtype_bytes * prob.ubatch_size;
  };
  auto input_bytes = [&](std::size_t layer_l) -> double {
    return layer_l == 1 ? static_cast<double>(prob.params_in) *
                              prob.dtype_bytes * prob.ubatch_size
                        : edge_bytes(layer_l - 1);
  };
  auto comm_time_s = [&](std::size_t layer_r, std::size_t u,
                         std::size_t v) -> double {
    // effective link bw = min of both endpoints (reference schedule.cpp:43)
    double mbps = std::min(prob.kinds[u].bw_mbps, prob.kinds[v].bw_mbps);
    return edge_bytes(layer_r) / (mbps * 1024.0 * 1024.0 / 8.0);
  };
  auto stage_fits = [&](std::size_t u, std::size_t l, std::size_t r) -> bool {
    // weights + recv/send/queue buffers + processing buffers must fit
    // (reference schedule.cpp:49-85)
    double need = (cum_mem[r] - cum_mem[l - 1]) * 1024.0 * 1024.0;
    double in_b = input_bytes(l);
    double out_b = edge_bytes(r);
    if (l > 1) need += in_b * prob.buffers_in;
    need += out_b * prob.buffers_out;
    need += in_b + out_b;
    return prob.kinds[u].mem_mb * 1024.0 * 1024.0 > need;
  };

  const Radix radix(prob.kind_count);
  const std::size_t M = radix.total;
  auto idx = [&](std::size_t i, std::size_t S, std::size_t u) {
    return (i * M + S) * K + u;
  };

  std::vector<double> best((L + 1) * M * K, kInfeasible);
  struct Parent {
    std::int64_t layer = -1;
    std::int64_t kind = -1;
  };
  std::vector<Parent> parent((L + 1) * M * K);
  for (std::size_t u = 0; u < K; ++u) best[idx(0, 0, u)] = 0.0;

  double answer = kInfeasible;
  std::size_t ans_i = 0, ans_S = 0, ans_u = 0;

  // S only ever grows (S + place[u] > S), so ascending S order is a valid
  // topological order for the relaxation.
  for (std::size_t i = 0; i < L; ++i) {
    for (std::size_t S = 0; S < M; ++S) {
      for (std::size_t u = 0; u < K; ++u) {
        double cur = best[idx(i, S, u)];
        if (cur == kInfeasible) continue;
        if (radix.digit(S, u, prob.kind_count) == prob.kind_count[u])
          continue;  // all devices of kind u already used
        for (std::size_t j = i + 1; j <= L; ++j) {
          if (!stage_fits(u, i + 1, j)) continue;
          double comp = cum_time[u][j] - cum_time[u][i];
          if (j == L) {
            double cost = std::max(cur, comp);
            if (cost < answer) {
              answer = cost;
              ans_i = i;
              ans_S = S;
              ans_u = u;
            }
            continue;
          }
          std::size_t S2 = S + radix.place[u];
          for (std::size_t v = 0; v < K; ++v) {
            if (radix.digit(S2, v, prob.kind_count) == prob.kind_count[v])
              continue;
            double cost = std::max(cur, std::max(comp, comm_time_s(j, u, v)));
            if (cost < best[idx(j, S2, v)]) {
              best[idx(j, S2, v)] = cost;
              parent[idx(j, S2, v)] = {static_cast<std::int64_t>(i),
                                       static_cast<std::int64_t>(u)};
            }
          }
        }
      }
    }
  }

  if (answer == kInfeasible) return {};

  // Backtrack the parent chain from the answer state.
  std::vector<StageAssignment> stages;
  std::size_t i = ans_i, S = ans_S, u = ans_u;
  stages.push_back({u, i + 1, L});
  while (i > 0) {
    Parent p = parent[idx(i, S, u)];
    stages.push_back({static_cast<std::size_t>(p.kind),
                      static_cast<std::size_t>(p.layer) + 1, i});
    S -= radix.place[p.kind];
    i = static_cast<std::size_t>(p.layer);
    u = static_cast<std::size_t>(p.kind);
  }
  // Backtracking emits stages last-to-first; reversing restores layer order.
  std::reverse(stages.begin(), stages.end());
  return stages;
}

std::vector<HostStage> assign_hosts(
    const std::vector<StageAssignment> &stages,
    const std::vector<DeviceKind> &kinds,
    const std::map<std::string, std::vector<std::string>> &kind_hosts) {
  std::map<std::string, std::size_t> next_host;
  std::vector<HostStage> out;
  for (const auto &s : stages) {
    const std::string &kind_name = kinds[s.kind_idx].name;
    std::size_t h = next_host[kind_name]++;
    out.push_back({kind_hosts.at(kind_name)[h], s.layer_l, s.layer_r});
  }
  return out;
}

}  // namespace tpusched
