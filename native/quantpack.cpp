// Native host-side quantized-activation wire codec.
//
// Packs k-bit quantization codes into uint32 words with the same layout and
// math as the XLA ops in pipeedge_tpu/ops/quant.py ('original' mode; value i
// -> word i/per_word at bit offset (i%per_word)*bit, per_word = 32/bit), so
// payloads produced on either side interoperate on the DCN wire. Mirrors the
// role of the reference's numpy packing (reference quantization/
// basic_op.py:38-90) but as C++ for the host runtime path: DCN edges encode
// on the host after device readback, and a native codec keeps that off the
// accelerator and out of the interpreter.
//
// Rounding: XLA's round() is round-half-to-even; std::nearbyint under the
// default FE_TONEAREST mode matches it exactly.

#include <cfenv>
#include <cmath>
#include <cstdint>

extern "C" {

int qp_abi_version() { return 1; }

// ceil(n / (32/bit)) words per item
int64_t qp_packed_words(int64_t n, int bit) {
  int64_t per_word = 32 / bit;
  return (n + per_word - 1) / per_word;
}

// x: [batch, n] row-major float32. Outputs: packed [batch, words] uint32,
// scale/shift [batch] float32. bit in {1..32}.
void qp_encode_f32(const float* x, int64_t batch, int64_t n, int bit,
                   uint32_t* packed, float* scale, float* shift) {
  const int64_t per_word = 32 / bit;
  const int64_t words = qp_packed_words(n, bit);
  const float levels =
      (bit >= 32) ? 4294967295.0f : static_cast<float>((1u << bit) - 1u);
  std::fesetround(FE_TONEAREST);
  for (int64_t b = 0; b < batch; ++b) {
    const float* xb = x + b * n;
    if (n == 0) {
      scale[b] = 0.0f;
      shift[b] = 0.0f;
      continue;
    }
    float mn = xb[0], mx_rel = 0.0f;
    for (int64_t i = 1; i < n; ++i) mn = xb[i] < mn ? xb[i] : mn;
    for (int64_t i = 0; i < n; ++i) {
      float rel = xb[i] - mn;
      mx_rel = rel > mx_rel ? rel : mx_rel;
    }
    const float sc = mx_rel;
    const float safe = sc > 0.0f ? sc : 1.0f;
    shift[b] = mn;
    scale[b] = sc;
    uint32_t* pb = packed + b * words;
    for (int64_t w = 0; w < words; ++w) pb[w] = 0u;
    for (int64_t i = 0; i < n; ++i) {
      const float x01 = (xb[i] - mn) / safe;
      const uint32_t q =
          static_cast<uint32_t>(static_cast<int64_t>(
              std::nearbyint(static_cast<double>(x01 * levels))));
      pb[i / per_word] |=
          (bit >= 32 ? q : (q & ((1u << bit) - 1u)))
          << ((i % per_word) * bit);
    }
  }
}

// packed: [batch, words]; out: [batch, n] float32.
void qp_decode_f32(const uint32_t* packed, int64_t batch, int64_t n, int bit,
                   const float* scale, const float* shift, float* out) {
  const int64_t per_word = 32 / bit;
  const int64_t words = qp_packed_words(n, bit);
  const float levels =
      (bit >= 32) ? 4294967295.0f : static_cast<float>((1u << bit) - 1u);
  const uint32_t mask = (bit >= 32) ? 0xFFFFFFFFu : ((1u << bit) - 1u);
  for (int64_t b = 0; b < batch; ++b) {
    const uint32_t* pb = packed + b * words;
    float* ob = out + b * n;
    const float sc = scale[b], sh = shift[b];
    for (int64_t i = 0; i < n; ++i) {
      const uint32_t q = (pb[i / per_word] >> ((i % per_word) * bit)) & mask;
      ob[i] = static_cast<float>(q) / levels * sc + sh;
    }
  }
}

}  // extern "C"
