"""Module shard profiler CLI (parity with /root/reference/profiler.py:176-263).

Measures per-layer time and memory on the available TPU/CPU device and
appends to a profiler_results.yml compatible with the reference's converters
and the native sched-pipeline scheduler.
"""
import argparse
import logging
import os

import jax.numpy as jnp
import numpy as np
import yaml

from pipeedge_tpu import profiler as prof
from pipeedge_tpu.models import registry

logger = logging.getLogger(__name__)

_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}


def main():
    parser = argparse.ArgumentParser(
        description="Module Shard Profiler",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-o", "--results-yml", default="profiler_results.yml",
                        type=str, help="output YAML file")
    parser.add_argument("-m", "--model-name", type=str,
                        default="google/vit-base-patch16-224",
                        choices=registry.get_model_names(),
                        help="the neural network model for loading")
    parser.add_argument("-M", "--model-file", type=str,
                        help="the model weights file, if not in working directory")
    parser.add_argument("-l", "--layer-start", default=1, type=int,
                        help="start layer")
    parser.add_argument("-L", "--layer-end", type=int,
                        help="end layer; default: last layer in the model")
    parser.add_argument("-s", "--shape-input", type=str, action="append",
                        help="comma-delimited shape input, e.g. '3,224,224' "
                             "(required for start_layer != 1)")
    parser.add_argument("-b", "--batch-size", default=8, type=int,
                        help="batch size")
    parser.add_argument("-t", "--dtype", default="float32",
                        choices=sorted(_DTYPES), help="compute dtype")
    parser.add_argument("-w", "--warmup", action="store_true", default=True,
                        help="perform a warmup iteration")
    parser.add_argument("--no-warmup", action="store_false", dest="warmup")
    parser.add_argument("-i", "--iterations", default=16, type=int,
                        help="iterations to average runtime over")
    parser.add_argument("--exhaustive", action="store_true",
                        help="measure every layer even when structurally "
                             "identical to an already-measured one (the "
                             "default reuses such measurements)")
    parser.add_argument("--trace", type=str, default=None, metavar="DIR",
                        help="capture a JAX profiler trace of the measured "
                             "forwards into DIR")
    args = parser.parse_args()

    dtype = _DTYPES[args.dtype]
    if args.shape_input is not None:
        shapes = [tuple(int(d) for d in shp.split(","))
                  for shp in args.shape_input]
        rng = np.random.default_rng(0)
        tensors = tuple(jnp.asarray(
            rng.normal(size=(args.batch_size,) + shp), dtype=dtype)
            for shp in shapes)
        inputs = tensors if len(tensors) > 1 else tensors[0]
    else:
        inputs = prof.default_inputs(args.model_name, args.batch_size, dtype)

    model_layers = registry.get_model_layers(args.model_name)
    layer_end = args.layer_end if args.layer_end is not None else model_layers
    dtype_name = args.dtype

    if os.path.exists(args.results_yml):
        print("Using existing results file")
        with open(args.results_yml, "r", encoding="utf-8") as yfile:
            profile_results = yaml.safe_load(yfile)
        prof.validate_profile_results(profile_results, args.model_name,
                                      dtype_name, args.batch_size,
                                      model_layers, args.layer_start, layer_end)
    else:
        profile_results = {
            "model_name": args.model_name,
            "dtype": dtype_name,
            "batch_size": args.batch_size,
            "layers": model_layers,
            "profile_data": [],
        }

    from pipeedge_tpu.utils import tracing
    with tracing.trace(args.trace):
        results = prof.profile_layers_individually(
            args.model_name, args.model_file, inputs, args.layer_start,
            layer_end, args.warmup, args.iterations, dtype=dtype,
            reuse_identical=not args.exhaustive)

    profile_results["profile_data"].extend(results)
    profile_results["profile_data"].sort(key=lambda pd: pd["layer"])
    with open(args.results_yml, "w", encoding="utf-8") as yfile:
        yaml.safe_dump(profile_results, yfile, default_flow_style=None,
                       encoding="utf-8")


if __name__ == "__main__":
    from pipeedge_tpu.utils import apply_env_platform
    apply_env_platform()  # honor an explicit JAX_PLATFORMS (e.g. cpu in CI)
    logging.basicConfig(level=logging.INFO)
    main()
