"""Gray-failure plane tests (docs/FAULT_TOLERANCE.md gray failures):

- health-scorer unit matrix: EWMA folding, hysteresis in both
  directions, confirmation windows, the min-fleet floor hold, probation
  readmission and relapse (pipeedge_tpu/health/scorer.py)
- chaos grammar: slow / jitter / corrupt parsing incl. the bounded
  slow@K-J:MS form (pipeedge_tpu/comm/chaos.py)
- frame integrity: wire-v2 CRC trailer encode/verify, corruption
  detection, the transport resend cache + bounded replay, heartbeat RTT
  measurement (comm/wire.py, comm/dcn.py)
- NaN/Inf activation guard: named error + postmortem bundle + counter
  (pipeedge_tpu/health/guard.py)
- trace_report `gray` section (telemetry/report.py)
- tier-1 fleet acceptance: a persistent 80 ms straggler (slow@2-J:MS)
  on a world-4 loopback fleet is quarantined at a round boundary, its
  stage re-planned onto a spare, and readmitted through probation once
  the chaos clears — while a clean fleet records ZERO quarantines.
"""
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from pipeedge_tpu import health  # noqa: E402
from pipeedge_tpu.comm import chaos, dcn, wire  # noqa: E402
from pipeedge_tpu.health import guard as nan_guard  # noqa: E402
from pipeedge_tpu.health.scorer import (HealthPolicy,  # noqa: E402
                                        HealthSample, PeerHealthScorer,
                                        STATE_HEALTHY, STATE_PROBATION,
                                        STATE_QUARANTINED, STATE_SUSPECT)
from pipeedge_tpu.telemetry import report  # noqa: E402


BAD = HealthSample(service_ratio=3.0)      # fully degraded (>= ratio_bad)
GOOD = HealthSample(service_ratio=1.0)     # nominal
EMPTY = HealthSample()


def _scorer(**kw):
    defaults = dict(alpha=1.0, suspect_threshold=0.4,
                    readmit_threshold=0.2, confirm=1, readmit=1,
                    probation=1)
    defaults.update(kw)
    return PeerHealthScorer([1, 2, 3], policy=HealthPolicy(**defaults))


# -- scorer unit matrix ------------------------------------------------

def test_scorer_healthy_rank_never_transitions():
    s = _scorer()
    for _ in range(10):
        assert s.observe(1, GOOD) is None
    assert s.state_of(1) == STATE_HEALTHY
    assert s.score_of(1) == 0.0


def test_scorer_suspect_then_quarantine_with_confirmation():
    s = _scorer(confirm=2)
    t = s.observe(1, BAD)
    assert t is not None and t.to == STATE_SUSPECT
    # confirm=2: the entry window never convicts; two MORE bad windows do
    assert s.observe(1, BAD) is None
    t = s.observe(1, BAD)
    assert t is not None and t.to == STATE_QUARANTINED
    assert s.quarantined() == [1]


def test_scorer_ewma_smooths_single_noisy_window():
    # alpha 0.25: one fully-bad window moves the score to 0.25 < 0.4 —
    # a single noisy window never even makes suspect
    s = _scorer(alpha=0.25)
    assert s.observe(1, BAD) is None
    assert s.state_of(1) == STATE_HEALTHY
    assert 0.2 < s.score_of(1) < 0.3


def test_scorer_suspect_recovers_without_quarantine():
    s = _scorer(confirm=3)
    assert s.observe(1, BAD).to == STATE_SUSPECT
    t = s.observe(1, GOOD)
    assert t is not None and t.to == STATE_HEALTHY
    # the streak reset: going bad again needs full re-confirmation
    assert s.observe(1, BAD).to == STATE_SUSPECT
    assert s.observe(1, GOOD).to == STATE_HEALTHY


def test_scorer_min_fleet_floor_holds_suspect():
    s = _scorer(confirm=1)
    assert s.observe(1, BAD, can_quarantine=False).to == STATE_SUSPECT
    # confirmed, but the floor refuses: a single "held" note, no bench
    t = s.observe(1, BAD, can_quarantine=False)
    assert t is not None and t.frm == t.to == STATE_SUSPECT
    assert "held" in t.reason
    assert s.observe(1, BAD, can_quarantine=False) is None  # fires once
    assert s.quarantined() == []
    # the floor clears (a spare appeared): quarantine proceeds
    assert s.observe(1, BAD, can_quarantine=True).to == STATE_QUARANTINED


def test_scorer_probation_readmit_and_graduation():
    s = _scorer(readmit=2, probation=2)
    s.observe(1, BAD)
    s.observe(1, BAD)
    assert s.state_of(1) == STATE_QUARANTINED
    # readmit=2: two consecutive recovered windows
    assert s.observe(1, GOOD) is None
    t = s.observe(1, GOOD)
    assert t is not None and t.to == STATE_PROBATION
    # probation=2: two clean windows graduate to healthy
    assert s.observe(1, GOOD) is None
    assert s.observe(1, GOOD).to == STATE_HEALTHY


def test_scorer_probation_relapse_respects_the_floor():
    """A probation relapse is still a QUARANTINE decision: with no
    runnable plan left (the spare died meanwhile) the rank is HELD on
    probation — running degraded beats aborting the fleet."""
    s = _scorer()
    s.observe(1, BAD)
    s.observe(1, BAD)
    s.observe(1, GOOD)
    assert s.state_of(1) == STATE_PROBATION
    t = s.observe(1, BAD, can_quarantine=False)
    assert t is not None and t.frm == t.to == STATE_PROBATION
    assert "held" in t.reason
    # the floor clears: the relapse proceeds
    assert s.observe(1, BAD, can_quarantine=True).to == STATE_QUARANTINED


def test_scorer_probation_relapse_requarantines_without_confirmation():
    s = _scorer(confirm=3)
    for _ in range(4):
        s.observe(1, BAD)
    assert s.state_of(1) == STATE_QUARANTINED
    s.observe(1, GOOD)
    assert s.state_of(1) == STATE_PROBATION
    # ONE bad probation window relapses (no 3-window re-confirmation)
    t = s.observe(1, BAD)
    assert t is not None and t.to == STATE_QUARANTINED


def test_scorer_empty_sample_holds_everything():
    s = _scorer()
    s.observe(1, BAD)
    s.observe(1, BAD)
    assert s.state_of(1) == STATE_QUARANTINED
    score = s.score_of(1)
    for _ in range(5):
        assert s.observe(1, EMPTY) is None
    # absence of evidence neither readmits nor convicts
    assert s.state_of(1) == STATE_QUARANTINED
    assert s.score_of(1) == score


def test_scorer_signal_fusion_takes_the_worst_signal():
    pol = HealthPolicy(rtt_bad=3.0, retries_bad=3)
    assert pol.degradation(HealthSample(service_ratio=1.0,
                                        rtt_ratio=3.0)) == 1.0
    assert pol.degradation(HealthSample(send_retries=3)) == 1.0
    assert pol.degradation(HealthSample(service_ratio=1.0, rtt_ratio=1.0,
                                        send_retries=0)) == 0.0
    assert pol.degradation(EMPTY) is None


def test_scorer_snapshot_and_module_singleton():
    s = _scorer()
    s.observe(1, BAD)
    health.set_scorer(s)
    try:
        snap = health.snapshot()
        assert snap["1"]["state"] == STATE_SUSPECT
        assert snap["2"]["state"] == STATE_HEALTHY
        assert snap["1"]["score"] >= 0.4
    finally:
        health.set_scorer(None)
    assert health.snapshot() == {}


def test_policy_validation():
    with pytest.raises(ValueError):
        HealthPolicy(alpha=0.0)
    with pytest.raises(ValueError):
        HealthPolicy(suspect_threshold=0.2, readmit_threshold=0.3)
    with pytest.raises(ValueError):
        HealthPolicy(confirm=0)
    with pytest.raises(ValueError):
        HealthPolicy(ratio_bad=1.0)


# -- chaos grammar ------------------------------------------------------

def test_chaos_grammar_gray_faults():
    spec = chaos.ChaosSpec.parse("slow@2:80;jitter@3-9:40;corrupt@5")
    kinds = {a.kind: a for a in spec.actions}
    assert kinds["slow"].at_send == 2 and kinds["slow"].delay_ms == 80
    assert kinds["slow"].until_send is None
    assert kinds["jitter"].at_send == 3 and kinds["jitter"].until_send == 9
    assert kinds["corrupt"].at_send == 5
    spec = chaos.ChaosSpec.parse("slow@2-12:80")
    assert spec.actions[0].until_send == 12


def test_chaos_grammar_rejects_bad_gray_clauses():
    for bad in ("slow@x:80", "jitter@2-z:10", "corrupt@", "wat@3"):
        with pytest.raises(ValueError):
            chaos.ChaosSpec.parse(bad)
    # a missing MS parses to 0 delay (the delay@K: precedent)
    assert chaos.ChaosSpec.parse("jitter@2:").actions[0].delay_ms == 0


# -- frame integrity (wire CRC) ----------------------------------------

def test_wire_crc_roundtrip_and_flag():
    import jax.numpy as jnp
    out = jnp.asarray(np.random.default_rng(0).normal(
        size=(4, 8)).astype(np.float32))
    pending = wire.wire_encode_device(out, 8, crc=True)
    frame = pending.finalize()
    header = np.asarray(frame[0])
    assert int(header[3]) & wire.FLAG_CRC
    crc_t = np.asarray(frame[-1], np.uint32)
    assert crc_t.shape == (2,)
    decoded = wire.wire_decode(frame, jnp.float32)
    ref = wire.wire_decode(wire.wire_encode_device(out, 8,
                                                   crc=False).finalize(),
                           jnp.float32)
    np.testing.assert_array_equal(np.asarray(decoded), np.asarray(ref))


def test_wire_crc_detects_corruption():
    import jax.numpy as jnp
    out = jnp.asarray(np.arange(64, dtype=np.float32).reshape(8, 8))
    frame = wire.wire_encode_device(out, 8, crc=True).finalize()
    # flip one bit in the packed payload (not header, not crc)
    sizes = [t.nbytes for t in frame[1:-1]]
    idx = 1 + sizes.index(max(sizes))
    bad = list(frame)
    victim = np.asarray(bad[idx]).copy()
    victim.reshape(-1).view(np.uint8)[0] ^= 1
    bad[idx] = victim
    with pytest.raises(wire.WireCorruptError):
        wire.wire_decode(bad, jnp.float32)


def test_wire_crc_absent_flag_still_decodes():
    import jax.numpy as jnp
    out = jnp.asarray(np.ones((2, 4), np.float32))
    frame = wire.wire_encode_device(out, 0, crc=False).finalize()
    assert not (int(np.asarray(frame[0])[3]) & wire.FLAG_CRC)
    np.testing.assert_array_equal(
        np.asarray(wire.wire_decode(frame, jnp.float32)), np.ones((2, 4)))


def test_wire_crc_local_parts_carry_no_trailer():
    # the colocated tier ships pending.parts WITHOUT finalize: no flag,
    # no checksum tensor — in-process hand-offs never pay the CRC
    import jax.numpy as jnp
    pending = wire.wire_encode_device(jnp.ones((2, 2)), 0, crc=True)
    header = np.asarray(pending.parts[0])
    assert not (int(header[3]) & wire.FLAG_CRC)
    assert len(pending.parts) == 2


def test_frame_payload_bytes_ignores_crc_trailer():
    import jax.numpy as jnp
    out = jnp.asarray(np.zeros((4, 16), np.float32))
    plain = wire.wire_encode_device(out, 8, crc=False).finalize()
    checked = wire.wire_encode_device(out, 8, crc=True).finalize()
    assert wire.frame_payload_bytes(checked) \
        == wire.frame_payload_bytes(plain)


def test_frame_checksum_algo_rides_the_frame():
    algo, crc = wire.frame_checksum([np.arange(16, dtype=np.int32)])
    assert algo in (wire.CRC_ALGO_CRC32C, wire.CRC_ALGO_CRC32)
    # verify_frame recomputes with the frame's own algorithm
    body = [np.arange(16, dtype=np.int32)]
    wire.verify_frame(body, np.asarray([algo, crc], np.uint32))
    with pytest.raises(wire.WireCorruptError):
        wire.verify_frame(body, np.asarray([algo, crc ^ 1], np.uint32))


# -- transport: RTT measurement + resend cache -------------------------

def _free_ports(n):
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _make_contexts(n):
    addrs = [("127.0.0.1", p) for p in _free_ports(n)]
    ctxs = [dcn.DistDcnContext(n, r, addrs) for r in range(n)]
    for c in ctxs:
        c.init()
    return ctxs


def test_heartbeat_rtt_measured_per_peer():
    ctxs = _make_contexts(2)
    samples = []
    try:
        ctxs[0].register_heartbeat_rtt_hook(
            lambda src, ms: samples.append((src, ms)))
        ctxs[0].start_heartbeat([1], interval=0.1, miss_threshold=10)
        deadline = time.monotonic() + 8.0
        while time.monotonic() < deadline:
            stats = ctxs[0].heartbeat_rtt_stats()
            if stats.get(1, {}).get("n", 0) >= 3:
                break
            time.sleep(0.05)
        stats = ctxs[0].heartbeat_rtt_stats()
        assert 1 in stats, "no RTT samples came home"
        assert stats[1]["n"] >= 3
        assert 0.0 <= stats[1]["p50_ms"] <= stats[1]["p99_ms"] < 5000.0
        assert samples and samples[0][0] == 1
    finally:
        for c in ctxs:
            c.shutdown()


def test_resend_cache_replays_last_frame_bounded(monkeypatch):
    monkeypatch.setenv(wire.ENV_WIRE_CRC, "1")
    import jax.numpy as jnp
    ctxs = _make_contexts(2)
    try:
        payload = np.arange(32, dtype=np.float32).reshape(4, 8)
        frame = wire.wire_encode_device(jnp.asarray(payload), 0,
                                        crc=True).finalize()
        ctxs[0].send_tensors(1, frame, channel=0)
        first = ctxs[1].recv_tensors(0, timeout=5.0, channel=0)
        np.testing.assert_array_equal(
            np.asarray(wire.wire_decode(first, jnp.float32)), payload)
        # consumer requests a replay: the cached frame arrives again
        ctxs[1].request_resend(0, 0)
        again = ctxs[1].recv_tensors(0, timeout=5.0, channel=0)
        np.testing.assert_array_equal(
            np.asarray(wire.wire_decode(again, jnp.float32)), payload)
        # bounded: send_retries=0 -> cap max(1, 0) = 1 replay per frame
        ctxs[1].request_resend(0, 0)
        with pytest.raises(Exception):   # queue.Empty
            ctxs[1].recv_tensors(0, timeout=1.0, channel=0)
    finally:
        for c in ctxs:
            c.shutdown()


def test_unflagged_frames_not_cached(monkeypatch):
    """Raw frames (feed microbatches, v1) carry no CRC header: the
    receiver can never verify or request them, so the producer must not
    pin dead copies in the resend cache."""
    monkeypatch.setenv(wire.ENV_WIRE_CRC, "1")
    ctxs = _make_contexts(2)
    try:
        ctxs[0].send_tensors(1, [np.arange(8, dtype=np.float32)],
                             channel=0)
        ctxs[1].recv_tensors(0, timeout=5.0, channel=0)
        assert not ctxs[0]._last_frames
        # a latest-frame request just misses (logged, never raises)
        ctxs[1].request_resend(0, 0)
        time.sleep(0.3)
    finally:
        for c in ctxs:
            c.shutdown()


def test_corrupt_frame_recovered_transparently(monkeypatch):
    """End-to-end integrity recovery: the RECEIVING READER verifies
    CRC-flagged frames, drops a corrupt one, and requests its exact
    sequence number back — the consumer only ever sees the clean
    replay (what chaos corrupt@K exercises on a fleet)."""
    monkeypatch.setenv(wire.ENV_WIRE_CRC, "1")
    import jax.numpy as jnp
    ctxs = _make_contexts(2)
    try:
        payload = np.random.default_rng(1).normal(
            size=(8, 8)).astype(np.float32)
        frame = wire.wire_encode_device(
            jnp.asarray(payload), 8, crc=True).finalize()
        before = dcn.FRAMES_CORRUPT.value(peer="0")
        ctxs[0]._corrupt_next_send = True      # what chaos corrupt@K sets
        ctxs[0].send_tensors(1, frame, channel=0)
        got = ctxs[1].recv_tensors(0, timeout=10.0, channel=0)
        # the corrupt original was dropped at the reader; this IS the
        # clean replay, and it decodes
        out = np.asarray(wire.wire_decode(got, jnp.float32))
        assert np.isfinite(out).all()
        assert dcn.FRAMES_CORRUPT.value(peer="0") == before + 1
    finally:
        for c in ctxs:
            c.shutdown()


def test_corrupt_frame_resend_is_seq_addressed(monkeypatch):
    """Pipelined sends must not confuse the replay: frame A is corrupted
    and frame B sent right behind it on the same channel. The reader
    requests A BY SEQ, so the consumer receives B and then A's clean
    replay — never B twice / A never."""
    monkeypatch.setenv(wire.ENV_WIRE_CRC, "1")
    import jax.numpy as jnp
    ctxs = _make_contexts(2)
    try:
        pa = np.full((4, 4), 3.0, np.float32)
        pb = np.full((4, 4), 7.0, np.float32)
        fa = wire.wire_encode_device(jnp.asarray(pa), 0,
                                     crc=True).finalize()
        fb = wire.wire_encode_device(jnp.asarray(pb), 0,
                                     crc=True).finalize()
        ctxs[0]._corrupt_next_send = True
        ctxs[0].send_tensors(1, fa, channel=0)   # corrupted in flight
        ctxs[0].send_tensors(1, fb, channel=0)   # clean, right behind
        got = [np.asarray(wire.wire_decode(
                   ctxs[1].recv_tensors(0, timeout=10.0, channel=0),
                   jnp.float32)) for _ in range(2)]
        vals = sorted(float(g[0, 0]) for g in got)
        assert vals == [3.0, 7.0], vals   # BOTH frames, exactly once
    finally:
        for c in ctxs:
            c.shutdown()


def test_send_retry_counts_snapshot():
    ctxs = _make_contexts(2)
    try:
        assert ctxs[0].send_retry_counts() == {}
    finally:
        for c in ctxs:
            c.shutdown()


# -- NaN/Inf guard ------------------------------------------------------

def test_nan_guard_off_by_default_passes_poison():
    poisoned = np.asarray([[1.0, float("nan")]], np.float32)
    assert nan_guard.check_finite(poisoned, "t") is poisoned


def test_nan_guard_raises_named_error_and_writes_bundle(
        tmp_path, monkeypatch):
    from pipeedge_tpu.telemetry import flight
    monkeypatch.setenv(nan_guard.ENV_NAN_GUARD, "1")
    flight.configure(rank=0, out_dir=str(tmp_path))
    before = nan_guard._POISONED.value()
    clean = np.ones((2, 2), np.float32)
    assert nan_guard.check_finite(clean, "t") is clean
    with pytest.raises(health.PoisonedActivationError) as exc:
        nan_guard.check_finite(
            (clean, np.asarray([[np.inf]], np.float32)), "stage1/input",
            mb=3, rid="r0.mb3")
    assert "stage1/input" in str(exc.value)
    assert nan_guard._POISONED.value() == before + 1
    bundles = list(tmp_path.glob("postmortem-*poison*.json"))
    assert bundles, "no poison postmortem written"
    doc = json.loads(bundles[0].read_text())
    assert doc["trigger"] == "poison"
    assert doc["context"]["where"] == "stage1/input"
    # integer payloads (token ids) can never poison
    ids = np.asarray([[1, 2, 3]], np.int32)
    assert nan_guard.check_finite(ids, "t") is ids


# -- report: gray section ----------------------------------------------

def test_report_gray_section():
    t = 1_000_000
    spans = [
        {"cat": "health", "name": "suspect:r2", "rank": 0, "stage": None,
         "mb": None, "t0": t, "t1": t},
        {"cat": "health", "name": "quarantine:r2", "rank": 0,
         "stage": None, "mb": None, "t0": t + 1, "t1": t + 1},
        {"cat": "health", "name": "readmit:r2", "rank": 0, "stage": None,
         "mb": None, "t0": t + 2, "t1": t + 2},
        {"cat": "compute", "name": "stage0", "rank": 0, "stage": 0,
         "mb": 0, "t0": t, "t1": t + 10},
    ]
    rec = report.analyze_spans(spans, span_cost_ns=100.0)
    gray = rec["gray"]
    assert gray["suspects"] == 1
    assert gray["quarantines"] == 1
    assert gray["readmits"] == 1
    assert gray["by_rank"]["r2"] == ["suspect", "quarantine", "readmit"]


def test_report_no_gray_section_on_clean_trace():
    t = 1_000_000
    spans = [{"cat": "compute", "name": "stage0", "rank": 0, "stage": 0,
              "mb": 0, "t0": t, "t1": t + 10}]
    rec = report.analyze_spans(spans, span_cost_ns=100.0)
    assert rec["gray"] == {}


# -- fleet acceptance (tier-1) -----------------------------------------

_MODEL = "pipeedge/test-tiny-vit"


def _run_gray_fleet(tmp_path, world, chaos_spec=None, victim=1, extra=(),
                    rounds=8, batch=24, timeout=280):
    """World-rank loopback fleet with the gray-failure plane armed;
    returns (data rc, data stdout, worker outputs)."""
    addrs = ",".join(f"127.0.0.1:{p}" for p in _free_ports(world))
    common = [sys.executable, os.path.join(REPO, "runtime.py")]
    opts = ["-c", "dcn", "--platform", "cpu", "-m", _MODEL,
            "-b", str(batch), "-u", "4", "-pt", "1,4,5,8", "-q", "0,0",
            "-r", "0,1", "--dcn-addrs", addrs, "--sched-timeout", "120",
            "--on-peer-death", "failover",
            "--on-peer-degraded", "quarantine",
            "--degraded-confirm", "1", "--degraded-readmit", "1",
            "--rounds", str(rounds),
            "--heartbeat-interval", "0.5", "--heartbeat-miss", "8",
            *extra]
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               DCN_CONNECT_TIMEOUT="30")
    dirs = []
    for r in range(world):
        d = tmp_path / f"rank{r}"
        d.mkdir(parents=True, exist_ok=True)
        dirs.append(d)
    workers = []
    for r in range(1, world):
        wenv = dict(env, DCN_CHAOS=chaos_spec) \
            if (chaos_spec and r == victim) else env
        workers.append(subprocess.Popen(
            common + [str(r), str(world)] + opts, cwd=dirs[r], env=wenv,
            text=True, stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    try:
        data = subprocess.run(common + ["0", str(world)] + opts,
                              cwd=dirs[0], env=env, capture_output=True,
                              text=True, timeout=timeout)
    finally:
        wouts = []
        for w in workers:
            try:
                out, _ = w.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                w.kill()
                out, _ = w.communicate()
            wouts.append(out)
    return data, wouts


@pytest.mark.fleet
def test_gray_straggler_quarantined_then_readmitted(tmp_path):
    """The tentpole acceptance: an 80 ms per-send straggler (never
    missing a beat) is quarantined at a round boundary, its stage moves
    to a spare (no replay — the round drained), and once the chaos
    clears (bounded slow@2-18: sends 2..18 ~= the first three rounds)
    probation readmits it. Round 0's jit-compile noise can mask the
    straggler for one window, so the bound leaves two clean measured
    windows either way. Deflaked: readmission is gated on probation
    STATE transitions (consecutive clean digest windows after the
    chaos clears), never wall-clock, so the assertions below key on
    the event ORDER in the log — quarantine strictly before readmit —
    and on the per-round result count, not on when either landed."""
    rounds = 8
    data, wouts = _run_gray_fleet(tmp_path, world=4,
                                  chaos_spec="slow@2-18:80",
                                  rounds=rounds)
    out = data.stdout + data.stderr
    fleet = out + "\n==WORKERS==\n" + "\n==\n".join(
        w[-4000:] for w in wouts)
    assert data.returncode == 0, fleet
    assert "quarantine_rank=1" in out, fleet
    # the re-plan moved stage 1 off the straggler onto a spare
    assert "moves rank 1 ->" in out, fleet
    # probation readmission once the bounded chaos cleared — and it must
    # FOLLOW the quarantine in event order (state machine, not timing)
    assert "readmit_rank=1" in out, fleet
    assert out.index("quarantine_rank=1") < out.index("readmit_rank=1"), \
        fleet
    # every round delivered its full batch (no results lost to the bench)
    assert out.count("latency_sec=") == rounds, fleet
    # the quarantine was planned, not a death: no failover replay ran
    assert "unacknowledged microbatch" not in out, fleet


@pytest.mark.fleet
def test_gray_clean_fleet_never_quarantines(tmp_path):
    """False-positive protection: the same fleet with NO chaos must
    finish with zero suspect/quarantine transitions."""
    data, wouts = _run_gray_fleet(tmp_path, world=4, chaos_spec=None,
                                  rounds=4)
    out = data.stdout + data.stderr
    assert data.returncode == 0, out
    assert "quarantine_rank=" not in out, out
    assert "readmit_rank=" not in out, out
    assert out.count("latency_sec=") == 4, out
