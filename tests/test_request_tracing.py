"""Request-scoped distributed tracing (docs/OBSERVABILITY.md, ISSUE 10):
trace-context wire round-trip (present / absent / truncated), span-ring
rid tagging, metric exemplars under window rollover, the flight recorder's
postmortem bundles, the per-request causal timeline with dominant-stall
attribution, and the tier-1 loopback acceptance run — one artificially
delayed request whose `trace_report --request` names the injected stall.
"""
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from pipeedge_tpu import telemetry
from pipeedge_tpu.comm import dcn
from pipeedge_tpu.telemetry import chrome_trace, flight, report
from pipeedge_tpu.telemetry import metrics as prom

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- trace context -------------------------------------------------------

def test_trace_context_wire_roundtrip():
    ctx = telemetry.TraceContext("q17", "batch", deadline_ms=1500.0,
                                 parent="serve.generate")
    back = telemetry.TraceContext.from_wire(ctx.to_wire())
    assert back.rid == "q17" and back.cls == "batch"
    assert back.deadline_ms == 1500.0 and back.parent == "serve.generate"
    # optional fields stay optional
    lean = telemetry.TraceContext.from_wire(
        telemetry.TraceContext("q0").to_wire())
    assert lean.rid == "q0" and lean.deadline_ms is None


def test_trace_context_absent_and_truncated_decode_to_none():
    """The tolerance contract: absent, truncated, or garbage blobs mean
    UNTRACED — never an exception into the reader thread."""
    blob = telemetry.TraceContext("q1").to_wire()
    assert telemetry.TraceContext.from_wire(blob[: len(blob) // 2]) is None
    assert telemetry.TraceContext.from_wire(np.zeros(0, np.uint8)) is None
    junk = np.frombuffer(b'{"cls": "no-rid-here"}', np.uint8)
    assert telemetry.TraceContext.from_wire(junk) is None
    not_json = np.frombuffer(b"\xff\xfe\x00garbage", np.uint8)
    assert telemetry.TraceContext.from_wire(not_json) is None


def test_trace_scope_thread_local_and_restores():
    outer = telemetry.TraceContext("outer")
    inner = telemetry.TraceContext("inner")
    telemetry.set_trace(None)
    with telemetry.trace_scope(outer):
        assert telemetry.current_trace().rid == "outer"
        with telemetry.trace_scope(inner):
            assert telemetry.current_trace().rid == "inner"
        assert telemetry.current_trace().rid == "outer"
        seen = []

        def other_thread():
            seen.append(telemetry.current_trace())

        t = threading.Thread(target=other_thread)
        t.start()
        t.join()
        assert seen == [None]     # TLS: never leaks across threads
    assert telemetry.current_trace() is None


def test_span_ring_rid_tagging_and_wire_compat():
    rec = telemetry.SpanRecorder(rank=1, capacity=8)
    rec.record("stage", "dispatch", 10, 20, stage=0, mb=3, rid="q5")
    rec.record("wire", "send->r1", 30, 40)          # untraced
    s_tagged, s_plain = rec.snapshot()
    assert s_tagged["rid"] == "q5" and s_plain["rid"] is None
    # implicit tagging from the thread's current trace context
    with telemetry.trace_scope(telemetry.TraceContext("q9")):
        rec.record("compute", "stage1", 50, 60, stage=1)
    assert rec.snapshot()[-1]["rid"] == "q9"
    # wire codec: rid survives, and PRE-tracing 7-field rows (a peer on
    # an older build) still decode — rid simply absent
    spans = rec.snapshot()
    assert telemetry.spans_from_wire(telemetry.spans_to_wire(spans)) \
        == spans
    old_row = json.dumps([["stage", "emit", 0, 1, 2, 100, 200]]).encode()
    (decoded,) = telemetry.spans_from_wire(
        np.frombuffer(old_row, np.uint8))
    assert decoded["name"] == "emit" and "rid" not in decoded


def test_chrome_trace_rid_roundtrip():
    spans = [{"cat": "stage", "name": "exec0", "rank": 0, "stage": 0,
              "mb": 1, "rid": "q3", "t0": 1000, "t1": 2000},
             {"cat": "wire", "name": "send->r1", "rank": 0, "stage": None,
              "mb": None, "rid": None, "t0": 1500, "t1": 1800}]
    doc = chrome_trace.build_trace(spans)
    back = chrome_trace.trace_to_spans(doc)
    by_name = {s["name"]: s for s in back}
    assert by_name["exec0"]["rid"] == "q3"
    assert by_name["send->r1"]["rid"] is None


# -- wire: traced frames -------------------------------------------------

def _free_ports(n):
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _make_contexts(n):
    ports = _free_ports(n)
    addrs = [("127.0.0.1", p) for p in ports]
    ctxs = [dcn.DistDcnContext(n, r, addrs) for r in range(n)]
    for c in ctxs:
        c.init()
    return ctxs


def test_dcn_traced_frame_roundtrip_present_absent_truncated():
    """A traced frame delivers its context; a plain frame delivers None;
    a truncated/garbage blob delivers the payload UNTRACED and bumps the
    invalid counter — the reader thread survives all three."""
    ctxs = _make_contexts(2)
    try:
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        tctx = telemetry.TraceContext("req-a", "interactive",
                                      deadline_ms=900.0)
        ctxs[0].send_tensors(1, [x], trace=tctx)
        got, _, back = ctxs[1].recv_tensors_traced(0, timeout=10)
        np.testing.assert_array_equal(got[0], x)
        assert back.rid == "req-a" and back.deadline_ms == 900.0
        assert dcn._TRACED_FRAMES.value(peer="0") >= 1

        ctxs[0].send_tensors(1, [x])                   # absent = untraced
        got, _, back = ctxs[1].recv_tensors_traced(0, timeout=10)
        np.testing.assert_array_equal(got[0], x)
        assert back is None

        # hand-build a traced frame whose blob is garbage: payload still
        # delivered, context None, invalid counter bumped
        invalid_before = dcn._TRACE_INVALID.value()
        with ctxs[0]._conn_locks[1]:
            conn = ctxs[0]._ensure_conn(1)
            dcn._send_frame(conn, dcn._MSG_TENSORS_TRACED, 0,
                            [np.frombuffer(b'{"truncated',
                                           np.uint8), x])
        got, _, back = ctxs[1].recv_tensors_traced(0, timeout=10)
        np.testing.assert_array_equal(got[0], x)
        assert back is None
        assert dcn._TRACE_INVALID.value() == invalid_before + 1
    finally:
        for c in ctxs:
            c.shutdown()


def test_dcn_stage_spans_inherit_request_id():
    """A DcnPipelineStage's dispatch/emit spans carry the inbound frame's
    request id, and the context propagates DOWNSTREAM automatically —
    rank 1's stage emits to rank 0 and the results frame still carries
    the feed's rid (the fleet-wide inheritance the tentpole requires)."""
    rec = telemetry.configure(rank=0)
    ctxs = _make_contexts(2)
    try:
        done = threading.Event()
        results = []

        def work(tensors):
            return [tensors[0] * 2]

        stage = dcn.DcnPipelineStage(ctxs[1], rank_src=0, rank_dst=0,
                                     work_cb=work, stage=1)
        with stage:
            tctx = telemetry.TraceContext("r0.mb0", "batch")
            ctxs[0].send_tensors(1, [np.ones(4, np.float32)], trace=tctx)
            got, _, back = ctxs[0].recv_tensors_traced(1, timeout=10)
            results.append(got)
            assert back is not None and back.rid == "r0.mb0"
            done.set()
        assert done.is_set()
        np.testing.assert_array_equal(results[0][0],
                                      2 * np.ones(4, np.float32))
        rids = {(s["cat"], s["name"]): s["rid"] for s in rec.snapshot()}
        assert rids.get(("stage", "dispatch")) == "r0.mb0"
        assert rids.get(("stage", "emit")) == "r0.mb0"
    finally:
        telemetry.disable()
        for c in ctxs:
            c.shutdown()


# -- metric exemplars ----------------------------------------------------

def test_exemplar_retains_max_latency_trace_id():
    h = prom.Histogram("t_req_latency", "t", buckets=(0.1, 1.0),
                       exemplar_window_s=60.0)
    # near the real clock so render() (which reads time.monotonic for
    # window pruning) sees the exemplars as fresh
    t = time.monotonic()
    h.observe(0.5, exemplar="q1", now=t - 3.0)
    h.observe(0.9, exemplar="q2", now=t - 2.0)  # same bucket, worse: wins
    h.observe(0.6, exemplar="q3", now=t - 1.0)  # not worse: ignored
    h.observe(5.0, exemplar="q4", now=t)        # +Inf overflow bucket
    ex = h.exemplars(now=t)
    assert ex["1"]["trace_id"] == "q2" and ex["1"]["value"] == 0.9
    assert ex["+Inf"]["trace_id"] == "q4"
    # exemplar comment lines ride /metrics without breaking the text
    # format (every '#' line that is not HELP/TYPE is parser-skipped)
    lines = h.render()
    ex_lines = [ln for ln in lines if ln.startswith("# EXEMPLAR")]
    assert any('trace_id="q2"' in ln and 'le="1"' in ln
               for ln in ex_lines)
    for ln in lines:
        assert ln.startswith("#") or ln.split()[1].replace(".", "").isdigit()


def test_exemplar_window_rollover_admits_fresh_observation():
    """After the window rolls over, a SMALLER fresh observation replaces
    the stale maximum — the 'per bucket window' retention semantics."""
    h = prom.Histogram("t_roll", "t", buckets=(1.0,),
                       exemplar_window_s=10.0)
    h.observe(0.9, exemplar="old-max", now=0.0)
    h.observe(0.2, exemplar="mid", now=5.0)          # within window: loses
    assert h.exemplars(now=5.0)["1"]["trace_id"] == "old-max"
    h.observe(0.1, exemplar="fresh", now=20.0)       # rolled over: wins
    assert h.exemplars(now=20.0)["1"]["trace_id"] == "fresh"
    # an expired exemplar with no successor disappears rather than lie
    h2 = prom.Histogram("t_expire", "t", buckets=(1.0,),
                        exemplar_window_s=10.0)
    h2.observe(0.5, exemplar="only", now=0.0)
    assert h2.exemplars(now=5.0) and not h2.exemplars(now=30.0)


# -- flight recorder -----------------------------------------------------

def test_flight_recorder_ring_dump_and_cooldown(tmp_path):
    fr = flight.FlightRecorder(rank=3, capacity=4,
                               out_dir=str(tmp_path), cooldown_s=60.0)
    for i in range(6):                       # overflow: drop-oldest
        fr.note("admit", rid=f"q{i}", cls="interactive")
    assert fr.dropped == 2
    assert [e["rid"] for e in fr.events()] == ["q2", "q3", "q4", "q5"]
    assert [e["rid"] for e in fr.events(rid="q4")] == ["q4"]

    before = fr.written_total()
    path = fr.maybe_dump("deadline", rid="q4",
                         context={"admission": {"queue_depth": 2}})
    assert path is not None and os.path.exists(path)
    assert fr.last_path() == path
    assert fr.written_total() == before + 1
    bundle = json.load(open(path))
    assert bundle["bundle"] == "pipeedge-postmortem"
    assert bundle["trigger"] == "deadline" and bundle["rid"] == "q4"
    assert bundle["rank"] == 3
    assert bundle["context"]["admission"]["queue_depth"] == 2
    assert any(e["rid"] == "q4" for e in bundle["events"])

    # cooldown: a second deadline dump inside the window is suppressed...
    assert not fr.would_dump("deadline")      # the cheap pre-check agrees
    assert fr.would_dump("manual")            # manual is never suppressed
    assert fr.maybe_dump("deadline", rid="q5") is None
    # ...other triggers have their own clocks, manual is never suppressed
    assert fr.maybe_dump("failover") is not None
    assert fr.maybe_dump("manual") is not None
    assert fr.maybe_dump("manual") is not None
    with pytest.raises(ValueError):
        fr.maybe_dump("nonsense")


def test_flight_bundle_carries_request_span_slice(tmp_path):
    """With span recording on, a bundle's `spans` slice holds the rid's
    spans plus mb-linked neighbors — and trace_report's loader consumes a
    bundle directly."""
    rec = telemetry.configure(rank=0)
    try:
        rec.record("serve", "admit:interactive", 0, 10, rid="q7")
        rec.record("stage", "exec0", 10, 50, stage=0, mb=0, rid="q7")
        rec.record("wire", "send->r1", 20, 30, mb=0)      # mb-linked
        rec.record("stage", "exec0", 60, 70, stage=0, mb=9, rid="other")
        fr = flight.FlightRecorder(out_dir=str(tmp_path))
        path = fr.maybe_dump("manual", rid="q7")
        bundle = json.load(open(path))
        names = {s["name"] for s in bundle["spans"]}
        assert names == {"admit:interactive", "exec0", "send->r1"}
        assert all(s["rid"] == "q7" or s["mb"] == 0
                   for s in bundle["spans"])
        tl = report.request_timeline(bundle["spans"], "q7")
        assert tl["found"] and tl["dominant_stall"]["segment"] \
            == "stage0/compute"
    finally:
        telemetry.disable()


def test_flight_trace_slice_none_keeps_all():
    spans = [{"rid": "a", "mb": 1, "t0": 0, "t1": 1},
             {"rid": None, "mb": 2, "t0": 1, "t1": 2}]
    assert len(flight.trace_slice(spans, None)) == 2


# -- request timeline ----------------------------------------------------

def _ms(n):
    return n * 1_000_000


def test_request_timeline_dominant_stall_and_attribution():
    """Hand-built two-rank request: queue wait 2ms, stage0 compute 3ms,
    wire 1ms, stage1 compute 20ms (the stall), retire 1ms — the dominant
    stall must name stage1's compute and the ranks/stages/mbs must cover
    the whole path."""
    spans = [
        {"cat": "serve", "name": "admit:interactive", "rank": 0,
         "t0": 0, "t1": _ms(2), "rid": "q1"},
        {"cat": "feed", "name": "mb0", "rank": 0, "mb": 0,
         "t0": _ms(2), "t1": _ms(3), "rid": "q1"},
        {"cat": "compute", "name": "stage0", "rank": 0, "stage": 0,
         "mb": 0, "t0": _ms(3), "t1": _ms(6), "rid": "q1"},
        {"cat": "wire", "name": "send->r1", "rank": 0, "mb": 0,
         "t0": _ms(6), "t1": _ms(7), "rid": "q1"},
        {"cat": "stage", "name": "dispatch", "rank": 1, "stage": 1,
         "mb": 0, "t0": _ms(7), "t1": _ms(27), "rid": "q1"},
        {"cat": "results", "name": "deliver", "rank": 0, "mb": 0,
         "t0": _ms(27), "t1": _ms(28), "rid": "q1"},
        # another request's spans must not contaminate the timeline
        {"cat": "compute", "name": "stage0", "rank": 0, "stage": 0,
         "mb": 5, "t0": 0, "t1": _ms(50), "rid": "q2"},
    ]
    tl = report.request_timeline(spans, "q1")
    assert tl["found"] and tl["spans"] == 6
    assert tl["ranks"] == [0, 1] and tl["stages"] == [0, 1]
    assert tl["mbs"] == [0] and tl["total_ms"] == 28.0
    assert tl["dominant_stall"]["segment"] == "stage1/dispatch"
    assert tl["dominant_stall"]["busy_ms"] == 20.0
    assert tl["segments"]["queue_wait"]["busy_ms"] == 2.0
    assert tl["segments"]["wire/send->r1"]["busy_ms"] == 1.0
    assert tl["unattributed_ms"] == 0.0
    assert report.request_timeline(spans, "nope") == {"rid": "nope",
                                                      "found": False}


def test_analyze_spans_requests_section():
    spans = [
        {"cat": "serve", "name": "generate", "rank": 0,
         "t0": 0, "t1": _ms(30), "rid": "q1"},
        {"cat": "serve", "name": "generate", "rank": 0,
         "t0": 0, "t1": _ms(5), "rid": "q2"},
        {"cat": "compute", "name": "stage0", "rank": 0, "stage": 0,
         "t0": 0, "t1": _ms(1)},
    ]
    rec = report.analyze_spans(spans, span_cost_ns=100.0)
    assert rec["requests"]["n"] == 2
    assert rec["requests"]["worst"][0] == {"rid": "q1", "ms": 30.0}
    # traces without rids carry an empty section, not a crash
    rec2 = report.analyze_spans(spans[-1:], span_cost_ns=100.0)
    assert rec2["requests"] == {}


# -- loadgen worst-N -----------------------------------------------------

def test_loadgen_stats_worst_n_and_deadline_rids():
    from tools import loadgen
    st = loadgen._Stats(["interactive"])
    for i in range(8):
        st.record("interactive", "ok", latency_ms=float(i), rid=f"q{i}")
    st.record("interactive", "deadline", rid="q504")
    assert [w[1] for w in st.worst["interactive"]] \
        == ["q7", "q6", "q5", "q4", "q3"]
    assert st.deadline_rids == ["q504"]


def test_streaming_shed_counts_class_outcome_matrix():
    """A STREAMING request shed at admission never reaches generate(),
    so the request-class x outcome matrix (and the endpoint counter)
    must be settled on the streaming path itself — the 503s and the
    matrix have to reconcile under a shed storm of streaming clients."""
    import urllib.error
    import urllib.request
    from http.server import ThreadingHTTPServer

    from pipeedge_tpu.serving import AdmissionShed
    from tools import serve as serve_mod

    from pipeedge_tpu.models import registry
    from pipeedge_tpu.parallel import decode
    total = registry.get_model_layers("pipeedge/test-tiny-gpt2")
    _, params, _ = registry.module_shard_factory(
        "pipeedge/test-tiny-gpt2", None, 1, total, unroll=False)
    pipe = decode.DecodePipeline(
        registry.get_model_entry("pipeedge/test-tiny-gpt2").family.FAMILY,
        registry.get_model_config("pipeedge/test-tiny-gpt2"),
        [(1, total)], [params], max_len=32)
    svc = serve_mod._Service(pipe, executor="wave")
    try:
        def always_shed(request_class, deadline_s=None, rid=None,
                        tokens=0):
            raise AdmissionShed(request_class, "queue_full", 1.25)

        svc.admit = always_shed
        shed_before = svc.m_class_outcome.value(
            **{"class": "interactive", "outcome": "shed"})
        server = ThreadingHTTPServer(
            ("127.0.0.1", 0), serve_mod.make_handler(svc, "tiny"))
        port = server.server_address[1]
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps({"ids": [[1, 2, 3]], "new_tokens": 2,
                                 "stream": True}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                urllib.request.urlopen(req, timeout=30)
            exc = exc_info.value
            body = json.loads(exc.read())
            # the shed surfaced as a real 503 (headers never committed),
            # rid included, and BOTH counters moved
            assert exc.code == 503 and body["shed"] and body["rid"]
            assert exc.headers.get("Retry-After") == "1.25"
            assert svc.m_class_outcome.value(
                **{"class": "interactive", "outcome": "shed"}) \
                == shed_before + 1
        finally:
            server.shutdown()
            t.join(timeout=10)
    finally:
        svc.stop()


# -- tier-1 loopback acceptance -----------------------------------------

def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _post(port, path, obj, timeout=120):
    import urllib.error
    import urllib.request
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


def _get(port, path, timeout=30):
    import urllib.request
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as resp:
        return json.loads(resp.read())


@pytest.mark.fleet
def test_traced_serve_stall_attribution(tmp_path):
    """The acceptance run (ISSUE 10): a traced serve with a deterministic
    80 ms stall injected into stage 1 answers one request; its rid (from
    the response body) must resolve via `trace_report --request` to a
    timeline whose dominant stall names stage 1. A second request with a
    too-small deadline must 504, auto-writing a postmortem bundle that
    /healthz names and that trace_report can read directly."""
    port = _free_port()
    trace_path = tmp_path / "serve_trace.json"
    pm_dir = tmp_path / "postmortems"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "serve.py"),
         "-m", "pipeedge/test-tiny-gpt2", "-pt", "1,4,5,8",
         "--max-len", "48", "-t", "float32", "--port", str(port),
         "--trace-spans", str(trace_path),
         "--inject-stall", "1:80",
         "--postmortem-dir", str(pm_dir)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if "serving" in line:
                break
            if proc.poll() is not None:
                raise RuntimeError(f"server died: {proc.stdout.read()}")
        else:
            raise RuntimeError("server never came up")

        # 0) warmup: the first request pays the XLA compiles, and its
        #    timeline would (correctly!) name the compile as its
        #    dominant stall — the assertion below is about the
        #    steady-state stall, so compile the same shapes first
        status, _ = _post(port, "/generate",
                          {"ids": [[1, 2, 3, 4]], "new_tokens": 2})
        assert status == 200

        # 1) the traced request: every response carries its rid
        status, resp = _post(port, "/generate",
                             {"ids": [[1, 2, 3, 4]], "new_tokens": 4})
        assert status == 200 and resp["rid"]
        rid = resp["rid"]

        # 2) deadline small enough that the stage-1 stall eats it ->
        #    504 + rid + an automatic deadline postmortem bundle
        status, resp504 = _post(port, "/generate",
                                {"ids": [[1, 2, 3, 4]], "new_tokens": 40,
                                 "deadline_ms": 250})
        assert status == 504 and resp504.get("deadline_exceeded")
        assert resp504["rid"]
        h = _get(port, "/healthz")
        assert h["flight"]["postmortems_written_total"] >= 1
        bundle_path = h["flight"]["last_postmortem"]
        assert bundle_path and os.path.exists(bundle_path)
        bundle = json.load(open(bundle_path))
        assert bundle["trigger"] == "deadline"
        assert bundle["rid"] == resp504["rid"]
        assert any(e["kind"] == "deadline" for e in bundle["events"])
        assert "serving" in bundle["context"]

        # 3) manual dump on demand
        status, dump = _post(port, "/debug/dump", {"rid": rid})
        assert status == 200 and os.path.exists(dump["path"])

        # 4) /metrics: exemplars link the latency histogram to a rid,
        #    and the postmortem counter is shared with /healthz
        import urllib.request
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30) as f:
            metrics = f.read().decode()
        assert "# EXEMPLAR pipeedge_serve_request_latency_seconds_bucket" \
            in metrics
        assert "pipeedge_postmortems_written_total" in metrics
        assert 'trace_id="' in metrics
    finally:
        proc.send_signal(signal.SIGTERM)   # trace written on unwind
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            raise

    # 5) trace_report --request: the dominant stall names stage 1 (the
    #    injected 80 ms sleep rides inside stage 1's exec span)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         str(trace_path), "--request", rid],
        capture_output=True, text=True, env=env, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    tl = json.loads(out.stdout)
    assert tl["found"] and tl["rid"] == rid
    assert tl["dominant_stall"]["segment"] == "stage1/compute", \
        tl["dominant_stall"]
    assert tl["segments"].get("queue_wait") is not None
    assert 1 in tl["stages"]
    # an unknown rid exits 3, not 0
    missing = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         str(trace_path), "--request", "no-such-rid"],
        capture_output=True, text=True, env=env, timeout=120)
    assert missing.returncode == 3
    # the full report's requests section names traced requests
    full = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         str(trace_path)],
        capture_output=True, text=True, env=env, timeout=120)
    assert full.returncode == 0
    rec = json.loads(full.stdout)
    assert rec["requests"]["n"] >= 2
    assert any(w["rid"] == resp504["rid"] or w["rid"] == rid
               for w in rec["requests"]["worst"])
