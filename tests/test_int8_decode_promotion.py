"""Int8-KV decode-attention promotion (ISSUE 19): the kernel opt-in is a
constructor knob on DecodePipeline (`int8_decode_attend=`), resolved once
at build — env `PIPEEDGE_INT8_DECODE_ATTEND` and the QuantizeCompute
config are fallbacks — and BOTH production executors (ContinuousBatcher,
StageWorkerExecutor) stay token-identical to the XLA dequant route while
the KV pages hold int8 in the KvPagePool."""
import threading

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from pipeedge_tpu.kv import PagedKvBackend  # noqa: E402
from pipeedge_tpu.models import layers, registry  # noqa: E402
from pipeedge_tpu.parallel import decode  # noqa: E402
from pipeedge_tpu.parallel.batcher import (ContinuousBatcher,  # noqa: E402
                                           StageWorkerExecutor)
from pipeedge_tpu.telemetry import metrics as prom  # noqa: E402

MODEL = "pipeedge/test-tiny-gpt2"
PARTITION = [(1, 4), (5, 8)]
MAX_LEN = 48


def _mk_pipe(int8_decode_attend):
    params = [registry.module_shard_factory(MODEL, None, l, r, stage=i,
                                            unroll=False)[1]
              for i, (l, r) in enumerate(PARTITION)]
    return decode.DecodePipeline(
        registry.get_model_entry(MODEL).family.FAMILY,
        registry.get_model_config(MODEL), PARTITION, params,
        max_len=MAX_LEN, cache_bits=8,
        int8_decode_attend=int8_decode_attend)


@pytest.fixture(scope="module")
def pipes():
    """(kernel-route pipe, XLA-dequant-route pipe), both int8 KV."""
    return _mk_pipe(1), _mk_pipe(0)


def _backend(pipe):
    return PagedKvBackend(pipe, 24, 4, registry=prom.Registry())


# -- opt-in resolution ---------------------------------------------------

def test_resolution_precedence(monkeypatch):
    monkeypatch.delenv("PIPEEDGE_INT8_DECODE_ATTEND", raising=False)
    prev = layers._QUANTIZE_COMPUTE
    try:
        layers.set_quantize_compute(None)
        assert decode._resolve_int8_optin(None) == 0       # all defaults
        assert decode._resolve_int8_optin(1) == 1          # explicit arg
        assert decode._resolve_int8_optin("auto") == 3
        assert decode._resolve_int8_optin("off") == 0
        # env fallback, including an explicit off
        monkeypatch.setenv("PIPEEDGE_INT8_DECODE_ATTEND", "2")
        assert decode._resolve_int8_optin(None) == 2
        # the int8 compute config turns decode attend on (auto policy)
        # unless the env explicitly says otherwise
        layers.set_quantize_compute(True)
        monkeypatch.setenv("PIPEEDGE_INT8_DECODE_ATTEND", "0")
        assert decode._resolve_int8_optin(None) == 0
        monkeypatch.delenv("PIPEEDGE_INT8_DECODE_ATTEND")
        assert decode._resolve_int8_optin(None) == 3
        # constructor arg beats everything
        assert decode._resolve_int8_optin("1") == 1
    finally:
        layers.set_quantize_compute(prev)


def test_constructor_arg_binds_optin(pipes):
    pipe_kernel, pipe_xla = pipes
    assert pipe_kernel.int8_decode_optin == 1
    assert pipe_xla.int8_decode_optin == 0


# -- both executors, token parity, int8 pages ---------------------------

def _assert_pool_pages_int8(kv):
    for stage_leaves in kv.pool._arena:
        assert stage_leaves["k"].dtype == jnp.int8
        assert stage_leaves["v"].dtype == jnp.int8
        assert "k_scale" in stage_leaves       # dequant rows ride along


def test_wave_batcher_token_identical_with_kernel(pipes):
    pipe_kernel, pipe_xla = pipes
    kv = _backend(pipe_kernel)
    _assert_pool_pages_int8(kv)
    batcher = ContinuousBatcher(pipe_kernel, kv=kv)
    rng = np.random.default_rng(23)
    prompts = [rng.integers(0, 100, size=(1, n)) for n in (6, 9)]
    for i, ids in enumerate(prompts):
        batcher.submit(i, ids, new_tokens=6)
    results = batcher.run()
    for i, ids in enumerate(prompts):
        ref = np.asarray(pipe_xla.generate(ids, 6))
        np.testing.assert_array_equal(results[i], ref)
    # pages all returned (kernel path leaks no pages either)
    cached = kv.trie.stats()["pages_cached"]
    assert kv.pool.free_pages + cached == kv.pool.n_pages


def test_stage_executor_token_identical_with_kernel(pipes):
    pipe_kernel, pipe_xla = pipes
    kv = _backend(pipe_kernel)
    ex = StageWorkerExecutor(pipe_kernel, kv=kv)
    try:
        rng = np.random.default_rng(31)
        ids = rng.integers(0, 100, size=(1, 7))
        outs = {}

        def client(rid):
            ex.submit(rid, ids, 6)
            outs[rid] = ex.wait(rid, timeout=300)

        threads = [threading.Thread(target=client, args=(f"r{i}",),
                                    daemon=True) for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive()
        ref = np.asarray(pipe_xla.generate(ids, 6))
        for rid in outs:
            np.testing.assert_array_equal(outs[rid], ref)
    finally:
        ex.stop()
    _assert_pool_pages_int8(kv)


def test_auto_policy_route_matches_xla(pipes):
    # 'auto' resolves to the width-policy v2 kernel at these tiny widths
    # (interpret mode off-TPU) — the route serve.py takes when the int8
    # compute path is enabled. Tier-1 coverage matters here: the v2
    # lowering once broke silently on a jax rename (TPUCompilerParams)
    # because the dedicated kernel suite is slow-marked.
    _, pipe_xla = pipes
    pipe_auto = _mk_pipe("auto")
    assert pipe_auto.int8_decode_optin == 3
    rng = np.random.default_rng(47)
    ids = rng.integers(0, 100, size=(1, 8))
    np.testing.assert_array_equal(
        np.asarray(pipe_auto.generate(ids, 8)),
        np.asarray(pipe_xla.generate(ids, 8)))


def test_solo_generate_kernel_matches_xla_route(pipes):
    pipe_kernel, pipe_xla = pipes
    rng = np.random.default_rng(41)
    ids = rng.integers(0, 100, size=(1, 8))
    np.testing.assert_array_equal(
        np.asarray(pipe_kernel.generate(ids, 8)),
        np.asarray(pipe_xla.generate(ids, 8)))
