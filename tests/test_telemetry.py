"""Per-edge send/recv telemetry feeding the adaptive quantization policies.

The reference measures each rank's real wire traffic with send/recv hooks
(reference comm/p2p/__init__.py:132-152, runtime.py:219-230) and each rank's
adaptive policy steers on its OWN send window (runtime.py:121-216). These
tests pin the single-controller equivalents: host-pipeline edges report their
actual wire bytes (packed size when quantized), the adaptive callback
consumes per-edge monitoring windows (a throttled edge's stage drops its
bitwidth while an uncongested edge's stage does not), and the DCN wire
format carries the bitwidth on the wire so a producer can change it mid-run.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

import monitoring
import runtime
from pipeedge_tpu.monitoring import MonitorIterationContext
from pipeedge_tpu.ops import quant as quant_ops
from pipeedge_tpu.parallel import pipeline as host_pipeline


def test_payload_wire_bytes_quantized_vs_raw():
    x = jnp.zeros((2, 8, 16), jnp.float32)
    raw = host_pipeline.payload_wire_bytes(x)
    assert raw == 2 * 8 * 16 * 4
    enc = quant_ops.tensor_encode_outerdim(x, 8)
    q = host_pipeline.payload_wire_bytes(enc)
    assert q == enc.nbytes_wire + enc.scale.nbytes + enc.shift.nbytes
    assert q < raw / 3  # 8-bit packing: ~4x smaller (plus scalar metadata)
    assert host_pipeline.payload_wire_bytes((x, x)) == 2 * raw


def test_host_pipeline_reports_per_edge_wire_bytes():
    dev = jax.devices()[0]
    stages = [host_pipeline.PipelineStage(shard_fn=lambda p, x: x + 1,
                                          params={}, device=dev, quant_bit=b)
              for b in (8, 0, 0)]
    seen = []
    pipe = host_pipeline.HostPipeline(
        stages, edge_bytes_callback=lambda i, eb: seen.append((i, list(eb))))
    ubatches = [jnp.zeros((2, 4, 8), jnp.float32) for _ in range(3)]
    pipe.run(ubatches)
    assert [i for i, _ in seen] == [0, 1, 2]
    for _, eb in seen:
        assert len(eb) == 2           # one count per inter-stage edge
        assert eb[0] < eb[1]          # 8-bit edge0 beats the raw f32 edge1
        assert eb[1] == 2 * 4 * 8 * 4


def _feed_window(key, work_mbits, duration_s, n):
    """Inject n beats of (work, duration) into a monitoring key — simulating
    an edge whose transfers take `duration_s` each (e.g. a throttled link)."""
    with monitoring.get_locked_context(key) as mctx:
        for _ in range(n):
            ic = MonitorIterationContext(
                t_ns_last=time.monotonic_ns() - int(duration_s * 1e9),
                e_uj_last=0)
            mctx.iteration(key=key, work=work_mbits, iter_ctx=ic)


def test_adaptive_drops_only_the_throttled_edge(monkeypatch, tmp_path):
    monkeypatch.chdir(tmp_path)  # monitoring writes per-key CSVs in cwd
    monkeypatch.setenv("ADAPTIVE_QUANT", "HEURISTIC2")
    monkeypatch.setenv("SEND_CONSTRAINT", "10")  # items/sec
    window = 4
    monitoring.init("shard", window)
    try:
        monitoring.add_key("send0", work_type="Mbits")
        monitoring.add_key("send1", work_type="Mbits")
        s0 = runtime._EdgeQuantState(8)
        s1 = runtime._EdgeQuantState(8)
        cb = runtime._make_adaptive_callback([s0, s1], window,
                                             edge_keys=["send0", "send1"])
        assert cb is not None
        # ubatch_size=2 at 10 items/s -> 0.2 s transfer budget per microbatch.
        # Edge 0 moves 1 Mbit in 0.05 s (fine); edge 1 takes 1.5 s (7.5x
        # over budget -> needs >=7.5x compression -> 4-bit, floor(32/4)=8).
        _feed_window("send0", 1.0, 0.05, window)
        _feed_window("send1", 1.0, 1.5, window)
        cb(window - 1, np.zeros((2, 5), np.float32))
        assert s0.quant_bit == 0       # uncongested edge: no quantization
        assert s1.quant_bit == 4       # throttled edge drops to 4-bit
    finally:
        monitoring.finish()


def test_dcn_wire_format_carries_bitwidth():
    """The consumer decodes with the bitwidth from the wire header — no
    shared schedule metadata needed (reference ships quant_bit as the 5th
    element of every encoded tensor, basic_op.py:143)."""
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 4, 8)),
                    jnp.float32)
    wire = runtime._wire_encode(x, 8)
    assert int(wire[0]) == 8
    dec = runtime._wire_decode(wire, jnp.float32)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(x), atol=0.1)

    wire0 = runtime._wire_encode(x, 0)  # passthrough still carries header
    assert int(wire0[0]) == 0
    np.testing.assert_array_equal(
        np.asarray(runtime._wire_decode(wire0, jnp.float32)), np.asarray(x))

    for bit in (2, 4, 16):  # producer may pick any supported width mid-run
        dec = runtime._wire_decode(runtime._wire_encode(x, bit), jnp.float32)
        assert np.asarray(dec).shape == x.shape

    # 2-tuple payloads (mid-block cuts) encode per tensor
    pair = (x, x + 1)
    dec = runtime._wire_decode(runtime._wire_encode(pair, 8), jnp.float32)
    assert isinstance(dec, tuple) and len(dec) == 2
