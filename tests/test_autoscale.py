"""The capacity controller's decision logic (pipeedge_tpu/serving/
autoscale.py): confirm streaks, dwell hysteresis, cooldown + flap
damper, brownout ordering, dry-run held transitions, advise-vs-auto
parity, and bound behaviour — all under the injected clock, no fleet
(ISSUE 20's unit matrix; the process-level acceptance lives in
tools/chaos_dcn.py --target autoscale and the CI autoscale-chaos job).
"""
import pytest

from pipeedge_tpu.serving.autoscale import (AutoscaleRunner,
                                            CapacityController,
                                            CapacityPolicy, DIRECTIONS,
                                            MODES, OUTCOMES,
                                            default_classify,
                                            signals_from_fleet)


HOT = {"queue_depth": 100.0, "brownout_level": 0, "burn_rate": 0.0}
COLD = {"queue_depth": 0.0, "brownout_level": 0, "burn_rate": 0.0}
NEUTRAL = {"queue_depth": 2.0, "brownout_level": 0, "burn_rate": 0.5}


def _ctl(size=1, mode="auto", plan_ok=True, **kw):
    """Controller over a mutable fake fleet: auto-apply mutates size."""
    kw.setdefault("min_size", 1)
    kw.setdefault("max_size", 3)
    kw.setdefault("confirm", 2)
    kw.setdefault("cooldown_s", 5.0)
    state = {"size": size, "applied": []}

    def plan_fn(direction, frm, to):
        if not plan_ok:
            return {"ok": False, "reason": "floor"}
        return {"ok": True, "direction": direction, "from": frm, "to": to}

    def apply_fn(plan):
        state["size"] = plan["to"]
        state["applied"].append(plan)

    ctl = CapacityController(CapacityPolicy(**kw), mode=mode,
                             size_fn=lambda: state["size"],
                             plan_fn=plan_fn, apply_fn=apply_fn)
    return ctl, state


def _drive(ctl, signals, n, t0=0.0, dt=1.0):
    """Tick `n` windows of `signals`; return decisions fired + end time."""
    out, t = [], t0
    for _ in range(n):
        d = ctl.tick(signals, now=t)
        if d is not None:
            out.append(d)
        t += dt
    return out, t


# ---------------------------------------------------------------------------
# policy validation
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("bad", [
    dict(min_size=0), dict(min_size=3, max_size=2), dict(confirm=0),
    dict(cooldown_s=-1), dict(dwell_up_s=-1),
    dict(queue_low=5.0, queue_high=4.0), dict(queue_low=-1.0),
    dict(burn_low=2.0, burn_high=1.0), dict(flap_cap=0.5),
])
def test_policy_rejects_bad_knobs(bad):
    with pytest.raises(ValueError):
        CapacityPolicy(**bad)


def test_controller_rejects_bad_mode():
    with pytest.raises(ValueError):
        CapacityController(mode="yolo")
    assert set(MODES) == {"off", "advise", "auto"}
    assert set(DIRECTIONS) == {"up", "down"}
    assert set(OUTCOMES) == {"applied", "advised", "held", "flap_damped"}


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

def test_classify_signs():
    pol = CapacityPolicy(queue_high=4, queue_low=0.5, burn_high=1.0,
                         burn_low=0.25)
    up = dict(HOT, size=1)
    assert default_classify(pol, up) == 1
    assert default_classify(pol, dict(COLD, size=1)) == -1
    assert default_classify(pol, dict(NEUTRAL, size=1)) == 0
    # brownout rung alone is up pressure
    assert default_classify(pol, dict(COLD, brownout_level=1, size=1)) == 1
    # burn alone is up pressure
    assert default_classify(pol, dict(COLD, burn_rate=2.0, size=1)) == 1
    # queue is per capacity unit: depth 6 over 4 units is calm
    assert default_classify(pol, {"queue_depth": 6.0, "brownout_level": 0,
                                  "burn_rate": 0.0, "size": 4}) == 0


# ---------------------------------------------------------------------------
# confirm + dwell hysteresis
# ---------------------------------------------------------------------------

def test_single_hot_window_moves_nothing():
    ctl, state = _ctl(confirm=3)
    assert ctl.tick(HOT, now=0.0) is None
    assert ctl.tick(NEUTRAL, now=1.0) is None     # streak broken
    assert ctl.tick(HOT, now=2.0) is None
    assert ctl.tick(HOT, now=3.0) is None
    assert state["size"] == 1                     # never reached confirm=3


def test_confirmed_pressure_scales_up():
    ctl, state = _ctl(confirm=2)
    fired, _ = _drive(ctl, HOT, 2)
    assert [d.outcome for d in fired] == ["applied"]
    assert fired[0].direction == "up"
    assert (fired[0].frm, fired[0].to) == (1, 2)
    assert state["size"] == 2
    assert "autoscale_decision direction=up" in fired[0].line()


def test_streak_resets_on_direction_change():
    ctl, state = _ctl(confirm=2)
    assert ctl.tick(HOT, now=0.0) is None
    assert ctl.tick(COLD, now=1.0) is None        # reversal resets streak
    assert ctl.tick(HOT, now=2.0) is None         # streak = 1 again
    assert state["size"] == 1


def test_dwell_blocks_until_streak_has_lasted():
    ctl, state = _ctl(confirm=2, dwell_up_s=10.0)
    fired, t = _drive(ctl, HOT, 5, dt=1.0)        # 5s of streak < 10s dwell
    assert fired == [] and state["size"] == 1
    fired, _ = _drive(ctl, HOT, 7, t0=t, dt=1.0)  # streak age crosses 10s
    assert [d.outcome for d in fired] == ["applied"]


def test_dwell_down_independent_of_dwell_up():
    ctl, state = _ctl(size=2, confirm=1, dwell_up_s=0.0, dwell_down_s=30.0)
    fired, _ = _drive(ctl, COLD, 10, dt=1.0)
    assert fired == [] and state["size"] == 2     # down dwell not served
    fired, _ = _drive(ctl, HOT, 1, t0=100.0)      # up fires immediately
    assert [d.direction for d in fired] == ["up"]


# ---------------------------------------------------------------------------
# bounds: steady state at the floor/ceiling is NOT a decision
# ---------------------------------------------------------------------------

def test_zero_decisions_at_floor_on_cold_fleet():
    ctl, state = _ctl(size=1, confirm=1, cooldown_s=0.0)
    fired, _ = _drive(ctl, COLD, 50)
    assert fired == []                            # the steady control run
    assert state["size"] == 1
    assert ctl.snapshot()["decisions"] == {o: 0 for o in OUTCOMES}


def test_zero_decisions_at_ceiling_under_pressure():
    ctl, state = _ctl(size=3, confirm=1, cooldown_s=0.0, max_size=3)
    fired, _ = _drive(ctl, HOT, 20)
    assert fired == [] and state["size"] == 3


# ---------------------------------------------------------------------------
# cooldown + flap damper
# ---------------------------------------------------------------------------

def test_cooldown_spaces_decisions():
    ctl, state = _ctl(confirm=1, cooldown_s=10.0)
    assert ctl.tick(HOT, now=0.0).outcome == "applied"   # 1 -> 2
    fired, _ = _drive(ctl, HOT, 9, t0=1.0)               # inside cooldown
    assert fired == [] and state["size"] == 2
    assert ctl.tick(HOT, now=11.0).outcome == "applied"  # 2 -> 3
    assert state["size"] == 3


def test_reversal_doubles_cooldown_and_renders_flap_damped():
    ctl, state = _ctl(confirm=1, cooldown_s=10.0)
    assert ctl.tick(HOT, now=0.0).direction == "up"      # 1 -> 2
    d = ctl.tick(COLD, now=11.0)                         # reversal: 2 -> 1
    assert d.direction == "down" and state["size"] == 1
    assert ctl.flap_factor == 2.0                        # damper armed
    # next reversal confirmed at t=22 — past cooldown_s but inside the
    # doubled window (11 + 10*2 = 31): renders flap_damped, moves nothing
    d = ctl.tick(HOT, now=22.0)
    assert d is not None and d.outcome == "flap_damped"
    assert (d.frm, d.to) == (1, 1) and state["size"] == 1
    # flap_damped emits once per streak episode, then stays quiet
    assert ctl.tick(HOT, now=23.0) is None
    # past the doubled window the decision goes through
    d = ctl.tick(HOT, now=40.0)
    assert d.outcome == "applied" and state["size"] == 2


def test_flap_factor_caps_and_calms():
    ctl, state = _ctl(confirm=1, cooldown_s=1.0, flap_cap=4.0)
    t = 0.0
    for sig in (HOT, COLD, HOT, COLD, HOT):              # ping-pong
        while ctl.tick(sig, now=t) is None or False:
            t += 1.0
        t += 100.0                                       # clear any damping
    assert ctl.flap_factor == 4.0                        # capped, not 16
    # two same-direction moves calm the damper back to 1
    ctl.tick(COLD, now=t)
    t += 100.0
    state["size"] = 3
    ctl.tick(COLD, now=t)
    assert ctl.flap_factor == 1.0


# ---------------------------------------------------------------------------
# brownout ordering: never shed capacity while the ladder sheds work
# ---------------------------------------------------------------------------

def test_scale_down_ordered_behind_brownout():
    ctl, state = _ctl(size=2, confirm=1, cooldown_s=0.0)
    # classifier would say "down" on these numbers if rung were 0, but a
    # custom classify_fn cannot smuggle a shed past an active ladder
    ctl._classify = lambda pol, sig: -1
    browned = dict(COLD, brownout_level=2)
    fired, _ = _drive(ctl, browned, 10)
    assert fired == [] and state["size"] == 2
    fired, _ = _drive(ctl, COLD, 1, t0=100.0)            # rung 0: sheds
    assert [d.direction for d in fired] == ["down"]


# ---------------------------------------------------------------------------
# dry-run plan -> held; apply failure -> held
# ---------------------------------------------------------------------------

def test_unrunnable_plan_renders_held():
    ctl, state = _ctl(size=2, confirm=1, plan_ok=False)
    d = ctl.tick(COLD, now=0.0)
    assert d.outcome == "held" and d.reason == "floor"
    assert (d.frm, d.to) == (2, 2) and state["size"] == 2
    assert "outcome=held" in d.line()
    # held arms the cooldown like any rendered decision
    assert ctl.tick(COLD, now=1.0) is None


def test_crashing_planner_renders_held_not_raise():
    def bad_plan(direction, frm, to):
        raise RuntimeError("boom")
    ctl = CapacityController(CapacityPolicy(confirm=1, max_size=3),
                             mode="auto", size_fn=lambda: 1,
                             plan_fn=bad_plan, apply_fn=lambda p: None)
    d = ctl.tick(HOT, now=0.0)
    assert d.outcome == "held" and "boom" in d.reason


def test_failing_apply_renders_held():
    def bad_apply(plan):
        raise RuntimeError("spawn refused")
    ctl = CapacityController(CapacityPolicy(confirm=1, max_size=3),
                             mode="auto", size_fn=lambda: 1,
                             plan_fn=lambda d, f, t: {"ok": True, "to": t},
                             apply_fn=bad_apply)
    d = ctl.tick(HOT, now=0.0)
    assert d.outcome == "held" and "spawn refused" in d.reason


# ---------------------------------------------------------------------------
# advise mode: the A/B control arm
# ---------------------------------------------------------------------------

def test_advise_logs_without_acting():
    ctl, state = _ctl(mode="advise", size=2, confirm=2)
    fired, t = _drive(ctl, HOT, 2)
    assert [d.outcome for d in fired] == ["advised"]
    assert state["size"] == 2 and state["applied"] == []
    # advise arms cooldown + flap state exactly like auto (A/B parity)
    assert ctl.tick(HOT, now=t) is None
    assert ctl.tick(COLD, now=t + 100.0) is None        # streak 1 of 2
    d = ctl.tick(COLD, now=t + 101.0)
    assert d is not None and d.outcome == "advised"
    assert ctl.flap_factor == 2.0                        # reversal tracked


# ---------------------------------------------------------------------------
# snapshot + fleet mining + runner
# ---------------------------------------------------------------------------

def test_snapshot_shape():
    ctl, _ = _ctl(confirm=1)
    ctl.tick(HOT, now=0.0)
    snap = ctl.snapshot()
    assert snap["mode"] == "auto" and snap["size"] == 2
    assert snap["min"] == 1 and snap["max"] == 3
    assert snap["decisions"]["applied"] == 1
    assert snap["last"]["direction"] == "up"
    assert snap["cooldown_factor"] == 1.0


def test_signals_from_fleet_mines_worst_burn():
    fleet = {"queue_depth": 7.0, "brownout_level": 2,
             "slo": {"burn_rate": {"interactive": {"short": 3.0,
                                                   "long": 0.1},
                                   "batch": {"short": 0.5}}}}
    sig = signals_from_fleet(fleet, size=2)
    assert sig == {"queue_depth": 7.0, "brownout_level": 2,
                   "burn_rate": 3.0, "size": 2}
    # missing blocks degrade to calm, not KeyError
    assert signals_from_fleet({}, size=1)["burn_rate"] == 0.0


def test_runner_emits_decision_lines():
    ctl, state = _ctl(confirm=1)
    lines = []
    runner = AutoscaleRunner(ctl, signals_fn=lambda: HOT,
                             interval_s=0.01, emit=lines.append)
    d = runner.tick_once()
    assert d is not None and state["size"] == 2
    assert lines and lines[0].startswith("autoscale_decision direction=up")
    # a crashing signals_fn is a skipped window, not a crash
    runner._signals_fn = lambda: (_ for _ in ()).throw(OSError("down"))
    assert runner.tick_once() is None
    with pytest.raises(ValueError):
        AutoscaleRunner(ctl, signals_fn=dict, interval_s=0.0)


# ---------------------------------------------------------------------------
# trace_report autoscale section (telemetry/report.py)
# ---------------------------------------------------------------------------

def test_report_autoscale_section():
    from pipeedge_tpu.telemetry import report
    t = 1_000_000
    mk = lambda name, t0, t1: {"cat": "autoscale", "name": name,  # noqa: E731
                               "rank": 0, "stage": None, "mb": None,
                               "t0": t0, "t1": t1}
    spans = [
        mk("plan:up", t, t + 2_000_000),
        mk("apply:up", t + 2_000_000, t + 9_000_000),
        mk("plan:down", t + 20, t + 25),
        mk("held:down", t + 25, t + 25),
        mk("flap_damped:down", t + 30, t + 30),
        {"cat": "compute", "name": "stage0", "rank": 0, "stage": 0,
         "mb": 0, "t0": t, "t1": t + 10_000_000},
    ]
    rec = report.analyze_spans(spans, span_cost_ns=100.0)
    a = rec["autoscale"]
    assert a["plans"] == 2 and a["applies"] == 1
    assert a["held"] == 1 and a["flap_damped"] == 1
    assert a["by_direction"]["up"] == {"apply": 1, "plan": 1}
    assert a["by_direction"]["down"] == {"flap_damped": 1, "held": 1,
                                         "plan": 1}
    assert a["apply_ms"]["n"] == 1 and a["apply_ms"]["max"] == 7.0


def test_report_no_autoscale_section_on_plain_trace():
    from pipeedge_tpu.telemetry import report
    t = 1_000_000
    spans = [{"cat": "compute", "name": "stage0", "rank": 0, "stage": 0,
              "mb": 0, "t0": t, "t1": t + 10}]
    rec = report.analyze_spans(spans, span_cost_ns=100.0)
    assert rec["autoscale"] == {}
