"""SPMD (shard_map + ppermute) pipeline tests on the 8-device CPU mesh.

This exercises the true multi-chip path: stage-sharded parameters, ppermute
inter-stage edges, masked uneven stages, dp x stage meshes, quantized edges.
"""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from pipeedge_tpu.models import ShardConfig  # noqa: E402
from pipeedge_tpu.models import bert as bert_mod  # noqa: E402
from pipeedge_tpu.models import gpt2 as gpt2_mod  # noqa: E402
from pipeedge_tpu.models import vit as vit_mod  # noqa: E402
from pipeedge_tpu.models.layers import TransformerConfig  # noqa: E402
from pipeedge_tpu.models.shard import make_shard_fn  # noqa: E402
from pipeedge_tpu.parallel import spmd  # noqa: E402

pytestmark = pytest.mark.slow  # every test compiles multi-stage shard_map programs

TINY4 = dict(hidden_size=32, num_hidden_layers=4, num_attention_heads=4,
             intermediate_size=64)


@pytest.fixture(scope="module")
def tiny_vit4():
    from transformers import ViTConfig, ViTForImageClassification
    hf_cfg = ViTConfig(**TINY4, image_size=16, patch_size=4, num_labels=5)
    torch.manual_seed(0)
    model = ViTForImageClassification(hf_cfg).eval()
    cfg = TransformerConfig(model_type="vit", **TINY4, num_labels=5,
                            image_size=16, patch_size=4)
    weights = vit_mod.hf_to_npz_weights(model.state_dict(), cfg)
    return cfg, weights


def _stage_params(family, cfg, partition, weights):
    total = 4 * cfg.num_hidden_layers
    out = []
    for l, r in partition:
        sc = ShardConfig(l, r, is_first=l == 1, is_last=r == total)
        out.append(family.load_params(cfg, sc, weights))
    return out


def _expected(family, cfg, weights, inputs):
    total = 4 * cfg.num_hidden_layers
    sc = ShardConfig(1, total, is_first=True, is_last=True)
    params = family.load_params(cfg, sc, weights)
    fn = make_shard_fn(family.FAMILY, cfg, sc)
    return np.stack([np.asarray(fn(params, u)) for u in inputs])


def test_partition_to_blocks_validates():
    assert spmd.partition_to_blocks([(1, 8), (9, 16)]) == [(0, 1), (2, 3)]
    with pytest.raises(ValueError):
        spmd.partition_to_blocks([(1, 6), (7, 16)])


@pytest.mark.parametrize("partition", [
    [(1, 4), (5, 8), (9, 12), (13, 16)],   # even 4-stage
    [(1, 8), (9, 12), (13, 16)],           # uneven: 2+1+1 blocks (masking)
    [(1, 16)],                             # single stage degenerate
])
def test_spmd_matches_single_shard(tiny_vit4, partition):
    cfg, weights = tiny_vit4
    mesh = spmd.make_pipeline_mesh(len(partition))
    pipe = spmd.build_spmd_pipeline(
        vit_mod.FAMILY, cfg, partition,
        _stage_params(vit_mod, cfg, partition, weights), mesh)
    rng = np.random.default_rng(0)
    inputs = jnp.asarray(rng.normal(size=(6, 2, 3, 16, 16)).astype(np.float32))
    got = np.asarray(pipe.run(inputs))
    expected = _expected(vit_mod, cfg, weights, inputs)
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-5)


def test_spmd_dp_stage_mesh(tiny_vit4):
    cfg, weights = tiny_vit4
    partition = [(1, 4), (5, 8), (9, 12), (13, 16)]
    mesh = spmd.make_pipeline_mesh(4, dp=2)
    assert mesh.shape == {"dp": 2, "stage": 4}
    pipe = spmd.build_spmd_pipeline(
        vit_mod.FAMILY, cfg, partition,
        _stage_params(vit_mod, cfg, partition, weights), mesh)
    rng = np.random.default_rng(1)
    inputs = jnp.asarray(rng.normal(size=(5, 4, 3, 16, 16)).astype(np.float32))
    got = np.asarray(pipe.run(inputs))
    expected = _expected(vit_mod, cfg, weights, inputs)
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-5)


def test_spmd_quantized_edges(tiny_vit4):
    cfg, weights = tiny_vit4
    partition = [(1, 8), (9, 16)]
    mesh = spmd.make_pipeline_mesh(2)
    sp = _stage_params(vit_mod, cfg, partition, weights)
    pipe = spmd.build_spmd_pipeline(vit_mod.FAMILY, cfg, partition, sp, mesh)
    rng = np.random.default_rng(2)
    inputs = jnp.asarray(rng.normal(size=(3, 2, 3, 16, 16)).astype(np.float32))
    exact = np.asarray(pipe.run(inputs))
    pipe.stage_bits = (8, 0)
    assert pipe.quant_bit == 8
    q8 = np.asarray(pipe.run(inputs))
    err = np.max(np.abs(q8 - exact))
    assert err < np.max(np.abs(exact)) * 0.5
    assert not np.allclose(q8, exact)  # quantization actually happened


def test_spmd_per_stage_quant_bits(tiny_vit4):
    """Mixed per-stage edge bitwidths (reference -q list semantics): the
    lax.switch wire codec must agree with the exact pipeline within the
    coarsest edge's quantization error, and differ from it (quantization
    really ran). Includes a raw (bit=0) edge mixed with quantized ones."""
    cfg, weights = tiny_vit4
    partition = [(1, 4), (5, 8), (9, 12), (13, 16)]
    mesh = spmd.make_pipeline_mesh(4)
    sp = _stage_params(vit_mod, cfg, partition, weights)
    exact_pipe = spmd.build_spmd_pipeline(vit_mod.FAMILY, cfg, partition, sp,
                                          mesh)
    rng = np.random.default_rng(5)
    inputs = jnp.asarray(rng.normal(size=(5, 2, 3, 16, 16)).astype(np.float32))
    exact = np.asarray(exact_pipe.run(inputs))

    pipe = spmd.build_spmd_pipeline(vit_mod.FAMILY, cfg, partition, sp, mesh,
                                    quant_bit=[8, 4, 0, 0])
    assert pipe.stage_bits == (8, 4, 0, 0)
    mixed = np.asarray(pipe.run(inputs))
    assert mixed.shape == exact.shape
    assert not np.allclose(mixed, exact)       # 4-bit edge really quantized
    # 16-level edge dominates the error; outputs stay in the same regime
    assert np.max(np.abs(mixed - exact)) < np.max(np.abs(exact))

    # high-precision mixed edges track the exact result closely
    pipe16 = spmd.build_spmd_pipeline(vit_mod.FAMILY, cfg, partition, sp,
                                      mesh, quant_bit=[16, 0, 16, 0])
    m16 = np.asarray(pipe16.run(inputs))
    np.testing.assert_allclose(m16, exact, rtol=0.05, atol=0.05)


def test_spmd_stage_ranks_mesh(tiny_vit4):
    """-r rank order: stages placed on the listed devices, same results."""
    cfg, weights = tiny_vit4
    partition = [(1, 8), (9, 16)]
    ranks = [3, 1]
    mesh = spmd.make_pipeline_mesh(2, stage_ranks=ranks)
    devs = list(mesh.devices.flat)
    assert devs == [jax.devices()[3], jax.devices()[1]]
    sp = _stage_params(vit_mod, cfg, partition, weights)
    pipe = spmd.build_spmd_pipeline(vit_mod.FAMILY, cfg, partition, sp, mesh)
    rng = np.random.default_rng(6)
    inputs = jnp.asarray(rng.normal(size=(4, 2, 3, 16, 16)).astype(np.float32))
    got = np.asarray(pipe.run(inputs))
    expected = _expected(vit_mod, cfg, weights, inputs)
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-5)
    with pytest.raises(ValueError):
        spmd.make_pipeline_mesh(2, stage_ranks=[1, 1])
    with pytest.raises(ValueError):
        spmd.make_pipeline_mesh(2, dp=2, stage_ranks=[0, 1])


def test_spmd_bert(tiny_vit4):
    from transformers import BertConfig, BertForSequenceClassification
    hf_cfg = BertConfig(**TINY4, vocab_size=100, max_position_embeddings=64,
                        num_labels=3)
    torch.manual_seed(3)
    model = BertForSequenceClassification(hf_cfg).eval()
    cfg = TransformerConfig(model_type="bert", **TINY4, num_labels=3,
                            vocab_size=100, max_position_embeddings=64)
    weights = {k: v.numpy() for k, v in model.state_dict().items()}
    partition = [(1, 8), (9, 16)]
    mesh = spmd.make_pipeline_mesh(2)
    pipe = spmd.build_spmd_pipeline(
        bert_mod.FAMILY, cfg, partition,
        _stage_params(bert_mod, cfg, partition, weights), mesh)
    ids = jnp.asarray(np.random.default_rng(4).integers(0, 100, size=(4, 2, 9)),
                      dtype=jnp.int32)
    got = np.asarray(pipe.run(ids))
    expected = _expected(bert_mod, cfg, weights, ids)
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-5)


def test_spmd_dp_stage_tp_mesh(tiny_vit4):
    """pp x dp x tp in ONE compiled program: blocks stage-sharded AND
    Megatron tp-sharded (two psums per block over 'tp'), batch dp-sharded,
    quantized ppermute stage edges — against the single-shard oracle."""
    cfg, weights = tiny_vit4
    partition = [(1, 8), (9, 16)]
    mesh = spmd.make_pipeline_mesh(2, dp=2, tp=2)
    assert mesh.shape == {"dp": 2, "stage": 2, "tp": 2}
    pipe = spmd.build_spmd_pipeline(
        vit_mod.FAMILY, cfg, partition,
        _stage_params(vit_mod, cfg, partition, weights), mesh, quant_bit=8)
    rng = np.random.default_rng(6)
    inputs = jnp.asarray(rng.normal(size=(4, 4, 3, 16, 16)).astype(np.float32))
    got = np.asarray(pipe.run(inputs))
    expected = _expected(vit_mod, cfg, weights, inputs)
    # 8-bit edge quantization dominates the tolerance
    np.testing.assert_allclose(got, expected, rtol=0.1, atol=0.05)
    pipe_raw = spmd.build_spmd_pipeline(
        vit_mod.FAMILY, cfg, partition,
        _stage_params(vit_mod, cfg, partition, weights), mesh)
    got_raw = np.asarray(pipe_raw.run(inputs))
    np.testing.assert_allclose(got_raw, expected, rtol=2e-4, atol=2e-5)


def test_spmd_bert_tp(tiny_vit4):
    from transformers import BertConfig, BertForSequenceClassification
    hf_cfg = BertConfig(**TINY4, vocab_size=100, max_position_embeddings=64,
                        num_labels=3)
    torch.manual_seed(3)
    model = BertForSequenceClassification(hf_cfg).eval()
    cfg = TransformerConfig(model_type="bert", **TINY4, num_labels=3,
                            vocab_size=100, max_position_embeddings=64)
    weights = {k: v.numpy() for k, v in model.state_dict().items()}
    partition = [(1, 8), (9, 16)]
    mesh = spmd.make_pipeline_mesh(2, dp=2, tp=2)
    pipe = spmd.build_spmd_pipeline(
        bert_mod.FAMILY, cfg, partition,
        _stage_params(bert_mod, cfg, partition, weights), mesh)
    ids = jnp.asarray(np.random.default_rng(4).integers(0, 100, size=(4, 2, 9)),
                      dtype=jnp.int32)
    got = np.asarray(pipe.run(ids))
    expected = _expected(bert_mod, cfg, weights, ids)
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-5)


def test_spmd_dp_stage_sp_mesh(tiny_vit4):
    """pp x dp x sp in ONE compiled program: activations sequence-sharded
    within each stage (stage edges carry only the local chunk), exact ring
    attention over 'sp' per block, last stage all-gathers for the pooler —
    against the single-shard oracle."""
    from transformers import BertConfig, BertForSequenceClassification
    hf_cfg = BertConfig(**TINY4, vocab_size=100, max_position_embeddings=64,
                        num_labels=3)
    torch.manual_seed(3)
    model = BertForSequenceClassification(hf_cfg).eval()
    cfg = TransformerConfig(model_type="bert", **TINY4, num_labels=3,
                            vocab_size=100, max_position_embeddings=64)
    weights = {k: v.numpy() for k, v in model.state_dict().items()}
    partition = [(1, 8), (9, 16)]
    mesh = spmd.make_pipeline_mesh(2, dp=2, sp=2)
    assert mesh.shape == {"dp": 2, "stage": 2, "sp": 2}
    pipe = spmd.build_spmd_pipeline(
        bert_mod.FAMILY, cfg, partition,
        _stage_params(bert_mod, cfg, partition, weights), mesh)
    # S=12 divides sp=2
    ids = jnp.asarray(
        np.random.default_rng(7).integers(0, 100, size=(4, 4, 12)),
        dtype=jnp.int32)
    got = np.asarray(pipe.run(ids))
    expected = _expected(bert_mod, cfg, weights, ids)
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-5)


def _tiny_gpt2():
    from transformers import GPT2Config, GPT2LMHeadModel
    hf_cfg = GPT2Config(n_embd=32, n_layer=4, n_head=4, n_inner=64,
                        vocab_size=100, n_positions=64)
    torch.manual_seed(5)
    model = GPT2LMHeadModel(hf_cfg).eval()
    cfg = TransformerConfig(model_type="gpt2", **TINY4, layer_norm_eps=1e-5,
                            vocab_size=100, max_position_embeddings=64)
    weights = {k: v.numpy() for k, v in model.state_dict().items()}
    return cfg, weights


def test_spmd_gpt2_sp_causal_ring():
    """Causal decoder through the pp x dp x sp program: sequence-sharded
    stages with CAUSAL ring attention (the long-context decode shape), last
    stage all-gathers for the full-sequence LM head — vs the single-shard
    oracle (itself HF-parity-tested in test_models.py)."""
    cfg, weights = _tiny_gpt2()
    partition = [(1, 8), (9, 16)]
    mesh = spmd.make_pipeline_mesh(2, dp=2, sp=2)
    pipe = spmd.build_spmd_pipeline(
        gpt2_mod.FAMILY, cfg, partition,
        _stage_params(gpt2_mod, cfg, partition, weights), mesh)
    ids = jnp.asarray(
        np.random.default_rng(11).integers(0, 100, size=(3, 4, 12)),
        dtype=jnp.int32)
    got = np.asarray(pipe.run(ids))
    expected = _expected(gpt2_mod, cfg, weights, ids)
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-4)


def test_spmd_gpt2_tp():
    """Causal decoder through pp x tp: Megatron-sharded blocks reuse the
    ViT spec table (same param names) with the causal+gelu_new body."""
    cfg, weights = _tiny_gpt2()
    partition = [(1, 8), (9, 16)]
    mesh = spmd.make_pipeline_mesh(2, dp=2, tp=2)
    pipe = spmd.build_spmd_pipeline(
        gpt2_mod.FAMILY, cfg, partition,
        _stage_params(gpt2_mod, cfg, partition, weights), mesh)
    ids = jnp.asarray(
        np.random.default_rng(12).integers(0, 100, size=(4, 2, 9)),
        dtype=jnp.int32)
    got = np.asarray(pipe.run(ids))
    expected = _expected(gpt2_mod, cfg, weights, ids)
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-4)


def test_spmd_sp_seq_divisibility_error(tiny_vit4):
    cfg, weights = tiny_vit4  # ViT: S = 17 tokens, indivisible by 2
    partition = [(1, 8), (9, 16)]
    mesh = spmd.make_pipeline_mesh(2, sp=2)
    pipe = spmd.build_spmd_pipeline(
        vit_mod.FAMILY, cfg, partition,
        _stage_params(vit_mod, cfg, partition, weights), mesh)
    inputs = jnp.asarray(np.zeros((3, 2, 3, 16, 16), np.float32))
    with pytest.raises(ValueError, match="sequence length 17"):
        pipe.run(inputs)


def test_spmd_sp_ulysses_matches_oracle():
    from transformers import BertConfig, BertForSequenceClassification
    hf_cfg = BertConfig(**TINY4, vocab_size=100, max_position_embeddings=64,
                        num_labels=3)
    torch.manual_seed(3)
    model = BertForSequenceClassification(hf_cfg).eval()
    cfg = TransformerConfig(model_type="bert", **TINY4, num_labels=3,
                            vocab_size=100, max_position_embeddings=64)
    weights = {k: v.numpy() for k, v in model.state_dict().items()}
    partition = [(1, 8), (9, 16)]
    mesh = spmd.make_pipeline_mesh(2, dp=2, sp=2)
    pipe = spmd.build_spmd_pipeline(
        bert_mod.FAMILY, cfg, partition,
        _stage_params(bert_mod, cfg, partition, weights), mesh,
        sp_kind="ulysses")
    ids = jnp.asarray(
        np.random.default_rng(9).integers(0, 100, size=(3, 4, 12)),
        dtype=jnp.int32)
    got = np.asarray(pipe.run(ids))
    expected = _expected(bert_mod, cfg, weights, ids)
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-5)
