"""Monitoring context + facade tests (the reference ships none — SURVEY.md §4)."""
import csv
import threading
import time

import pytest

import monitoring as monitoring_facade
from pipeedge_tpu.monitoring import MonitorContext
from pipeedge_tpu.utils.threads import RWLock, ThreadSafeCounter


def test_monitor_lifecycle_and_metrics(tmp_path):
    log = tmp_path / "shard.csv"
    with MonitorContext(key="shard", window_size=3, log_name=str(log)) as ctx:
        for i in range(7):
            ctx.iteration_start(key="shard")
            time.sleep(0.002)
            ctx.iteration(key="shard", work=8, accuracy=i)
        assert ctx.get_tag(key="shard") == 7
        assert ctx.get_global_work(key="shard") == 56
        assert ctx.get_window_work(key="shard") == 24          # last 3 beats
        assert ctx.get_instant_work(key="shard") == 8
        assert ctx.get_global_time_s(key="shard") >= 0.014
        assert ctx.get_instant_heartrate(key="shard") > 0
        assert ctx.get_global_perf(key="shard") > 0
        # no energy source -> zero energy/power, not an error
        assert ctx.get_global_energy_j(key="shard") == 0
        assert ctx.get_window_power_w(key="shard") == 0
        assert ctx.energy_source == "None"
    # CSV: header + 7 rows
    rows = list(csv.reader(open(log)))
    assert rows[0][0] == "Tag"
    assert len(rows) == 8


def test_monitor_multiple_keys(tmp_path):
    ctx = MonitorContext(key="a", window_size=2, log_name=None)
    ctx.add_heartbeat(key="b", log_name=str(tmp_path / "b.csv"))
    with ctx:
        ctx.iteration_start(key="b")
        ctx.iteration(key="b", work=3)
        assert ctx.get_global_work(key="b") == 3
        assert ctx.get_global_work(key="a") == 0
    with pytest.raises(ValueError):
        ctx.add_heartbeat(key="b")


def test_monitor_not_open_raises():
    ctx = MonitorContext(key="x")
    with pytest.raises(RuntimeError):
        ctx.iteration_start(key="x")


def test_monitor_pickle_blocked():
    import pickle
    with pytest.raises(TypeError):
        pickle.dumps(MonitorContext(key="x"))


def test_facade_lifecycle(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    monitoring_facade.init("shard", 2, work_type="items", acc_type="layers")
    monitoring_facade.add_key("send", work_type="Mbits")
    monitoring_facade.iteration_start("shard")
    monitoring_facade.iteration("shard", work=4, accuracy=12)
    monitoring_facade.iteration_start("send")
    monitoring_facade.iteration("send", work=1.5)
    with monitoring_facade.get_locked_context("send") as mctx:
        assert mctx.get_tag(key="send") == 1
        assert mctx.get_window_work(key="send") == 1.5
    monitoring_facade.finish()
    assert (tmp_path / "shard.csv").exists()
    assert (tmp_path / "send.csv").exists()
    # after finish: no-ops, no errors
    monitoring_facade.iteration_start("shard")
    monitoring_facade.iteration("shard")


def test_facade_unbalanced_iteration_raises(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    monitoring_facade.init("k", 2)
    try:
        with pytest.raises(KeyError):
            monitoring_facade.iteration("k", work=1)  # no start
        monitoring_facade.iteration("k", work=1, safe=False)  # tolerated
    finally:
        monitoring_facade.finish()


def test_facade_threads_same_key(tmp_path, monkeypatch):
    """Concurrent threads measuring the same key (reference monitoring.py:1-8)."""
    monkeypatch.chdir(tmp_path)
    monitoring_facade.init("k", 4)
    errors = []

    def worker():
        try:
            for _ in range(5):
                monitoring_facade.iteration_start("k")
                monitoring_facade.iteration("k", work=1)
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    with monitoring_facade.get_locked_context("k") as mctx:
        assert mctx.get_tag(key="k") == 20
        assert mctx.get_global_work(key="k") == 20
    monitoring_facade.finish()
    assert not errors


def test_rwlock_and_counter():
    lock = RWLock()
    with lock.lock_read():
        with lock.lock_read():
            pass  # concurrent readers fine
    with lock.lock_write():
        pass
    counter = ThreadSafeCounter()
    t = threading.Thread(target=lambda: (time.sleep(0.01), counter.add(5)))
    t.start()
    assert counter.wait_gte(5, timeout=2)
    t.join()
    assert counter.value == 5


def test_monitor_rows_on_disk_before_close(tmp_path):
    """Crash posture: every CSV row is flushed as it is written (and
    `flush()` is explicit), so a rank that dies mid-run leaves a complete
    post-mortem log — no buffered tail to lose."""
    log = tmp_path / "k.csv"
    ctx = MonitorContext(key="k", window_size=2, log_name=str(log))
    with ctx:
        for _ in range(3):
            ctx.iteration_start(key="k")
            ctx.iteration(key="k", work=1)
        ctx.flush()
        # read WHILE the context is still open: rows must already be there
        rows = list(csv.reader(open(log)))
        assert len(rows) == 4          # header + 3 beats


def test_facade_flush_safe_anytime(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    monitoring_facade.flush()          # no session: no-op
    monitoring_facade.init("k", 2)
    monitoring_facade.iteration_start("k")
    monitoring_facade.iteration("k", work=1)
    monitoring_facade.flush()
    rows = list(csv.reader(open(tmp_path / "k.csv")))
    assert len(rows) == 2
    monitoring_facade.finish()
    monitoring_facade.flush()          # after finish: no-op


def test_snapshot_matrix_all_keys(tmp_path, monkeypatch):
    """`snapshot()` returns the whole (instant|window|global) getter matrix
    for every key in ONE dict, matching the individual getters — the
    single synchronized read the telemetry/metrics export consumes."""
    monkeypatch.chdir(tmp_path)
    log = tmp_path / "a.csv"
    with MonitorContext(key="a", window_size=2, log_name=str(log)) as ctx:
        ctx.add_heartbeat(key="b", log_name=None)
        for i in range(5):
            ctx.iteration_start(key="a")
            time.sleep(0.001)
            ctx.iteration(key="a", work=3, accuracy=i)
        ctx.iteration_start(key="b")
        ctx.iteration(key="b", work=7)
        snap = ctx.snapshot()
        assert set(snap) == {"a", "b"}
        for key in ("a", "b"):
            assert set(snap[key]) == {"instant", "window", "global",
                                      "tag", "window_size"}
            for scope in ("instant", "window", "global"):
                assert set(snap[key][scope]) == {
                    "time_s", "heartrate", "work", "perf", "energy_j",
                    "power_w", "accuracy", "accuracy_rate"}
        # values agree with the per-key getter matrix
        assert snap["a"]["global"]["work"] == ctx.get_global_work(key="a")
        assert snap["a"]["window"]["work"] == ctx.get_window_work(key="a")
        assert snap["a"]["instant"]["work"] == 3
        assert snap["a"]["global"]["perf"] == ctx.get_global_perf(key="a")
        assert snap["a"]["tag"] == 5 and snap["a"]["window_size"] == 2
        assert snap["b"]["global"]["work"] == 7


def test_facade_snapshot(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    assert monitoring_facade.snapshot() == {}      # no session
    monitoring_facade.init("k", 2)
    try:
        monitoring_facade.add_key("j", work_type="Mbits")
        monitoring_facade.iteration_start("k")
        monitoring_facade.iteration("k", work=4)
        snap = monitoring_facade.snapshot()
        assert set(snap) == {"k", "j"}
        assert snap["k"]["global"]["work"] == 4
        assert snap["j"]["tag"] == 0
    finally:
        monitoring_facade.finish()
    assert monitoring_facade.snapshot() == {}      # after finish
