"""Offline int8 calibration (utils/calibrate.py + tools/calibrate.py):
stat accumulation, Banner alpha derivation from the clamp lineage, the
sidecar write/load round-trip, and the one-call shard sweep against the
tiny ViT fixture."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from pipeedge_tpu.models import layers  # noqa: E402
from pipeedge_tpu.ops.clamp import (clamp_factor_gelu,  # noqa: E402
                                    clamp_factor_laplace)
from pipeedge_tpu.utils import calibrate  # noqa: E402

MODEL = "pipeedge/test-tiny-vit"


def test_tag_stats_accumulate_across_batches():
    st = calibrate.TagStats()
    a = np.array([1.0, -3.0, 2.0], np.float32)
    b = np.array([0.5, -0.5], np.float32)
    st.update(a)
    st.update(b)
    both = np.concatenate([a, b])
    assert st.amax == 3.0
    assert st.count == 5
    assert st.second_moment == pytest.approx(np.mean(both**2), rel=1e-6)
    assert st.var == pytest.approx(np.var(both), rel=1e-6)


def test_compute_alphas_distribution_split_and_floor():
    laplace = calibrate.TagStats()
    gelu = calibrate.TagStats()
    rng = np.random.default_rng(0)
    laplace.update(rng.laplace(size=4096).astype(np.float32))
    gelu.update(np.abs(rng.normal(size=4096)).astype(np.float32))
    alphas = calibrate.compute_alphas(
        {"attn.q": laplace, "mlp.down": gelu}, bit=8)
    exp_lap = clamp_factor_laplace(8) * np.sqrt(0.5 * laplace.var)
    exp_gelu = clamp_factor_gelu(8) * np.sqrt(gelu.second_moment)
    assert alphas["attn.q"] == pytest.approx(
        max(exp_lap, 0.5 * laplace.amax), rel=1e-6)
    assert alphas["mlp.down"] == pytest.approx(
        max(exp_gelu, 0.5 * gelu.amax), rel=1e-6)
    # outlier-robust floor: a degenerate spike can't produce a clip far
    # below the observed range
    spiky = calibrate.TagStats()
    spiky.update(np.array([0.01] * 1000 + [10.0], np.float32))
    a = calibrate.compute_alphas({"attn.q": spiky})["attn.q"]
    assert a == pytest.approx(5.0)                 # 0.5 * amax
    # nothing observed -> neutral 1.0
    assert calibrate.compute_alphas({"t": calibrate.TagStats()})["t"] == 1.0


def test_collect_stats_requires_eager_and_tags():
    with pytest.raises(RuntimeError, match="no tagged denses"):
        calibrate.collect_activation_stats(
            lambda p, b: b, None, [np.zeros(3, np.float32)])

    def jitted_run(p, b):
        import jax.numpy as jnp
        return jax.jit(lambda x: layers.dense(
            {"w": jnp.eye(4), "b": jnp.zeros(4)}, x, tag="attn.q"))(b)

    with pytest.raises(RuntimeError, match="tracer"):
        calibrate.collect_activation_stats(
            jitted_run, None, [np.zeros((2, 4), np.float32)])
    assert layers._QC_OBSERVER is None             # restored on the way out


def test_sidecar_round_trip_and_config(tmp_path):
    path = str(tmp_path / "m.npz.int8scales.npz")
    alphas = {"attn.q": 1.25, "mlp.down": 0.5}
    wscales = {"blocks/0/attn_q": np.array([0.1, 0.2], np.float32)}
    calibrate.write_sidecar(path, alphas, wscales,
                            meta={"model": MODEL, "bit": 8})
    side = calibrate.load_sidecar(path)
    assert side["alphas"] == pytest.approx(alphas)
    np.testing.assert_array_equal(side["weight_scales"]["blocks/0/attn_q"],
                                  wscales["blocks/0/attn_q"])
    assert side["meta"] == {"model": MODEL, "bit": 8}

    qc = calibrate.quantize_compute_from_sidecar(
        path, skip_tags=("attn.out",), block_k=64, tunnel=True)
    assert qc.enabled and qc.tunnel and qc.block_k == 64
    assert qc.skip_tags == frozenset({"attn.out"})
    assert qc.clamp_alphas == pytest.approx(alphas)
    assert calibrate.sidecar_path("/x/m.npz") == "/x/m.npz.int8scales.npz"


def test_calibrate_shard_sweeps_fixture():
    from pipeedge_tpu.models import registry
    cfg = registry.get_model_config(MODEL)
    rng = np.random.default_rng(0)
    batches = [np.asarray(rng.normal(size=(
        4, cfg.num_channels, cfg.image_size, cfg.image_size)), np.float32)
        for _ in range(2)]
    alphas, wscales, stats = calibrate.calibrate_shard(
        MODEL, None, 1, registry.get_model_layers(MODEL), batches)
    assert set(alphas) == {"attn.q", "attn.k", "attn.v", "attn.out",
                           "mlp.up", "mlp.down"}
    assert all(a > 0 for a in alphas.values())
    # per-channel scales for every 2-D dense in the shard, incl. head
    assert wscales and all(v.ndim == 1 for v in wscales.values())
    assert all(st.count > 0 for st in stats.values())


@pytest.mark.fleet      # subprocess CLI run
def test_calibrate_cli_emits_sidecar_and_json(tmp_path):
    out = str(tmp_path / "tiny.int8scales.npz")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "calibrate.py"),
         "-m", MODEL, "--batch", "4", "--batches", "1", "--out", out],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env)
    assert proc.returncode == 0, proc.stderr[-3000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["bench"] == "calibrate" and rec["sidecar"] == out
    assert rec["alphas"] and rec["weight_scale_tensors"] > 0
    qc = calibrate.quantize_compute_from_sidecar(out)
    assert qc.enabled and set(qc.clamp_alphas) == set(rec["alphas"])
