"""The decode-fleet router's decision logic (pipeedge_tpu/serving/
router.py): registry lifecycle + hysteresis, prefix-affinity scoring,
retry/backoff/hedge decisions, stream failover, and the drain state
machine — all through injected I/O, no sockets (ISSUE 17's unit
matrix; the process-level acceptance lives in test_router_fleet.py).
"""
import threading
import time

import pytest

from pipeedge_tpu.serving import router as router_mod
from pipeedge_tpu.serving.router import (DecodeRouter, REPLICA_DEAD,
                                         REPLICA_DRAINED, REPLICA_HEALTHY,
                                         REPLICA_SUSPECT, ReplicaRegistry,
                                         RouterPolicy)


def _policy(**kw):
    kw.setdefault("backoff_s", 0.001)
    kw.setdefault("backoff_max_s", 0.002)
    return RouterPolicy(**kw)


def _registry(n=2, **kw):
    reg = ReplicaRegistry(_policy(**kw))
    for i in range(n):
        reg.add(f"r{i}", f"http://test:{9000 + i}")
    return reg


# ---------------------------------------------------------------------------
# policy validation
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("bad", [
    dict(alpha=0.0), dict(alpha=1.5),
    dict(suspect_threshold=0.2, readmit_threshold=0.4),
    dict(readmit_threshold=0.0),
    dict(readmit=0), dict(fail_dead=0),
    dict(route_retries=-1), dict(latency_bad_s=0),
    dict(poll_interval_s=0), dict(hedge_ms=-1),
])
def test_policy_rejects_nonsense(bad):
    with pytest.raises(ValueError):
        RouterPolicy(**bad)


# ---------------------------------------------------------------------------
# registry lifecycle + hysteresis
# ---------------------------------------------------------------------------

def test_healthy_to_suspect_on_degradation():
    reg = _registry()
    assert reg.state_of("r0") == REPLICA_HEALTHY
    # slow-but-answering polls walk the EWMA up past suspect_threshold
    for _ in range(3):
        reg.observe("r0", ok=True, latency_s=10.0)   # d = 1.0 (clamped)
    assert reg.state_of("r0") == REPLICA_SUSPECT
    assert reg.score_of("r0") > reg.policy.suspect_threshold


def test_hysteresis_band_changes_nothing():
    """A score oscillating BETWEEN readmit and suspect thresholds must
    not flap the state in either direction."""
    pol = _policy(alpha=1.0, suspect_threshold=0.6, readmit_threshold=0.2)
    reg = ReplicaRegistry(pol)
    reg.add("r0", "u")
    reg.observe("r0", ok=True, latency_s=0.7)     # score 0.7 -> suspect
    assert reg.state_of("r0") == REPLICA_SUSPECT
    for _ in range(10):
        reg.observe("r0", ok=True, latency_s=0.4)  # in the band
    assert reg.state_of("r0") == REPLICA_SUSPECT   # no readmit
    reg2 = ReplicaRegistry(pol)
    reg2.add("r0", "u")
    for _ in range(10):
        reg2.observe("r0", ok=True, latency_s=0.4)  # band from healthy
    assert reg2.state_of("r0") == REPLICA_HEALTHY   # no demotion


def test_readmit_needs_consecutive_clean_polls():
    reg = _registry(readmit=3)
    for _ in range(3):
        reg.observe("r0", ok=False)
    assert reg.state_of("r0") == REPLICA_DEAD
    # two clean, one dirty, resets the confirmation streak
    reg.observe("r0", ok=True, latency_s=0.0)
    reg.observe("r0", ok=True, latency_s=0.0)
    reg.observe("r0", ok=False)
    for _ in range(4):      # drive the score back below readmit
        reg.observe("r0", ok=True, latency_s=0.0)
        if reg.score_of("r0") <= reg.policy.readmit_threshold:
            break
    assert reg.state_of("r0") == REPLICA_DEAD     # streak was reset
    reg.observe("r0", ok=True, latency_s=0.0)
    reg.observe("r0", ok=True, latency_s=0.0)
    reg.observe("r0", ok=True, latency_s=0.0)
    assert reg.state_of("r0") == REPLICA_HEALTHY


def test_fail_dead_convicts_without_ewma_wait():
    """`fail_dead` consecutive poll failures kill the replica outright
    even though EWMA smoothing would take longer to cross any band."""
    reg = _registry(alpha=0.01, fail_dead=3)   # glacial EWMA
    reg.observe("r0", ok=False)
    reg.observe("r0", ok=False)
    assert reg.state_of("r0") == REPLICA_HEALTHY
    reg.observe("r0", ok=False)
    assert reg.state_of("r0") == REPLICA_DEAD


def test_mark_failed_is_instant_conviction():
    reg = _registry()
    reg.mark_failed("r1")
    assert reg.state_of("r1") == REPLICA_DEAD
    assert reg.score_of("r1") == 1.0


def test_drain_state_machine():
    reg = _registry()
    assert reg.drain("r0") is True
    assert reg.state_of("r0") == REPLICA_DRAINED
    # health polls never exit drained — it is administrative
    for _ in range(10):
        reg.observe("r0", ok=True, latency_s=0.0)
    assert reg.state_of("r0") == REPLICA_DRAINED
    # undrain re-proves through suspect, not straight to healthy
    reg.undrain("r0")
    assert reg.state_of("r0") == REPLICA_SUSPECT
    reg.observe("r0", ok=True, latency_s=0.0)
    reg.observe("r0", ok=True, latency_s=0.0)
    assert reg.state_of("r0") == REPLICA_HEALTHY
    # drain on a dead replica refuses: nothing graceful left
    reg.mark_failed("r1")
    assert reg.drain("r1") is False


def test_dead_drained_replicas_not_picked():
    reg = _registry(3)
    reg.mark_failed("r0")
    assert reg.drain("r1")
    for _ in range(20):
        assert reg.pick() == "r2"
    reg.mark_failed("r2")
    assert reg.pick() is None


def test_suspect_only_pool_still_routes():
    """Degraded-but-alive beats shedding: with no healthy replica,
    suspects take traffic."""
    reg = _registry(2)
    for _ in range(3):
        reg.observe("r0", ok=True, latency_s=10.0)
        reg.observe("r1", ok=True, latency_s=10.0)
    assert reg.state_of("r0") == REPLICA_SUSPECT
    assert reg.pick() in ("r0", "r1")


# ---------------------------------------------------------------------------
# prefix affinity + load
# ---------------------------------------------------------------------------

def test_affinity_sticks_and_follows_tokens():
    reg = _registry(2)
    toks = list(range(40))
    first = reg.pick(toks)
    # make the first choice the BUSIER one; affinity must still win
    reg.note_route(first)
    reg.note_route(first)
    for _ in range(5):
        assert reg.pick(toks) == first
    # a different prompt goes to the less-loaded replica
    other = reg.pick(list(range(100, 140)))
    assert other != first


def test_affinity_key_is_leading_tokens_only():
    reg = _registry(2, affinity_tokens=4)
    a = reg.pick([1, 2, 3, 4, 5, 6])
    assert reg.pick([1, 2, 3, 4, 99, 98]) == a     # same leading 4
    assert reg.affinity_owner([1, 2, 3, 4]) == a


def test_affinity_owner_dead_falls_back_and_relearns():
    reg = _registry(2)
    toks = list(range(8))
    owner = reg.pick(toks)
    reg.mark_failed(owner)
    survivor = reg.pick(toks)
    assert survivor != owner
    assert reg.affinity_owner(toks) == survivor    # relearned


def test_reassign_affinity_moves_all_keys():
    reg = _registry(2)
    owner = reg.pick([1, 2, 3])
    reg.pick([4, 5, 6])
    keys_before = reg.affinity_keys_of(owner)
    assert keys_before
    other = [n for n in reg.names() if n != owner][0]
    moved = reg.reassign_affinity(owner, other)
    assert moved == len(keys_before)
    assert reg.affinity_keys_of(owner) == []
    for k in keys_before:
        assert reg.affinity_owner(list(k)) == other


def test_affinity_lru_bounded():
    reg = _registry(2, affinity_capacity=4)
    for i in range(10):
        reg.pick([i, i + 1, i + 2])
    total = sum(len(reg.affinity_keys_of(n)) for n in reg.names())
    assert total <= 4


def test_least_loaded_pick_tracks_inflight():
    reg = _registry(3)
    reg.note_route("r0")
    reg.note_route("r0")
    reg.note_route("r1")
    assert reg.pick() == "r2"
    reg.done("r1")
    reg.note_route("r2")
    reg.note_route("r2")
    assert reg.pick() == "r1"


# ---------------------------------------------------------------------------
# dispatch: retry / backoff / shed / hedge (injected post_fn)
# ---------------------------------------------------------------------------

def _router(n=2, policy=None, post=None, get=None):
    replicas = {f"r{i}": f"http://test:{9000 + i}" for i in range(n)}
    return DecodeRouter(replicas, policy=policy or _policy(),
                        post_fn=post, get_fn=get)


def test_dispatch_routes_and_returns_body():
    calls = []

    def post(url, path, payload, timeout):
        calls.append((url, path))
        return 200, {"ids": [[1, 2]], "rid": "q0"}, []

    rt = _router(post=post)
    status, body, _ = rt.dispatch({"ids": [1, 2], "new_tokens": 2})
    assert status == 200 and body["ids"] == [[1, 2]]
    assert calls and calls[0][1] == "/generate"


def test_dispatch_fails_over_on_connect_error():
    attempts = []

    def post(url, path, payload, timeout):
        attempts.append(url)
        if len(attempts) == 1:
            raise OSError("connection refused")
        return 200, {"ids": [[7]]}, []

    rt = _router(post=post)
    before = router_mod._M_FAILOVERS.value()
    status, body, _ = rt.dispatch({"ids": [7], "new_tokens": 1})
    assert status == 200
    assert len(attempts) == 2 and attempts[0] != attempts[1]
    assert router_mod._M_FAILOVERS.value() == before + 1
    # the failed replica was convicted immediately
    dead = [n for n in rt.registry.names()
            if rt.registry.state_of(n) == REPLICA_DEAD]
    assert len(dead) == 1


def test_dispatch_retries_exhausted_503_with_retry_after():
    def post(url, path, payload, timeout):
        raise OSError("down")

    rt = _router(policy=_policy(route_retries=1), post=post)
    status, body, headers = rt.dispatch({"ids": [1], "new_tokens": 1})
    assert status == 503
    assert "error" in body
    assert ("Retry-After", "1") in headers      # PL403


def test_dispatch_no_replica_is_503():
    rt = _router(post=lambda *a: (200, {}, []))
    for n in rt.registry.names():
        rt.registry.mark_failed(n)
    status, body, headers = rt.dispatch({"ids": [1], "new_tokens": 1})
    assert status == 503 and body.get("no_replica")
    assert any(h == "Retry-After" for h, _ in headers)


def test_dispatch_shed_retries_on_other_replica():
    """A 503 from one replica spends a retry on a different one before
    surfacing — shed here does not mean shed everywhere."""
    shed_urls = []

    def post(url, path, payload, timeout):
        if not shed_urls:
            shed_urls.append(url)
            return 503, {"error": "shed"}, [("Retry-After", "2")]
        return 200, {"ids": [[5]]}, []

    rt = _router(post=post)
    status, body, _ = rt.dispatch({"ids": [5], "new_tokens": 1})
    assert status == 200
    # neither replica was convicted — a shed is an answer, not a fault
    assert all(rt.registry.state_of(n) != REPLICA_DEAD
               for n in rt.registry.names())


def test_dispatch_unanimous_shed_passes_through_retry_after():
    def post(url, path, payload, timeout):
        return 503, {"error": "shed"}, [("Retry-After", "7")]

    rt = _router(post=post)
    status, body, headers = rt.dispatch({"ids": [5], "new_tokens": 1})
    assert status == 503
    assert ("Retry-After", "7") in headers


def test_hedge_fires_on_slow_primary():
    """The primary stalls past hedge_ms; the hedge branch answers and
    wins. Both answers are identical (deterministic decode), so either
    winning is correct — the test pins that ONE answer returns fast."""
    release = threading.Event()

    def post(url, path, payload, timeout):
        if url.endswith("9000"):     # primary pick is least-loaded = r0
            release.wait(5.0)
            return 200, {"ids": [[3]], "who": "slow"}, []
        return 200, {"ids": [[3]], "who": "fast"}, []

    rt = _router(policy=_policy(hedge_ms=30.0), post=post)
    before = router_mod._M_HEDGES.value(winner="hedge")
    t0 = time.monotonic()
    status, body, _ = rt.dispatch({"ids": [3], "new_tokens": 1,
                                   "class": "interactive"})
    took = time.monotonic() - t0
    release.set()
    assert status == 200 and body["who"] == "fast"
    assert took < 2.0
    assert router_mod._M_HEDGES.value(winner="hedge") == before + 1


def test_hedge_skipped_for_batch_class():
    """Hedging doubles work — it is an interactive-tail tool only."""
    urls = []

    def post(url, path, payload, timeout):
        urls.append(url)
        time.sleep(0.05)             # well past hedge_ms
        return 200, {"ids": [[3]]}, []

    rt = _router(policy=_policy(hedge_ms=1.0), post=post)
    status, _, _ = rt.dispatch({"ids": [3], "new_tokens": 1,
                                "class": "batch"})
    assert status == 200
    assert len(urls) == 1


# ---------------------------------------------------------------------------
# prefix registration through the router
# ---------------------------------------------------------------------------

def test_prefix_registered_lazily_per_replica():
    registrations = []

    def post(url, path, payload, timeout):
        if path == "/prefix":
            registrations.append(url)
            return 200, {"prefix_id": f"p-{len(registrations)}",
                         "len": len(payload["ids"])}, []
        return 200, {"ids": [[9]], "seen_prefix": payload.get(
            "prefix_id")}, []

    rt = _router(post=post)
    pid, plen = rt.register_prefix([1, 2, 3, 4])
    assert plen == 4
    status, body, _ = rt.dispatch({"prefix_id": pid, "ids": [9],
                                   "new_tokens": 1})
    assert status == 200
    # the replica saw ITS prefix id, not the router-level one
    assert body["seen_prefix"] == "p-1"
    assert len(registrations) == 1
    # second use: already registered there, no second /prefix call
    rt.dispatch({"prefix_id": pid, "ids": [9], "new_tokens": 1})
    assert len(registrations) == 1


def test_prefix_reregisters_on_failover_target():
    state = {"fail_next_generate": True}
    registrations = []

    def post(url, path, payload, timeout):
        if path == "/prefix":
            registrations.append(url)
            return 200, {"prefix_id": f"p@{url}", "len": 2}, []
        if state.pop("fail_next_generate", False):
            raise OSError("replica died")
        return 200, {"ids": [[1]]}, []

    rt = _router(post=post)
    pid, _ = rt.register_prefix([1, 2])
    status, _, _ = rt.dispatch({"prefix_id": pid, "ids": [9],
                                "new_tokens": 1})
    assert status == 200
    # registered on the first pick AND again on the failover target
    assert len(set(registrations)) == 2


# ---------------------------------------------------------------------------
# stream failover (injected _stream_from)
# ---------------------------------------------------------------------------

class _ScriptedStreamRouter(DecodeRouter):
    """DecodeRouter whose per-replica stream is a scripted item list:
    each call pops the next script — exactly how a replica looks to
    stream() (the real _stream_from yields the same shapes)."""

    def __init__(self, scripts, **kw):
        replicas = {f"r{i}": f"http://test:{9000 + i}" for i in range(2)}
        super().__init__(replicas, **kw)
        self.scripts = list(scripts)
        self.streamed_to = []
        self.stream_rids = []

    def _stream_from(self, name, payload, rid=None, hop=0):
        self.streamed_to.append(name)
        self.stream_rids.append(rid)
        yield from self.scripts.pop(0)


def _collect(gen):
    items = list(gen)
    status = next(i for i in items if i[0] == "status")
    lines = [i[1] for i in items if i[0] == "line"]
    return status, lines


def test_stream_passthrough_success():
    rt = _ScriptedStreamRouter([[
        ("ok", None),
        ("line", {"step": 0, "tokens": [4]}),
        ("line", {"step": 1, "tokens": [5]}),
        ("line", {"ids": [[4, 5]], "steps": 2}),
    ]], policy=_policy())
    (_, code, _), lines = _collect(rt.stream({"ids": [1],
                                              "new_tokens": 2}))
    assert code == 200
    assert [l.get("step") for l in lines[:-1]] == [0, 1]
    assert lines[-1]["ids"] == [[4, 5]]


def test_stream_midstream_death_fails_over_and_suppresses_replay():
    """Replica dies after step 1; the survivor replays from step 0 and
    the client must see each step exactly once, then the terminal."""
    rt = _ScriptedStreamRouter([
        [("ok", None),
         ("line", {"step": 0, "tokens": [4]}),
         ("line", {"step": 1, "tokens": [5]})],    # then truncates
        [("ok", None),
         ("line", {"step": 0, "tokens": [4]}),     # replay, suppressed
         ("line", {"step": 1, "tokens": [5]}),     # replay, suppressed
         ("line", {"step": 2, "tokens": [6]}),
         ("line", {"ids": [[4, 5, 6]], "steps": 3})],
    ], policy=_policy())
    before = router_mod._M_FAILOVERS.value()
    (_, code, _), lines = _collect(rt.stream({"ids": [1],
                                              "new_tokens": 3}))
    assert code == 200
    assert [l["step"] for l in lines if "step" in l] == [0, 1, 2]
    assert lines[-1]["ids"] == [[4, 5, 6]]
    assert len(rt.streamed_to) == 2
    assert rt.streamed_to[0] != rt.streamed_to[1]
    assert router_mod._M_FAILOVERS.value() == before + 1
    # the dead replica was convicted
    assert rt.registry.state_of(rt.streamed_to[0]) == REPLICA_DEAD


def test_stream_error_line_fails_over_without_conviction():
    """A terminal {"error"} line means the replica's executor died
    under the request but the process answered — failover, yes;
    transport conviction, no (its health polls decide)."""
    rt = _ScriptedStreamRouter([
        [("ok", None),
         ("line", {"step": 0, "tokens": [4]}),
         ("line", {"error": "executor died", "rid": "q1"})],
        [("ok", None),
         ("line", {"step": 0, "tokens": [4]}),
         ("line", {"ids": [[4]], "steps": 1})],
    ], policy=_policy())
    (_, code, _), lines = _collect(rt.stream({"ids": [1],
                                              "new_tokens": 1}))
    assert code == 200
    assert lines[-1]["ids"] == [[4]]
    assert [l["step"] for l in lines if "step" in l] == [0]
    assert rt.registry.state_of(rt.streamed_to[0]) != REPLICA_DEAD


def test_stream_shed_retries_then_serves():
    rt = _ScriptedStreamRouter([
        [("refusal", (503, [("Retry-After", "3")], {"error": "shed"}))],
        [("ok", None),
         ("line", {"step": 0, "tokens": [4]}),
         ("line", {"ids": [[4]], "steps": 1})],
    ], policy=_policy())
    (_, code, _), lines = _collect(rt.stream({"ids": [1],
                                              "new_tokens": 1}))
    assert code == 200
    assert lines[-1]["ids"] == [[4]]
    assert len(rt.streamed_to) == 2


def test_stream_retries_exhausted_surfaces_error_line():
    rt = _ScriptedStreamRouter([
        [("ok", None), ("line", {"step": 0, "tokens": [4]})],
        [("ok", None), ("line", {"step": 1, "tokens": [5]})],
    ], policy=_policy(route_retries=1))
    (_, code, _), lines = _collect(rt.stream({"ids": [1],
                                              "new_tokens": 3}))
    assert code == 200           # headers had already committed
    assert "error" in lines[-1]


# ---------------------------------------------------------------------------
# drain orchestration (injected post/get)
# ---------------------------------------------------------------------------

def _drain_fns(active_polls, exported_pages=2):
    """Fake replica pair: the draining one answers /drain, reports
    `active_polls` then 0, and exports a prefix blob; the survivor
    accepts the import."""
    log = []

    def post(url, path, payload, timeout):
        log.append((url, path))
        if path == "/prefix":
            return 200, {"prefix_id": f"p@{url}",
                         "len": len(payload["ids"])}, []
        if path == "/drain":
            return 200, {"draining": True, "active": 1}, []
        if path == "/kv/export":
            return 200, {"blob": "AAAA", "tokens_covered": 8,
                         "pages": exported_pages}, []
        if path == "/kv/import":
            return 200, {"installed_pages": exported_pages}, []
        return 200, {"ids": [[1]]}, []

    def get(url, path, timeout):
        n = active_polls.pop(0) if active_polls else 0
        return 200, {"ok": True, "stats": {"active": n}}

    return post, get, log


def test_drain_waits_for_inflight_then_migrates():
    post, get, log = _drain_fns(active_polls=[2, 1])
    rt = _router(post=post, get=get)
    # give the drained replica an affinity key: its pages are warm
    victim = rt.registry.pick([1, 2, 3, 4])
    before = router_mod._M_MIGRATED.value()
    out = rt.drain_replica(victim)
    assert out["drained"] is True
    assert out["migrated_prefixes"] == 1
    assert out["target"] != victim
    assert router_mod._M_MIGRATED.value() == before + 1
    paths = [p for _, p in log]
    assert paths.index("/drain") < paths.index("/kv/export") \
        < paths.index("/kv/import")
    # export hit the victim, import hit the survivor
    exp_url = next(u for u, p in log if p == "/kv/export")
    imp_url = next(u for u, p in log if p == "/kv/import")
    assert exp_url == rt.registry.url_of(victim)
    assert imp_url == rt.registry.url_of(out["target"])
    # affinity followed the pages
    assert rt.registry.affinity_owner([1, 2, 3, 4]) == out["target"]
    assert rt.registry.state_of(victim) == REPLICA_DRAINED


def test_drain_routes_nothing_to_drained_replica():
    post, get, _ = _drain_fns(active_polls=[])
    rt = _router(post=post, get=get)
    victim = rt.registry.names()[0]
    rt.drain_replica(victim)
    for i in range(10):
        assert rt.registry.pick([i]) != victim


def test_drain_registered_prefixes_migrate_too():
    post, get, log = _drain_fns(active_polls=[])
    rt = _router(post=post, get=get)
    pid, _ = rt.register_prefix([5, 6, 7, 8])
    # route once so the prefix lands on a replica
    status, _, _ = rt.dispatch({"prefix_id": pid, "ids": [9],
                                "new_tokens": 1})
    assert status == 200
    victim = next(n for n in rt.registry.names()
                  if rt._prefixes[pid]["replicas"].get(n))
    out = rt.drain_replica(victim)
    assert out["migrated_prefixes"] >= 1


def test_drain_dead_replica_refuses():
    rt = _router(post=lambda *a: (200, {}, []),
                 get=lambda *a: (200, {"ok": True, "stats": {}}))
    victim = rt.registry.names()[0]
    rt.registry.mark_failed(victim)
    out = rt.drain_replica(victim)
    assert out["drained"] is False


def test_drain_replica_dying_mid_drain_reports_error():
    def post(url, path, payload, timeout):
        if path == "/drain":
            raise OSError("connection reset")
        return 200, {}, []

    rt = _router(post=post,
                 get=lambda *a: (200, {"ok": True, "stats": {}}))
    victim = rt.registry.names()[0]
    out = rt.drain_replica(victim)
    assert out["drained"] is False
    assert rt.registry.state_of(victim) == REPLICA_DEAD


def test_failed_export_falls_back_silently():
    """A prefix whose export fails (no pages / error) is skipped — the
    survivor re-prefills it on first use instead."""
    def post(url, path, payload, timeout):
        if path == "/drain":
            return 200, {"draining": True}, []
        if path == "/kv/export":
            return 200, {"blob": None, "tokens_covered": 0,
                         "pages": 0}, []
        return 200, {"ids": [[1]]}, []

    rt = _router(post=post,
                 get=lambda *a: (200, {"ok": True, "stats": {}}))
    victim = rt.registry.pick([1, 2, 3])
    out = rt.drain_replica(victim)
    assert out["drained"] is True
    assert out["migrated_prefixes"] == 0


# ---------------------------------------------------------------------------
# healthz fleet block
# ---------------------------------------------------------------------------

def test_healthz_reports_fleet_and_routability():
    rt = _router(post=lambda *a: (200, {}, []),
                 get=lambda *a: (200, {"ok": True, "draining": False,
                                       "stats": {"active": 3}}))
    rt._poll_once()
    code, body = rt.healthz()
    assert code == 200 and body["ok"] is True
    assert set(body["fleet"]) == set(rt.registry.names())
    rec = body["fleet"]["r0"]
    assert rec["state"] == REPLICA_HEALTHY
    assert rec["active"] == 3 and rec["draining"] is False
    for n in rt.registry.names():
        rt.registry.mark_failed(n)
    code, body = rt.healthz()
    assert code == 503 and body["ok"] is False


def test_poll_failure_walks_replica_dead():
    def get(url, path, timeout):
        raise OSError("refused")

    rt = _router(get=get, policy=_policy(fail_dead=2))
    rt._poll_once()
    rt._poll_once()
    assert all(rt.registry.state_of(n) == REPLICA_DEAD
               for n in rt.registry.names())


# ---------------------------------------------------------------------------
# trace propagation: rid minting, derivation, response identity headers
# (ISSUE 18 — docs/OBSERVABILITY.md rid-derivation grammar)
# ---------------------------------------------------------------------------

def _hdr(headers, name):
    return next((v for h, v in headers if h == name), None)


def _capture_post(script):
    """Headers-aware injected post fn: pops (status, body) per call and
    logs the (url, rid-header, hop-header) of every attempt. An OSError
    entry raises instead."""
    seen = []

    def post(url, path, payload, timeout, headers=None):
        seen.append((url, (headers or {}).get(router_mod.RID_HEADER),
                     (headers or {}).get(router_mod.HOP_HEADER),
                     dict(payload)))
        step = script.pop(0)
        if isinstance(step, Exception):
            raise step
        return step[0], step[1], []

    return post, seen


def test_dispatch_mints_rid_and_echoes_identity_headers():
    post, seen = _capture_post([(200, {"ids": [[1, 2]]})])
    rt = _router(post=post)
    status, _, headers = rt.dispatch({"ids": [1, 2], "new_tokens": 2})
    assert status == 200
    rid = _hdr(headers, router_mod.RID_HEADER)
    assert rid and rid.startswith("R")
    assert _hdr(headers, router_mod.REPLICA_HEADER) in rt.registry.names()
    # the replica saw the SAME rid, via header (hop 0), not body
    assert seen[0][1] == rid and seen[0][2] == "0"
    assert "rid" not in seen[0][3]


def test_dispatch_honors_caller_supplied_rid():
    post, seen = _capture_post([(200, {"ids": [[1]]})])
    rt = _router(post=post)
    _, _, headers = rt.dispatch({"ids": [1], "new_tokens": 1,
                                 "rid": "client-7"})
    assert _hdr(headers, router_mod.RID_HEADER) == "client-7"
    assert seen[0][1] == "client-7"


def test_dispatch_rejects_garbage_caller_rid():
    post, seen = _capture_post([(200, {"ids": [[1]]})])
    rt = _router(post=post)
    _, _, headers = rt.dispatch({"ids": [1], "new_tokens": 1,
                                 "rid": "x" * 300})     # oversized: remint
    assert _hdr(headers, router_mod.RID_HEADER) != "x" * 300


def test_dispatch_connect_retry_derives_dot_t1():
    post, seen = _capture_post([OSError("refused"), (200, {"ids": [[7]]})])
    rt = _router(post=post)
    status, _, headers = rt.dispatch({"ids": [7], "new_tokens": 1})
    assert status == 200
    base = seen[0][1]
    assert seen[1][1] == f"{base}.t1" and seen[1][2] == "1"
    # the client resolves the whole tree from the BASE rid
    assert _hdr(headers, router_mod.RID_HEADER) == base


def test_dispatch_shed_retry_derives_dot_t1():
    post, seen = _capture_post([(503, {"error": "shed"}),
                                (200, {"ids": [[7]]})])
    rt = _router(post=post)
    status, _, headers = rt.dispatch({"ids": [7], "new_tokens": 1})
    assert status == 200
    assert seen[1][1] == f"{seen[0][1]}.t1"
    assert _hdr(headers, router_mod.RID_HEADER) == seen[0][1]


def test_hedged_dispatch_derives_dot_hedge_and_echoes_base_rid():
    seen = []
    release = threading.Event()

    def post(url, path, payload, timeout, headers=None):
        rid = (headers or {}).get(router_mod.RID_HEADER)
        seen.append(rid)
        if not rid.endswith(".hedge"):
            release.wait(2.0)        # primary stalls past the hedge fuse
        return 200, {"ids": [[3]]}, []

    rt = _router(post=post, policy=_policy(hedge_ms=5.0))
    try:
        status, _, headers = rt.dispatch({"ids": [3], "new_tokens": 1})
    finally:
        release.set()
    assert status == 200
    base = next(r for r in seen if not r.endswith(".hedge"))
    assert f"{base}.hedge" in seen
    # whichever branch won, the response names the resolvable tree ROOT
    assert _hdr(headers, router_mod.RID_HEADER) == base
    assert _hdr(headers, router_mod.REPLICA_HEADER) in rt.registry.names()


def test_stream_failover_derives_dot_fo1_and_annotates_terminal():
    rt = _ScriptedStreamRouter([
        [("ok", None),
         ("line", {"step": 0, "tokens": [4]})],        # then truncates
        [("ok", None),
         ("line", {"step": 0, "tokens": [4]}),         # replay, suppressed
         ("line", {"step": 1, "tokens": [5]}),
         ("line", {"ids": [[4, 5]], "steps": 2})],
    ], policy=_policy())
    (_, code, headers), lines = _collect(rt.stream({"ids": [1],
                                                    "new_tokens": 2}))
    assert code == 200
    base = rt.stream_rids[0]
    assert rt.stream_rids == [base, f"{base}.fo1"]
    # headers committed after the FIRST replica's first byte, so the
    # terminal line carries who actually finished + the base rid
    assert _hdr(headers, router_mod.REPLICA_HEADER) == rt.streamed_to[0]
    assert lines[-1]["replica"] == rt.streamed_to[1]
    assert lines[-1]["rid"] == base


def test_stream_shed_hop_derives_dot_t1_and_names_survivor():
    rt = _ScriptedStreamRouter([
        [("refusal", (503, [("Retry-After", "3")], {"error": "shed"}))],
        [("ok", None),
         ("line", {"step": 0, "tokens": [4]}),
         ("line", {"ids": [[4]], "steps": 1})],
    ], policy=_policy())
    (_, code, headers), lines = _collect(rt.stream({"ids": [1],
                                                    "new_tokens": 1}))
    assert code == 200
    base = rt.stream_rids[0]
    assert rt.stream_rids == [base, f"{base}.t1"]
    # the shed happened BEFORE any byte reached the client: the 200
    # headers name the survivor that actually streamed
    assert _hdr(headers, router_mod.RID_HEADER) == base
    assert _hdr(headers, router_mod.REPLICA_HEADER) == rt.streamed_to[1]


def test_stream_honors_caller_rid():
    rt = _ScriptedStreamRouter([
        [("ok", None),
         ("line", {"step": 0, "tokens": [4]}),
         ("line", {"ids": [[4]], "steps": 1})],
    ], policy=_policy())
    (_, code, headers), _ = _collect(rt.stream({"ids": [1],
                                                "new_tokens": 1,
                                                "rid": "trace-9"}))
    assert code == 200
    assert rt.stream_rids == ["trace-9"]
    assert _hdr(headers, router_mod.RID_HEADER) == "trace-9"


def test_mint_rid_is_unique_and_clean_rid_filters():
    rt = _router(post=lambda *a, **k: (200, {}, []))
    assert rt.mint_rid() != rt.mint_rid()
    assert DecodeRouter._clean_rid("  q7 ") == "q7"
    assert DecodeRouter._clean_rid(17) is None
    assert DecodeRouter._clean_rid("") is None
    assert DecodeRouter._clean_rid("a\nb") is None
    assert DecodeRouter._clean_rid("x" * 200) is None
