"""Weights file generation + loading round trip (offline --random path)."""
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from pipeedge_tpu.models import registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


pytestmark = pytest.mark.fleet  # every test here spawns OS processes

@pytest.mark.parametrize("model", ["pipeedge/test-tiny-vit",
                                   "pipeedge/test-tiny-bert",
                                   "pipeedge/test-tiny-gpt2"])
def test_save_random_weights_and_load(model, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "save_model_weights.py"),
         "-m", model, "--random"],
        capture_output=True, env=env, cwd=str(tmp_path), text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr
    weights_file = registry.get_model_default_weights_file(model)
    assert os.path.exists(weights_file)

    # factory must load from the file (not fall back to random init)
    layers = registry.get_model_layers(model)
    fn, params, _ = registry.module_shard_factory(model, weights_file, 1, layers)
    cfg = registry.get_model_config(model)
    if cfg.vocab_size:  # token models: BERT and GPT-2
        x = jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, size=(2, 9)), dtype=jnp.int32)
    else:
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(2, 3, cfg.image_size, cfg.image_size)), dtype=jnp.float32)
    out = np.asarray(fn(params, x))
    assert np.all(np.isfinite(out))
    assert out.shape[0] == 2

    # partial shard loads only its own keys without error
    fn2, params2, _ = registry.module_shard_factory(model, weights_file, 2, 5)
    assert "embeddings" not in params2 and "final" not in params2


def test_read_checkpoint_keys_tool(tmp_path):
    np.savez(tmp_path / "w.npz", **{"a/b": np.zeros((2, 3))})
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "read_checkpoint_keys.py"),
         str(tmp_path / "w.npz")],
        capture_output=True, env=env, text=True, timeout=60)
    assert proc.returncode == 0
    assert "a/b   (2, 3)" in proc.stdout


def test_create_playbook_tool(tmp_path):
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "create_playbook.py"),
         "-wz", "4", "-nz", "hostA,hostB", "-sn", str(tmp_path / "pb.yml")],
        capture_output=True, env=env, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    content = open(tmp_path / "pb.yml").read()
    assert "- hosts: hostA" in content and "runtime.py 0 4" in content


def test_full_size_model_npz_to_logits_pipeline(tmp_path, monkeypatch):
    """Real-checkpoint path on a full-size registry model (BASELINE.md
    families, not the test-tiny oracles): save_model_weights --random ->
    .npz -> per-stage key slicing -> logits, with a mid-block split matching
    the whole-model forward bit-for-bit (VERDICT r1 'missing #5')."""
    monkeypatch.chdir(tmp_path)
    model = "facebook/deit-tiny-distilled-patch16-224"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "save_model_weights.py"),
         "-m", model, "--random"],
        capture_output=True, env=env, cwd=str(tmp_path), text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stderr
    weights_file = registry.get_model_default_weights_file(model)
    assert os.path.exists(weights_file)

    cfg = registry.get_model_config(model)
    layers = registry.get_model_layers(model)
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(2, 3, cfg.image_size, cfg.image_size)), dtype=jnp.float32)

    fn, params, _ = registry.module_shard_factory(model, weights_file, 1,
                                                  layers)
    whole = np.asarray(fn(params, x))
    assert whole.shape == (2, cfg.num_labels)
    assert np.all(np.isfinite(whole))

    # mid-block cut (sublayer 2 of block 6): each stage loads only its own
    # keys from the SAME npz (reference per-stage lazy loading, vit.py:93-118)
    cut = 22
    fn_a, params_a, _ = registry.module_shard_factory(model, weights_file,
                                                      1, cut)
    fn_b, params_b, _ = registry.module_shard_factory(model, weights_file,
                                                      cut + 1, layers)
    piped = np.asarray(fn_b(params_b, fn_a(params_a, x)))
    np.testing.assert_array_equal(piped, whole)


def test_full_size_model_npz_runtime_cli(tmp_path, monkeypatch):
    """End-to-end runtime CLI on a full-size model with a real weights file:
    2-stage host pipeline with a quantized edge."""
    monkeypatch.chdir(tmp_path)
    model = "facebook/deit-tiny-distilled-patch16-224"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "save_model_weights.py"),
         "-m", model, "--random"],
        capture_output=True, env=env, cwd=str(tmp_path), text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stderr
    weights_file = registry.get_model_default_weights_file(model)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "runtime.py"), "0", "2",
         "--platform", "cpu", "-m", model, "-M", weights_file,
         "-b", "8", "-u", "4", "-pt", "1,24,25,48", "-q", "8,0"],
        capture_output=True, env=env, cwd=str(tmp_path), text=True,
        timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "latency_sec=" in proc.stdout
