"""Continuous batching + chunked prefill (ISSUE 16): iteration-level
scheduling inside the decode executors.

The invariants that let the serving plane interleave prompt ingress
with decode steps without touching numerics:

- chunked prompt passes are TOKEN-IDENTICAL to run-to-completion
  prefill on pinned seeds (greedy, sampled, and multirow) — a chunk is
  a span at an offset, and span-at-offset already carries the exact
  softmax-zero masking argument (tests/test_kv_plane.py);
- the chunk interleave is deterministic (same workload -> same chunk
  count, same tokens);
- join/retire happen at step boundaries: `on_step` fires once per
  decode-step pick, `step_join` admits a queued request in the same
  tick a slot frees, and the prefill token budget defers prompt work
  behind waiting decode steps without ever starving it;
- expiry/cancel retire mid-prompt at a CHUNK boundary with every page
  returned to the pool;
- paged speculative decoding (draft/verify caches on the page pools)
  stays token-identical to the dense speculative path and closes its
  page accounting.
"""
import threading

import numpy as np
import pytest

from pipeedge_tpu.kv import KvPagePool, PagedKvBackend  # noqa: E402
from pipeedge_tpu.parallel.batcher import (ContinuousBatcher,  # noqa: E402
                                           StageWorkerExecutor)
from pipeedge_tpu.parallel.speculative import SpeculativeDecoder  # noqa: E402
from pipeedge_tpu.telemetry import metrics as prom  # noqa: E402

MODEL = "pipeedge/test-tiny-gpt2"
PARTITION = [(1, 4), (5, 8)]
MAX_LEN = 48


def _mk_pipe(max_len=MAX_LEN, seed_perturb=None):
    from pipeedge_tpu.models import registry
    from pipeedge_tpu.parallel import decode
    params = [registry.module_shard_factory(MODEL, None, l, r, stage=i,
                                            unroll=False)[1]
              for i, (l, r) in enumerate(PARTITION)]
    if seed_perturb is not None:
        import jax
        params = jax.tree_util.tree_map(
            lambda x: x + 0.01 * (seed_perturb % 7), params)
    return decode.DecodePipeline(
        registry.get_model_entry(MODEL).family.FAMILY,
        registry.get_model_config(MODEL), PARTITION, params,
        max_len=max_len)


@pytest.fixture(scope="module")
def pipe():
    return _mk_pipe()


def _backend(pipe, n_pages=24, page_size=4):
    return PagedKvBackend(pipe, n_pages, page_size,
                          registry=prom.Registry())


def _prompts(n, batch=1, lens=(6,), seed0=11):
    rng = np.random.default_rng(seed0)
    return [np.asarray(rng.integers(
        0, 100, size=(batch, lens[i % len(lens)])), np.int64)
        for i in range(n)]


# ---------------------------------------------------------------------------
# chunked prefill: token parity + determinism
# ---------------------------------------------------------------------------

def test_chunked_wave_token_identical_to_dense(pipe):
    """Long prompts through the chunked wave batcher (greedy, sampled,
    multirow) match solo dense generate() token for token, and every
    long prompt actually ran as chunk waves."""
    kv = _backend(pipe)
    batcher = ContinuousBatcher(pipe, kv=kv, chunk_tokens=4)
    prompts = _prompts(3, lens=(17, 23, 9))
    kwargs = [dict(), dict(temperature=0.8, seed=3),
              dict(temperature=1.1, top_k=5, seed=9)]
    for i, (ids, kw) in enumerate(zip(prompts, kwargs)):
        batcher.submit(i, ids, new_tokens=6, **kw)
    multirow = _prompts(1, batch=2, lens=(14,), seed0=29)[0]
    batcher.submit("b2", multirow, new_tokens=5)
    results = batcher.run()
    for i, (ids, kw) in enumerate(zip(prompts, kwargs)):
        solo = np.asarray(pipe.generate(ids, 6, **kw))
        np.testing.assert_array_equal(results[i], solo)
    np.testing.assert_array_equal(
        results["b2"], np.asarray(pipe.generate(multirow, 5)))
    # 17 -> 5 chunks, 23 -> 6, 9 -> 3, 14 -> 4 (the 4-token chunking of
    # every prompt longer than chunk_tokens)
    assert batcher.stats["prefill_chunks"] == 5 + 6 + 3 + 4
    # every page came back
    cached = kv.trie.stats()["pages_cached"]
    assert kv.pool.free_pages + cached == kv.pool.n_pages


def test_chunked_stage_executor_token_identical(pipe):
    """The worker-thread executor chunks at submit and re-enqueues at
    _finish: same parity contract, same chunk accounting."""
    kv = _backend(pipe)
    ex = StageWorkerExecutor(pipe, kv=kv, chunk_tokens=4)
    try:
        prompts = _prompts(2, lens=(17, 11), seed0=13)
        outs = {}

        def client(rid, ids, **kw):
            ex.submit(rid, ids, 6, **kw)
            outs[rid] = ex.wait(rid, timeout=300)

        threads = [threading.Thread(
            target=client, args=(i, ids), daemon=True,
            kwargs={} if i == 0 else {"temperature": 0.7, "seed": 5})
            for i, ids in enumerate(prompts)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive()
        np.testing.assert_array_equal(
            outs[0], np.asarray(pipe.generate(prompts[0], 6)))
        np.testing.assert_array_equal(
            outs[1], np.asarray(pipe.generate(prompts[1], 6,
                                              temperature=0.7, seed=5)))
        assert ex.snapshot()["prefill_chunks"] == 5 + 3
    finally:
        ex.stop()
    assert kv.pool.free_pages \
        + kv.trie.stats()["pages_cached"] == kv.pool.n_pages


def test_chunk_interleave_deterministic(pipe):
    """Two runs of the same mixed workload produce identical tokens AND
    identical chunk counts — the interleave policy is pure queue
    arithmetic, not timing (the bench-record reproducibility
    contract)."""
    def run_once():
        kv = _backend(pipe)
        b = ContinuousBatcher(pipe, kv=kv, chunk_tokens=4,
                              prefill_budget=2)
        prompts = _prompts(3, lens=(15, 5, 21), seed0=7)
        for i, ids in enumerate(prompts):
            b.submit(i, ids, new_tokens=5)
        res = b.run()
        return ([np.asarray(res[i]) for i in range(3)],
                b.stats["prefill_chunks"], b.stats["ticks"])

    toks_a, chunks_a, steps_a = run_once()
    toks_b, chunks_b, steps_b = run_once()
    assert chunks_a == chunks_b and steps_a == steps_b
    for a, b_ in zip(toks_a, toks_b):
        np.testing.assert_array_equal(a, b_)


# ---------------------------------------------------------------------------
# step boundaries: join / retire / budget
# ---------------------------------------------------------------------------

def test_on_step_fires_once_per_decode_pick(pipe):
    """`on_step` is the admission plane's step-boundary hook: it must
    fire exactly once per decode-step pick (tokens picked), never for
    chunk or prefill waves."""
    steps = []
    kv = _backend(pipe)
    b = ContinuousBatcher(pipe, kv=kv, chunk_tokens=4,
                          on_step=lambda: steps.append(1))
    prompts = _prompts(2, lens=(13, 6), seed0=19)
    for i, ids in enumerate(prompts):
        b.submit(i, ids, new_tokens=4)
    b.run()
    assert len(steps) == 2 * 4


def test_step_join_admits_in_the_completion_tick(pipe):
    """With max_active=1, a queued request must enter stage 0 in the
    SAME tick its predecessor completes (the reversed stage drain
    visits stage 0 after the completion) — strictly fewer ticks than
    the wave-boundary default."""
    def ticks_to_drain(step_join):
        b = ContinuousBatcher(pipe, max_active=1, step_join=step_join)
        for i, ids in enumerate(_prompts(3, lens=(5,), seed0=23)):
            b.submit(i, ids, new_tokens=3)
        n = 0
        while b.tick():
            n += 1
        assert len(b.results) == 3
        return n

    joined, waved = ticks_to_drain(True), ticks_to_drain(False)
    assert joined < waved, (joined, waved)


def test_stage0_budget_policy_defers_and_never_starves(pipe):
    """White-box on the stage-0 pop — deficit-round-robin over prompt
    tokens: a prompt head that outruns the accrued budget is deferred
    behind the first QUEUED decode step; with budget in hand FIFO order
    resumes; with no step waiting, prompt work passes regardless
    (spending into deficit), so starvation is impossible."""
    b = ContinuousBatcher(pipe, chunk_tokens=4, prefill_budget=2)
    chunk = ("rc", np.zeros((1, 4), np.int64), "chunk")
    step = ("rs", np.zeros((1, 1), np.int64), "step")
    # budget short of the 4-token head + a step queued -> step jumps
    b._stage_q[0].extend([chunk, step])
    b._budget = 2
    assert b._pop_stage0() is step
    assert list(b._stage_q[0]) == [chunk]
    # budget covers the head -> FIFO resumes, tokens are spent
    b._stage_q[0].append(step)
    b._budget = 4
    assert b._pop_stage0() is chunk
    assert b._budget == 0
    assert b._pop_stage0() is step
    # no decode step waiting -> the prompt passes anyway, into deficit
    b._stage_q[0].append(chunk)
    b._budget = 0
    assert b._pop_stage0() is chunk
    assert b._budget == -4
    assert not b._stage_q[0]


def test_budget_runs_token_identical(pipe):
    """The budget policy reorders work; it must never change it: the
    same workload under a starved budget and the one-chunk-per-tick
    default produces identical tokens."""
    def run(budget):
        kv = _backend(pipe)
        b = ContinuousBatcher(pipe, kv=kv, chunk_tokens=4,
                              prefill_budget=budget)
        b.submit("d", _prompts(1, lens=(4,), seed0=31)[0], new_tokens=8)
        b.submit("p", _prompts(1, lens=(20,), seed0=37)[0], new_tokens=4)
        res = b.run()
        return {k: np.asarray(v) for k, v in res.items()}

    res_tight, res_loose = run(1), run(4)
    for k in res_tight:
        np.testing.assert_array_equal(res_tight[k], res_loose[k])


def test_set_chunk_tokens_is_live(pipe):
    """The brownout governor's lever: set_chunk_tokens takes effect for
    the NEXT admitted prompt (in-flight chunk trains are unaffected)."""
    kv = _backend(pipe)
    b = ContinuousBatcher(pipe, kv=kv, chunk_tokens=8)
    b.submit(0, _prompts(1, lens=(16,), seed0=41)[0], new_tokens=2)
    b.run()
    first = b.stats["prefill_chunks"]
    assert first == 2                      # 16 tokens / 8
    b.set_chunk_tokens(4)
    b.submit(1, _prompts(1, lens=(16,), seed0=43)[0], new_tokens=2)
    b.run()
    assert b.stats["prefill_chunks"] == first + 4


# ---------------------------------------------------------------------------
# chunk-boundary expiry / cancel: retire mid-prompt, zero leaks
# ---------------------------------------------------------------------------

def test_cancel_mid_chunk_retires_and_frees_pages(pipe):
    """A request cancelled while its prompt is still chunk-streaming
    retires at the next chunk boundary — bare-prompt result, every
    page back in the pool."""
    kv = _backend(pipe)
    b = ContinuousBatcher(pipe, kv=kv, chunk_tokens=4)
    cancel = threading.Event()
    ids = _prompts(1, lens=(20,), seed0=47)[0]
    b.submit("c", ids, new_tokens=6, cancel=cancel)
    assert b.tick()                        # first chunk enters flight
    cancel.set()
    while b.tick():
        pass
    # retired with the bare prompt (the serving layer's 504 shape)
    np.testing.assert_array_equal(b.results["c"], ids)
    assert b.active == 0
    assert kv.pool.free_pages \
        + kv.trie.stats()["pages_cached"] == kv.pool.n_pages


def test_deadline_expiry_mid_chunk_stage_executor(pipe):
    """Same retire point on the worker-thread executor, driven by the
    deadline flavor of cancellation: expired mid-prompt -> bare prompt
    back, no leaked pages, slot freed for the next request."""
    import time
    kv = _backend(pipe)
    ex = StageWorkerExecutor(pipe, kv=kv, chunk_tokens=4)
    try:
        ids = _prompts(1, lens=(20,), seed0=53)[0]
        ex.submit("d", ids, 6, deadline=time.monotonic() + 0.001)
        out = ex.wait("d", timeout=300)
        # expiry can land before any decode pick; wherever the chunk
        # train stopped, the result is a prefix of prompt+tokens and
        # the accounting is closed
        assert out.shape[0] == 1 and out.shape[1] >= ids.shape[1]
        assert ex.active == 0
        # the slot is genuinely free: a fresh request still serves
        ex.submit("after", _prompts(1, lens=(6,), seed0=59)[0], 2)
        ex.wait("after", timeout=300)
    finally:
        ex.stop()
    assert kv.pool.free_pages \
        + kv.trie.stats()["pages_cached"] == kv.pool.n_pages


# ---------------------------------------------------------------------------
# paged speculative decoding (draft/verify caches on the page pools)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def draft_pipe():
    return _mk_pipe(seed_perturb=23)


def test_paged_speculative_token_identical(pipe, draft_pipe):
    """Speculative generation over paged caches matches BOTH the dense
    speculative path and plain greedy, on pinned seeds, for a real
    (perturbed-weights) draft and for self-draft — and both pools close
    their accounting."""
    ids = np.asarray(_prompts(1, lens=(9,), seed0=61)[0])
    want = np.asarray(pipe.generate(ids, 8))
    dense = SpeculativeDecoder(pipe, draft_pipe, gamma=3)
    np.testing.assert_array_equal(
        np.asarray(dense.generate(ids, 8)), want)

    kv = _backend(pipe)
    dpool = KvPagePool(draft_pipe, 24, 4, registry=prom.Registry())
    spec = SpeculativeDecoder(pipe, draft_pipe, gamma=3)
    spec.attach_paged(kv, dpool)
    out = np.asarray(spec.generate(ids, 8, rid="r1"))
    np.testing.assert_array_equal(out, want)
    assert spec.live_rids() == set()
    assert kv.pool.free_pages == kv.pool.n_pages
    assert dpool.free_pages == dpool.n_pages
    # self-draft accepts everything — the acceptance-path numerics
    selfspec = SpeculativeDecoder(pipe, pipe, gamma=2)
    selfspec.attach_paged(_backend(pipe),
                          KvPagePool(pipe, 24, 4,
                                     registry=prom.Registry()))
    np.testing.assert_array_equal(
        np.asarray(selfspec.generate(ids, 6)),
        np.asarray(pipe.generate(ids, 6)))
    assert selfspec.last_acceptance_rate == 1.0


def test_paged_speculative_batch_rows(pipe, draft_pipe):
    """Multirow prompts allocate per-row page tables; parity holds for
    every row."""
    ids = np.asarray(_prompts(1, batch=2, lens=(7,), seed0=67)[0])
    kv = _backend(pipe, n_pages=32)
    dpool = KvPagePool(draft_pipe, 32, 4, registry=prom.Registry())
    spec = SpeculativeDecoder(pipe, draft_pipe, gamma=2)
    spec.attach_paged(kv, dpool)
    np.testing.assert_array_equal(
        np.asarray(spec.generate(ids, 6)),
        np.asarray(pipe.generate(ids, 6)))
    assert kv.pool.free_pages == kv.pool.n_pages
    assert dpool.free_pages == dpool.n_pages


def test_paged_speculative_rejects_dense_prefix(pipe, draft_pipe):
    spec = SpeculativeDecoder(pipe, draft_pipe, gamma=2)
    spec.attach_paged(_backend(pipe),
                      KvPagePool(draft_pipe, 16, 4,
                                 registry=prom.Registry()))
    handle = spec.precompute_prefix(np.asarray([[1, 2, 3, 4]]))
    with pytest.raises(ValueError, match="paged speculative"):
        spec.generate(np.asarray([[5, 6]]), 4, prefix=handle)


def test_paged_speculative_orphan_sweep_spares_live_owners(pipe,
                                                           draft_pipe):
    """The governor-facing leak contract: pages adopted under a live
    owner survive sweeps; once the owner is gone, a simulated die-
    between-charge-and-release is reclaimed by sweep_orphans."""
    kv = _backend(pipe)
    dpool = KvPagePool(draft_pipe, 24, 4, registry=prom.Registry())
    spec = SpeculativeDecoder(pipe, draft_pipe, gamma=2)
    spec.attach_paged(kv, dpool)
    # simulate a generation that died after the page charge
    spec._live.add("dead")
    spec._alloc_paged("dead", 1, 6, 4)
    assert dpool.free_pages < dpool.n_pages
    assert spec.sweep_orphans() == 0       # owner still listed live
    spec._live.discard("dead")
    assert spec.sweep_orphans() > 0        # now reclaimed
    assert dpool.free_pages == dpool.n_pages
    # the target pool side rides the serving sweep with the same
    # liveness callable
    leaked = kv.pool.sweep_leaked(lambda: spec.live_rids())
    assert leaked > 0
    assert kv.pool.free_pages == kv.pool.n_pages
