"""The routed decode-replica fleet (ISSUE 17 acceptance): a real
router PROCESS supervising two real replica processes must serve
token-identically through a SIGKILL of one replica mid-burst (zero
lost requests), readmit the respawn, and migrate warm KV on a
graceful drain. Also the `--host` satellite: serve.py binds the
requested address instead of unconditional loopback.
"""
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MODEL = "pipeedge/test-tiny-gpt2"

pytestmark = pytest.mark.fleet      # spawns a 3-process fleet


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(port, path, timeout=10):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as resp:
        return json.loads(resp.read())


def _post(port, path, obj, timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _metrics(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as resp:
        return resp.read().decode()


def _metric_value(port, name):
    for line in _metrics(port).splitlines():
        if line.startswith(name + " ") or line == name:
            return float(line.split()[-1])
    return 0.0


def _wait_fleet_healthy(port, deadline_s=180, min_epoch=0):
    deadline = time.monotonic() + deadline_s
    last = None
    while time.monotonic() < deadline:
        try:
            last = _get(port, "/healthz", timeout=3)
            fleet = last["fleet"]
            if last.get("ok") \
                    and all(r["state"] == "healthy"
                            for r in fleet.values()) \
                    and max(r["epoch"] for r in fleet.values()) \
                    >= min_epoch:
                return last
        except Exception:
            pass
        time.sleep(0.3)
    raise AssertionError(f"fleet never became healthy: {last}")


@pytest.fixture(scope="module")
def router_fleet():
    """serve.py --role router over 2 supervised tiny replicas; yields
    the router port. One fixture for the whole module — the tests
    below are ORDERED around the faults they inject."""
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "serve.py"),
         "--role", "router", "--replicas", "2",
         "-m", MODEL, "-pt", "1,4,5,8", "--max-len", "48",
         "-t", "float32", "--kv-pages", "24", "--kv-page-size", "4",
         "--port", str(port), "--router-poll-interval", "0.2",
         "--inject-stall", "0:60"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)
    # drain stdout so replica pumps never block on a full pipe
    threading.Thread(target=lambda: [None for _ in proc.stdout],
                     daemon=True).start()
    try:
        _wait_fleet_healthy(port, deadline_s=240)
        yield port
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()


@pytest.fixture(scope="module")
def solo_pipe():
    from pipeedge_tpu.models import registry
    from pipeedge_tpu.parallel import decode
    partition = [(1, 4), (5, 8)]
    params = []
    for i, (l, r) in enumerate(partition):
        _, p, _ = registry.module_shard_factory(MODEL, None, l, r,
                                                stage=i, unroll=False)
        params.append(p)
    return decode.DecodePipeline(
        registry.get_model_entry(MODEL).family.FAMILY,
        registry.get_model_config(MODEL), partition, params, max_len=48)


SHARED = list(range(5, 13))          # 8 tokens = 2 full pages


def _burst_ids(n):
    rng = np.random.default_rng(23)
    return [SHARED + rng.integers(0, 50, size=4).tolist()
            for _ in range(n)]


def test_routed_generate_matches_solo(router_fleet, solo_pipe):
    """Tokens through the router equal solo pipeline runs — for plain
    and streaming requests, twice (the second ride hits whichever
    replica the affinity map kept warm)."""
    port = router_fleet
    ids = SHARED + [40, 41, 42, 43]
    want = np.asarray(solo_pipe.generate(np.asarray([ids]), 6))
    for _ in range(2):
        out = _post(port, "/generate", {"ids": ids, "new_tokens": 6})
        np.testing.assert_array_equal(np.asarray(out["ids"]), want)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps({"ids": ids, "new_tokens": 6,
                         "stream": True}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as resp:
        lines = [json.loads(l) for l in resp if l.strip()]
    assert [l["step"] for l in lines if "step" in l] == list(range(6))
    np.testing.assert_array_equal(np.asarray(lines[-1]["ids"]), want)


def test_drain_migrates_pages_and_respawns(router_fleet, solo_pipe):
    """Graceful drain: POST /drain ships the victim's warm prefix
    pages to the survivor (kv/ship.py codec), the supervised victim
    respawns with epoch+1, and the fleet readmits it."""
    port = router_fleet
    # warm a shared prefix on whichever replica affinity picks
    ids = SHARED + [30, 31, 32, 33]
    _post(port, "/generate", {"ids": ids, "new_tokens": 4})
    h = _get(port, "/healthz")
    victim = None
    # drain the affinity owner: find it by draining the replica that
    # served the request (the one with the higher request count is not
    # exposed, so just drain r0 — migration work includes affinity keys)
    victim = sorted(h["fleet"])[0]
    migrated_before = _metric_value(
        port, "pipeedge_router_migrated_prefixes_total")
    out = _post(port, "/drain", {"replica": victim}, timeout=120)
    assert out["drained"] is True
    # the drained replica respawned (epoch+1) and was readmitted
    _wait_fleet_healthy(port, deadline_s=120, min_epoch=1)
    # tokens unchanged after the drain/migration dance
    want = np.asarray(solo_pipe.generate(np.asarray([ids]), 4))
    res = _post(port, "/generate", {"ids": ids, "new_tokens": 4})
    np.testing.assert_array_equal(np.asarray(res["ids"]), want)
    if out["migrated_prefixes"]:
        assert _metric_value(
            port, "pipeedge_router_migrated_prefixes_total") \
            > migrated_before


def test_replica_sigkill_midburst_loses_zero_requests(router_fleet,
                                                      solo_pipe):
    """THE acceptance: SIGKILL one replica while a shared-prefix burst
    is in flight. Every request completes token-identically (failover
    re-routes, streams replay with suppression), the failover counter
    moves, and the respawn readmits."""
    port = router_fleet
    h = _wait_fleet_healthy(port, deadline_s=60)
    burst = _burst_ids(8)
    want = [np.asarray(solo_pipe.generate(np.asarray([ids]), 6))
            for ids in burst]
    results = [None] * len(burst)
    errors = []

    def run(i):
        try:
            if i % 2:          # half the burst rides the stream path
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/generate",
                    data=json.dumps({"ids": burst[i], "new_tokens": 6,
                                     "stream": True}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=180) as resp:
                    lines = [json.loads(l) for l in resp if l.strip()]
                final = lines[-1]
                if "error" in final:
                    raise RuntimeError(final["error"])
                steps = [l["step"] for l in lines if "step" in l]
                assert steps == sorted(set(steps)), \
                    f"duplicate/disordered steps: {steps}"
                results[i] = final["ids"]
            else:
                results[i] = _post(port, "/generate",
                                   {"ids": burst[i], "new_tokens": 6},
                                   timeout=180)["ids"]
        except Exception as exc:   # noqa: BLE001 — asserted below
            errors.append((i, exc))

    failovers_before = _metric_value(port,
                                     "pipeedge_router_failovers_total")
    threads = [threading.Thread(target=run, args=(i,))
               for i in range(len(burst))]
    for t in threads:
        t.start()
    # let the burst spread across both replicas, then kill one that is
    # actively serving (fall back to r0's pid if the poll misses it)
    time.sleep(0.6)
    fleet = _get(port, "/healthz")
    victim = next((n for n, rec in fleet["fleet"].items()
                   if rec.get("active")), sorted(fleet["fleet"])[0])
    pid = fleet["workers"][victim[1:]]["pid"]
    os.kill(pid, signal.SIGKILL)
    for t in threads:
        t.join(timeout=240)
    assert not errors, f"lost/errored requests through the kill: {errors}"
    for i, ids in enumerate(burst):
        np.testing.assert_array_equal(np.asarray(results[i]), want[i])
    assert _metric_value(port, "pipeedge_router_failovers_total") \
        >= failovers_before    # >= : the kill may land between requests
    # the killed replica respawned with a bumped epoch and readmitted
    h2 = _wait_fleet_healthy(port, deadline_s=120)
    assert h2["fleet"][victim]["epoch"] \
        >= h["fleet"][victim]["epoch"] + 1
    # and serves correctly again
    res = _post(port, "/generate", {"ids": burst[0], "new_tokens": 6})
    np.testing.assert_array_equal(np.asarray(res["ids"]), want[0])


# ---------------------------------------------------------------------------
# fleet observatory (ISSUE 18 acceptance): /fleet aggregation, burn-rate
# gauges, and trace_report --fleet resolving one cross-process timeline
# for a request that survived a mid-stream SIGKILL
# ---------------------------------------------------------------------------

def test_fleet_observatory_resolves_failover_timeline(router_fleet):
    """Stream through the router, SIGKILL the serving replica mid-
    stream, then resolve the request's rid TREE across the fleet's
    live span rings: `trace_report --fleet <router> --request RID` must
    render one causal timeline spanning the router's route hops (both
    replicas named) and the surviving replica's serve spans, dominant
    stall named. (The SIGKILLed process takes its span ring with it —
    its hop survives in the ROUTER's spans, which is exactly why the
    router records one per attempt.) Also: GET /fleet aggregates both
    replicas and the pre-declared burn-rate gauge matrix renders on
    the router's /metrics."""
    port = router_fleet
    _wait_fleet_healthy(port, deadline_s=120)
    ids = SHARED + [17, 18, 19, 20]

    # -- the surviving-failover request ---------------------------------
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps({"ids": ids, "new_tokens": 8,
                         "stream": True}).encode(),
        headers={"Content-Type": "application/json"})
    resp = urllib.request.urlopen(req, timeout=180)
    rid = resp.headers.get("X-PipeEdge-Rid")
    victim = resp.headers.get("X-PipeEdge-Replica")
    assert rid and victim, "router must echo identity headers on streams"
    lines = []
    it = iter(resp)
    for raw in it:                     # let a couple of steps flow
        if raw.strip():
            lines.append(json.loads(raw))
        if len(lines) >= 2:
            break
    pid = _get(port, "/healthz")["workers"][victim[1:]]["pid"]
    os.kill(pid, signal.SIGKILL)
    for raw in it:
        if raw.strip():
            lines.append(json.loads(raw))
    final = lines[-1]
    assert "error" not in final, final
    steps = [l["step"] for l in lines if "step" in l]
    assert steps == list(range(8)), steps      # replay suppressed
    survivor = final["replica"]
    assert survivor != victim
    assert final["rid"] == rid

    # -- one federated causal timeline ----------------------------------
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         "--fleet", f"http://127.0.0.1:{port}", "--request", rid],
        capture_output=True, text=True, timeout=120, env=env)
    assert out.returncode == 0, out.stdout + out.stderr
    rec = json.loads(out.stdout)
    assert rec["found"], rec
    # the whole derivation tree resolved from the BASE rid
    assert rid in rec["rids"]
    assert f"{rid}.fo1" in rec["rids"]
    # router hops name BOTH replicas; >= 2 processes contributed spans
    assert f"route/{victim}" in rec["segments"]
    assert f"route/{survivor}" in rec["segments"]
    assert len(rec["ranks"]) >= 2
    contributing = {rec["processes"][str(r)]["target"]
                    for r in rec["ranks"]}
    assert "router" in contributing
    assert survivor in contributing    # a REPLICA process's serve spans
    assert rec["dominant_stall"] and rec["dominant_stall"]["segment"]

    # -- /fleet aggregates every live replica ---------------------------
    _wait_fleet_healthy(port, deadline_s=120)
    time.sleep(2.5)                    # let the collector re-scrape all
    fleet = _get(port, "/fleet")
    assert set(fleet["replicas"]) >= {"r0", "r1"}
    cls = fleet["classes"]["interactive"]
    assert cls["requests_total"] > 0
    assert fleet["slo"]["burn_rate"]["interactive"]["short"] is not None

    # -- burn-rate gauge matrix pre-declared on the router (PL501) ------
    text = _metrics(port)
    for klass in ("interactive", "batch", "best_effort"):
        for window in ("short", "long"):
            assert (f'pipeedge_slo_burn_rate{{class="{klass}",'
                    f'window="{window}"}}') in text


# ---------------------------------------------------------------------------
# --host (the non-loopback prerequisite, shipped as its own change)
# ---------------------------------------------------------------------------

def test_host_flag_binds_requested_address():
    """serve.py --host 0.0.0.0 binds the wildcard (reachable via
    loopback too) and the readiness line names the requested host —
    before ISSUE 17 the bind was a hard-coded 127.0.0.1."""
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "serve.py"),
         "-m", MODEL, "-pt", "1,4,5,8", "--max-len", "48",
         "-t", "float32", "--port", str(port), "--host", "0.0.0.0"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)
    try:
        deadline = time.monotonic() + 120
        line = ""
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if "serving" in line:
                break
            if proc.poll() is not None:
                raise RuntimeError(f"server died: {proc.stdout.read()}")
        assert f"on 0.0.0.0:{port}" in line, line
        body = _get(port, "/healthz", timeout=30)
        assert body["ok"] is True
    finally:
        proc.terminate()
        proc.wait(timeout=10)
