"""Speculative decoding: greedy-exact vs the plain pipeline.

The guarantee under test (parallel/speculative.py): for fp caches,
SpeculativeDecoder.generate is token-identical to the target pipeline's
own greedy generate, for ANY draft over the same vocabulary — acceptance
only changes the dispatch count. Drafts here are independently-seeded
(gpt2) or noise-perturbed (llama/mistral) models, so rounds exercise
both accepted and rejected prefixes.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pipeedge_tpu.models import registry
from pipeedge_tpu.parallel import decode
from pipeedge_tpu.parallel.speculative import SpeculativeDecoder

pytestmark = pytest.mark.slow   # compile-heavy decode programs

MAX_LEN = 48


def _pipe(name, partition=None, seed_perturb=None, max_len=MAX_LEN,
          **kw):
    cfg = registry.get_model_config(name)
    total = registry.get_model_layers(name)
    partition = partition or [(1, total)]
    family = registry.get_model_entry(name).family.FAMILY
    params = []
    for i, (l, r) in enumerate(partition):
        _, p, _ = registry.module_shard_factory(name, None, l, r,
                                                unroll=False)
        if seed_perturb is not None:
            rng = np.random.default_rng(seed_perturb + i)
            p = jax.tree_util.tree_map(
                lambda x: x + jnp.asarray(
                    rng.normal(scale=0.02, size=x.shape), x.dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, p)
        params.append(p)
    return decode.DecodePipeline(family, cfg, partition, params,
                                 max_len=max_len, **kw)


@pytest.fixture(scope="module")
def gpt2_pipes():
    return _pipe("pipeedge/test-tiny-gpt2"), \
        _pipe("pipeedge/test-tiny-gpt2", seed_perturb=11)


def _ids(batch, prompt_len, vocab=100, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab, size=(batch, prompt_len))


@pytest.mark.parametrize("gamma", [1, 2, 4])
@pytest.mark.parametrize("batch", [1, 3])
def test_spec_greedy_exact_gpt2(gpt2_pipes, gamma, batch):
    target, draft = gpt2_pipes
    ids = _ids(batch, 8)
    want = np.asarray(target.generate(ids, 12))
    spec = SpeculativeDecoder(target, draft, gamma=gamma)
    got = np.asarray(spec.generate(ids, 12))
    np.testing.assert_array_equal(got, want)
    assert 0.0 <= spec.last_acceptance_rate <= 1.0


def test_spec_self_draft_accepts_everything(gpt2_pipes):
    """Draft == target: every proposal matches, acceptance 1.0, each
    round commits gamma+1 tokens."""
    target, _ = gpt2_pipes
    ids = _ids(2, 8)
    want = np.asarray(target.generate(ids, 10))
    spec = SpeculativeDecoder(target, target, gamma=3)
    got = np.asarray(spec.generate(ids, 10))
    np.testing.assert_array_equal(got, want)
    assert spec.last_acceptance_rate == 1.0


def test_spec_multistage_target(gpt2_pipes):
    """The verify span rides the pipeline stages like any decode."""
    _, draft = gpt2_pipes
    target = _pipe("pipeedge/test-tiny-gpt2", partition=[(1, 4), (5, 8)])
    ids = _ids(2, 8)
    want = np.asarray(target.generate(ids, 12))
    got = np.asarray(
        SpeculativeDecoder(target, draft, gamma=3).generate(ids, 12))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("name", ["pipeedge/test-tiny-llama",
                                  "pipeedge/test-tiny-mistral"])
def test_spec_greedy_exact_llama_family(name):
    """RoPE/GQA (and mistral's sliding window) under span verification."""
    target = _pipe(name)
    draft = _pipe(name, seed_perturb=23)
    ids = _ids(2, 8)
    want = np.asarray(target.generate(ids, 12))
    spec = SpeculativeDecoder(target, draft, gamma=3)
    got = np.asarray(spec.generate(ids, 12))
    np.testing.assert_array_equal(got, want)


def test_extend_matches_serial_steps(gpt2_pipes):
    """The verify primitive itself: one K-token extend produces the same
    last-stage logits and cache state as K serial decode steps."""
    target, _ = gpt2_pipes
    ids = jnp.asarray(_ids(2, 8), jnp.int32)
    k_span = 4
    rng = np.random.default_rng(3)
    toks = jnp.asarray(rng.integers(0, 100, size=(2, k_span)), jnp.int32)

    _, caches_a = target._prefill(ids)
    span_logits, caches_a = target.extend(toks, caches_a, 8)

    _, caches_b = target._prefill(ids)
    serial = []
    for j in range(k_span):
        data = toks[:, j:j + 1]
        for i, st in enumerate(target.stages):
            data, caches_b[i] = target._decode_step(st, data, caches_b[i],
                                                    8 + j)
        serial.append(data[:, 0])
    np.testing.assert_allclose(np.asarray(span_logits),
                               np.asarray(jnp.stack(serial, axis=1)),
                               rtol=2e-5, atol=2e-5)
    for ca, cb in zip(caches_a, caches_b):
        for key in ca:
            np.testing.assert_allclose(np.asarray(ca[key][:, :, :12]),
                                       np.asarray(cb[key][:, :, :12]),
                                       rtol=2e-5, atol=2e-5)


def test_spec_moe_dropless_ok_capacity_refused():
    """Dropless MoE keeps the greedy-exact guarantee; capacity-bounded
    routing is refused with the reason."""
    target = _pipe("pipeedge/test-tiny-moe")
    draft = _pipe("pipeedge/test-tiny-moe", seed_perturb=5)
    ids = _ids(2, 8)
    want = np.asarray(target.generate(ids, 10))
    got = np.asarray(
        SpeculativeDecoder(target, draft, gamma=2).generate(ids, 10))
    np.testing.assert_array_equal(got, want)

    import dataclasses
    target.cfg = dataclasses.replace(
        registry.get_model_config("pipeedge/test-tiny-moe"),
        capacity_factor=1.0)
    with pytest.raises(ValueError, match="capacity-bounded"):
        SpeculativeDecoder(target, draft, gamma=2)


def test_spec_vocab_mismatch_refused(gpt2_pipes):
    import dataclasses
    target, draft = gpt2_pipes
    odd = _pipe("pipeedge/test-tiny-gpt2")
    odd.cfg = dataclasses.replace(odd.cfg, vocab_size=101)
    with pytest.raises(ValueError, match="vocabulary"):
        SpeculativeDecoder(target, odd)


def test_spec_tp_target():
    """A tensor-parallel target (head-sharded cache under shard_map)
    verifies spans like the plain pipeline: spec == plain greedy."""
    from jax.sharding import Mesh
    target_plain = _pipe("pipeedge/test-tiny-gpt2")
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    target_tp = _pipe("pipeedge/test-tiny-gpt2", mesh=mesh)
    draft = _pipe("pipeedge/test-tiny-gpt2", seed_perturb=11)
    ids = _ids(2, 8)
    want = np.asarray(target_plain.generate(ids, 12))
    got = np.asarray(
        SpeculativeDecoder(target_tp, draft, gamma=3).generate(ids, 12))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("name", ["pipeedge/test-tiny-gpt2",
                                  "pipeedge/test-tiny-llama",
                                  "pipeedge/test-tiny-mistral"])
def test_prefix_cache_matches_full_prefill(name):
    """Prompt caching: precompute_prefix + suffix-span generate ==
    monolithic-prompt generate, token for token (fp caches), for every
    decode family incl. RoPE at global offsets and sliding windows."""
    pipe = _pipe(name)
    rng = np.random.default_rng(17)
    prefix = rng.integers(0, 100, size=(1, 6))
    suffix = rng.integers(0, 100, size=(3, 5))
    full = np.concatenate([np.repeat(prefix, 3, axis=0), suffix], axis=1)
    want = np.asarray(pipe.generate(full, 10))
    handle = pipe.precompute_prefix(prefix)
    got = np.asarray(pipe.generate(suffix, 10, prefix=handle))
    # the returned array omits the prefix; compare suffix + continuation
    np.testing.assert_array_equal(got, want[:, 6:])
    # the handle is reusable (a second batch, sampled decode)
    suffix2 = rng.integers(0, 100, size=(2, 5))
    full2 = np.concatenate([np.repeat(prefix, 2, axis=0), suffix2], axis=1)
    want2 = np.asarray(pipe.generate(full2, 8, temperature=0.8, seed=3))
    got2 = np.asarray(pipe.generate(suffix2, 8, temperature=0.8, seed=3,
                                    prefix=handle))
    np.testing.assert_array_equal(got2, want2[:, 6:])


def test_prefix_cache_multistage():
    """Prefix reuse rides multi-stage pipelines: a prefix-seeded request
    matches the full-prompt run under a multi-stage partition."""
    target = _pipe("pipeedge/test-tiny-gpt2", partition=[(1, 4), (5, 8)])
    rng = np.random.default_rng(21)
    prefix = rng.integers(0, 100, size=(1, 4))
    suffix = rng.integers(0, 100, size=(2, 4))
    full = np.concatenate([np.repeat(prefix, 2, axis=0), suffix], axis=1)
    want = np.asarray(target.generate(full, 8))
    got = np.asarray(target.generate(
        suffix, 8, prefix=target.precompute_prefix(prefix)))
    np.testing.assert_array_equal(got, want[:, 4:])


@pytest.mark.parametrize("name", ["pipeedge/test-tiny-gpt2",
                                  "pipeedge/test-tiny-llama"])
def test_spec_with_prefix_cache(name):
    """Speculative decoding composes with prompt caching: both pipelines
    seed from the shared prefix (each with its own K/V), the first draft
    catch-up span covers the whole suffix, and the output still equals
    the target's plain full-prompt greedy decode."""
    target = _pipe(name)
    draft = _pipe(name, seed_perturb=31)
    spec = SpeculativeDecoder(target, draft, gamma=3)
    rng = np.random.default_rng(41)
    prefix = rng.integers(0, 100, size=(1, 6))
    suffix = rng.integers(0, 100, size=(2, 4))
    full = np.concatenate([np.repeat(prefix, 2, axis=0), suffix], axis=1)
    want = np.asarray(target.generate(full, 11))
    handle = spec.precompute_prefix(prefix)
    got = np.asarray(spec.generate(suffix, 11, prefix=handle))
    np.testing.assert_array_equal(got, want[:, 6:])
    # full acceptance path too (self-draft) with the same handle shape
    spec2 = SpeculativeDecoder(target, target, gamma=2)
    got2 = np.asarray(spec2.generate(
        suffix, 11, prefix=spec2.precompute_prefix(prefix)))
    np.testing.assert_array_equal(got2, want[:, 6:])
    assert spec2.last_acceptance_rate == 1.0


@pytest.mark.parametrize("gamma", [2, 4])
def test_device_rounds_token_identical_two_syncs_per_round(gpt2_pipes,
                                                           gamma):
    """sync='device' (the default here via 'auto') fuses each round's
    DRAFT side into one program: tokens identical to sync='host' (the
    target verify runs the same compiled stage programs in both modes),
    and the host round trips drop from (gamma+1)/round to 2/round (one
    packed proposal readback + the verify argmax)."""
    target, draft = gpt2_pipes
    ids = _ids(2, 8, seed=5)
    host = SpeculativeDecoder(target, draft, gamma=gamma, sync="host")
    dev = SpeculativeDecoder(target, draft, gamma=gamma, sync="device")
    want = np.asarray(host.generate(ids, 12))
    got = np.asarray(dev.generate(ids, 12))
    np.testing.assert_array_equal(got, want)
    assert dev.last_acceptance_rate == host.last_acceptance_rate
    # host pays 1 + rounds*(gamma+1); device pays 1 + 2*rounds
    n_rounds = (host.last_sync_count - 1) // (gamma + 1)
    assert host.last_sync_count == 1 + n_rounds * (gamma + 1)
    assert dev.last_sync_count == 1 + 2 * n_rounds
    assert dev.last_sync_count < host.last_sync_count


def test_device_rounds_with_prefix_and_auto_fallback(gpt2_pipes):
    """Device rounds compose with prompt caching (the catch-up span is
    just longer on round 1); 'auto' falls back to host rounds when a
    pipeline pins stages to devices, and sync='device' refuses with the
    reason."""
    target, draft = gpt2_pipes
    rng = np.random.default_rng(77)
    prefix = rng.integers(0, 100, size=(1, 6))
    suffix = rng.integers(0, 100, size=(2, 4))
    spec = SpeculativeDecoder(target, draft, gamma=3)
    assert spec.sync == "device"     # auto picked the fused rounds
    handle = spec.precompute_prefix(prefix)
    got = np.asarray(spec.generate(suffix, 9, prefix=handle))
    want = np.asarray(
        SpeculativeDecoder(target, draft, gamma=3, sync="host")
        .generate(suffix, 9, prefix=handle))
    np.testing.assert_array_equal(got, want)

    placed = _pipe("pipeedge/test-tiny-gpt2",
                   devices=[jax.devices()[0]])
    # a placed TARGET is fine (its verify rides the normal stage
    # programs either way); a placed DRAFT forces the host fallback
    assert SpeculativeDecoder(placed, draft, gamma=2).sync == "device"
    auto = SpeculativeDecoder(target, placed, gamma=2)
    assert auto.sync == "host"       # fell back, still works
    with pytest.raises(ValueError, match="device placement"):
        SpeculativeDecoder(target, placed, gamma=2, sync="device")


def test_device_rounds_eligibility_gate():
    """The fused-round gate names every blocker: per-stage placement and
    tp/ep/tp x ep meshes all refuse (their programs carry shardings or
    host-driven transfers a single jitted round must not inline)."""
    from types import SimpleNamespace as NS

    from pipeedge_tpu.parallel.speculative import _device_rounds_eligible

    def pipe(**kw):
        base = dict(stages=[{"device": None}], mesh=None, ep_mesh=None,
                    tp_ep_mesh=None)
        base.update(kw)
        return NS(**base)

    assert _device_rounds_eligible(pipe()) is None
    assert "device placement" in _device_rounds_eligible(
        pipe(stages=[{"device": object()}]))
    assert "tensor-parallel" in _device_rounds_eligible(
        pipe(mesh=object()))
    assert "expert-parallel" in _device_rounds_eligible(
        pipe(ep_mesh=object()))
    assert "tp x ep" in _device_rounds_eligible(pipe(tp_ep_mesh=object()))
