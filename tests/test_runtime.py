"""Runtime CLI integration tests (subprocess, 8 fake devices).

Covers the reference's primary usage patterns (README.md:75-79): single-node
degenerate, manual multi-stage partition, quantized edges, SPMD driver, and
scheduler-driven auto-partitioning.
"""
import os
import shutil
import subprocess
import sys

import pytest
import yaml

from pipeedge_tpu.sched.scheduler import _REPO_BUILD_PATHS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MODEL = "pipeedge/test-tiny-vit"


pytestmark = pytest.mark.fleet  # every test here spawns OS processes

def _run(tmp_path, *extra, env_extra=None, timeout=300):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=REPO)
    if env_extra:
        env.update(env_extra)
    cmd = [sys.executable, os.path.join(REPO, "runtime.py")] + list(extra)
    return subprocess.run(cmd, capture_output=True, env=env, cwd=str(tmp_path),
                          timeout=timeout, text=True)


def _throughput(proc) -> float:
    for line in proc.stdout.splitlines():
        if line.startswith("latency_sec="):
            return float(line.split("throughput_items_sec=")[1])
    raise AssertionError(f"no stats line in output:\n{proc.stdout}\n{proc.stderr}")


def test_single_stage_degenerate(tmp_path):
    proc = _run(tmp_path, "0", "1", "-m", MODEL, "-b", "4", "-u", "2")
    assert proc.returncode == 0, proc.stderr
    assert _throughput(proc) > 0
    # monitoring CSVs created per key
    assert (tmp_path / "shard.csv").exists()
    assert (tmp_path / "output.csv").exists()


def test_two_stage_host_with_quant(tmp_path):
    proc = _run(tmp_path, "0", "2", "-m", MODEL, "-pt", "1,4,5,8",
                "-q", "8,0", "-b", "4", "-u", "2")
    assert proc.returncode == 0, proc.stderr
    assert _throughput(proc) > 0


def test_midblock_partition_host(tmp_path):
    """Sublayer (mid-block) cuts: 2-tensor payload across the edge."""
    proc = _run(tmp_path, "0", "3", "-m", MODEL, "-pt", "1,1,2,5,6,8",
                "-b", "4", "-u", "2")
    assert proc.returncode == 0, proc.stderr


def test_spmd_driver(tmp_path):
    proc = _run(tmp_path, "0", "2", "-m", MODEL, "-pt", "1,4,5,8",
                "-c", "spmd", "-b", "4", "-u", "2")
    assert proc.returncode == 0, proc.stderr
    assert _throughput(proc) > 0


def test_spmd_falls_back_on_midblock_cut(tmp_path):
    proc = _run(tmp_path, "0", "2", "-m", MODEL, "-pt", "1,5,6,8",
                "-c", "spmd", "-b", "4", "-u", "2")
    assert proc.returncode == 0, proc.stderr
    assert "falling back to host driver" in proc.stderr + proc.stdout


def test_gpt2_host_and_spmd(tmp_path):
    """Causal-decoder family end-to-end through the runtime CLI: 2-stage
    host driver with a quantized edge, then the SPMD driver."""
    proc = _run(tmp_path, "0", "2", "-m", "pipeedge/test-tiny-gpt2",
                "-pt", "1,4,5,8", "-q", "8,0", "-b", "4", "-u", "2")
    assert proc.returncode == 0, proc.stderr
    assert _throughput(proc) > 0
    proc = _run(tmp_path, "0", "2", "-c", "spmd",
                "-m", "pipeedge/test-tiny-gpt2", "-pt", "1,4,5,8",
                "-b", "4", "-u", "2")
    assert proc.returncode == 0, proc.stderr
    assert _throughput(proc) > 0


def test_nonzero_rank_exits(tmp_path):
    proc = _run(tmp_path, "1", "2", "-m", MODEL)
    assert proc.returncode == 0


@pytest.mark.skipif(
    not (os.path.exists(_REPO_BUILD_PATHS[0]) or shutil.which("sched-pipeline")),
    reason="sched-pipeline binary not built")
def test_scheduler_driven_partition(tmp_path):
    # synthetic profile files for the tiny model over 2 identical chips
    n = 8
    models = {MODEL: {"layers": n, "parameters_in": 768,
                      "parameters_out": [1000] * n, "mem_MB": [1.0] * n}}
    types = {"chip": {"mem_MB": 1024, "bw_Mbps": 10000, "model_profiles": {
        MODEL: [{"dtype": "torch.float32", "batch_size": 2,
                 "time_s": [0.01] * n}]}}}
    devs = {"chip": ["0", "1"]}
    for fname, data in (("models.yml", models), ("device_types.yml", types),
                        ("devices.yml", devs)):
        with open(tmp_path / fname, "w") as f:
            yaml.safe_dump(data, f, default_flow_style=None)
    proc = _run(tmp_path, "0", "2", "-m", MODEL, "-u", "2", "-b", "4",
                "-sm", "models.yml", "-sdt", "device_types.yml",
                "-sd", "devices.yml")
    assert proc.returncode == 0, proc.stderr
    assert _throughput(proc) > 0


def test_per_edge_send_telemetry_csvs(tmp_path):
    """Each inter-stage edge gets its own send telemetry key/CSV with real
    wire bytes per microbatch (VERDICT r1 #1): the 8-bit quantized edge 0
    reports far fewer Mbits than the raw mid-block edge 1."""
    import csv as csvmod
    proc = _run(tmp_path, "0", "3", "-m", MODEL, "-pt", "1,4,5,6,7,8",
                "-q", "8,0,0", "-b", "8", "-u", "2")
    assert proc.returncode == 0, proc.stderr
    rows = {}
    for key in ("send0", "send1", "send"):
        f = tmp_path / f"{key}.csv"
        assert f.exists(), f"missing {key}.csv"
        with open(f) as fh:
            rows[key] = list(csvmod.DictReader(fh))
    # 4 microbatches -> >=3 beats per edge (the first call starts the clock)
    assert len(rows["send0"]) >= 3
    assert len(rows["send0"]) == len(rows["send1"])
    w0 = float(rows["send0"][-1]["Work"])
    w1 = float(rows["send1"][-1]["Work"])
    assert 0 < w0 < w1 / 3


def test_adaptive_quant_heuristic(tmp_path):
    proc = _run(tmp_path, "0", "2", "-m", MODEL, "-pt", "1,4,5,8",
                "-q", "8,0", "-b", "12", "-u", "2",
                env_extra={"ADAPTIVE_QUANT": "HEURISTIC",
                           "SEND_CONSTRAINT": "100", "WINDOW_SIZE": "3"})
    assert proc.returncode == 0, proc.stderr
    assert "Adaptive quantization" in proc.stderr + proc.stdout


def test_adaptive_quant_controller(tmp_path):
    proc = _run(tmp_path, "0", "2", "-m", MODEL, "-pt", "1,4,5,8",
                "-q", "8,0", "-b", "12", "-u", "2",
                env_extra={"ADAPTIVE_QUANT": "CONTROLLER",
                           "SEND_CONSTRAINT": "50", "WINDOW_SIZE": "3"})
    assert proc.returncode == 0, proc.stderr
    assert "Adaptive quantization" in proc.stderr + proc.stdout


def test_runtime_spmd_dp_tp_mesh(tmp_path):
    """CLI spmd driver over a stages x dp x tp mesh (one XLA program)."""
    import subprocess
    import sys
    env = dict(os.environ, PYTHONPATH=REPO,
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "runtime.py"), "0", "8",
         "--platform", "cpu", "-c", "spmd", "-m", "pipeedge/test-tiny-vit",
         "-b", "16", "-u", "4", "-pt", "1,4,5,8", "-q", "8,0",
         "--spmd-dp", "2", "--spmd-tp", "2"],
        capture_output=True, env=env, cwd=str(tmp_path), text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "latency_sec=" in proc.stdout


def test_runtime_spmd_sp_mesh(tmp_path):
    """CLI spmd driver with sequence parallelism inside pipeline stages
    (ring attention over 'sp'; BERT synthetic tokens, seq divisible)."""
    import subprocess
    import sys
    env = dict(os.environ, PYTHONPATH=REPO,
               XLA_FLAGS="--xla_force_host_platform_device_count=4")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "runtime.py"), "0", "4",
         "--platform", "cpu", "-c", "spmd", "-m", "pipeedge/test-tiny-bert",
         "-b", "8", "-u", "4", "-pt", "1,4,5,8", "--spmd-sp", "2"],
        capture_output=True, env=env, cwd=str(tmp_path), text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "latency_sec=" in proc.stdout


def test_measure_rounds_reports_cold_and_warm(tmp_path):
    """--measure-rounds N re-runs the ubatch stream, printing a latency
    line per round (round 0 pays the XLA compiles) ahead of the final
    plain stats line; results/accuracy counting stays per-round exact."""
    proc = _run(tmp_path, "0", "1", "-m", MODEL, "-b", "4", "-u", "2",
                "--measure-rounds", "3")
    assert proc.returncode == 0, proc.stderr
    rounds = [line for line in proc.stdout.splitlines()
              if line.startswith("round=")]
    assert [line.split()[0] for line in rounds] == \
        ["round=0", "round=1", "round=2"]
    # the final plain line repeats the LAST round's numbers
    assert _throughput(proc) == float(
        rounds[-1].split("throughput_items_sec=")[1])
