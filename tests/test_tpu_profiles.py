"""Committed real-chip (TPU v5e) profiles drive the native scheduler:
profile -> models.yml/device_types.yml -> sched-pipeline DP partition
(BASELINE.md config 3), no TPU needed at test time.

Skipped until profiles/tpu/*.yml are generated on the chip
(profiles/README.md recipe), and skipped wherever the native
`sched-pipeline` binary can neither be found nor built — CI runners
without cmake/ninja would otherwise fail here on every run
(tests/README_TESTS.md documents the native build).
"""
import os
import shutil

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROF = os.path.join(REPO, "profiles", "tpu")
FILES = {name: os.path.join(PROF, name)
         for name in ("models.yml", "device_types.yml", "devices.yml")}


def _native_binary_present() -> bool:
    """The prebuilt binary exists, or the toolchain to build it does
    (build_native needs BOTH cmake and ninja — native/CMakeLists.txt
    is generated with -G Ninja)."""
    if os.path.exists(os.path.join(REPO, "native", "build",
                                   "sched-pipeline")):
        return True
    return bool(shutil.which("cmake") and shutil.which("ninja"))


pytestmark = [
    pytest.mark.skipif(
        not all(os.path.exists(p) for p in FILES.values()),
        reason="TPU profile fixtures not generated yet "
               "(profiles/README.md)"),
    pytest.mark.skipif(
        not _native_binary_present(),
        reason="native sched-pipeline binary unbuilt and no cmake+ninja "
               "toolchain to build it (tests/README_TESTS.md)"),
]


@pytest.fixture(scope="module")
def native_sched():
    from pipeedge_tpu.sched import scheduler
    scheduler.build_native()
    return scheduler


@pytest.mark.parametrize("model,layers", [
    ("google/vit-base-patch16-224", 48),
    ("google/vit-large-patch16-224", 96),
    ("bert-base-uncased", 48),
    ("facebook/deit-base-distilled-patch16-224", 48),
    ("gpt2", 48),
])
def test_sched_pipeline_on_tpu_profiles(native_sched, model, layers):
    """The DP scheduler produces a full-coverage 4-stage partition over four
    identical tpu-v5e devices from the committed chip profiles."""
    sched = native_sched.sched_pipeline(
        model, 2, 2, 8, dtype="bfloat16",
        models_file=FILES["models.yml"],
        dev_types_file=FILES["device_types.yml"],
        dev_file=FILES["devices.yml"])
    assert sched, "no viable schedule from the chip profiles"
    covered = []
    hosts = []
    for stage in sched:
        for host, (l, r) in ((h, tuple(v)) for h, v in stage.items()):
            covered.extend(range(l, r + 1))
            hosts.append(host)
    assert covered == list(range(1, layers + 1))
    assert len(hosts) == len(set(hosts))  # one stage per device
    # identical devices + negligible comm time at 100 Gbps -> the
    # throughput-optimal partition uses all four devices, roughly balanced
    assert len(sched) == 4, sched
    sizes = [len(range(tuple(v)[0], tuple(v)[1] + 1))
             for stage in sched for v in stage.values()]
    assert max(sizes) - min(sizes) <= layers // 4, sizes
