"""The fleet observatory's unit matrix (ISSUE 18): the Prometheus text
parser + fleet collector aggregation, the SLO burn-rate engine's window
math and edge-triggered breach, exemplar retention under a racing
scrape, rid-tree resolution (flight.trace_slice + report.
request_timeline), and the /debug/spans drain payload — all through
injected fetch/now, no sockets (the process-level acceptance lives in
test_router_fleet.py).
"""
import threading

import pipeedge_tpu.telemetry as telemetry
from pipeedge_tpu.telemetry import collector as fc
from pipeedge_tpu.telemetry import flight
from pipeedge_tpu.telemetry import metrics as prom
from pipeedge_tpu.telemetry import report


# ---------------------------------------------------------------------------
# prometheus text parsing
# ---------------------------------------------------------------------------

def _replica_text(ok=5.0, shed=1.0, queue=2.0, exemplars=()):
    """A synthetic replica /metrics document rendered by the REAL
    instrument classes — the parser is tested against the actual
    exposition, not a hand-written imitation of it."""
    reg = prom.Registry()
    c = reg.counter(fc.CLASS_FAMILY, "requests by class and outcome")
    c.inc(ok, **{"class": "interactive", "outcome": "ok"})
    c.inc(shed, **{"class": "interactive", "outcome": "shed"})
    reg.gauge(fc.QUEUE_FAMILY, "queue depth").set(queue)
    h = reg.histogram(fc.LATENCY_FAMILY, "latency", buckets=(0.1, 1.0))
    for value, rid in exemplars:
        h.observe(value, exemplar=rid)
    return reg.render()


def test_parse_prom_text_families_and_labels():
    fams = fc.parse_prom_text(_replica_text(ok=7, shed=3, queue=4))
    rows = fams[fc.CLASS_FAMILY]
    by = {(d["class"], d["outcome"]): v for d, v in rows}
    assert by[("interactive", "ok")] == 7.0
    assert by[("interactive", "shed")] == 3.0
    assert fams[fc.QUEUE_FAMILY][0][1] == 4.0


def test_parse_prom_text_skips_garbage_and_filters():
    text = "# HELP x y\nnot a metric line !!\nfoo_total 3\nbar_total 4\n"
    fams = fc.parse_prom_text(text, families=("foo_total",))
    assert set(fams) == {"foo_total"}
    assert fams["foo_total"] == [({}, 3.0)]


# ---------------------------------------------------------------------------
# SLO burn-rate engine
# ---------------------------------------------------------------------------

def _engine(**kw):
    kw.setdefault("objective", 0.99)
    kw.setdefault("fast_window_s", 30.0)
    kw.setdefault("slow_window_s", 300.0)
    kw.setdefault("threshold", 10.0)
    kw.setdefault("registry", prom.Registry())
    return fc.BurnRateEngine(**kw)


def test_burn_gauge_matrix_predeclared_at_zero():
    reg = prom.Registry()
    _engine(registry=reg)
    text = reg.render()
    for cls in fc.REQUEST_CLASSES:
        for window in fc.BURN_WINDOWS:
            assert (f'pipeedge_slo_burn_rate{{class="{cls}",'
                    f'window="{window}"}} 0') in text


def test_burn_zero_on_clean_traffic():
    eng = _engine()
    eng.update({"interactive": (0.0, 0.0)}, now=0.0)
    burns = eng.update({"interactive": (50.0, 50.0)}, now=10.0)
    assert burns["interactive"] == {"short": 0.0, "long": 0.0}
    assert eng.gauge.value(**{"class": "interactive",
                              "window": "short"}) == 0.0


def test_burn_math_all_bad_window():
    # 100% bad over the window against a 1% budget -> burn rate 100
    eng = _engine()
    eng.update({"interactive": (0.0, 0.0)}, now=0.0)
    burns = eng.update({"interactive": (0.0, 10.0)}, now=10.0)
    assert abs(burns["interactive"]["short"] - 100.0) < 1e-9
    assert abs(eng.gauge.value(**{"class": "interactive",
                                  "window": "short"}) - 100.0) < 1e-3


def test_burn_windows_diverge_after_recovery():
    """A burst of errors ages out of the short window while the long
    window still remembers it — the classic fast-page/slow-confirm
    split."""
    eng = _engine(fast_window_s=30.0, slow_window_s=300.0)
    eng.update({"interactive": (0.0, 0.0)}, now=0.0)
    eng.update({"interactive": (0.0, 10.0)}, now=10.0)    # all bad
    # 100 clean requests later, 60 s on: the short window sees only
    # clean traffic, the long window still covers the burst
    burns = eng.update({"interactive": (100.0, 110.0)}, now=70.0)
    assert burns["interactive"]["short"] == 0.0
    assert burns["interactive"]["long"] > 0.0


def test_burn_breach_fires_once_per_episode():
    fired = []
    eng = _engine(on_breach=lambda cls, burn: fired.append((cls, burn)))
    eng.update({"interactive": (0.0, 0.0)}, now=0.0)
    eng.update({"interactive": (0.0, 10.0)}, now=5.0)     # breach
    eng.update({"interactive": (0.0, 20.0)}, now=10.0)    # still breached
    assert [cls for cls, _ in fired] == ["interactive"]
    assert fired[0][1] > eng.threshold
    # recovery re-arms: only clean deltas inside the short window
    eng.update({"interactive": (500.0, 520.0)}, now=50.0)
    eng.update({"interactive": (1000.0, 1020.0)}, now=78.0)
    # second episode fires again
    eng.update({"interactive": (1000.0, 1100.0)}, now=100.0)
    assert len(fired) == 2


def test_counts_from_counter_matches_families():
    reg = prom.Registry()
    c = reg.counter(fc.CLASS_FAMILY, "h")
    c.inc(4, **{"class": "batch", "outcome": "ok"})
    c.inc(2, **{"class": "batch", "outcome": "deadline"})
    via_counter = fc.BurnRateEngine.counts_from_counter(c)
    via_text = fc.BurnRateEngine.counts_from_families(
        fc.parse_prom_text(reg.render()))
    assert via_counter["batch"] == (4.0, 6.0)
    assert via_counter == via_text


# ---------------------------------------------------------------------------
# fleet collector
# ---------------------------------------------------------------------------

def _collector(texts, burn=None):
    """Collector over an injectable text table: {name: text}. Mutate
    `texts` between scrapes to advance the fake fleet's counters."""
    urls = {name: f"http://{name}.test" for name in texts}

    def fetch(url, timeout):
        for name, base in urls.items():
            if url.startswith(base):
                doc = texts[name]
                if isinstance(doc, Exception):
                    raise doc
                return doc
        raise OSError(f"unknown target {url}")

    return fc.FleetCollector(lambda: dict(urls), interval_s=1.0,
                             history=16, fetch_fn=fetch, burn=burn)


def test_collector_aggregates_two_replicas():
    texts = {"r0": _replica_text(ok=10, shed=0, queue=1),
             "r1": _replica_text(ok=20, shed=10, queue=3)}
    col = _collector(texts)
    assert col.scrape_once(now=0.0) == 2
    texts["r0"] = _replica_text(ok=20, shed=0, queue=1)
    texts["r1"] = _replica_text(ok=40, shed=20, queue=3)
    assert col.scrape_once(now=10.0) == 2
    snap = col.fleet_snapshot(now=10.0)
    assert set(snap["replicas"]) == {"r0", "r1"}
    assert all(rec["ok"] for rec in snap["replicas"].values())
    cls = snap["classes"]["interactive"]
    # cumulative: latest good sample summed across replicas
    assert cls["ok_total"] == 60.0 and cls["requests_total"] == 80.0
    # windowed: (10 + 20) good over the 10 s window
    assert cls["goodput_rps"] == 3.0
    assert cls["shed_rps"] == 1.0
    assert cls["window_attainment"] == round(30 / 40, 4)
    assert snap["queue_depth"] == 4.0
    assert snap["replicas"]["r1"]["goodput_rps"]["interactive"] == 2.0


def test_collector_counts_scrape_errors_and_keeps_serving():
    texts = {"r0": _replica_text(), "r1": OSError("connection refused")}
    col = _collector(texts)
    assert col.scrape_once(now=0.0) == 1
    snap = col.fleet_snapshot(now=1.0)
    assert snap["scrape_errors"] == 1
    assert snap["replicas"]["r0"]["ok"] is True
    assert snap["replicas"]["r1"]["ok"] is False
    # the dead target's ring still exists (health visibility), and the
    # live one's numbers still aggregate
    assert snap["classes"]["interactive"]["requests_total"] == 6.0


def test_collector_feeds_burn_engine_with_fleet_counts():
    texts = {"r0": _replica_text(ok=0, shed=0),
             "r1": _replica_text(ok=0, shed=0)}
    fired = []
    burn = _engine(on_breach=lambda cls, b: fired.append(cls))
    col = _collector(texts, burn=burn)
    col.scrape_once(now=0.0)
    texts["r0"] = _replica_text(ok=0, shed=50)      # overload on r0
    texts["r1"] = _replica_text(ok=0, shed=50)
    col.scrape_once(now=10.0)
    assert fired == ["interactive"]
    snap = col.fleet_snapshot(now=10.0)
    assert snap["slo"]["burn_rate"]["interactive"]["short"] > burn.threshold


def test_collector_exemplar_union_roundtrips_parse_exemplars():
    texts = {"r0": _replica_text(exemplars=[(0.05, "q1"), (5.0, "q2")]),
             "r1": _replica_text(exemplars=[(0.08, "q9")])}
    col = _collector(texts)
    col.scrape_once(now=0.0)
    snap = col.fleet_snapshot(now=0.0)
    # union keeps the max-value exemplar per bucket across replicas
    by_le = {row["le"]: row for row in snap["exemplars"]}
    assert by_le["0.1"]["trace_id"] == "q9"
    assert by_le["+Inf"]["trace_id"] == "q2"
    parsed = prom.parse_exemplars(snap["exemplars_text"],
                                  fc.LATENCY_FAMILY)
    assert sorted(parsed, key=lambda r: r["le"]) == \
        sorted(snap["exemplars"], key=lambda r: r["le"])


# ---------------------------------------------------------------------------
# exemplar retention under a racing scrape (satellite: the Histogram
# render fix — one lock acquisition for counts + exemplars)
# ---------------------------------------------------------------------------

def test_histogram_exemplar_lines_consistent_under_concurrent_scrape():
    """Rollover during an in-flight render must never drop or duplicate
    `# EXEMPLAR` lines: every render sees at most ONE line per (label
    set, le) bucket and each line parses back. A tiny exemplar window
    forces constant rollover while a writer hammers observe()."""
    h = prom.Histogram("race_latency_seconds", "h", buckets=(0.1, 1.0),
                       exemplar_window_s=0.0005)
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            h.observe(0.05 if i % 3 else 5.0, exemplar=f"q{i}")
            i += 1

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    try:
        for _ in range(200):
            text = "\n".join(h.render())
            ex_lines = [ln for ln in text.splitlines()
                        if ln.startswith("# EXEMPLAR")]
            keys = [ln.split(" {trace_id=", 1)[0] for ln in ex_lines]
            assert len(keys) == len(set(keys)), keys
            parsed = prom.parse_exemplars(text, "race_latency_seconds")
            assert len(parsed) == len(ex_lines)
            for row in parsed:
                assert row["le"] in ("0.1", "1", "+Inf")
    finally:
        stop.set()
        t.join(timeout=5)


def test_render_exemplar_lines_roundtrip():
    rows = [{"le": "0.1", "trace_id": "R3.fo1", "value": 0.07},
            {"le": "+Inf", "trace_id": "R8", "value": 12.5}]
    text = "\n".join(fc.render_exemplar_lines("f_seconds", rows))
    assert prom.parse_exemplars(text, "f_seconds") == rows


# ---------------------------------------------------------------------------
# rid-tree resolution
# ---------------------------------------------------------------------------

def test_rid_tree_member_grammar():
    assert flight.rid_tree_member("R4", "R4")
    assert flight.rid_tree_member("R4.t2", "R4")
    assert flight.rid_tree_member("R4.hedge.t1", "R4")
    assert flight.rid_tree_member("R4.fo1", "R4")
    assert not flight.rid_tree_member("R40", "R4")     # no prefix bleed
    assert not flight.rid_tree_member("R5", "R4")
    assert not flight.rid_tree_member(None, "R4")


def _span(rid, rank=0, t0=0, t1=1_000_000, cat="router",
          name="dispatch:r0", mb=None):
    return {"cat": cat, "name": name, "rank": rank, "stage": None,
            "mb": mb, "t0": t0, "t1": t1, "rid": rid}


def test_trace_slice_includes_derived_rids():
    spans = [_span("R1"), _span("R1.fo1", rank=1),
             _span("R10", rank=2), _span("R2")]
    rids = {s["rid"] for s in flight.trace_slice(spans, "R1")}
    assert rids == {"R1", "R1.fo1"}


def test_request_timeline_resolves_tree_across_ranks():
    spans = [
        _span("R7", rank=0, t0=0, t1=4_000_000, name="stream:r0"),
        _span("R7", rank=1, t0=500_000, t1=2_000_000, cat="serve",
              name="request"),
        _span("R7.fo1", rank=0, t0=4_000_000, t1=9_000_000,
              name="stream:r1"),
        _span("R7.fo1", rank=2, t0=4_500_000, t1=8_500_000, cat="serve",
              name="request"),
        _span("R8", rank=0, t0=0, t1=1_000_000),     # different request
    ]
    rec = report.request_timeline(spans, "R7")
    assert rec["found"] and rec["spans"] == 4
    assert rec["rids"] == ["R7", "R7.fo1"]
    assert rec["ranks"] == [0, 1, 2]     # router + both replicas
    assert "route/r0" in rec["segments"] and "route/r1" in rec["segments"]
    # exact-match mode pins the base rid only
    assert report.request_timeline(spans, "R7", tree=False)["spans"] == 2


# ---------------------------------------------------------------------------
# /debug/spans payload
# ---------------------------------------------------------------------------

def test_debug_spans_payload_drains_ring_once():
    telemetry.configure(rank=3)
    try:
        telemetry.record("router", "dispatch:r0", 10, 20, rid="R1")
        body = fc.debug_spans_payload(drain=True)
        assert body["enabled"] and body["rank"] == 3
        assert [s["rid"] for s in body["spans"]] == ["R1"]
        assert body["t_recv_ns"] <= body["t_send_ns"]
        # drained: a second federating fetch sees only newer spans
        assert fc.debug_spans_payload(drain=True)["spans"] == []
        # peek mode leaves the ring alone
        telemetry.record("router", "dispatch:r1", 30, 40, rid="R2")
        assert len(fc.debug_spans_payload(drain=False)["spans"]) == 1
        assert len(fc.debug_spans_payload(drain=False)["spans"]) == 1
    finally:
        telemetry.disable()
