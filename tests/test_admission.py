"""Overload-protection plane (docs/SERVING.md): admission control,
deadline propagation, and the brownout ladder.

Units cover the mechanisms in isolation (token bucket, EDF queue,
service-rate estimator, admission controller, ladder hysteresis,
executor deadline-expiry cancellation); the fleet tests prove the wired
plane under fire — 5x sustained overload on a loopback serve.py keeps
interactive goodput while shedding the excess with 503 + a DYNAMIC
Retry-After, expires work mid-flight via the executor `cancel` flag
(HTTP 504), steps the brownout ladder up and back down, and survives an
overload window that overlaps a rank death (the /degraded lifecycle the
chaos orchestrator drives) without deadlock.
"""
import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MODEL = "pipeedge/test-tiny-gpt2"

from pipeedge_tpu.serving import (AdmissionController, AdmissionShed,  # noqa: E402
                                  BrownoutLadder, EDFQueue,
                                  ServiceRateEstimator, TokenBucket,
                                  Watermarks, default_policies)
from pipeedge_tpu.telemetry import metrics as prom  # noqa: E402


# ---------------------------------------------------------------------------
# token bucket
# ---------------------------------------------------------------------------

def test_token_bucket_refill_and_burst_cap():
    b = TokenBucket(rate=2.0, burst=4.0, now=0.0)
    # burst capacity available immediately
    for _ in range(4):
        assert b.try_take(now=0.0)
    assert not b.try_take(now=0.0)           # empty
    assert b.try_take(now=0.5)               # 0.5s * 2/s = 1 token back
    assert not b.try_take(now=0.5)
    # refill never exceeds burst
    assert b.try_take(now=100.0)
    assert b.tokens == pytest.approx(3.0)

    with pytest.raises(ValueError):
        TokenBucket(rate=0.0, burst=1.0)
    with pytest.raises(ValueError):
        TokenBucket(rate=1.0, burst=0.0)


def test_default_policies_reject_zero_rate():
    # 0 must not silently mean "unlimited" (shed via brownout to block)
    with pytest.raises(ValueError, match="rate must be > 0"):
        default_policies(rates={"best_effort": 0.0})
    assert default_policies(rates={"batch": 2.5})["batch"].rate == 2.5


# ---------------------------------------------------------------------------
# EDF queue
# ---------------------------------------------------------------------------

def test_edf_queue_pops_earliest_deadline_first():
    q = EDFQueue(capacity=8)
    q.push("d5", 5.0)
    q.push("d1", 1.0)
    q.push("forever", None)                  # None sorts last
    q.push("d3", 3.0)
    assert len(q) == 4
    assert [q.pop()[0] for _ in range(4)] == ["d1", "d3", "d5", "forever"]
    assert q.pop() is None


def test_edf_queue_shed_on_full_evicts_latest_deadline():
    q = EDFQueue(capacity=2)
    assert q.push("a", 5.0) is None
    assert q.push("b", 1.0) is None
    # full: the new earlier-deadline arrival evicts the latest ("a")
    assert q.push("c", 3.0) == "a"
    # full: an arrival that IS the latest deadline is itself shed
    assert q.push("worst", 10.0) == "worst"
    assert q.push("never", None) == "never"  # None = latest of all
    assert [q.pop()[0] for _ in range(2)] == ["b", "c"]

    with pytest.raises(ValueError):
        EDFQueue(capacity=0)


def test_edf_queue_pop_expired_and_remove():
    q = EDFQueue(capacity=8)
    q.push("old", 1.0)
    q.push("older", 0.5)
    q.push("live", 9.0)
    q.push("forever", None)
    assert sorted(q.pop_expired(now=2.0)) == ["old", "older"]
    assert len(q) == 2
    assert q.remove("live")
    assert not q.remove("live")              # already gone
    assert q.pop()[0] == "forever"


# ---------------------------------------------------------------------------
# service-rate estimator (the dynamic Retry-After)
# ---------------------------------------------------------------------------

def test_service_rate_estimator_rate_and_retry_after():
    est = ServiceRateEstimator(halflife_s=10.0)
    assert est.rate() is None
    assert est.retry_after(3, fallback=7.0) == 7.0   # no data yet: fallback
    for t in (0.0, 1.0, 2.0, 3.0):
        est.observe(now=t)
    assert est.rate() == pytest.approx(1.0, rel=0.05)
    # "come back when the backlog you'd join has drained": (4+1)/1 = 5s
    assert est.retry_after(4) == pytest.approx(5.0, rel=0.05)
    # clamped at both ends
    assert est.retry_after(0, lo=2.0) == 2.0
    assert est.retry_after(10_000, hi=60.0) == 60.0


def test_percentile_from_counts_window_math():
    buckets = (0.1, 1.0, 10.0)
    assert prom.percentile_from_counts(buckets, [5, 4, 1], 10, 50.0) == 0.1
    assert prom.percentile_from_counts(buckets, [5, 4, 1], 10, 95.0) == 10.0
    # observations beyond the last bound live only in n: overflow -> inf
    assert prom.percentile_from_counts(buckets, [1, 0, 0], 5, 95.0) \
        == float("inf")
    assert prom.percentile_from_counts(buckets, [0, 0, 0], 0, 95.0) is None


# ---------------------------------------------------------------------------
# admission controller
# ---------------------------------------------------------------------------

def _controller(**kw):
    kw.setdefault("registry", prom.Registry())
    return AdmissionController(**kw)


def test_admission_immediate_grant_and_release():
    c = _controller(concurrency=2)
    t1 = c.admit("interactive")
    t2 = c.admit("batch")
    assert c.in_flight == 2 and c.queue_depth == 0
    with pytest.raises(KeyError):
        c.admit("no-such-class")
    c.release(t1)
    c.release(t2)
    assert c.in_flight == 0


def test_admission_grants_in_edf_order():
    c = _controller(concurrency=1)
    holder = c.admit("interactive")
    order = []
    now = time.monotonic()

    def waiter(name, deadline_s):
        t = c.admit("interactive", deadline=now + deadline_s)
        order.append(name)
        c.release(t)

    late = threading.Thread(target=waiter, args=("late", 30.0))
    early = threading.Thread(target=waiter, args=("early", 10.0))
    late.start()
    time.sleep(0.2)          # "late" queues first, but...
    early.start()
    time.sleep(0.2)
    c.release(holder)        # ...the grant goes to the EARLIER deadline
    late.join(timeout=30)
    early.join(timeout=30)
    assert order == ["early", "late"]


def test_admission_queue_full_sheds_latest_deadline():
    c = _controller(concurrency=1, queue_capacity=1)
    holder = c.admit("interactive")
    now = time.monotonic()
    shed = {}

    def waiter(name, deadline_s):
        try:
            t = c.admit("batch", deadline=now + deadline_s)
            c.release(t)
        except AdmissionShed as exc:
            shed[name] = exc

    far = threading.Thread(target=waiter, args=("far", 60.0))
    far.start()
    time.sleep(0.2)
    # queue full with "far": a later-deadline arrival is itself shed...
    with pytest.raises(AdmissionShed) as err:
        c.admit("batch", deadline=now + 120.0)
    assert err.value.reason == "queue_full"
    assert err.value.retry_after > 0
    # ...while an earlier-deadline arrival evicts "far" from the queue
    near = threading.Thread(target=waiter, args=("near", 10.0))
    near.start()
    far.join(timeout=30)
    assert shed["far"].reason == "queue_full"
    c.release(holder)
    near.join(timeout=30)
    assert "near" not in shed
    assert c.m_shed.value(**{"class": "batch", "reason": "queue_full"}) == 2


def test_admission_rate_limit_and_brownout_shed():
    c = _controller(concurrency=4,
                    policies=default_policies(rates={"batch": 1.0}))
    t = c.admit("batch")                     # burst of 1
    with pytest.raises(AdmissionShed) as err:
        c.admit("batch")
    assert err.value.reason == "rate"
    c.release(t)
    # brownout: listed classes shed at the door, others unaffected
    c.set_shed_classes({"best_effort"})
    with pytest.raises(AdmissionShed) as err:
        c.admit("best_effort")
    assert err.value.reason == "brownout"
    c.release(c.admit("interactive"))
    c.set_shed_classes(())
    c.release(c.admit("best_effort"))


def test_admission_expired_in_queue_sheds_not_grants():
    c = _controller(concurrency=1)
    holder = c.admit("interactive")
    # deadline passes while queued: the waiter withdraws and sheds
    with pytest.raises(AdmissionShed) as err:
        c.admit("interactive", deadline=time.monotonic() + 0.3)
    assert err.value.reason == "expired"
    # an ALREADY-expired deadline is refused without queueing
    with pytest.raises(AdmissionShed) as err:
        c.admit("interactive", deadline=time.monotonic() - 1.0)
    assert err.value.reason == "expired"
    assert c.queue_depth == 0
    c.release(holder)


def test_admission_release_sheds_expired_queue_heads():
    """A release pops expired waiters as sheds instead of granting work
    that would only 504 mid-flight."""
    c = _controller(concurrency=1)
    holder = c.admit("interactive")
    outcome = {}

    def waiter():
        try:
            outcome["t"] = c.admit("interactive",
                                   deadline=time.monotonic() + 5.0)
        except AdmissionShed as exc:
            outcome["shed"] = exc.reason

    w = threading.Thread(target=waiter)
    w.start()
    time.sleep(0.3)                  # let it queue (own timeout far off)
    # injectable now: the release's grant pass sees the deadline as
    # already lapsed and sheds instead of granting
    c.release(holder, now=time.monotonic() + 10.0)
    w.join(timeout=30)
    assert outcome.get("shed") == "expired"
    assert c.in_flight == 0 and c.queue_depth == 0


def test_admission_close_sheds_waiters_with_shutdown():
    c = _controller(concurrency=1)
    holder = c.admit("interactive")
    outcome = {}

    def waiter():
        try:
            c.admit("batch")
        except AdmissionShed as exc:
            outcome["reason"] = exc.reason

    w = threading.Thread(target=waiter)
    w.start()
    time.sleep(0.2)
    c.close()
    w.join(timeout=30)
    assert outcome["reason"] == "shutdown"
    with pytest.raises(AdmissionShed) as err:
        c.admit("interactive")
    assert err.value.reason == "shutdown"
    del holder


def test_admission_retry_after_tracks_service_rate():
    c = _controller(concurrency=1, retry_after_fallback=9.0)
    assert c.retry_after() == 9.0            # no completions yet: fallback
    now = time.monotonic()
    for i in range(5):                       # 10 completions/s observed
        c.release(c.admit("interactive"), now=now + i * 0.1)
    ra_idle = c.retry_after(backlog=0)
    ra_deep = c.retry_after(backlog=50)
    assert ra_idle < ra_deep                 # deeper backlog -> later retry
    assert ra_deep == pytest.approx(51 / 10.0, rel=0.5)


def test_admission_metrics_and_snapshot():
    reg = prom.Registry()
    c = _controller(concurrency=1, registry=reg)
    c.release(c.admit("interactive"))
    with pytest.raises(AdmissionShed):
        c.admit("interactive", deadline=time.monotonic() - 1.0)
    snap = c.snapshot()
    assert snap["in_flight"] == 0 and snap["queue_depth"] == 0
    assert snap["shed_total"] == 1 and snap["concurrency"] == 1
    text = reg.render()
    # the full (class, reason) matrix renders from the first scrape
    assert ('pipeedge_requests_shed_total{class="interactive",'
            'reason="expired"} 1') in text
    assert ('pipeedge_requests_shed_total{class="best_effort",'
            'reason="brownout"} 0') in text
    assert ('pipeedge_admission_latency_seconds_count'
            '{class="interactive"} 1') in text
    assert "pipeedge_admission_queue_depth 0" in text


# ---------------------------------------------------------------------------
# brownout ladder
# ---------------------------------------------------------------------------

def _ladder(**kw):
    kw.setdefault("registry", prom.Registry())
    kw.setdefault("marks", Watermarks(queue_high=4, queue_low=1,
                                      p95_high_s=1.0, p95_low_s=0.2,
                                      dwell_up_s=1.0, dwell_down_s=2.0))
    return BrownoutLadder(**kw)


def test_brownout_steps_up_one_rung_per_dwell():
    lad = _ladder()
    assert lad.update(10, None, now=0.0) == 0     # hot, but dwelling
    assert lad.update(10, None, now=0.5) == 0
    assert lad.update(10, None, now=1.0) == 1     # dwell_up_s elapsed
    assert lad.update(10, None, now=1.5) == 1     # re-armed: one per dwell
    assert lad.update(10, None, now=2.0) == 2
    assert lad.update(0, 5.0, now=3.0) == 3       # p95 alone is hot too
    assert lad.level_name == "evict_cold_pages"
    assert lad.update(10, None, now=4.0) == 4
    assert lad.level_name == "colocate_prefill"
    assert lad.update(10, None, now=5.0) == 5
    assert lad.update(10, None, now=6.0) == 6
    assert lad.update(10, None, now=9.0) == 6     # capped at max rung
    assert lad.level_name == "shed_batch"


def test_brownout_steps_down_with_hysteresis():
    lad = _ladder()
    lad.update(10, None, now=0.0)
    lad.update(10, None, now=1.0)
    lad.update(10, None, now=2.0)
    assert lad.level == 2
    # calm must persist dwell_down_s (2s) per rung
    assert lad.update(0, 0.1, now=2.5) == 2
    assert lad.update(0, 0.1, now=4.5) == 1
    assert lad.update(0, 0.1, now=5.0) == 1
    assert lad.update(0, 0.1, now=6.5) == 0
    # an idle window (no p95) counts as calm, not hot
    assert lad.update(0, None, now=7.0) == 0


def test_brownout_between_marks_holds_and_resets_dwells():
    lad = _ladder()
    lad.update(10, None, now=0.0)
    lad.update(10, None, now=1.0)
    assert lad.level == 1
    # queue between low and high: hold the rung, restart BOTH dwells
    assert lad.update(2, 0.5, now=1.5) == 1
    assert lad.update(10, None, now=2.0) == 1     # hot dwell restarted
    assert lad.update(10, None, now=3.0) == 2
    assert lad.update(0, 0.1, now=3.5) == 2
    assert lad.update(2, 0.5, now=4.0) == 2       # calm dwell restarted
    assert lad.update(0, 0.1, now=4.5) == 2
    assert lad.update(0, 0.1, now=6.5) == 1


def test_brownout_lifecycle_floor_and_effects():
    lad = _ladder()
    assert lad.allow_speculative()
    # healing implies at least rung 1, whatever the watermarks say
    assert lad.set_floor(1) == 1
    assert not lad.allow_speculative()
    assert lad.update(0, 0.1, now=100.0) == 1     # calm cannot go below
    assert lad.set_floor(0) == 0
    # effects ladder: clamp at >=2, evict cold KV pages at >=3,
    # colocate prefill at >=4, shed best_effort at >=5, batch at >=6
    assert lad.clamp(100) == 100
    lad.update(10, None, now=200.0)
    lad.update(10, None, now=201.0)
    lad.update(10, None, now=202.0)
    assert lad.level == 2 and lad.clamp(100) == lad.clamp_new_tokens
    assert lad.shed_classes() == frozenset()
    evictions = []
    lad.evict_hook = lambda: evictions.append(1) or 1
    lad.update(10, None, now=203.0)
    assert lad.level == 3 and lad.shed_classes() == frozenset()
    assert evictions, "evict_cold_pages rung never called its hook"
    assert lad.allow_disaggregate()
    lad.update(10, None, now=204.0)
    # colocate_prefill: shipping stops BEFORE any request class sheds
    assert lad.level == 4 and not lad.allow_disaggregate()
    assert lad.shed_classes() == frozenset()
    lad.update(10, None, now=205.0)
    assert lad.shed_classes() == frozenset({"best_effort"})
    lad.update(10, None, now=206.0)
    assert lad.shed_classes() == frozenset({"best_effort", "batch"})
    # the hook keeps firing while the ladder holds at/above the rung
    # (pages that re-chill during a long hot spell keep reclaiming)
    n = len(evictions)
    lad.update(10, None, now=206.5)
    assert len(evictions) > n
    snap = lad.snapshot()
    assert snap["level"] == 6 and snap["name"] == "shed_batch"
    assert snap["evicting"]


def test_brownout_gauge_and_transition_counter():
    reg = prom.Registry()
    lad = _ladder(registry=reg)
    lad.update(10, None, now=0.0)
    lad.update(10, None, now=1.0)
    assert "pipeedge_brownout_level 1" in reg.render()
    lad.update(0, 0.1, now=2.0)
    lad.update(0, 0.1, now=4.0)
    text = reg.render()
    assert "pipeedge_brownout_level 0" in text
    assert ('pipeedge_brownout_transitions_total{direction="up"} 1'
            in text)
    assert ('pipeedge_brownout_transitions_total{direction="down"} 1'
            in text)


# ---------------------------------------------------------------------------
# trace report: the serving section
# ---------------------------------------------------------------------------

def test_report_serving_section_from_spans():
    from pipeedge_tpu.telemetry import report

    ms = 1_000_000
    spans = [
        {"cat": "serve", "name": "admit:interactive", "rank": 0,
         "t0": 0, "t1": 2 * ms},
        {"cat": "serve", "name": "admit:interactive", "rank": 0,
         "t0": 0, "t1": 6 * ms},
        {"cat": "serve", "name": "generate", "rank": 0,
         "t0": 2 * ms, "t1": 50 * ms},
        {"cat": "serve", "name": "shed:batch:queue_full", "rank": 0,
         "t0": 10 * ms, "t1": 10 * ms},
        {"cat": "serve", "name": "shed:best_effort:brownout", "rank": 0,
         "t0": 11 * ms, "t1": 11 * ms},
        {"cat": "serve", "name": "shed:batch:rate", "rank": 0,
         "t0": 12 * ms, "t1": 12 * ms},
        {"cat": "serve", "name": "brownout:1", "rank": 0,
         "t0": 13 * ms, "t1": 13 * ms},
        {"cat": "serve", "name": "brownout:2", "rank": 0,
         "t0": 14 * ms, "t1": 14 * ms},
    ]
    serving = report.analyze_spans(spans, span_cost_ns=100.0)["serving"]
    assert serving["requests"] == 1
    assert serving["sheds"] == 3
    assert serving["sheds_by_class"] == {"batch": 2, "best_effort": 1}
    assert serving["sheds_by_reason"] == {"brownout": 1, "queue_full": 1,
                                          "rate": 1}
    w = serving["admit_wait_ms"]["interactive"]
    assert w["n"] == 2 and w["p50"] == 2.0 and w["p95"] == 6.0
    assert serving["brownout"] == {"transitions": 2, "max_level": 2}
    # traces without serve spans carry an empty section, not a crash
    other = [{"cat": "compute", "name": "x", "rank": 0, "t0": 0, "t1": ms}]
    assert report.analyze_spans(other, span_cost_ns=100.0)["serving"] == {}


# ---------------------------------------------------------------------------
# deadline propagation into the executors (the cancel-flag contract)
# ---------------------------------------------------------------------------

def _tiny_pipe(max_len=64):
    from pipeedge_tpu.models import registry
    from pipeedge_tpu.parallel import decode
    total = registry.get_model_layers(MODEL)
    _, params, _ = registry.module_shard_factory(MODEL, None, 1, total,
                                                 unroll=False)
    return decode.DecodePipeline(
        registry.get_model_entry(MODEL).family.FAMILY,
        registry.get_model_config(MODEL), [(1, total)], [params],
        max_len=max_len)


@pytest.mark.parametrize("executor", ["wave", "stage"])
def test_pre_expired_deadline_never_touches_pipeline(executor):
    """A request whose deadline already passed completes with the bare
    prompt — no cache seeding, no decode steps spent on dead work."""
    import jax.numpy as jnp

    from pipeedge_tpu.parallel.batcher import (ContinuousBatcher,
                                               StageWorkerExecutor)

    pipe = _tiny_pipe()
    ids = jnp.zeros((1, 4), jnp.int32)
    dead = time.monotonic() - 1.0
    if executor == "stage":
        ex = StageWorkerExecutor(pipe, max_active=1)
        try:
            ex.submit("r", ids, 8, deadline=dead)
            out = ex.wait("r", timeout=120)
        finally:
            ex.stop()
    else:
        b = ContinuousBatcher(pipe, max_active=1)
        b.submit("r", ids, 8, deadline=dead)
        out = b.run()["r"]
    assert out.shape == (1, 4)               # prompt only, zero tokens


@pytest.mark.parametrize("executor", ["wave", "stage"])
def test_deadline_expiry_cancels_mid_flight(executor):
    """The executor checks the deadline at every decode-step boundary and
    fires the existing `cancel` flag on expiry: the request completes
    with the tokens decoded so far, far short of the cap — expired work
    stops consuming the pipeline (docs/SERVING.md)."""
    import jax.numpy as jnp

    from pipeedge_tpu.parallel.batcher import (ContinuousBatcher,
                                               StageWorkerExecutor)

    pipe = _tiny_pipe()
    cap = 40
    ids = jnp.zeros((1, 4), jnp.int32)
    cancel = threading.Event()

    # pace decode via the streaming hook so the deadline trips after a
    # handful of steps regardless of host speed
    def on_token(step, tok):
        time.sleep(0.05)

    deadline = time.monotonic() + 0.3
    if executor == "stage":
        ex = StageWorkerExecutor(pipe, max_active=1)
        try:
            ex.submit("r", ids, cap, on_token=on_token, cancel=cancel,
                      deadline=deadline)
            out = ex.wait("r", timeout=120)
        finally:
            ex.stop()
    else:
        b = ContinuousBatcher(pipe, max_active=1)
        b.submit("r", ids, cap, on_token=on_token, cancel=cancel,
                 deadline=deadline)
        out = b.run()["r"]
    decoded = out.shape[1] - 4
    assert 1 <= decoded < cap, f"decoded {decoded} of {cap}"
    # expiry cancels through the ONE shared mechanism: the cancel flag
    assert cancel.is_set()


# ---------------------------------------------------------------------------
# the wired plane under fire (loopback serve.py + tools/loadgen.py)
# ---------------------------------------------------------------------------

def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _post(port, path, obj, timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _healthz(port):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
        return json.loads(resp.read())


@pytest.fixture(scope="module")
def overload_server():
    """serve.py pinned to ONE execution slot with a tight brownout
    governor: capacity is small and deterministic, so '5x overload' is a
    modest absolute rate any test box can offer."""
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "serve.py"),
         "-m", MODEL, "-pt", "1,4,5,8", "--max-len", "48",
         "-t", "float32", "--port", str(port),
         "--max-active", "1", "--queue-capacity", "16",
         "--brownout-queue-high", "4", "--brownout-queue-low", "1",
         "--brownout-p95-high", "0.75", "--brownout-p95-low", "0.3",
         "--brownout-dwell-up", "0.3", "--brownout-dwell-down", "0.7",
         "--brownout-clamp-tokens", "8", "--governor-interval", "0.1"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if "serving" in line:
                break
            if proc.poll() is not None:
                raise RuntimeError(f"server died: {proc.stdout.read()}")
        else:
            raise RuntimeError("server never came up")
        yield port
    finally:
        proc.terminate()
        proc.wait(timeout=10)


class _LevelWatcher:
    """Polls /healthz brownout state in the background; records the max
    level seen and every (phase, level) pair while a degraded window was
    open."""

    def __init__(self, port, interval=0.1):
        self.port = port
        self.interval = interval
        self.max_level = 0
        self.degraded_samples = []
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                h = _healthz(self.port)
            except Exception:   # noqa: BLE001 — transient poll failure
                continue
            level = h["serving"]["brownout"]["level"]
            self.max_level = max(self.max_level, level)
            if h["degraded"]:
                self.degraded_samples.append(
                    (h["degraded"]["phase"], level))

    def __enter__(self):
        self._t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._t.join(timeout=10)


@pytest.mark.fleet
def test_overload_5x_bounded_goodput_shed_and_brownout(overload_server):
    """The acceptance run (ISSUE 7): at 5x sustained synthetic overload,
    interactive goodput stays within 20% of its uncontended value, the
    excess converts to 503 + a DYNAMIC Retry-After (never an unbounded
    queue), and the brownout ladder steps up under fire and back down
    with hysteresis once the surge passes."""
    from tools import loadgen

    port = overload_server
    url = f"http://127.0.0.1:{port}/generate"
    slo = {"interactive": 2000.0, "batch": 6000.0, "best_effort": 10000.0}
    capacity = loadgen.calibrate(url, seconds=2.0, new_tokens=40,
                                 prompt_len=6, timeout=120)
    assert capacity > 0

    # uncontended baseline: interactive only, well under capacity
    base = loadgen.run_load(url, duration_s=4.0, qps=max(0.5, 0.6 * capacity),
                            mix={"interactive": 1.0}, slo_ms=slo,
                            new_tokens=40, timeout=120, seed=1)
    assert base["totals"]["error"] == 0, base["first_error"]
    base_goodput = base["classes"]["interactive"]["goodput_rps"]
    assert base_goodput > 0

    # 5x overload, mixed classes, brownout watched live
    with _LevelWatcher(port) as watch:
        hot = loadgen.run_load(
            url, duration_s=6.0, qps=5.0 * capacity,
            mix={"interactive": 0.6, "batch": 0.25, "best_effort": 0.15},
            slo_ms=slo, new_tokens=40, timeout=120, seed=2)
    assert hot["totals"]["error"] == 0, hot["first_error"]
    # the offered load genuinely overloaded the service (>= 3x even if
    # the client box lagged behind the 5x schedule)
    offered = hot["requests"] / hot["duration_s"]
    assert offered >= 3.0 * capacity, (offered, capacity)
    # excess load SHED, with a service-rate-derived (dynamic) Retry-After
    assert hot["totals"]["shed"] > 0
    ra = hot["retry_after"]
    assert ra["n"] > 0 and ra["min"] > 0
    assert ra["distinct"] >= 2, f"Retry-After looks constant: {ra}"
    # interactive goodput held within 20% of the uncontended value
    hot_goodput = hot["classes"]["interactive"]["goodput_rps"]
    assert hot_goodput >= 0.8 * base_goodput, (hot_goodput, base_goodput)
    # the ladder stepped up under fire...
    assert watch.max_level >= 1, "brownout never engaged at 5x overload"
    # ...and steps back down (hysteresis: dwell_down per rung) once calm
    deadline = time.monotonic() + 20
    while _healthz(port)["serving"]["brownout"]["level"] > 0:
        assert time.monotonic() < deadline, \
            "brownout ladder never stepped back down after the surge"
        time.sleep(0.2)
    # nothing queued unbounded, nothing stuck
    h = _healthz(port)
    assert h["ok"]
    adm = h["serving"]["admission"]
    assert adm["queue_depth"] <= adm["queue_capacity"]


@pytest.mark.fleet
def test_deadline_exceeded_504_mid_flight(overload_server):
    """A request whose budget cannot cover its generation is cancelled at
    a decode-step boundary and answered 504 — with strictly fewer tokens
    decoded than the cap (the executor cancel flag did the work)."""
    port = overload_server
    before = _healthz(port)
    tokens_before = before["stats"]["tokens"]
    d_before = before["serving"]["deadline_exceeded_total"]
    cap = 40
    with pytest.raises(urllib.error.HTTPError) as err:
        _post(port, "/generate", {"ids": [[1, 2, 3, 4, 5, 6]],
                                  "new_tokens": cap, "deadline_ms": 10})
    assert err.value.code == 504
    body = json.loads(err.value.read())
    assert body["deadline_exceeded"] and body["class"] == "interactive"
    after = _healthz(port)
    assert after["serving"]["deadline_exceeded_total"] == d_before + 1
    assert after["stats"]["tokens"] - tokens_before < cap
    # the slot freed: a normal request sails through afterwards
    out = _post(port, "/generate", {"ids": [[1, 2, 3]], "new_tokens": 2})
    assert len(out["ids"][0]) == 5


@pytest.mark.fleet
def test_overload_overlapping_rank_death_no_deadlock(overload_server):
    """Overload overlapping a chaos-injected rank death (the /degraded
    lifecycle the failover orchestrator drives at a kill@K fault): the
    load generator must complete — degraded 503s, not hangs — the
    healing phase floors the brownout ladder at rung 1, and the service
    returns to normal once healed."""
    from tools import loadgen

    port = overload_server
    url = f"http://127.0.0.1:{port}/generate"

    def chaos():
        time.sleep(1.2)
        _post(port, "/degraded", {"degraded": True, "dead_rank": 1})
        time.sleep(1.0)
        _post(port, "/degraded", {"degraded": True, "healing": True})
        time.sleep(1.0)
        _post(port, "/degraded", {"degraded": False, "healed": True,
                                  "rank": 1})

    killer = threading.Thread(target=chaos, daemon=True)
    with _LevelWatcher(port) as watch:
        killer.start()
        report = loadgen.run_load(
            url, duration_s=5.0, qps=30.0,
            mix={"interactive": 0.7, "batch": 0.3},
            slo_ms={"interactive": 2000.0, "batch": 6000.0},
            new_tokens=24, timeout=120, seed=3)
        killer.join(timeout=30)
        assert not killer.is_alive()
    # the window bounced load with degraded 503s instead of queueing it
    assert report["totals"]["degraded"] > 0
    assert report["totals"]["error"] == 0, report["first_error"]
    # healing implies at least brownout rung 1 (the lifecycle floor)
    healing = [lvl for phase, lvl in watch.degraded_samples
               if phase == "healing"]
    assert healing and min(healing) >= 1, watch.degraded_samples
    # no deadlock: the service is clean and serving after the heal
    deadline = time.monotonic() + 20
    while True:
        h = _healthz(port)
        if h["degraded"] is False and h["serving"]["brownout"]["level"] == 0:
            break
        assert time.monotonic() < deadline, h
        time.sleep(0.2)
    out = _post(port, "/generate", {"ids": [[1, 2, 3]], "new_tokens": 2})
    assert len(out["ids"][0]) == 5
    assert h["stats"]["rejoined_ranks_total"] >= 1


@pytest.mark.fleet
def test_overload_metrics_exported(overload_server):
    """After the fire drill, every new instrument is live on /metrics:
    per-(class, reason) shed counters, the brownout level gauge, the
    mid-flight deadline counter, and the per-class admission-latency
    histogram (docs/OBSERVABILITY.md table)."""
    port = overload_server
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30) as resp:
        text = resp.read().decode()
    assert "# TYPE pipeedge_requests_shed_total counter" in text
    from pipeedge_tpu.serving.admission import SHED_REASONS
    shed_lines = [ln for ln in text.splitlines()
                  if ln.startswith("pipeedge_requests_shed_total{")]
    # the full (class, reason) matrix renders, and something was shed
    assert len(shed_lines) == 3 * len(SHED_REASONS), shed_lines
    assert any(float(ln.rsplit(" ", 1)[1]) > 0 for ln in shed_lines)
    assert "pipeedge_brownout_level" in text
    assert "pipeedge_brownout_transitions_total" in text
    assert "pipeedge_deadline_exceeded_total" in text
    assert ('pipeedge_admission_latency_seconds_bucket{class="interactive"'
            in text)
    assert "pipeedge_admission_queue_depth" in text
