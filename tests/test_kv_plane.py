"""The paged KV plane (pipeedge_tpu/kv): page-table accounting, prefix
trie, eviction under pressure, token-budget admission, KV shipping, and
the loopback disaggregated prefill/decode acceptance.

Tier-1 by design (ISSUE 14): the paged executors must stay
TOKEN-IDENTICAL to the dense-cache path on a pinned seed, and the
disaggregated split must produce the same tokens as the colocated path
— these are the gates that let the serving plane swap its memory model
without touching numerics.
"""
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp  # noqa: E402

from pipeedge_tpu.kv import (KvPagePool, PagedKvBackend,  # noqa: E402
                             PoolExhausted, PrefillFleet, PrefixTrie,
                             pages_for)
from pipeedge_tpu.kv import ship as ship_mod  # noqa: E402
from pipeedge_tpu.parallel.batcher import (ContinuousBatcher,  # noqa: E402
                                           StageWorkerExecutor)
from pipeedge_tpu.telemetry import metrics as prom  # noqa: E402

MODEL = "pipeedge/test-tiny-gpt2"
PARTITION = [(1, 4), (5, 8)]
MAX_LEN = 48


def _mk_pipe(max_len=MAX_LEN):
    from pipeedge_tpu.models import registry
    from pipeedge_tpu.parallel import decode
    params = [registry.module_shard_factory(MODEL, None, l, r, stage=i,
                                            unroll=False)[1]
              for i, (l, r) in enumerate(PARTITION)]
    return decode.DecodePipeline(
        registry.get_model_entry(MODEL).family.FAMILY,
        registry.get_model_config(MODEL), PARTITION, params,
        max_len=max_len)


@pytest.fixture(scope="module")
def pipe():
    return _mk_pipe()


def _pool(pipe, n_pages=16, page_size=4):
    return KvPagePool(pipe, n_pages, page_size,
                      registry=prom.Registry())


def _backend(pipe, n_pages=24, page_size=4, **kw):
    reg = prom.Registry()
    return PagedKvBackend(pipe, n_pages, page_size, registry=reg, **kw)


def _prompts(n, batch=1, lens=(6,), seed0=11):
    rng = np.random.default_rng(seed0)
    return [np.asarray(rng.integers(
        0, 100, size=(batch, lens[i % len(lens)])), np.int64)
        for i in range(n)]


# ---------------------------------------------------------------------------
# page pool: alloc / free / refcount
# ---------------------------------------------------------------------------

def test_pool_alloc_free_refcount(pipe):
    pool = _pool(pipe, n_pages=8, page_size=4)
    assert pool.tokens_capacity == 32
    a = pool.alloc(3)
    assert len(a) == 3 and len(set(a)) == 3
    assert pool.free_pages == 5
    # sharing adds references; release drops one at a time
    pool.share(a[:2])
    pool.release(a)                 # the original refs
    assert pool.free_pages == 6     # a[2] freed; a[0], a[1] still shared
    assert pool.refcount(a[0]) == 1
    pool.release(a[:2])
    assert pool.free_pages == 8
    assert pool.refcount(a[0]) == 0
    # over-release and foreign shares are errors, not corruption
    with pytest.raises(ValueError, match="unallocated"):
        pool.release([a[0]])
    with pytest.raises(ValueError, match="unallocated"):
        pool.share([a[0]])
    # exhaustion raises with the arithmetic in the message
    with pytest.raises(PoolExhausted):
        pool.alloc(9)
    b = pool.alloc(8)
    with pytest.raises(PoolExhausted):
        pool.alloc(1)
    pool.release(b)
    assert pages_for(0, 4) == 0 and pages_for(1, 4) == 1 \
        and pages_for(9, 4) == 3


def test_pool_gather_scatter_roundtrip(pipe):
    pool = _pool(pipe, n_pages=6, page_size=4)
    pids = pool.alloc(2)
    table = np.asarray([pids], np.int32)
    view = pool.gather(0, table)
    n_blocks = pipe.stages[0]["n_blocks"]
    cfg = pipe.cfg
    assert view["k"].shape == (n_blocks, 1, 8, cfg.kv_heads,
                               cfg.head_dim)
    # write a recognizable pattern, scatter back, re-gather
    marked = {k: jnp.full_like(v, 7.0) for k, v in view.items()}
    pool.scatter(0, table, marked, [(0, 0), (0, 1)])
    again = pool.gather(0, table)
    np.testing.assert_array_equal(np.asarray(again["k"]),
                                  np.full_like(np.asarray(view["k"]), 7.0))
    # scattering only page 0 leaves page 1 untouched
    half = {k: jnp.zeros_like(v) for k, v in again.items()}
    pool.scatter(0, table, half, [(0, 0)])
    mixed = np.asarray(pool.gather(0, table)["k"])
    assert (mixed[:, :, :4] == 0).all() and (mixed[:, :, 4:] == 7).all()
    pool.release(pids)


# ---------------------------------------------------------------------------
# prefix trie: hit / miss / partial + eviction under pressure
# ---------------------------------------------------------------------------

def test_prefix_trie_hit_miss_partial(pipe):
    pool = _pool(pipe, n_pages=16, page_size=4)
    trie = PrefixTrie(pool, registry=prom.Registry())
    toks = list(range(12))          # 3 full pages
    pids = pool.alloc(3)
    assert trie.insert(toks, pids) == 3
    assert len(trie) == 3
    # full hit: all 3 pages (caller ref taken)
    got = trie.lookup(toks)
    assert got == pids
    assert all(pool.refcount(p) == 3 for p in pids)  # alloc+trie+lookup
    # partial: first 2 pages match, third chunk differs
    part = trie.lookup(toks[:8] + [99, 98, 97, 96])
    assert part == pids[:2]
    # miss: nothing matches
    assert trie.lookup([55] * 12) == []
    # max_tokens caps the match to whole pages BELOW the limit (the
    # span-needs-a-suffix rule)
    capped = trie.lookup(toks, max_tokens=11)
    assert capped == pids[:2]
    st = trie.stats()
    assert st["lookups"] == 4 and st["pages_cached"] == 3
    for got_pids in (got, part, capped):
        pool.release(got_pids)


def test_trie_eviction_under_pressure(pipe):
    pool = _pool(pipe, n_pages=4, page_size=4)
    trie = PrefixTrie(pool, registry=prom.Registry())
    pool.set_evict_hook(trie.evict_cold)
    pids = pool.alloc(3)
    trie.insert(list(range(12)), pids)
    pool.release(pids)              # now trie-only refs: COLD
    assert trie.cold_pages() == 3 and pool.free_pages == 1
    # allocation pressure evicts cold pages (deepest/oldest leaves
    # first) instead of failing
    got = pool.alloc(3)
    assert len(got) == 3 and len(trie) < 3
    # a page still referenced by a request is NOT evictable
    pool.release(got)
    trie.evict_cold(None)           # clear phase-1 leftovers
    pids2 = pool.alloc(2)
    trie.insert(list(range(8)), pids2)
    held = trie.lookup(list(range(8)))       # live request ref
    assert held == pids2
    assert trie.cold_pages() == 0
    with pytest.raises(PoolExhausted):
        pool.alloc(4)
    pool.release(held)
    pool.release(pids2)
    assert trie.evict_cold(None) == 2        # the brownout rung's sweep
    assert pool.free_pages == 4


# ---------------------------------------------------------------------------
# token-budget admission
# ---------------------------------------------------------------------------

def test_token_budget_admission_admits_beyond_slots_worth():
    """With a token budget, many small requests are granted where the
    equivalent dense capacity would be exhausted — and a request bigger
    than the whole budget sheds immediately."""
    from pipeedge_tpu.serving import AdmissionController, AdmissionShed
    reg = prom.Registry()
    # budget = what TWO dense max_len=48 slots would hold
    ctl = AdmissionController(concurrency=32, queue_capacity=8,
                              registry=reg, token_budget=96)
    small = [ctl.admit("interactive", tokens=12) for _ in range(8)]
    assert len(small) == 8          # 8 concurrent > 2 dense slots
    snap = ctl.snapshot()
    assert snap["token_budget"] == 96 and snap["tokens_free"] == 0
    with pytest.raises(AdmissionShed) as err:
        ctl.admit("interactive", tokens=97)
    assert err.value.reason == "budget"
    # a 9th small request queues until a release returns tokens
    granted = []

    def late():
        t = ctl.admit("interactive", tokens=12)
        granted.append(t)

    th = threading.Thread(target=late, daemon=True)
    th.start()
    th.join(timeout=0.5)
    assert th.is_alive() and not granted     # parked on the budget
    ctl.release(small[0])
    th.join(timeout=30)
    assert not th.is_alive() and granted
    for t in small[1:] + granted:
        ctl.release(t)
    assert ctl.snapshot()["tokens_free"] == 96


def test_token_budget_head_keeps_queue_position():
    """A token-short EDF head is NOT re-queued behind same-deadline
    arrivals: it waits in place (peek, not pop+push) and is granted
    before later small requests once tokens free up — no starvation of
    big-context requests under sustained small-request load."""
    from pipeedge_tpu.serving import AdmissionController
    ctl = AdmissionController(concurrency=4, queue_capacity=8,
                              registry=prom.Registry(), token_budget=100)
    h1 = ctl.admit("interactive", tokens=50)
    h2 = ctl.admit("interactive", tokens=50)
    order = []

    def waiter(name, tokens):
        ctl.admit("interactive", tokens=tokens)
        order.append(name)

    def wait_depth(n, budget=120.0):
        end = time.monotonic() + budget
        while time.monotonic() < end and ctl.queue_depth != n:
            time.sleep(0.01)
        assert ctl.queue_depth == n

    t_big = threading.Thread(target=waiter, args=("big", 80), daemon=True)
    t_big.start()
    wait_depth(1)
    t_small = threading.Thread(target=waiter, args=("small", 10),
                               daemon=True)
    t_small.start()
    wait_depth(2)
    # 50 tokens free: not enough for the 80-token head — the small
    # request behind it must NOT overtake
    ctl.release(h1)
    t_small.join(timeout=0.5)
    assert t_small.is_alive() and not order, order
    ctl.release(h2)            # 100 free: head first, then the small
    t_big.join(timeout=30)
    t_small.join(timeout=30)
    assert order == ["big", "small"], order


def test_paged_submit_rejects_bigger_than_pool(pipe):
    """A reservation exceeding the WHOLE pool is rejected at submit on
    both executors (waiting could never admit it; the wave batcher's
    pending queue would otherwise wedge behind it forever) — and so is
    a hand-passed prefix handle (rejected at submit, not as a deferred
    crash of the wave loop)."""
    ids = np.zeros((1, 6), np.int64)    # 6+8 tokens -> 4 pages > 2
    b = ContinuousBatcher(pipe, kv=_backend(pipe, n_pages=2, page_size=4))
    with pytest.raises(ValueError, match="KV page"):
        b.submit("big", ids, new_tokens=8)
    assert not b.pending and b.tick() is False
    handle = pipe.precompute_prefix(np.asarray([[1, 2, 3, 4]]))
    with pytest.raises(ValueError, match="prefix trie"):
        b.submit("pfx", ids, new_tokens=2, prefix=handle)
    ex = StageWorkerExecutor(pipe,
                             kv=_backend(pipe, n_pages=2, page_size=4))
    try:
        with pytest.raises(ValueError, match="KV page"):
            ex.submit("big", ids, 8)
        with pytest.raises(ValueError, match="prefix trie"):
            ex.submit("pfx", ids, 2, prefix=handle)
        assert ex.active == 0
    finally:
        ex.stop()


def test_paged_stop_wakes_page_blocked_submitter(pipe):
    """The wake-on-death/stop contract extends to PAGE waits: a
    submitter parked on pool availability (slots free, pages not) must
    raise on stop(), not hang — the paged twin of
    test_stage_executor_stop_wakes_blocked_submitter."""
    kv = _backend(pipe, n_pages=16, page_size=4)
    ex = StageWorkerExecutor(pipe, kv=kv, max_active=8)
    errs = {}
    first_token = threading.Event()
    ids = np.zeros((1, 4), np.int64)

    def client(rid, tokens, **kw):
        try:
            ex.submit(rid, ids, tokens, **kw)
            ex.wait(rid, timeout=120)
        except RuntimeError as exc:
            errs[rid] = str(exc)

    # "a" reserves the WHOLE pool (4+44 tokens -> 12 pages -> bucket 16)
    # with a generation long enough that it cannot complete between the
    # first streamed token and stop()
    t_a = threading.Thread(target=client, args=("a", 44), daemon=True,
                           kwargs={"on_token":
                                   lambda s, t: first_token.set()})
    t_a.start()
    assert first_token.wait(timeout=120)
    # "b" passes the slot semaphore and parks in the PAGE wait
    t_b = threading.Thread(target=client, args=("b", 4), daemon=True)
    t_b.start()
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and "b" not in ex._live:
        time.sleep(0.01)
    assert "b" in ex._live
    ex.stop()
    t_a.join(timeout=120)
    t_b.join(timeout=120)
    assert not t_a.is_alive() and not t_b.is_alive(), \
        "stop() left a page-blocked submitter hanging"
    assert "b" in errs


def test_paged_batcher_active_exceeds_dense_slot_equivalent(pipe):
    """The acceptance-criteria core: on a shared-prefix workload the
    paged batcher runs MORE concurrent requests than the dense-slot
    capacity holding the same KV tokens could. Pool = 2 dense slots'
    worth of tokens (2 x max_len = 96); dense max_active for that
    memory is 2; the paged run must exceed it."""
    kv = _backend(pipe, n_pages=24, page_size=4)   # 96 tokens
    batcher = ContinuousBatcher(pipe, kv=kv)
    rng = np.random.default_rng(5)
    shared = rng.integers(0, 100, size=(1, 8))
    # seed the trie: one request runs to completion first, publishing
    # the shared prefix's pages for the concurrent burst to reuse
    batcher.submit("seed", np.concatenate(
        [shared, rng.integers(0, 100, size=(1, 4))], axis=1),
        new_tokens=4)
    batcher.run()
    for i in range(6):
        suffix = rng.integers(0, 100, size=(1, 4))
        ids = np.concatenate([shared, suffix], axis=1)
        batcher.submit(i, ids, new_tokens=4)
    peak = 0
    while batcher.tick():
        peak = max(peak, batcher.active)
    assert peak > 2, (
        f"paged admission peaked at {peak} concurrent requests; dense "
        "slots holding the same 96 KV tokens cap at 2")
    assert len(batcher.results) == 7
    # and the trie actually shared the prefix across them
    st = kv.trie.stats()
    assert st["pages_reused_total"] > 0
    assert kv.pool.free_pages + kv.trie.stats()["pages_cached"] \
        == kv.pool.n_pages


# ---------------------------------------------------------------------------
# paged decode parity (pinned seeds)
# ---------------------------------------------------------------------------

def test_paged_wave_batcher_token_identical_to_dense(pipe):
    """Greedy + sampled + eos + multirow requests through the paged
    wave batcher match solo dense generate() token for token."""
    kv = _backend(pipe)
    batcher = ContinuousBatcher(pipe, kv=kv)
    prompts = _prompts(3, lens=(6, 9, 5))
    kwargs = [dict(), dict(temperature=0.8, seed=3),
              dict(temperature=1.1, top_k=5, seed=9)]
    for i, (ids, kw) in enumerate(zip(prompts, kwargs)):
        batcher.submit(i, ids, new_tokens=6, **kw)
    multirow = _prompts(1, batch=2, seed0=29)[0]
    batcher.submit("b2", multirow, new_tokens=5)
    results = batcher.run()
    for i, (ids, kw) in enumerate(zip(prompts, kwargs)):
        solo = np.asarray(pipe.generate(ids, 6, **kw))
        np.testing.assert_array_equal(results[i], solo)
    np.testing.assert_array_equal(
        results["b2"], np.asarray(pipe.generate(multirow, 5)))
    # every page came back (no leaks across mixed request shapes)
    cached = kv.trie.stats()["pages_cached"]
    assert kv.pool.free_pages + cached == kv.pool.n_pages


def test_paged_stage_executor_token_identical_and_prefix_shared(pipe):
    """StageWorkerExecutor over pages: concurrent submitters, token
    parity, and the second same-prompt request hits the trie."""
    kv = _backend(pipe)
    ex = StageWorkerExecutor(pipe, kv=kv)
    try:
        rng = np.random.default_rng(17)
        ids = rng.integers(0, 100, size=(1, 9))
        outs = {}

        def client(rid, **kw):
            ex.submit(rid, ids, 6, **kw)
            outs[rid] = ex.wait(rid, timeout=300)

        # first request publishes the prompt's pages; the concurrent
        # wave behind it shares them through the trie
        client("r0")
        threads = [threading.Thread(target=client, args=(f"r{i}",),
                                    daemon=True) for i in (1, 2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive()
        solo = np.asarray(pipe.generate(ids, 6))
        for rid in outs:
            np.testing.assert_array_equal(outs[rid], solo)
        st = kv.trie.stats()
        assert st["pages_reused_total"] > 0, (
            "same-prompt requests never shared prefix pages")
    finally:
        ex.stop()


# ---------------------------------------------------------------------------
# KV shipping: int8 bit-path + disaggregated loopback acceptance
# ---------------------------------------------------------------------------

def test_int8_kv_ship_bit_path(pipe):
    """The int8 ship path is deterministic bit-for-bit (socket bytes =
    in-memory bytes decode identically) and its dequantization error is
    bounded; bits=0 ships exactly."""
    rng = np.random.default_rng(23)
    ids = jnp.asarray(rng.integers(0, 100, size=(1, 7)), jnp.int32)
    out, caches = pipe._prefill(ids)
    logits = np.asarray(out[:, -1])
    for bits in (0, 8):
        frames = ship_mod.encode_kv_ship(caches, 7, logits, bits=bits)
        blob = ship_mod.frames_to_bytes(frames)
        via_socket = ship_mod.frames_from_bytes(
            ship_mod.ship_over_socket(blob))
        direct = ship_mod.frames_from_bytes(blob)
        h1 = ship_mod.decode_kv_ship(via_socket, pipe.dtype)
        h2 = ship_mod.decode_kv_ship(direct, pipe.dtype)
        assert h1["prompt_len"] == 7
        np.testing.assert_array_equal(h1["logits"], logits)
        for r1, r2, cache in zip(h1["stage_rows"], h2["stage_rows"],
                                 caches):
            for name in ("k", "v"):
                a, b = np.asarray(r1[name]), np.asarray(r2[name])
                np.testing.assert_array_equal(a, b)  # bit-path determinism
                ref = np.asarray(cache[name][:, :, :7])
                if bits == 0:
                    np.testing.assert_array_equal(a, ref)
                else:
                    span = ref.max() - ref.min()
                    assert np.abs(a - ref).max() <= max(
                        1e-6, float(span) / 255.0 * 2), (
                        "int8 ship error beyond the codec's step size")


def test_kv_ship_rejects_malformed(pipe):
    rng = np.random.default_rng(31)
    ids = jnp.asarray(rng.integers(0, 100, size=(1, 5)), jnp.int32)
    out, caches = pipe._prefill(ids)
    frames = ship_mod.encode_kv_ship(caches, 5, np.asarray(out[:, -1]))
    with pytest.raises(ValueError, match="magic"):
        ship_mod.decode_kv_ship(frames[1:], pipe.dtype)
    with pytest.raises(ValueError, match="bits"):
        ship_mod.encode_kv_ship(caches, 5, np.asarray(out[:, -1]),
                                bits=4)


def test_disaggregated_loopback_matches_colocated(pipe):
    """THE acceptance gate: prefill rank -> decode rank over both ship
    paths produces token streams identical to the colocated paged path
    AND to solo dense generate(), greedy and sampled, on pinned
    seeds."""
    prefill_pipe = _mk_pipe()       # the dedicated prefill fleet rank
    rng = np.random.default_rng(41)
    ids = rng.integers(0, 100, size=(1, 7))
    sampled_kw = dict(temperature=0.9, seed=6)
    for path in ("local", "wire"):
        kv = _backend(pipe)
        fleet = PrefillFleet(prefill_pipe, path=path, ship_bits=0,
                             registry=prom.Registry())
        batcher = ContinuousBatcher(pipe, kv=kv)
        batcher.submit("greedy", ids, new_tokens=6,
                       shipped=fleet.prefill(ids, rid="greedy"))
        batcher.submit("sampled", ids, new_tokens=5, **sampled_kw,
                       shipped=fleet.prefill(ids, rid="sampled"))
        results = batcher.run()
        np.testing.assert_array_equal(
            results["greedy"], np.asarray(pipe.generate(ids, 6)))
        np.testing.assert_array_equal(
            results["sampled"],
            np.asarray(pipe.generate(ids, 5, **sampled_kw)))
        # colocated paged run for the same request: identical too
        kv2 = _backend(pipe)
        colo = ContinuousBatcher(pipe, kv=kv2)
        colo.submit("greedy", ids, new_tokens=6)
        np.testing.assert_array_equal(colo.run()["greedy"],
                                      results["greedy"])


def test_pool_owner_sweep_reclaims_orphans(pipe):
    """The leak audit's mechanism: pages adopted by an owner that is no
    longer live are reclaimed by `sweep_leaked`, counted on
    pipeedge_kv_pages_leaked_total, and returned to the free list —
    while live owners and un-adopted allocations are untouched."""
    pool = _pool(pipe, n_pages=8, page_size=4)
    dead = pool.alloc(3)
    pool.adopt("dead-req", dead)
    live = pool.alloc(2)
    pool.adopt("live-req", live)
    bare = pool.alloc(1)            # raw allocation, no owner: invisible
    assert pool.free_pages == 2
    assert pool.sweep_leaked({"live-req"}) == 3
    assert pool.free_pages == 5
    assert pool.stats()["leaked"] == 3
    # idempotent: the dead owner's ledger entry is gone
    assert pool.sweep_leaked({"live-req"}) == 0
    # the live-system form: liveness as a CALLABLE, invoked AFTER the
    # ledger snapshot (a request admitted between the two reads is
    # provably live — the TOCTOU the serve governor must not hit);
    # None from the callable (snapshot raced a mutation) aborts cleanly
    assert pool.sweep_leaked(lambda: {"live-req"}) == 0
    assert pool.sweep_leaked(lambda: None) == 0
    # the racing-release contract: a disowned owner's own release path
    # sees None and does nothing (no double-release ValueError)
    assert pool.disown("dead-req") is None
    pool.release(live + bare)
    pool.disown("live-req")
    assert pool.free_pages == 8


def test_mid_ship_death_leaks_zero_pages_after_sweep(pipe):
    """Satellite acceptance (ISSUE 15): a request whose submitter dies
    mid-ship — pages charged, KV installed, nothing ever released —
    leaks ZERO pages once the orphan sweep reconciles against liveness,
    and the pool is fully usable afterwards."""
    prefill_pipe = _mk_pipe()
    kv = _backend(pipe, n_pages=24, page_size=4)
    fleet = PrefillFleet(prefill_pipe, path="local",
                         registry=prom.Registry())
    rng = np.random.default_rng(61)
    ids = rng.integers(0, 100, size=(1, 6))
    handle = fleet.prefill(ids)

    class _Req:      # the executor-side request skeleton admit needs
        rid = "died-mid-ship"
        prompt_len = 6
        new_tokens = 4
        tokens = []
        rows_done = None
        eos_token = None
        on_token = None
        pick = staticmethod(
            lambda logits, sub: jnp.argmax(logits, axis=-1))

    req = _Req()
    req.ids = np.asarray(ids)
    req.shipped = handle
    import jax
    req.rng = jax.random.PRNGKey(0)
    kind, _ = kv.admit(req)
    assert kind == "step"
    taken = kv.pool.n_pages - kv.pool.free_pages
    assert taken > 0
    # the submitter dies here: no release ever runs. The sweep (liveness
    # = no live requests) must reclaim every page it held.
    leaked = kv.sweep_orphans(set())
    assert leaked == taken
    assert kv.pool.stats()["leaked"] == leaked
    # accounting closes exactly: every page is either free again or
    # legitimately retained by the TRIE (the install published the
    # prompt's full page for reuse — cached capacity, not a leak)
    cached = kv.trie.stats()["pages_cached"]
    assert kv.pool.free_pages + cached == kv.pool.n_pages
    # the request's own (late) release is a no-op, not a double-free
    kv.release(req)
    assert kv.pool.free_pages + cached == kv.pool.n_pages
    # and the pool still serves fresh requests
    batcher = ContinuousBatcher(pipe, kv=kv)
    batcher.submit("after", ids, new_tokens=4)
    np.testing.assert_array_equal(
        batcher.run()["after"], np.asarray(pipe.generate(ids, 4)))


def test_shipped_install_is_idempotent(pipe):
    """The install fence: a second `_install_shipped` for the same
    request (a retried/zombie ship delivered twice above the lease
    fence) returns the FIRST install's decision and appends no second
    token — page tables and the token stream cannot be corrupted by
    at-least-once ship delivery."""
    prefill_pipe = _mk_pipe()
    kv = _backend(pipe, n_pages=24, page_size=4, share_prefixes=False)
    fleet = PrefillFleet(prefill_pipe, path="local",
                         registry=prom.Registry())
    rng = np.random.default_rng(67)
    ids = rng.integers(0, 100, size=(1, 6))
    handle = fleet.prefill(ids)
    batcher = ContinuousBatcher(pipe, kv=kv)
    batcher.submit("idem", ids, new_tokens=4, shipped=handle)
    batcher._admit()
    req = batcher._stage_q[0][0][0]
    assert len(req.tokens) == 1        # the shipped first token
    first = req.kvstate["install_result"]
    again = kv._install_shipped(req, handle)
    assert again == first
    assert len(req.tokens) == 1, "double install double-appended tokens"
    while batcher.tick():
        pass
    np.testing.assert_array_equal(
        batcher.results["idem"], np.asarray(pipe.generate(ids, 4)))


def test_shipped_install_publishes_prefix(pipe):
    """A shipped prompt's full pages land in the decode-side trie: the
    NEXT colocated request with that prompt prefix reuses them."""
    prefill_pipe = _mk_pipe()
    kv = _backend(pipe, n_pages=24, page_size=4)
    fleet = PrefillFleet(prefill_pipe, path="local",
                         registry=prom.Registry())
    rng = np.random.default_rng(47)
    ids = rng.integers(0, 100, size=(1, 8))
    ex = StageWorkerExecutor(pipe, kv=kv)
    try:
        ex.submit("shipped", ids, 4, shipped=fleet.prefill(ids))
        out = ex.wait("shipped", timeout=300)
        np.testing.assert_array_equal(
            out, np.asarray(pipe.generate(ids, 4)))
        assert kv.trie.stats()["pages_cached"] == 2   # 8 tokens / 4
        ex.submit("reuse", ids, 4)
        np.testing.assert_array_equal(ex.wait("reuse", timeout=300), out)
        assert kv.trie.stats()["pages_reused_total"] > 0
    finally:
        ex.stop()


# ---------------------------------------------------------------------------
# replica-to-replica prefix migration (the router drain path, ISSUE 17)
# ---------------------------------------------------------------------------

def test_export_install_prefix_roundtrip_token_identical(pipe):
    """A warm prefix exported from one backend and installed into a
    fresh one serves the SAME tokens there — the drain migration's
    correctness gate (router /kv/export -> ship codec -> /kv/import)."""
    be_a = _backend(pipe, n_pages=24, page_size=4)
    be_b = _backend(pipe, n_pages=24, page_size=4)
    bat = ContinuousBatcher(pipe, kv=be_a)
    ids = (np.arange(5, 17) % 50)[None, :]     # 12 tokens = 3 full pages
    bat.submit("warm", ids, new_tokens=4)
    out = np.asarray(bat.run()["warm"])

    toks = ids[0].tolist()
    frames, plen, pages = be_a.export_prefix(toks)
    assert plen == 12 and pages == 3
    # the export takes no lasting references: A's accounting unchanged
    assert be_a.pool.free_pages + be_a.trie.stats()["pages_cached"] \
        == be_a.pool.n_pages

    blob = ship_mod.frames_to_bytes(frames)
    handle = ship_mod.decode_kv_ship(ship_mod.frames_from_bytes(blob),
                                     pipe.dtype)
    assert be_b.install_prefix(toks, handle) == pages
    assert be_b.install_prefix(toks, handle) == 0      # idempotent
    assert be_b.pool.free_pages + be_b.trie.stats()["pages_cached"] \
        == be_b.pool.n_pages

    bat_b = ContinuousBatcher(pipe, kv=be_b)
    bat_b.submit("rerun", ids, new_tokens=4)
    out_b = np.asarray(bat_b.run()["rerun"])
    np.testing.assert_array_equal(out, out_b)
    # B really served the prompt from the migrated pages (admit's trie
    # lookup caps at prompt_len - 1, so the last full page recomputes)
    assert be_b.trie.stats()["pages_reused_total"] >= pages - 1


def test_export_unknown_prefix_returns_none(pipe):
    be = _backend(pipe, n_pages=8, page_size=4)
    assert be.export_prefix([999, 998, 997, 996]) is None


def test_install_prefix_rejects_malformed_without_leaking(pipe):
    be_a = _backend(pipe, n_pages=24, page_size=4)
    be_b = _backend(pipe, n_pages=24, page_size=4)
    bat = ContinuousBatcher(pipe, kv=be_a)
    ids = (np.arange(30, 42) % 50)[None, :]
    bat.submit("warm", ids, new_tokens=2)
    bat.run()
    toks = ids[0].tolist()
    frames, plen, pages = be_a.export_prefix(toks)
    handle = ship_mod.decode_kv_ship(
        ship_mod.frames_from_bytes(ship_mod.frames_to_bytes(frames)),
        pipe.dtype)
    free0 = be_b.pool.free_pages
    with pytest.raises(ValueError):          # stage-count mismatch
        be_b.install_prefix(toks, dict(handle,
                                       stage_rows=handle["stage_rows"][:1]))
    with pytest.raises(ValueError):          # not page-aligned
        be_b.install_prefix(toks, dict(handle, prompt_len=plen - 1))
    with pytest.raises(ValueError):          # covers more than the prefix
        be_b.install_prefix(toks[:4], handle)
    assert be_b.pool.free_pages == free0     # nothing leaked
