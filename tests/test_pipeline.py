"""Host-driven pipeline integration tests on the 8-device virtual CPU mesh.

SURVEY.md §4 test strategy (c): multi-device pipeline execution with fake
devices. Stages are placed on distinct devices; outputs must match the
unsharded model, with and without quantized edges.
"""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from pipeedge_tpu.models import ShardConfig  # noqa: E402
from pipeedge_tpu.models import vit as vit_mod  # noqa: E402
from pipeedge_tpu.models.layers import TransformerConfig  # noqa: E402
from pipeedge_tpu.models.shard import make_shard_fn  # noqa: E402
from pipeedge_tpu.parallel.pipeline import HostPipeline, PipelineStage  # noqa: E402

pytestmark = pytest.mark.slow  # host-pipeline integration compiles per-stage programs

TINY = dict(hidden_size=32, num_hidden_layers=3, num_attention_heads=4,
            intermediate_size=64)


@pytest.fixture(scope="module")
def tiny_vit():
    from transformers import ViTConfig, ViTForImageClassification
    hf_cfg = ViTConfig(**TINY, image_size=16, patch_size=4, num_labels=5)
    torch.manual_seed(0)
    model = ViTForImageClassification(hf_cfg).eval()
    cfg = TransformerConfig(model_type="vit", **TINY, num_labels=5,
                            image_size=16, patch_size=4)
    weights = vit_mod.hf_to_npz_weights(model.state_dict(), cfg)
    return cfg, weights


def _stages(cfg, weights, partition, devices, quant_bits=None):
    stages = []
    total = 4 * cfg.num_hidden_layers
    for i, (l, r) in enumerate(partition):
        sc = ShardConfig(l, r, is_first=l == 1, is_last=r == total)
        params = vit_mod.load_params(cfg, sc, weights)
        fn = make_shard_fn(vit_mod.FAMILY, cfg, sc)
        bit = 0 if quant_bits is None or i == len(partition) - 1 else quant_bits[i]
        stages.append(PipelineStage(shard_fn=fn, params=params,
                                    device=devices[i % len(devices)],
                                    quant_bit=bit))
    return stages


def test_eight_devices_available():
    assert jax.device_count() >= 8, "conftest must fake 8 CPU devices"


def test_pipeline_matches_single_shard(tiny_vit):
    cfg, weights = tiny_vit
    devices = jax.devices()
    rng = np.random.default_rng(0)
    ubatches = [jnp.asarray(rng.normal(size=(2, 3, 16, 16)).astype(np.float32))
                for _ in range(5)]

    single = HostPipeline(_stages(cfg, weights, [(1, 12)], devices[:1]))
    expected, _ = single.run(ubatches)

    partition = [(1, 1), (2, 5), (6, 11), (12, 12)]  # incl. tuple edges
    pipe = HostPipeline(_stages(cfg, weights, partition, devices))
    got, stats = pipe.run(ubatches)

    assert stats["microbatches"] == 5
    assert stats["throughput_items_sec"] > 0
    for e, g in zip(expected, got):
        np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                   rtol=2e-4, atol=1e-5)


def test_pipeline_fifo_order(tiny_vit):
    cfg, weights = tiny_vit
    devices = jax.devices()
    pipe = HostPipeline(_stages(cfg, weights, [(1, 6), (7, 12)], devices))
    # distinguishable inputs: scaled copies of one image
    base = np.random.default_rng(1).normal(size=(1, 3, 16, 16)).astype(np.float32)
    ubatches = [jnp.asarray(base * (i + 1)) for i in range(6)]
    seen = []
    pipe.ubatch_callback = lambda i, out: seen.append(i)
    results, _ = pipe.run(ubatches)
    assert seen == list(range(6))
    # outputs differ pairwise => no mixing
    outs = [np.asarray(r) for r in results]
    for i in range(len(outs) - 1):
        assert not np.allclose(outs[i], outs[i + 1])


@pytest.mark.parametrize("bit", [8, 16])
def test_pipeline_quantized_edges(tiny_vit, bit):
    cfg, weights = tiny_vit
    devices = jax.devices()
    rng = np.random.default_rng(2)
    ubatches = [jnp.asarray(rng.normal(size=(2, 3, 16, 16)).astype(np.float32))]

    exact, _ = HostPipeline(_stages(cfg, weights, [(1, 12)], devices[:1])).run(ubatches)
    partition = [(1, 4), (5, 8), (9, 12)]
    pipe = HostPipeline(_stages(cfg, weights, partition, devices,
                                quant_bits=[bit] * 3))
    got, _ = pipe.run(ubatches)
    # logits drift bounded: quantization noise but same argmax ordering scale
    err = np.max(np.abs(np.asarray(got[0]) - np.asarray(exact[0])))
    scale = np.max(np.abs(np.asarray(exact[0])))
    assert err < scale * (0.5 if bit == 8 else 0.1)


def test_quantized_edge_changes_bitwidth_without_error(tiny_vit):
    """Adaptive policies mutate quant_bit between microbatches (runtime.py:143-153)."""
    cfg, weights = tiny_vit
    devices = jax.devices()
    stages = _stages(cfg, weights, [(1, 6), (7, 12)], devices, quant_bits=[8])
    pipe = HostPipeline(stages)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(2, 3, 16, 16)).astype(np.float32))
    r1, _ = pipe.run([x])
    stages[0].quant_bit = 4
    r2, _ = pipe.run([x])
    stages[0].quant_bit = 0
    r3, _ = pipe.run([x])
    assert np.asarray(r1[0]).shape == np.asarray(r2[0]).shape == np.asarray(r3[0]).shape


def test_run_reports_steady_state_throughput(tiny_vit):
    """steady_state_throughput_items_sec excludes the first (compile-
    tainted) microbatch: on a cold pipeline it must beat the end-to-end
    number, and the warm cadence interval must be positive."""
    cfg, weights = tiny_vit
    devices = jax.devices()
    pipe = HostPipeline(_stages(cfg, weights, [(1, 6), (7, 12)], devices))
    rng = np.random.default_rng(4)
    ubatches = [jnp.asarray(rng.normal(size=(2, 3, 16, 16)).astype(np.float32))
                for _ in range(6)]
    _, stats = pipe.run(ubatches)
    steady = stats["steady_state_throughput_items_sec"]
    assert steady > 0 and stats["steady_mb_interval_s"] > 0
    # round 0 paid the XLA compiles in its first microbatch; excluding it
    # must not report a SLOWER cadence than the tainted end-to-end one
    assert steady > stats["throughput_items_sec"]
    assert stats["host_dispatch_s_per_ubatch"] >= 0
    # a single microbatch has no steady window to report
    _, stats1 = pipe.run(ubatches[:1])
    assert "steady_state_throughput_items_sec" not in stats1


def test_retirement_is_opportunistic_and_fifo(tiny_vit):
    """With a tiny window, already-finished heads retire without waiting
    for the window to fill, callbacks stay FIFO, and results match the
    unwindowed run (the satellite fix: a full window no longer always
    blocks dispatch on the oldest microbatch's full host readback)."""
    from pipeedge_tpu.parallel import pipeline as pipeline_mod

    cfg, weights = tiny_vit
    devices = jax.devices()
    stages = _stages(cfg, weights, [(1, 6), (7, 12)], devices)
    rng = np.random.default_rng(5)
    base = rng.normal(size=(1, 3, 16, 16)).astype(np.float32)
    ubatches = [jnp.asarray(base * (i + 1)) for i in range(8)]
    expected, _ = HostPipeline(stages).run(ubatches)

    pipe = HostPipeline(stages, max_inflight=2)
    seen = []
    pipe.ubatch_callback = lambda i, out: seen.append(i)
    got, _ = pipe.run(ubatches)
    assert seen == list(range(8))
    for e, g in zip(expected, got):
        np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                   rtol=2e-4, atol=1e-5)
    # ready payloads answer the non-blocking probe; odd payloads don't
    assert pipeline_mod.payload_ready(jax.block_until_ready(got[0]))
    assert not pipeline_mod.payload_ready(object())
