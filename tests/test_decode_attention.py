"""Fused int8 decode-attention Pallas kernel vs the XLA dequantize path.

The kernel (ops/decode_attention.py) must reproduce the XLA int8 decode
step's semantics: dequantized cache reads, EXACT fresh-row substitution,
[0, pos] masking. Interpret mode on CPU; the same code lowers natively
on TPU (bench_decode's int8 rows exercise it there)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pipeedge_tpu.models import registry
from pipeedge_tpu.ops import decode_attention
from pipeedge_tpu.parallel import decode


pytestmark = pytest.mark.slow   # compile-heavy decode programs


@pytest.mark.parametrize("variant", [1, 2])
def test_kernel_matches_xla_dequant_attend(variant):
    """Direct kernel check against the reference computation — both the
    per-cell grid (v1) and the batch-as-sublane grid (v2)."""
    rng = np.random.default_rng(0)
    b, t, h, d = 2, 24, 4, 16
    pos = 13
    k_rows = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
    v_rows = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
    k_new = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
    v_new = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)

    kq, ks, kz = decode._quantize_rows(k_rows)
    vq, vs, vz = decode._quantize_rows(v_rows)

    got = decode_attention.int8_decode_attention(
        q, kq, ks, kz, vq, vs, vz, k_new, v_new, pos, interpret=True,
        variant=variant)

    # reference: the XLA path's math
    k = decode._dequantize_rows(kq, ks, kz, jnp.float32)
    v = decode._dequantize_rows(vq, vs, vz, jnp.float32)
    k = k.at[:, pos:pos + 1].set(k_new)
    v = v.at[:, pos:pos + 1].set(v_new)
    keep = (jnp.arange(t) <= pos)[None, :]
    cfg = registry.get_model_config("pipeedge/test-tiny-gpt2")
    want = decode._attend(q, k, v, keep, cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_int8_pipeline_tokens_match_with_kernel(monkeypatch):
    """End-to-end: the int8 pipeline generates the same tokens with the
    fused kernel (interpret mode) as with the XLA dequantize path."""
    name = "pipeedge/test-tiny-gpt2"
    cfg = registry.get_model_config(name)
    total = registry.get_model_layers(name)
    _, params, _ = registry.module_shard_factory(name, None, 1, total,
                                                 unroll=False)
    fam = registry.get_model_entry(name).family.FAMILY
    rng = np.random.default_rng(5)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 8))

    def generate():
        pipe = decode.DecodePipeline(fam, cfg, [(1, total)], [params],
                                     max_len=32, cache_bits=8)
        return np.asarray(pipe.generate(ids, 10))

    monkeypatch.setenv("PIPEEDGE_INT8_DECODE_ATTEND", "0")
    want = generate()
    monkeypatch.setenv("PIPEEDGE_INT8_DECODE_ATTEND", "1")
    got = generate()
    np.testing.assert_array_equal(got, want)
    monkeypatch.setenv("PIPEEDGE_INT8_DECODE_ATTEND", "2")
    got_v2 = generate()                  # batch-as-sublane variant
    np.testing.assert_array_equal(got_v2, want)


def test_kernel_gate_scope(monkeypatch):
    """The kernel only takes the MHA single-token path: spans, GQA,
    sliding-window, and VMEM-overflowing windows stay on the XLA path
    (gate returns None). The opt-in is resolved ONCE at pipeline
    construction (`_int8_kernel_env`) and passed in, so env toggles after
    stage programs compile cannot desynchronize cached shapes."""
    import dataclasses
    cfg = registry.get_model_config("pipeedge/test-tiny-gpt2")
    cache8 = {"k_scale": None}
    # span / fp cache / GQA / window / huge window never route, even
    # when opted in
    assert decode._use_int8_decode_kernel(cache8, 2, cfg, 64, 1) is None
    assert decode._use_int8_decode_kernel({}, 1, cfg, 64, 1) is None
    gqa = dataclasses.replace(cfg, num_kv_heads=2, num_attention_heads=4)
    assert decode._use_int8_decode_kernel(cache8, 1, gqa, 64, 1) is None
    windowed = dataclasses.replace(cfg, sliding_window=4)
    assert decode._use_int8_decode_kernel(cache8, 1, windowed, 64,
                                          1) is None
    huge = decode._INT8_KERNEL_VMEM_CAP // (cfg.kv_heads * cfg.head_dim) + 8
    assert decode._use_int8_decode_kernel(cache8, 1, cfg, huge, 1) is None
    # opt-in off: the eligible shape stays on the XLA path; on: interpret
    # mode on this TPU-less host, kernel variant passed through
    assert decode._use_int8_decode_kernel(cache8, 1, cfg, 64, 0) is None
    assert decode._use_int8_decode_kernel(cache8, 1, cfg, 64, 1) == (True, 1)
    assert decode._use_int8_decode_kernel(cache8, 1, cfg, 64, 2) == (True, 2)
    # env resolution: unset/empty/0/off mean off; '2' selects variant 2;
    # anything else truthy means variant 1
    monkeypatch.delenv("PIPEEDGE_INT8_DECODE_ATTEND", raising=False)
    assert decode._int8_kernel_env() == 0
    for off in ("", "0", "false", "no", "off"):
        monkeypatch.setenv("PIPEEDGE_INT8_DECODE_ATTEND", off)
        assert decode._int8_kernel_env() == 0
    monkeypatch.setenv("PIPEEDGE_INT8_DECODE_ATTEND", "1")
    assert decode._int8_kernel_env() == 1
    monkeypatch.setenv("PIPEEDGE_INT8_DECODE_ATTEND", "2")
    assert decode._int8_kernel_env() == 2


def test_kernel_optin_bound_at_construction(monkeypatch):
    """Toggling the env var AFTER a pipeline is built must not change its
    routing: the flag is captured at construction (round-4 advice)."""
    monkeypatch.setenv("PIPEEDGE_INT8_DECODE_ATTEND", "1")
    name = "pipeedge/test-tiny-gpt2"
    cfg = registry.get_model_config(name)
    total = registry.get_model_layers(name)
    _, params, _ = registry.module_shard_factory(name, None, 1, total,
                                                 unroll=False)
    fam = registry.get_model_entry(name).family.FAMILY
    pipe = decode.DecodePipeline(fam, cfg, [(1, total)], [params],
                                 max_len=32, cache_bits=8)
    assert pipe.int8_decode_optin == 1
    monkeypatch.setenv("PIPEEDGE_INT8_DECODE_ATTEND", "0")
    assert pipe.int8_decode_optin == 1   # captured, not re-read
    monkeypatch.delenv("PIPEEDGE_INT8_DECODE_ATTEND", raising=False)
    pipe2 = decode.DecodePipeline(fam, cfg, [(1, total)], [params],
                                  max_len=32, cache_bits=8)
    assert pipe2.int8_decode_optin == 0


@pytest.mark.slow
def test_int8_pipeline_bf16_tokens_match_with_kernel(monkeypatch):
    """bf16 pipeline (the realistic serving dtype): kernel and XLA paths
    share cast points (dequant -> dtype, probs -> dtype), so tokens
    match on the tiny model; the flash-style softmax ordering is the
    only remaining numeric difference."""
    name = "pipeedge/test-tiny-gpt2"
    cfg = registry.get_model_config(name)
    total = registry.get_model_layers(name)
    _, params, _ = registry.module_shard_factory(name, None, 1, total,
                                                 dtype=jnp.bfloat16,
                                                 unroll=False)
    fam = registry.get_model_entry(name).family.FAMILY
    rng = np.random.default_rng(9)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 8))

    def generate():
        pipe = decode.DecodePipeline(fam, cfg, [(1, total)], [params],
                                     max_len=32, cache_bits=8,
                                     dtype=jnp.bfloat16)
        return np.asarray(pipe.generate(ids, 10))

    monkeypatch.setenv("PIPEEDGE_INT8_DECODE_ATTEND", "0")
    want = generate()
    monkeypatch.setenv("PIPEEDGE_INT8_DECODE_ATTEND", "1")
    got = generate()
    np.testing.assert_array_equal(got, want)


def test_kernel_auto_policy(monkeypatch):
    """PIPEEDGE_INT8_DECODE_ATTEND=auto routes kernel v2 ONLY at attend
    widths <= 256 (the 3/3-session measured crossover); wider windows
    stay on the XLA path, and shapes whose whole-batch block can't fit
    VMEM fall back to XLA too."""
    cfg = registry.get_model_config("pipeedge/test-tiny-gpt2")
    cache8 = {"k_scale": None}
    monkeypatch.setenv("PIPEEDGE_INT8_DECODE_ATTEND", "auto")
    assert decode._int8_kernel_env() == 3
    # small window -> v2; wide window -> XLA (None)
    assert decode._use_int8_decode_kernel(cache8, 1, cfg, 256, 3,
                                          batch=2) == (True, 2)
    assert decode._use_int8_decode_kernel(cache8, 1, cfg, 512, 3,
                                          batch=2) is None
    # whole-batch block can't fit -> XLA rather than dying in Mosaic
    assert decode._use_int8_decode_kernel(cache8, 1, cfg, 256, 3,
                                          batch=100000) is None

    # end-to-end: auto tokens == XLA-path tokens on the tiny model
    name = "pipeedge/test-tiny-gpt2"
    total = registry.get_model_layers(name)
    _, params, _ = registry.module_shard_factory(name, None, 1, total,
                                                 unroll=False)
    fam = registry.get_model_entry(name).family.FAMILY
    rng = np.random.default_rng(7)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 8))

    def generate():
        pipe = decode.DecodePipeline(fam, cfg, [(1, total)], [params],
                                     max_len=32, cache_bits=8)
        return np.asarray(pipe.generate(ids, 10))

    monkeypatch.setenv("PIPEEDGE_INT8_DECODE_ATTEND", "0")
    want = generate()
    monkeypatch.setenv("PIPEEDGE_INT8_DECODE_ATTEND", "auto")
    np.testing.assert_array_equal(generate(), want)
