"""DCN transport tests: wire framing, context P2P, command channel, and a
3-stage cross-"host" pipeline on localhost (reference never tests its wire
protocol or multi-rank paths at all, SURVEY.md §4)."""
import queue
import socket
import threading
import time

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np
import pytest

from pipeedge_tpu.comm import CMD_SCHED, CMD_STOP
from pipeedge_tpu.comm import dcn


def _free_ports(n):
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _make_contexts(n, handlers=None):
    ports = _free_ports(n)
    addrs = [("127.0.0.1", p) for p in ports]
    ctxs = [dcn.DistDcnContext(n, r, addrs,
                               cmd_handler=(handlers or {}).get(r))
            for r in range(n)]
    for c in ctxs:
        c.init()
    return ctxs


# -- framing -----------------------------------------------------------

@pytest.mark.parametrize("dtype", [
    np.float16, np.float32, np.float64, np.uint8, np.int8, np.int16,
    np.int32, np.int64, np.bool_, np.complex64, np.complex128,
    ml_dtypes.bfloat16])
def test_frame_roundtrip_dtypes(dtype):
    a, b = socket.socketpair()
    arr = np.arange(24).reshape(2, 3, 4).astype(dtype)
    dcn._send_frame(a, dcn._MSG_TENSORS, 7, [arr], channel=1)
    msg_type, aux, channel, out = dcn._recv_frame(b)
    assert msg_type == dcn._MSG_TENSORS and aux == 7 and channel == 1
    np.testing.assert_array_equal(np.asarray(out[0], np.float64),
                                  np.asarray(arr, np.float64))
    assert out[0].dtype == arr.dtype
    a.close(), b.close()


def test_frame_roundtrip_edge_shapes():
    a, b = socket.socketpair()
    tensors = [np.float32(3.5).reshape(()),                # 0-d
               np.zeros((0, 5), np.int32),                 # zero-size
               np.arange(20, dtype=np.float32)[::2]]       # non-contiguous
    dcn._send_frame(a, dcn._MSG_TENSORS, 0, tensors)
    _, _, _, out = dcn._recv_frame(b)
    assert out[0].shape == () and float(out[0]) == 3.5
    assert out[1].shape == (0, 5)
    np.testing.assert_array_equal(out[2], np.arange(0, 20, 2, dtype=np.float32))
    a.close(), b.close()


def test_frame_rejects_unknown_dtype():
    a, b = socket.socketpair()
    with pytest.raises(TypeError):
        dcn._send_frame(a, dcn._MSG_TENSORS, 0,
                        [np.array(["x"], dtype=object)])
    a.close(), b.close()


# -- context P2P -------------------------------------------------------

def test_context_send_recv_bidirectional():
    ctxs = _make_contexts(2)
    try:
        x = np.random.default_rng(0).normal(size=(4, 8)).astype(np.float32)
        y = np.arange(6, dtype=np.int64)
        ctxs[0].send_tensors(1, [x, y])
        got = ctxs[1].recv_tensors(0, timeout=10)
        np.testing.assert_array_equal(got[0], x)
        np.testing.assert_array_equal(got[1], y)
        # reverse direction on the 1->0 link
        ctxs[1].send_tensors(0, [y])
        np.testing.assert_array_equal(ctxs[0].recv_tensors(1, timeout=10)[0], y)
    finally:
        for c in ctxs:
            c.shutdown()


def test_self_loop_channels_demultiplex():
    """A rank sending to itself on two channels reads them back separately
    (the colocated data-rank + single-stage schedule case)."""
    ctxs = _make_contexts(1)
    try:
        a = np.arange(4, dtype=np.float32)
        b = np.arange(8, dtype=np.float32)
        ctxs[0].send_tensors(0, [a], channel=dcn.CHANNEL_DATA)
        ctxs[0].send_tensors(0, [b], channel=dcn.CHANNEL_RESULTS)
        got_b = ctxs[0].recv_tensors(0, timeout=10,
                                     channel=dcn.CHANNEL_RESULTS)
        got_a = ctxs[0].recv_tensors(0, timeout=10, channel=dcn.CHANNEL_DATA)
        np.testing.assert_array_equal(got_a[0], a)
        np.testing.assert_array_equal(got_b[0], b)
    finally:
        ctxs[0].shutdown()


def test_context_lifecycle_reusable():
    """Context-manager init/shutdown/reenter (reference test_context.py) —
    and the reentered session must actually move data, not just report
    initialized."""
    ports = _free_ports(1)
    ctx = dcn.DistDcnContext(1, 0, [("127.0.0.1", ports[0])])
    for session in range(2):
        with ctx:
            assert ctx.initialized
            x = np.full((3,), session, np.int32)
            ctx.send_tensors(0, [x])
            np.testing.assert_array_equal(
                ctx.recv_tensors(0, timeout=10)[0], x)
        assert not ctx.initialized


def test_cmd_broadcast_reaches_all_peers():
    received = {r: queue.Queue() for r in range(3)}
    handlers = {r: (lambda cmd, tensors, _r=r: received[_r].put((cmd, tensors)))
                for r in range(3)}
    ctxs = _make_contexts(3, handlers)
    try:
        sched = np.asarray([[1, 24], [25, 48]], np.int32)
        ctxs[0].cmd_broadcast(CMD_SCHED, [sched])
        for r in (1, 2):
            cmd, tensors = received[r].get(timeout=10)
            assert cmd == CMD_SCHED
            np.testing.assert_array_equal(tensors[0], sched)
        ctxs[0].cmd_broadcast(CMD_STOP)
        for r in (1, 2):
            cmd, tensors = received[r].get(timeout=10)
            assert cmd == CMD_STOP and tensors == ()
        assert received[0].empty()  # no self-delivery, like the reference
    finally:
        for c in ctxs:
            c.shutdown()


def test_send_recv_hooks_measure_wire_bytes():
    """Transport hooks see every data frame's actual bytes (reference
    p2p:132-152); command frames are not measured."""
    ctxs = _make_contexts(2)
    events = {"send_pre": [], "send": [], "recv_pre": [], "recv": []}
    ctxs[0].register_send_hooks(
        lambda dst, ch: events["send_pre"].append((dst, ch)),
        lambda dst, ch, ts: events["send"].append(
            (dst, ch, sum(t.nbytes for t in ts))))
    ctxs[1].register_recv_hooks(
        lambda src, ch: events["recv_pre"].append((src, ch)),
        lambda src, ch, ts: events["recv"].append(
            (src, ch, sum(t.nbytes for t in ts))))
    try:
        x = np.zeros((4, 8), np.float32)
        y = np.arange(6, dtype=np.int64)
        ctxs[0].send_tensors(1, [x, y], channel=dcn.CHANNEL_DATA)
        ctxs[1].recv_tensors(0, timeout=10)
        ctxs[0].cmd_broadcast(CMD_STOP)
        time.sleep(0.3)  # let rank 1's reader drain the command frame
        nbytes = x.nbytes + y.nbytes
        assert events["send_pre"] == [(1, dcn.CHANNEL_DATA)]
        assert events["send"] == [(1, dcn.CHANNEL_DATA, nbytes)]
        assert events["recv_pre"] == [(0, dcn.CHANNEL_DATA)]
        assert events["recv"] == [(0, dcn.CHANNEL_DATA, nbytes)]
    finally:
        for c in ctxs:
            c.shutdown()


# -- pipeline stages ---------------------------------------------------

def test_three_stage_pipeline_matches_single_shard():
    """Stream microbatches through 3 DCN stages (3 contexts in-process ==
    3 hosts on localhost) and compare with the monolithic forward."""
    pytest.importorskip("torch")
    import torch
    from transformers import ViTConfig, ViTForImageClassification

    from pipeedge_tpu.models import ShardConfig
    from pipeedge_tpu.models import vit as vit_mod
    from pipeedge_tpu.models.layers import TransformerConfig
    from pipeedge_tpu.models.shard import make_shard_fn

    tiny = dict(hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
                intermediate_size=64)
    torch.manual_seed(0)
    model = ViTForImageClassification(
        ViTConfig(**tiny, image_size=16, patch_size=4, num_labels=5)).eval()
    cfg = TransformerConfig(model_type="vit", **tiny, num_labels=5,
                            image_size=16, patch_size=4)
    weights = vit_mod.hf_to_npz_weights(model.state_dict(), cfg)
    total = 4 * cfg.num_hidden_layers

    full_sc = ShardConfig(1, total, is_first=True, is_last=True)
    params_full = vit_mod.load_params(cfg, full_sc, weights)
    full_fn = jax.jit(make_shard_fn(vit_mod.FAMILY, cfg, full_sc))

    partition = [(1, 3), (4, 5), (6, 8)]  # includes mid-block cuts
    stage_fns = []
    for (l, r) in partition:
        sc = ShardConfig(l, r, is_first=l == 1, is_last=r == total)
        sp = vit_mod.load_params(cfg, sc, weights)
        fn = jax.jit(make_shard_fn(vit_mod.FAMILY, cfg, sc))
        stage_fns.append((fn, sp))

    def work(stage_idx):
        fn, sp = stage_fns[stage_idx]
        def cb(tensors):
            data = jnp.asarray(tensors[0]) if len(tensors) == 1 \
                else tuple(jnp.asarray(t) for t in tensors)
            out = fn(sp, data)
            out = out if isinstance(out, tuple) else (out,)
            return [np.asarray(t) for t in out]
        return cb

    results = queue.Queue()
    ctxs = _make_contexts(3)
    stages = [
        dcn.DcnPipelineStage(ctxs[0], None, 1, work(0)),
        dcn.DcnPipelineStage(ctxs[1], 0, 2, work(1)),
        dcn.DcnPipelineStage(ctxs[2], 1, None, work(2),
                             results_cb=results.put),
    ]
    rng = np.random.default_rng(0)
    ubatches = [rng.normal(size=(2, 3, 16, 16)).astype(np.float32)
                for _ in range(4)]
    try:
        for s in stages:
            s.start()
        for u in ubatches:
            stages[0].enqueue_tensors([u])
        outs = [results.get(timeout=60) for _ in ubatches]
    finally:
        for s in stages:
            s.stop()
        for c in ctxs:
            c.shutdown()
    for u, out in zip(ubatches, outs):  # FIFO order is guaranteed
        expect = np.asarray(full_fn(params_full, jnp.asarray(u)))
        np.testing.assert_allclose(out[0], expect, rtol=2e-4, atol=2e-4)


def test_stage_stop_while_blocked():
    """stop() releases a work thread blocked on a full hand-off queue."""
    ctxs = _make_contexts(1)
    blocker = threading.Event()

    def slow(tensors):
        blocker.wait(5)
        return tensors

    stage = dcn.DcnPipelineStage(ctxs[0], None, None, slow,
                                 results_cb=lambda x: time.sleep(1))
    try:
        stage.start()
        for _ in range(2):
            stage.enqueue_tensors([np.zeros(2, np.float32)])
        blocker.set()
        stage.stop()
        assert all(not t.is_alive() for t in stage._threads)
    finally:
        ctxs[0].shutdown()


def test_cmd_broadcast_bypasses_backpressured_data_send():
    """Commands ride dedicated per-peer connections: an abort broadcast must
    complete even while a data send to the same peer is blocked on
    backpressure (the receiver's single-slot queue is full and the kernel
    socket buffers are saturated). Regression for a deadlock class where
    commands queued behind a blocked data send on a shared socket."""
    got = queue.Queue()
    ctxs = _make_contexts(2, handlers={1: lambda c, t: got.put(c)})
    a, b = ctxs
    try:
        # saturate a->b: b never drains its recv queue, so after the 1-slot
        # queue + kernel buffers fill, the sender blocks inside sendmsg
        big = np.zeros(4 * 1024 * 1024, np.uint8)
        sent = [0]

        def flood():
            try:
                while True:
                    a.send_tensors(1, [big])
                    sent[0] += 1
            except OSError:
                pass  # context shutdown closes the socket

        t = threading.Thread(target=flood, daemon=True)
        t.start()
        # wait until the flood stalls (no progress for a full second)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            before = sent[0]
            time.sleep(1.0)
            if sent[0] == before and before > 0:
                break
        assert sent[0] > 0, "flood never sent a frame"
        tik = time.monotonic()
        a.cmd_broadcast(CMD_STOP)
        elapsed = time.monotonic() - tik
        assert got.get(timeout=10) == CMD_STOP
        assert elapsed < 5, f"cmd_broadcast took {elapsed:.1f}s behind a " \
                            "blocked data send"
    finally:
        for c in ctxs:
            c.shutdown()
