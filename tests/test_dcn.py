"""DCN transport tests: wire framing, context P2P, command channel, and a
3-stage cross-"host" pipeline on localhost (reference never tests its wire
protocol or multi-rank paths at all, SURVEY.md §4)."""
import queue
import socket
import threading
import time

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np
import pytest

from pipeedge_tpu.comm import CMD_SCHED, CMD_STOP
from pipeedge_tpu.comm import dcn


def _free_ports(n):
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _make_contexts(n, handlers=None):
    ports = _free_ports(n)
    addrs = [("127.0.0.1", p) for p in ports]
    ctxs = [dcn.DistDcnContext(n, r, addrs,
                               cmd_handler=(handlers or {}).get(r))
            for r in range(n)]
    for c in ctxs:
        c.init()
    return ctxs


# -- framing -----------------------------------------------------------

@pytest.mark.parametrize("dtype", [
    np.float16, np.float32, np.float64, np.uint8, np.int8, np.int16,
    np.int32, np.int64, np.bool_, np.complex64, np.complex128,
    ml_dtypes.bfloat16])
def test_frame_roundtrip_dtypes(dtype):
    a, b = socket.socketpair()
    arr = np.arange(24).reshape(2, 3, 4).astype(dtype)
    dcn._send_frame(a, dcn._MSG_TENSORS, 7, [arr], channel=1)
    msg_type, aux, channel, out = dcn._recv_frame(b)
    assert msg_type == dcn._MSG_TENSORS and aux == 7 and channel == 1
    np.testing.assert_array_equal(np.asarray(out[0], np.float64),
                                  np.asarray(arr, np.float64))
    assert out[0].dtype == arr.dtype
    a.close(), b.close()


def test_frame_roundtrip_edge_shapes():
    a, b = socket.socketpair()
    tensors = [np.float32(3.5).reshape(()),                # 0-d
               np.zeros((0, 5), np.int32),                 # zero-size
               np.arange(20, dtype=np.float32)[::2]]       # non-contiguous
    dcn._send_frame(a, dcn._MSG_TENSORS, 0, tensors)
    _, _, _, out = dcn._recv_frame(b)
    assert out[0].shape == () and float(out[0]) == 3.5
    assert out[1].shape == (0, 5)
    np.testing.assert_array_equal(out[2], np.arange(0, 20, 2, dtype=np.float32))
    a.close(), b.close()


@pytest.mark.parametrize("dtype", [ml_dtypes.int4, ml_dtypes.uint4])
def test_frame_roundtrip_4bit_nibble_packed(dtype):
    """4-bit dtypes ride the wire nibble-packed (2 values/byte): values,
    dtype, and shape survive, including odd element counts (pad nibble)
    and signed two's-complement values."""
    lo = -8 if dtype == ml_dtypes.int4 else 0
    a, b = socket.socketpair()
    tensors = [np.arange(lo, lo + 15, dtype=np.int8).astype(dtype),  # odd n
               np.arange(lo, lo + 8, dtype=np.int8).astype(dtype).reshape(2, 4),
               np.asarray(5, np.int8).astype(dtype).reshape(())]      # 0-d
    dcn._send_frame(a, dcn._MSG_TENSORS, 3, tensors, channel=2)
    msg_type, aux, channel, out = dcn._recv_frame(b)
    assert (msg_type, aux, channel) == (dcn._MSG_TENSORS, 3, 2)
    for sent, got in zip(tensors, out):
        assert got.dtype == sent.dtype and got.shape == sent.shape
        np.testing.assert_array_equal(got.astype(np.int8),
                                      sent.astype(np.int8))
    a.close(), b.close()


def test_frame_4bit_wire_bytes_are_halved():
    """The nibble packing actually shrinks the on-wire payload: a 4-bit
    frame's socket bytes are ~half an int8 frame's (the whole point of
    sub-byte wire dtypes — their in-memory form burns a byte per value)."""
    n = 64
    u4 = np.zeros(n, np.int8).astype(ml_dtypes.uint4)
    i8 = np.zeros(n, np.int8)
    sizes = {}
    for name, arr in (("u4", u4), ("i8", i8)):
        a, b = socket.socketpair()
        dcn._send_frame(a, dcn._MSG_TENSORS, 0, [arr])
        a.close()
        data = bytearray()
        while True:
            chunk = b.recv(1 << 16)
            if not chunk:
                break
            data += chunk
        b.close()
        sizes[name] = len(data)
    overhead = dcn._HEADER.size + dcn._TENSOR_HEADER.size + dcn._DIM.size
    assert sizes["i8"] - overhead == n
    assert sizes["u4"] - overhead == n // 2


def test_frame_rejects_unknown_dtype():
    a, b = socket.socketpair()
    with pytest.raises(TypeError):
        dcn._send_frame(a, dcn._MSG_TENSORS, 0,
                        [np.array(["x"], dtype=object)])
    a.close(), b.close()


# -- context P2P -------------------------------------------------------

def test_context_send_recv_bidirectional():
    ctxs = _make_contexts(2)
    try:
        x = np.random.default_rng(0).normal(size=(4, 8)).astype(np.float32)
        y = np.arange(6, dtype=np.int64)
        ctxs[0].send_tensors(1, [x, y])
        got = ctxs[1].recv_tensors(0, timeout=10)
        np.testing.assert_array_equal(got[0], x)
        np.testing.assert_array_equal(got[1], y)
        # reverse direction on the 1->0 link
        ctxs[1].send_tensors(0, [y])
        np.testing.assert_array_equal(ctxs[0].recv_tensors(1, timeout=10)[0], y)
    finally:
        for c in ctxs:
            c.shutdown()


def test_self_loop_channels_demultiplex():
    """A rank sending to itself on two channels reads them back separately
    (the colocated data-rank + single-stage schedule case)."""
    ctxs = _make_contexts(1)
    try:
        a = np.arange(4, dtype=np.float32)
        b = np.arange(8, dtype=np.float32)
        ctxs[0].send_tensors(0, [a], channel=dcn.CHANNEL_DATA)
        ctxs[0].send_tensors(0, [b], channel=dcn.CHANNEL_RESULTS)
        got_b = ctxs[0].recv_tensors(0, timeout=10,
                                     channel=dcn.CHANNEL_RESULTS)
        got_a = ctxs[0].recv_tensors(0, timeout=10, channel=dcn.CHANNEL_DATA)
        np.testing.assert_array_equal(got_a[0], a)
        np.testing.assert_array_equal(got_b[0], b)
    finally:
        ctxs[0].shutdown()


def test_context_lifecycle_reusable():
    """Context-manager init/shutdown/reenter (reference test_context.py) —
    and the reentered session must actually move data, not just report
    initialized."""
    ports = _free_ports(1)
    ctx = dcn.DistDcnContext(1, 0, [("127.0.0.1", ports[0])])
    for session in range(2):
        with ctx:
            assert ctx.initialized
            x = np.full((3,), session, np.int32)
            ctx.send_tensors(0, [x])
            np.testing.assert_array_equal(
                ctx.recv_tensors(0, timeout=10)[0], x)
        assert not ctx.initialized


def test_cmd_broadcast_reaches_all_peers():
    received = {r: queue.Queue() for r in range(3)}
    handlers = {r: (lambda cmd, tensors, _r=r: received[_r].put((cmd, tensors)))
                for r in range(3)}
    ctxs = _make_contexts(3, handlers)
    try:
        sched = np.asarray([[1, 24], [25, 48]], np.int32)
        ctxs[0].cmd_broadcast(CMD_SCHED, [sched])
        for r in (1, 2):
            cmd, tensors = received[r].get(timeout=10)
            assert cmd == CMD_SCHED
            np.testing.assert_array_equal(tensors[0], sched)
        ctxs[0].cmd_broadcast(CMD_STOP)
        for r in (1, 2):
            cmd, tensors = received[r].get(timeout=10)
            assert cmd == CMD_STOP and tensors == ()
        assert received[0].empty()  # no self-delivery, like the reference
    finally:
        for c in ctxs:
            c.shutdown()


def test_send_recv_hooks_measure_wire_bytes():
    """Transport hooks see every data frame's actual bytes (reference
    p2p:132-152); command frames are not measured."""
    ctxs = _make_contexts(2)
    events = {"send_pre": [], "send": [], "recv_pre": [], "recv": []}
    ctxs[0].register_send_hooks(
        lambda dst, ch: events["send_pre"].append((dst, ch)),
        lambda dst, ch, ts: events["send"].append(
            (dst, ch, sum(t.nbytes for t in ts))))
    ctxs[1].register_recv_hooks(
        lambda src, ch: events["recv_pre"].append((src, ch)),
        lambda src, ch, ts: events["recv"].append(
            (src, ch, sum(t.nbytes for t in ts))))
    try:
        x = np.zeros((4, 8), np.float32)
        y = np.arange(6, dtype=np.int64)
        ctxs[0].send_tensors(1, [x, y], channel=dcn.CHANNEL_DATA)
        ctxs[1].recv_tensors(0, timeout=10)
        ctxs[0].cmd_broadcast(CMD_STOP)
        time.sleep(0.3)  # let rank 1's reader drain the command frame
        nbytes = x.nbytes + y.nbytes
        assert events["send_pre"] == [(1, dcn.CHANNEL_DATA)]
        assert events["send"] == [(1, dcn.CHANNEL_DATA, nbytes)]
        assert events["recv_pre"] == [(0, dcn.CHANNEL_DATA)]
        assert events["recv"] == [(0, dcn.CHANNEL_DATA, nbytes)]
    finally:
        for c in ctxs:
            c.shutdown()


# -- pipeline stages ---------------------------------------------------

def test_three_stage_pipeline_matches_single_shard():
    """Stream microbatches through 3 DCN stages (3 contexts in-process ==
    3 hosts on localhost) and compare with the monolithic forward."""
    pytest.importorskip("torch")
    import torch
    from transformers import ViTConfig, ViTForImageClassification

    from pipeedge_tpu.models import ShardConfig
    from pipeedge_tpu.models import vit as vit_mod
    from pipeedge_tpu.models.layers import TransformerConfig
    from pipeedge_tpu.models.shard import make_shard_fn

    tiny = dict(hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
                intermediate_size=64)
    torch.manual_seed(0)
    model = ViTForImageClassification(
        ViTConfig(**tiny, image_size=16, patch_size=4, num_labels=5)).eval()
    cfg = TransformerConfig(model_type="vit", **tiny, num_labels=5,
                            image_size=16, patch_size=4)
    weights = vit_mod.hf_to_npz_weights(model.state_dict(), cfg)
    total = 4 * cfg.num_hidden_layers

    full_sc = ShardConfig(1, total, is_first=True, is_last=True)
    params_full = vit_mod.load_params(cfg, full_sc, weights)
    full_fn = jax.jit(make_shard_fn(vit_mod.FAMILY, cfg, full_sc))

    partition = [(1, 3), (4, 5), (6, 8)]  # includes mid-block cuts
    stage_fns = []
    for (l, r) in partition:
        sc = ShardConfig(l, r, is_first=l == 1, is_last=r == total)
        sp = vit_mod.load_params(cfg, sc, weights)
        fn = jax.jit(make_shard_fn(vit_mod.FAMILY, cfg, sc))
        stage_fns.append((fn, sp))

    def work(stage_idx):
        fn, sp = stage_fns[stage_idx]
        def cb(tensors):
            data = jnp.asarray(tensors[0]) if len(tensors) == 1 \
                else tuple(jnp.asarray(t) for t in tensors)
            out = fn(sp, data)
            out = out if isinstance(out, tuple) else (out,)
            return [np.asarray(t) for t in out]
        return cb

    results = queue.Queue()
    ctxs = _make_contexts(3)
    stages = [
        dcn.DcnPipelineStage(ctxs[0], None, 1, work(0)),
        dcn.DcnPipelineStage(ctxs[1], 0, 2, work(1)),
        dcn.DcnPipelineStage(ctxs[2], 1, None, work(2),
                             results_cb=results.put),
    ]
    rng = np.random.default_rng(0)
    ubatches = [rng.normal(size=(2, 3, 16, 16)).astype(np.float32)
                for _ in range(4)]
    try:
        for s in stages:
            s.start()
        for u in ubatches:
            stages[0].enqueue_tensors([u])
        outs = [results.get(timeout=60) for _ in ubatches]
    finally:
        for s in stages:
            s.stop()
        for c in ctxs:
            c.shutdown()
    for u, out in zip(ubatches, outs):  # FIFO order is guaranteed
        expect = np.asarray(full_fn(params_full, jnp.asarray(u)))
        np.testing.assert_allclose(out[0], expect, rtol=2e-4, atol=2e-4)


def test_stage_stop_while_blocked():
    """stop() releases a work thread blocked on a full hand-off queue."""
    ctxs = _make_contexts(1)
    blocker = threading.Event()

    def slow(tensors):
        blocker.wait(5)
        return tensors

    stage = dcn.DcnPipelineStage(ctxs[0], None, None, slow,
                                 results_cb=lambda x: time.sleep(1))
    try:
        stage.start()
        for _ in range(2):
            stage.enqueue_tensors([np.zeros(2, np.float32)])
        blocker.set()
        stage.stop()
        assert all(not t.is_alive() for t in stage._threads)
    finally:
        ctxs[0].shutdown()


def test_context_int8_uint4_frames_roundtrip():
    """Quantized wire payloads (int8 values, nibble-packed uint4) survive
    the full send_tensors/recv_tensors path between two contexts."""
    ctxs = _make_contexts(2)
    try:
        rng = np.random.default_rng(3)
        i8 = rng.integers(-128, 128, size=(4, 33), dtype=np.int64).astype(np.int8)
        u4 = rng.integers(0, 16, size=(3, 7)).astype(np.uint8).astype(
            ml_dtypes.uint4)                      # odd inner dim: pad nibble
        scale = rng.normal(size=(4,)).astype(np.float32)
        ctxs[0].send_tensors(1, [i8, u4, scale])
        got = ctxs[1].recv_tensors(0, timeout=10)
        np.testing.assert_array_equal(got[0], i8)
        assert got[1].dtype == u4.dtype
        np.testing.assert_array_equal(got[1].astype(np.uint8),
                                      u4.astype(np.uint8))
        np.testing.assert_array_equal(got[2], scale)
    finally:
        for c in ctxs:
            c.shutdown()


# -- peer death on the receive path ------------------------------------

def test_truncated_frame_mid_payload_raises():
    """A peer dying mid-payload must surface as ConnectionError("peer
    closed") — never as a short or garbage tensor (comm/dcn.py:195)."""
    a, b = socket.socketpair()
    # header promises one float32[100] tensor (400 payload bytes)...
    parts = [dcn._HEADER.pack(dcn._MSG_TENSORS, 0, 0, 1),
             dcn._TENSOR_HEADER.pack(dcn._dtype_code(np.dtype(np.float32)),
                                     1),
             dcn._DIM.pack(100)]
    a.sendall(b"".join(parts))
    a.sendall(b"\x00" * 40)    # ...but delivers only 40 before dying
    a.close()
    with pytest.raises(ConnectionError, match="peer closed"):
        dcn._recv_frame(b)
    b.close()


def test_context_mid_payload_death_never_yields_garbage():
    """Context-level version: a raw peer HELLOs, starts a tensor frame,
    and dies mid-payload. recv_tensors must raise the peer's death, not
    deliver a partial tensor."""
    ctxs = _make_contexts(2)
    deaths = queue.Queue()
    ctxs[0].register_peer_death_handler(deaths.put)
    try:
        host, port = ctxs[0]._rank_addrs[0]
        raw = socket.create_connection((host, port))
        dcn._send_frame(raw, dcn._MSG_HELLO, 1, ())
        raw.sendall(dcn._HEADER.pack(dcn._MSG_TENSORS, 1, 0, 1))
        raw.sendall(dcn._TENSOR_HEADER.pack(
            dcn._dtype_code(np.dtype(np.float32)), 1))
        raw.sendall(dcn._DIM.pack(64))
        raw.sendall(b"\x00" * 16)          # 16 of 256 payload bytes
        raw.close()
        assert deaths.get(timeout=10) == 1
        with pytest.raises(ConnectionError, match="died"):
            ctxs[0].recv_tensors(1, timeout=10)
    finally:
        for c in ctxs:
            c.shutdown()


def test_peer_restart_within_grace_reconnects():
    """With a reconnect grace window, a RESTARTING peer (listener rebinds
    and HELLOs again before the window expires) is revived: the death
    handler never fires and traffic flows to the new incarnation."""
    ports = _free_ports(2)
    addrs = [("127.0.0.1", p) for p in ports]
    watcher = dcn.DistDcnContext(2, 0, addrs, reconnect_grace=1.5)
    watcher.init()
    deaths = queue.Queue()
    watcher.register_peer_death_handler(deaths.put)
    peer = dcn.DistDcnContext(2, 1, addrs)
    peer.init()
    try:
        peer.send_tensors(0, [np.arange(3, dtype=np.int32)])
        watcher.recv_tensors(1, timeout=10)
        peer.shutdown()                      # connections drop...
        peer = dcn.DistDcnContext(2, 1, addrs)
        peer.init()                          # ...and the rank restarts
        peer.send_tensors(0, [np.full((2,), 7, np.int32)])
        got = watcher.recv_tensors(1, timeout=10)
        np.testing.assert_array_equal(got[0], np.full((2,), 7, np.int32))
        # outlive the grace window: the pending death must have been
        # revived by the reconnect, not merely delayed
        time.sleep(2.0)
        assert deaths.empty(), f"death fired for rank {deaths.get()}"
        assert not watcher.dead_ranks()
    finally:
        peer.shutdown()
        watcher.shutdown()


def test_grace_window_expires_to_death():
    """A peer that drops and does NOT return within the grace window is
    declared dead when the window expires."""
    ports = _free_ports(2)
    addrs = [("127.0.0.1", p) for p in ports]
    watcher = dcn.DistDcnContext(2, 0, addrs, reconnect_grace=0.5)
    watcher.init()
    deaths = queue.Queue()
    watcher.register_peer_death_handler(deaths.put)
    peer = dcn.DistDcnContext(2, 1, addrs)
    peer.init()
    try:
        peer.send_tensors(0, [np.zeros(1, np.float32)])
        watcher.recv_tensors(1, timeout=10)
        peer.shutdown()                      # gone for good
        assert deaths.get(timeout=10) == 1
        assert 1 in watcher.dead_ranks()
    finally:
        watcher.shutdown()


# -- liveness plane ----------------------------------------------------

def test_heartbeat_detects_beat_silent_peer():
    """The hung-rank case: a peer whose sockets stay OPEN but whose beats
    stop is declared dead after interval * miss_threshold — the failure
    mode stream errors can never catch."""
    ctxs = _make_contexts(2)
    deaths = queue.Queue()
    beats = queue.Queue()
    ctxs[0].register_peer_death_handler(deaths.put)
    ctxs[0].register_heartbeat_hook(beats.put)
    try:
        ctxs[0].start_heartbeat([1], interval=0.2, miss_threshold=3)
        ctxs[1].start_heartbeat([0], interval=0.2, miss_threshold=3)
        assert beats.get(timeout=10) == 1    # rank 1's beats are flowing
        # rank 1 "hangs": beats stop, every socket stays open
        ctxs[1].stop_heartbeat()
        assert deaths.get(timeout=10) == 1
        assert 1 in ctxs[0].dead_ranks()
    finally:
        for c in ctxs:
            c.shutdown()


def test_heartbeat_disabled_by_default():
    ctxs = _make_contexts(1)
    try:
        ctxs[0].start_heartbeat([0])   # env interval unset -> no thread
        assert ctxs[0]._hb_thread is None
    finally:
        ctxs[0].shutdown()


# -- elastic membership: epoch fencing + JOIN admission ----------------

def _wait_for(predicate, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def test_stale_epoch_frames_fenced_after_death():
    """Frames from a dead incarnation never reach the receive queues: a
    'zombie' context HELLOing with the fenced epoch has every frame
    dropped at the reader (counter bump), while a NEW incarnation (higher
    epoch) that passes the JOIN handshake flows normally."""
    ports = _free_ports(2)
    addrs = [("127.0.0.1", p) for p in ports]
    watcher = dcn.DistDcnContext(2, 0, addrs)
    watcher.init()
    deaths = queue.Queue()
    rejoins = queue.Queue()
    watcher.register_peer_death_handler(deaths.put)
    watcher.register_peer_rejoin_handler(lambda r, e: rejoins.put((r, e)))
    peer = dcn.DistDcnContext(2, 1, addrs)       # incarnation 0
    peer.init()
    zombie = fresh = None
    try:
        peer.send_tensors(0, [np.arange(3, dtype=np.int32)])
        watcher.recv_tensors(1, timeout=10)
        peer.shutdown()                          # dies for real
        assert deaths.get(timeout=10) == 1
        assert 1 in watcher.dead_ranks()
        assert watcher.min_epoch_of(1) == 1      # incarnation 0 fenced
        # a zombie of the dead incarnation keeps sending: every frame is
        # dropped at the reader and never reaches the queue/ledger
        zombie = dcn.DistDcnContext(2, 1, addrs, epoch=0)
        zombie.init()
        zombie.send_tensors(0, [np.full((2,), 6, np.int32)])
        _wait_for(lambda: watcher.stale_frames_dropped >= 1,
                  what="stale frame drop")
        assert 1 in watcher.dead_ranks()         # a zombie does not revive
        zombie.shutdown()                        # frees rank 1's listener
        zombie = None
        # the restarted incarnation joins with a higher epoch: admitted,
        # un-deaded, and its frames flow
        fresh = dcn.DistDcnContext(2, 1, addrs, epoch=1)
        fresh.init()
        assert fresh.announce_join() == [0]
        assert rejoins.get(timeout=10) == (1, 1)
        assert 1 not in watcher.dead_ranks()
        fresh.send_tensors(0, [np.full((2,), 7, np.int32)])
        got, epoch = watcher.recv_tensors_meta(1, timeout=10)
        np.testing.assert_array_equal(got[0], np.full((2,), 7, np.int32))
        assert epoch == 1
    finally:
        for c in (zombie, fresh, watcher):
            if c is not None:
                c.shutdown()


def test_join_refused_when_accept_joins_off():
    """accept_joins=False (--on-peer-rejoin ignore): the JOIN handshake
    is refused and a confirmed death stays terminal."""
    ports = _free_ports(2)
    addrs = [("127.0.0.1", p) for p in ports]
    watcher = dcn.DistDcnContext(2, 0, addrs, accept_joins=False)
    watcher.init()
    deaths = queue.Queue()
    rejoins = queue.Queue()
    watcher.register_peer_death_handler(deaths.put)
    watcher.register_peer_rejoin_handler(lambda r, e: rejoins.put((r, e)))
    peer = dcn.DistDcnContext(2, 1, addrs)
    peer.init()
    fresh = None
    try:
        peer.send_tensors(0, [np.zeros(1, np.float32)])
        watcher.recv_tensors(1, timeout=10)
        peer.shutdown()
        assert deaths.get(timeout=10) == 1
        fresh = dcn.DistDcnContext(2, 1, addrs, epoch=1)
        fresh.init()
        fresh.announce_join()
        time.sleep(0.5)                          # ack round trip
        assert rejoins.empty()
        assert 1 in watcher.dead_ranks()
    finally:
        for c in (fresh, watcher):
            if c is not None:
                c.shutdown()


def test_heartbeat_rewatch_dead_alive_dead():
    """Heartbeat hygiene across a rejoin: the liveness plane resumes
    watching a re-admitted rank, so a SECOND death of the same rank is
    detected exactly like the first (satellite: dead -> alive -> dead)."""
    ports = _free_ports(2)
    addrs = [("127.0.0.1", p) for p in ports]
    watcher = dcn.DistDcnContext(2, 0, addrs)
    watcher.init()
    deaths = queue.Queue()
    beats = queue.Queue()
    watcher.register_peer_death_handler(deaths.put)
    watcher.register_heartbeat_hook(beats.put)
    peer = dcn.DistDcnContext(2, 1, addrs)
    peer.init()
    fresh = None
    try:
        watcher.start_heartbeat([1], interval=0.2, miss_threshold=3)
        peer.start_heartbeat([0], interval=0.2, miss_threshold=3)
        assert beats.get(timeout=10) == 1
        # first death: beats stop AND the sockets drop
        peer.stop_heartbeat()
        peer.shutdown()
        assert deaths.get(timeout=10) == 1
        # restart as a new incarnation; admission must reset the watch
        # (_hb_last_rx) and the beat-loop dial backoff for rank 1
        fresh = dcn.DistDcnContext(2, 1, addrs, epoch=1)
        fresh.init()
        assert fresh.announce_join() == [0]
        _wait_for(lambda: 1 not in watcher.dead_ranks(), what="rejoin")
        while not beats.empty():
            beats.get()
        fresh.start_heartbeat([0], interval=0.2, miss_threshold=3)
        assert beats.get(timeout=10) == 1        # beats flow again
        # second death of the SAME rank: beats stop, sockets stay open —
        # only a live re-armed watch can catch it
        fresh.stop_heartbeat()
        assert deaths.get(timeout=10) == 1
        assert 1 in watcher.dead_ranks()
    finally:
        for c in (fresh, watcher):
            if c is not None:
                c.shutdown()


def test_send_retries_heal_transient_break(monkeypatch):
    """DCN_SEND_RETRIES: a send hitting a broken connection redials and
    resends instead of failing — paired with a receiver-side grace window
    so the torn frame's drop is not declared a death."""
    ports = _free_ports(2)
    addrs = [("127.0.0.1", p) for p in ports]
    rx = dcn.DistDcnContext(2, 0, addrs, reconnect_grace=5.0)
    tx = dcn.DistDcnContext(2, 1, addrs, send_retries=2)
    rx.init()
    tx.init()
    deaths = queue.Queue()
    rx.register_peer_death_handler(deaths.put)
    try:
        tx.send_tensors(0, [np.zeros(4, np.float32)])
        rx.recv_tensors(1, timeout=10)
        # sever the established data connection from UNDER the sender
        with tx._conns_lock:
            conn = tx._conns[0]
        conn.shutdown(socket.SHUT_RDWR)
        conn.close()
        tx.send_tensors(0, [np.full((4,), 9, np.float32)])   # heals
        got = rx.recv_tensors(1, timeout=10)
        np.testing.assert_array_equal(got[0], np.full((4,), 9, np.float32))
        assert deaths.empty()
    finally:
        tx.shutdown()
        rx.shutdown()


# -- edge bitwidth negotiation -----------------------------------------

def test_edge_bit_negotiation_caps_to_receiver():
    """The control-channel handshake returns what the CONSUMER accepts:
    the proposal when supported, else the widest supported bitwidth below
    it, else 0 (uncompressed)."""
    ports = _free_ports(2)
    addrs = [("127.0.0.1", p) for p in ports]
    ctxs = [dcn.DistDcnContext(2, 0, addrs),
            dcn.DistDcnContext(2, 1, addrs, edge_bits_supported=(0, 4, 8))]
    for c in ctxs:
        c.init()
    try:
        assert ctxs[0].negotiate_edge_bits(1, 8) == 8     # supported as-is
        assert ctxs[0].negotiate_edge_bits(1, 16) == 8    # capped down
        assert ctxs[0].negotiate_edge_bits(1, 6) == 4     # next lower
        assert ctxs[0].negotiate_edge_bits(1, 2) == 0     # nothing below
        assert ctxs[1].negotiate_edge_bits(0, 16) == 16   # default set
        # colocated producer/consumer: the self-loopback edge negotiates too
        assert ctxs[0].negotiate_edge_bits(0, 8) == 8
    finally:
        for c in ctxs:
            c.shutdown()


# -- overlapped stage (dispatch/readback split, depth >= 2) ------------

def test_stage_contract_validation():
    ctxs = _make_contexts(1)
    try:
        with pytest.raises(ValueError, match="not both"):
            dcn.DcnPipelineStage(ctxs[0], None, None, work_cb=lambda t: t,
                                 dispatch_cb=lambda t: t)
        with pytest.raises(ValueError, match="requires dispatch_cb"):
            dcn.DcnPipelineStage(ctxs[0], None, None,
                                 readback_cb=lambda t: t)
        with pytest.raises(ValueError, match="depth"):
            dcn.DcnPipelineStage(ctxs[0], None, None, work_cb=lambda t: t,
                                 depth=0)
        with pytest.raises(ValueError, match="needs a"):
            dcn.DcnPipelineStage(ctxs[0], 0, 1)   # wired but no callback
        # idle (not-in-schedule) stage: no ranks, no callback — fine
        dcn.DcnPipelineStage(ctxs[0], None, None).start()
    finally:
        ctxs[0].shutdown()


def test_stage_depth2_preserves_fifo_under_jitter():
    """With depth 2 and the dispatch/readback split, microbatches retire
    in exactly the order they entered even when per-item phase costs
    vary (the acceptance-criteria FIFO guarantee for depth >= 2)."""
    ctxs = _make_contexts(1)
    rng = np.random.default_rng(0)
    delays = rng.uniform(0.0, 0.01, size=(16, 2))
    results = queue.Queue()
    idx = [0, 0]

    def dispatch(tensors):
        time.sleep(delays[idx[0] % len(delays)][0])
        idx[0] += 1
        return tensors

    def readback(tensors):
        time.sleep(delays[idx[1] % len(delays)][1])
        idx[1] += 1
        return tensors

    stage = dcn.DcnPipelineStage(ctxs[0], None, None, dispatch_cb=dispatch,
                                 readback_cb=readback, depth=2,
                                 results_cb=results.put)
    try:
        stage.start()
        n = 16
        for i in range(n):
            stage.enqueue_tensors([np.full((2,), i, np.int32)])
        outs = [results.get(timeout=30) for _ in range(n)]
    finally:
        stage.stop()
        ctxs[0].shutdown()
    assert [int(o[0][0]) for o in outs] == list(range(n))


def test_stage_overlap_beats_serialized_depth1():
    """Overlapped configuration (dispatch/readback split, depth 2) has
    lower steady-state microbatch latency than the pre-overlap one
    (single-phase work_cb, depth 1 — compute and readback serialize on
    the work thread). Phase costs are fixed sleeps, so serialized costs
    work+drain per microbatch and perfect overlap costs max(work, drain):
    generous margins keep this robust on a loaded machine."""
    ctxs = _make_contexts(1)
    work_s = drain_s = 0.02
    n = 12

    def run(depth, split):
        results = queue.Queue()

        def dispatch(tensors):
            time.sleep(work_s)
            return tensors

        def readback(tensors):
            time.sleep(drain_s)
            return tensors

        if split:
            stage = dcn.DcnPipelineStage(
                ctxs[0], None, None, dispatch_cb=dispatch,
                readback_cb=readback, depth=depth, results_cb=results.put)
        else:
            stage = dcn.DcnPipelineStage(
                ctxs[0], None, None,
                work_cb=lambda t: readback(dispatch(t)),
                depth=depth, results_cb=results.put)
        stage.start()
        try:
            tik = time.monotonic()
            for i in range(n):
                stage.enqueue_tensors([np.full((1,), i, np.int32)])
            outs = [results.get(timeout=60) for _ in range(n)]
            elapsed = time.monotonic() - tik
        finally:
            stage.stop()
        assert [int(o[0][0]) for o in outs] == list(range(n))
        return elapsed

    try:
        serialized = run(depth=1, split=False)
        overlapped = run(depth=2, split=True)
    finally:
        ctxs[0].shutdown()
    # ideal: 2x (work == drain); require a solid 1.33x so scheduler noise
    # can't flake the assertion while a regression to serialization
    # (ratio ~1.0) is still caught
    assert overlapped < serialized * 0.75, (
        f"overlap ineffective: serialized {serialized:.3f}s vs "
        f"overlapped {overlapped:.3f}s")


def test_cmd_broadcast_bypasses_backpressured_data_send():
    """Commands ride dedicated per-peer connections: an abort broadcast must
    complete even while a data send to the same peer is blocked on
    backpressure (the receiver's single-slot queue is full and the kernel
    socket buffers are saturated). Regression for a deadlock class where
    commands queued behind a blocked data send on a shared socket."""
    got = queue.Queue()
    ctxs = _make_contexts(2, handlers={1: lambda c, t: got.put(c)})
    a, b = ctxs
    try:
        # saturate a->b: b never drains its recv queue, so after the 1-slot
        # queue + kernel buffers fill, the sender blocks inside sendmsg
        big = np.zeros(4 * 1024 * 1024, np.uint8)
        sent = [0]

        def flood():
            try:
                while True:
                    a.send_tensors(1, [big])
                    sent[0] += 1
            except OSError:
                pass  # context shutdown closes the socket

        t = threading.Thread(target=flood, daemon=True)
        t.start()
        # wait until the flood stalls (no progress for a full second)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            before = sent[0]
            time.sleep(1.0)
            if sent[0] == before and before > 0:
                break
        assert sent[0] > 0, "flood never sent a frame"
        tik = time.monotonic()
        a.cmd_broadcast(CMD_STOP)
        elapsed = time.monotonic() - tik
        assert got.get(timeout=10) == CMD_STOP
        assert elapsed < 5, f"cmd_broadcast took {elapsed:.1f}s behind a " \
                            "blocked data send"
    finally:
        for c in ctxs:
            c.shutdown()
