"""Multi-process runtime integration over the DCN transport: the reference's
`runtime.py RANK WORLDSIZE` deployment shape (one OS process per rank,
schedule broadcast via CMD_SCHED, results + CMD_STOP), on localhost."""
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n):
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def test_two_process_dcn_runtime_quantized_edge(tmp_path):
    addrs = ",".join(f"127.0.0.1:{p}" for p in _free_ports(2))
    common = [sys.executable, os.path.join(REPO, "runtime.py")]
    opts = ["-c", "dcn", "--platform", "cpu",
            "-m", "pipeedge/test-tiny-vit", "-b", "16", "-u", "4",
            "-pt", "1,4,5,8", "-q", "8,0", "-r", "0,1",
            "--dcn-addrs", addrs, "--sched-timeout", "120"]
    env = dict(os.environ, PYTHONPATH=REPO)
    worker = subprocess.Popen(common + ["1", "2"] + opts, cwd=tmp_path,
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
    try:
        data = subprocess.run(common + ["0", "2"] + opts, cwd=tmp_path,
                              env=env, capture_output=True, text=True,
                              timeout=240)
        wout, _ = worker.communicate(timeout=60)
    finally:
        worker.kill()
    assert data.returncode == 0, data.stdout + data.stderr
    assert "latency_sec=" in data.stdout
    assert worker.returncode == 0, wout
    assert "======= pipeedge/test-tiny-vit stage 1: layers [5, 8]" in wout
