"""Multi-process runtime integration over the DCN transport: the reference's
`runtime.py RANK WORLDSIZE` deployment shape (one OS process per rank,
schedule broadcast via CMD_SCHED, results + CMD_STOP), on localhost."""
import os
import socket
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


import pytest

pytestmark = pytest.mark.fleet  # every test here spawns OS processes

def _free_ports(n):
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class _OutReader:
    """Drain a subprocess's stdout on a thread so the test can poll for a
    marker without blocking on readline."""

    def __init__(self, proc):
        self.proc = proc
        self.lines = []
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        for line in self.proc.stdout:
            self.lines.append(line)

    def wait_for(self, needle, timeout):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if any(needle in line for line in self.lines):
                return True
            if self.proc.poll() is not None:
                self._thread.join(timeout=5)
                return any(needle in line for line in self.lines)
            time.sleep(0.05)
        return False

    def text(self):
        self._thread.join(timeout=5)
        return "".join(self.lines)



def _run_fleet(tmp_path, opts, world, env_extra=None, per_rank_dirs=False,
               data_timeout=300, script="runtime.py",
               rank_argv=lambda r, world: [str(r), str(world)]):
    """Launch a `world`-rank DCN fleet (workers as Popen, the data rank in
    the foreground), collect everyone's output.

    Returns (data CompletedProcess, [worker stdout by rank], rank_dirs).
    `opts` excludes --dcn-addrs (allocated here). Worker processes are
    always killed on exit. `script` (repo-relative) + `rank_argv(r, world)`
    cover CLIs with other rank conventions (e.g. tools/generate.py)."""
    addrs = ",".join(f"127.0.0.1:{p}" for p in _free_ports(world))
    common = [sys.executable, os.path.join(REPO, script)]
    argv = opts + ["--dcn-addrs", addrs]
    env = dict(os.environ, PYTHONPATH=REPO, **(env_extra or {}))
    if per_rank_dirs:
        rank_dirs = []
        for r in range(world):
            d = tmp_path / f"rank{r}"
            d.mkdir()
            rank_dirs.append(d)
    else:
        rank_dirs = [tmp_path] * world
    workers = [subprocess.Popen(common + rank_argv(r, world) + argv,
                                cwd=rank_dirs[r], env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)
               for r in range(1, world)]
    try:
        data = subprocess.run(common + rank_argv(0, world) + argv,
                              cwd=rank_dirs[0], env=env, capture_output=True,
                              text=True, timeout=data_timeout)
        wouts = [w.communicate(timeout=60)[0] for w in workers]
    finally:
        for w in workers:
            w.kill()
    for r, (w, wout) in enumerate(zip(workers, wouts), start=1):
        assert w.returncode == 0, f"rank {r}:\n{wout}"
    return data, wouts, rank_dirs

def test_two_process_dcn_runtime_quantized_edge(tmp_path):
    data, wouts, _ = _run_fleet(
        tmp_path, ["-c", "dcn", "--platform", "cpu",
                   "-m", "pipeedge/test-tiny-vit", "-b", "16", "-u", "4",
                   "-pt", "1,4,5,8", "-q", "8,0", "-r", "0,1",
                   "--sched-timeout", "120"], world=2, data_timeout=240)
    assert data.returncode == 0, data.stdout + data.stderr
    assert "latency_sec=" in data.stdout
    assert "======= pipeedge/test-tiny-vit stage 1: layers [5, 8]" in wouts[0]


def test_two_process_dcn_adaptive_quant(tmp_path):
    """Adaptive quantization over DCN: rank 0 (stage 0) measures its own
    send window via the transport hooks and adapts its output-edge bitwidth;
    the bitwidth rides the wire header so rank 1 decodes without
    coordination (reference per-rank policy, runtime.py:121-216)."""
    data, wouts, rank_dirs = _run_fleet(
        tmp_path, ["-c", "dcn", "--platform", "cpu",
                   "-m", "pipeedge/test-tiny-vit", "-b", "24", "-u", "4",
                   "-pt", "1,4,5,8", "-q", "8,0", "-r", "0,1",
                   "--sched-timeout", "120"], world=2,
        env_extra={"ADAPTIVE_QUANT": "HEURISTIC", "SEND_CONSTRAINT": "100",
                   "WINDOW_SIZE": "3"}, per_rank_dirs=True, data_timeout=240)
    assert data.returncode == 0, data.stdout + data.stderr
    # the data rank hosts stage 0, whose policy adapts its output edge
    assert "Adaptive quantization" in data.stdout + data.stderr
    # transport hooks produced per-rank wire telemetry CSVs
    assert (rank_dirs[0] / "send.csv").exists()
    assert (rank_dirs[1] / "recv.csv").exists()


def test_peer_death_aborts_fleet(tmp_path):
    """Fault tolerance beyond the reference (whose RPC backpressure 'breaks
    down if the previous stage fails to send data afterward',
    rpc/__init__.py:83-86): kill a middle stage mid-run and assert the whole
    fleet stops deterministically — well before --sched-timeout — with every
    surviving rank raising a message naming the dead rank."""
    addrs = ",".join(f"127.0.0.1:{p}" for p in _free_ports(3))
    common = [sys.executable, os.path.join(REPO, "runtime.py")]
    opts = ["-c", "dcn", "--platform", "cpu",
            "-m", "pipeedge/test-tiny-vit", "-b", "1024", "-u", "4",
            "-pt", "1,2,3,5,6,8", "-q", "0,0,0", "-r", "0,1,2",
            "--dcn-addrs", addrs, "--sched-timeout", "600"]
    env = dict(os.environ, PYTHONPATH=REPO)

    def launch(rank):
        return subprocess.Popen(common + [str(rank), "3"] + opts,
                                cwd=tmp_path, env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)

    victim, survivor = launch(1), launch(2)
    victim_out, survivor_out = _OutReader(victim), _OutReader(survivor)
    data = launch(0)
    data_out = _OutReader(data)
    try:
        # the victim has its schedule and model built; data is about to flow
        assert victim_out.wait_for("stage 1: layers", 180), victim_out.text()
        victim.kill()
        # detection + CMD_STOP fan-out, NOT the 600s timeout
        data.wait(timeout=120)
        survivor.wait(timeout=60)
    finally:
        for proc in (victim, survivor, data):
            proc.kill()
    assert data.returncode not in (None, 0), data_out.text()
    assert "died" in data_out.text(), data_out.text()
    assert survivor.returncode not in (None, 0), survivor_out.text()
    assert "died" in survivor_out.text(), survivor_out.text()


def test_four_process_idle_rank_adaptive_quant(tmp_path):
    """4 ranks, 3-stage schedule: rank 3 is NOT in the schedule and must idle
    until CMD_STOP (reference model_cfg.py:154-159, runtime.py:456-460), while
    the scheduled ranks run a mixed-bitwidth quantized pipeline with the
    adaptive policy live on every edge's owner."""
    data, wouts, rank_dirs = _run_fleet(
        tmp_path, ["-c", "dcn", "--platform", "cpu",
                   "-m", "pipeedge/test-tiny-vit", "-b", "32", "-u", "4",
                   "-pt", "1,2,3,5,6,8", "-q", "8,4,0", "-r", "0,1,2",
                   "--sched-timeout", "180"], world=4,
        env_extra={"ADAPTIVE_QUANT": "HEURISTIC", "SEND_CONSTRAINT": "100",
                   "WINDOW_SIZE": "3"}, per_rank_dirs=True)
    assert data.returncode == 0, data.stdout + data.stderr
    assert "latency_sec=" in data.stdout
    assert "stage 1: layers [3, 5]" in wouts[0]
    assert "stage 2: layers [6, 8]" in wouts[1]
    assert "not in schedule; idling" in wouts[2]
    # stage 0 (data rank) and stage 1 both own quantized output edges whose
    # bitwidth the policy adapts on their measured send window
    assert "Adaptive quantization" in data.stdout + data.stderr
    assert "Adaptive quantization" in wouts[0]
    # per-rank wire telemetry from the transport hooks
    assert (rank_dirs[0] / "send.csv").exists()
    assert (rank_dirs[1] / "send.csv").exists()
    assert (rank_dirs[2] / "recv.csv").exists()


def test_live_reschedule_two_rounds(tmp_path):
    """Live re-scheduling over one DCN fleet: the reference DESIGNED this
    (CMD_SCHED lands on a queue any time, runtime.py:404-415) but its runtime
    consumes exactly one schedule. Here the data rank broadcasts a second,
    DIFFERENT partition at the run boundary and the same worker processes
    rebuild their stages and run again — ending on an empty CMD_SCHED."""
    data, wouts, _ = _run_fleet(
        tmp_path, ["-c", "dcn", "--platform", "cpu",
                   "-m", "pipeedge/test-tiny-vit", "-b", "16", "-u", "4",
                   "-pt", "1,4,5,8;1,2,3,8", "-q", "8,0;4,0", "-r", "0,1",
                   "--sched-timeout", "180"], world=2)
    assert data.returncode == 0, data.stdout + data.stderr
    # one latency report per round
    assert data.stdout.count("latency_sec=") == 2, data.stdout
    assert "re-schedule: broadcasting round 1" in data.stdout + data.stderr
    # the worker rebuilt its stage with the round-2 partition
    assert "stage 1: layers [5, 8]" in wouts[0]
    assert "stage 1: layers [3, 8]" in wouts[0]
    assert "empty CMD_SCHED; shutting down" in wouts[0]


def test_dcn_stage_tp_hierarchical(tmp_path):
    """Hierarchical parallelism the reference cannot express: pipeline
    stages span hosts over DCN (TCP) while each rank Megatron-TP-shards its
    stage's blocks over its local devices (--stage-tp). Numerical equality
    of the TP block against the plain block is covered by
    tests/test_tensor_parallel.py; this exercises the full runtime path."""
    data, wouts, _ = _run_fleet(
        tmp_path, ["-c", "dcn", "--platform", "cpu", "--stage-tp", "2",
                   "-m", "pipeedge/test-tiny-vit", "-b", "16", "-u", "4",
                   "-pt", "1,4,5,8", "-q", "8,0", "-r", "0,1",
                   "--sched-timeout", "180"], world=2,
        env_extra={"XLA_FLAGS": "--xla_force_host_platform_device_count=2"})
    assert data.returncode == 0, data.stdout + data.stderr
    assert "latency_sec=" in data.stdout
    assert "TP-sharded over 2 local devices" in data.stdout + data.stderr
    assert "TP-sharded over 2 local devices" in wouts[0]


def test_tp_stage_matches_plain_stage():
    """_make_tp_stage output == plain module_shard_factory stage output for
    both shard ends (embed+block and block+finalize)."""
    import argparse

    import jax.numpy as jnp
    import numpy as np

    import runtime as rt
    from pipeedge_tpu.models import registry

    rng = np.random.default_rng(0)
    for model, payload in (
            ("pipeedge/test-tiny-vit",
             jnp.asarray(rng.normal(size=(2, 3, 16, 16)), jnp.float32)),
            ("pipeedge/test-tiny-bert",
             jnp.asarray(rng.integers(0, 30, size=(2, 9)), jnp.int32))):
        args = argparse.Namespace(stage_tp=2, model_name=model,
                                  model_file=None)
        for l, r, stage in ((1, 4, 0), (5, 8, 1)):
            fn_ref, p_ref, _ = registry.module_shard_factory(
                model, None, l, r, stage=stage, dtype=jnp.float32)
            fn_tp, p_tp = rt._make_tp_stage(args, l, r, stage, jnp.float32,
                                            None)
            ref = np.asarray(fn_ref(p_ref, payload))
            got = np.asarray(fn_tp(p_tp, payload))
            np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)
            payload = fn_ref(p_ref, payload)


def test_multi_round_shifting_fleet(tmp_path):
    """The whole control-plane feature matrix on one fleet: three schedule
    rounds where the partition, rank order, stage count, AND the idle set
    all change between rounds, with adaptive quantization and TP-sharded
    stages throughout. Round 2 runs single-stage on a rank that was idle in
    round 1; round 3 swaps the rank order of round 1."""
    data, wouts, _ = _run_fleet(
        tmp_path, ["-c", "dcn", "--platform", "cpu", "--stage-tp", "2",
                   "-m", "pipeedge/test-tiny-vit", "-b", "24", "-u", "4",
                   "-pt", "1,4,5,8;1,8;1,4,5,8", "-q", "8,0;0;4,0",
                   "-r", "0,1;2;1,0", "--sched-timeout", "180"], world=4,
        env_extra={"ADAPTIVE_QUANT": "HEURISTIC", "SEND_CONSTRAINT": "100",
                   "WINDOW_SIZE": "3",
                   "XLA_FLAGS": "--xla_force_host_platform_device_count=2"},
        per_rank_dirs=True, data_timeout=420)
    assert data.returncode == 0, data.stdout + data.stderr
    assert data.stdout.count("latency_sec=") == 3, data.stdout
    for wout in wouts:
        assert "Traceback" not in wout, wout
    # rank 2 idles in round 1, runs the whole model in round 2
    assert "not in schedule; idling" in wouts[1]
    assert "stage 0: layers [1, 8]" in wouts[1]
    # rank 3 never appears in any schedule: idles all three rounds
    assert wouts[2].count("not in schedule; idling") == 3
