"""Multi-process runtime integration over the DCN transport: the reference's
`runtime.py RANK WORLDSIZE` deployment shape (one OS process per rank,
schedule broadcast via CMD_SCHED, results + CMD_STOP), on localhost."""
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n):
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def test_two_process_dcn_runtime_quantized_edge(tmp_path):
    addrs = ",".join(f"127.0.0.1:{p}" for p in _free_ports(2))
    common = [sys.executable, os.path.join(REPO, "runtime.py")]
    opts = ["-c", "dcn", "--platform", "cpu",
            "-m", "pipeedge/test-tiny-vit", "-b", "16", "-u", "4",
            "-pt", "1,4,5,8", "-q", "8,0", "-r", "0,1",
            "--dcn-addrs", addrs, "--sched-timeout", "120"]
    env = dict(os.environ, PYTHONPATH=REPO)
    worker = subprocess.Popen(common + ["1", "2"] + opts, cwd=tmp_path,
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
    try:
        data = subprocess.run(common + ["0", "2"] + opts, cwd=tmp_path,
                              env=env, capture_output=True, text=True,
                              timeout=240)
        wout, _ = worker.communicate(timeout=60)
    finally:
        worker.kill()
    assert data.returncode == 0, data.stdout + data.stderr
    assert "latency_sec=" in data.stdout
    assert worker.returncode == 0, wout
    assert "======= pipeedge/test-tiny-vit stage 1: layers [5, 8]" in wout


def test_two_process_dcn_adaptive_quant(tmp_path):
    """Adaptive quantization over DCN: rank 0 (stage 0) measures its own
    send window via the transport hooks and adapts its output-edge bitwidth;
    the bitwidth rides the wire header so rank 1 decodes without
    coordination (reference per-rank policy, runtime.py:121-216)."""
    addrs = ",".join(f"127.0.0.1:{p}" for p in _free_ports(2))
    common = [sys.executable, os.path.join(REPO, "runtime.py")]
    opts = ["-c", "dcn", "--platform", "cpu",
            "-m", "pipeedge/test-tiny-vit", "-b", "24", "-u", "4",
            "-pt", "1,4,5,8", "-q", "8,0", "-r", "0,1",
            "--dcn-addrs", addrs, "--sched-timeout", "120"]
    rank_dirs = []
    for r in range(2):
        d = tmp_path / f"rank{r}"
        d.mkdir()
        rank_dirs.append(d)
    env = dict(os.environ, PYTHONPATH=REPO, ADAPTIVE_QUANT="HEURISTIC",
               SEND_CONSTRAINT="100", WINDOW_SIZE="3")
    worker = subprocess.Popen(common + ["1", "2"] + opts, cwd=rank_dirs[1],
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
    try:
        data = subprocess.run(common + ["0", "2"] + opts, cwd=rank_dirs[0],
                              env=env, capture_output=True, text=True,
                              timeout=240)
        wout, _ = worker.communicate(timeout=60)
    finally:
        worker.kill()
    assert data.returncode == 0, data.stdout + data.stderr
    assert worker.returncode == 0, wout
    # the data rank hosts stage 0, whose policy adapts its output edge
    assert "Adaptive quantization" in data.stdout + data.stderr
    # transport hooks produced per-rank wire telemetry CSVs
    assert (rank_dirs[0] / "send.csv").exists()
    assert (rank_dirs[1] / "recv.csv").exists()
