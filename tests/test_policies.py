"""Adaptive-bitwidth policy + controller tests."""
import pytest

from pipeedge_tpu.utils.controller import AdaptiveIntegralXupController, KalmanFilter
from pipeedge_tpu.utils.quant import (
    BITWIDTHS, AdaptiveBitwidthPerformanceController, constrain_max_bitwidth)


def test_bitwidths_unique_discrete_compressions():
    # 32,16,10,8,6,5,4,3,2: largest bit per distinct floor(32/bit)
    assert BITWIDTHS == [32, 16, 10, 8, 6, 5, 4, 3, 2]


def test_kalman_converges():
    kf = KalmanFilter()
    est = 0.0
    for _ in range(100):
        est = kf(10.0)
    assert est == pytest.approx(10.0, rel=0.01)


def test_controller_tracks_reference():
    # plant: y = base * u with base workload 2.0; target y = 10 -> u = 5
    ctl = AdaptiveIntegralXupController(reference=10.0, u_0=1.0, u_max=16.0)
    u = 1.0
    for _ in range(50):
        y = 2.0 * u
        u = ctl(y)
    assert 2.0 * u == pytest.approx(10.0, rel=0.05)


def test_controller_pole_validation():
    ctl = AdaptiveIntegralXupController(1.0, 1.0)
    with pytest.raises(ValueError):
        ctl.pole = 1.0
    with pytest.raises(ValueError):
        ctl.pole = -0.1
    ctl.pole = 0.5


def test_controller_antiwindup_clamp():
    ctl = AdaptiveIntegralXupController(reference=1e9, u_0=1.0, u_max=4.0)
    for _ in range(10):
        u = ctl(1.0)
    assert u == 4.0  # clamped at u_max


def test_constrain_max_bitwidth():
    # scale = speed*t/size; need effective compression 1/floor(32/b) <= scale
    assert constrain_max_bitwidth(1.0, 1.0, 1.0, 32) == 32     # no constraint
    assert constrain_max_bitwidth(0.5, 1.0, 1.0, 32) == 16     # 2x needed
    assert constrain_max_bitwidth(0.25, 1.0, 1.0, 32) == 8     # 4x needed
    assert constrain_max_bitwidth(0.24, 1.0, 1.0, 32) == 6     # >4x -> floor(32/6)=5
    assert constrain_max_bitwidth(0.01, 1.0, 1.0, 32) == 0     # unsatisfiable
    assert constrain_max_bitwidth(1.0, 0.0, 1.0, 32) == 32     # no data


def test_bitwidth_perf_controller_splits_window():
    ctl = AdaptiveBitwidthPerformanceController(
        perf_constraint=100.0, bitwidths=BITWIDTHS, bitwidth_start=32)
    bw1, bw2, iters1 = ctl(50.0, 10)
    assert bw1 in BITWIDTHS and bw2 in BITWIDTHS
    assert bw1 >= bw2           # bw1 is the slower (higher-precision) one
    assert 0 <= iters1 <= 10
    # sustained underperformance drives toward smaller bitwidths
    for _ in range(30):
        bw1, bw2, iters1 = ctl(50.0, 10)
    assert bw2 <= 4
