"""Fused Pallas quant codec: bit-identity + dispatch-seam tests.

The acceptance invariant of ops/fused_quant.py: for every supported
bitwidth and shape — including odd tails that exercise the nibble/byte
packing's zero-padded last word — the fused encode produces the SAME
packed words, scale, and shift as `ops/quant.py tensor_encode_outerdim`,
and the fused decode matches `tensor_decode_outerdim`. Tier-1 on CPU via
Pallas interpret mode (the kernels' math without TPU hardware); the
shared `_blocks.pick_block` resolver is covered here too since the fused
kernels and both attention kernel families now use the one definition.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from pipeedge_tpu.ops import fused_quant, quant
from pipeedge_tpu.ops._blocks import pick_block

# odd-tail matrix: n % per_word sweeps 0 (exact words) and nonzero tails
# for both the int8 (4/word) and int4 (8/word) packings
SHAPES = [
    (2, 37),        # int8: 1-value tail; int4: 5-value tail
    (3, 128),       # exact words both widths
    (1, 5),         # sub-word single item
    (4, 7, 9),      # multi-dim inner shape, 63 values: 3-tail / 7-tail
    (8, 197, 64),   # ViT-ish: per-item 12608 values, exact int8 words
    (5, 33),        # int8 1-tail, int4 1-tail
]


def _rand(shape, seed=0):
    rng = np.random.default_rng(seed)
    # mixed-sign, non-unit range so scale/shift are non-trivial
    return jnp.asarray((rng.normal(size=shape) * 3.7 - 1.2)
                       .astype(np.float32))


@pytest.mark.parametrize("bit", fused_quant.FUSED_BITS)
@pytest.mark.parametrize("shape", SHAPES)
def test_fused_encode_bit_identical(bit, shape):
    x = _rand(shape)
    enc = fused_quant.fused_encode_outerdim(x, bit, interpret=True)
    ref = quant.tensor_encode_outerdim(x, bit)
    assert enc.bit == ref.bit and enc.shape == ref.shape
    assert np.array_equal(np.asarray(enc.data), np.asarray(ref.data))
    assert np.array_equal(np.asarray(enc.scale), np.asarray(ref.scale))
    assert np.array_equal(np.asarray(enc.shift), np.asarray(ref.shift))


@pytest.mark.parametrize("bit", fused_quant.FUSED_BITS)
@pytest.mark.parametrize("shape", SHAPES)
def test_fused_decode_bit_identical(bit, shape):
    x = _rand(shape, seed=1)
    ref = quant.tensor_encode_outerdim(x, bit)
    dec = fused_quant.fused_decode_outerdim(ref, interpret=True)
    refd = quant.tensor_decode_outerdim(ref)
    assert np.array_equal(np.asarray(dec), np.asarray(refd))


@pytest.mark.parametrize("bit", fused_quant.FUSED_BITS)
def test_cross_generation_pairing(bit):
    """Fused producer with XLA consumer and vice versa: the wire contract
    (comm/wire.py) — any encoder generation pairs with any decoder."""
    x = _rand((3, 41), seed=2)
    fused_enc = fused_quant.fused_encode_outerdim(x, bit, interpret=True)
    xla_dec = np.asarray(quant.tensor_decode_outerdim(fused_enc))
    xla_enc = quant.tensor_encode_outerdim(x, bit)
    fused_dec = np.asarray(
        fused_quant.fused_decode_outerdim(xla_enc, interpret=True))
    assert np.array_equal(xla_dec, fused_dec)


def test_nibble_packing_layout(monkeypatch):
    """int4 values land at their reference bit offsets: value i sits in
    word i//8 at bit (i%8)*4 (reference basic_op.py layout)."""
    vals = np.arange(16, dtype=np.float32)  # identity under 4-bit encode
    x = jnp.asarray(vals[None])             # one item, exact 2 words
    enc = fused_quant.fused_encode_outerdim(x, 4, interpret=True)
    words = np.asarray(enc.data)[0]
    unpacked = [(int(words[i // 8]) >> ((i % 8) * 4)) & 0xF
                for i in range(16)]
    assert unpacked == list(range(16))


def test_zero_range_item():
    """Constant items (scale == 0) must not NaN — the quant.py guard."""
    x = jnp.ones((2, 19), jnp.float32) * 4.5
    for bit in fused_quant.FUSED_BITS:
        enc = fused_quant.fused_encode_outerdim(x, bit, interpret=True)
        ref = quant.tensor_encode_outerdim(x, bit)
        assert np.array_equal(np.asarray(enc.data), np.asarray(ref.data))
        dec = np.asarray(fused_quant.fused_decode_outerdim(enc,
                                                           interpret=True))
        assert np.allclose(dec, 4.5)


def test_unsupported_bit_raises():
    x = _rand((2, 8))
    with pytest.raises(ValueError):
        fused_quant.fused_encode_outerdim(x, 6, interpret=True)


# -- dispatch seam --------------------------------------------------------

def test_seam_interpret_mode(monkeypatch):
    """PIPEEDGE_FUSED_QUANT=interpret routes the seam through the Pallas
    kernels (CPU CI path) and stays bit-identical to the XLA ops."""
    monkeypatch.setenv(fused_quant.ENV_FUSED_QUANT, "interpret")
    x = _rand((4, 29), seed=3)
    for bit in fused_quant.FUSED_BITS:
        assert fused_quant.fused_available(bit)
        enc = fused_quant.encode_outerdim(x, bit)
        ref = quant.tensor_encode_outerdim(x, bit)
        assert np.array_equal(np.asarray(enc.data), np.asarray(ref.data))
        dec = fused_quant.decode_outerdim(enc)
        assert np.array_equal(np.asarray(dec),
                              np.asarray(quant.tensor_decode_outerdim(ref)))


def test_seam_off_and_auto_on_cpu(monkeypatch):
    """'0' forces the XLA ops; 'auto' on a CPU backend also stays XLA (no
    native Mosaic kernels off-TPU) — both must still round-trip."""
    x = _rand((2, 11), seed=4)
    for mode in ("0", "auto"):
        monkeypatch.setenv(fused_quant.ENV_FUSED_QUANT, mode)
        assert not fused_quant.fused_available(8)
        enc = fused_quant.encode_outerdim(x, 8)
        ref = quant.tensor_encode_outerdim(x, 8)
        assert np.array_equal(np.asarray(enc.data), np.asarray(ref.data))


def test_seam_unfused_bits_fall_back(monkeypatch):
    """Bitwidths without a fused kernel (e.g. 16) silently use the XLA
    ops even in forced-fused modes — the adaptive-bitwidth policies pick
    from the full SUPPORTED_BITS set."""
    monkeypatch.setenv(fused_quant.ENV_FUSED_QUANT, "interpret")
    x = _rand((2, 10), seed=5)
    enc = fused_quant.encode_outerdim(x, 16)
    ref = quant.tensor_encode_outerdim(x, 16)
    assert np.array_equal(np.asarray(enc.data), np.asarray(ref.data))
    assert np.array_equal(
        np.asarray(fused_quant.decode_outerdim(enc)),
        np.asarray(quant.tensor_decode_outerdim(ref)))


# -- shared block resolver (ops/_blocks.py) -------------------------------

def test_pick_block_divides_and_aligns():
    for width in (8, 24, 128, 136, 1024, 4096):
        b = pick_block(width, 128)
        assert width % b == 0
        assert b % 8 == 0 or b == width
        assert b <= max(128, width)


def test_pick_block_fallback_full_width():
    # prime width > preferred: no multiple of 8 divides it -> full width
    assert pick_block(97, 64) == 97
    # tiny widths fall through to the full extent
    assert pick_block(5, 128) == 5


def test_pick_block_is_the_shared_resolver():
    """The three pre-dedup copies (attention.py, decode_attention.py x2)
    now alias the one definition."""
    from pipeedge_tpu.ops import attention, decode_attention
    assert attention._pick_block is pick_block
    assert decode_attention._pick_block is pick_block
