"""Real-sized parity anchors vs committed HF-torch logits (in-process).

Separate from test_weights.py because that module is fleet-marked
(subprocess CLIs); these tests are in-process, only slow (full-size
compiles + two forwards per family).

One anchor per model family (VERDICT r3 item 7): the torch side is FROZEN
at fixture-generation time (tools/make_parity_fixture.py), so HF init-
recipe drift and this framework's conversion/forward drift are both
caught for every family — not just ViT, as in rounds 2-3.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from pipeedge_tpu.models import registry


@pytest.mark.slow
@pytest.mark.parametrize("model_name", [
    "google/vit-base-patch16-224",
    "facebook/deit-base-distilled-patch16-224",
    "textattack/bert-base-uncased-CoLA",
    "gpt2",
    "pipeedge/test-tiny-llama",
])
def test_full_size_parity_vs_committed_torch_logits(model_name, tmp_path,
                                                    monkeypatch):
    """Parity ANCHOR: our npz conversion + shard pipeline must reproduce
    HF torch's own float32 logits recorded in the committed per-family
    fixture, regenerating the weights from the same seeded --random
    recipe. Pretrained weights are not downloadable here (zero egress,
    docs/REAL_WEIGHTS.md); with them, this same path yields accuracy."""
    import save_model_weights
    from tools.make_parity_fixture import (SPECS, build_torch_model,
                                           fixture_input, fixture_path,
                                           weight_probe)

    spec = SPECS[model_name]
    fx = np.load(fixture_path(model_name))
    monkeypatch.chdir(tmp_path)

    # regenerate the seeded weights; probe guards the HF init recipe
    model, cfg = build_torch_model(model_name)
    np.testing.assert_allclose(
        weight_probe(model, model_name), fx["weight_probe"], rtol=0, atol=0,
        err_msg=f"HF --random init recipe drifted for {model_name}; "
                "regenerate tools/make_parity_fixture.py")
    del model

    save_model_weights.save_weights(model_name, "w.npz", random_init=True)
    layers = registry.get_model_layers(model_name)
    fn, params, _ = registry.module_shard_factory(model_name, "w.npz", 1,
                                                  layers)
    x = jnp.asarray(fixture_input(cfg, model_name))
    got = np.asarray(fn(params, x))
    tail = spec.get("tail_positions")
    if tail:
        got = got[:, -tail:]
    np.testing.assert_allclose(got, fx["logits"], rtol=2e-4, atol=2e-4)
