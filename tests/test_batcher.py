"""Continuous batching: multi-request wave scheduling over DecodePipeline.

VERDICT r2 item 4: interleaving S concurrent requests across K pipeline
stages must (a) stay token-identical per request to a solo generate() run
and (b) approach min(S, K)x a single stream's throughput (a solo stream
busies 1 of K stages per tick; a full wave busies all K).
"""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from pipeedge_tpu.models import ShardConfig  # noqa: E402
from pipeedge_tpu.models import gpt2 as gpt2_mod  # noqa: E402
from pipeedge_tpu.models.layers import TransformerConfig  # noqa: E402
from pipeedge_tpu.parallel import decode  # noqa: E402
from pipeedge_tpu.parallel.batcher import ContinuousBatcher  # noqa: E402

pytestmark = pytest.mark.slow  # multi-request decode runs over a 3-stage pipeline (compile-heavy)

TINY = dict(hidden_size=32, num_hidden_layers=3, num_attention_heads=4,
            intermediate_size=64)
PARTITION = [(1, 4), (5, 8), (9, 12)]      # 3 stages


@pytest.fixture(scope="module")
def tiny_pipe():
    from transformers import GPT2Config, GPT2LMHeadModel
    hf_cfg = GPT2Config(n_embd=32, n_layer=3, n_head=4, n_inner=64,
                        vocab_size=100, n_positions=64)
    torch.manual_seed(7)
    model = GPT2LMHeadModel(hf_cfg).eval()
    cfg = TransformerConfig(model_type="gpt2", **TINY, layer_norm_eps=1e-5,
                            vocab_size=100, max_position_embeddings=64)
    weights = {k: v.numpy() for k, v in model.state_dict().items()}
    total = 4 * cfg.num_hidden_layers
    stage_params = [gpt2_mod.load_params(
        cfg, ShardConfig(l, r, is_first=l == 1, is_last=r == total), weights)
        for l, r in PARTITION]
    return decode.DecodePipeline(gpt2_mod.FAMILY, cfg, PARTITION,
                                 stage_params, max_len=48)


def _prompts(n, batch=1, lens=(7,), seed0=11):
    rng = np.random.default_rng(seed0)
    return [np.asarray(rng.integers(0, 100, size=(batch, lens[i % len(lens)])),
                       np.int64) for i in range(n)]


def test_steady_state_throughput_3_requests_3_stages(tiny_pipe):
    """S=3 requests over K=3 stages: every stage works every steady-state
    tick, so total ticks ~= S*N (vs a solo stream's N*K ticks per request
    = S*N*K total) -> ~K x aggregate throughput."""
    S, N, K = 3, 8, len(PARTITION)
    prompts = _prompts(S)
    batcher = ContinuousBatcher(tiny_pipe)
    for i, ids in enumerate(prompts):
        batcher.submit(i, ids, new_tokens=N)
    results = batcher.run()

    # (a) token-identical to solo runs
    for i, ids in enumerate(prompts):
        solo = np.asarray(tiny_pipe.generate(ids, new_tokens=N))
        np.testing.assert_array_equal(results[i], solo)

    # (b) wave utilization: S*N*K stage-steps packed into ~S*N ticks
    # (+K fill/drain slack) = ~K tokens per K ticks vs solo's 1
    assert batcher.stats["stage_steps"] == S * N * K
    assert batcher.stats["tokens"] == S * N
    assert batcher.stats["ticks"] <= S * N + K
    solo_ticks_equiv = S * N * K          # a solo stream: K ticks per token
    speedup = solo_ticks_equiv / batcher.stats["ticks"]
    assert speedup >= 0.85 * min(S, K)


def test_single_request_loses_nothing_vs_solo(tiny_pipe):
    """A lone request through the batcher costs exactly N*K ticks — wave
    scheduling adds no overhead below saturation."""
    N, K = 6, len(PARTITION)
    ids = _prompts(1)[0]
    batcher = ContinuousBatcher(tiny_pipe)
    batcher.submit("solo", ids, new_tokens=N)
    results = batcher.run()
    np.testing.assert_array_equal(
        results["solo"], np.asarray(tiny_pipe.generate(ids, new_tokens=N)))
    assert batcher.stats["ticks"] == N * K


def test_ready_queue_admission_and_heterogeneous_requests(tiny_pipe):
    """More requests than active slots, mixed prompt lengths and token
    budgets: completions free cache slots for pending requests; every
    result stays identical to its solo run."""
    lens = (7, 5, 9)
    prompts = _prompts(5, lens=lens)
    budgets = [4, 9, 3, 6, 5]
    batcher = ContinuousBatcher(tiny_pipe, max_active=3)
    for i, ids in enumerate(prompts):
        batcher.submit(i, ids, new_tokens=budgets[i])
    results = batcher.run()
    assert set(results) == set(range(5))
    for i, ids in enumerate(prompts):
        solo = np.asarray(tiny_pipe.generate(ids, new_tokens=budgets[i]))
        np.testing.assert_array_equal(results[i], solo)


def test_sampling_requests_match_solo_rng_discipline(tiny_pipe):
    """Sampled requests (temperature/top_k/seed) reproduce their solo
    generate() streams exactly: the batcher splits each request's rng
    once per picked token, like generate()."""
    prompts = _prompts(3)
    kw = [dict(temperature=0.8, top_k=0, seed=3),
          dict(temperature=0.0, top_k=0, seed=0),
          dict(temperature=1.2, top_k=5, seed=9)]
    batcher = ContinuousBatcher(tiny_pipe)
    for i, ids in enumerate(prompts):
        batcher.submit(i, ids, new_tokens=6, **kw[i])
    results = batcher.run()
    for i, ids in enumerate(prompts):
        solo = np.asarray(tiny_pipe.generate(ids, new_tokens=6, **kw[i]))
        np.testing.assert_array_equal(results[i], solo)


def test_batched_rows_and_validation(tiny_pipe):
    """A request may itself carry a lockstep batch; invalid submissions
    are rejected up front."""
    ids = _prompts(1, batch=4)[0]
    batcher = ContinuousBatcher(tiny_pipe)
    batcher.submit("b4", ids, new_tokens=5)
    results = batcher.run()
    np.testing.assert_array_equal(
        results["b4"], np.asarray(tiny_pipe.generate(ids, new_tokens=5)))
    assert results["b4"].shape == (4, ids.shape[1] + 5)

    with pytest.raises(ValueError, match="duplicate"):
        batcher.submit("b4", ids, new_tokens=5)  # rid already completed
    # the guard also covers ACTIVE (admitted, in-flight) requests, not
    # just pending/completed ones
    mid = ContinuousBatcher(tiny_pipe)
    mid.submit("x", ids, new_tokens=4)
    mid.tick()
    with pytest.raises(ValueError, match="duplicate"):
        mid.submit("x", ids, new_tokens=4)
    mid.run()
    with pytest.raises(ValueError, match="new_tokens"):
        batcher.submit("bad", ids, new_tokens=0)
    with pytest.raises(ValueError, match="exceeds"):
        batcher.submit("huge", ids, new_tokens=1000)
    with pytest.raises(ValueError, match="max_active"):
        ContinuousBatcher(tiny_pipe, max_active=0)


def test_eos_early_stop_frees_slot_for_pending(tiny_pipe):
    """A request with eos_token finishes the moment every row has emitted
    it — its tokens are the solo stream truncated at the first eos — and
    its freed cache slot admits a pending request."""
    prompts = _prompts(4, seed0=47)
    cap = 8
    solo0 = np.asarray(tiny_pipe.generate(prompts[0], cap))
    gen0 = solo0[0, prompts[0].shape[1]:]
    eos = int(gen0[2])                      # the 3rd greedy token
    n_stop = int(np.argmax(gen0 == eos)) + 1   # first occurrence

    batcher = ContinuousBatcher(tiny_pipe, max_active=2)
    batcher.submit(0, prompts[0], new_tokens=cap, eos_token=eos)
    for i in (1, 2, 3):
        batcher.submit(i, prompts[i], new_tokens=cap)
    results = batcher.run()

    np.testing.assert_array_equal(
        results[0], solo0[:, :prompts[0].shape[1] + n_stop])
    assert results[0][0, -1] == eos
    for i in (1, 2, 3):
        np.testing.assert_array_equal(
            results[i], np.asarray(tiny_pipe.generate(prompts[i], cap)))


def test_eos_multirow_masks_post_eos_tokens(tiny_pipe):
    """In a multi-row request, a row that hits eos early keeps decoding in
    lockstep until the whole request stops — but its post-eos tokens come
    back masked (default pad = eos; explicit pad_token honored), so
    callers never see the lockstep rows' garbage continuations."""
    rng = np.random.default_rng(53)
    ids = rng.integers(0, 100, size=(2, 4))
    cap = 8
    solo = np.asarray(tiny_pipe.generate(ids, cap))
    gen = solo[:, ids.shape[1]:]                       # [2, cap]
    # choose row 0's 2nd token as eos; make sure row 1 emits it later (or
    # never at the same step), so the request keeps running after row 0
    eos = int(gen[0, 1])
    first = [int(np.argmax(g == eos)) if eos in g else cap for g in gen]
    assert first[0] < first[1], "fixture rows stopped in the same step"

    batcher = ContinuousBatcher(tiny_pipe)
    batcher.submit("r", ids, new_tokens=cap, eos_token=eos, pad_token=77)
    out = batcher.run()["r"][:, ids.shape[1]:]
    stop = min(max(first) + 1, cap)                    # request length
    for b in range(2):
        row_stop = min(first[b] + 1, stop)
        np.testing.assert_array_equal(out[b, :row_stop], gen[b, :row_stop])
        assert (out[b, row_stop:stop] == 77).all(), (b, out[b])


def test_devices_placement_composes(tiny_pipe):
    """Stage-per-device placement (the host pipeline's deployment shape)
    composes with the batcher: results still solo-identical."""
    devices = jax.devices()
    if len(devices) < 3:
        pytest.skip("needs 3 devices")
    cfg = tiny_pipe.cfg
    placed = decode.DecodePipeline(
        gpt2_mod.FAMILY, cfg, PARTITION,
        [s["params"] for s in tiny_pipe.stages], max_len=48,
        devices=devices[:3])
    prompts = _prompts(3)
    batcher = ContinuousBatcher(placed)
    for i, ids in enumerate(prompts):
        batcher.submit(i, ids, new_tokens=5)
    results = batcher.run()
    for i, ids in enumerate(prompts):
        np.testing.assert_array_equal(
            results[i], np.asarray(tiny_pipe.generate(ids, new_tokens=5)))


def test_prefix_cached_requests_match_solo_and_full(tiny_pipe):
    """Prompt caching in the batcher: requests seeded from one shared
    prefix handle produce the same tokens as (a) a solo prefix-seeded
    generate and (b) a solo FULL-prompt generate, while interleaving
    with a plain (non-prefix) request."""
    rng = np.random.default_rng(29)
    prefix = rng.integers(0, 100, size=(1, 6))
    handle = tiny_pipe.precompute_prefix(prefix)
    suffixes = [rng.integers(0, 100, size=(1, 4)) for _ in range(2)]
    plain = rng.integers(0, 100, size=(1, 7))

    batcher = ContinuousBatcher(tiny_pipe)
    for i, suf in enumerate(suffixes):
        batcher.submit(i, suf, new_tokens=6, prefix=handle)
    batcher.submit("plain", plain, new_tokens=6)
    batcher.submit("sampled", suffixes[0], new_tokens=5, temperature=0.9,
                   seed=4, prefix=handle)
    results = batcher.run()

    for i, suf in enumerate(suffixes):
        want_solo = np.asarray(tiny_pipe.generate(suf, 6, prefix=handle))
        np.testing.assert_array_equal(results[i], want_solo)
        full = np.concatenate([prefix, suf], axis=1)
        want_full = np.asarray(tiny_pipe.generate(full, 6))
        np.testing.assert_array_equal(results[i], want_full[:, 6:])
    np.testing.assert_array_equal(
        results["plain"], np.asarray(tiny_pipe.generate(plain, 6)))
    np.testing.assert_array_equal(
        results["sampled"],
        np.asarray(tiny_pipe.generate(suffixes[0], 5, temperature=0.9,
                                      seed=4, prefix=handle)))


def test_prefix_handle_validated_at_submit(tiny_pipe):
    """A prefix handle built by an INCOMPATIBLE pipeline (different
    max_len here) is rejected up front with the two signatures named —
    not deep inside jit as an opaque shape error (round-4 advice). Same
    check guards solo generate(prefix=)."""
    rng = np.random.default_rng(31)
    prefix = rng.integers(0, 100, size=(1, 6))
    suffix = rng.integers(0, 100, size=(1, 4))
    handle = tiny_pipe.precompute_prefix(prefix)

    # strip the stamp -> rejected as not-a-handle
    batcher = ContinuousBatcher(tiny_pipe)
    bad = {k: v for k, v in handle.items() if k != "sig"}
    with pytest.raises(ValueError, match="precompute_prefix handle"):
        batcher.submit(0, suffix, new_tokens=4, prefix=bad)

    # forge an incompatible signature -> rejected with both sigs shown
    sig = list(handle["sig"])
    sig[2] = handle["sig"][2] + 16       # max_len field
    forged = dict(handle, sig=tuple(sig))
    with pytest.raises(ValueError, match="incompatible pipeline"):
        batcher.submit(1, suffix, new_tokens=4, prefix=forged)
    with pytest.raises(ValueError, match="incompatible pipeline"):
        tiny_pipe.generate(suffix, 4, prefix=forged)

    # the genuine handle still passes end-to-end
    batcher.submit(2, suffix, new_tokens=4, prefix=handle)
    results = batcher.run()
    np.testing.assert_array_equal(
        results[2], np.asarray(tiny_pipe.generate(suffix, 4, prefix=handle)))


def test_stage_worker_executor_matches_solo(tiny_pipe):
    """StageWorkerExecutor (one thread pinned per stage) is token-
    identical to solo generate() for mixed plain/sampled/prefix/eos
    requests submitted concurrently, streams per-step tokens via
    on_token, and reports per-worker stats."""
    import threading

    from pipeedge_tpu.parallel.batcher import StageWorkerExecutor

    rng = np.random.default_rng(41)
    prefix = rng.integers(0, 100, size=(1, 6))
    handle = tiny_pipe.precompute_prefix(prefix)
    ex = StageWorkerExecutor(tiny_pipe)
    try:
        plain = rng.integers(0, 100, size=(2, 7))
        sampled = rng.integers(0, 100, size=(1, 5))
        suffix = rng.integers(0, 100, size=(1, 4))
        streamed = []
        outs = {}

        def client(rid, ids, n, **kw):
            ex.submit(rid, ids, n, **kw)
            outs[rid] = ex.wait(rid, timeout=300)

        threads = [
            threading.Thread(target=client, args=("plain", plain, 6),
                             kwargs={"on_token": lambda s, t:
                                     streamed.append((s, np.asarray(t)))}),
            threading.Thread(target=client, args=("sampled", sampled, 5),
                             kwargs={"temperature": 0.8, "seed": 3}),
            threading.Thread(target=client, args=("pfx", suffix, 5),
                             kwargs={"prefix": handle}),
            threading.Thread(target=client, args=("eos", plain, 6),
                             kwargs={"eos_token": 11}),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive()

        np.testing.assert_array_equal(
            outs["plain"], np.asarray(tiny_pipe.generate(plain, 6)))
        np.testing.assert_array_equal(
            outs["sampled"], np.asarray(tiny_pipe.generate(
                sampled, 5, temperature=0.8, seed=3)))
        np.testing.assert_array_equal(
            outs["pfx"], np.asarray(tiny_pipe.generate(suffix, 5,
                                                       prefix=handle)))
        want_eos = ContinuousBatcher(tiny_pipe)
        want_eos.submit("eos", plain, 6, eos_token=11)
        np.testing.assert_array_equal(outs["eos"], want_eos.run()["eos"])

        # the stream delivered every step's token in order, matching the
        # result's continuation columns
        steps = sorted(streamed, key=lambda x: x[0])
        assert [s for s, _ in steps] == list(range(6))
        got = np.stack([t for _, t in steps], axis=1)
        np.testing.assert_array_equal(got, outs["plain"][:, 7:])

        snap = ex.snapshot()
        assert len(snap["stage_steps"]) == len(PARTITION)
        assert all(s > 0 for s in snap["stage_steps"])
        assert snap["active"] == 0 and snap["tokens"] >= 22

        with pytest.raises(ValueError, match="duplicate"):
            ex.submit("plain2", plain, 2)  # rid free, fine
            ex.submit("plain2", plain, 2)  # duplicate while live/result
    finally:
        ex.stop()
