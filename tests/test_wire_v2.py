"""Wire protocol v2 (device-encoded quantized DCN edges): header format,
round-trip numerics, byte accounting, and v1 compatibility.

The v2 frame is [int32 header vector (magic, version, bit, flags, n)] +
per payload tensor [packed, scale, shift, shape]; encoding runs on the
producing device with async D2H readback (`wire_encode_device` ->
`PendingWire.finalize`), decoding on the receiving device. These tests pin
the acceptance criteria: >= 4x activation wire-byte reduction at int8 vs
fp32, a bounded dequantization error, and the bitwidth traveling ON the
wire (no consumer coordination when adaptive policies move it)."""
import numpy as np
import pytest

import jax.numpy as jnp

from pipeedge_tpu.comm import wire
from pipeedge_tpu.ops import quant as quant_ops


def _ubatch(shape=(4, 32, 16), seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=shape).astype(np.float32)


@pytest.mark.parametrize("bit", [8, 4, 16])
def test_v2_roundtrip_bounds_dequant_error(bit):
    """encode_device -> finalize -> decode recovers the activation to
    within one quantization step per item (q = round(x01 * (2^b - 1)),
    so |err| <= 0.5 * range / (2^b - 1) plus f32 rounding)."""
    x = _ubatch()
    parts = wire.wire_encode_device(jnp.asarray(x), bit).finalize()
    assert all(isinstance(p, np.ndarray) for p in parts)
    dec = np.asarray(wire.wire_decode(parts, jnp.float32))
    per_item_range = (x.reshape(len(x), -1).max(1)
                      - x.reshape(len(x), -1).min(1))
    bound = 0.5 * per_item_range / ((1 << bit) - 1) + 1e-5
    err = np.abs(dec - x).reshape(len(x), -1).max(1)
    assert (err <= bound).all(), (err, bound)


def test_v2_fp32_passthrough_exact():
    x = _ubatch()
    parts = wire.wire_encode_device(jnp.asarray(x), 0).finalize()
    np.testing.assert_array_equal(
        np.asarray(wire.wire_decode(parts, jnp.float32)), x)


def test_v2_tuple_payload():
    pair = (jnp.asarray(_ubatch(seed=1)), jnp.asarray(_ubatch(seed=2)) * 3)
    parts = wire.wire_encode_device(pair, 8).finalize()
    dec = wire.wire_decode(parts, jnp.float32)
    assert isinstance(dec, tuple) and len(dec) == 2
    for d, orig in zip(dec, pair):
        assert np.abs(np.asarray(d) - np.asarray(orig)).max() < 0.2


def test_v2_bitwidth_travels_on_wire():
    """The consumer never needs to be told the bitwidth: frames encoded at
    different bits (an adaptive policy moving mid-run) decode back to back
    from their headers alone."""
    x = _ubatch()
    for bit in (0, 8, 4, 2):
        parts = wire.wire_encode_device(jnp.asarray(x), bit).finalize()
        header = parts[0]
        assert header.ndim == 1 and int(header[0]) == wire.WIRE_V2_MAGIC
        assert int(header[2]) == bit
        dec = np.asarray(wire.wire_decode(parts, jnp.float32))
        assert dec.shape == x.shape


def test_v2_int8_payload_reduction_at_least_4x():
    """THE acceptance counter: the bytes replacing the raw fp32
    activations on the wire shrink >= 4x at int8 (exactly 32/bit when the
    per-item element count packs evenly), >= 8x at 4-bit; total frame
    bytes (payload + O(ubatch) scale/shift/shape metadata) are within 1%
    of the same ratio."""
    x = jnp.asarray(_ubatch(shape=(8, 197, 1024)))   # ViT-Large edge shape
    fp32 = wire.wire_encode_device(x, 0).finalize()
    p_fp32 = wire.frame_payload_bytes(fp32)
    t_fp32 = wire.frame_wire_bytes(fp32)
    for bit, factor in ((8, 4.0), (4, 8.0)):
        q = wire.wire_encode_device(x, bit).finalize()
        assert p_fp32 / wire.frame_payload_bytes(q) >= factor
        assert t_fp32 / wire.frame_wire_bytes(q) >= factor * 0.99


def test_v2_packing_bit_identical_to_v1():
    """v1 (host XLA encode) and v2 (device encode) produce the same packed
    words/scale/shift for the same input — any producer generation pairs
    with any consumer generation."""
    x = _ubatch()
    import os
    env = os.environ.get("PIPEEDGE_NATIVE_QUANT")
    os.environ["PIPEEDGE_NATIVE_QUANT"] = "0"   # force v1 through the XLA ops
    try:
        v1 = wire.wire_encode(jnp.asarray(x), 8)
    finally:
        if env is None:
            os.environ.pop("PIPEEDGE_NATIVE_QUANT")
        else:
            os.environ["PIPEEDGE_NATIVE_QUANT"] = env
    v2 = wire.wire_encode_device(jnp.asarray(x), 8).finalize()
    # v1: [bit, packed, scale, shift, shape]; v2: [header, packed, scale,
    # shift, shape]
    for a, b in zip(v1[1:], v2[1:]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_v1_frames_still_decode():
    """wire_decode keeps accepting v1 frames (scalar bitwidth header) —
    mixed producer generations decode through one consumer path."""
    x = _ubatch()
    dec = np.asarray(wire.wire_decode(wire.wire_encode(x, 8), jnp.float32))
    assert np.abs(dec - x).max() < 0.1
    dec0 = np.asarray(wire.wire_decode(wire.wire_encode(x, 0), jnp.float32))
    np.testing.assert_array_equal(dec0, x)


def test_v2_malformed_frame_raises():
    x = jnp.asarray(_ubatch())
    parts = wire.wire_encode_device(x, 8).finalize()
    with pytest.raises(ValueError, match="malformed"):
        wire.wire_decode(parts[:-1], jnp.float32)   # dropped shape tensor


def test_v2_decode_matches_ops_decode():
    """The v2 consumer path is exactly ops/quant decode: reconstructing the
    QuantizedTensor from the wire quads and decoding equals decoding the
    original encoder output directly."""
    x = jnp.asarray(_ubatch())
    enc = quant_ops.tensor_encode_outerdim(x, 8)
    direct = np.asarray(quant_ops.tensor_decode_outerdim(enc))
    parts = wire.wire_encode_device(x, 8).finalize()
    via_wire = np.asarray(wire.wire_decode(parts, jnp.float32))
    np.testing.assert_allclose(via_wire, direct, rtol=1e-6, atol=1e-6)
