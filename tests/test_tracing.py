"""Profiler trace capture (utils/tracing.py) — the trace-viewer integration
the reference lacks entirely (SURVEY.md §5.1)."""
import os

import jax
import jax.numpy as jnp

from pipeedge_tpu.utils import tracing


def _profile_files(root):
    return [os.path.join(dp, f) for dp, _, fs in os.walk(root) for f in fs]


def test_trace_captures_profile(tmp_path):
    out = str(tmp_path / "trace")
    with tracing.trace(out):
        with tracing.annotate("traced-region"):
            x = jnp.ones((64, 64))
            jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
    files = _profile_files(out)
    assert files, "profiler session produced no files"
    assert any(f.endswith((".xplane.pb", ".trace.json.gz")) for f in files), files


def test_trace_none_is_noop(tmp_path):
    with tracing.trace(None):
        pass  # nothing written, no error
    with tracing.trace(""):
        pass


def test_annotate_outside_trace_is_harmless():
    with tracing.annotate("no-session"):
        jax.block_until_ready(jnp.ones((4,)) + 1)
