"""Profiler trace capture (utils/tracing.py) — the trace-viewer integration
the reference lacks entirely (SURVEY.md §5.1)."""
import os

import jax
import jax.numpy as jnp

from pipeedge_tpu.utils import tracing


def _profile_files(root):
    return [os.path.join(dp, f) for dp, _, fs in os.walk(root) for f in fs]


def test_trace_captures_profile(tmp_path):
    out = str(tmp_path / "trace")
    with tracing.trace(out):
        with tracing.annotate("traced-region"):
            x = jnp.ones((64, 64))
            jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
    files = _profile_files(out)
    assert files, "profiler session produced no files"
    assert any(f.endswith((".xplane.pb", ".trace.json.gz")) for f in files), files


def test_trace_none_is_noop(tmp_path):
    with tracing.trace(None):
        pass  # nothing written, no error
    with tracing.trace(""):
        pass


def test_annotate_outside_trace_is_harmless():
    with tracing.annotate("no-session"):
        jax.block_until_ready(jnp.ones((4,)) + 1)


def test_annotate_degrades_when_backend_unavailable(monkeypatch, caplog):
    """Module contract ("Both degrade to no-ops"): a profiler backend that
    fails at construction OR at region entry must yield a harmless no-op
    context manager, never an exception."""
    class _BoomCtor:
        def __init__(self, name):
            raise RuntimeError("profiler busy")

    monkeypatch.setattr(jax.profiler, "TraceAnnotation", _BoomCtor)
    with tracing.annotate("degraded-ctor"):
        pass    # no raise

    class _BoomEnter:
        def __init__(self, name):
            pass

        def __enter__(self):
            raise RuntimeError("no active session")

        def __exit__(self, *exc):
            raise AssertionError("exit must not run for a failed enter")

    monkeypatch.setattr(jax.profiler, "TraceAnnotation", _BoomEnter)
    with tracing.annotate("degraded-enter"):
        jax.block_until_ready(jnp.ones((2,)) + 1)


def test_annotate_normal_path_still_works():
    with tracing.annotate("ok-region"):
        jax.block_until_ready(jnp.ones((2,)) * 2)
