"""Tiered inter-stage transport (docs/DCN_WIRE.md selection matrix):
path negotiation, the colocated in-process hand-off, the zero-copy pooled
socket path, buffer-ownership safety, the ledger's snapshot-bounded
failover replay, and the per-tier telemetry the reports consume."""
import queue
import socket
import sys
import threading
import time

import ml_dtypes
import numpy as np
import pytest

from pipeedge_tpu import telemetry
from pipeedge_tpu.comm import dcn
from pipeedge_tpu.telemetry import report


def _free_ports(n):
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _make_contexts(n, **kwargs):
    addrs = [("127.0.0.1", p) for p in _free_ports(n)]
    ctxs = [dcn.DistDcnContext(n, r, addrs, **kwargs) for r in range(n)]
    for c in ctxs:
        c.init()
    return ctxs


def _shutdown(ctxs):
    for c in ctxs:
        c.shutdown()


# every wire dtype the frame codec supports, including the nibble-packed
# sub-byte ones — the bit-identity matrix below runs each through every
# transport tier
def _all_dtype_tensors():
    rng = np.random.default_rng(7)
    tensors = []
    for dt in (np.float16, np.float32, np.float64, np.uint8, np.int8,
               np.int16, np.int32, np.int64, np.bool_, np.complex64,
               np.complex128, np.uint16, np.uint32, np.uint64):
        tensors.append((rng.normal(size=(3, 5)) * 10).astype(dt))
    tensors.append(rng.normal(size=(2, 7)).astype(ml_dtypes.bfloat16))
    # 4-bit, odd element count (exercises the pad nibble) + signed values
    tensors.append(np.arange(-8, 7, dtype=np.int8).astype(ml_dtypes.int4))
    tensors.append(np.arange(0, 15, dtype=np.int8).astype(ml_dtypes.uint4))
    tensors.append(np.float32(3.25).reshape(()))          # 0-d
    tensors.append(np.zeros((0, 4), np.float32))          # zero-size
    return tensors


def _roundtrip(ctxs, tensors):
    ctxs[0].send_tensors(1, tensors)
    return ctxs[1].recv_tensors(0, timeout=10)


# -- negotiation matrix ------------------------------------------------

def test_negotiates_local_for_in_process_peers():
    ctxs = _make_contexts(2)
    try:
        assert ctxs[0].negotiate_edge_path(1, timeout=10) == dcn.PATH_LOCAL
        assert ctxs[0].edge_path(1) == dcn.PATH_LOCAL
        # the consumer's own view of its upstream edge is independent
        assert ctxs[1].edge_path(0) is None
    finally:
        _shutdown(ctxs)


def test_negotiates_zerocopy_when_local_disabled(monkeypatch):
    monkeypatch.setenv(dcn.ENV_LOCAL_HANDOFF, "0")
    ctxs = _make_contexts(2)
    try:
        assert ctxs[0].negotiate_edge_path(1, timeout=10) \
            == dcn.PATH_ZEROCOPY
    finally:
        _shutdown(ctxs)


def test_negotiates_legacy_when_pool_also_disabled(monkeypatch):
    monkeypatch.setenv(dcn.ENV_LOCAL_HANDOFF, "0")
    monkeypatch.setenv(dcn.ENV_RECV_POOL, "0")
    ctxs = _make_contexts(2)
    try:
        assert ctxs[0].negotiate_edge_path(1, timeout=10) \
            == dcn.PATH_SOCKET_V2
    finally:
        _shutdown(ctxs)


def test_self_edge_negotiates_local():
    """The data rank feeding its own colocated stage (the `-r 0,...`
    layout) is the most common colocated edge: a self-send must skip the
    loopback socket."""
    ctxs = _make_contexts(2)
    try:
        assert ctxs[0].negotiate_edge_path(0, timeout=10) == dcn.PATH_LOCAL
        ctxs[0].send_tensors(0, [np.arange(6.0)], channel=dcn.CHANNEL_FEED)
        out = ctxs[0].recv_tensors(0, timeout=5, channel=dcn.CHANNEL_FEED)
        np.testing.assert_array_equal(out[0], np.arange(6.0))
    finally:
        _shutdown(ctxs)


def test_negotiation_records_transport_span():
    telemetry.configure(rank=0)
    try:
        ctxs = _make_contexts(2)
        try:
            ctxs[0].negotiate_edge_path(1, timeout=10)
        finally:
            _shutdown(ctxs)
        names = [s["name"] for s in telemetry.recorder().snapshot()
                 if s["cat"] == "transport"]
        assert "local:0->1" in names
    finally:
        telemetry.disable()


# -- bit-identity across tiers -----------------------------------------

@pytest.mark.parametrize("env", [
    {},                                                     # local
    {dcn.ENV_LOCAL_HANDOFF: "0"},                           # zerocopy
    {dcn.ENV_LOCAL_HANDOFF: "0", dcn.ENV_RECV_POOL: "0"},   # legacy v2
], ids=["local", "zerocopy", "socket_v2"])
def test_tier_bit_identical_all_wire_dtypes(monkeypatch, env):
    """Every tier must deliver byte-identical tensors (all wire dtypes,
    incl. the nibble-packed sub-byte ones) — the colocated and zero-copy
    fast paths are transports, not transforms."""
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    ctxs = _make_contexts(2)
    try:
        tier = ctxs[0].negotiate_edge_path(1, timeout=10)
        expect = {(): dcn.PATH_LOCAL,
                  ("DCN_LOCAL_HANDOFF",): dcn.PATH_ZEROCOPY}.get(
            tuple(sorted(env)), dcn.PATH_SOCKET_V2)
        assert tier == expect
        tensors = _all_dtype_tensors()
        out = _roundtrip(ctxs, tensors)
        assert len(out) == len(tensors)
        for sent, got in zip(tensors, out):
            got = np.asarray(got)
            assert got.dtype == sent.dtype and got.shape == sent.shape
            assert got.tobytes() == sent.tobytes()
    finally:
        _shutdown(ctxs)


# -- zero-copy pool ownership ------------------------------------------

def test_recv_pool_reuses_free_buffers():
    pool = dcn._RecvBufferPool()
    buf = pool.acquire(1000)
    ident = id(buf)
    del buf            # no consumer holds it: next acquire reuses it
    assert id(pool.acquire(500)) == ident


def test_recv_pool_never_recycles_referenced_buffers():
    pool = dcn._RecvBufferPool()
    buf = pool.acquire(1000)
    arr = np.frombuffer(buf, np.uint8, count=16)   # a consumer view
    assert id(pool.acquire(1000)) != id(buf)
    del arr
    del buf
    assert any(sys.getrefcount(b) == dcn._RecvBufferPool._FREE_REFCOUNT
               for b in pool._bufs)


def test_recv_pool_evicts_held_entries_before_free_ones():
    """A full pool drops a RETAINED entry (useless to the pool — its
    consumer keeps it alive) before sacrificing a free, reusable one."""
    pool = dcn._RecvBufferPool(max_buffers=2)
    free_buf = pool.acquire(64)
    free_id = id(free_buf)
    del free_buf                                    # stays free in-pool
    held = np.frombuffer(pool.acquire(64), np.uint8, count=8)
    pool.acquire(1 << 20)   # full pool, nothing fits: must evict the held
    assert any(id(b) == free_id for b in pool._bufs)
    del held


def test_retained_recv_array_survives_buffer_churn():
    """The satellite-fix contract: an array a consumer RETAINS (the
    ledger holding a result through a replay) must never observe a
    recycled buffer, no matter how many frames follow on the edge."""
    ctxs = _make_contexts(2)
    try:
        marker = np.full((64, 64), 7.5, np.float32)
        ctxs[0].send_tensors(1, [marker])
        retained = ctxs[1].recv_tensors(0, timeout=10)[0]
        for i in range(40):   # > pool size: plenty of recycle pressure
            ctxs[0].send_tensors(1, [np.full((64, 64), float(i),
                                             np.float32)])
            ctxs[1].recv_tensors(0, timeout=10)
        assert retained.tobytes() == marker.tobytes()
    finally:
        _shutdown(ctxs)


# -- colocated hand-off semantics --------------------------------------

def test_local_handoff_fires_monitor_hooks():
    ctxs = _make_contexts(2)
    seen = {"send": [], "recv": []}
    ctxs[0].register_send_hooks(
        pre=lambda dst, ch: None,
        post=lambda dst, ch, ts: seen["send"].append(
            sum(int(t.nbytes) for t in ts)))
    ctxs[1].register_recv_hooks(
        pre=lambda src, ch: None,
        post=lambda src, ch, ts: seen["recv"].append(
            sum(int(t.nbytes) for t in ts)))
    try:
        ctxs[0].negotiate_edge_path(1, timeout=10)
        payload = np.ones((8, 8), np.float32)
        ctxs[0].send_tensors(1, [payload])
        ctxs[1].recv_tensors(0, timeout=10)
        assert seen["send"] == [payload.nbytes]
        assert seen["recv"] == [payload.nbytes]
    finally:
        _shutdown(ctxs)


def test_local_handoff_respects_epoch_fence():
    """A fenced incarnation's hand-off must be dropped at the queue door,
    exactly like the socket reader's zombie-frame fence."""
    ctxs = _make_contexts(2)
    try:
        ctxs[0].negotiate_edge_path(1, timeout=10)
        with ctxs[1]._dead_lock:
            ctxs[1]._min_epoch[0] = 5    # rank 0 epochs < 5 are zombies
        before = ctxs[1].stale_frames_dropped
        ctxs[0].send_tensors(1, [np.ones(4)])
        assert ctxs[1].stale_frames_dropped == before + 1
        with pytest.raises(queue.Empty):
            ctxs[1].recv_tensors(0, timeout=0.3)
    finally:
        _shutdown(ctxs)


def test_local_handoff_preserves_backpressure():
    """The bounded recv queue (depth 1) is the tier's backpressure: a
    second hand-off blocks until the consumer drains the first."""
    ctxs = _make_contexts(2)
    try:
        ctxs[0].negotiate_edge_path(1, timeout=10)
        ctxs[0].send_tensors(1, [np.ones(2)])     # fills the depth-1 queue
        done = threading.Event()

        def second():
            ctxs[0].send_tensors(1, [np.ones(2) * 2])
            done.set()

        t = threading.Thread(target=second, daemon=True)
        t.start()
        assert not done.wait(timeout=0.5)          # blocked: queue full
        ctxs[1].recv_tensors(0, timeout=5)         # drain one
        assert done.wait(timeout=5)                # unblocked
        ctxs[1].recv_tensors(0, timeout=5)
        t.join(timeout=5)
    finally:
        _shutdown(ctxs)


def test_local_grant_degrades_to_socket_when_peer_leaves():
    """A negotiated colocated grant whose peer context shut down (clean
    exit from this process) must fall back to the socket truth instead
    of delivering into a dead queue."""
    addrs = [("127.0.0.1", p) for p in _free_ports(2)]
    a = dcn.DistDcnContext(2, 0, addrs)
    b = dcn.DistDcnContext(2, 1, addrs)
    a.init()
    b.init()
    try:
        assert a.negotiate_edge_path(1, timeout=10) == dcn.PATH_LOCAL
        b.shutdown()
        # fresh context, same rank/address, NEW process in spirit: the
        # stale grant must not reach it through the local registry
        b2 = dcn.DistDcnContext(2, 1, addrs)
        b2.init()
        try:
            # grant cleared lazily on first send; the send itself rides
            # the socket (b2 registered itself, so a re-negotiation may
            # re-grant local — the point is no dead-queue delivery)
            a.send_tensors(1, [np.arange(3.0)])
            out = b2.recv_tensors(0, timeout=10)
            np.testing.assert_array_equal(out[0], np.arange(3.0))
        finally:
            b2.shutdown()
    finally:
        a.shutdown()
        b.shutdown()


def test_set_local_device_routes_handoff_buffers():
    """A consumer that declared its compute device gets colocated device
    buffers moved there (device-to-device `device_put`); host arrays and
    already-resident buffers pass through untouched."""
    import jax
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >= 2 devices (conftest forces 8 on CPU)")
    ctxs = _make_contexts(2)
    try:
        ctxs[1].set_local_device(devs[1])
        ctxs[0].negotiate_edge_path(1, timeout=10)
        committed = jax.device_put(jax.numpy.arange(8.0), devs[0])
        host = np.full((3,), 2.5, np.float32)
        ctxs[0].send_tensors(1, [committed, host])
        out = ctxs[1].recv_tensors(0, timeout=10)
        assert out[0].sharding.device_set == {devs[1]}
        np.testing.assert_array_equal(np.asarray(out[0]), np.arange(8.0))
        assert isinstance(out[1], np.ndarray)          # host passthrough
        np.testing.assert_array_equal(out[1], host)
    finally:
        _shutdown(ctxs)


def test_local_handoff_wire_span_per_tier():
    telemetry.configure(rank=0)
    try:
        ctxs = _make_contexts(2)
        try:
            ctxs[0].negotiate_edge_path(1, timeout=10)
            ctxs[0].send_tensors(1, [np.ones(8)])
            ctxs[1].recv_tensors(0, timeout=10)
        finally:
            _shutdown(ctxs)
        wire = [s["name"] for s in telemetry.recorder().snapshot()
                if s["cat"] == "wire"]
        assert "local->r1" in wire
        assert not any(n.startswith("send->") for n in wire)
    finally:
        telemetry.disable()


# -- report: transport + segments sections ------------------------------

def _span(cat, name, t0, t1, rank=0, stage=None, mb=None):
    return {"cat": cat, "name": name, "rank": rank, "stage": stage,
            "mb": mb, "t0": t0, "t1": t1}


def test_report_transport_section():
    spans = [
        _span("transport", "local:0->0", 0, 0),
        _span("transport", "local:1->0", 0, 0),
        _span("transport", "zerocopy:1->2", 0, 0),
        _span("wire", "local->r0", 0, 1_000_000),
        _span("wire", "send->r2", 1_000_000, 4_000_000),
        _span("stage", "dispatch", 0, 2_000_000, stage=0, mb=0),
    ]
    rec = report.analyze_spans(spans, span_cost_ns=100.0)
    t = rec["transport"]
    assert t["edges_by_tier"] == {"local": 2, "zerocopy": 1}
    assert t["local_edges"] == 2
    assert t["local_share_pct"] == 25.0
    assert rec["segments"]["wire/local->"]["n"] == 1
    assert rec["segments"]["stage/dispatch"]["p50_ms"] == 2.0


def test_report_transport_counts_edges_not_negotiations():
    """The runtime renegotiates every round build: repeated instants for
    one edge are ONE edge in the report, at its latest tier."""
    spans = [
        _span("transport", "socket_v2:0->1", 0, 0),     # round 1
        _span("transport", "local:0->1", 5, 5),         # round 2: upgraded
        _span("transport", "local:0->1", 9, 9),         # round 3
    ]
    rec = report.analyze_spans(spans, span_cost_ns=100.0)
    assert rec["transport"]["edges_by_tier"] == {"local": 1}
    assert rec["transport"]["local_edges"] == 1


def test_report_without_transport_spans_is_empty_not_missing():
    spans = [_span("stage", "dispatch", 0, 1_000_000, stage=0, mb=0)]
    rec = report.analyze_spans(spans, span_cost_ns=100.0)
    assert rec["transport"]["local_edges"] == 0
    assert rec["transport"]["edges_by_tier"] == {}


def test_segment_medians_folds_unbounded_names():
    spans = [_span("feed", "mb0", 0, 1_000_000),
             _span("feed", "mb1", 0, 3_000_000),
             _span("wire", "send->r1", 0, 2_000_000),
             _span("wire", "send->r2", 0, 2_000_000)]
    seg = report.segment_medians(spans)
    assert seg["feed/mb"]["n"] == 2
    assert seg["wire/send->"]["n"] == 2


# -- ledger snapshots: O(in-flight) failover replay ---------------------

def _make_ledger(n, snapshot_every):
    import runtime as rt
    ubatches = [np.full((2, 3), i, np.float32) for i in range(n)]
    return rt, rt._MicrobatchLedger(ubatches, None,
                                    snapshot_every=snapshot_every), ubatches


def test_ledger_snapshot_compacts_and_bounds_replay():
    rt, ledger, ubatches = _make_ledger(8, snapshot_every=3)
    orig = rt.handle_results
    rt.handle_results = lambda out: None
    try:
        for i in range(5):
            assert ledger.ack(i, np.full((2,), float(i)))
            ledger.maybe_snapshot()
        # 5 acks at cadence 3 -> 1 snapshot; payloads of the acked prefix
        # are compacted away and the replay frontier moved past it
        assert ledger.snapshots == 1
        assert ledger._frontier == 3
        assert all(ledger._ubatches[i] is None for i in range(3))
        # the replay set is still exactly the unacknowledged tail,
        # payloads intact and bit-identical to the originals
        pending = ledger.pending()
        assert [i for i, _ in pending] == [5, 6, 7]
        for i, u in pending:
            assert u.tobytes() == ubatches[i].tobytes()
    finally:
        rt.handle_results = orig
        while not rt.label_queue.empty():
            rt.label_queue.get()


def test_ledger_replay_from_snapshot_exactly_once_bit_identical():
    """The failover contract with snapshots on: replaying the post-
    snapshot set (duplicates included — a replay overlaps in-flight
    results) delivers every result exactly once, in order, bit-identical."""
    rt, ledger, _ = _make_ledger(6, snapshot_every=2)
    delivered = []
    orig = rt.handle_results
    rt.handle_results = lambda out: delivered.append(np.asarray(out))
    try:
        results = {i: np.full((2, 2), i * 1.5, np.float32)
                   for i in range(6)}
        for i in (0, 1, 2):                       # pre-death progress
            assert ledger.ack(i, results[i])
            ledger.maybe_snapshot()
        assert ledger.snapshots >= 1
        replay = [i for i, _ in ledger.pending()]
        assert replay == [3, 4, 5]
        # failover replay: a duplicate of an acked mb arrives too (it was
        # in flight when the stage died) — dropped, not re-delivered
        assert not ledger.ack(2, results[2] + 99)
        for i in (4, 3, 5):                       # out-of-order arrivals
            assert ledger.ack(i, results[i])
            ledger.maybe_snapshot()
        assert ledger.done.is_set()
        assert len(delivered) == 6
        for i, out in enumerate(delivered):
            assert out.tobytes() == results[i].tobytes()
    finally:
        rt.handle_results = orig
        while not rt.label_queue.empty():
            rt.label_queue.get()


def test_ledger_snapshot_disabled_keeps_payloads():
    rt, ledger, _ = _make_ledger(4, snapshot_every=0)
    orig = rt.handle_results
    rt.handle_results = lambda out: None
    try:
        for i in range(4):
            ledger.ack(i, np.full((2,), float(i)))
            assert not ledger.maybe_snapshot()
        assert ledger.snapshots == 0
        assert all(u is not None for u in ledger._ubatches)
    finally:
        rt.handle_results = orig
        while not rt.label_queue.empty():
            rt.label_queue.get()


def test_ledger_snapshot_respects_epoch_fence():
    rt, ledger, _ = _make_ledger(4, snapshot_every=1)
    orig = rt.handle_results
    rt.handle_results = lambda out: None
    try:
        ledger.fence_rank(2, 3)
        assert not ledger.ack(0, np.zeros(2), epoch=1, src=2)  # stale
        assert ledger.stale_dropped == 1
        assert ledger.ack(0, np.zeros(2), epoch=3, src=2)
        ledger.maybe_snapshot()
        assert ledger._frontier == 1
    finally:
        rt.handle_results = orig
        while not rt.label_queue.empty():
            rt.label_queue.get()


# -- host pipeline: edge-skip + latency breakdown -----------------------

def test_host_pipeline_latency_breakdown_stats():
    import jax.numpy as jnp

    from pipeedge_tpu.parallel.pipeline import HostPipeline, PipelineStage

    import jax
    dev = jax.devices()[0]
    stage = PipelineStage(shard_fn=lambda p, x: x * 2.0, params={},
                          device=dev, name="s0")
    pipe = HostPipeline([stage])
    ubatches = [jnp.ones((2, 4)) * i for i in range(5)]
    results, stats = pipe.run(ubatches)
    assert len(results) == 5
    bd = stats["latency_breakdown"]
    assert set(bd) == {"fill_ms", "steady_p50_ms", "steady_p99_ms"}
    assert bd["fill_ms"] > 0 and bd["steady_p50_ms"] > 0
    assert bd["steady_p99_ms"] >= bd["steady_p50_ms"]


def test_payload_on_device_detection():
    import jax
    import jax.numpy as jnp

    from pipeedge_tpu.parallel import pipeline as pl

    devs = jax.devices()
    committed = jax.device_put(jnp.ones((2, 2)), devs[0])
    assert pl._payload_on_device(committed, devs[0])
    assert not pl._payload_on_device(np.ones((2, 2)), devs[0])
    if len(devs) > 1:
        assert not pl._payload_on_device(committed, devs[1])
    assert pl._payload_on_device((committed, committed), devs[0])
