"""Int8 compute path (ops/int8_matmul.py + the QuantizeCompute routing in
models/layers.py): kernel-vs-XLA parity in interpret mode (incl. all-zero
blocks and saturating outliers), the wire-tunnel activation-exactness
contract, config/env/setter semantics, and the end-to-end tunnel through
build_pipeline on the tiny ViT fixture."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from pipeedge_tpu.models import layers  # noqa: E402
from pipeedge_tpu.ops import int8_matmul, quant as quant_ops  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_quantize_state(monkeypatch):
    """Tests toggle trace-time process globals; leave no residue."""
    monkeypatch.delenv("PIPEEDGE_QUANTIZE_COMPUTE", raising=False)
    monkeypatch.delenv("PIPEEDGE_QUANTIZE_SKIP", raising=False)
    monkeypatch.delenv(int8_matmul.ENV_INT8_MATMUL, raising=False)
    prev = layers._QUANTIZE_COMPUTE
    yield
    layers.set_quantize_compute(prev)


def _quantized(m=16, k=256, n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(m, k)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(k, n)), jnp.float32)
    x_q, x_s = int8_matmul.quantize_act_blocks(x, 128)
    w_q, w_s = int8_matmul.quantize_weight(w)
    return x, w, x_q, x_s, w_q, w_s


# -- kernel parity -------------------------------------------------------

def test_interpret_kernel_matches_xla_reference():
    _, _, x_q, x_s, w_q, w_s = _quantized()
    got = int8_matmul.matmul_pallas(x_q, x_s, w_q, w_s, 128,
                                    interpret=True)
    ref = int8_matmul.matmul_xla(x_q, x_s, w_q, w_s, 128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


def test_block_scaled_math_tracks_exact_matmul():
    x, w, x_q, x_s, w_q, w_s = _quantized()
    got = np.asarray(int8_matmul.matmul_xla(x_q, x_s, w_q, w_s, 128))
    exact = np.asarray(x @ w)
    rel = np.abs(got - exact).max() / np.abs(exact).max()
    assert rel < 0.05, rel


def test_all_zero_blocks_decode_exactly():
    """Scale-1 guard: zero activations/channels must stay exactly zero
    (a 0/0 scale would NaN the whole tile)."""
    x = jnp.zeros((8, 256), jnp.float32)
    w = jnp.zeros((256, 32), jnp.float32)
    x_q, x_s = int8_matmul.quantize_act_blocks(x, 128)
    w_q, w_s = int8_matmul.quantize_weight(w)
    assert np.all(np.asarray(x_s) == 1.0)
    assert np.all(np.asarray(w_s) == 1.0)
    y = int8_matmul.matmul_pallas(x_q, x_s, w_q, w_s, 128, interpret=True)
    assert np.all(np.asarray(y) == 0.0)


def test_saturating_outlier_clips_and_stays_blockwise():
    """A huge outlier saturates its own k-block's scale; other blocks'
    quantization is untouched (the point of block scaling)."""
    rng = np.random.default_rng(1)
    x = rng.normal(size=(4, 256)).astype(np.float32)
    x[0, 3] = 1e4                                   # outlier in block 0
    x_q, x_s = int8_matmul.quantize_act_blocks(jnp.asarray(x), 128)
    x_q, x_s = np.asarray(x_q), np.asarray(x_s)
    assert x_q.min() >= -127 and x_q.max() <= 127
    assert x_s[0, 0] == pytest.approx(1e4 / 127.0)
    # row 0 block 1 scale is outlier-free (pure ~N(0,1) amax)
    assert x_s[0, 1] < 0.1
    # other rows completely unaffected
    ref_q, ref_s = int8_matmul.quantize_act_blocks(jnp.asarray(x[1:]), 128)
    np.testing.assert_array_equal(x_q[1:], np.asarray(ref_q))
    np.testing.assert_array_equal(x_s[1:], np.asarray(ref_s))


def test_int8_dense_shapes_bias_and_clamp():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, 5, 96)), jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(96, 32)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(32,)), jnp.float32)
    y = int8_matmul.int8_dense(x, w, b)
    assert y.shape == (2, 5, 32) and y.dtype == jnp.bfloat16
    exact = np.asarray(x.astype(jnp.float32) @ w + b)
    got = np.asarray(y, np.float32)
    assert np.abs(got - exact).max() / np.abs(exact).max() < 0.1
    # a clamp alpha below the data range changes the result (it's applied)
    y_cl = int8_matmul.int8_dense(x, w, b, clamp_alpha=0.1)
    assert not np.array_equal(np.asarray(y_cl, np.float32), got)


def test_mode_env_dispatch(monkeypatch):
    monkeypatch.setenv(int8_matmul.ENV_INT8_MATMUL, "off")
    assert not int8_matmul.kernel_available()
    monkeypatch.setenv(int8_matmul.ENV_INT8_MATMUL, "interpret")
    assert int8_matmul.kernel_available()
    monkeypatch.setenv(int8_matmul.ENV_INT8_MATMUL, "auto")
    if jax.default_backend() != "tpu":
        assert not int8_matmul.kernel_available()   # XLA reference path


# -- wire tunnel (consumer side) ----------------------------------------

def test_wire_dense_activation_side_is_exact():
    """The affine identity: wire_dense == decode-then-matmul against the
    DEQUANTIZED weight — the activation side loses nothing; only the
    weight quantization deviates from the f32 dense."""
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(6, 4, 128)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(128, 48)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(48,)), jnp.float32)
    enc = quant_ops.tensor_encode_outerdim(x, 8)
    got = np.asarray(int8_matmul.wire_dense({"w": w, "b": b}, enc))
    w_q, w_s = int8_matmul.quantize_weight(w)
    w_deq = np.asarray(w_q, np.float32) * np.asarray(w_s)[None, :]
    x_deq = np.asarray(quant_ops.tensor_decode_outerdim(enc))
    ref = x_deq.reshape(-1, 128) @ w_deq + np.asarray(b)
    np.testing.assert_allclose(got.reshape(-1, 48), ref,
                               rtol=1e-4, atol=1e-4)
    assert got.shape == (6, 4, 48)


def test_wire_dense_rejects_non_8bit():
    x = jnp.ones((2, 2, 128), jnp.float32)
    enc = quant_ops.tensor_encode_outerdim(x, 4)
    with pytest.raises(ValueError, match="8-bit"):
        int8_matmul.wire_dense({"w": jnp.ones((128, 8)),
                                "b": jnp.zeros((8,))}, enc)


# -- QuantizeCompute config semantics -----------------------------------

def test_quantize_compute_setter_env_and_skip(monkeypatch):
    assert not layers.quantize_compute().enabled       # default off
    monkeypatch.setenv("PIPEEDGE_QUANTIZE_COMPUTE", "1")
    monkeypatch.setenv("PIPEEDGE_QUANTIZE_SKIP", "attn.out,mlp.down")
    layers.set_quantize_compute(None)                  # defer to env
    qc = layers.quantize_compute()
    assert qc.enabled and qc.skip_tags == {"attn.out", "mlp.down"}
    # the programmatic setter beats the env (the A/B pin the recipes use)
    layers.set_quantize_compute(False)
    assert not layers.quantize_compute().enabled
    cfg = layers.QuantizeCompute(enabled=True, block_k=64,
                                 clamp_alphas={"mlp.up": 2.5})
    layers.set_quantize_compute(cfg)
    assert layers.quantize_compute() is cfg


def test_tagged_dense_routes_and_untagged_stays_exact():
    rng = np.random.default_rng(4)
    p = {"w": jnp.asarray(rng.normal(size=(128, 32)), jnp.float32),
         "b": jnp.zeros((32,), jnp.float32)}
    x = jnp.asarray(rng.normal(size=(3, 128)), jnp.float32)
    exact = np.asarray(layers.dense(p, x))
    layers.set_quantize_compute(layers.QuantizeCompute(enabled=True))
    tagged = np.asarray(layers.dense(p, x, tag="mlp.up"))
    untagged = np.asarray(layers.dense(p, x))
    skipped = None
    layers.set_quantize_compute(layers.QuantizeCompute(
        enabled=True, skip_tags=frozenset({"mlp.up"})))
    skipped = np.asarray(layers.dense(p, x, tag="mlp.up"))
    np.testing.assert_array_equal(untagged, exact)     # untagged: exact
    np.testing.assert_array_equal(skipped, exact)      # opt-out: exact
    assert not np.array_equal(tagged, exact)           # routed: quantized
    assert np.abs(tagged - exact).max() / np.abs(exact).max() < 0.05


def test_observer_sees_tagged_activations():
    seen = []
    rng = np.random.default_rng(5)
    p = {"w": jnp.asarray(rng.normal(size=(64, 16)), jnp.float32),
         "b": jnp.zeros((16,), jnp.float32)}
    x = jnp.asarray(rng.normal(size=(2, 64)), jnp.float32)
    prev = layers._QC_OBSERVER
    layers._QC_OBSERVER = lambda tag, a: seen.append(tag)
    try:
        layers.dense(p, x, tag="attn.q")
        layers.dense(p, x)                             # untagged: silent
    finally:
        layers._QC_OBSERVER = prev
    assert seen == ["attn.q"]


# -- end-to-end tunnel through build_pipeline ---------------------------

MODEL = "pipeedge/test-tiny-vit"


def _tiny_images(batch=8, seed=0):
    from pipeedge_tpu.models import registry
    cfg = registry.get_model_config(MODEL)
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(
        batch, cfg.num_channels, cfg.image_size, cfg.image_size)),
        jnp.float32)


def test_tunnel_stage_consumes_wire_payload_without_dequant():
    """build_pipeline with tunnel=True + an 8-bit edge: stage 1 is marked
    tunnel, runs, and its logits track the non-tunnel quantized pipeline
    (same wire bytes in, int8-weight deviation only)."""
    from pipeedge_tpu.parallel import pipeline as pl_mod

    x = _tiny_images()
    layers.set_quantize_compute(layers.QuantizeCompute(
        enabled=True, tunnel=True))
    try:
        stages = pl_mod.build_pipeline(MODEL, [(1, 1), (2, 8)],
                                       quant_bits=[8]).stages
        assert [s.tunnel for s in stages] == [False, True]
        payload = stages[0](x)
        logits_tunnel = np.asarray(stages[1](payload))
    finally:
        layers.set_quantize_compute(None)

    # reference: same int8 compute + 8-bit edge, but decode-then-matmul
    layers.set_quantize_compute(layers.QuantizeCompute(enabled=True))
    try:
        ref_stages = pl_mod.build_pipeline(MODEL, [(1, 1), (2, 8)],
                                           quant_bits=[8]).stages
        assert [s.tunnel for s in ref_stages] == [False, False]
        logits_ref = np.asarray(ref_stages[1](ref_stages[0](x)))
    finally:
        layers.set_quantize_compute(None)
    assert logits_tunnel.shape == logits_ref.shape
    # the tunnel's only deviation is consuming the identical wire bytes
    # on the MXU directly; agreement with the decode-first route is tight
    assert np.abs(logits_tunnel - logits_ref).max() < 0.05
    assert np.mean(np.argmax(logits_tunnel, -1)
                   == np.argmax(logits_ref, -1)) >= 0.99


def test_tunnel_gating_requires_wire_sub_boundary():
    """A partition split at a non-wire sublayer (layer_start % 4 not in
    wire_subs) must NOT tunnel even when asked to."""
    from pipeedge_tpu.parallel import pipeline as pl_mod

    layers.set_quantize_compute(layers.QuantizeCompute(
        enabled=True, tunnel=True))
    try:
        # layer_start=4 -> (4-1)%4 == 3 in wire_subs -> tunnel
        stages = pl_mod.build_pipeline(MODEL, [(1, 3), (4, 8)],
                                       quant_bits=[8]).stages
        assert stages[1].tunnel
        # layer_start=3 -> (3-1)%4 == 2 not in wire_subs -> no tunnel
        stages = pl_mod.build_pipeline(MODEL, [(1, 2), (3, 8)],
                                       quant_bits=[8]).stages
        assert not stages[1].tunnel
        # 4-bit edge: wire_dense can't consume it -> no tunnel
        stages = pl_mod.build_pipeline(MODEL, [(1, 3), (4, 8)],
                                       quant_bits=[4]).stages
        assert not stages[1].tunnel
    finally:
        layers.set_quantize_compute(None)


def test_int8_compute_top1_agreement_on_fixture():
    """The recipe's quality gate, in-process: pure int8 compute (no wire
    edge) agrees >= 0.99 top-1 with exact on the tiny fixture."""
    from pipeedge_tpu.models import registry

    x = _tiny_images(batch=16)
    fn, params, _ = registry.module_shard_factory(
        MODEL, None, 1, registry.get_model_layers(MODEL))
    raw = fn.__wrapped__
    exact = np.asarray(jax.jit(raw)(params, x))
    layers.set_quantize_compute(layers.QuantizeCompute(enabled=True))
    try:
        q = np.asarray(jax.jit(raw)(params, x))
    finally:
        layers.set_quantize_compute(None)
    assert np.mean(np.argmax(exact, -1) == np.argmax(q, -1)) >= 0.99
