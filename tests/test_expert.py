"""Expert parallelism: the ep-sharded switch-FFN must match the
single-device routing oracle exactly, and capacity overflow must drop to
the residual path."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from pipeedge_tpu.models.layers import TransformerConfig, gelu
from pipeedge_tpu.parallel import expert as ep_mod

CFG = TransformerConfig(model_type="vit", hidden_size=32,
                        num_hidden_layers=1, num_attention_heads=4,
                        intermediate_size=64, num_labels=0, image_size=16,
                        patch_size=4)


@pytest.mark.parametrize("n_ep", [2, 4])
def test_ep_ffn_matches_reference(n_ep):
    n_experts = 8
    params = ep_mod.init_moe_params(CFG, n_experts, seed=1)
    x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 16, 32)),
                    jnp.float32)
    expected = np.asarray(ep_mod.reference_moe_ffn(params, x, n_experts))
    mesh = Mesh(np.asarray(jax.devices()[:n_ep]), ("ep",))
    fn = ep_mod.make_ep_ffn_fn(CFG, mesh, n_experts, act=gelu)
    got = np.asarray(fn(ep_mod.shard_moe_params(params, mesh), x))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_ep_capacity_drops_to_residual():
    """With capacity_factor small enough, overflow tokens must pass through
    unchanged (the switch-style residual drop)."""
    n_experts = 4
    params = ep_mod.init_moe_params(CFG, n_experts, seed=3)
    x = jnp.asarray(np.random.default_rng(4).normal(size=(1, 32, 32)),
                    jnp.float32)
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("ep",))
    fn = ep_mod.make_ep_ffn_fn(CFG, mesh, n_experts, capacity_factor=0.125, act=gelu)
    out = np.asarray(fn(ep_mod.shard_moe_params(params, mesh), x))
    # capacity = ceil(0.125 * 32 / 4) = 1 slot per expert -> at most
    # n_experts tokens transformed; everyone else must be untouched
    changed = (np.abs(out - np.asarray(x)) > 1e-7).any(axis=-1).sum()
    assert 0 < changed <= n_experts, changed
    ref = np.asarray(ep_mod.reference_moe_ffn(params, x, n_experts,
                                              capacity_factor=0.125))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_ep_requires_divisible_experts():
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("ep",))
    with pytest.raises(ValueError, match="must divide"):
        ep_mod.make_ep_ffn_fn(CFG, mesh, n_experts=6, act=gelu)


def test_ep_capacity_clamps_to_token_count():
    """capacity_factor large enough that per-expert capacity exceeds the
    token count must clamp, not crash top_k."""
    n_experts = 2
    params = ep_mod.init_moe_params(CFG, n_experts, seed=5)
    x = jnp.asarray(np.random.default_rng(6).normal(size=(1, 16, 32)),
                    jnp.float32)
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("ep",))
    fn = ep_mod.make_ep_ffn_fn(CFG, mesh, n_experts, capacity_factor=8.0, act=gelu)
    got = np.asarray(fn(ep_mod.shard_moe_params(params, mesh), x))
    ref = np.asarray(ep_mod.reference_moe_ffn(params, x, n_experts,
                                              capacity_factor=8.0))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
