"""Profiler -> converters -> scheduler end-to-end loop.

This is the reference's offline workflow (README_Profiler.md): profile per
layer, convert to models.yml + device_types.yml, feed the native scheduler.
"""
import os
import shutil
import subprocess
import sys

import jax.numpy as jnp
import pytest
import yaml

from pipeedge_tpu import profiler as prof
from pipeedge_tpu.models import registry
from pipeedge_tpu.sched.scheduler import _REPO_BUILD_PATHS, sched_pipeline

pytestmark = pytest.mark.slow  # profiles compile per-layer programs

MODEL = "pipeedge/test-tiny-vit"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def profile_results():
    inputs = prof.default_inputs(MODEL, 2)
    results = prof.profile_layers_individually(
        MODEL, None, inputs, 1, registry.get_model_layers(MODEL),
        warmup=True, iterations=2, reuse_identical=False)
    return {
        "model_name": MODEL,
        "dtype": "float32",
        "batch_size": 2,
        "layers": registry.get_model_layers(MODEL),
        "profile_data": results,
    }


def test_profile_schema_and_chaining(profile_results):
    data = profile_results["profile_data"]
    assert [d["layer"] for d in data] == list(range(1, 9))
    for d in data:
        assert d["time"] > 0
        assert d["memory"] > 0
        assert isinstance(d["shape_in"], list)
    # chaining: layer n's shape_out == layer n+1's shape_in
    for a, b in zip(data, data[1:]):
        assert a["shape_out"] == b["shape_in"]
    # tuple payloads after attention/MLP-up sublayers (2 shapes)
    assert len(data[0]["shape_out"]) == 2   # after sublayer 0
    assert len(data[1]["shape_out"]) == 1   # residual folded
    # first input: image dims; last output: logits
    assert data[0]["shape_in"] == [[3, 16, 16]]
    assert data[-1]["shape_out"] == [[5]]


def test_reuse_identical_matches_exhaustive(profile_results, monkeypatch):
    """Structural memoization measures only the unique layer computations
    (embed+block sublayers+tail) and reproduces the exhaustive schema."""
    measured = []
    real = prof.time_shard_fn

    def counting(fn, params, payload, iterations, warmup=True):
        measured.append(1)
        return real(fn, params, payload, iterations, warmup=warmup)

    monkeypatch.setattr(prof, "time_shard_fn", counting)
    inputs = prof.default_inputs(MODEL, 2)
    results = prof.profile_layers_individually(
        MODEL, None, inputs, 1, registry.get_model_layers(MODEL),
        warmup=True, iterations=2)
    # 8 layers = 2 blocks: layer 1 (embed+type0), types 1-3, type0 bare,
    # layer 8 (type3+tail) -> 6 unique computations
    assert len(measured) == 6
    exhaustive = profile_results["profile_data"]
    assert [(d["layer"], d["shape_in"], d["shape_out"], d["memory"])
            for d in results] == \
           [(d["layer"], d["shape_in"], d["shape_out"], d["memory"])
            for d in exhaustive]


def test_profile_gpt2_family():
    """The causal-decoder family profiles per layer like the encoders:
    token-id inputs, chained payloads (incl. mid-block 2-tuples), LM-head
    logits at the end — so the profile -> models.yml/device_types.yml ->
    sched-pipeline loop covers GPT-2 unchanged."""
    model = "pipeedge/test-tiny-gpt2"
    inputs = prof.default_inputs(model, 2)
    assert inputs.dtype == jnp.int32 and inputs.shape == (2, 64)
    results = prof.profile_layers_individually(
        model, None, inputs, 1, registry.get_model_layers(model),
        warmup=True, iterations=2)
    assert [d["layer"] for d in results] == list(range(1, 9))
    for a, b in zip(results, results[1:]):
        assert a["shape_out"] == b["shape_in"]
    assert len(results[0]["shape_out"]) == 2    # (ctx, residual) mid-block
    assert results[-1]["shape_out"] == [[64, 100]]  # per-token vocab logits


def test_profile_moe_family():
    """MoE blocks profile per layer too: the routed FFN is all in sublayer
    2, sublayer 3 is the parameter-free residual add."""
    model = "pipeedge/test-tiny-moe"
    inputs = prof.default_inputs(model, 2)
    results = prof.profile_layers_individually(
        model, None, inputs, 1, registry.get_model_layers(model),
        warmup=True, iterations=2)
    assert [d["layer"] for d in results] == list(range(1, 9))
    for a, b in zip(results, results[1:]):
        assert a["shape_out"] == b["shape_in"]
    assert len(results[2]["shape_out"]) == 2   # (delta, residual) after sub 2
    assert results[-1]["shape_out"] == [[64, 100]]


def test_validate_profile_results(profile_results):
    prof.validate_profile_results(profile_results, MODEL, "float32", 2, 8, 9, 9)
    with pytest.raises(AssertionError):
        prof.validate_profile_results(profile_results, MODEL, "float32", 2, 8, 1, 1)
    with pytest.raises(AssertionError):
        prof.validate_profile_results(profile_results, "other", "float32", 2, 8, 9, 9)


@pytest.mark.skipif(
    not (os.path.exists(_REPO_BUILD_PATHS[0]) or shutil.which("sched-pipeline")),
    reason="sched-pipeline binary not built")
@pytest.mark.fleet
def test_convert_and_schedule_end_to_end(profile_results, tmp_path):
    results_yml = tmp_path / "profiler_results.yml"
    with open(results_yml, "w", encoding="utf-8") as f:
        yaml.safe_dump(profile_results, f, default_flow_style=None)

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    models_yml = tmp_path / "models.yml"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "profiler_results_to_models.py"),
         "-i", str(results_yml), "-o", str(models_yml)],
        capture_output=True, env=env, cwd=REPO)
    assert r.returncode == 0, r.stdout.decode() + r.stderr.decode()

    types_yml = tmp_path / "device_types.yml"
    r = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "profiler_results_to_device_types.py"), "tpu-v5e",
         "-i", str(results_yml), "-o", str(types_yml),
         "-dtm", "14000", "-dtb", "10000"],
        capture_output=True, env=env, cwd=REPO)
    assert r.returncode == 0, r.stdout.decode() + r.stderr.decode()

    models = yaml.safe_load(open(models_yml))
    assert models[MODEL]["layers"] == 8
    assert models[MODEL]["parameters_in"] == 3 * 16 * 16
    assert models[MODEL]["parameters_out"][-1] == 5
    types = yaml.safe_load(open(types_yml))
    assert len(types["tpu-v5e"]["model_profiles"][MODEL][0]["time_s"]) == 8

    devices_yml = tmp_path / "devices.yml"
    with open(devices_yml, "w") as f:
        yaml.safe_dump({"tpu-v5e": ["chip0", "chip1"]}, f)
    schedule = sched_pipeline(MODEL, 2, 2, 2, dtype="float32",
                              models_file=str(models_yml),
                              dev_types_file=str(types_yml),
                              dev_file=str(devices_yml))
    covered = []
    for stage in schedule:
        (_, (l, r)), = stage.items()
        covered.extend(range(l, r + 1))
    assert covered == list(range(1, 9))
