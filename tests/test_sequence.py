"""Sequence-parallel (ring / Ulysses) attention tests on the 8-device mesh."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from pipeedge_tpu.parallel.sequence import (
    _ring_steps, make_sequence_parallel_attention)


def _reference_attention(q, k, v, causal=False, window=None):
    d = q.shape[-1]
    scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if causal or window is not None:
        s = q.shape[1]
        mask = np.tril(np.ones((s, s), bool))
        if window is not None:
            # Mistral semantics: q attends to k in (q - window, q]
            pos = np.arange(s)
            mask &= pos[None, :] > pos[:, None] - window
        scores = np.where(mask[None, None], scores, -np.inf)
    scores = scores - scores.max(axis=-1, keepdims=True)
    probs = np.exp(scores)
    probs = probs / probs.sum(axis=-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", probs, v)


def _mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("sp",))


@pytest.mark.parametrize("kind", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_matches_full_attention(kind, causal):
    rng = np.random.default_rng(0)
    b, s, h, d = 2, 64, 8, 16
    q = rng.normal(size=(b, s, h, d)).astype(np.float32)
    k = rng.normal(size=(b, s, h, d)).astype(np.float32)
    v = rng.normal(size=(b, s, h, d)).astype(np.float32)
    fn = make_sequence_parallel_attention(_mesh(8), kind=kind, causal=causal)
    out = np.asarray(fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    expected = _reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)


def test_ring_long_sequence_4_devices():
    rng = np.random.default_rng(1)
    b, s, h, d = 1, 512, 4, 32
    q = rng.normal(size=(b, s, h, d)).astype(np.float32)
    k = rng.normal(size=(b, s, h, d)).astype(np.float32)
    v = rng.normal(size=(b, s, h, d)).astype(np.float32)
    fn = make_sequence_parallel_attention(_mesh(4), kind="ring")
    out = np.asarray(fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    np.testing.assert_allclose(out, _reference_attention(q, k, v),
                               rtol=2e-4, atol=2e-5)


def test_ring_bfloat16():
    rng = np.random.default_rng(2)
    b, s, h, d = 1, 32, 4, 16
    q = rng.normal(size=(b, s, h, d)).astype(np.float32)
    k = rng.normal(size=(b, s, h, d)).astype(np.float32)
    v = rng.normal(size=(b, s, h, d)).astype(np.float32)
    fn = make_sequence_parallel_attention(_mesh(4), kind="ring")
    out = np.asarray(fn(jnp.asarray(q, jnp.bfloat16), jnp.asarray(k, jnp.bfloat16),
                        jnp.asarray(v, jnp.bfloat16)))
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(out.astype(np.float32),
                               _reference_attention(q, k, v), rtol=0.1, atol=0.05)


@pytest.mark.parametrize("kind", ["ring", "ulysses"])
def test_gqa_kv_heads_match_full_attention(kind):
    """GQA-aware cores: k/v carry fewer heads than q, repeat only inside
    the local attend (AFTER the ppermute/all-to-all, so inter-chip bytes
    stay kv_heads-sized) — output matches full attention on the repeated
    oracle."""
    rng = np.random.default_rng(7)
    b, s, h, kv, d = 2, 32, 8, 4, 16
    q = rng.normal(size=(b, s, h, d)).astype(np.float32)
    k = rng.normal(size=(b, s, kv, d)).astype(np.float32)
    v = rng.normal(size=(b, s, kv, d)).astype(np.float32)
    fn = make_sequence_parallel_attention(_mesh(4), kind=kind, causal=True)
    out = np.asarray(fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    expected = _reference_attention(q, np.repeat(k, h // kv, 2),
                                    np.repeat(v, h // kv, 2), causal=True)
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("kind", ["ring", "ulysses"])
@pytest.mark.parametrize("window", [1, 3, 8, 17, 64])
def test_sliding_window_matches_full_attention(kind, window):
    """Windowed causal attention (Mistral semantics: k in (q - window, q])
    matches the masked reference for windows smaller than, equal to, and
    larger than the per-chip chunk (s/n = 8) — so both the mask math and
    the ring's step-skipping are exercised."""
    rng = np.random.default_rng(11)
    b, s, h, d = 2, 64, 8, 16
    q = rng.normal(size=(b, s, h, d)).astype(np.float32)
    k = rng.normal(size=(b, s, h, d)).astype(np.float32)
    v = rng.normal(size=(b, s, h, d)).astype(np.float32)
    fn = make_sequence_parallel_attention(_mesh(8), kind=kind, causal=True,
                                          window=window)
    out = np.asarray(fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    expected = _reference_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)


def test_sliding_window_gqa_ring():
    """Window + GQA compose in the ring core (the Mistral sp-prefill
    shape: fewer kv heads AND a window anchored to global positions)."""
    rng = np.random.default_rng(13)
    b, s, h, kv, d = 1, 64, 8, 4, 16
    q = rng.normal(size=(b, s, h, d)).astype(np.float32)
    k = rng.normal(size=(b, s, kv, d)).astype(np.float32)
    v = rng.normal(size=(b, s, kv, d)).astype(np.float32)
    fn = make_sequence_parallel_attention(_mesh(4), kind="ring", causal=True,
                                          window=5)
    out = np.asarray(fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    expected = _reference_attention(q, np.repeat(k, h // kv, 2),
                                    np.repeat(v, h // kv, 2), causal=True,
                                    window=5)
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)


def test_ring_window_skips_out_of_window_blocks():
    """The ring runs only the steps whose K/V block can intersect some
    query's window: the skipped blocks are never computed or rotated."""
    assert _ring_steps(8, 8, 1) == 1        # self-attention only
    # window 8, chunk 8: a chunk-boundary query reaches 7 keys into the
    # previous block -> resident + 1 predecessor
    assert _ring_steps(8, 8, 8) == 2
    assert _ring_steps(8, 8, 9) == 2        # max distance 8 still fits t=1
    assert _ring_steps(8, 8, 10) == 3       # distance 9 first needs t=2
    assert _ring_steps(8, 8, 17) == 3
    assert _ring_steps(8, 8, None) == 8     # no window: full ring
    assert _ring_steps(8, 8, 10**9) == 8    # huge window: capped at n
    # the Mistral shape: 4k window, 128k prompt over 8 chips (16k chunks)
    assert _ring_steps(8, 16384, 4096) == 2


def test_window_requires_causal():
    with pytest.raises(ValueError, match="requires causal"):
        make_sequence_parallel_attention(_mesh(4), kind="ring", causal=False,
                                         window=4)(
            jnp.zeros((1, 16, 4, 8)), jnp.zeros((1, 16, 4, 8)),
            jnp.zeros((1, 16, 4, 8)))


@pytest.mark.slow
def test_ulysses_blockwise_no_full_score_materialization():
    """Ulysses runs blockwise local attention after the all-to-all: peak
    temp memory must stay well under the full [S, S] float32 score
    matrix a naive local softmax would materialize per head group."""
    mesh = _mesh(8)
    b, s, h, d = 1, 2048, 8, 16
    # build the shard_map'd core directly so it can be lowered (compiled
    # memory analysis) without executing
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from pipeedge_tpu.parallel.sequence import resolve_sp_core
    from pipeedge_tpu.utils import jax_compat
    spec = P(None, "sp")
    inner = resolve_sp_core("ulysses")
    f = jax.jit(jax_compat.shard_map(
        partial(inner, axis_name="sp", causal=True),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec))
    x = jnp.zeros((b, s, h, d), jnp.float32)
    mem = f.lower(x, x, x).compile().memory_analysis()
    full_scores_bytes = s * s * 4              # [1, h/n=1, S, S] f32
    assert mem.temp_size_in_bytes < full_scores_bytes / 2, (
        f"temp {mem.temp_size_in_bytes} vs full-score "
        f"{full_scores_bytes} — blockwise regressed to [S,S]?")
    # sanity: ring at the same shape has the same memory scale
    ring = jax.jit(jax_compat.shard_map(
        partial(resolve_sp_core("ring"), axis_name="sp", causal=True),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec))
    ring_mem = ring.lower(x, x, x).compile().memory_analysis()
    assert mem.temp_size_in_bytes < 4 * ring_mem.temp_size_in_bytes


def test_gqa_ulysses_indivisible_kv_falls_back():
    """kv_heads not divisible by the axis size: ulysses pre-repeats K/V to
    lcm(kv, n) — the smallest evenly-splittable head count (here
    lcm(2,4)=4, not the full 8) — instead of failing: correct output,
    lcm/kv x the GQA-ideal all-to-all bytes, one-time warning."""
    rng = np.random.default_rng(9)
    b, s, h, kv, d = 1, 32, 8, 2, 16
    q = rng.normal(size=(b, s, h, d)).astype(np.float32)
    k = rng.normal(size=(b, s, kv, d)).astype(np.float32)
    v = rng.normal(size=(b, s, kv, d)).astype(np.float32)
    fn = make_sequence_parallel_attention(_mesh(4), kind="ulysses",
                                          causal=True)
    out = np.asarray(fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    expected = _reference_attention(q, np.repeat(k, h // kv, 2),
                                    np.repeat(v, h // kv, 2), causal=True)
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)
