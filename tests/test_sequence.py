"""Sequence-parallel (ring / Ulysses) attention tests on the 8-device mesh."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from pipeedge_tpu.parallel.sequence import make_sequence_parallel_attention


def _reference_attention(q, k, v, causal=False):
    d = q.shape[-1]
    scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if causal:
        s = q.shape[1]
        mask = np.tril(np.ones((s, s), bool))
        scores = np.where(mask[None, None], scores, -np.inf)
    scores = scores - scores.max(axis=-1, keepdims=True)
    probs = np.exp(scores)
    probs = probs / probs.sum(axis=-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", probs, v)


def _mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("sp",))


@pytest.mark.parametrize("kind", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_matches_full_attention(kind, causal):
    rng = np.random.default_rng(0)
    b, s, h, d = 2, 64, 8, 16
    q = rng.normal(size=(b, s, h, d)).astype(np.float32)
    k = rng.normal(size=(b, s, h, d)).astype(np.float32)
    v = rng.normal(size=(b, s, h, d)).astype(np.float32)
    fn = make_sequence_parallel_attention(_mesh(8), kind=kind, causal=causal)
    out = np.asarray(fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    expected = _reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)


def test_ring_long_sequence_4_devices():
    rng = np.random.default_rng(1)
    b, s, h, d = 1, 512, 4, 32
    q = rng.normal(size=(b, s, h, d)).astype(np.float32)
    k = rng.normal(size=(b, s, h, d)).astype(np.float32)
    v = rng.normal(size=(b, s, h, d)).astype(np.float32)
    fn = make_sequence_parallel_attention(_mesh(4), kind="ring")
    out = np.asarray(fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    np.testing.assert_allclose(out, _reference_attention(q, k, v),
                               rtol=2e-4, atol=2e-5)


def test_ring_bfloat16():
    rng = np.random.default_rng(2)
    b, s, h, d = 1, 32, 4, 16
    q = rng.normal(size=(b, s, h, d)).astype(np.float32)
    k = rng.normal(size=(b, s, h, d)).astype(np.float32)
    v = rng.normal(size=(b, s, h, d)).astype(np.float32)
    fn = make_sequence_parallel_attention(_mesh(4), kind="ring")
    out = np.asarray(fn(jnp.asarray(q, jnp.bfloat16), jnp.asarray(k, jnp.bfloat16),
                        jnp.asarray(v, jnp.bfloat16)))
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(out.astype(np.float32),
                               _reference_attention(q, k, v), rtol=0.1, atol=0.05)


@pytest.mark.parametrize("kind", ["ring", "ulysses"])
def test_gqa_kv_heads_match_full_attention(kind):
    """GQA-aware cores: k/v carry fewer heads than q, repeat only inside
    the local attend (AFTER the ppermute/all-to-all, so inter-chip bytes
    stay kv_heads-sized) — output matches full attention on the repeated
    oracle."""
    rng = np.random.default_rng(7)
    b, s, h, kv, d = 2, 32, 8, 4, 16
    q = rng.normal(size=(b, s, h, d)).astype(np.float32)
    k = rng.normal(size=(b, s, kv, d)).astype(np.float32)
    v = rng.normal(size=(b, s, kv, d)).astype(np.float32)
    fn = make_sequence_parallel_attention(_mesh(4), kind=kind, causal=True)
    out = np.asarray(fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    expected = _reference_attention(q, np.repeat(k, h // kv, 2),
                                    np.repeat(v, h // kv, 2), causal=True)
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)


def test_gqa_ulysses_indivisible_kv_falls_back():
    """kv_heads not divisible by the axis size: ulysses pre-repeats to the
    full head count (the pre-GQA behavior) instead of failing — correct
    output, full-head all-to-all cost."""
    rng = np.random.default_rng(9)
    b, s, h, kv, d = 1, 32, 8, 2, 16
    q = rng.normal(size=(b, s, h, d)).astype(np.float32)
    k = rng.normal(size=(b, s, kv, d)).astype(np.float32)
    v = rng.normal(size=(b, s, kv, d)).astype(np.float32)
    fn = make_sequence_parallel_attention(_mesh(4), kind="ulysses",
                                          causal=True)
    out = np.asarray(fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    expected = _reference_attention(q, np.repeat(k, h // kv, 2),
                                    np.repeat(v, h // kv, 2), causal=True)
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)
