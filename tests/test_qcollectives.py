"""Quantized ICI collectives (ops/qcollectives.py): error bounds,
determinism, the tensor.py psum gate, and the wire-footprint tally.

Runs on the conftest's 8-device virtual CPU mesh — the ring ppermute
implementation is the portable path (utils/jax_compat.py), so the CPU
mesh exercises exactly the collective the TPU runs.
"""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from pipeedge_tpu.ops import qcollectives
from pipeedge_tpu.utils import jax_compat


def _mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("tp",))


def _shards(n, m, seed=0, scale=1.0):
    rng = np.random.default_rng(seed)
    return jnp.asarray((rng.normal(size=(n, m)) * scale)
                       .astype(np.float32))


def _qpsum_fn(mesh, bit, **kw):
    return jax.jit(jax_compat.shard_map(
        partial(qcollectives.qpsum, axis_name="tp", bit=bit, **kw),
        mesh=mesh, in_specs=P("tp"), out_specs=P("tp")))


def _qag_fn(mesh, bit, **kw):
    return jax.jit(jax_compat.shard_map(
        partial(qcollectives.qall_gather, axis_name="tp", bit=bit, **kw),
        mesh=mesh, in_specs=P("tp"), out_specs=P(None)))


def test_qpsum_bit0_is_exact_psum():
    mesh = _mesh(2)
    x = _shards(2, 512)
    got = np.asarray(_qpsum_fn(mesh, 0)(x))
    exact = np.asarray(x).sum(axis=0)
    assert np.array_equal(got, np.stack([exact, exact]))


@pytest.mark.parametrize("bit", [8, 4])
@pytest.mark.parametrize("n", [2, 4])
def test_qpsum_within_error_bound(bit, n):
    mesh = _mesh(n)
    x = _shards(n, 1024, seed=bit * 10 + n)
    got = np.asarray(_qpsum_fn(mesh, bit)(x))
    exact = np.asarray(x).sum(axis=0)
    absrange = float(max(np.asarray(x)[i].max() - np.asarray(x)[i].min()
                         for i in range(n)))
    bound = qcollectives.qpsum_error_bound(absrange, bit, n)
    err = np.abs(got - exact[None]).max()
    assert err <= bound, (err, bound)
    # and the quantization is actually doing something at int4 (not a
    # silently exact path pretending to compress)
    if bit == 4:
        assert err > 0


@pytest.mark.parametrize("bit", [8, 4])
def test_qpsum_deterministic(bit):
    mesh = _mesh(4)
    x = _shards(4, 768, seed=7)
    fn = _qpsum_fn(mesh, bit)
    a = np.asarray(fn(x))
    b = np.asarray(fn(x))
    assert np.array_equal(a, b)


def test_qpsum_odd_length_and_dtype():
    """Non-block-aligned flat sizes zero-pad internally; bf16 inputs come
    back bf16 with f32 internal accumulation."""
    mesh = _mesh(2)
    x = _shards(2, 333).astype(jnp.bfloat16)
    got = _qpsum_fn(mesh, 8)(x)
    assert got.dtype == jnp.bfloat16
    assert got.shape == (2, 333)
    exact = np.asarray(x.astype(jnp.float32)).sum(axis=0)
    absrange = float(np.abs(np.asarray(x.astype(jnp.float32))).max()) * 2
    bound = qcollectives.qpsum_error_bound(absrange, 8, 2) \
        + np.abs(exact).max() * 2 ** -7  # bf16 output round-off
    assert np.abs(np.asarray(got, np.float32) - exact[None]).max() <= bound


def test_qpsum_multidim_shape_preserved():
    mesh = _mesh(2)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(2, 4, 19, 32)).astype(np.float32))
    got = np.asarray(jax.jit(jax_compat.shard_map(
        partial(qcollectives.qpsum, axis_name="tp", bit=8),
        mesh=mesh, in_specs=P("tp"), out_specs=P("tp")))(x))
    assert got.shape == x.shape
    exact = np.asarray(x).sum(axis=0)
    assert np.abs(got - exact[None]).max() < 0.1


def test_qpsum_clamped_path_runs():
    mesh = _mesh(2)
    x = _shards(2, 512, seed=11)
    got = np.asarray(_qpsum_fn(mesh, 8, clamp=True)(x))
    exact = np.asarray(x).sum(axis=0)
    # Banner clamp trades bounded bias for a smaller step: still close
    assert np.abs(got - exact[None]).max() < 0.5


def test_qpsum_invalid_bit():
    with pytest.raises(ValueError):
        qcollectives.qpsum(jnp.zeros((4,)), "tp", 6)


@pytest.mark.parametrize("bit", [8, 4])
def test_qall_gather_tiled(bit):
    mesh = _mesh(4)
    x = _shards(4, 256, seed=5).reshape(4, 1, 256)
    got = np.asarray(_qag_fn(mesh, bit, axis=1, tiled=True)(x))
    # per-device shard [1, 1, 256]; tiled gather along axis 1 -> [1, 4, 256]
    assert got.shape == (1, 4, 256)
    full = np.concatenate([np.asarray(x)[i] for i in range(4)], axis=0)
    levels = (1 << bit) - 1
    per_shard_range = max(float(np.ptp(np.asarray(x)[i]))
                          for i in range(4))
    tol = per_shard_range / levels / 2 + 1e-5
    assert np.abs(got.reshape(full.shape) - full).max() <= tol


def test_qall_gather_stacked():
    mesh = _mesh(2)
    x = _shards(2, 64, seed=6)
    got = np.asarray(jax.jit(jax_compat.shard_map(
        partial(qcollectives.qall_gather, axis_name="tp", bit=8,
                axis=0, tiled=False),
        mesh=mesh, in_specs=P("tp"), out_specs=P(None)))(x))
    # per-device shard is [1, 64]; tiled=False stacks a new leading axis
    # (the jax.lax.all_gather contract)
    assert got.shape == (2, 1, 64)
    tol = max(float(np.ptp(np.asarray(x)[i])) for i in range(2)) / 255 / 2 \
        + 1e-5
    assert np.abs(got.reshape(2, 64) - np.asarray(x)).max() <= tol


def test_qall_gather_bit0_exact():
    mesh = _mesh(2)
    x = _shards(2, 64).reshape(2, 1, 64)
    got = np.asarray(_qag_fn(mesh, 0, axis=1, tiled=True)(x))
    full = np.concatenate([np.asarray(x)[i] for i in range(2)], axis=0)
    assert np.array_equal(got.reshape(full.shape), full)


# -- tensor.py psum gate --------------------------------------------------

def test_tp_quant_bits_flag_roundtrip():
    from pipeedge_tpu.parallel import tensor
    assert tensor.get_tp_quant_bits() == 0
    tensor.set_tp_quant_bits(8)
    try:
        assert tensor.get_tp_quant_bits() == 8
    finally:
        tensor.set_tp_quant_bits(0)
    with pytest.raises(ValueError):
        tensor.set_tp_quant_bits(3)


def test_tp_block_quantized_close_to_exact():
    """The Megatron block body with quantized psums stays within a tight
    activation tolerance of the exact body — the numerics claim behind
    the near-1.0 top-1 agreement target (ROADMAP item 2)."""
    from pipeedge_tpu.models import registry
    from pipeedge_tpu.parallel import tensor

    cfg = registry.get_model_entry("pipeedge/test-tiny-vit").config
    rng = np.random.default_rng(0)
    bp = registry.module_shard_factory(
        "pipeedge/test-tiny-vit", None, 1, 4, dtype=jnp.float32,
        unroll=True)[1]["blocks"][0]
    mesh = _mesh(2)
    sharded = tensor.shard_block_params(cfg, bp, mesh)
    x = jnp.asarray(rng.normal(size=(2, 17, cfg.hidden_size))
                    .astype(np.float32))
    exact = np.asarray(tensor.make_tp_block_fn(cfg, mesh)(sharded, x))
    tensor.set_tp_quant_bits(8)
    try:
        quant = np.asarray(tensor.make_tp_block_fn(cfg, mesh)(sharded, x))
    finally:
        tensor.set_tp_quant_bits(0)
    assert not np.array_equal(exact, quant)      # the gate actually flips
    scale = max(1.0, float(np.abs(exact).max()))
    assert np.abs(exact - quant).max() / scale < 0.05


# -- wire-footprint tally + telemetry ------------------------------------

def test_tally_records_sites_and_reduction():
    qcollectives.reset_trace_tally()
    mesh = _mesh(2)
    x = _shards(2, 1024, seed=9)
    np.asarray(_qpsum_fn(mesh, 8)(x))
    np.asarray(_qag_fn(mesh, 4, axis=0, tiled=False)(
        x.reshape(2, 1, 1024)))
    tally = qcollectives.trace_tally()
    kinds = {t["kind"] for t in tally}
    assert kinds == {"psum", "all_gather"}
    for t in tally:
        assert 0 < t["wire_bytes"] < t["raw_bytes"]
    ps = next(t for t in tally if t["kind"] == "psum")
    # int8 block-scaled payload: ~4x smaller minus scale/shift metadata
    assert 3.5 < ps["raw_bytes"] / ps["wire_bytes"] < 4.0
    qcollectives.reset_trace_tally()


def test_record_collectives_spans_and_metrics():
    from pipeedge_tpu import telemetry
    qcollectives.reset_trace_tally()
    mesh = _mesh(2)
    np.asarray(_qpsum_fn(mesh, 4)(_shards(2, 512, seed=13)))
    before = qcollectives.COLLECTIVE_BITS_TOTAL.total()
    rec = telemetry.configure(rank=0)
    try:
        summary = qcollectives.record_collectives(executions=3)
    finally:
        spans = rec.snapshot()
        telemetry.disable()
    assert summary["sites"] == 1
    assert summary["wire_bits_total"] > 0
    assert summary["wire_reduction"] > 7      # int4: ~8x minus metadata
    col = [s for s in spans if s["cat"] == "collective"]
    assert len(col) == 1
    name = col[0]["name"]
    assert name.startswith("psum4:")
    # the span name carries the run-total wire bytes (report.py parses it)
    assert int(name.split(":")[1]) * 8 == summary["wire_bits_total"]
    assert qcollectives.COLLECTIVE_BITS_TOTAL.total() - before \
        == summary["wire_bits_total"]
    qcollectives.reset_trace_tally()


def test_report_collectives_section():
    """analyze_spans folds collective spans into the per-stage bits-moved
    section (tools/trace_report.py consumes it)."""
    from pipeedge_tpu.telemetry import report

    t = 1_000_000
    spans = [
        {"cat": "collective", "name": "psum8:1024", "t0": t, "t1": t,
         "rank": 0, "stage": 0},
        {"cat": "collective", "name": "all_gather8:512", "t0": t, "t1": t,
         "rank": 0, "stage": 1},
        {"cat": "stage", "name": "dispatch", "t0": t, "t1": t + 10_000,
         "rank": 0, "stage": 0, "mb": 0},
        {"cat": "wire", "name": "send->r1", "t0": t, "t1": t + 5_000,
         "rank": 0},
    ]
    rec = report.analyze_spans(spans, span_cost_ns=100.0)
    col = rec["collectives"]
    assert col["sites"] == 2
    assert col["wire_bytes"] == 1536
    assert col["by_kind"] == {"all_gather8": 512, "psum8": 1024}
    assert col["per_stage"]["stage0"]["wire_bytes"] == 1024
    assert col["per_stage"]["stage1"]["wire_bytes"] == 512
    assert col["dcn_edge_busy_s"] > 0


def test_error_bound_monotonic():
    """More shards and fewer bits both widen the bound."""
    b84 = qcollectives.qpsum_error_bound(1.0, 8, 4)
    b88 = qcollectives.qpsum_error_bound(1.0, 8, 8)
    b44 = qcollectives.qpsum_error_bound(1.0, 4, 4)
    assert b84 < b88
    assert b84 < b44
