"""SPMD wave decode: one shard_map program per phase vs the host-driven
pipeline, token for token."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from pipeedge_tpu.models import ShardConfig  # noqa: E402
from pipeedge_tpu.models import gpt2 as gpt2_mod  # noqa: E402
from pipeedge_tpu.models.layers import TransformerConfig  # noqa: E402
from pipeedge_tpu.parallel import decode  # noqa: E402
from pipeedge_tpu.parallel.spmd_decode import SpmdDecodePipeline  # noqa: E402

pytestmark = pytest.mark.slow  # compiles whole-wave shard_map programs

TINY = dict(hidden_size=32, num_hidden_layers=3, num_attention_heads=4,
            intermediate_size=64)


@pytest.fixture(scope="module")
def setup():
    from transformers import GPT2Config, GPT2LMHeadModel
    hf_cfg = GPT2Config(n_embd=32, n_layer=3, n_head=4, n_inner=64,
                        vocab_size=100, n_positions=64)
    torch.manual_seed(7)
    model = GPT2LMHeadModel(hf_cfg).eval()
    cfg = TransformerConfig(model_type="gpt2", **TINY, layer_norm_eps=1e-5,
                            vocab_size=100, max_position_embeddings=64)
    weights = {k: v.numpy() for k, v in model.state_dict().items()}
    return cfg, weights


def _stage_params(cfg, partition, weights):
    total = 4 * cfg.num_hidden_layers
    return [gpt2_mod.load_params(
        cfg, ShardConfig(l, r, is_first=l == 1, is_last=r == total), weights)
        for l, r in partition]


@pytest.mark.parametrize("partition", [
    [(1, 4), (5, 8), (9, 12)],      # 3 equal stages
    [(1, 8), (9, 12)],              # uneven: padded+masked block stacks
])
def test_spmd_wave_decode_matches_host_pipeline(setup, partition):
    """R = n_stages concurrent greedy requests through the two shard_map
    wave programs == each request solo through the host DecodePipeline."""
    cfg, weights = setup
    n_stages = len(partition)
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("stage",))
    stage_params = _stage_params(cfg, partition, weights)
    wave = SpmdDecodePipeline(gpt2_mod.FAMILY, cfg, partition, stage_params,
                              mesh, max_len=32)
    host = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition,
                                 stage_params, max_len=32)
    rng = np.random.default_rng(23)
    ids = rng.integers(0, 100, size=(n_stages, 2, 7))
    got = np.asarray(wave.generate(ids, new_tokens=6))
    assert got.shape == (n_stages, 2, 13)
    for r in range(n_stages):
        solo = np.asarray(host.generate(ids[r], new_tokens=6))
        np.testing.assert_array_equal(got[r], solo, err_msg=f"slot {r}")


def test_spmd_wave_sampling_matches_host_rng_discipline(setup):
    """Sampled wave decoding: each slot's token stream reproduces its solo
    host generate() run with the same temperature/top_k/seed — the fleet
    splits each slot's key once per picked token, in lockstep."""
    cfg, weights = setup
    partition = [(1, 4), (5, 8), (9, 12)]
    mesh = Mesh(np.asarray(jax.devices()[:3]), ("stage",))
    stage_params = _stage_params(cfg, partition, weights)
    wave = SpmdDecodePipeline(gpt2_mod.FAMILY, cfg, partition, stage_params,
                              mesh, max_len=32)
    host = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition,
                                 stage_params, max_len=32)
    ids = np.random.default_rng(31).integers(0, 100, size=(3, 2, 6))
    seeds = [5, 11, 2]
    got = np.asarray(wave.generate(ids, new_tokens=5, temperature=0.9,
                                   top_k=7, seeds=seeds))
    for r in range(3):
        solo = np.asarray(host.generate(ids[r], new_tokens=5,
                                        temperature=0.9, top_k=7,
                                        seed=seeds[r]))
        np.testing.assert_array_equal(got[r], solo, err_msg=f"slot {r}")
    with pytest.raises(ValueError, match="seeds"):
        wave.generate(ids, new_tokens=2, temperature=0.9, seeds=[1])


def test_spmd_wave_quantized_prefill_edges(setup):
    """edge_bits packs the [B, S_p, D] prefill hops (QuantPipe riding the
    ppermute): the wave still decodes end-to-end with in-vocab tokens at
    8-bit edges, and 16-bit edges are token-identical to raw on this tiny
    model (quant error far below the argmax margins)."""
    cfg, weights = setup
    partition = [(1, 4), (5, 8), (9, 12)]
    mesh = Mesh(np.asarray(jax.devices()[:3]), ("stage",))
    stage_params = _stage_params(cfg, partition, weights)
    raw = SpmdDecodePipeline(gpt2_mod.FAMILY, cfg, partition, stage_params,
                             mesh, max_len=32)
    ids = np.random.default_rng(37).integers(0, 100, size=(3, 2, 7))
    want = np.asarray(raw.generate(ids, new_tokens=5))
    q16 = SpmdDecodePipeline(gpt2_mod.FAMILY, cfg, partition, stage_params,
                             mesh, max_len=32, edge_bits=16)
    got16 = np.asarray(q16.generate(ids, new_tokens=5))
    np.testing.assert_array_equal(got16, want)
    q8 = SpmdDecodePipeline(gpt2_mod.FAMILY, cfg, partition, stage_params,
                            mesh, max_len=32, edge_bits=8)
    got8 = np.asarray(q8.generate(ids, new_tokens=5))
    assert got8.shape == want.shape
    assert got8[:, :, :7].tolist() == ids.tolist()   # prompts untouched
    assert got8.min() >= 0 and got8.max() < 100      # in-vocab tokens
    with pytest.raises(ValueError, match="edge_bits"):
        SpmdDecodePipeline(gpt2_mod.FAMILY, cfg, partition, stage_params,
                           mesh, max_len=32, edge_bits=5)


def test_spmd_wave_decode_single_token_and_validation(setup):
    cfg, weights = setup
    partition = [(1, 4), (5, 12)]
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("stage",))
    stage_params = _stage_params(cfg, partition, weights)
    wave = SpmdDecodePipeline(gpt2_mod.FAMILY, cfg, partition, stage_params,
                              mesh, max_len=32)
    host = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition,
                                 stage_params, max_len=32)
    ids = np.random.default_rng(29).integers(0, 100, size=(2, 1, 5))
    got = np.asarray(wave.generate(ids, new_tokens=1))
    for r in range(2):
        np.testing.assert_array_equal(
            got[r], np.asarray(host.generate(ids[r], new_tokens=1)))

    with pytest.raises(ValueError, match="slots"):
        wave.generate(ids[:1], new_tokens=2)       # wrong R
    with pytest.raises(ValueError, match="new_tokens"):
        wave.generate(ids, new_tokens=0)
    with pytest.raises(ValueError, match="exceeds"):
        wave.generate(ids, new_tokens=1000)
    moe_cfg = TransformerConfig(model_type="gpt2", **TINY,
                                layer_norm_eps=1e-5, vocab_size=100,
                                max_position_embeddings=64, n_experts=4)
    with pytest.raises(NotImplementedError, match="MoE"):
        SpmdDecodePipeline(gpt2_mod.FAMILY, moe_cfg, partition,
                           stage_params, mesh, max_len=32)


def test_spmd_wave_prefix_caching_matches_host(setup):
    """Wave prompt caching: precompute_prefix + suffix-span-wave generate
    == (a) monolithic wave generate and (b) each slot's solo host
    prefix-seeded generate — the host prefix contract through the wave
    programs, greedy AND sampled."""
    cfg, weights = setup
    partition = [(1, 4), (5, 8), (9, 12)]
    mesh = Mesh(np.asarray(jax.devices()[:3]), ("stage",))
    stage_params = _stage_params(cfg, partition, weights)
    wave = SpmdDecodePipeline(gpt2_mod.FAMILY, cfg, partition, stage_params,
                              mesh, max_len=32)
    host = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition,
                                 stage_params, max_len=32)
    rng = np.random.default_rng(41)
    prefix = rng.integers(0, 100, size=(1, 5))
    suffix = rng.integers(0, 100, size=(3, 2, 4))

    handle = wave.precompute_prefix(prefix)
    got = np.asarray(wave.generate(suffix, 6, prefix=handle))
    assert got.shape == (3, 2, 10)          # prefix omitted
    # (a) == monolithic wave run on prefix+suffix
    full = np.concatenate(
        [np.broadcast_to(prefix[None], (3, 2, 5)), suffix], axis=2)
    want_full = np.asarray(wave.generate(full, 6))
    np.testing.assert_array_equal(got, want_full[:, :, 5:])
    # (b) == per-slot host prefix-seeded generate
    h_handle = host.precompute_prefix(prefix)
    for r in range(3):
        want = np.asarray(host.generate(suffix[r], 6, prefix=h_handle))
        np.testing.assert_array_equal(got[r], want, err_msg=f"slot {r}")

    # sampled: per-slot rng discipline holds through the suffix span
    got_s = np.asarray(wave.generate(suffix, 5, temperature=0.8,
                                     seeds=[3, 4, 5], prefix=handle))
    for r in range(3):
        want = np.asarray(host.generate(suffix[r], 5, temperature=0.8,
                                        seed=3 + r, prefix=h_handle))
        np.testing.assert_array_equal(got_s[r], want, err_msg=f"slot {r}")

    # foreign/stripped handles are rejected up front
    bad = {k: v for k, v in handle.items() if k != "sig"}
    with pytest.raises(ValueError, match="precompute_prefix handle"):
        wave.generate(suffix, 4, prefix=bad)
    sig = list(handle["sig"])
    sig[2] = handle["sig"][2] + 16          # max_len field
    with pytest.raises(ValueError, match="incompatible"):
        wave.generate(suffix, 4, prefix=dict(handle, sig=tuple(sig)))


def test_spmd_wave_speculative_matches_wave_greedy(setup):
    """SpmdSpeculativeDecoder: verify spans ride ONE span-wave program
    per round; output is token-identical to the wave pipeline's plain
    greedy generate (and hence to per-slot host runs) with both a
    perturbed draft (accept AND reject rounds) and a self-draft
    (acceptance 1.0)."""
    from pipeedge_tpu.parallel.spmd_decode import SpmdSpeculativeDecoder
    cfg, weights = setup
    partition = [(1, 4), (5, 8), (9, 12)]
    mesh = Mesh(np.asarray(jax.devices()[:3]), ("stage",))
    stage_params = _stage_params(cfg, partition, weights)
    wave = SpmdDecodePipeline(gpt2_mod.FAMILY, cfg, partition, stage_params,
                              mesh, max_len=40)
    total = 4 * cfg.num_hidden_layers
    full_params = _stage_params(cfg, [(1, total)], weights)
    draft = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, [(1, total)],
                                  full_params, max_len=40)
    rng = np.random.default_rng(43)
    ids = rng.integers(0, 100, size=(3, 2, 6))
    want = np.asarray(wave.generate(ids, 8))

    # self-draft: every proposal accepted, maximal spans
    spec = SpmdSpeculativeDecoder(wave, draft, gamma=3)
    got = np.asarray(spec.generate(ids, 8))
    np.testing.assert_array_equal(got, want)
    assert spec.last_acceptance_rate == 1.0

    # perturbed draft: rounds exercise accept AND reject paths
    pert = jax.tree_util.tree_map(
        lambda x: x + jnp.asarray(
            np.random.default_rng(7).normal(scale=0.05, size=x.shape),
            x.dtype)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, full_params[0])
    draft2 = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, [(1, total)],
                                   [pert], max_len=40)
    spec2 = SpmdSpeculativeDecoder(wave, draft2, gamma=2)
    got2 = np.asarray(spec2.generate(ids, 8))
    np.testing.assert_array_equal(got2, want)
    assert 0.0 <= spec2.last_acceptance_rate <= 1.0


def test_spmd_wave_prefix_with_quantized_edges(setup):
    """Prefix caching on a quantized-edge wave pipeline: the suffix span
    wave rides the SAME edge codec as the prefill wave, so the prefix
    path stays token-identical to the monolithic quantized-edge run
    (code-review finding: the span hops initially crossed raw)."""
    cfg, weights = setup
    partition = [(1, 4), (5, 8), (9, 12)]
    mesh = Mesh(np.asarray(jax.devices()[:3]), ("stage",))
    stage_params = _stage_params(cfg, partition, weights)
    wave = SpmdDecodePipeline(gpt2_mod.FAMILY, cfg, partition, stage_params,
                              mesh, max_len=32, edge_bits=8)
    rng = np.random.default_rng(47)
    prefix = rng.integers(0, 100, size=(1, 5))
    suffix = rng.integers(0, 100, size=(3, 2, 4))
    handle = wave.precompute_prefix(prefix)
    got = np.asarray(wave.generate(suffix, 6, prefix=handle))
    full = np.concatenate(
        [np.broadcast_to(prefix[None], (3, 2, 5)), suffix], axis=2)
    want = np.asarray(wave.generate(full, 6))
    np.testing.assert_array_equal(got, want[:, :, 5:])

    # a raw-edge pipeline rejects the quantized-edge handle (numerics
    # differ): edge_bits is part of the signature
    raw = SpmdDecodePipeline(gpt2_mod.FAMILY, cfg, partition, stage_params,
                             mesh, max_len=32)
    with pytest.raises(ValueError, match="incompatible"):
        raw.generate(suffix, 4, prefix=handle)
