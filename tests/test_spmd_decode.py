"""SPMD wave decode: one shard_map program per phase vs the host-driven
pipeline, token for token."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from pipeedge_tpu.models import ShardConfig  # noqa: E402
from pipeedge_tpu.models import gpt2 as gpt2_mod  # noqa: E402
from pipeedge_tpu.models.layers import TransformerConfig  # noqa: E402
from pipeedge_tpu.parallel import decode  # noqa: E402
from pipeedge_tpu.parallel.spmd_decode import SpmdDecodePipeline  # noqa: E402

pytestmark = pytest.mark.slow  # compiles whole-wave shard_map programs

TINY = dict(hidden_size=32, num_hidden_layers=3, num_attention_heads=4,
            intermediate_size=64)


@pytest.fixture(scope="module")
def setup():
    from transformers import GPT2Config, GPT2LMHeadModel
    hf_cfg = GPT2Config(n_embd=32, n_layer=3, n_head=4, n_inner=64,
                        vocab_size=100, n_positions=64)
    torch.manual_seed(7)
    model = GPT2LMHeadModel(hf_cfg).eval()
    cfg = TransformerConfig(model_type="gpt2", **TINY, layer_norm_eps=1e-5,
                            vocab_size=100, max_position_embeddings=64)
    weights = {k: v.numpy() for k, v in model.state_dict().items()}
    return cfg, weights


def _stage_params(cfg, partition, weights):
    total = 4 * cfg.num_hidden_layers
    return [gpt2_mod.load_params(
        cfg, ShardConfig(l, r, is_first=l == 1, is_last=r == total), weights)
        for l, r in partition]


@pytest.mark.parametrize("partition", [
    [(1, 4), (5, 8), (9, 12)],      # 3 equal stages
    [(1, 8), (9, 12)],              # uneven: padded+masked block stacks
])
def test_spmd_wave_decode_matches_host_pipeline(setup, partition):
    """R = n_stages concurrent greedy requests through the two shard_map
    wave programs == each request solo through the host DecodePipeline."""
    cfg, weights = setup
    n_stages = len(partition)
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("stage",))
    stage_params = _stage_params(cfg, partition, weights)
    wave = SpmdDecodePipeline(gpt2_mod.FAMILY, cfg, partition, stage_params,
                              mesh, max_len=32)
    host = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition,
                                 stage_params, max_len=32)
    rng = np.random.default_rng(23)
    ids = rng.integers(0, 100, size=(n_stages, 2, 7))
    got = np.asarray(wave.generate(ids, new_tokens=6))
    assert got.shape == (n_stages, 2, 13)
    for r in range(n_stages):
        solo = np.asarray(host.generate(ids[r], new_tokens=6))
        np.testing.assert_array_equal(got[r], solo, err_msg=f"slot {r}")


def test_spmd_wave_sampling_matches_host_rng_discipline(setup):
    """Sampled wave decoding: each slot's token stream reproduces its solo
    host generate() run with the same temperature/top_k/seed — the fleet
    splits each slot's key once per picked token, in lockstep."""
    cfg, weights = setup
    partition = [(1, 4), (5, 8), (9, 12)]
    mesh = Mesh(np.asarray(jax.devices()[:3]), ("stage",))
    stage_params = _stage_params(cfg, partition, weights)
    wave = SpmdDecodePipeline(gpt2_mod.FAMILY, cfg, partition, stage_params,
                              mesh, max_len=32)
    host = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition,
                                 stage_params, max_len=32)
    ids = np.random.default_rng(31).integers(0, 100, size=(3, 2, 6))
    seeds = [5, 11, 2]
    got = np.asarray(wave.generate(ids, new_tokens=5, temperature=0.9,
                                   top_k=7, seeds=seeds))
    for r in range(3):
        solo = np.asarray(host.generate(ids[r], new_tokens=5,
                                        temperature=0.9, top_k=7,
                                        seed=seeds[r]))
        np.testing.assert_array_equal(got[r], solo, err_msg=f"slot {r}")
    with pytest.raises(ValueError, match="seeds"):
        wave.generate(ids, new_tokens=2, temperature=0.9, seeds=[1])


def test_spmd_wave_quantized_prefill_edges(setup):
    """edge_bits packs the [B, S_p, D] prefill hops (QuantPipe riding the
    ppermute): the wave still decodes end-to-end with in-vocab tokens at
    8-bit edges, and 16-bit edges are token-identical to raw on this tiny
    model (quant error far below the argmax margins)."""
    cfg, weights = setup
    partition = [(1, 4), (5, 8), (9, 12)]
    mesh = Mesh(np.asarray(jax.devices()[:3]), ("stage",))
    stage_params = _stage_params(cfg, partition, weights)
    raw = SpmdDecodePipeline(gpt2_mod.FAMILY, cfg, partition, stage_params,
                             mesh, max_len=32)
    ids = np.random.default_rng(37).integers(0, 100, size=(3, 2, 7))
    want = np.asarray(raw.generate(ids, new_tokens=5))
    q16 = SpmdDecodePipeline(gpt2_mod.FAMILY, cfg, partition, stage_params,
                             mesh, max_len=32, edge_bits=16)
    got16 = np.asarray(q16.generate(ids, new_tokens=5))
    np.testing.assert_array_equal(got16, want)
    q8 = SpmdDecodePipeline(gpt2_mod.FAMILY, cfg, partition, stage_params,
                            mesh, max_len=32, edge_bits=8)
    got8 = np.asarray(q8.generate(ids, new_tokens=5))
    assert got8.shape == want.shape
    assert got8[:, :, :7].tolist() == ids.tolist()   # prompts untouched
    assert got8.min() >= 0 and got8.max() < 100      # in-vocab tokens
    with pytest.raises(ValueError, match="edge_bits"):
        SpmdDecodePipeline(gpt2_mod.FAMILY, cfg, partition, stage_params,
                           mesh, max_len=32, edge_bits=5)


def test_spmd_wave_decode_single_token_and_validation(setup):
    cfg, weights = setup
    partition = [(1, 4), (5, 12)]
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("stage",))
    stage_params = _stage_params(cfg, partition, weights)
    wave = SpmdDecodePipeline(gpt2_mod.FAMILY, cfg, partition, stage_params,
                              mesh, max_len=32)
    host = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition,
                                 stage_params, max_len=32)
    ids = np.random.default_rng(29).integers(0, 100, size=(2, 1, 5))
    got = np.asarray(wave.generate(ids, new_tokens=1))
    for r in range(2):
        np.testing.assert_array_equal(
            got[r], np.asarray(host.generate(ids[r], new_tokens=1)))

    with pytest.raises(ValueError, match="slots"):
        wave.generate(ids[:1], new_tokens=2)       # wrong R
    with pytest.raises(ValueError, match="new_tokens"):
        wave.generate(ids, new_tokens=0)
    with pytest.raises(ValueError, match="exceeds"):
        wave.generate(ids, new_tokens=1000)
    moe_cfg = TransformerConfig(model_type="gpt2", **TINY,
                                layer_norm_eps=1e-5, vocab_size=100,
                                max_position_embeddings=64, n_experts=4)
    with pytest.raises(NotImplementedError, match="MoE"):
        SpmdDecodePipeline(gpt2_mod.FAMILY, moe_cfg, partition,
                           stage_params, mesh, max_len=32)
