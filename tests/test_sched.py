"""Native scheduler (sched-pipeline) golden tests.

The reference ships its DP scheduler untested (SURVEY.md §4); here the binary
is cross-checked against a brute-force enumerator over all feasible
contiguous partitions and device assignments, using the Python cost model
(which mirrors the native one — reference sched/__init__.py docstring).
"""
import itertools
import os
import shutil
import subprocess

import pytest
import yaml

from pipeedge_tpu import sched
from pipeedge_tpu.sched import yaml_files, yaml_types
from pipeedge_tpu.sched.scheduler import (_REPO_BUILD_PATHS, build_native,
                                          sched_pipeline)

BIN = _REPO_BUILD_PATHS[0]
pytestmark = pytest.mark.skipif(
    not (os.path.exists(BIN) or shutil.which('sched-pipeline')
         or shutil.which('cmake')),
    reason="sched-pipeline binary not built and no native toolchain")


@pytest.fixture(scope="module", autouse=True)
def _ensure_binary():
    """Build on demand at fixture time (not import time, so unrelated pytest
    collection never triggers a native compile)."""
    global BIN
    if not os.path.exists(BIN):
        built = shutil.which('sched-pipeline') or build_native()
        if built is None:
            pytest.skip("sched-pipeline auto-build failed")
        BIN = built

BATCH = 8
DTYPE = 'torch.float32'


def _write_files(tmp_path, models, device_types, devices):
    mf = tmp_path / "models.yml"
    tf = tmp_path / "device_types.yml"
    df = tmp_path / "devices.yml"
    yaml_files.yaml_save(models, str(mf))
    yaml_files.yaml_save(device_types, str(tf))
    yaml_files.yaml_save(devices, str(df))
    return str(mf), str(tf), str(df)


def _brute_force_bottleneck(model, device_types, devices, batch, dtype):
    """Enumerate every contiguous partition + device-instance assignment."""
    n_layers = model['layers']
    # expand device instances (type name repeated per host)
    instances = []
    for tname, hosts in devices.items():
        if tname in device_types:
            instances.extend([tname] * len(hosts))

    model_full = dict(model)
    # expand repeated blocks like the native loader (sched-pipeline.cpp:24-45)
    po = model['parameters_out']
    model_full['parameters_out'] = [po[i % len(po)] for i in range(n_layers)]

    def feasible(tname, l, r):  # 0-based inclusive
        need = sched.mem_bytes(model_full, l, r, dtype, batch)
        return device_types[tname]['mem_MB'] * 1024 * 1024 > need

    def comp(tname, l, r):
        prof = device_types[tname]['model_profiles']['m'][0]
        return sched.computation_time(prof, l, r)

    def comm(tname_u, tname_v, r):
        data = sched.ubatch_bytes(model_full['parameters_out'][r], batch, dtype)
        bw = min(device_types[tname_u]['bw_Mbps'], device_types[tname_v]['bw_Mbps'])
        return sched.communication_time_bw(bw, data)

    best = float('inf')
    for n_stages in range(1, len(instances) + 1):
        for cuts in itertools.combinations(range(1, n_layers), n_stages - 1):
            bounds = [0] + list(cuts) + [n_layers]
            ranges = [(bounds[i], bounds[i + 1] - 1) for i in range(n_stages)]
            for assign in itertools.permutations(instances, n_stages):
                ok = all(feasible(t, l, r) for t, (l, r) in zip(assign, ranges))
                if not ok:
                    continue
                cost = 0.0
                for k, (t, (l, r)) in enumerate(zip(assign, ranges)):
                    cost = max(cost, comp(t, l, r))
                    if k < n_stages - 1:
                        cost = max(cost, comm(t, assign[k + 1], r))
                best = min(best, cost)
    return best


def _sched_cost(schedule, model, device_types, devices, batch, dtype):
    """Bottleneck cost of a returned schedule."""
    host_type = {}
    for tname, hosts in devices.items():
        for h in hosts:
            host_type[h] = tname
    model_full = dict(model)
    po = model['parameters_out']
    model_full['parameters_out'] = [po[i % len(po)]
                                    for i in range(model['layers'])]
    cost = 0.0
    for k, stage in enumerate(schedule):
        (host, (l1, r1)), = stage.items()
        t = host_type[host]
        prof = device_types[t]['model_profiles']['m'][0]
        cost = max(cost, sched.computation_time(prof, l1 - 1, r1 - 1))
        if k < len(schedule) - 1:
            (host2, _), = schedule[k + 1].items()
            t2 = host_type[host2]
            data = sched.ubatch_bytes(model_full['parameters_out'][r1 - 1],
                                      batch, dtype)
            bw = min(device_types[t]['bw_Mbps'], device_types[t2]['bw_Mbps'])
            cost = max(cost, sched.communication_time_bw(bw, data))
    return cost


def _mk_model(n_layers, params_out, mem_mb, params_in=1000):
    return yaml_types.yaml_model(n_layers, params_in, params_out, mem_mb)


def _mk_type(mem_mb, bw, time_s):
    return yaml_types.yaml_device_type(
        mem_mb, bw, {'m': [yaml_types.yaml_model_profile(DTYPE, BATCH, time_s)]})


def test_optimal_heterogeneous(tmp_path):
    """Fast device should get more layers; result must match brute force."""
    n = 6
    models = {'m': _mk_model(n, [1000] * n, [1.0] * n)}
    device_types = {
        'fast': _mk_type(1024, 1000, [0.1] * n),
        'slow': _mk_type(1024, 1000, [0.3] * n),
    }
    devices = {'fast': ['f0'], 'slow': ['s0']}
    mf, tf, df = _write_files(tmp_path, models, device_types, devices)
    schedule = sched_pipeline('m', 2, 2, BATCH, dtype=DTYPE, models_file=mf,
                              dev_types_file=tf, dev_file=df)
    got = _sched_cost(schedule, models['m'], device_types, devices, BATCH, DTYPE)
    want = _brute_force_bottleneck(models['m'], device_types, devices, BATCH, DTYPE)
    assert got == pytest.approx(want, rel=1e-9)
    # layers are contiguous and cover [1, n]
    covered = []
    for stage in schedule:
        (_, (l, r)), = stage.items()
        covered.extend(range(l, r + 1))
    assert covered == list(range(1, n + 1))


def test_memory_constraint_forces_split(tmp_path):
    """One device can't hold the model -> must split across two."""
    n = 4
    big_mem = 100.0  # MB per layer
    models = {'m': _mk_model(n, [1000] * n, [big_mem] * n)}
    device_types = {'small': _mk_type(250, 1000, [0.1] * n)}
    devices = {'small': ['h0', 'h1', 'h2']}
    mf, tf, df = _write_files(tmp_path, models, device_types, devices)
    schedule = sched_pipeline('m', 2, 2, BATCH, dtype=DTYPE, models_file=mf,
                              dev_types_file=tf, dev_file=df)
    assert len(schedule) >= 2
    hosts = [list(s.keys())[0] for s in schedule]
    assert len(set(hosts)) == len(hosts)  # distinct hosts per stage
    want = _brute_force_bottleneck(models['m'], device_types, devices, BATCH, DTYPE)
    got = _sched_cost(schedule, models['m'], device_types, devices, BATCH, DTYPE)
    assert got == pytest.approx(want, rel=1e-9)


def test_infeasible_returns_empty(tmp_path):
    n = 2
    models = {'m': _mk_model(n, [1000] * n, [10000.0] * n)}
    device_types = {'tiny': _mk_type(1, 1000, [0.1] * n)}
    devices = {'tiny': ['h0']}
    mf, tf, df = _write_files(tmp_path, models, device_types, devices)
    schedule = sched_pipeline('m', 2, 2, BATCH, dtype=DTYPE, models_file=mf,
                              dev_types_file=tf, dev_file=df)
    assert schedule == []


def test_repeated_blocks_and_wrapped_flow_lists(tmp_path):
    """parameters_out shorter than layers repeats (sched-pipeline.cpp:24-45);
    long PyYAML flow lists wrap across lines and must still parse."""
    n = 48
    models = {'m': _mk_model(n, [302592, 151296, 756480, 151296],
                             [25.0 + 0.001 * i for i in range(n)],
                             params_in=150528)}
    device_types = {'dev': _mk_type(4096, 1000,
                                    [0.05 + 0.0001 * i for i in range(n)])}
    devices = {'dev': ['h0', 'h1']}
    mf, tf, df = _write_files(tmp_path, models, device_types, devices)
    # confirm the file really has wrapped flow lists
    with open(tf) as f:
        assert any(line.rstrip().endswith(',') for line in f)
    schedule = sched_pipeline('m', 2, 2, BATCH, dtype=DTYPE, models_file=mf,
                              dev_types_file=tf, dev_file=df)
    assert len(schedule) >= 1
    covered = []
    for stage in schedule:
        (_, (l, r)), = stage.items()
        covered.extend(range(l, r + 1))
    assert covered == list(range(1, n + 1))


def test_type_without_profile_skipped(tmp_path):
    n = 4
    models = {'m': _mk_model(n, [1000] * n, [1.0] * n)}
    device_types = {
        'good': _mk_type(1024, 1000, [0.1] * n),
        'noprof': yaml_types.yaml_device_type(99999, 99999, {}),
    }
    devices = {'good': ['g0'], 'noprof': ['n0']}
    mf, tf, df = _write_files(tmp_path, models, device_types, devices)
    schedule = sched_pipeline('m', 2, 2, BATCH, dtype=DTYPE, models_file=mf,
                              dev_types_file=tf, dev_file=df)
    hosts = [list(s.keys())[0] for s in schedule]
    assert 'n0' not in hosts


def test_bfloat16_dtype_supported(tmp_path):
    """TPU extension: bf16 halves edge bytes and buffer memory."""
    n = 4
    models = {'m': _mk_model(n, [1000] * n, [1.0] * n)}
    device_types = {'dev': yaml_types.yaml_device_type(
        1024, 1000,
        {'m': [yaml_types.yaml_model_profile('bfloat16', BATCH, [0.1] * n)]})}
    devices = {'dev': ['h0']}
    mf, tf, df = _write_files(tmp_path, models, device_types, devices)
    schedule = sched_pipeline('m', 2, 2, BATCH, dtype='bfloat16',
                              models_file=mf, dev_types_file=tf, dev_file=df)
    assert schedule == [{'h0': [1, n]}]


def test_dtype_name_normalization(tmp_path):
    """Profiles with bare jnp names match torch-style requests and vice
    versa (the TPU profiler writes 'float32', reference files
    'torch.float32')."""
    n = 4
    models = {'m': _mk_model(n, [1000] * n, [1.0] * n)}
    for prof_dtype, req_dtype in (('float32', 'torch.float32'),
                                  ('torch.float32', 'float32')):
        device_types = {'dev': yaml_types.yaml_device_type(
            1024, 1000,
            {'m': [yaml_types.yaml_model_profile(prof_dtype, BATCH,
                                                 [0.1] * n)]})}
        devices = {'dev': ['h0']}
        mf, tf, df = _write_files(tmp_path, models, device_types, devices)
        schedule = sched_pipeline('m', 2, 2, BATCH, dtype=req_dtype,
                                  models_file=mf, dev_types_file=tf,
                                  dev_file=df)
        assert schedule == [{'h0': [1, n]}], (prof_dtype, req_dtype)


def test_unknown_model_errors(tmp_path):
    models = {'m': _mk_model(2, [10, 10], [1.0, 1.0])}
    device_types = {'dev': _mk_type(1024, 1000, [0.1, 0.1])}
    devices = {'dev': ['h0']}
    mf, tf, df = _write_files(tmp_path, models, device_types, devices)
    with pytest.raises(subprocess.CalledProcessError):
        sched_pipeline('nope', 2, 2, BATCH, dtype=DTYPE, models_file=mf,
                       dev_types_file=tf, dev_file=df)


def test_cost_model_mem_bytes():
    model = {'layers': 3, 'parameters_in': 100,
             'parameters_out': [10, 20, 30], 'mem_MB': [1.0, 1.0, 1.0]}
    # first stage: no recv buffers; 2 send buffers + processing in+out
    got = sched.mem_bytes(model, 0, 1, DTYPE, 2)
    want = 2 * 1024 * 1024 + (20 * 2 * 4) * 2 + (100 * 2 * 4 + 20 * 2 * 4)
    assert got == want
    # middle stage: recv + send buffers
    got = sched.mem_bytes(model, 2, 2, DTYPE, 2)
    want = 1 * 1024 * 1024 + (20 * 2 * 4) * 2 + (30 * 2 * 4) * 2 \
        + (20 * 2 * 4 + 30 * 2 * 4)
    assert got == want


def test_profiles_upsert_semantics(tmp_path):
    """sched/profiles.py merge rules: dup model refuses without overwrite;
    device-type capacity must not silently change; (dtype, batch) keys a
    device type's model profiles with normalized dtype comparison."""
    import yaml as yaml_mod

    from pipeedge_tpu.sched import profiles

    results_yml = tmp_path / "r.yml"
    rec = {"model_name": "m", "dtype": "torch.float32", "batch_size": 2,
           "layers": 2,
           "profile_data": [
               {"layer": 1, "time": 0.1, "memory": 5.0,
                "shape_in": [[3, 4]], "shape_out": [[3, 4], [3, 4]]},
               {"layer": 2, "time": 0.2, "memory": 6.0,
                "shape_in": [[3, 4], [3, 4]], "shape_out": [[7]]},
           ]}
    with open(results_yml, "w") as f:
        yaml_mod.safe_dump(rec, f)
    res = profiles.ProfilerResults.load(str(results_yml))

    models_yml = str(tmp_path / "models.yml")
    profiles.upsert_model(models_yml, res)
    with pytest.raises(profiles.ProfileError, match="already exists"):
        profiles.upsert_model(models_yml, res)
    profiles.upsert_model(models_yml, res, overwrite=True)
    entry = yaml_mod.safe_load(open(models_yml))["m"]
    assert entry == {"layers": 2, "parameters_in": 12,
                     "parameters_out": [24, 7], "mem_MB": [5.0, 6.0]}

    types_yml = str(tmp_path / "types.yml")
    with pytest.raises(profiles.ProfileError, match="required"):
        profiles.upsert_device_type(types_yml, "dev", res)
    profiles.upsert_device_type(types_yml, "dev", res, mem_MB=100, bw_Mbps=10)
    with pytest.raises(profiles.ProfileError, match="mismatch"):
        profiles.upsert_device_type(types_yml, "dev", res, mem_MB=999,
                                    bw_Mbps=10)
    # same (dtype, batch) under a different spelling is the SAME key
    res2 = profiles.ProfilerResults(
        model_name="m", dtype="float32", batch_size=2, layers=2,
        profile_data=res.profile_data)
    with pytest.raises(profiles.ProfileError, match="already exists"):
        profiles.upsert_device_type(types_yml, "dev", res2)
    profiles.upsert_device_type(types_yml, "dev", res2, overwrite=True)
    # a different batch size appends a second profile
    res3 = profiles.ProfilerResults(
        model_name="m", dtype="float32", batch_size=8, layers=2,
        profile_data=res.profile_data)
    profiles.upsert_device_type(types_yml, "dev", res3)
    out = yaml_mod.safe_load(open(types_yml))["dev"]
    assert [p["batch_size"] for p in out["model_profiles"]["m"]] == [2, 8]

    # inconsistent record counts refuse at load
    bad = dict(rec, layers=3)
    with open(results_yml, "w") as f:
        yaml_mod.safe_dump(bad, f)
    with pytest.raises(profiles.ProfileError, match="layer count"):
        profiles.ProfilerResults.load(str(results_yml))


def test_scaling_projection_from_committed_profiles():
    """The round-5 scaling projection (tools/project_scaling.py) is
    deterministic given the committed chip profiles: the native
    scheduler balances ViT-L b=8 across 8 v5e stages and the projected
    speedup beats the >=4x north star in every comm scenario."""
    import importlib.util
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "project_scaling", os.path.join(repo, "tools",
                                        "project_scaling.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    r = mod.project("google/vit-large-patch16-224", 8, 8)
    assert len(r["partition"]) == 8
    # contiguous cover of the 96 sublayers
    assert r["partition"][0][0] == 1 and r["partition"][-1][1] == 96
    for proj in r["projected"].values():
        assert proj["speedup_vs_single"] >= 4.0, r["projected"]
    fa = r["fused_anchor_projection"]
    assert fa is not None
    for k in ("overlapped_comm", "serialized_ici_1600gbps",
              "serialized_dcn_100gbps"):
        assert fa[k]["speedup_vs_single"] >= 4.0, fa
