"""Multi-host SPMD smoke: the spmd driver composes with process-spanning
meshes via `MultiHostContext` (jax.distributed.initialize) — the mechanism
that joins TPU slices over DCN into one global device mesh (SURVEY.md §5.8,
the role of the reference's init_process_group bring-up, p2p:62)."""
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


import pytest

pytestmark = pytest.mark.fleet  # every test here spawns OS processes


def _cpu_multiprocess_collectives_available() -> bool:
    """Whether this jax build can run cross-process collectives on the
    CPU backend. jax 0.4.x's CPU client has no multiprocess collective
    implementation (no Gloo/MPI wiring in jaxlib <= 0.4.36): any
    computation spanning processes — including the jitted psum inside
    `multihost_utils.broadcast_one_to_all`, which `device_put` onto a
    process-spanning NamedSharding triggers via assert_equal — dies
    with `XlaRuntimeError: INVALID_ARGUMENT: Multiprocess computations
    aren't implemented on the CPU backend.` jax >= 0.5 ships a
    CpuCollectives/Gloo layer; on such a build this test must run (and
    the xfail below turns into a hard failure via strict=True +
    condition)."""
    import jax
    major, minor = (int(v) for v in jax.__version__.split(".")[:2])
    return (major, minor) >= (0, 5)


@pytest.mark.xfail(
    condition=not _cpu_multiprocess_collectives_available(),
    reason="jax 0.4.x CPU backend cannot run multiprocess collectives "
           "(XlaRuntimeError 'Multiprocess computations aren't "
           "implemented on the CPU backend' from the broadcast inside "
           "device_put-to-global-mesh); needs jax >= 0.5's Gloo CPU "
           "collectives or a real TPU fleet",
    strict=True, run=True)
def test_two_process_spmd_pipeline():
    with socket.create_server(("127.0.0.1", 0)) as s:
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    script = os.path.join(REPO, "tests", "multihost_spmd_main.py")
    # a clean environment: the parent test process forced its own platform
    # config, but each child must bring up its own 4-device CPU backend
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["PYTHONPATH"] = REPO
    procs = [subprocess.Popen([sys.executable, script, str(r), "2", coord],
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for r in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r}:\n{out}"
        assert f"MULTIHOST-OK rank={r} local=4 global=8" in out, out

    # the training leg: every rank saw the same descending loss sequence
    # (one global program; the ranks hold shards of one model) ...
    import re
    seqs = [re.search(r"train_losses=\[([^\]]+)\]", out).group(1)
            for out in outs]
    assert seqs[0] == seqs[1], seqs
    losses = [float(v) for v in seqs[0].split(",")]
    assert losses[-1] < losses[0], losses

    # ... and it matches a SINGLE-process oracle on this test's own
    # 8-device CPU backend, step for step: spanning the mesh over two
    # OS processes changed nothing about the training math
    import jax.numpy as jnp
    import numpy as np
    import optax

    from pipeedge_tpu.models import ShardConfig
    from pipeedge_tpu.models import vit as vit_mod
    from pipeedge_tpu.models.layers import TransformerConfig
    from pipeedge_tpu.parallel import spmd
    from pipeedge_tpu.parallel import train as train_mod
    dp, n_stages = 2, 4
    cfg = TransformerConfig(model_type="vit", hidden_size=32,
                            num_hidden_layers=n_stages,
                            num_attention_heads=4, intermediate_size=64,
                            num_labels=5, image_size=16, patch_size=4)
    total = 4 * cfg.num_hidden_layers
    partition = [(4 * i + 1, 4 * (i + 1)) for i in range(n_stages)]
    stage_params = [vit_mod.init_params(
        cfg, ShardConfig(l, r, is_first=l == 1, is_last=r == total),
        seed=0) for l, r in partition]
    mesh = spmd.make_pipeline_mesh(n_stages, dp=dp)
    pipe = spmd.build_spmd_pipeline(vit_mod.FAMILY, cfg, partition,
                                    stage_params, mesh)
    batch = 2 * dp
    t_inputs = jnp.asarray(np.random.default_rng(7).normal(
        size=(n_stages + 1, batch, 3, 16, 16)), jnp.float32)
    t_labels = jnp.asarray(np.random.default_rng(8).integers(
        0, cfg.num_labels, size=(n_stages + 1, batch)), jnp.int32)
    step_fn, opt_state = train_mod.make_train_step(
        pipe, optax.sgd(0.05), t_inputs)
    params = pipe.params
    for want in losses:
        params, opt_state, loss = step_fn(params, opt_state, t_inputs,
                                          t_labels)
        np.testing.assert_allclose(float(loss), want, rtol=1e-4)
