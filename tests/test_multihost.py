"""Multi-host SPMD smoke: the spmd driver composes with process-spanning
meshes via `MultiHostContext` (jax.distributed.initialize) — the mechanism
that joins TPU slices over DCN into one global device mesh (SURVEY.md §5.8,
the role of the reference's init_process_group bring-up, p2p:62)."""
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


import pytest

pytestmark = pytest.mark.fleet  # every test here spawns OS processes

def test_two_process_spmd_pipeline():
    with socket.create_server(("127.0.0.1", 0)) as s:
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    script = os.path.join(REPO, "tests", "multihost_spmd_main.py")
    # a clean environment: the parent test process forced its own platform
    # config, but each child must bring up its own 4-device CPU backend
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["PYTHONPATH"] = REPO
    procs = [subprocess.Popen([sys.executable, script, str(r), "2", coord],
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for r in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r}:\n{out}"
        assert f"MULTIHOST-OK rank={r} local=4 global=8" in out, out
