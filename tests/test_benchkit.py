"""Benchmark observatory (pipeedge_tpu/benchkit + tools/bench_report.py):
recipe registry resolution, trajectory-record schema validation for every
recipe, bench_report diff/regression/noise-band logic on hand-built
records, and the tier-1 loopback serve-recipe acceptance run."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from pipeedge_tpu import benchkit                      # noqa: E402
from pipeedge_tpu.benchkit import schema               # noqa: E402
from tools import bench_report                         # noqa: E402
from tools.loadgen import arrival_offsets              # noqa: E402

ALL_RECIPES = {"exact", "quant_collectives", "spmd", "dcn", "decode",
               "train", "serve", "serve_kv", "int8_compute",
               "autoscale"}


# -- registry resolution -------------------------------------------------

def test_registry_lists_every_recipe():
    names = {r.name for r in benchkit.list_recipes()}
    assert names == ALL_RECIPES


def test_get_recipe_resolution_and_tiers():
    serve = benchkit.get_recipe("serve")
    assert serve.tier == "fast"
    assert serve.setup is not None and serve.teardown is not None
    assert benchkit.get_recipe("exact").tier == "chip"
    with pytest.raises(KeyError, match="unknown recipe 'nope'"):
        benchkit.get_recipe("nope")


def test_recipe_parsers_have_defaults():
    """Every recipe must parse an empty argv (bench.py's bare
    invocation contract)."""
    for recipe in benchkit.list_recipes():
        args = recipe.parser().parse_args([])
        assert vars(args), recipe.name


def test_run_counter_matrix_predeclared():
    """PL501 semantics, checked live: every recipe x status series
    renders before any recipe ever ran in this process."""
    from pipeedge_tpu.telemetry import metrics as prom
    benchkit.list_recipes()            # force recipe registration
    counter = prom.REGISTRY.get_or_create(
        prom.Counter, "pipeedge_bench_runs_total", "")
    values = counter.values()
    for name in ALL_RECIPES:
        for status in benchkit.RUN_STATUSES:
            key = (("recipe", name), ("status", status))
            assert key in values, (name, status)


# -- trajectory schema (one sample per recipe; the set must stay in
#    lock-step with the registry so a new recipe adds its sample) -------

def _sample_blocks(name):
    base = {"throughput": {"value": 100.0, "unit": "items/sec"}}
    if name == "exact":
        return {"throughput": {"value": 945.8, "unit": "images/sec",
                               "samples": [945.2, 946.1],
                               "spread": [945.2, 946.1]},
                "latency_ms": {"p50": 8.1, "p99": 9.0, "n": 127},
                "mfu": {"calibrated": 0.89, "nominal": 0.59,
                        "calibration_version": "cal-v1",
                        "off_recipe": False},
                "legacy": {"metric": "vit_large_images_per_sec_b8",
                           "value": 945.8, "unit": "images/sec"}}
    if name == "quant_collectives":
        return {"throughput": {"value": 980.0, "unit": "images/sec"},
                "quality": {"top1_agreement_vs_exact": 1.0,
                            "max_abs_logit_delta": 0.04},
                "extras": {"bits": 8, "tp": 2}}
    if name == "int8_compute":
        return {"throughput": {"value": 1180.0, "unit": "images/sec"},
                "quality": {"top1_agreement_vs_exact": 0.995,
                            "max_abs_logit_delta": 0.12},
                "extras": {"exact_images_per_sec": 940.0,
                           "fast_images_per_sec": 1010.0,
                           "int8_images_per_sec": 1180.0,
                           "chip_window_target_img_s": 1126.0,
                           "chip_window_met": True,
                           "block_k": 128}}
    if name == "serve":
        return {"throughput": {"value": 56.3, "unit": "req/s"},
                "latency_ms": {"p50": 120.0, "p95": 300.0, "p99": 366.0,
                               "n": 235,
                               "exemplars": [{"le": "0.5",
                                              "trace_id": "q17",
                                              "value_s": 0.37}]},
                "serve": {"goodput_rps": {"interactive": 56.3,
                                          "total": 56.3},
                          "slo_attainment": {"interactive": 0.99},
                          "shed": {"shed": 842, "error": 0},
                          "overload_factor": 3.0,
                          "p99_exemplar_rid": "q17",
                          "trace": "bench_serve_trace.json"}}
    if name == "serve_kv":
        return {"throughput": {"value": 2.4, "unit": "req/s"},
                "latency_ms": {"p50": 40.0, "p95": 80.0, "p99": 95.0,
                               "n": 12},
                "kv": {"pages": 96, "page_size": 8,
                       "disaggregate": "local",
                       "prefix_hit_rate": 0.73,
                       "prefix_lookups": 11,
                       "pages_reused_total": 18,
                       "pages_cached": 10,
                       "pool_occupancy_after": 0.958,
                       "pages_evicted_total": 4,
                       "decode_p99_ms": {"solo": 93.8,
                                         "with_prefill": 190.6},
                       "decode_p99_ratio": 2.03,
                       "shed": {"shared": 0, "solo": 0,
                                "with_prefill": 0},
                       "errors": 0}}
    if name == "autoscale":
        return {"throughput": {"value": 0.4, "unit": "req/s"},
                "latency_ms": {"p50": 900.0, "p95": 1400.0,
                               "p99": 1500.0, "n": 71},
                "serve": {"goodput_rps": {"interactive": 0.4,
                                          "total": 0.4},
                          "slo_attainment": {"interactive": 0.2},
                          "shed": {"shed": 0, "error": 0,
                                   "ok_late": 60},
                          "ramp": "ramp:1:8:0.4", "seed": 7,
                          "floor": 1, "ceiling": 2},
                "extras": {"time_to_scale_up_s": 3.3,
                           "advise_first_up_s": 2.8,
                           "decision_count": {"advise": 8, "auto": 2}}}
    if name == "dcn":
        return {"throughput": {"value": 210.0, "unit": "items/sec"},
                "latency_ms": {"p50": 40.0, "p95": 55.0, "p99": 60.0,
                               "n": 64},
                "extras": {"bubble_pct": 12.3, "world": 2}}
    if name in ("spmd", "decode", "train"):
        return dict(base, extras={"measured": {}})
    raise AssertionError(f"no sample blocks for recipe {name!r} — add "
                         "one (the schema test must cover every recipe)")


@pytest.mark.parametrize("name", sorted(ALL_RECIPES))
def test_every_recipe_record_validates(name):
    record = schema.make_record(name, {"model": "m", "knob": 1},
                                _sample_blocks(name),
                                env={"platform": "cpu"})
    assert schema.validate_record(record) == []
    # legacy keys merged at top level, never clobbering the envelope
    if name == "exact":
        assert record["value"] == 945.8
        assert record["schema"] == schema.SCHEMA


def test_make_record_rejects_unknown_blocks():
    with pytest.raises(ValueError, match="unknown block"):
        schema.make_record("exact", {}, {"bogus": 1})


def test_validator_rejects_bad_records():
    good = schema.make_record("serve", {"a": 1}, _sample_blocks("serve"),
                              env={"platform": "cpu"})
    assert schema.validate_record(good) == []

    bad = dict(good, schema="pipeedge-bench/v0")
    assert any("schema" in p for p in schema.validate_record(bad))

    bad = dict(good, config={"a": 2})        # fingerprint now stale
    assert any("fingerprint" in p for p in schema.validate_record(bad))

    bad = dict(good, throughput={"value": -1, "unit": "req/s"})
    assert any("throughput" in p for p in schema.validate_record(bad))

    bad = dict(good, latency_ms={"p50": 100.0, "p99": 50.0})
    assert any("monotonic" in p for p in schema.validate_record(bad))

    bad = dict(good, latency_ms={"p50": 1.0,
                                 "exemplars": [{"trace_id": "q1"}]})
    assert any("exemplar" in p for p in schema.validate_record(bad))

    bad = dict(good, serve={"goodput_rps": {}})
    problems = schema.validate_record(bad)
    assert any("goodput_rps" in p for p in problems)
    assert any("slo_attainment" in p for p in problems)


def test_artifact_append_replaces_by_scenario(tmp_path):
    path = str(tmp_path / "BENCH_rXX.json")
    rec_a = schema.make_record("serve", {"v": 1}, _sample_blocks("serve"),
                              env={})
    rec_b = schema.make_record("dcn", {"v": 1}, _sample_blocks("dcn"),
                              env={})
    schema.artifact_append(path, rec_a)
    doc = schema.artifact_append(path, rec_b)
    assert [r["scenario"] for r in doc["records"]] == ["serve", "dcn"]
    rec_a2 = schema.make_record("serve", {"v": 2},
                                _sample_blocks("serve"), env={})
    doc = schema.artifact_append(path, rec_a2)
    assert len(doc["records"]) == 2
    by_scenario = schema.records_from_any(doc)
    assert by_scenario["serve"]["config"] == {"v": 2}
    # single-record and list shapes load through the same entry point
    assert set(schema.records_from_any(rec_b)) == {"dcn"}
    assert set(schema.records_from_any([rec_a, rec_b])) == {"serve",
                                                            "dcn"}


# -- bench_report diff / regression / noise bands ------------------------

def _serve_record(goodput=50.0, attainment=0.99, p99=400.0, errors=0,
                  config=None):
    return schema.make_record(
        "serve", config or {"model": "m"},
        {"throughput": {"value": goodput, "unit": "req/s"},
         "latency_ms": {"p50": 100.0, "p95": 250.0, "p99": p99, "n": 100},
         "serve": {"goodput_rps": {"interactive": goodput,
                                   "total": goodput},
                   "slo_attainment": {"interactive": attainment},
                   "shed": {"shed": 10, "error": errors}}},
        env={"platform": "cpu"})


def test_compare_within_noise_is_ok():
    diff = bench_report.compare_records(_serve_record(goodput=50.0),
                                        _serve_record(goodput=48.0))
    assert diff["ok"], diff["regressed"]
    assert diff["metrics"]["throughput"]["verdict"] == "ok"
    assert diff["config_match"]


def test_compare_flags_throughput_regression():
    diff = bench_report.compare_records(_serve_record(goodput=50.0),
                                        _serve_record(goodput=30.0))
    assert not diff["ok"]
    assert "throughput" in diff["regressed"]
    assert "serve.goodput_rps.interactive" in diff["regressed"]


def test_compare_lower_better_latency():
    base, worse = _serve_record(p99=400.0), _serve_record(p99=900.0)
    diff = bench_report.compare_records(base, worse)
    assert "latency_ms.p99" in diff["regressed"]
    # the improvement direction is reported but never gated
    diff = bench_report.compare_records(worse, base)
    assert diff["ok"]
    assert diff["metrics"]["latency_ms.p99"]["verdict"] == "improved"


def test_compare_zero_tolerance_on_errors():
    diff = bench_report.compare_records(_serve_record(errors=0),
                                        _serve_record(errors=1))
    assert "serve.shed.error" in diff["regressed"]


def test_compare_missing_metric_regresses():
    base = _serve_record()
    new = _serve_record()
    del new["latency_ms"]
    diff = bench_report.compare_records(base, new)
    assert "latency_ms.p99" in diff["regressed"]
    assert diff["metrics"]["latency_ms.p99"]["verdict"] == "missing"


def test_noise_override_widens_band():
    base, new = _serve_record(goodput=50.0), _serve_record(goodput=30.0)
    assert not bench_report.compare_records(base, new)["ok"]
    diff = bench_report.compare_records(
        base, new, overrides={"throughput": 0.6,
                              "serve.goodput_rps": 0.6})
    assert diff["ok"], diff["regressed"]


def test_noise_band_uses_record_spread():
    base = _serve_record(goodput=50.0)
    base["throughput"]["spread"] = [30.0, 70.0]   # measured wobble 80%
    diff = bench_report.compare_records(base, _serve_record(goodput=32.0))
    assert diff["metrics"]["throughput"]["verdict"] == "ok"


def test_record_noise_bands_tighten_below_default():
    # a -8% attainment drop sits INSIDE the old one-size-fits-all band;
    # the committed baseline pins its own tighter band and catches it
    base = _serve_record(attainment=0.95)
    new = _serve_record(attainment=0.874)
    base["noise_bands"] = {"serve.slo_attainment": 0.02}
    diff = bench_report.compare_records(base, new)
    assert "serve.slo_attainment.interactive" in diff["regressed"]
    # without the record band the default (5%) band... still catches
    # this one; a 4% drop splits them
    mid = _serve_record(attainment=0.912)
    assert bench_report.compare_records(_serve_record(attainment=0.95),
                                        mid)["ok"]
    diff = bench_report.compare_records(base, mid)
    assert "serve.slo_attainment.interactive" in diff["regressed"]
    # a --noise override still widens past the record band (the CI
    # escape hatch keeps working)
    diff = bench_report.compare_records(
        base, new, overrides={"serve.slo_attainment": 0.15})
    assert diff["ok"], diff["regressed"]


def test_bench_report_main_gate_exit_codes(tmp_path, capsys):
    base_path = str(tmp_path / "base.json")
    ok_path = str(tmp_path / "ok.json")
    bad_path = str(tmp_path / "bad.json")
    json.dump(_serve_record(goodput=50.0), open(base_path, "w"))
    json.dump(_serve_record(goodput=48.0), open(ok_path, "w"))
    json.dump(_serve_record(goodput=20.0), open(bad_path, "w"))
    assert bench_report.main([ok_path, "--baseline", base_path,
                              "--gate"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["ok"]
    assert bench_report.main([bad_path, "--baseline", base_path,
                              "--gate"]) == 1
    report = json.loads(capsys.readouterr().out)
    assert report["regressed"]
    # no common scenarios -> usage error
    other = schema.make_record("dcn", {}, _sample_blocks_dcn(), env={})
    other_path = str(tmp_path / "other.json")
    json.dump(other, open(other_path, "w"))
    assert bench_report.main([other_path, "--baseline", base_path,
                              "--gate"]) == 2


def _sample_blocks_dcn():
    return {"throughput": {"value": 210.0, "unit": "items/sec"}}


def test_bench_report_strict_config(tmp_path):
    base_path = str(tmp_path / "base.json")
    new_path = str(tmp_path / "new.json")
    json.dump(_serve_record(config={"model": "a"}), open(base_path, "w"))
    json.dump(_serve_record(config={"model": "b"}), open(new_path, "w"))
    assert bench_report.main([new_path, "--baseline", base_path]) == 0
    assert bench_report.main([new_path, "--baseline", base_path,
                              "--strict-config"]) == 2


# -- loadgen seeded arrivals + exemplar parse-back -----------------------

def test_arrival_offsets_seeded_and_shaped():
    import random
    uniform = arrival_offsets(10, 5.0, "uniform")
    assert uniform == [i / 5.0 for i in range(10)]
    a = arrival_offsets(50, 5.0, "poisson", random.Random(7))
    b = arrival_offsets(50, 5.0, "poisson", random.Random(7))
    c = arrival_offsets(50, 5.0, "poisson", random.Random(8))
    assert a == b                      # same seed -> same schedule
    assert a != c and a != uniform[:50]
    gaps = [t1 - t0 for t0, t1 in zip(a, a[1:])]
    assert 0.05 < sum(gaps) / len(gaps) < 0.8   # mean gap ~ 1/qps
    with pytest.raises(ValueError, match="unknown arrival"):
        arrival_offsets(1, 1.0, "bursty")


def test_parse_ramp_spec():
    from tools.loadgen import parse_ramp_spec
    assert parse_ramp_spec(None) is None
    assert parse_ramp_spec("uniform") is None
    assert parse_ramp_spec("poisson") is None
    assert parse_ramp_spec("ramp:2:8") == {"lo": 2.0, "hi": 8.0,
                                           "hold": 1.0 / 3.0}
    assert parse_ramp_spec("ramp:1:4:0.5") == {"lo": 1.0, "hi": 4.0,
                                               "hold": 0.5}
    for bad in ("ramp:", "ramp:2", "ramp:2:8:0.3:9", "ramp:x:y",
                "ramp:0:8", "ramp:8:2", "ramp:2:8:1.0", "ramp:2:8:-0.1"):
        with pytest.raises(ValueError, match="ramp"):
            parse_ramp_spec(bad)


def test_ramp_offsets_shape_and_determinism():
    from tools.loadgen import ramp_rate
    ramp = {"lo": 2.0, "hi": 10.0, "hold": 1.0 / 3.0}
    a = arrival_offsets(0, None, "ramp:2:10", duration_s=12.0)
    b = arrival_offsets(0, None, "ramp:2:10", duration_s=12.0)
    assert a == b                      # deterministic grid, no RNG
    assert a[0] == 0.0 and a[-1] < 12.0
    # arrival count ~ integral of the rate: (lo+hi)/2 on each edge,
    # hi on the plateau -> 4*(2+10)/2 + 4*10 = 88 arrivals over 12 s
    assert 80 <= len(a) <= 96
    # instantaneous spacing tracks the piecewise-linear rate: gaps on
    # the plateau (~1/hi) are much tighter than at the ramp floor
    first_gap = a[1] - a[0]
    mid = min(range(len(a)), key=lambda i: abs(a[i] - 6.0))
    assert a[mid + 1] - a[mid] < first_gap / 2
    # rate endpoints and plateau value
    assert ramp_rate(0.0, 12.0, ramp) == 2.0
    assert ramp_rate(6.0, 12.0, ramp) == 10.0
    assert ramp_rate(12.0, 12.0, ramp) == 2.0
    with pytest.raises(ValueError, match="duration"):
        arrival_offsets(0, None, "ramp:2:10")


def test_parse_burst_spec():
    from tools.loadgen import parse_burst_spec
    assert parse_burst_spec(None) is None
    assert parse_burst_spec("0.5:3:48") == {
        "at": 0.5, "n": 3, "len": 48, "window_s": 2.0}
    assert parse_burst_spec("0.25:2:32:4.5") == {
        "at": 0.25, "n": 2, "len": 32, "window_s": 4.5}
    # dicts pass through (run_load callers hand the parsed form in)
    spec = {"at": 0.5, "n": 1, "len": 8, "window_s": 2.0}
    assert parse_burst_spec(spec) is spec
    for bad in ("1.5:3:48", "0.5:0:48", "0.5:3:0", "0.5:3", "x:y:z",
                "0.5:3:48:0"):
        with pytest.raises(ValueError):
            parse_burst_spec(bad)


def test_parse_exemplars_roundtrip():
    from pipeedge_tpu.telemetry import metrics as prom
    reg = prom.Registry()
    h = reg.histogram("bench_test_latency_seconds", "x",
                      buckets=(0.1, 1.0))
    h.observe(0.05, exemplar="q1")
    h.observe(0.5, exemplar="q2")
    h.observe(5.0, exemplar="q3")
    rows = prom.parse_exemplars(reg.render(),
                                "bench_test_latency_seconds")
    assert {(r["le"], r["trace_id"]) for r in rows} == {
        ("0.1", "q1"), ("1", "q2"), ("+Inf", "q3")}
    assert prom.parse_exemplars(reg.render(), "other_family") == []


def test_parse_runtime_stdout():
    from pipeedge_tpu.benchkit import fleet
    text = ("round=0 latency_sec=2.5 x\n"
            "steady_state_throughput_items_sec=104.2 other=1\n"
            "latency_sec=1.25 throughput_items_sec=99.7\n")
    out = fleet.parse_runtime_stdout(text)
    assert out == {"steady_items_per_sec": 104.2,
                   "items_per_sec": 99.7, "round_latency_s": 1.25}
    assert fleet.parse_runtime_stdout("no numbers here") == {}


# -- the tier-1 loopback serve-recipe acceptance run ---------------------

@pytest.mark.fleet      # spawns the serve.py subprocess via the recipe
def test_serve_recipe_acceptance(tmp_path):
    """ISSUE 13 acceptance: `python bench.py --recipe serve` (loopback,
    1-slot, 3x overload) emits one JSON line with per-class goodput_rps
    and slo_attainment, and at least one p99 exemplar trace id resolves
    through `tools/trace_report.py --request`."""
    trace = str(tmp_path / "serve_trace.json")
    artifact = str(tmp_path / "BENCH_smoke.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--recipe", "serve", "--append-record", artifact,
         "--duration", "4", "--calibrate-s", "1.5",
         "--overload-factor", "3", "--trace-out", trace],
        capture_output=True, text=True, timeout=400, cwd=REPO, env=env)
    assert proc.returncode == 0, proc.stderr[-3000:]
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    assert schema.validate_record(record) == []
    assert record["scenario"] == "serve"
    assert record["config"]["max_active"] == 1          # 1-slot default
    assert record["config"]["overload_factor"] == 3.0
    serve = record["serve"]
    assert serve["goodput_rps"]["interactive"] > 0
    assert 0.0 <= serve["slo_attainment"]["interactive"] <= 1.0
    assert serve["shed"]["error"] == 0, record.get("notes")
    assert serve["seed"] == 0 and serve["arrival"] == "uniform"
    # overload must actually shed (3x a 1-slot server)
    assert serve["shed"]["shed"] > 0
    # the artifact re-armed with this scenario
    doc = json.load(open(artifact))
    assert [r["scenario"] for r in doc["records"]] == ["serve"]

    rid = serve["p99_exemplar_rid"]
    assert rid, "no p99 exemplar trace id in the record"
    assert record["latency_ms"]["exemplars"], "no exemplar rows"
    timeline = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         trace, "--request", rid],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env)
    assert timeline.returncode == 0, timeline.stderr[-2000:]
    t = json.loads(timeline.stdout)
    assert t["found"] and t["dominant_stall"] is not None


# -- the int8-compute recipe acceptance run ------------------------------

@pytest.mark.fleet      # subprocess bench.py run (the CI smoke shape)
def test_int8_compute_recipe_acceptance(tmp_path):
    """ISSUE 19 acceptance: `python bench.py --recipe int8_compute` on
    the CPU fixture emits one valid pipeedge-bench/v1 record carrying
    exact/fast/int8 interleaved img/s, >= 0.99 int8-vs-exact top-1
    agreement, and the chip-window target (gated null off-TPU)."""
    artifact = str(tmp_path / "BENCH_int8.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("PIPEEDGE_FAST_NUMERICS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--recipe", "int8_compute", "--model", "pipeedge/test-tiny-vit",
         "--ubatches", "4", "--reps", "2", "--append-record", artifact],
        capture_output=True, text=True, timeout=500, cwd=REPO, env=env)
    assert proc.returncode == 0, proc.stderr[-3000:]
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    assert schema.validate_record(record) == []
    assert record["scenario"] == "int8_compute"
    # the headline quality gate: int8 compute must agree with exact
    assert record["quality"]["top1_agreement_vs_exact"] >= 0.99
    extras = record["extras"]
    for key in ("exact_images_per_sec", "fast_images_per_sec",
                "int8_images_per_sec"):
        assert extras[key] > 0, key
    assert extras["chip_window_target_img_s"] == 1126.0
    assert extras["chip_window_met"] is None        # CPU: no chip claim
    assert extras["clamp"] == "inline-1-batch"
    assert record["throughput"]["value"] == extras["int8_images_per_sec"]
    doc = json.load(open(artifact))
    assert [r["scenario"] for r in doc["records"]] == ["int8_compute"]
