"""Tensor-parallel block tests: TP output must match the unsharded block."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from pipeedge_tpu.models import ShardConfig
from pipeedge_tpu.models import vit as vit_mod
from pipeedge_tpu.models.layers import TransformerConfig
from pipeedge_tpu.parallel.tensor import make_tp_block_fn, shard_vit_block_params

CFG = TransformerConfig(model_type="vit", hidden_size=64, num_hidden_layers=1,
                        num_attention_heads=8, intermediate_size=128,
                        num_labels=0, image_size=16, patch_size=4)


pytestmark = pytest.mark.slow  # tp blocks compile shard_map programs per degree

def _block_params():
    params = vit_mod.init_params(CFG, ShardConfig(1, 4), seed=3)
    return jax.tree_util.tree_map(lambda x: x[0], params["blocks"])


def _expected(bp, x):
    data = jnp.asarray(x)
    for sub in range(4):
        data = vit_mod.sublayer(bp, sub, data, CFG)
    return np.asarray(data)


@pytest.mark.parametrize("n_tp", [2, 4, 8])
def test_tp_block_matches_unsharded(n_tp):
    bp = _block_params()
    x = np.random.default_rng(0).normal(size=(2, 17, 64)).astype(np.float32)
    expected = _expected(bp, x)
    mesh = Mesh(np.asarray(jax.devices()[:n_tp]), ("tp",))
    sharded = shard_vit_block_params(bp, mesh)
    fn = make_tp_block_fn(CFG, mesh)
    got = np.asarray(fn(sharded, jnp.asarray(x)))
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-5)


def test_tp_params_are_actually_sharded():
    bp = _block_params()
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("tp",))
    sharded = shard_vit_block_params(bp, mesh)
    # column-parallel kernel: local shard holds 1/4 of the out dim
    shard_shapes = [s.data.shape for s in sharded["q"]["w"].addressable_shards]
    assert all(shape == (64, 16) for shape in shard_shapes)
    shard_shapes = [s.data.shape for s in sharded["mlp_down"]["w"].addressable_shards]
    assert all(shape == (32, 64) for shape in shard_shapes)


BERT_CFG = TransformerConfig(model_type="bert", hidden_size=64,
                             num_hidden_layers=1, num_attention_heads=8,
                             intermediate_size=128, num_labels=0,
                             vocab_size=50, max_position_embeddings=64,
                             type_vocab_size=2)


@pytest.mark.parametrize("n_tp", [2, 4])
def test_tp_bert_block_matches_unsharded(n_tp):
    """BERT's post-LN block under Megatron TP: same column/row layout,
    LayerNorm after each residual (bert.py sublayers 0-3)."""
    from pipeedge_tpu.models import bert as bert_mod
    from pipeedge_tpu.parallel.tensor import shard_block_params

    params = bert_mod.init_params(BERT_CFG, ShardConfig(1, 4), seed=5)
    bp = jax.tree_util.tree_map(lambda x: x[0], params["blocks"])
    x = np.random.default_rng(1).normal(size=(2, 9, 64)).astype(np.float32)
    data = jnp.asarray(x)
    for sub in range(4):
        data = bert_mod.sublayer(bp, sub, data, BERT_CFG)
    expected = np.asarray(data)
    mesh = Mesh(np.asarray(jax.devices()[:n_tp]), ("tp",))
    sharded = shard_block_params(BERT_CFG, bp, mesh)
    fn = make_tp_block_fn(BERT_CFG, mesh)
    got = np.asarray(fn(sharded, jnp.asarray(x)))
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-5)
