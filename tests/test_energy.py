"""RAPL energy source: fake powercap sysfs tree, wraparound, fallback."""
import os

import pytest

from pipeedge_tpu.monitoring import MonitorContext
from pipeedge_tpu.monitoring.energy import (RaplEnergySource,
                                            default_energy_source)


def _mk_domain(root, n, energy_uj, max_range=1_000_000):
    d = root / f"intel-rapl:{n}"
    d.mkdir(parents=True)
    (d / "energy_uj").write_text(str(energy_uj))
    (d / "max_energy_range_uj").write_text(str(max_range))
    return d


def test_reads_and_sums_domains(tmp_path):
    _mk_domain(tmp_path, 0, 100)
    _mk_domain(tmp_path, 1, 50)
    sub = tmp_path / "intel-rapl:0:0"  # subdomain must be ignored
    sub.mkdir()
    (sub / "energy_uj").write_text("999999")
    src = RaplEnergySource(str(tmp_path))
    src.init()
    assert src.get_uj() == 150
    assert src.get_source() == "RAPL(2 domains)"


def test_wraparound(tmp_path):
    d = _mk_domain(tmp_path, 0, 900, max_range=1000)
    src = RaplEnergySource(str(tmp_path))
    src.init()
    assert src.get_uj() == 900
    (d / "energy_uj").write_text("100")  # counter wrapped past 1000
    assert src.get_uj() == 1100  # 100 + one full range
    (d / "energy_uj").write_text("400")
    assert src.get_uj() == 1400


def test_default_source_fallback(tmp_path):
    assert default_energy_source(str(tmp_path / "missing")) is None
    _mk_domain(tmp_path, 0, 7)
    src = default_energy_source(str(tmp_path))
    assert src is not None
    src.init()
    assert src.get_uj() == 7


def test_monitor_context_uses_energy(tmp_path):
    d = _mk_domain(tmp_path, 0, 1000)
    src = RaplEnergySource(str(tmp_path))
    with MonitorContext(key="k", window_size=4, energy_source=src) as ctx:
        ctx.iteration_start(key="k")
        (d / "energy_uj").write_text("250000")  # +0.249 J
        ctx.iteration(key="k", work=1)
        assert ctx.get_instant_energy_j(key="k") == pytest.approx(0.249)
        assert ctx.energy_source == "RAPL(1 domains)"
