"""Quantization round-trip tests.

Mirrors the reference's test coverage (test/quant/test_quant.py:8-29:
quantize → pack → unpack → dequantize restores values within tolerance for
bitwidths {1,2,3,4,5,6,8,16,32} including shape [8,197,768]) and extends it
with per-outer-item encoding, bit=0 passthrough, wire-size, and clamp tests.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipeedge_tpu.ops import clamp, quant

BITS = [1, 2, 3, 4, 5, 6, 8, 16, 32]
SHAPES = [(8, 24, 48), (3, 5), (128,), (8, 197, 768)]


def _max_roundtrip_err(x, bit):
    """Worst-case error: half a quantization step, floored by f32 precision."""
    rng = float(np.max(x) - np.min(x))
    levels = (1 << bit) - 1
    return rng / levels / 2 + rng * 2.0 ** -20 + 1e-6


@pytest.mark.parametrize("bit", BITS)
@pytest.mark.parametrize("shape", SHAPES[:3])
def test_roundtrip_whole_tensor(bit, shape):
    rng = np.random.default_rng(42)
    x = rng.uniform(-3, 7, size=shape).astype(np.float32)
    enc = quant.tensor_encode(jnp.asarray(x), bit)
    dec = np.asarray(quant.tensor_decode(enc))
    assert dec.shape == x.shape
    assert np.max(np.abs(dec - x)) <= _max_roundtrip_err(x, bit)


@pytest.mark.parametrize("bit", [2, 4, 8, 16])
def test_roundtrip_outerdim_large(bit):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 197, 768)).astype(np.float32)
    enc = quant.tensor_encode_outerdim(jnp.asarray(x), bit)
    dec = np.asarray(quant.tensor_decode_outerdim(enc))
    # per-item scale/shift → per-item error bound
    for i in range(x.shape[0]):
        assert np.max(np.abs(dec[i] - x[i])) <= _max_roundtrip_err(x[i], bit)


def test_outerdim_independent_scales():
    # items with wildly different ranges must not pollute each other
    x = np.stack([np.linspace(0, 1, 64, dtype=np.float32),
                  np.linspace(-1000, 1000, 64, dtype=np.float32)])
    enc = quant.tensor_encode_outerdim(jnp.asarray(x), 8)
    assert enc.scale.shape == (2,)
    dec = np.asarray(quant.tensor_decode_outerdim(enc))
    assert np.max(np.abs(dec[0] - x[0])) < 1 / 255 + 1e-6
    assert np.max(np.abs(dec[1] - x[1])) < 2000 / 255 + 1e-3


def test_bit0_passthrough():
    x = jnp.arange(12, dtype=jnp.float32).reshape(3, 4)
    enc = quant.tensor_encode(x, 0)
    assert enc.bit == 0
    np.testing.assert_array_equal(np.asarray(quant.tensor_decode(enc)), np.asarray(x))
    enc2 = quant.tensor_encode_outerdim(x, 0)
    np.testing.assert_array_equal(
        np.asarray(quant.tensor_decode_outerdim(enc2)), np.asarray(x))


@pytest.mark.parametrize("bit", [2, 4, 8])
def test_wire_size_matches_compression_factor(bit):
    n = 1024
    x = jnp.ones((n,), jnp.float32)
    enc = quant.tensor_encode(x, bit)
    per_word = 32 // bit
    assert enc.data.shape == (n // per_word,)
    assert enc.nbytes_wire == n * 4 / quant.compression_factor(bit)


def test_modified_mode_roundtrip():
    rng = np.random.default_rng(7)
    x = rng.uniform(0, 1, size=(64,)).astype(np.float32)
    enc = quant.tensor_encode(jnp.asarray(x), 8, mode="modified")
    dec = np.asarray(quant.tensor_decode(enc))
    # floor-based quantization: one full step worst case
    assert np.max(np.abs(dec - x)) <= 1.5 / 255


def test_constant_tensor_no_nan():
    x = jnp.full((16,), 3.25)
    dec = np.asarray(quant.tensor_decode(quant.tensor_encode(x, 4)))
    assert np.all(np.isfinite(dec))
    np.testing.assert_allclose(dec, 3.25, atol=1e-6)


def test_encode_is_jittable_inside_larger_fn():
    @jax.jit
    def edge(x):
        enc = quant.tensor_encode_outerdim(x, 8)
        return quant.tensor_decode_outerdim(enc)

    x = jnp.asarray(np.random.default_rng(1).normal(size=(4, 32)).astype(np.float32))
    out = edge(x)
    assert np.max(np.abs(np.asarray(out) - np.asarray(x))) < 0.05


# --- clamp ---

def test_clamp_laplace_bounds_and_factor():
    rng = np.random.default_rng(3)
    x = rng.laplace(size=(4096,)).astype(np.float32)
    out = np.asarray(clamp.clamp_banner2019_laplace(jnp.asarray(x), 4))
    expected_alpha = clamp.clamp_factor_laplace(4) * np.sqrt(0.5 * np.var(x))
    assert np.max(np.abs(out)) <= expected_alpha + 1e-5
    inside = np.abs(x) < expected_alpha
    np.testing.assert_allclose(out[inside], x[inside], rtol=1e-6)


def test_clamp_factors_increase_with_bit():
    lap = [clamp.clamp_factor_laplace(b) for b in (2, 4, 8, 16)]
    assert all(a < b for a, b in zip(lap, lap[1:]))
    # gelu factor at bit b equals laplace factor at bit b+1 (clamp_op.py:6-8, 22-24)
    assert clamp.clamp_factor_gelu(4) == pytest.approx(clamp.clamp_factor_laplace(5))


def test_clamp_gelu_halfbell():
    rng = np.random.default_rng(5)
    x = np.abs(rng.normal(size=(4096,))).astype(np.float32)  # post-GeLU-like
    out = np.asarray(clamp.clamp_banner2019_gelu(jnp.asarray(x), 4))
    second = 2 * np.mean(x ** 2)
    expected_alpha = clamp.clamp_factor_gelu(4) * np.sqrt(0.5 * second)
    assert np.max(out) <= expected_alpha + 1e-5
