"""Pipeline-parallel training over the SPMD pipeline (parallel/train.py).

The claim under test: jax.grad through the ONE-program pipelined forward
(ppermute edges, fill/drain masking, stage-sharded blocks) produces the
same gradients as a plain single-device forward of the same model — and
an optimizer loop on the pipeline actually learns.
"""
import json

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from pipeedge_tpu.models import ShardConfig  # noqa: E402
from pipeedge_tpu.models import vit as vit_mod  # noqa: E402
from pipeedge_tpu.models.layers import TransformerConfig  # noqa: E402
from pipeedge_tpu.models.shard import make_shard_fn  # noqa: E402
from pipeedge_tpu.parallel import spmd, train  # noqa: E402

pytestmark = pytest.mark.slow   # compiles forward+backward shard_map programs

TINY4 = dict(hidden_size=32, num_hidden_layers=4, num_attention_heads=4,
             intermediate_size=64)
PARTITION = [(1, 8), (9, 16)]


def _stage_params(cfg, weights):
    total = 4 * cfg.num_hidden_layers
    return [vit_mod.load_params(
        cfg, ShardConfig(l, r, is_first=l == 1, is_last=r == total), weights)
        for l, r in PARTITION]


@pytest.fixture(scope="module")
def setup():
    from jax.sharding import Mesh
    from transformers import ViTConfig, ViTForImageClassification
    hf_cfg = ViTConfig(**TINY4, image_size=16, patch_size=4, num_labels=5)
    torch.manual_seed(0)
    model = ViTForImageClassification(hf_cfg).eval()
    cfg = TransformerConfig(model_type="vit", **TINY4, num_labels=5,
                            image_size=16, patch_size=4)
    weights = vit_mod.hf_to_npz_weights(model.state_dict(), cfg)
    stage_params = _stage_params(cfg, weights)
    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("stage",))
    pipe = spmd.build_spmd_pipeline(vit_mod.FAMILY, cfg, PARTITION,
                                    stage_params, mesh)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(3, 2, 3, 16, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 5, size=(3, 2)), jnp.int32)
    return cfg, weights, pipe, x, y


def _single_device_loss(cfg, weights):
    """The same model as ONE unsharded forward (oracle for grads)."""
    total = 4 * cfg.num_hidden_layers
    sc = ShardConfig(1, total, is_first=True, is_last=True)
    params = vit_mod.load_params(cfg, sc, weights)
    fn = make_shard_fn(vit_mod.FAMILY, cfg, sc)

    def loss(params, x, y):
        logits = jnp.stack([fn(params, u) for u in x])
        return train.softmax_xent(logits, y)

    return params, loss


def test_pipeline_grads_match_single_device(setup):
    """d loss/d params through the 2-stage pipelined program equals the
    single-device gradient of the same model (the ppermute/psum/scan
    transposes are exact)."""
    cfg, weights, pipe, x, y = setup
    fwd = pipe.compiled_for(x)
    n_blocks = pipe.params["n_blocks"]

    def pipe_loss(trainable):
        return train.softmax_xent(
            fwd({**trainable, "n_blocks": n_blocks}, x), y)

    trainable = {k: v for k, v in pipe.params.items() if k != "n_blocks"}
    pipe_val, pipe_grads = jax.value_and_grad(pipe_loss)(trainable)

    ref_params, ref_loss = _single_device_loss(cfg, weights)
    ref_val, ref_grads = jax.value_and_grad(ref_loss)(ref_params, x, y)
    np.testing.assert_allclose(float(pipe_val), float(ref_val),
                               rtol=1e-5, atol=1e-6)

    # EVERY leaf. Stage-stacked block grads [n_stages, max_b, ...] map to
    # the oracle's [total_blocks, ...] stack: stage s covers [2s, 2s+2)
    def path_str(kp):
        return jax.tree_util.keystr(kp)

    checked = [0]

    def check_block_leaf(kp, g, w):
        g, w = np.asarray(g), np.asarray(w)
        for s in range(2):
            np.testing.assert_allclose(
                g[s], w[2 * s:2 * s + 2], rtol=2e-4, atol=1e-5,
                err_msg=f"blocks{path_str(kp)} stage {s}")
        checked[0] += 1

    jax.tree_util.tree_map_with_path(check_block_leaf,
                                     pipe_grads["blocks"],
                                     ref_grads["blocks"])

    def check_leaf(kp, g, w, name):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=1e-5,
                                   err_msg=f"{name}{path_str(kp)}")
        checked[0] += 1

    jax.tree_util.tree_map_with_path(
        lambda kp, g, w: check_leaf(kp, g, w, "embed"),
        pipe_grads["embed"], ref_grads["embeddings"])
    jax.tree_util.tree_map_with_path(
        lambda kp, g, w: check_leaf(kp, g, w, "final"),
        pipe_grads["final"], ref_grads["final"])
    assert checked[0] > 20, f"only {checked[0]} grad leaves compared"

    # remat (per-block jax.checkpoint) recomputes instead of saving —
    # gradients must be identical
    rpipe = spmd.build_spmd_pipeline(vit_mod.FAMILY, cfg, PARTITION,
                                     _stage_params(cfg, weights),
                                     pipe.mesh, remat=True)
    rfwd = rpipe.compiled_for(x)

    def rloss(trainable):
        return train.softmax_xent(
            rfwd({**trainable, "n_blocks": n_blocks}, x), y)

    rgrads = jax.grad(rloss)(trainable)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7),
        rgrads, pipe_grads)


def test_train_step_learns_and_shards(setup):
    """A few SGD steps through the pipeline reduce the loss; quantized
    edges are refused."""
    import optax
    cfg, weights, pipe, x, y = setup
    step, opt_state = train.make_train_step(pipe, optax.sgd(0.05), x)
    params = pipe.params
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses
    assert np.isfinite(losses).all()

    from jax.sharding import Mesh
    qmesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("stage",))
    qpipe = spmd.build_spmd_pipeline(vit_mod.FAMILY, cfg, PARTITION,
                                     _stage_params(cfg, weights),
                                     qmesh, quant_bit=8)
    with pytest.raises(ValueError, match="not differentiable"):
        train.make_train_step(qpipe, optax.sgd(0.05), x)


def test_lm_training_gpt2_pipeline():
    """Causal-LM training through the pipeline: logits [M, B, S, V],
    shifted-id labels [M, B, S]; loss decreases under SGD."""
    import optax
    from jax.sharding import Mesh

    from pipeedge_tpu.models import gpt2 as gpt2_mod
    cfg = TransformerConfig(model_type="gpt2", hidden_size=32,
                            num_hidden_layers=2, num_attention_heads=4,
                            intermediate_size=64, layer_norm_eps=1e-5,
                            vocab_size=50, max_position_embeddings=32)
    partition = [(1, 4), (5, 8)]
    sp = [gpt2_mod.init_params(
        cfg, ShardConfig(l, r, is_first=l == 1, is_last=r == 8), seed=0)
        for l, r in partition]
    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("stage",))
    pipe = spmd.build_spmd_pipeline(gpt2_mod.FAMILY, cfg, partition, sp,
                                    mesh)
    rng = np.random.default_rng(2)
    ids = jnp.asarray(rng.integers(0, 50, size=(3, 2, 9)), jnp.int32)
    inputs, labels = ids[..., :-1], ids[..., 1:]   # next-token targets
    step, opt_state = train.make_train_step(pipe, optax.sgd(0.1), inputs)
    params, losses = pipe.params, []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, inputs, labels)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.95, losses


def test_train_state_checkpoint_resume(setup, tmp_path):
    """save_train_state / restore_train_state round-trip the full
    training state (params + optimizer state + step) and training
    resumes identically: one more step from the restored state produces
    the same loss as continuing the original run."""
    import optax
    cfg, weights, pipe, x, y = setup
    opt = optax.adam(1e-3)   # stateful optimizer (momenta round-trip)
    step, opt_state = train.make_train_step(pipe, opt, x)
    params = pipe.params
    for i in range(2):
        params, opt_state, _ = step(params, opt_state, x, y)
    train.save_train_state(str(tmp_path / "ckpt"), params, opt_state, 2)
    params_cont, opt_cont, loss_cont = step(params, opt_state, x, y)

    # fresh structures (as a new process would build them)
    _, like_opt = train.make_train_step(pipe, opt, x)
    r_params, r_opt, r_step = train.restore_train_state(
        str(tmp_path / "ckpt"), pipe.params, like_opt)
    assert r_step == 2
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        r_params, params)
    # bit-continuous: the same compiled step on the same state on the
    # same backend — resumed training is exactly the uninterrupted run
    r_params2, _, r_loss = step(r_params, r_opt, x, y)
    assert float(r_loss) == float(loss_cont)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        r_params2, params_cont)


def test_dp_stage_training_grads_match(setup):
    """Data parallelism composes with pipeline training: a ('dp','stage')
    2x2 mesh produces the same gradients as the single-device oracle
    (the dp batch shard's gradient mean rides the program's transposes)."""
    from jax.sharding import Mesh
    cfg, weights, pipe, x, y = setup
    stage_params = _stage_params(cfg, weights)
    mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                ("dp", "stage"))
    dpipe = spmd.build_spmd_pipeline(vit_mod.FAMILY, cfg, PARTITION,
                                     stage_params, mesh)
    fwd = dpipe.compiled_for(x)
    n_blocks = dpipe.params["n_blocks"]

    def dloss(trainable):
        return train.softmax_xent(
            fwd({**trainable, "n_blocks": n_blocks}, x), y)

    trainable = {k: v for k, v in dpipe.params.items() if k != "n_blocks"}
    dval, dgrads = jax.value_and_grad(dloss)(trainable)

    ref_params, ref_loss = _single_device_loss(cfg, weights)
    rval, rgrads = jax.value_and_grad(ref_loss)(ref_params, x, y)
    np.testing.assert_allclose(float(dval), float(rval),
                               rtol=1e-5, atol=1e-6)
    got = np.asarray(dgrads["blocks"]["mlp_up"]["w"])
    want = np.asarray(rgrads["blocks"]["mlp_up"]["w"])
    for s in range(2):
        np.testing.assert_allclose(got[s], want[2 * s:2 * s + 2],
                                   rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(dgrads["final"]["head"]["w"]),
        np.asarray(rgrads["final"]["head"]["w"]), rtol=2e-4, atol=1e-5)


def test_lm_training_llama_pipeline():
    """LLaMA-family training through the pipeline (RoPE/RMSNorm/SwiGLU/
    GQA sublayers are differentiable as-is): loss decreases under SGD."""
    import optax
    from jax.sharding import Mesh

    from pipeedge_tpu.models import llama as llama_mod
    cfg = TransformerConfig(model_type="llama", hidden_size=32,
                            num_hidden_layers=2, num_attention_heads=4,
                            num_kv_heads=2, intermediate_size=64,
                            layer_norm_eps=1e-5, vocab_size=50,
                            max_position_embeddings=32)
    partition = [(1, 4), (5, 8)]
    sp = [llama_mod.init_params(
        cfg, ShardConfig(l, r, is_first=l == 1, is_last=r == 8), seed=0)
        for l, r in partition]
    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("stage",))
    pipe = spmd.build_spmd_pipeline(llama_mod.FAMILY, cfg, partition, sp,
                                    mesh)
    rng = np.random.default_rng(4)
    ids = jnp.asarray(rng.integers(0, 50, size=(3, 2, 9)), jnp.int32)
    inputs, labels = ids[..., :-1], ids[..., 1:]
    step, opt_state = train.make_train_step(pipe, optax.sgd(0.1), inputs)
    params, losses = pipe.params, []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, inputs, labels)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.95, losses


@pytest.mark.fleet
def test_train_cli_multistage_dp_resume(tmp_path):
    """tools/train.py end-to-end in a subprocess: 2 stages x dp 2 mesh,
    adam, checkpoint at the end, then a second invocation resumes from
    the saved step and continues."""
    import os
    import re
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=repo)
    ck = str(tmp_path / "ck")
    cmd = [sys.executable, os.path.join(repo, "tools", "train.py"),
           "-m", "pipeedge/test-tiny-gpt2", "-pt", "1,4,5,8", "--dp", "2",
           "-b", "2", "-u", "2", "--seq-len", "8", "--optimizer", "adam",
           "--ckpt-dir", ck, "--log-every", "1"]
    first = subprocess.run(cmd + ["--steps", "3"], capture_output=True,
                           text=True, env=env, timeout=600)
    assert first.returncode == 0, first.stdout + first.stderr
    losses = [float(m) for m in
              re.findall(r"loss=([0-9.]+)", first.stdout)]
    assert len(losses) == 3 and losses[-1] < losses[0]

    second = subprocess.run(cmd + ["--steps", "5"], capture_output=True,
                            text=True, env=env, timeout=600)
    assert second.returncode == 0, second.stdout + second.stderr
    assert "resumed from" in second.stdout and "step 3" in second.stdout
    more = [float(m) for m in re.findall(r"loss=([0-9.]+)", second.stdout)]
    assert len(more) == 2 and more[-1] < losses[0]
    summary = json.loads(second.stdout.strip().splitlines()[-1])
    assert summary["steps"] == 2 and summary["mesh"] == {"dp": 2,
                                                         "stage": 2}


def test_bert_and_moe_training_learn():
    """The remaining families train through the pipeline too: BERT
    sequence classification (tanh pooler + head) and switch-MoE blocks
    (the top-1 gate probability scales the expert output, so routing
    passes gradients); loss decreases under SGD for both."""
    import optax
    from jax.sharding import Mesh

    from pipeedge_tpu.models import bert as bert_mod
    from pipeedge_tpu.models import gpt2 as gpt2_mod
    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("stage",))
    rng = np.random.default_rng(6)

    bert_cfg = TransformerConfig(model_type="bert", hidden_size=32,
                                 num_hidden_layers=2, num_attention_heads=4,
                                 intermediate_size=64, layer_norm_eps=1e-12,
                                 vocab_size=60, max_position_embeddings=32,
                                 num_labels=2)
    moe_cfg = TransformerConfig(model_type="gpt2", hidden_size=32,
                                num_hidden_layers=2, num_attention_heads=4,
                                intermediate_size=64, layer_norm_eps=1e-5,
                                vocab_size=50, max_position_embeddings=32,
                                n_experts=4, capacity_factor=4.0)
    for name, (mod, cfg) in {"bert": (bert_mod, bert_cfg),
                             "moe": (gpt2_mod, moe_cfg)}.items():
        partition = [(1, 4), (5, 8)]
        sp = [mod.init_params(
            cfg, ShardConfig(l, r, is_first=l == 1, is_last=r == 8), seed=0)
            for l, r in partition]
        pipe = spmd.build_spmd_pipeline(mod.FAMILY, cfg, partition, sp,
                                        mesh)
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(3, 2, 8)),
                          jnp.int32)
        if name == "bert":
            inputs = ids
            labels = jnp.asarray(rng.integers(0, 2, size=(3, 2)), jnp.int32)
        else:
            inputs, labels = ids[..., :-1], ids[..., 1:]
        step, opt_state = train.make_train_step(pipe, optax.sgd(0.1),
                                                inputs)
        params, losses = pipe.params, []
        for _ in range(5):
            params, opt_state, loss = step(params, opt_state, inputs,
                                           labels)
            losses.append(float(loss))
        assert np.isfinite(losses).all(), (name, losses)
        # every step improves (bert's near-chance binary loss moves
        # slowly in absolute terms; monotonic descent is the real claim)
        assert all(b < a for a, b in zip(losses, losses[1:])), (name,
                                                                losses)


def test_sp_ring_attention_training_grads():
    """Long-context training: a ('stage','sp') pipeline with
    sequence-sharded activations and ring attention per block is
    differentiable — JAX transposes the ring ppermutes — and its
    gradients match the single-device oracle."""
    from jax.sharding import Mesh
    from transformers import BertConfig, BertForSequenceClassification

    from pipeedge_tpu.models import bert as bert_mod
    hf_cfg = BertConfig(hidden_size=32, num_hidden_layers=2,
                        num_attention_heads=4, intermediate_size=64,
                        vocab_size=60, max_position_embeddings=32,
                        num_labels=2)
    torch.manual_seed(1)
    model = BertForSequenceClassification(hf_cfg).eval()
    cfg = TransformerConfig(model_type="bert", hidden_size=32,
                            num_hidden_layers=2, num_attention_heads=4,
                            intermediate_size=64, layer_norm_eps=1e-12,
                            vocab_size=60, max_position_embeddings=32,
                            num_labels=2)
    weights = {k: v.numpy() for k, v in model.state_dict().items()}
    partition = [(1, 4), (5, 8)]
    sp_params = [bert_mod.load_params(
        cfg, ShardConfig(l, r, is_first=l == 1, is_last=r == 8), weights)
        for l, r in partition]
    mesh = spmd.make_pipeline_mesh(2, sp=2)
    pipe = spmd.build_spmd_pipeline(bert_mod.FAMILY, cfg, partition,
                                    sp_params, mesh)
    rng = np.random.default_rng(8)
    x = jnp.asarray(rng.integers(0, 60, size=(3, 2, 8)), jnp.int32)
    y = jnp.asarray(rng.integers(0, 2, size=(3, 2)), jnp.int32)
    fwd = pipe.compiled_for(x)
    n_blocks = pipe.params["n_blocks"]

    def sp_loss(trainable):
        return train.softmax_xent(
            fwd({**trainable, "n_blocks": n_blocks}, x), y)

    trainable = {k: v for k, v in pipe.params.items() if k != "n_blocks"}
    sp_val, sp_grads = jax.value_and_grad(sp_loss)(trainable)

    total = 8
    sc = ShardConfig(1, total, is_first=True, is_last=True)
    ref_params = bert_mod.load_params(cfg, sc, weights)
    fn = make_shard_fn(bert_mod.FAMILY, cfg, sc)

    def ref_loss(params):
        return train.softmax_xent(
            jnp.stack([fn(params, u) for u in x]), y)

    ref_val, ref_grads = jax.value_and_grad(ref_loss)(ref_params)
    np.testing.assert_allclose(float(sp_val), float(ref_val),
                               rtol=1e-5, atol=1e-6)
    got = np.asarray(sp_grads["blocks"]["q"]["w"])
    want = np.asarray(ref_grads["blocks"]["q"]["w"])
    for s in range(2):
        np.testing.assert_allclose(got[s], want[s:s + 1], rtol=2e-4,
                                   atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(sp_grads["final"]["head"]["w"]),
        np.asarray(ref_grads["final"]["head"]["w"]), rtol=2e-4, atol=1e-5)


@pytest.mark.fleet
def test_train_cli_bert(tmp_path):
    """tools/train.py covers BERT sequence classification (round-4
    advice: the library always did; now the CLI agrees)."""
    import os
    import re
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=repo)
    cmd = [sys.executable, os.path.join(repo, "tools", "train.py"),
           "-m", "pipeedge/test-tiny-bert", "-pt", "1,4,5,8",
           "-b", "2", "-u", "2", "--seq-len", "8", "--steps", "3",
           "--log-every", "1"]
    run = subprocess.run(cmd, capture_output=True, text=True, env=env,
                         timeout=600)
    assert run.returncode == 0, run.stdout + run.stderr
    losses = [float(m) for m in re.findall(r"loss=([0-9.]+)", run.stdout)]
    assert len(losses) == 3 and losses[-1] < losses[0]


def test_mixed_precision_training():
    """bf16-compute/f32-master mixed precision: params stay float32
    masters across updates, loss tracks the full-f32 run closely, and
    descends; bf16-param pipelines are refused (they have no masters)."""
    import optax
    from jax.sharding import Mesh

    from pipeedge_tpu.models import vit as vit_mod
    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("stage",))
    cfg = TransformerConfig(model_type="vit", hidden_size=32,
                            num_hidden_layers=2, num_attention_heads=4,
                            intermediate_size=64, num_labels=5,
                            image_size=16, patch_size=4)
    partition = [(1, 4), (5, 8)]
    rng = np.random.default_rng(11)
    inputs = jnp.asarray(rng.normal(size=(3, 2, 3, 16, 16)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 5, size=(3, 2)), jnp.int32)

    def run(mixed):
        sp = [vit_mod.init_params(
            cfg, ShardConfig(l, r, is_first=l == 1, is_last=r == 8),
            seed=0) for l, r in partition]
        pipe = spmd.build_spmd_pipeline(vit_mod.FAMILY, cfg, partition,
                                        sp, mesh)
        step, opt_state = train.make_train_step(
            pipe, optax.sgd(0.1), inputs, mixed_precision=mixed)
        params, losses = pipe.params, []
        for _ in range(5):
            params, opt_state, loss = step(params, opt_state, inputs,
                                           labels)
            losses.append(float(loss))
        return params, losses

    params_mp, losses_mp = run(True)
    _, losses_fp = run(False)
    assert all(np.isfinite(losses_mp)), losses_mp
    assert losses_mp[-1] < losses_mp[0], losses_mp
    # master weights never degrade to bf16 across updates
    for leaf in jax.tree_util.tree_leaves(
            {k: v for k, v in params_mp.items() if k != "n_blocks"}):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.float32, leaf.dtype
    # the bf16 compute path tracks full precision closely on this scale
    np.testing.assert_allclose(losses_mp, losses_fp, rtol=0.05)

    # a bf16-param pipeline has no f32 masters: refused with guidance
    sp16 = [vit_mod.init_params(
        cfg, ShardConfig(l, r, is_first=l == 1, is_last=r == 8),
        seed=0, dtype=jnp.bfloat16) for l, r in partition]
    pipe16 = spmd.build_spmd_pipeline(vit_mod.FAMILY, cfg, partition,
                                      sp16, mesh)
    with pytest.raises(ValueError, match="float32"):
        train.make_train_step(pipe16, optax.sgd(0.1), inputs,
                              mixed_precision=True)
