"""Golden-value parity tests for model shards vs HuggingFace torch models.

SURVEY.md §4's test strategy: (b) golden-value parity for shard forward
passes. Tiny randomly-initialized HF torch models are the oracle; weights are
converted through our loaders (the same code path real checkpoints use), and
outputs must match within float32 tolerance. Shard-composition tests split the
model at mid-block cut points — including edges where a (hidden, residual)
2-tuple crosses the stage boundary — and must reproduce the unsharded output.
"""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

from pipeedge_tpu.models import ShardConfig, block_slices, edge_arity, plan_shard  # noqa: E402
from pipeedge_tpu.models import bert as bert_mod  # noqa: E402
from pipeedge_tpu.models import deit as deit_mod  # noqa: E402
from pipeedge_tpu.models import gpt2 as gpt2_mod  # noqa: E402
from pipeedge_tpu.models import vit as vit_mod  # noqa: E402
from pipeedge_tpu.models.layers import TransformerConfig  # noqa: E402
from pipeedge_tpu.models.shard import make_shard_fn  # noqa: E402

TINY = dict(hidden_size=32, num_hidden_layers=3, num_attention_heads=4,
            intermediate_size=64)


@pytest.fixture(scope="module")
def vit_setup():
    from transformers import ViTConfig, ViTForImageClassification
    hf_cfg = ViTConfig(**TINY, image_size=16, patch_size=4, num_labels=5)
    torch.manual_seed(0)
    model = ViTForImageClassification(hf_cfg).eval()
    cfg = TransformerConfig(model_type="vit", **TINY, num_labels=5,
                            image_size=16, patch_size=4)
    weights = vit_mod.hf_to_npz_weights(model.state_dict(), cfg)
    x = torch.randn(2, 3, 16, 16)
    with torch.no_grad():
        expected = model(x).logits.numpy()
    return cfg, weights, np.asarray(x), expected


@pytest.fixture(scope="module")
def bert_setup():
    from transformers import BertConfig, BertForSequenceClassification
    hf_cfg = BertConfig(**TINY, vocab_size=100, max_position_embeddings=64,
                        num_labels=2)
    torch.manual_seed(1)
    model = BertForSequenceClassification(hf_cfg).eval()
    cfg = TransformerConfig(model_type="bert", **TINY, num_labels=2,
                            vocab_size=100, max_position_embeddings=64)
    weights = {k: v.numpy() for k, v in model.state_dict().items()}
    ids = torch.randint(0, 100, (2, 9))
    with torch.no_grad():
        expected = model(ids).logits.numpy()
    return cfg, weights, np.asarray(ids), expected


@pytest.fixture(scope="module")
def deit_setup():
    from transformers import DeiTConfig, DeiTForImageClassificationWithTeacher
    hf_cfg = DeiTConfig(**TINY, image_size=16, patch_size=4, num_labels=5)
    torch.manual_seed(2)
    model = DeiTForImageClassificationWithTeacher(hf_cfg).eval()
    cfg = TransformerConfig(model_type="deit", **TINY, num_labels=5,
                            image_size=16, patch_size=4)
    weights = deit_mod.hf_to_npz_weights(model.state_dict(), cfg)
    x = torch.randn(2, 3, 16, 16)
    with torch.no_grad():
        # reference classifier = head on CLS token only (deit.py:224-227)
        expected = model(x).cls_logits.numpy()
    return cfg, weights, np.asarray(x), expected


@pytest.fixture(scope="module")
def gpt2_setup():
    from transformers import GPT2Config, GPT2LMHeadModel
    hf_cfg = GPT2Config(n_embd=32, n_layer=3, n_head=4, n_inner=64,
                        vocab_size=100, n_positions=64)
    torch.manual_seed(3)
    model = GPT2LMHeadModel(hf_cfg).eval()
    cfg = TransformerConfig(model_type="gpt2", **TINY, layer_norm_eps=1e-5,
                            vocab_size=100, max_position_embeddings=64)
    weights = {k: v.numpy() for k, v in model.state_dict().items()}
    ids = torch.randint(0, 100, (2, 9))
    with torch.no_grad():
        expected = model(ids).logits.numpy()
    return cfg, weights, np.asarray(ids), expected


def _run_partition(family, cfg, weights, x, partition):
    """Run shards for `partition` = [(l0, r0), (l1, r1), ...] in sequence."""
    total = 4 * cfg.num_hidden_layers
    data = jnp.asarray(x)
    for layer_start, layer_end in partition:
        shard_cfg = ShardConfig(layer_start=layer_start, layer_end=layer_end,
                                is_first=layer_start == 1,
                                is_last=layer_end == total)
        params = family.load_params(cfg, shard_cfg, weights)
        fn = make_shard_fn(family.FAMILY, cfg, shard_cfg)
        data = fn(params, data)
    return np.asarray(data)


FULL = [(1, 12)]
# cuts after sublayer 0 (2-tensor edge), mid-model, after sublayer 2
PARTITIONS = [
    [(1, 12)],
    [(1, 4), (5, 12)],            # block-aligned 2-stage
    [(1, 1), (2, 5), (6, 12)],    # cut after attention: tuple edge
    [(1, 7), (8, 12)],            # cut after MLP-up: tuple edge
    [(1, 2), (3, 3), (4, 9), (10, 11), (12, 12)],  # scattered sublayers
]


@pytest.mark.parametrize("partition", PARTITIONS)
def test_vit_parity_and_composition(vit_setup, partition):
    cfg, weights, x, expected = vit_setup
    got = _run_partition(vit_mod, cfg, weights, x, partition)
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("partition", PARTITIONS)
def test_bert_parity_and_composition(bert_setup, partition):
    cfg, weights, ids, expected = bert_setup
    got = _run_partition(bert_mod, cfg, weights, ids, partition)
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("partition", PARTITIONS[:3])
def test_deit_parity_and_composition(deit_setup, partition):
    cfg, weights, x, expected = deit_setup
    got = _run_partition(deit_mod, cfg, weights, x, partition)
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("partition", PARTITIONS)
def test_gpt2_parity_and_composition(gpt2_setup, partition):
    """Causal-decoder parity vs HF GPT2LMHeadModel (per-token vocab logits),
    including mid-block cuts where a (ctx, residual) 2-tuple crosses the
    stage edge — beyond-reference family, same shard machinery."""
    cfg, weights, ids, expected = gpt2_setup
    got = _run_partition(gpt2_mod, cfg, weights, ids, partition)
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-4)


def test_gpt2_causal_masking(gpt2_setup):
    """Perturbing future tokens must not change earlier positions' logits."""
    cfg, weights, ids, _ = gpt2_setup
    total = 4 * cfg.num_hidden_layers
    sc = ShardConfig(1, total, is_first=True, is_last=True)
    params = gpt2_mod.load_params(cfg, sc, weights)
    fn = make_shard_fn(gpt2_mod.FAMILY, cfg, sc)
    base = np.asarray(fn(params, jnp.asarray(ids)))
    mutated = np.array(ids)
    mutated[:, 5:] = (mutated[:, 5:] + 1) % 100
    got = np.asarray(fn(params, jnp.asarray(mutated)))
    np.testing.assert_array_equal(base[:, :5], got[:, :5])
    assert not np.allclose(base[:, 5:], got[:, 5:])


def test_unrolled_blocks_match_scanned(vit_setup):
    """The unrolled execution layout (shard.unstack_blocks — the faster TPU
    path) computes bit-identical results to the scanned stacked layout."""
    from pipeedge_tpu.models.shard import unstack_blocks

    cfg, weights, x, expected = vit_setup
    total = 4 * cfg.num_hidden_layers
    sc = ShardConfig(1, total, is_first=True, is_last=True)
    params = vit_mod.load_params(cfg, sc, weights)
    fn = make_shard_fn(vit_mod.FAMILY, cfg, sc)
    scanned = np.asarray(fn(params, jnp.asarray(x)))
    unrolled_params = unstack_blocks(params)
    assert isinstance(unrolled_params["blocks"], tuple)
    unrolled = np.asarray(fn(unrolled_params, jnp.asarray(x)))
    np.testing.assert_array_equal(scanned, unrolled)
    np.testing.assert_allclose(unrolled, expected, rtol=2e-4, atol=2e-5)
    # idempotent / no-op cases
    assert unstack_blocks(unrolled_params) is not None
    head_only = ShardConfig(1, 2, is_first=True, is_last=False)
    hp = vit_mod.load_params(cfg, head_only, weights)
    assert unstack_blocks(hp) is hp  # no full blocks: returned unchanged


def test_bert_model_no_head_returns_pooler(bert_setup):
    from transformers import BertModel
    cfg, weights, ids, _ = bert_setup
    # bare BertModel weights (strip prefix, drop classifier) -> pooled output
    shard_cfg = ShardConfig(1, 12, is_first=True, is_last=True)
    cfg_nohead = TransformerConfig(model_type="bert", **TINY, num_labels=0,
                                   vocab_size=100, max_position_embeddings=64)
    params = bert_mod.load_params(cfg_nohead, shard_cfg, weights)
    fn = make_shard_fn(bert_mod.FAMILY, cfg_nohead, shard_cfg)
    out = np.asarray(fn(params, jnp.asarray(ids)))
    assert out.shape == (2, 32)  # pooled [B, D]


# --- partition arithmetic -------------------------------------------------

def test_block_slices_matches_reference_arithmetic():
    # reference vit.py:99-113: block = ceil(l/4)-1, sub = (l-1)%4
    sl = block_slices(2, 11)
    assert [(s.block_id, s.sub_start, s.sub_end) for s in sl] == [
        (0, 1, 3), (1, 0, 3), (2, 0, 2)]
    sl = block_slices(5, 8)
    assert [(s.block_id, s.sub_start, s.sub_end) for s in sl] == [(1, 0, 3)]
    sl = block_slices(6, 6)
    assert [(s.block_id, s.sub_start, s.sub_end) for s in sl] == [(1, 1, 1)]


def test_plan_shard_head_scan_tail():
    plan = plan_shard(ShardConfig(2, 11))
    assert plan.head is not None and plan.head.sub_start == 1
    assert plan.full_ids == (1,)
    assert plan.tail is not None and plan.tail.sub_end == 2
    plan = plan_shard(ShardConfig(1, 48))
    assert plan.head is None and plan.tail is None
    assert plan.full_ids == tuple(range(12))


def test_edge_arity():
    # after sub 0 or 2 -> 2 tensors in flight; after 1 or 3 -> 1
    assert edge_arity(1) == 2   # ends at sublayer 0
    assert edge_arity(2) == 1
    assert edge_arity(3) == 2
    assert edge_arity(4) == 1
    assert edge_arity(24) == 1
    assert edge_arity(47) == 2


def test_fast_numerics_mode_close_and_restorable(monkeypatch):
    """Opt-in fast numerics (model-dtype LN/softmax, tanh GeLU): logits
    stay close to the exact mode on the tiny ViT (top-1 agreement on
    random inputs), turning the mode off restores bit-exactness with a
    freshly traced program, and the programmatic toggle OVERRIDES an
    inherited env var (an env-poisoned exact baseline would silently
    void every A/B — code-review finding)."""
    import jax
    import jax.numpy as jnp

    from pipeedge_tpu.models import layers as layers_mod
    from pipeedge_tpu.models import registry
    from pipeedge_tpu.models.layers import (fast_numerics_enabled,
                                            set_fast_numerics)

    monkeypatch.setenv("PIPEEDGE_FAST_NUMERICS", "1")
    set_fast_numerics(False)
    try:
        assert fast_numerics_enabled() is False   # setter wins over env
    finally:
        monkeypatch.setattr(layers_mod, "_FAST_NUMERICS", None)
    assert fast_numerics_enabled() is True        # unset -> env applies
    monkeypatch.delenv("PIPEEDGE_FAST_NUMERICS")
    assert fast_numerics_enabled() is False

    name = "pipeedge/test-tiny-vit"
    total = registry.get_model_layers(name)
    fn, params, _ = registry.module_shard_factory(name, None, 1, total)
    rng = np.random.default_rng(3)
    cfg = registry.get_model_config(name)
    x = jnp.asarray(rng.normal(size=(4, 3, cfg.image_size,
                                     cfg.image_size)), jnp.float32)

    # NB: jit caches by function identity — a fresh lambda over the
    # UN-jitted shard apply per mode forces the retrace that binds the
    # trace-time flag (the factory's fn is jitted and would go stale)
    raw = fn.__wrapped__
    exact = np.asarray(jax.jit(lambda p, xx: raw(p, xx))(params, x))
    set_fast_numerics(True)
    try:
        fast = np.asarray(jax.jit(lambda p, xx: raw(p, xx))(params, x))
    finally:
        set_fast_numerics(False)
    again = np.asarray(jax.jit(lambda p, xx: raw(p, xx))(params, x))

    np.testing.assert_array_equal(again, exact)      # mode fully restored
    assert not np.array_equal(fast, exact)           # mode really changed
    np.testing.assert_allclose(fast, exact, rtol=0.05, atol=0.05)
    assert (np.argmax(fast, -1) == np.argmax(exact, -1)).mean() >= 0.75
