"""Multi-host SPMD smoke worker: one process per "host", composed into a
single global device mesh via `MultiHostContext` (jax.distributed).

Run by tests/test_multihost.py as `python multihost_spmd_main.py RANK WORLD
COORD_ADDR`: each process contributes 4 virtual CPU devices, the global mesh
spans 2x4=8 devices, and the full SPMD pipeline step (pp x dp, quantized
ppermute edges) compiles and executes across the process boundary — the
mechanism that spans TPU slices over DCN (SURVEY.md §5.8).
"""
import os
import sys

rank = int(sys.argv[1])
world = int(sys.argv[2])
coord = sys.argv[3]

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from pipeedge_tpu.comm import MultiHostContext  # noqa: E402
from pipeedge_tpu.models import ShardConfig  # noqa: E402
from pipeedge_tpu.models import vit as vit_mod  # noqa: E402
from pipeedge_tpu.models.layers import TransformerConfig  # noqa: E402
from pipeedge_tpu.parallel import spmd  # noqa: E402


def main() -> None:
    with MultiHostContext(coord, world, rank):
        n_local = len(jax.local_devices())
        n_global = len(jax.devices())
        assert n_local == 4, n_local
        assert n_global == 4 * world, n_global

        dp, n_stages = 2, n_global // 2
        cfg = TransformerConfig(model_type="vit", hidden_size=32,
                                num_hidden_layers=n_stages,
                                num_attention_heads=4, intermediate_size=64,
                                num_labels=5, image_size=16, patch_size=4)
        total = 4 * cfg.num_hidden_layers
        partition = [(4 * i + 1, 4 * (i + 1)) for i in range(n_stages)]
        stage_params = []
        for l, r in partition:
            sc = ShardConfig(l, r, is_first=l == 1, is_last=r == total)
            # same seed everywhere: every process must contribute identical
            # replicated values to the global arrays
            stage_params.append(vit_mod.init_params(cfg, sc, seed=0))
        mesh = spmd.make_pipeline_mesh(n_stages, dp=dp)
        pipe = spmd.build_spmd_pipeline(vit_mod.FAMILY, cfg, partition,
                                        stage_params, mesh,
                                        quant_bit=[8] * (n_stages - 1) + [0])
        batch = 2 * dp
        inputs = jnp.asarray(
            np.random.default_rng(0).normal(
                size=(n_stages + 1, batch, 3, 16, 16)), dtype=jnp.float32)
        out = pipe.run(inputs)
        jax.block_until_ready(out)
        assert out.shape == (n_stages + 1, batch, 5), out.shape

        # tensor-parallel KV-cache decoding over a PROCESS-SPANNING 'tp'
        # mesh: the head-sharded cache and the vocab-sharded LM head cross
        # the host boundary (the dimension the reference cannot span)
        from jax.sharding import Mesh
        from pipeedge_tpu.models import gpt2 as gpt2_mod
        from pipeedge_tpu.parallel import decode as dec_mod
        g_cfg = TransformerConfig(
            model_type="gpt2", hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=64, layer_norm_eps=1e-5,
            vocab_size=48, max_position_embeddings=32)
        g_partition = [(1, 4), (5, 8)]
        g_params = [gpt2_mod.init_params(
            g_cfg, ShardConfig(l, r, is_first=l == 1, is_last=r == 8),
            seed=0) for l, r in g_partition]
        # 2 devices from each process so the tp axis genuinely crosses the
        # host boundary (devices are ordered process-major)
        picks = [0, 1, n_local, n_local + 1] if world > 1 else [0, 1, 2, 3]
        tp_mesh = Mesh(np.asarray(jax.devices())[picks], ("tp",))
        g_pipe = dec_mod.DecodePipeline(gpt2_mod.FAMILY, g_cfg, g_partition,
                                        g_params, max_len=16, mesh=tp_mesh)
        g_out = g_pipe.generate(
            np.random.default_rng(3).integers(0, 48, size=(2, 5)),
            new_tokens=3)
        jax.block_until_ready(g_out)
        assert g_out.shape == (2, 8), g_out.shape

        # TRAINING across the process boundary (round-4 verdict item 6):
        # the same ('dp','stage') process-spanning mesh, differentiated —
        # grads ride the reversed ppermute edges over the host boundary,
        # the optimizer updates the process-sharded params in place. The
        # quantized-edge pipeline above is inference-only, so build a
        # clean-edge pipeline for the gradient path. Deterministic seeds
        # make every rank print the same loss sequence; the parent test
        # compares them across ranks AND against its own single-process
        # oracle run.
        import optax
        from pipeedge_tpu.parallel import train as train_mod
        t_pipe = spmd.build_spmd_pipeline(vit_mod.FAMILY, cfg, partition,
                                          stage_params, mesh)
        t_inputs = jnp.asarray(
            np.random.default_rng(7).normal(
                size=(n_stages + 1, batch, 3, 16, 16)), jnp.float32)
        t_labels = jnp.asarray(
            np.random.default_rng(8).integers(
                0, cfg.num_labels, size=(n_stages + 1, batch)), jnp.int32)
        step_fn, opt_state = train_mod.make_train_step(
            t_pipe, optax.sgd(0.05), t_inputs)
        t_params, losses = t_pipe.params, []
        for _ in range(3):
            t_params, opt_state, loss = step_fn(t_params, opt_state,
                                                t_inputs, t_labels)
            losses.append(float(loss))
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0], losses
        loss_str = ",".join(f"{v:.6f}" for v in losses)

        print(f"MULTIHOST-OK rank={rank} local={n_local} global={n_global} "
              f"out={out.shape} decode={g_out.shape} "
              f"train_losses=[{loss_str}]", flush=True)


if __name__ == "__main__":
    main()
