"""Observability plane: span ring, clock alignment, merged Chrome trace,
Prometheus metrics registry, and the bubble/latency report math
(pipeedge_tpu/telemetry + tools/trace_report.py).

The fleet test at the bottom drives the acceptance path end to end: a
loopback 2-rank DCN round with `--trace-spans` must yield ONE merged
Perfetto-loadable trace covering all ranks with microbatch flow events,
and `tools/trace_report.py` must report bubble %, per-edge wire share, and
per-microbatch percentiles off that artifact with sub-1% recording
overhead.
"""
import json
import os
import re
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from pipeedge_tpu import telemetry
from pipeedge_tpu.comm import dcn
from pipeedge_tpu.telemetry import chrome_trace, metrics, report

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_global_recorder():
    """Tests configure the module recorder; never leak it across tests."""
    yield
    telemetry.disable()


# -- span ring ----------------------------------------------------------

def test_ring_overflow_drops_oldest_never_blocks():
    rec = telemetry.SpanRecorder(rank=3, capacity=8)
    for i in range(20):
        rec.record("stage", f"s{i}", i * 10, i * 10 + 5, mb=i)
    assert len(rec) == 8
    assert rec.dropped == 12
    spans = rec.snapshot()
    # drop-oldest: only the 8 most recent survive, in order
    assert [s["mb"] for s in spans] == list(range(12, 20))
    assert all(s["rank"] == 3 for s in spans)

    # concurrent recording against snapshot/drain must neither block nor
    # corrupt the ring (the send-thread guarantee)
    stop = threading.Event()
    def hammer():
        i = 0
        while not stop.is_set():
            with rec.span("wire", "send", mb=i):
                pass
            i += 1
    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    t0 = time.monotonic()
    for _ in range(50):
        rec.snapshot()
        rec.drain()
    stop.set()
    for t in threads:
        t.join()
    assert time.monotonic() - t0 < 5.0
    assert len(rec.snapshot()) <= 8


def test_span_context_manager_and_disabled_fast_path():
    assert not telemetry.enabled()
    with telemetry.span("stage", "noop"):   # disabled: shared no-op
        pass
    rec = telemetry.configure(rank=1, capacity=16)
    with telemetry.span("stage", "работа", stage=2, mb=7):
        time.sleep(0.001)
    (s,) = rec.snapshot()
    assert s["cat"] == "stage" and s["stage"] == 2 and s["mb"] == 7
    assert s["t1"] - s["t0"] >= 1_000_000  # the 1 ms sleep
    assert s["rank"] == 1


def test_spans_wire_roundtrip():
    rec = telemetry.SpanRecorder(rank=2, capacity=4)
    rec.record("wire", "send->r1", 100, 200, mb=0)
    rec.record("compute", "stage0", 150, 300, stage=0, mb=1)
    spans = rec.snapshot()
    arr = telemetry.spans_to_wire(spans)
    assert arr.dtype == np.uint8
    assert telemetry.spans_from_wire(arr) == spans
    assert telemetry.spans_from_wire(np.zeros(0, np.uint8)) == []


# -- clock alignment ----------------------------------------------------

def test_clock_offset_recovers_known_skew():
    """Symmetric-RTT synthetic peers: the NTP estimate recovers the skew
    within tolerance regardless of (symmetric) network-delay noise."""
    rng = np.random.default_rng(0)
    skew = 123_456_789_000          # peer clock runs 123.5 ms ahead
    samples = []
    for _ in range(8):
        t0 = int(rng.integers(1e9, 2e9))
        d = int(rng.integers(50_000, 5_000_000))    # one-way transit
        proc = int(rng.integers(1_000, 50_000))
        t1 = t0 + d + skew
        t2 = t1 + proc
        t3 = t0 + d + proc + d
        samples.append((t0, t1, t2, t3))
    est = telemetry.estimate_clock_offset(samples)
    assert abs(est - skew) < 1_000   # sub-microsecond on symmetric paths
    # aligning a peer span lands it on the local timeline
    peer_span = {"cat": "stage", "name": "x", "rank": 1, "stage": None,
                 "mb": None, "t0": 1_000 + skew, "t1": 2_000 + skew}
    (aligned,) = telemetry.align_spans([peer_span], est)
    assert abs(aligned["t0"] - 1_000) < 1_000


def test_clock_offset_picks_min_rtt_sample():
    # the asymmetric-congestion sample would give a wrong answer; the
    # filter must prefer the clean (min-RTT) one
    clean = (1000, 2000, 2100, 3100)        # symmetric: offset 0
    congested = (1000, 2000, 2100, 60000)   # slow return path: offset
    # estimate would be badly negative if this sample were used
    assert telemetry.estimate_clock_offset([congested, clean]) == \
        telemetry.estimate_clock_offset([clean]) == 0


# -- merged chrome trace ------------------------------------------------

def _two_stage_spans():
    """Hand-built two-stage timeline: stages alternate perfectly (50%
    idle each), two microbatches, one wire hop."""
    ms = 1_000_000
    return [
        {"cat": "runtime", "name": "round0", "rank": 0, "stage": None,
         "mb": None, "t0": 0, "t1": 40 * ms},
        {"cat": "stage", "name": "stage0", "rank": 0, "stage": 0, "mb": 0,
         "t0": 0, "t1": 10 * ms},
        {"cat": "wire", "name": "send->r1", "rank": 0, "stage": None,
         "mb": None, "t0": 9 * ms, "t1": 10 * ms},
        {"cat": "stage", "name": "stage1", "rank": 1, "stage": 1, "mb": 0,
         "t0": 10 * ms, "t1": 20 * ms},
        {"cat": "stage", "name": "stage0", "rank": 0, "stage": 0, "mb": 1,
         "t0": 20 * ms, "t1": 30 * ms},
        {"cat": "stage", "name": "stage1", "rank": 1, "stage": 1, "mb": 1,
         "t0": 30 * ms, "t1": 40 * ms},
    ]


def test_chrome_trace_valid_and_deterministic(tmp_path):
    spans = _two_stage_spans()
    doc = chrome_trace.build_trace(spans)
    # deterministic for a fixed span set (CI artifact diffs rely on it)
    assert json.dumps(doc, sort_keys=True) == json.dumps(
        chrome_trace.build_trace(list(reversed(spans))), sort_keys=True)
    events = doc["traceEvents"]
    x = [e for e in events if e["ph"] == "X"]
    assert len(x) == len(spans)
    assert {e["pid"] for e in x} == {0, 1}          # one process per rank
    # one named track per (rank, category)
    names = {(e["pid"], e["args"]["name"]) for e in events
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert (0, "stage") in names and (1, "stage") in names \
        and (0, "wire") in names
    # microbatch flow events: start + finish per mb that crosses ranks
    flows = [e for e in events if e.get("cat") == "mb"]
    assert {e["name"] for e in flows} == {"mb0", "mb1"}
    assert len({e["id"] for e in flows}) == 2   # one flow id per group
    for mb in ("mb0", "mb1"):
        phases = [e["ph"] for e in flows if e["name"] == mb]
        assert phases[0] == "s" and phases[-1] == "f"
    # file round trip preserves the spans (what trace_report reads)
    path = tmp_path / "trace.json"
    chrome_trace.dump_trace(spans, str(path))
    back = chrome_trace.trace_to_spans(json.loads(path.read_text()))
    assert len(back) == len(spans)
    assert {(s["cat"], s["name"]) for s in back} == \
        {(s["cat"], s["name"]) for s in spans}


# -- report math --------------------------------------------------------

def test_report_bubble_math_two_stage_timeline():
    rep = report.analyze_spans(_two_stage_spans(), span_cost_ns=1000.0)
    assert rep["spans"] == 6
    assert rep["window_s"] == 0.04
    # each stage busy 20 of 40 ms -> 50% bubble
    assert rep["stages"]["stage0"]["busy_s"] == 0.02
    assert rep["stages"]["stage0"]["bubble_pct"] == 50.0
    assert rep["bubble_pct"] == 50.0
    # wire: 1 ms of 40 ms
    assert rep["edges"]["r0:send->r1"]["share_pct"] == 2.5
    # both microbatches take 20 ms end to end
    assert rep["mb_latency"]["n"] == 2
    assert rep["mb_latency"]["p50_ms"] == 20.0
    assert rep["mb_latency"]["p99_ms"] == 20.0
    # overhead: 6 spans x 1 us over 40 ms
    assert rep["span_overhead_pct"] == pytest.approx(0.015)


def test_report_failover_breakdown_and_empty():
    ms = 1_000_000
    spans = _two_stage_spans() + [
        {"cat": "failover", "name": "detect", "rank": 0, "stage": None,
         "mb": None, "t0": 12 * ms, "t1": 12 * ms},
        {"cat": "failover", "name": "reschedule", "rank": 0, "stage": None,
         "mb": None, "t0": 13 * ms, "t1": 15 * ms},
        {"cat": "failover", "name": "recover", "rank": 0, "stage": None,
         "mb": None, "t0": 12 * ms, "t1": 33 * ms},
    ]
    rep = report.analyze_spans(spans, span_cost_ns=1000.0)
    assert rep["failover"]["reschedule"] == 0.002
    assert rep["failover"]["detect_to_recover_s"] == 0.021
    assert rep["failover"]["recoveries_s"] == [0.021]
    assert report.analyze_spans([]) == {"spans": 0}


def test_report_rejoin_breakdown():
    """The elastic-membership section: admissions counted from instant
    'admit' spans; each 'heal' span's duration is that episode's
    time-to-full-capacity."""
    ms = 1_000_000
    spans = _two_stage_spans() + [
        {"cat": "rejoin", "name": "admit", "rank": 0, "stage": None,
         "mb": None, "t0": 20 * ms, "t1": 20 * ms},
        {"cat": "rejoin", "name": "heal", "rank": 0, "stage": None,
         "mb": None, "t0": 12 * ms, "t1": 37 * ms},
    ]
    rep = report.analyze_spans(spans, span_cost_ns=1000.0)
    assert rep["rejoin"]["admissions"] == 1
    assert rep["rejoin"]["heals"] == 1
    assert rep["rejoin"]["heals_s"] == [0.025]
    assert rep["rejoin"]["time_to_full_capacity_s"] == 0.025
    # no rejoin spans -> empty section (key present, falsy)
    assert report.analyze_spans(_two_stage_spans(),
                                span_cost_ns=1000.0)["rejoin"] == {}


def test_report_multi_failover_recoveries_are_per_event():
    """Two failovers far apart must NOT report the healthy time between
    them as recovery time — each recover span is its own event."""
    s = 1_000_000_000
    spans = [
        {"cat": "runtime", "name": "round0", "rank": 0, "stage": None,
         "mb": None, "t0": 0, "t1": 200 * s},
        {"cat": "failover", "name": "recover", "rank": 0, "stage": None,
         "mb": None, "t0": 10 * s, "t1": 11 * s},
        {"cat": "failover", "name": "recover", "rank": 0, "stage": None,
         "mb": None, "t0": 110 * s, "t1": 112 * s},
    ]
    rep = report.analyze_spans(spans, span_cost_ns=1000.0)
    assert rep["failover"]["recoveries_s"] == [1.0, 2.0]
    assert rep["failover"]["detect_to_recover_s"] == 2.0   # worst event


def test_mb_latency_segments_by_round():
    """mb ids restart each schedule round (re-schedule replays the same
    batch; --measure-rounds reruns it): latency must be per (round, mb),
    and flows must not chain across rounds."""
    ms = 1_000_000
    spans = []
    for rnd, base in ((0, 0), (1, 100 * ms)):
        spans.append({"cat": "runtime", "name": f"round{rnd}", "rank": 0,
                      "stage": None, "mb": None, "t0": base,
                      "t1": base + 20 * ms})
        for mb in (0, 1):
            spans.append({"cat": "stage", "name": "stage0", "rank": 0,
                          "stage": 0, "mb": mb, "t0": base + mb * 10 * ms,
                          "t1": base + mb * 10 * ms + 10 * ms})
    rep = report.analyze_spans(spans, span_cost_ns=1000.0)
    # 4 per-round microbatches of 10 ms each — NOT 2 of ~110 ms
    assert rep["mb_latency"]["n"] == 4
    assert rep["mb_latency"]["p99_ms"] == 10.0
    doc = chrome_trace.build_trace(spans)
    flows = [e for e in doc["traceEvents"] if e.get("cat") == "mb"]
    # 2 rounds x 2 mbs, each a distinct flow group (here single-hop
    # groups emit no arrows; ids would chain rounds if shared)
    assert len({e["id"] for e in flows}) == len({
        (e["id"], e["name"]) for e in flows})


# -- prometheus metrics -------------------------------------------------

_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"                 # metric name
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'          # first label
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'     # more labels
    r" [-+]?([0-9]*\.?[0-9]+([eE][-+]?[0-9]+)?|Inf|NaN)$")


def _assert_prometheus_text(text):
    """Every non-comment line must match the exposition format."""
    assert text.endswith("\n")
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _PROM_LINE.match(line), f"bad prometheus line: {line!r}"


def test_metrics_registry_renders_prometheus_text():
    r = metrics.Registry()
    c = r.counter("edge_wire_bytes_total", "per-edge wire bytes")
    c.declare(edge="0->1")
    c.inc(4096, edge="1->2")
    g = r.gauge("edge_bits", "negotiated bitwidth")
    g.set(8, edge="0->1")
    h = r.histogram("request_latency_seconds", "latency")
    for v in (0.004, 0.03, 0.03, 7.0):
        h.observe(v)
    text = r.render()
    _assert_prometheus_text(text)
    assert 'edge_wire_bytes_total{edge="0->1"} 0' in text
    assert 'edge_wire_bytes_total{edge="1->2"} 4096' in text
    assert "# TYPE request_latency_seconds histogram" in text
    assert 'request_latency_seconds_bucket{le="0.005"} 1' in text
    assert 'request_latency_seconds_bucket{le="0.05"} 3' in text
    assert 'request_latency_seconds_bucket{le="+Inf"} 4' in text
    assert "request_latency_seconds_count 4" in text
    # idempotent declaration returns the same instrument
    assert r.counter("edge_wire_bytes_total", "x") is c
    with pytest.raises(ValueError):
        r.gauge("edge_wire_bytes_total", "wrong type")
    with pytest.raises(ValueError):
        c.inc(-1)


def test_metrics_monitoring_snapshot_bridge(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)   # monitoring writes per-key CSVs in cwd
    import monitoring
    monitoring.init("shard", 4)
    try:
        monitoring.add_key("send", work_type="Mbits")
        monitoring.iteration_start("shard")
        monitoring.iteration("shard", work=8)
        snap = monitoring.snapshot()
        assert set(snap) == {"shard", "send"}
        assert snap["shard"]["global"]["work"] == 8
        assert snap["shard"]["instant"]["work"] == 8
        assert snap["shard"]["tag"] == 1
        assert snap["send"]["window"]["work"] == 0
        lines = metrics.render_monitoring_snapshot(snap)
        text = "\n".join(lines) + "\n"
        _assert_prometheus_text(text)
        assert 'pipeedge_monitor_work{key="shard",scope="global"} 8' in lines
    finally:
        monitoring.finish()
    assert monitoring.snapshot() == {}   # no session: empty, not an error


# -- fleet span collection over the command channel ---------------------

def _free_ports(n):
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def test_collect_spans_over_command_channel():
    """_MSG_SPANS: rank 0 pulls rank 1's ring + a clock offset; both live
    in this process, so the true offset is ~0 and the spans are shared."""
    rec = telemetry.configure(rank=1, capacity=64)
    rec.record("compute", "stage1", 1000, 2000, stage=1, mb=0)
    addrs = [("127.0.0.1", p) for p in _free_ports(2)]
    ctxs = [dcn.DistDcnContext(2, r, addrs) for r in range(2)]
    for c in ctxs:
        c.init()
    try:
        spans, offset = ctxs[0].collect_spans(1, probes=3, timeout=10.0)
        assert any(s["name"] == "stage1" and s["rank"] == 1 for s in spans)
        assert abs(offset) < 50_000_000   # same host, same clock: ~0
    finally:
        for c in ctxs:
            c.shutdown()


# -- acceptance path: traced loopback fleet + report --------------------

@pytest.mark.fleet
def test_traced_dcn_round_and_report(tmp_path):
    """A 2-rank loopback DCN round with --trace-spans produces one merged
    Perfetto-loadable trace covering all ranks with microbatch flow
    events; trace_report.py emits bubble/edge/latency fields off it, with
    span overhead under 1% (the ISSUE acceptance criteria)."""
    ports = _free_ports(2)
    addrs = ",".join(f"127.0.0.1:{p}" for p in ports)
    trace = tmp_path / "trace.json"
    common = [sys.executable, os.path.join(REPO, "runtime.py")]
    opts = ["-c", "dcn", "--platform", "cpu", "-m",
            "pipeedge/test-tiny-vit", "-pt", "1,4,5,8", "-q", "8,0",
            "-r", "0,1", "-b", "16", "-u", "4", "--dcn-addrs", addrs,
            "--sched-timeout", "120", "--trace-spans", str(trace)]
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               DCN_CONNECT_TIMEOUT="30")
    worker = subprocess.Popen(common + ["1", "2"] + opts, cwd=tmp_path,
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
    try:
        data = subprocess.run(common + ["0", "2"] + opts, cwd=tmp_path,
                              env=env, capture_output=True, text=True,
                              timeout=300)
    finally:
        try:
            worker.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            worker.kill()
    assert data.returncode == 0, data.stdout + data.stderr

    doc = json.loads(trace.read_text())
    x = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert {e["pid"] for e in x} == {0, 1}, "trace must cover all ranks"
    assert [e for e in doc["traceEvents"] if e.get("cat") == "mb"], \
        "microbatch flow events missing"

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         str(trace), "--require-spans"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stdout + out.stderr
    rep = json.loads(out.stdout)
    assert rep["spans"] > 0
    assert rep["bubble_pct"] is not None
    assert rep["edges"], "per-edge wire share missing"
    assert rep["mb_latency"]["n"] == 4     # 16/4 microbatches
    assert rep["mb_latency"]["p50_ms"] > 0
    assert rep["failover"] == {}           # clean run
    assert rep["span_overhead_pct"] < 1.0  # hot-path tax stays negligible


def test_per_round_bubble_skips_absent_stages():
    """A stage with no spans in a round (failed over away) is absent from
    that round's mean, not counted 100% idle."""
    ms = 1_000_000
    spans = []
    for rnd, base in ((0, 0), (1, 100 * ms)):
        spans.append({"cat": "runtime", "name": f"round{rnd}", "rank": 0,
                      "stage": None, "mb": None, "t0": base,
                      "t1": base + 20 * ms})
        stages = (0, 1, 2) if rnd == 0 else (0, 1)   # stage 2 died
        for st in stages:
            spans.append({"cat": "stage", "name": "dispatch", "rank": st,
                          "stage": st, "mb": 0, "t0": base,
                          "t1": base + 10 * ms})
    rep = report.analyze_spans(spans, span_cost_ns=1000.0)
    assert [r["bubble_pct"] for r in rep["rounds"]] == [50.0, 50.0]
