"""Fused (Pallas) attention kernel tests — interpret mode on CPU."""
import numpy as np
import pytest

import jax.numpy as jnp

from pipeedge_tpu.ops.attention import fused_attention


def _reference(q, k, v, causal=False):
    d = q.shape[-1]
    scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if causal:
        s = q.shape[1]
        mask = np.tril(np.ones((s, s), bool))
        scores = np.where(mask, scores, -1e30)
    scores = scores - scores.max(axis=-1, keepdims=True)
    p = np.exp(scores)
    p /= p.sum(axis=-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("shape", [
    (2, 128, 4, 64),    # block-aligned
    (1, 197, 3, 64),    # ViT sequence length: prime-ish, forces odd blocks
    (2, 512, 2, 32),    # BERT max sequence
])
def test_matches_reference(shape):
    rng = np.random.default_rng(0)
    q, k, v = (rng.normal(size=shape).astype(np.float32) for _ in range(3))
    out = np.asarray(fused_attention(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), interpret=True))
    np.testing.assert_allclose(out, _reference(q, k, v), rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("shape", [
    (2, 128, 4, 64),    # block-aligned: exercises the early-stop loop bound
    (1, 100, 3, 32),    # padded sequence + causal masking combined
])
def test_causal_matches_reference(shape):
    rng = np.random.default_rng(3)
    q, k, v = (rng.normal(size=shape).astype(np.float32) for _ in range(3))
    out = np.asarray(fused_attention(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), q_block=32, kv_block=32,
                                     causal=True, interpret=True))
    np.testing.assert_allclose(out, _reference(q, k, v, causal=True),
                               rtol=2e-4, atol=2e-5)


def test_bfloat16_inputs():
    rng = np.random.default_rng(1)
    shape = (1, 64, 2, 32)
    q, k, v = (rng.normal(size=shape).astype(np.float32) for _ in range(3))
    out = fused_attention(jnp.asarray(q, jnp.bfloat16),
                          jnp.asarray(k, jnp.bfloat16),
                          jnp.asarray(v, jnp.bfloat16), interpret=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               _reference(q, k, v), rtol=0.1, atol=0.05)


def test_numerically_stable_large_scores():
    rng = np.random.default_rng(2)
    shape = (1, 64, 1, 16)
    q = (rng.normal(size=shape) * 30).astype(np.float32)
    k = (rng.normal(size=shape) * 30).astype(np.float32)
    v = rng.normal(size=shape).astype(np.float32)
    out = np.asarray(fused_attention(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), interpret=True))
    assert np.all(np.isfinite(out))
    np.testing.assert_allclose(out, _reference(q, k, v), rtol=1e-3, atol=1e-4)
