"""Switch-MoE decoder family: routed FFN blocks through the shard engine,
pipeline drivers, KV-cache decoding, and the ep mesh axis."""
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from pipeedge_tpu.models import ShardConfig
from pipeedge_tpu.models import gpt2 as gpt2_mod
from pipeedge_tpu.models.layers import TransformerConfig, gelu, gelu_new
from pipeedge_tpu.models.registry import get_model_config
from pipeedge_tpu.models.shard import make_shard_fn
from pipeedge_tpu.parallel import decode, expert, spmd

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MODEL = "pipeedge/test-tiny-moe"


@pytest.fixture(scope="module")
def moe_setup():
    cfg = get_model_config(MODEL)
    weights = gpt2_mod.moe_state_dict(cfg, seed=3)
    return cfg, weights


def _shard(cfg, weights, l, r):
    total = 4 * cfg.num_hidden_layers
    sc = ShardConfig(l, r, is_first=l == 1, is_last=r == total)
    return gpt2_mod.load_params(cfg, sc, weights), sc


def test_moe_delta_matches_reference_ffn(moe_setup):
    """moe_ffn_delta == reference_moe_ffn - input (same routing/capacity)."""
    cfg, _ = moe_setup
    params = expert.init_moe_params(cfg, n_experts=4, seed=1)
    x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 8, 32)),
                    jnp.float32)
    delta = expert.moe_ffn_delta(params, x, 4, 1.25, act=gelu)
    full = expert.reference_moe_ffn(params, x, 4)
    np.testing.assert_allclose(np.asarray(delta), np.asarray(full - x),
                               rtol=2e-5, atol=2e-5)


def test_moe_delta_matches_ep_sharded(moe_setup):
    """The family's FFN math == the ep-sharded switch-FFN over 2 devices
    (same act), so MoE blocks and the 'ep' axis share one semantics."""
    cfg, _ = moe_setup
    params = expert.init_moe_params(cfg, n_experts=4, seed=4)
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("ep",))
    x = jnp.asarray(np.random.default_rng(5).normal(size=(2, 6, 32)),
                    jnp.float32)
    ep_fn = expert.make_ep_ffn_fn(cfg, mesh, n_experts=4, act=gelu_new)
    ep_out = ep_fn(expert.shard_moe_params(params, mesh), x)
    delta = expert.moe_ffn_delta(params, x, 4, 1.25, act=gelu_new)
    np.testing.assert_allclose(np.asarray(ep_out - x), np.asarray(delta),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("partition", [
    [(1, 8)],
    [(1, 4), (5, 8)],
    [(1, 3), (4, 8)],     # cut after the MoE sublayer: (delta, res) edge
    [(1, 7), (8, 8)],
])
def test_moe_split_matches_whole(moe_setup, partition):
    cfg, weights = moe_setup
    ids = jnp.asarray(np.random.default_rng(7).integers(0, 100, size=(2, 9)),
                      jnp.int32)
    whole, sc = _shard(cfg, weights, 1, 8)
    expected = np.asarray(make_shard_fn(gpt2_mod.FAMILY, cfg, sc)(whole, ids))
    data = ids
    for l, r in partition:
        params, sc = _shard(cfg, weights, l, r)
        data = make_shard_fn(gpt2_mod.FAMILY, cfg, sc)(params, data)
    np.testing.assert_allclose(np.asarray(data), expected, rtol=2e-5,
                               atol=2e-5)
    assert expected.shape == (2, 9, 100)


@pytest.mark.slow
def test_moe_spmd_pipeline(moe_setup):
    """MoE blocks through the one-program SPMD pipeline (pp x dp).

    Under dp the batch is sharded, and capacity routing — which depends on
    the token set — runs per dp shard (the standard data-parallel MoE
    semantics: each group routes its own tokens). The oracle therefore
    routes each half-batch independently."""
    cfg, weights = moe_setup
    partition = [(1, 4), (5, 8)]
    stage_params = [_shard(cfg, weights, l, r)[0] for l, r in partition]
    mesh = spmd.make_pipeline_mesh(2, dp=2)
    pipe = spmd.build_spmd_pipeline(gpt2_mod.FAMILY, cfg, partition,
                                    stage_params, mesh)
    ids = jnp.asarray(
        np.random.default_rng(8).integers(0, 100, size=(3, 4, 8)), jnp.int32)
    got = np.asarray(pipe.run(ids))
    whole, sc = _shard(cfg, weights, 1, 8)
    fn = make_shard_fn(gpt2_mod.FAMILY, cfg, sc)
    expected = np.stack([
        np.concatenate([np.asarray(fn(whole, u[:2])),
                        np.asarray(fn(whole, u[2:]))]) for u in ids])
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-4)
    with pytest.raises(NotImplementedError, match="tp.*sp|MoE"):
        spmd.build_spmd_pipeline(gpt2_mod.FAMILY, cfg, partition,
                                 stage_params,
                                 spmd.make_pipeline_mesh(2, tp=2))


@pytest.mark.slow
def test_moe_decode_matches_forward_greedy(moe_setup):
    """KV-cache greedy decode == no-cache greedy (full forward per step)."""
    cfg, weights = moe_setup
    partition = [(1, 4), (5, 8)]
    stage_params = [_shard(cfg, weights, l, r)[0] for l, r in partition]
    pipe = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition,
                                 stage_params, max_len=16)
    ids = np.random.default_rng(9).integers(0, 100, size=(2, 5))
    got = np.asarray(pipe.generate(ids, 6))

    whole, sc = _shard(cfg, weights, 1, 8)
    fn = make_shard_fn(gpt2_mod.FAMILY, cfg, sc)
    seq = np.array(ids)
    for _ in range(6):
        logits = np.asarray(fn(whole, jnp.asarray(seq, jnp.int32)))
        seq = np.concatenate([seq, logits[:, -1].argmax(-1)[:, None]], axis=1)
    np.testing.assert_array_equal(got, seq)
    with pytest.raises(NotImplementedError, match="MoE"):
        decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition, stage_params,
                              max_len=16,
                              mesh=Mesh(np.asarray(jax.devices()[:2]),
                                        ("tp",)))


@pytest.mark.slow
def test_moe_ep_decode_matches_plain(moe_setup):
    """Expert-parallel MoE decode: experts shard over an 'ep' mesh inside
    the decode step (global routing, local expert slab, one psum), cache
    replicated — same tokens as the single-device pipeline (top-1 routing
    means the psum adds exactly one nonzero term, so this is exact)."""
    cfg, weights = moe_setup
    partition = [(1, 4), (5, 8)]
    stage_params = [_shard(cfg, weights, l, r)[0] for l, r in partition]
    ids = np.random.default_rng(13).integers(0, 100, size=(2, 5))
    plain = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition,
                                  stage_params, max_len=16)
    want = np.asarray(plain.generate(ids, 6))
    ep_mesh = Mesh(np.asarray(jax.devices()[:2]), ("ep",))
    piped = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition,
                                  stage_params, max_len=16, ep_mesh=ep_mesh)
    got = np.asarray(piped.generate(ids, 6))
    np.testing.assert_array_equal(got, want)
    # int8 KV composes with ep (cache + per-head scale rows replicated
    # across the ep axis -> identical quantization on every device):
    # tokens match the single-device int8 MoE pipeline
    int8_plain = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition,
                                       stage_params, max_len=16,
                                       cache_bits=8)
    int8_ep = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition,
                                    stage_params, max_len=16,
                                    cache_bits=8, ep_mesh=ep_mesh)
    np.testing.assert_array_equal(
        np.asarray(int8_ep.generate(ids, 6)),
        np.asarray(int8_plain.generate(ids, 6)))
    with pytest.raises(ValueError, match="requires an MoE config"):
        decode.make_ep_stage_fns(
            gpt2_mod.FAMILY,
            TransformerConfig(model_type="gpt2", hidden_size=32,
                              num_hidden_layers=2, num_attention_heads=4,
                              intermediate_size=64, vocab_size=100,
                              max_position_embeddings=64),
            ShardConfig(1, 8, is_first=True, is_last=True), ep_mesh, {})


@pytest.mark.slow
def test_moe_tp_ep_decode_matches_plain(moe_setup):
    """VERDICT r2 item 7 — the MoE serving composition: attention
    tp-sharded AND experts ep-sharded in ONE ('tp','ep') mesh per decode
    stage. Exact vs the single-device pipeline: attention psums over tp
    reproduce the dense result, routing sees the full token set, and the
    expert psum over ep adds one nonzero term per token."""
    cfg, weights = moe_setup
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices for a 2x2 tp x ep mesh")
    partition = [(1, 4), (5, 8)]
    stage_params = [_shard(cfg, weights, l, r)[0] for l, r in partition]
    ids = np.random.default_rng(17).integers(0, 100, size=(2, 5))
    plain = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition,
                                  stage_params, max_len=16)
    want = np.asarray(plain.generate(ids, 6))
    mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2), ("tp", "ep"))
    piped = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition,
                                  stage_params, max_len=16, tp_ep_mesh=mesh)
    got = np.asarray(piped.generate(ids, 6))
    np.testing.assert_array_equal(got, want)

    # guard rails: dense configs refuse (use plain tp), bad divisibility
    # refuses, and tp_ep_mesh does not stack with the single-axis meshes
    dense_cfg = TransformerConfig(
        model_type="gpt2", hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64, vocab_size=100,
        max_position_embeddings=64)
    with pytest.raises(ValueError, match="requires an MoE config"):
        decode.make_tp_ep_stage_fns(
            gpt2_mod.FAMILY, dense_cfg,
            ShardConfig(1, 8, is_first=True, is_last=True), mesh, {})
    with pytest.raises(ValueError, match="does not compose|replaces"):
        decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition, stage_params,
                              max_len=16, tp_ep_mesh=mesh,
                              ep_mesh=Mesh(np.asarray(jax.devices()[:2]),
                                           ("ep",)))


@pytest.mark.fleet
def test_moe_runtime_cli(tmp_path):
    """MoE decoder end-to-end through the runtime CLI (host driver with a
    quantized (delta, residual) edge, then the SPMD driver)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=REPO)
    for extra in (["-pt", "1,3,4,8", "-q", "8,0"], ["-c", "spmd"]):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "runtime.py"), "0", "2",
             "-m", MODEL, "-b", "4", "-u", "2"] + extra
            + ([] if "-pt" in extra else ["-pt", "1,4,5,8"]),
            capture_output=True, env=env, cwd=str(tmp_path), text=True,
            timeout=300)
        assert proc.returncode == 0, proc.stderr
        assert "throughput_items_sec=" in proc.stdout


@pytest.mark.fleet
def test_moe_save_weights_roundtrip(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "save_model_weights.py"),
         "-m", MODEL, "--random"],
        capture_output=True, env=env, cwd=str(tmp_path), text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stderr
    assert os.path.exists("test-tiny-moe.npz")
    from pipeedge_tpu.models import registry
    fn, params, _ = registry.module_shard_factory(
        MODEL, "test-tiny-moe.npz", 1, 8)
    block0 = params["blocks"][0] if isinstance(params["blocks"], tuple) \
        else jax.tree_util.tree_map(lambda x: x[0], params["blocks"])
    assert block0["moe"]["router"]["w"].shape == (32, 4)
    assert block0["moe"]["experts"]["mlp_up"]["w"].shape == (4, 32, 64)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 100, size=(2, 7)),
                      jnp.int32)
    out = np.asarray(fn(params, ids))
    assert out.shape == (2, 7, 100) and np.all(np.isfinite(out))


@pytest.mark.slow
def test_moe_sp_prefill_matches_plain(moe_setup):
    """Droppless MoE (capacity_factor == n_experts, routing a pure
    per-token gate) supports sequence-parallel prefill: chunk-local
    routing is exact, so tokens match the plain pipeline. A
    capacity-BOUNDED MoE config still refuses (chunk-local capacity
    changes drop semantics)."""
    import dataclasses

    cfg, weights = moe_setup
    assert cfg.capacity_factor >= cfg.n_experts
    partition = [(1, 4), (5, 8)]
    stage_params = [_shard(cfg, weights, l, r)[0] for l, r in partition]
    plain = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition,
                                  stage_params, max_len=16)
    ids = np.random.default_rng(21).integers(0, 100, size=(2, 6))
    want = np.asarray(plain.generate(ids, 5))

    sp_mesh = Mesh(np.asarray(jax.devices()[:2]), ("sp",))
    sp_pipe = decode.DecodePipeline(gpt2_mod.FAMILY, cfg, partition,
                                    stage_params, max_len=16,
                                    sp_mesh=sp_mesh)
    got = np.asarray(sp_pipe.generate(ids, 5))
    np.testing.assert_array_equal(got, want)

    bounded = dataclasses.replace(cfg, capacity_factor=1.25)
    with pytest.raises(NotImplementedError, match="dropless"):
        bounded_pipe = decode.DecodePipeline(
            gpt2_mod.FAMILY, bounded, partition, stage_params, max_len=16,
            sp_mesh=sp_mesh)
        bounded_pipe.generate(ids, 2)
