"""The cross-process prefill fleet (pipeedge_tpu/kv/fleet.py): the
lease/ack ship protocol and its fault matrix.

The disaggregated split is only trustworthy if the ship edge survives
every fault deterministically (ISSUE 15): CRC-corrupt frame -> bounded
re-ship -> success; resend exhaustion -> colocated fallback with token
parity; worker death mid-lease -> re-dispatch to a survivor; zombie
acks (stale lease attempt) fenced; and the chaos acceptance — a prefill
worker PROCESS killed mid-burst with every in-flight request completing
token-identically and zero leaked pages.
"""
import os
import queue as queue_mod
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp  # noqa: E402

from pipeedge_tpu.comm import dcn  # noqa: E402
from pipeedge_tpu.kv import (PagedKvBackend, PrefillUnavailable,  # noqa: E402
                             PrefillWorkerLoop, RemotePrefillFleet)
from pipeedge_tpu.kv import fleet as fleet_mod  # noqa: E402
from pipeedge_tpu.parallel.batcher import ContinuousBatcher  # noqa: E402
from pipeedge_tpu.telemetry import metrics as prom  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MODEL = "pipeedge/test-tiny-gpt2"
PARTITION = [(1, 4), (5, 8)]
MAX_LEN = 48


def _mk_pipe(max_len=MAX_LEN):
    from pipeedge_tpu.models import registry
    from pipeedge_tpu.parallel import decode
    params = [registry.module_shard_factory(MODEL, None, l, r, stage=i,
                                            unroll=False)[1]
              for i, (l, r) in enumerate(PARTITION)]
    return decode.DecodePipeline(
        registry.get_model_entry(MODEL).family.FAMILY,
        registry.get_model_config(MODEL), PARTITION, params,
        max_len=max_len)


@pytest.fixture(scope="module")
def pipe():
    return _mk_pipe()


@pytest.fixture(scope="module")
def prefill_pipe():
    return _mk_pipe()


def _free_ports(n):
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _make_contexts(n):
    """Contexts with the LOCAL hand-off tier disabled: production
    prefill workers are separate processes, so the in-process test
    plane must ride the socket path too (the corrupt@K hook and the
    CRC layer live there)."""
    addrs = [("127.0.0.1", p) for p in _free_ports(n)]
    before = os.environ.get("DCN_LOCAL_HANDOFF")
    os.environ["DCN_LOCAL_HANDOFF"] = "0"
    try:
        ctxs = [dcn.DistDcnContext(n, r, addrs) for r in range(n)]
    finally:
        if before is None:
            os.environ.pop("DCN_LOCAL_HANDOFF", None)
        else:
            os.environ["DCN_LOCAL_HANDOFF"] = before
    for c in ctxs:
        c.init()
    return ctxs


def _backend(pipe, n_pages=24, page_size=4):
    return PagedKvBackend(pipe, n_pages, page_size,
                          registry=prom.Registry())


class _ShipPlane:
    """One decode rank + N in-process worker ranks over real sockets:
    the unit-test stand-in for the subprocess fleet (same frames, same
    transport, millisecond setup)."""

    def __init__(self, prefill_pipe, n_workers=1, start=True, **fleet_kw):
        self.ctxs = _make_contexts(1 + n_workers)
        self.workers = [PrefillWorkerLoop(prefill_pipe, self.ctxs[r])
                        for r in range(1, 1 + n_workers)]
        self.threads = []
        if start:
            for i, w in enumerate(self.workers):
                t = threading.Thread(target=w.run, daemon=True,
                                     name=f"test-prefill-w{i}")
                t.start()
                self.threads.append(t)
        fleet_kw.setdefault("registry", prom.Registry())
        fleet_kw.setdefault("lease_timeout_s", 30.0)
        self.fleet = RemotePrefillFleet(
            self.ctxs[0], ranks=range(1, 1 + n_workers),
            dtype=prefill_pipe.dtype, **fleet_kw)

    def close(self):
        self.fleet.close()
        for w in self.workers:
            w.stop()
        for t in self.threads:
            t.join(timeout=10)
        for c in self.ctxs:
            c.shutdown()


# ---------------------------------------------------------------------------
# protocol codec
# ---------------------------------------------------------------------------

def test_lease_and_ack_header_roundtrip():
    hdr = fleet_mod.lease_header(7, 2, 8, True, 1500.0)
    lease = fleet_mod.parse_lease_header(hdr)
    assert lease == {"lease_id": 7, "attempt": 2, "ship_bits": 8,
                     "crc": True, "deadline_ms": 1500}
    ack = fleet_mod.parse_ack_header(fleet_mod.ack_header(7, 2, 0))
    assert ack == {"lease_id": 7, "attempt": 2, "status": 0}
    with pytest.raises(ValueError, match="magic"):
        fleet_mod.parse_lease_header(np.asarray([1, 2, 3], np.int64))
    with pytest.raises(ValueError, match="magic"):
        fleet_mod.parse_ack_header(hdr)     # a lease is not an ack


# ---------------------------------------------------------------------------
# happy path: cross-context ship, token parity
# ---------------------------------------------------------------------------

def test_remote_prefill_token_parity(pipe, prefill_pipe):
    """Leases over real sockets produce token streams identical to solo
    dense generate(), greedy and sampled, on pinned seeds — the same
    gate the in-process fleet passes (test_kv_plane.py)."""
    plane = _ShipPlane(prefill_pipe)
    try:
        rng = np.random.default_rng(41)
        ids = rng.integers(0, 100, size=(1, 7))
        kv = _backend(pipe)
        batcher = ContinuousBatcher(pipe, kv=kv)
        batcher.submit("greedy", ids, new_tokens=6,
                       shipped=plane.fleet.prefill(ids, rid="greedy"))
        batcher.submit("sampled", ids, new_tokens=5, temperature=0.9,
                       seed=6,
                       shipped=plane.fleet.prefill(ids, rid="sampled"))
        results = batcher.run()
        np.testing.assert_array_equal(
            results["greedy"], np.asarray(pipe.generate(ids, 6)))
        np.testing.assert_array_equal(
            results["sampled"],
            np.asarray(pipe.generate(ids, 5, temperature=0.9, seed=6)))
        snap = plane.fleet.snapshot()
        assert snap["leases"]["shipped"] == 2
        assert snap["leases"]["fallback"] == 0
        assert snap["zombies_dropped_total"] == 0
    finally:
        plane.close()


# ---------------------------------------------------------------------------
# the ship fault matrix
# ---------------------------------------------------------------------------

def test_crc_corrupt_ship_bounded_resend_then_success(pipe, prefill_pipe):
    """CRC-corrupt ship frame -> bounded re-ship -> success: the first
    ack's KV payload takes a flipped bit (the chaos corrupt@K hook,
    below the integrity layer), decode_kv_ship raises WireCorruptError,
    and the fleet re-leases — the second, clean ship installs with full
    token parity."""
    plane = _ShipPlane(prefill_pipe, crc=True)
    try:
        # corrupt exactly the NEXT send from the worker: its first ack
        plane.ctxs[1].send_retries = 0
        plane.ctxs[1]._corrupt_next_send = True
        rng = np.random.default_rng(43)
        ids = rng.integers(0, 100, size=(1, 6))
        handle = plane.fleet.prefill(ids, rid="crc")
        kv = _backend(pipe)
        batcher = ContinuousBatcher(pipe, kv=kv)
        batcher.submit("crc", ids, new_tokens=5, shipped=handle)
        np.testing.assert_array_equal(
            batcher.run()["crc"], np.asarray(pipe.generate(ids, 5)))
        snap = plane.fleet.snapshot()
        assert snap["ship_corrupt_total"] == 1
        assert snap["leases"]["corrupt_retry"] == 1
        assert snap["leases"]["shipped"] == 1
    finally:
        plane.close()


def test_resend_exhaustion_degrades_to_colocated_with_parity(
        pipe, prefill_pipe):
    """Every ship corrupt -> retry budget exhausted -> the caller falls
    back to COLOCATED prefill (submit without `shipped`) and the tokens
    still match solo generate() exactly — the request survives, only
    the isolation degrades (the serving layer's PrefillUnavailable
    contract)."""
    plane = _ShipPlane(prefill_pipe, crc=True, max_attempts=2)
    try:
        wctx = plane.ctxs[1]
        orig_send = wctx.send_tensors

        def corrupt_every_send(dst, tensors, channel=0, **kw):
            wctx._corrupt_next_send = True
            return orig_send(dst, tensors, channel=channel, **kw)

        wctx.send_tensors = corrupt_every_send
        rng = np.random.default_rng(47)
        ids = rng.integers(0, 100, size=(1, 8))
        kw = {}
        try:
            kw["shipped"] = plane.fleet.prefill(ids, rid="exhaust")
        except PrefillUnavailable:
            pass        # the serving layer's colocated fallback
        assert "shipped" not in kw, "corrupt ships should have exhausted"
        kv = _backend(pipe)
        batcher = ContinuousBatcher(pipe, kv=kv)
        batcher.submit("exhaust", ids, new_tokens=4, **kw)
        np.testing.assert_array_equal(
            batcher.run()["exhaust"], np.asarray(pipe.generate(ids, 4)))
        snap = plane.fleet.snapshot()
        assert snap["leases"]["fallback"] == 1
        assert snap["ship_corrupt_total"] == 2      # one per attempt
    finally:
        plane.close()


def test_worker_death_mid_lease_redispatches_to_survivor(
        pipe, prefill_pipe):
    """Prefill-peer death: rank 1 swallows its lease (no ack) and dies;
    the fleet resolves the stranded lease IMMEDIATELY (no full timeout
    burn), re-dispatches to surviving rank 2, and the request completes
    token-identically."""
    plane = _ShipPlane(prefill_pipe, n_workers=2, start=False,
                       lease_timeout_s=60.0,
                       heartbeat_interval=0.3, heartbeat_miss=3)
    try:
        # workers beat the decode rank like the real CLI does — beat
        # SILENCE is how a black-holed peer's death is detectable at
        # all (it never sends data the decode reader could see drop)
        for wctx in plane.ctxs[1:]:
            wctx.start_heartbeat([0], interval=0.3, miss_threshold=10)
        # rank 1 black-holes leases (receives, never acks); rank 2 serves
        stop_hole = threading.Event()

        def black_hole():
            while not stop_hole.is_set():
                try:
                    plane.ctxs[1].recv_tensors(0, timeout=0.2,
                                               channel=fleet_mod.CH_LEASE)
                except (queue_mod.Empty, ConnectionError, OSError):
                    continue

        hole = threading.Thread(target=black_hole, daemon=True)
        hole.start()
        t2 = threading.Thread(target=plane.workers[1].run, daemon=True)
        t2.start()
        plane.threads.append(t2)
        # pin round-robin so the first dispatch lands on doomed rank 1
        plane.fleet._rr = 1
        rng = np.random.default_rng(53)
        ids = rng.integers(0, 100, size=(1, 6))
        killer_fired = threading.Event()

        def kill_rank1():
            # wait until the lease is in flight on rank 1, then die
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                snap = plane.fleet.snapshot()
                if snap["in_flight"] >= 1:
                    break
                time.sleep(0.05)
            time.sleep(0.2)     # let the black hole swallow the lease
            stop_hole.set()
            plane.ctxs[1].shutdown()
            killer_fired.set()

        killer = threading.Thread(target=kill_rank1, daemon=True)
        killer.start()
        t0 = time.monotonic()
        handle = plane.fleet.prefill(ids, rid="death")
        took = time.monotonic() - t0
        assert killer_fired.wait(timeout=30)
        # re-dispatch was driven by the death, not the 60s lease timeout
        assert took < 45.0
        kv = _backend(pipe)
        batcher = ContinuousBatcher(pipe, kv=kv)
        batcher.submit("death", ids, new_tokens=4, shipped=handle)
        np.testing.assert_array_equal(
            batcher.run()["death"], np.asarray(pipe.generate(ids, 4)))
        snap = plane.fleet.snapshot()
        assert snap["leases"]["redispatched"] >= 1
        assert snap["leases"]["shipped"] == 1
        assert 1 in snap["dead"]
    finally:
        plane.close()


def test_zombie_ack_stale_attempt_is_fenced():
    """A ship ack for a re-dispatched lease (stale attempt number) or an
    unknown lease must DROP, never resolve — the lease fence above the
    transport's epoch fence. Exercised against a stub context so the
    fence logic is isolated from socket timing."""

    class _StubCtx:
        CONNECT_TIMEOUT = 60.0

        def register_peer_death_handler(self, h):
            pass

        def register_peer_rejoin_handler(self, h):
            pass

        def stop_heartbeat(self):
            pass

        def recv_tensors(self, src, timeout=None, channel=0):
            raise queue_mod.Empty

    fleet = RemotePrefillFleet(_StubCtx(), ranks=[1], dtype=jnp.float32,
                               registry=prom.Registry())
    try:
        # unknown lease id: zombie
        fleet._resolve({"lease_id": 99, "attempt": 1, "status": 0}, [])
        assert fleet.snapshot()["zombies_dropped_total"] == 1
        # stale attempt: the lease moved on to attempt 2
        ls = fleet_mod._Lease(5, 2, 1, "r5")
        with fleet._lock:
            fleet._leases[5] = ls
        fleet._resolve({"lease_id": 5, "attempt": 1, "status": 0},
                       [np.zeros(3)])
        assert not ls.event.is_set(), "stale ack resolved a live lease"
        assert fleet.snapshot()["zombies_dropped_total"] == 2
        # the CURRENT attempt resolves normally
        fleet._resolve({"lease_id": 5, "attempt": 2, "status": 0},
                       [np.zeros(3)])
        assert ls.event.is_set() and ls.tensors is not None
        # ...and a second (duplicate) ack for the now-resolved lease is
        # a zombie again, not a double-resolution
        fleet._resolve({"lease_id": 5, "attempt": 2, "status": 0},
                       [np.ones(3)])
        assert fleet.snapshot()["zombies_dropped_total"] == 3
        np.testing.assert_array_equal(ls.tensors[0], np.zeros(3))
    finally:
        fleet.close()


def test_cancelled_lease_skipped_not_executed(prefill_pipe):
    """A lease the decode side cancelled (it timed out and was
    re-dispatched elsewhere) must be SKIPPED by the worker, not run
    into a zombie ack — cancels ride their own channel so they can
    overtake the stale lease they exist to stop."""
    ctxs = _make_contexts(2)
    calls = []

    class _Spy:
        cache_bits = 0
        dtype = prefill_pipe.dtype

        def _prefill(self, ids):
            calls.append(np.asarray(ids).shape)
            return prefill_pipe._prefill(ids)

    worker = PrefillWorkerLoop(_Spy(), ctxs[1])
    t = threading.Thread(target=worker.run, daemon=True)
    try:
        # cancel for lease 7 arrives BEFORE the lease (worker not yet
        # running, both frames queued), then lease 9 un-cancelled
        ctxs[0].send_tensors(1, [fleet_mod.cancel_header(7)],
                             channel=fleet_mod.CH_CANCEL)
        ctxs[0].send_tensors(
            1, [fleet_mod.lease_header(7, 1, 0, False, 1000),
                np.zeros((1, 4), np.int64)], channel=fleet_mod.CH_LEASE)
        ctxs[0].send_tensors(
            1, [fleet_mod.lease_header(9, 1, 0, False, 1000),
                np.ones((1, 5), np.int64)], channel=fleet_mod.CH_LEASE)
        t.start()
        # lease 9's ack arrives; lease 7 never produced one
        tensors = ctxs[0].recv_tensors(1, timeout=60.0,
                                       channel=fleet_mod.CH_SHIP)
        ack = fleet_mod.parse_ack_header(tensors[0])
        assert ack["lease_id"] == 9 and ack["status"] == fleet_mod.ACK_OK
        assert worker.leases_cancelled == 1
        assert calls == [(1, 5)], "the cancelled lease ran a prompt pass"
    finally:
        worker.stop()
        t.join(timeout=10)
        for c in ctxs:
            c.shutdown()


def test_worker_error_ack_counts_redispatch(pipe, prefill_pipe):
    """A worker that FAILS the prompt pass acks with an error status
    (silence would cost the full lease timeout); the fleet re-dispatches
    and, with no healthy alternative behavior, falls back."""
    plane = _ShipPlane(prefill_pipe, max_attempts=2)
    try:
        # poison the worker's prefill
        plane.workers[0].pipe = _Boom()
        with pytest.raises(PrefillUnavailable):
            plane.fleet.prefill(np.zeros((1, 4), np.int64), rid="boom")
        snap = plane.fleet.snapshot()
        # max_attempts=2: ONE re-dispatch actually happened (the final
        # failed attempt re-dispatches nothing — it falls back)
        assert snap["leases"]["redispatched"] == 1
        assert snap["leases"]["fallback"] == 1
    finally:
        plane.close()


class _Boom:
    cache_bits = 0

    def _prefill(self, ids):
        raise RuntimeError("poisoned prompt")


# ---------------------------------------------------------------------------
# chaos acceptance: kill a REAL prefill worker process mid-burst
# ---------------------------------------------------------------------------

@pytest.mark.fleet
def test_chaos_prefill_process_killed_midburst_all_requests_survive(
        pipe, tmp_path):
    """THE acceptance gate (ISSUE 15): two real prefill worker PROCESSES
    over DCN sockets; a burst of requests is in flight when one worker
    is SIGKILLed. Every request completes (re-dispatch to the survivor
    or colocated fallback), tokens are identical to solo runs on pinned
    seeds, and the page pool accounts for every page afterwards (zero
    leaks after the orphan sweep)."""
    world = 3
    addrs = [("127.0.0.1", p) for p in _free_ports(world)]
    addr_arg = ",".join(f"h:{p}".replace("h", "127.0.0.1")
                        for _, p in addrs)
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               DCN_CONNECT_TIMEOUT="30")
    workers = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tools",
                                          "prefill_worker.py"),
             str(r), str(world), "--dcn-addrs", addr_arg, "-m", MODEL,
             "-pt", "1,4,5,8", "--max-len", str(MAX_LEN),
             "-t", "float32", "--heartbeat-interval", "0.5"],
            env=env, text=True, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT)
        for r in (1, 2)]
    ctx = dcn.DistDcnContext(world, 0, addrs)
    ctx.init()
    # heartbeats make the SIGKILL detectable in ~2s (a killed worker
    # that never acked has no data conn whose drop rank 0 could see),
    # so leases leave the dead rank's rotation instead of each burning
    # the full lease timeout
    fleet = RemotePrefillFleet(ctx, ranks=[1, 2], dtype=pipe.dtype,
                               lease_timeout_s=10.0,
                               heartbeat_interval=0.5, heartbeat_miss=4,
                               registry=prom.Registry())
    kv = _backend(pipe, n_pages=24, page_size=4)
    batcher = ContinuousBatcher(pipe, kv=kv)
    try:
        rng = np.random.default_rng(59)
        prompts = [rng.integers(0, 100, size=(1, 6)) for _ in range(6)]
        lock = threading.Lock()
        shipped = {}

        def prefill_one(i):
            kw = {}
            try:
                kw["shipped"] = fleet.prefill(prompts[i], rid=f"b{i}")
            except PrefillUnavailable:
                pass       # colocated fallback: submit without shipped
            with lock:
                shipped[i] = kw

        threads = [threading.Thread(target=prefill_one, args=(i,),
                                    daemon=True) for i in range(6)]
        for t in threads[:2]:
            t.start()
        # let the first leases go out, then KILL worker rank 1 mid-burst
        time.sleep(0.3)
        os.kill(workers[0].pid, signal.SIGKILL)
        for t in threads[2:]:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive(), "a prefill call never returned"
        for i in range(6):
            batcher.submit(i, prompts[i], new_tokens=4,
                           **shipped.get(i, {}))
        results = batcher.run()
        assert len(results) == 6, "a request was lost to the fault"
        for i in range(6):
            np.testing.assert_array_equal(
                results[i], np.asarray(pipe.generate(prompts[i], 4)))
        # zero leaked pages: the orphan sweep (liveness = nothing live)
        # finds nothing to reclaim, and pool accounting closes exactly
        assert kv.sweep_orphans(set()) == 0
        assert kv.pool.stats()["leaked"] == 0
        cached = kv.trie.stats()["pages_cached"]
        assert kv.pool.free_pages + cached == kv.pool.n_pages
        snap = fleet.snapshot()
        assert 1 in snap["dead"]
        assert snap["leases"]["shipped"] >= 1
    finally:
        fleet.close()
        ctx.shutdown()
        for w in workers:
            if w.poll() is None:
                os.kill(w.pid, signal.SIGKILL)
            w.wait()
            w.stdout.close()
