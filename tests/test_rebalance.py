"""Closed-loop rebalancing: the measured-cost DP solver, the policy
guardrails (hysteresis / cooldown / failover composition), the telemetry
digest plane it runs on, the adaptive microbatch planner, and the
measured-profile emission path.

The fleet tests at the bottom drive the acceptance scenario end to end: a
loopback DCN fleet with one chaos-delayed stage must rebalance under
`--rebalance auto` (and show the event in the trace report), while a
balanced fleet must never churn."""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from pipeedge_tpu import telemetry
from pipeedge_tpu.parallel.pipeline import plan_microbatches
from pipeedge_tpu.sched import failover, profiles, rebalance
from pipeedge_tpu.telemetry import feedback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _est(stage, layer_s, emit_s, n=6):
    """A StageEstimate whose layer-proportional part splits evenly across
    dispatch/readback (how the DCN stage threads actually measure it)."""
    return feedback.StageEstimate(stage=stage, n=n, dispatch_s=layer_s / 2,
                                  readback_s=layer_s / 2, emit_s=emit_s)


# -- solver --------------------------------------------------------------

def test_solver_balanced_costs_even_split():
    part, bottleneck = rebalance.solve_partition([1.0] * 8, 2)
    assert part == [(1, 4), (5, 8)] and bottleneck == 4.0
    assert rebalance.solve_partition([1.0] * 12, 3)[0] == \
        [(1, 4), (5, 8), (9, 12)]


def test_solver_shifts_layers_off_expensive_region():
    # layers 1-2 cost 5x: stage 0 must carry fewer layers
    part, bottleneck = rebalance.solve_partition([5, 5, 1, 1, 1, 1, 1, 1], 2)
    assert part == [(1, 2), (3, 8)] and bottleneck == 10.0


def test_solver_fixed_costs_shrink_the_burdened_stage():
    # stage 1 pays a 10s per-microbatch fixed cost (slow link): the solver
    # hands it as few layers as possible — but knows it cannot remove the
    # fixed cost by handing it zero
    part, bottleneck = rebalance.solve_partition([1.0] * 8, 2,
                                                 fixed_costs=[0.0, 10.0])
    assert part == [(1, 7), (8, 8)] and bottleneck == 11.0


def test_solver_alignment_keeps_block_cuts():
    part, bottleneck = rebalance.solve_partition([3, 3, 3, 3, 1, 1, 1, 1],
                                                 2, align=4)
    assert part == [(1, 4), (5, 8)] and bottleneck == 12.0
    part16, _ = rebalance.solve_partition([1.0] * 16, 2,
                                          fixed_costs=[0.0, 5.0], align=4)
    for l, r in part16:
        assert (l - 1) % 4 == 0 and r % 4 == 0


def test_solver_rejects_impossible_splits():
    with pytest.raises(ValueError):
        rebalance.solve_partition([1.0] * 2, 3)
    with pytest.raises(ValueError):
        rebalance.solve_partition([1.0] * 6, 2, align=4)


def test_spread_layer_costs_uniform_per_stage():
    costs = rebalance.spread_layer_costs([(1, 2), (3, 8)], [4.0, 12.0])
    assert costs == [2.0, 2.0] + [2.0] * 6
    with pytest.raises(ValueError):
        rebalance.spread_layer_costs([(1, 2), (4, 8)], [1.0, 1.0])


def test_expand_partition_recuts_over_more_stages():
    """The healing side of the loop: a contracted partition re-expands
    over restored capacity with the same bottleneck-minimizing DP."""
    assert rebalance.expand_partition([(1, 8)], 2) == [(1, 4), (5, 8)]
    # measured costs steer the cut: a heavy tail gets the smaller range
    skewed = rebalance.expand_partition(
        [(1, 8)], 2, layer_costs=[1, 1, 1, 1, 1, 1, 4, 4])
    assert skewed[-1][0] > 5
    # alignment constraint (--stage-tp) holds through an expansion
    aligned = rebalance.expand_partition([(1, 16)], 2, align=4)
    for l, r in aligned:
        assert (l - 1) % 4 == 0 and r % 4 == 0


def test_expand_partition_rejects_non_expansions():
    with pytest.raises(ValueError):
        rebalance.expand_partition([(1, 4), (5, 8)], 2)   # not more stages
    with pytest.raises(ValueError):
        rebalance.expand_partition([], 2)
    with pytest.raises(ValueError):
        rebalance.expand_partition([(1, 8)], 2, layer_costs=[1.0] * 3)


# -- policy guardrails ---------------------------------------------------

def test_policy_balanced_fleet_is_noop():
    """Hysteresis: equal measured stages produce no proposal, ever."""
    pol = rebalance.RebalancePolicy(threshold=0.10, cooldown=1)
    part = [(1, 4), (5, 8)]
    ests = {0: _est(0, 0.040, 0.002), 1: _est(1, 0.040, 0.002)}
    for rnd in range(4):
        assert pol.consider(part, ests, rnd) is None
    assert pol.events == 0


def test_policy_slow_stage_shifts_layers_off_it():
    pol = rebalance.RebalancePolicy(threshold=0.10, cooldown=1, confirm=1)
    part = [(1, 4), (5, 8)]
    # stage 1: same compute, plus a 30ms per-microbatch emit stall
    ests = {0: _est(0, 0.040, 0.001), 1: _est(1, 0.040, 0.030)}
    # first actionable window: held for confirmation (one window of noise
    # must never re-partition the fleet)
    assert pol.consider(part, ests, 0) is None
    # the straggler persists: the second agreeing window acts
    prop = pol.consider(part, ests, 1)
    assert prop is not None and pol.events == 1
    assert prop.partition[0][1] > 4          # layers moved onto stage 0
    assert prop.gain >= 0.10
    assert prop.bottleneck_after_s < prop.bottleneck_before_s


def test_policy_confirmation_filters_flip_flopping_noise():
    """Windows that blame a DIFFERENT stage each round (drift noise, not
    a straggler) never accumulate the confirmation streak."""
    pol = rebalance.RebalancePolicy(threshold=0.05, cooldown=0, confirm=1)
    part = [(1, 4), (5, 8)]
    slow1 = {0: _est(0, 0.040, 0.001), 1: _est(1, 0.040, 0.030)}
    slow0 = {0: _est(0, 0.040, 0.030), 1: _est(1, 0.040, 0.001)}
    for rnd in range(6):
        assert pol.consider(part, slow1 if rnd % 2 == 0 else slow0,
                            rnd) is None
    assert pol.events == 0


def test_policy_min_gain_threshold_holds_partition():
    # a fixed 100ms stall dwarfs the 4ms of movable compute: predicted
    # relative gain is tiny, so a high threshold keeps the partition
    pol = rebalance.RebalancePolicy(threshold=0.10, cooldown=0, confirm=0)
    ests = {0: _est(0, 0.004, 0.001), 1: _est(1, 0.004, 0.100)}
    assert pol.consider([(1, 4), (5, 8)], ests, 0) is None
    assert pol.events == 0
    # the same measurements clear a permissive threshold
    pol2 = rebalance.RebalancePolicy(threshold=0.0, cooldown=0, confirm=0)
    assert pol2.consider([(1, 4), (5, 8)], ests, 0) is not None


def test_policy_cooldown_prevents_oscillation():
    pol = rebalance.RebalancePolicy(threshold=0.05, cooldown=2, confirm=0)
    part = [(1, 4), (5, 8)]
    slow1 = {0: _est(0, 0.040, 0.001), 1: _est(1, 0.040, 0.030)}
    prop = pol.consider(part, slow1, 0)
    assert prop is not None
    # next windows flip the imbalance (noise): cooldown holds the plan
    slow0 = {0: _est(0, 0.060, 0.030), 1: _est(1, 0.020, 0.001)}
    assert pol.consider(prop.partition, slow0, 1) is None
    assert pol.consider(prop.partition, slow0, 2) is None
    # cooldown expired: a persistent imbalance may act again
    assert pol.consider(prop.partition, slow0, 3) is not None
    assert pol.events == 2


def test_policy_settles_after_rebalancing():
    """Once the measured profile matches the new partition, re-solving
    reproduces it: no further proposals (convergence, not churn)."""
    pol = rebalance.RebalancePolicy(threshold=0.05, cooldown=0, confirm=0)
    prop = pol.consider([(1, 4), (5, 8)],
                        {0: _est(0, 0.040, 0.001),
                         1: _est(1, 0.040, 0.030)}, 0)
    assert prop is not None and prop.partition == [(1, 5), (6, 8)]
    settled = {0: _est(0, 0.050, 0.001), 1: _est(1, 0.030, 0.030)}
    assert pol.consider(prop.partition, settled, 1) is None
    assert pol.events == 1


def test_rebalance_composes_with_failover():
    """A death landing while a re-plan is pending: the failover planner
    must run on the PROPOSED partition (what the next round will
    broadcast) exactly as on a static one — spare substitution keeps the
    new cuts and moves the dead rank's stage to the spare."""
    pol = rebalance.RebalancePolicy(threshold=0.05, cooldown=0, confirm=0)
    prop = pol.consider([(1, 4), (5, 8)],
                        {0: _est(0, 0.040, 0.001),
                         1: _est(1, 0.040, 0.030)}, 0)
    assert prop is not None
    planned = failover.plan_failover(prop.partition, [0, 0], [0, 1],
                                     world_size=3, dead_ranks={1})
    assert planned is not None
    layers, quant, ranks = planned
    assert layers == prop.partition     # rebalanced cuts survive failover
    assert ranks == [0, 2]              # stage 1 moved onto the spare


# -- digest plane --------------------------------------------------------

def test_recorder_digest_accumulates_and_survives_ring_overflow():
    rec = telemetry.SpanRecorder(rank=0, capacity=4)
    for i in range(10):
        rec.record("stage", "dispatch", 0, 1000, stage=1)
    rec.record("feed", "mb0", 0, 500)          # not a digest category
    assert len(rec) == 4                       # ring dropped the oldest...
    dig = rec.digest()
    assert dig[("stage", "dispatch", 1)] == (10, 10_000)   # ...digest didn't
    assert ("feed", "mb0", None) not in dig


def test_digest_wire_roundtrip_and_diff():
    rec = telemetry.SpanRecorder(rank=2, capacity=16)
    rec.record("stage", "emit", 100, 400, stage=0)
    rec.record("wire", "send->r1", 0, 50)
    prev = rec.digest()
    assert telemetry.digest_from_wire(telemetry.digest_to_wire(prev)) == prev
    assert telemetry.digest_from_wire(np.zeros(0, np.uint8)) == {}
    rec.record("stage", "emit", 0, 100, stage=0)
    delta = feedback.diff_digests(rec.digest(), prev)
    assert delta == {("stage", "emit", 0): (1, 100)}
    # a restarted rank's fresh (smaller) counters fall back, never negative
    regressed = feedback.diff_digests(prev, rec.digest())
    assert all(n > 0 and ns >= 0 for n, ns in regressed.values())


def test_stage_estimates_from_merged_digests():
    d0 = {("stage", "dispatch", 0): (4, 8_000_000_000),
          ("stage", "readback", 0): (4, 4_000_000_000),
          ("stage", "emit", 0): (4, 2_000_000_000)}
    d1 = {("stage", "dispatch", 1): (4, 2_000_000_000),
          ("stage", "emit", 1): (4, 12_000_000_000),
          ("wire", "send->r0", None): (4, 1_000_000_000)}
    ests = feedback.stage_estimates(feedback.merge_digests([d0, d1]))
    assert ests[0].n == 4
    assert ests[0].layer_s == pytest.approx(3.0)   # (2 + 1) s/mb
    assert ests[0].fixed_s == pytest.approx(0.5)
    assert ests[1].service_s == pytest.approx(3.5)
    assert feedback.edge_estimates(d1) == {"send->r0": pytest.approx(0.25)}
    assert feedback.check_estimates(ests, 2) == []
    problems = feedback.check_estimates(ests, 3)
    assert any("stage 2" in p for p in problems)
    assert feedback.check_estimates(ests, 2, min_samples=5)
    assert feedback.check_estimates({0: ests[0]}, 1) == []
    stale = feedback.check_estimates({0: ests[0], 5: ests[1]}, 1)
    assert any("outside" in p for p in stale)


def test_digest_from_spans_matches_recorder_rollup():
    rec = telemetry.SpanRecorder(rank=0, capacity=64)
    rec.record("stage", "dispatch", 0, 3_000, stage=0)
    rec.record("compute", "stage0", 0, 1_000, stage=0)
    rec.record("runtime", "round0", 0, 9_000)      # not a digest category
    assert feedback.digest_from_spans(rec.snapshot()) == rec.digest()


# -- adaptive microbatch planner ----------------------------------------

def test_plan_microbatches_bubble_vs_overhead():
    # no per-microbatch overhead: finest split (bubble term dominates)
    u, m, _ = plan_microbatches(64, 4, t_item_s=0.01, t_fixed_s=0.0)
    assert (u, m) == (1, 64)
    # overhead dominates: one big microbatch
    u, m, _ = plan_microbatches(64, 4, t_item_s=1e-4, t_fixed_s=0.05)
    assert (u, m) == (64, 1)
    # single stage has no fill/drain bubble: overhead alone decides
    u, m, _ = plan_microbatches(64, 1, t_item_s=0.01, t_fixed_s=0.001)
    assert (u, m) == (64, 1)
    # the balanced case lands strictly between the extremes
    u, m, t = plan_microbatches(64, 4, t_item_s=0.01, t_fixed_s=0.01)
    assert 1 < u < 64 and m == -(-64 // u)
    assert t == pytest.approx((m + 3) * (0.01 + 0.01 * u))
    with pytest.raises(ValueError):
        plan_microbatches(0, 4, 0.01, 0.01)
    with pytest.raises(ValueError):
        plan_microbatches(8, 2, 0.01, 0.01, max_ubatch=0)


# -- measured-profile emission (sched/profiles.py ingestion) ------------

def test_measured_profiles_roundtrip_and_upsert(tmp_path):
    record = profiles.results_from_measured(
        "pipeedge/test-tiny-vit", "float32", 4, total_layers=8,
        partition=[(1, 6), (7, 8)], stage_times_s=[0.12, 0.08])
    times = [rec["time"] for rec in record["profile_data"]]
    assert times == pytest.approx([0.02] * 6 + [0.04] * 2)
    path = tmp_path / "live.yaml"
    profiles.save_measured_profiles(str(path), record)
    back = profiles.ProfilerResults.load(str(path))
    assert back.layers == 8 and back.batch_size == 4
    # the timing profile merges into a device_types.yml like any offline
    # profiler run (what "re-schedule from live measurements" consumes)
    dev_types = tmp_path / "device_types.yml"
    profiles.upsert_device_type(str(dev_types), "tpuv4", back,
                                mem_MB=1024, bw_Mbps=1000)
    import yaml
    loaded = yaml.safe_load(dev_types.read_text())
    prof = loaded["tpuv4"]["model_profiles"]["pipeedge/test-tiny-vit"][0]
    assert prof["time_s"] == pytest.approx(times)


def test_measured_profiles_reject_bad_partitions():
    with pytest.raises(profiles.ProfileError):
        profiles.results_from_measured("m", "float32", 4, total_layers=8,
                                       partition=[(1, 4)],
                                       stage_times_s=[1.0, 1.0])
    with pytest.raises(profiles.ProfileError):
        profiles.results_from_measured("m", "float32", 4, total_layers=9,
                                       partition=[(1, 4), (5, 8)],
                                       stage_times_s=[1.0, 1.0])


# -- fleet acceptance ----------------------------------------------------

def _free_ports(n):
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _run_rebalance_fleet(tmp_path, chaos, rebalance_mode="auto",
                         threshold=0.02):
    trace = tmp_path / "trace.json"
    addrs = ",".join(f"127.0.0.1:{p}" for p in _free_ports(2))
    common = [sys.executable, os.path.join(REPO, "runtime.py")]
    opts = ["-c", "dcn", "--platform", "cpu", "-m",
            "pipeedge/test-tiny-vit", "-pt", "1,4,5,8", "-b", "24",
            "-u", "4", "--dcn-addrs", addrs, "--sched-timeout", "120",
            "--rounds", "3", "--rebalance", rebalance_mode,
            "--rebalance-threshold", str(threshold),
            "--rebalance-cooldown", "0", "--trace-spans", str(trace)]
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               DCN_CONNECT_TIMEOUT="30")
    wenv = dict(env, DCN_CHAOS=chaos) if chaos else env
    worker = subprocess.Popen(common + ["1", "2"] + opts, cwd=tmp_path,
                              env=wenv, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
    try:
        data = subprocess.run(common + ["0", "2"] + opts, cwd=tmp_path,
                              env=env, capture_output=True, text=True,
                              timeout=300)
    finally:
        try:
            worker.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            worker.kill()
    assert data.returncode == 0, data.stdout + data.stderr
    rep = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         str(trace), "--require-spans"],
        capture_output=True, text=True, timeout=120)
    assert rep.returncode == 0, rep.stdout + rep.stderr
    return data.stdout + data.stderr, json.loads(rep.stdout)


@pytest.mark.fleet
@pytest.mark.slow
def test_fleet_rebalances_around_chaos_delayed_stage(tmp_path):
    """Acceptance: a chaos-delayed stage (delay@1:80 on rank 1's sends)
    makes the data rank re-solve the partition from the measured digests,
    shift layers OFF the slow rank at a round boundary, and finish all
    rounds; the merged trace records exactly the applied rebalances."""
    out, rep = _run_rebalance_fleet(tmp_path, chaos="delay@1:80")
    assert "rebalance_round=" in out
    # layers moved off the delayed stage: its range shrank below 4 layers
    import re
    part = re.search(r"rebalance_round=\d+ partition=(\d+),(\d+),(\d+),(\d+)",
                     out)
    assert part is not None, out
    l1, r1 = int(part.group(3)), int(part.group(4))
    assert r1 - l1 + 1 < 4, f"slow stage kept {r1 - l1 + 1} layers: {out}"
    assert rep["rebalance_events"] >= 1
    assert rep["bubble_pct"] is not None and rep["spans"] > 0


@pytest.mark.fleet
@pytest.mark.slow
def test_fleet_balanced_never_churns(tmp_path):
    """Zero-churn guard: the same fleet with NO injected slowness runs all
    rounds without a single rebalance event."""
    out, rep = _run_rebalance_fleet(tmp_path, chaos=None)
    assert "rebalance_round=" not in out
    assert rep["rebalance_events"] == 0
