"""Test configuration: run on a virtual 8-device CPU mesh.

Multi-"chip" testing story per SURVEY.md §4: tests run on CPU with
XLA_FLAGS=--xla_force_host_platform_device_count=8 so pipeline/mesh code is
exercised across 8 fake devices without TPU hardware. Must be set before the
first jax backend initialization, hence at conftest import time.

Lock-order witness (docs/STATIC_ANALYSIS.md): with PIPEEDGE_LOCKDEP=1 the
suite runs with `analysis/lockdep.py` tracking every `make_lock` site —
the tier-1 tests exercise the runtime's REAL lock interleavings, so a new
lock-order cycle or blocking-call-under-lock introduced by a PR is
witnessed here. Each witnessing process (this one and any spawned
runtime.py fleet rank, which inherits the env) appends a one-JSON-line
report to PIPEEDGE_LOCKDEP_OUT at exit; the CI gate asserts zero cycles.
"""
import os

# must precede the first pipeedge_tpu import: the witness activates when
# analysis/lockdep.py loads, and locks created before that are untracked
if os.getenv("PIPEEDGE_LOCKDEP") == "1" \
        and not os.getenv("PIPEEDGE_LOCKDEP_OUT"):
    os.environ["PIPEEDGE_LOCKDEP_OUT"] = os.path.abspath(
        "lockdep_report.json")

import jax  # noqa: E402

from pipeedge_tpu.utils import force_host_cpu_devices  # noqa: E402


def pytest_sessionfinish(session, exitstatus):
    """Print the lockdep verdict at the end of a witnessed run (the JSON
    line itself is appended by the module's atexit hook)."""
    from pipeedge_tpu.analysis import lockdep
    st = lockdep.state()
    if st is None:
        return
    rep = st.report()
    print(f"\nlockdep: {len(rep['locks'])} locks, {rep['edges']} order "
          f"edges, {rep['threads']} threads, "
          f"{len(rep['cycles'])} cycle(s), "
          f"{len(rep['blocking_violations'])} blocking-under-lock; "
          f"report -> {os.getenv('PIPEEDGE_LOCKDEP_OUT')}")
    if rep["cycles"]:
        print(f"lockdep CYCLES: {rep['cycles']}")

# The axon TPU plugin registers itself via sitecustomize and overrides
# JAX_PLATFORMS; the helper forces the CPU backend explicitly so the 8 fake
# devices apply. Must run before the first backend initialization.
force_host_cpu_devices(8)

# XLA CPU's default matmul precision is reduced (bf16-like passes); golden
# parity tests against torch float32 need full fp32 accumulation.
jax.config.update("jax_default_matmul_precision", "highest")
