"""Test configuration: run on a virtual 8-device CPU mesh.

Multi-"chip" testing story per SURVEY.md §4: tests run on CPU with
XLA_FLAGS=--xla_force_host_platform_device_count=8 so pipeline/mesh code is
exercised across 8 fake devices without TPU hardware. Must be set before the
first jax backend initialization, hence at conftest import time.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (must come after the env setup above)

# The axon TPU plugin registers itself via sitecustomize and overrides
# JAX_PLATFORMS; force the CPU backend explicitly so the 8 fake devices apply.
jax.config.update("jax_platforms", "cpu")

# XLA CPU's default matmul precision is reduced (bf16-like passes); golden
# parity tests against torch float32 need full fp32 accumulation.
jax.config.update("jax_default_matmul_precision", "highest")
