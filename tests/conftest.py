"""Test configuration: run on a virtual 8-device CPU mesh.

Multi-"chip" testing story per SURVEY.md §4: tests run on CPU with
XLA_FLAGS=--xla_force_host_platform_device_count=8 so pipeline/mesh code is
exercised across 8 fake devices without TPU hardware. Must be set before the
first jax backend initialization, hence at conftest import time.
"""
import jax

from pipeedge_tpu.utils import force_host_cpu_devices

# The axon TPU plugin registers itself via sitecustomize and overrides
# JAX_PLATFORMS; the helper forces the CPU backend explicitly so the 8 fake
# devices apply. Must run before the first backend initialization.
force_host_cpu_devices(8)

# XLA CPU's default matmul precision is reduced (bf16-like passes); golden
# parity tests against torch float32 need full fp32 accumulation.
jax.config.update("jax_default_matmul_precision", "highest")
