"""Reverse-auction scheduler tests (library + CLI).

The reference ships these schedulers untested (SURVEY.md §4); here the DAG
schedulers are validated against hand-computed optima on small instances.
"""
import os
import subprocess
import sys

import pytest
import yaml

from pipeedge_tpu.sched import revauct, yaml_types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DTYPE = 'torch.float32'
UB = 1


def _model(n, params_out=100, mem_mb=1.0):
    return {'layers': n, 'parameters_in': 100,
            'parameters_out': [params_out] * n, 'mem_MB': [mem_mb] * n}


def _dev_type(mem_mb, bw, time_s):
    return yaml_types.yaml_device_type(
        mem_mb, bw, {'m': [yaml_types.yaml_model_profile(DTYPE, UB, time_s)]})


def _bids_for(model, dev_types, neighbors):
    """host -> (shard bid dict, neighbor links)."""
    out = {}
    for host, tname in dev_types.items():
        prof = tname['model_profiles']['m'][0]
        shard_bids = dict(revauct.bid_latency(model, tname, prof, UB, DTYPE))
        out[host] = (shard_bids, neighbors.get(host, {}))
    return out


def test_bid_latency_memory_filter():
    model = _model(4, mem_mb=100.0)
    small = _dev_type(250, 1000, [0.1] * 4)   # fits at most 2 layers
    bids = revauct.bid_latency(model, small, small['model_profiles']['m'][0], UB)
    assert bids
    assert all(r - l <= 1 for (l, r), _ in bids)
    big = _dev_type(100000, 1000, [0.1] * 4)
    bids = revauct.bid_latency(model, big, big['model_profiles']['m'][0], UB)
    assert ((0, 3), pytest.approx(0.4)) in [(s, c) for s, c in bids]


def test_filter_bids_chunk_and_largest():
    model = _model(8)
    bids = {(0, 3): 1.0, (0, 7): 2.0, (1, 3): 0.5, (4, 7): 1.0, (4, 5): 0.6,
            (0, 5): 1.5}
    chunked = revauct.filter_bids_chunk(model, bids, chunk=4)
    assert set(chunked) == {(0, 3), (0, 7), (4, 7)}
    largest = revauct.filter_bids_largest(bids)
    assert set(largest) == {(0, 7), (1, 3), (4, 7)}


def test_greedy_host_count():
    model = _model(4, mem_mb=100.0)
    types = {'a': _dev_type(250, 1000, [0.1] * 4),
             'b': _dev_type(250, 1000, [0.2] * 4),
             'c': _dev_type(250, 1000, [0.3] * 4)}
    neighbors = {h: {o: {'bw_Mbps': 1000} for o in types if o != h}
                 for h in types}
    bids = _bids_for(model, types, neighbors)
    sched = revauct.sched_greedy_host_count(model, UB, DTYPE, bids, 'a', 'a')
    covered = []
    for stage in sched:
        for _, layers in stage.items():
            if layers:
                covered.extend(range(layers[0], layers[1] + 1))
    assert covered == list(range(4))
    # data host 'a' holds the first shard
    assert list(sched[0].keys()) == ['a']


def test_optimal_latency_picks_fast_path():
    """Two orderings of the same 2-layer model: the optimum must assign the
    whole model to the fast device when memory allows."""
    model = _model(2, mem_mb=1.0)
    types = {'fast': _dev_type(1024, 1000, [0.01, 0.01]),
             'slow': _dev_type(1024, 1000, [10.0, 10.0])}
    neighbors = {'fast': {'slow': {'bw_Mbps': 1000}},
                 'slow': {'fast': {'bw_Mbps': 1000}}}
    bids = _bids_for(model, types, neighbors)
    sched, cost = revauct.sched_optimal_latency_dev_order(
        model, UB, DTYPE, bids, 'fast', 'fast', ['fast', 'slow'],
        strict_order=True, strict_first=False, strict_last=False)
    assert sched == [{'fast': [0, 1]}]
    assert cost == pytest.approx(0.02)


def test_optimal_latency_splits_when_memory_forces():
    model = _model(4, mem_mb=100.0)
    types = {'h0': _dev_type(250, 1000, [0.1] * 4),
             'h1': _dev_type(250, 1000, [0.1] * 4),
             'h2': _dev_type(250, 1000, [0.1] * 4)}
    neighbors = {h: {o: {'bw_Mbps': 1000} for o in types if o != h}
                 for h in types}
    bids = _bids_for(model, types, neighbors)
    sched, cost = revauct.sched_optimal_latency_dev_order(
        model, UB, DTYPE, bids, 'h0', 'h0', ['h0', 'h1', 'h2'],
        strict_order=False, strict_first=False, strict_last=False)
    covered = []
    for stage in sched:
        for _, layers in stage.items():
            if layers:
                covered.extend(range(layers[0], layers[1] + 1))
    assert covered == list(range(4))
    assert cost < float('inf')


def test_optimal_throughput_minimizes_bottleneck():
    """Throughput objective must prefer even stage split over uneven."""
    model = _model(4, mem_mb=100.0)
    types = {'h0': _dev_type(350, 1000, [0.1] * 4),   # fits up to 3 layers
             'h1': _dev_type(350, 1000, [0.1] * 4)}
    neighbors = {h: {o: {'bw_Mbps': 100000} for o in types if o != h}
                 for h in types}
    bids = _bids_for(model, types, neighbors)
    sched, tput = revauct.sched_optimal_throughput_dev_order(
        model, UB, DTYPE, bids, 'h0', 'h0', ['h0', 'h1'],
        strict_order=True, strict_first=False, strict_last=False)
    stages = [layers for stage in sched for _, layers in stage.items() if layers]
    sizes = sorted(r - l + 1 for l, r in stages)
    assert sizes == [2, 2]          # even split: bottleneck 0.2s
    assert tput == pytest.approx(1 / 0.2, rel=1e-6)


def test_no_path_returns_empty():
    model = _model(2, mem_mb=10000.0)  # doesn't fit anywhere
    types = {'h0': _dev_type(100, 1000, [0.1, 0.1])}
    bids = _bids_for(model, types, {'h0': {}})
    sched, cost = revauct.sched_optimal_latency_dev_order(
        model, UB, DTYPE, bids, 'h0', 'h0', ['h0'])
    assert sched == []
    assert cost == float('inf')


@pytest.mark.fleet
def test_revauct_cli(tmp_path):
    n = 8
    models = {"pipeedge/test-tiny-vit": {
        "layers": n, "parameters_in": 768, "parameters_out": [1000] * n,
        "mem_MB": [50.0] * n}}
    types = {"chip": {"mem_MB": 300, "bw_Mbps": 10000, "model_profiles": {
        "pipeedge/test-tiny-vit": [{"dtype": DTYPE, "batch_size": 2,
                                    "time_s": [0.01] * n}]}}}
    devs = {"chip": ["c0", "c1", "c2"]}
    neighbors = {h: {o: {"bw_Mbps": 10000} for o in ["c0", "c1", "c2"] if o != h}
                 for h in ["c0", "c1", "c2"]}
    for fname, data in (("models.yml", models), ("device_types.yml", types),
                        ("devices.yml", devs),
                        ("device_neighbors_world.yml", neighbors)):
        with open(tmp_path / fname, "w") as f:
            yaml.safe_dump(data, f, default_flow_style=None)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "revauct.py"), "0", "3",
         "-m", "pipeedge/test-tiny-vit", "-u", "2", "--seed", "0",
         "-sch", "throughput_ordered", "--no-strict-order"],
        capture_output=True, env=env, cwd=str(tmp_path), text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    sched = yaml.safe_load(proc.stdout)
    covered = []
    for stage in sched:
        for _, layers in stage.items():
            if layers:
                covered.extend(range(layers[0], layers[1] + 1))
    assert covered == list(range(1, n + 1))  # 1-based in CLI output


@pytest.mark.fleet
def test_revauct_distributed_dcn_matches_centralized(tmp_path):
    """Distributed auction over the DCN command plane (reference deployment,
    revauct.py:168-180): one process per rank, each bidding ONLY from its own
    local device_types file. The resulting schedule must equal the
    centralized (--comm local) run over the same fleet and seed."""
    import socket as socket_mod
    n = 8
    hosts = ["c0", "c1", "c2"]
    times = {"c0": 0.01, "c1": 0.02, "c2": 0.04}  # heterogeneous fleet
    models = {"pipeedge/test-tiny-vit": {
        "layers": n, "parameters_in": 768, "parameters_out": [1000] * n,
        "mem_MB": [50.0] * n}}
    neighbors = {h: {o: {"bw_Mbps": 10000} for o in hosts if o != h}
                 for h in hosts}

    def dev_type(host):
        return {f"type-{host}": {
            "mem_MB": 300, "bw_Mbps": 10000, "model_profiles": {
                "pipeedge/test-tiny-vit": [{
                    "dtype": DTYPE, "batch_size": 2,
                    "time_s": [times[host]] * n}]}}}

    # centralized fixture: every type in one file + devices.yml
    central = tmp_path / "central"
    central.mkdir()
    all_types = {}
    for h in hosts:
        all_types.update(dev_type(h))
    devs = {f"type-{h}": [h] for h in hosts}
    for fname, data in (("models.yml", models), ("device_types.yml", all_types),
                        ("devices.yml", devs),
                        ("device_neighbors_world.yml", neighbors)):
        with open(central / fname, "w") as f:
            yaml.safe_dump(data, f, default_flow_style=None)
    # per-rank fixtures: each rank dir has ONLY its own device type
    for r, h in enumerate(hosts):
        d = tmp_path / f"rank{r}"
        d.mkdir()
        for fname, data in (("models.yml", models),
                            ("device_types.yml", dev_type(h)),
                            ("device_neighbors_world.yml", neighbors)):
            with open(d / fname, "w") as f:
                yaml.safe_dump(data, f, default_flow_style=None)

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    base = [sys.executable, os.path.join(REPO, "revauct.py")]
    sched_args = ["-m", "pipeedge/test-tiny-vit", "-u", "2", "--seed", "7",
                  "-sch", "throughput_ordered", "--no-strict-order"]

    central_proc = subprocess.run(
        base + ["0", "3"] + sched_args, capture_output=True, env=env,
        cwd=str(central), text=True, timeout=120)
    assert central_proc.returncode == 0, central_proc.stderr

    socks = [socket_mod.create_server(("127.0.0.1", 0)) for _ in range(3)]
    addrs = ",".join(f"127.0.0.1:{s.getsockname()[1]}" for s in socks)
    for s in socks:
        s.close()
    dcn_args = sched_args + ["-c", "dcn", "--dcn-addrs", addrs]
    bidders = [subprocess.Popen(
        base + [str(r), "3"] + dcn_args +
        ["--host", hosts[r], "--dev-type", f"type-{hosts[r]}"],
        cwd=str(tmp_path / f"rank{r}"), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True) for r in (1, 2)]
    try:
        auctioneer = subprocess.run(
            base + ["0", "3"] + dcn_args +
            ["--host", hosts[0], "--dev-type", "type-c0"],
            capture_output=True, env=env, cwd=str(tmp_path / "rank0"),
            text=True, timeout=200)
        bouts = [b.communicate(timeout=30)[0] for b in bidders]
    finally:
        for b in bidders:
            b.kill()
    assert auctioneer.returncode == 0, auctioneer.stdout + auctioneer.stderr
    for b, bout in zip(bidders, bouts):
        assert b.returncode == 0, bout

    central_sched = yaml.safe_load(central_proc.stdout)
    dcn_sched = yaml.safe_load(auctioneer.stdout)
    assert dcn_sched == central_sched
    # sanity: the schedule covers all layers and uses the fast host
    covered = []
    for stage in dcn_sched:
        for _, layers in stage.items():
            if layers:
                covered.extend(range(layers[0], layers[1] + 1))
    assert covered == list(range(1, n + 1))


@pytest.mark.fleet
def test_revauct_dcn_missing_bidder_releases_fleet(tmp_path):
    """A bidder that never shows up must not hang the auction: the
    auctioneer fails fast (broadcast undeliverable) or after
    --auction-timeout (connected but silent), and either way releases the
    live bidders via CMD_STOP."""
    import socket as socket_mod
    n = 8
    models = {"pipeedge/test-tiny-vit": {
        "layers": n, "parameters_in": 768, "parameters_out": [1000] * n,
        "mem_MB": [50.0] * n}}
    types = {"t0": {"mem_MB": 300, "bw_Mbps": 10000, "model_profiles": {
        "pipeedge/test-tiny-vit": [{"dtype": DTYPE, "batch_size": 2,
                                    "time_s": [0.01] * n}]}}}
    neighbors = {h: {o: {"bw_Mbps": 10000} for o in ("c0", "c1", "c2")
                     if o != h} for h in ("c0", "c1", "c2")}
    for r in range(2):
        d = tmp_path / f"rank{r}"
        d.mkdir()
        for fname, data in (("models.yml", models),
                            ("device_types.yml", types),
                            ("device_neighbors_world.yml", neighbors)):
            with open(d / fname, "w") as f:
                yaml.safe_dump(data, f, default_flow_style=None)
    socks = [socket_mod.create_server(("127.0.0.1", 0)) for _ in range(3)]
    addrs = ",".join(f"127.0.0.1:{s.getsockname()[1]}" for s in socks)
    for s in socks:
        s.close()
    # short dial deadline: the auctioneer must fail fast on the absent
    # rank and still have time to release the live bidder
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               DCN_CONNECT_TIMEOUT="5")
    base = [sys.executable, os.path.join(REPO, "revauct.py")]
    opts = ["-m", "pipeedge/test-tiny-vit", "-u", "2", "-c", "dcn",
            "--dcn-addrs", addrs, "--auction-timeout", "120"]
    # rank 2 never starts
    bidder = subprocess.Popen(
        base + ["1", "3"] + opts + ["--host", "c1", "--dev-type", "t0"],
        cwd=str(tmp_path / "rank1"), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        auctioneer = subprocess.run(
            base + ["0", "3"] + opts + ["--host", "c0", "--dev-type", "t0"],
            capture_output=True, env=env, cwd=str(tmp_path / "rank0"),
            text=True, timeout=200)
        bout = bidder.communicate(timeout=60)[0]
    finally:
        bidder.kill()
    assert auctioneer.returncode != 0
    out = auctioneer.stdout + auctioneer.stderr
    # unreachable at broadcast time -> fast "undeliverable" failure; a rank
    # that connects but never bids -> "no bid from rank" after the timeout
    assert "undeliverable" in out or "no bid from rank 2" in out, out
    # the live bidder was RELEASED by the auctioneer's CMD_STOP — not its
    # own --auction-timeout (the release marker only logs on a True wait)
    assert bidder.returncode == 0, bout
    assert "released by auctioneer" in bout, bout
