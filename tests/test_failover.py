"""Failover building blocks, in-process: the data rank's microbatch ledger
(exactly-once, in-order delivery, replay set), the survivor re-scheduling
cascade (sched/failover.py), and the chaos spec grammar."""
import numpy as np
import pytest

from pipeedge_tpu.comm import chaos
from pipeedge_tpu.sched import failover


# -- microbatch ledger -------------------------------------------------

def _make_ledger(n=4):
    import runtime as rt
    ubatches = [np.full((2, 3), i, np.float32) for i in range(n)]
    labels = [np.asarray([i, i]) for i in range(n)]
    return rt, rt._MicrobatchLedger(ubatches, labels)


def test_ledger_in_order_delivery_and_replay_set():
    rt, ledger = _make_ledger(4)
    delivered = []
    orig = rt.handle_results
    rt.handle_results = lambda out: delivered.append(np.asarray(out))
    try:
        assert [i for i, _ in ledger.pending()] == [0, 1, 2, 3]
        # out-of-order arrival: 1 before 0 — delivery holds until contiguous
        assert ledger.ack(1, np.full((2,), 1.0))
        assert delivered == [] and not ledger.done.is_set()
        assert ledger.ack(0, np.full((2,), 0.0))
        assert [float(d[0]) for d in delivered] == [0.0, 1.0]
        # the replay set is exactly the unacknowledged tail
        assert [i for i, _ in ledger.pending()] == [2, 3]
        assert ledger.ack(3, np.full((2,), 3.0))
        assert ledger.ack(2, np.full((2,), 2.0))
        assert [float(d[0]) for d in delivered] == [0.0, 1.0, 2.0, 3.0]
        assert ledger.done.is_set()
    finally:
        rt.handle_results = orig
        while not rt.label_queue.empty():
            rt.label_queue.get()


def test_ledger_dedupes_replay_overlap():
    rt, ledger = _make_ledger(2)
    delivered = []
    orig = rt.handle_results
    rt.handle_results = lambda out: delivered.append(np.asarray(out))
    try:
        assert ledger.ack(0, np.zeros(2))
        # a replayed microbatch whose original result was in flight: dropped
        assert not ledger.ack(0, np.ones(2))
        assert not ledger.ack(99, np.ones(2))   # out-of-range id: dropped
        assert ledger.ack(1, np.ones(2))
        assert len(delivered) == 2 and ledger.done.is_set()
        assert ledger.acked_count == 2
    finally:
        rt.handle_results = orig
        while not rt.label_queue.empty():
            rt.label_queue.get()


def test_ledger_empty_batch_is_done():
    import runtime as rt
    assert rt._MicrobatchLedger([], []).done.is_set()


def test_ledger_dedupe_keys_include_epoch():
    """Epoch-aware dedupe: the accepted ack records which incarnation
    produced it, and a duplicate is dropped regardless of the epoch it
    claims (exactly-once is by microbatch id; the epoch is the forensic
    key distinguishing a resend from a stale-incarnation replay)."""
    rt, ledger = _make_ledger(2)
    orig = rt.handle_results
    rt.handle_results = lambda out: None
    try:
        assert ledger.ack(0, np.zeros(2), epoch=0)
        assert not ledger.ack(0, np.ones(2), epoch=1)   # dup, any epoch
        assert ledger.ack(1, np.ones(2), epoch=2)
        assert ledger.acked_epochs() == {0: 0, 1: 2}
    finally:
        rt.handle_results = orig
        while not rt.label_queue.empty():
            rt.label_queue.get()


def test_ledger_fences_stale_incarnation_acks():
    """A result signed by an incarnation below the per-source fence floor
    must never acknowledge a microbatch — the ledger-level mirror of the
    transport's reader fence."""
    rt, ledger = _make_ledger(2)
    orig = rt.handle_results
    rt.handle_results = lambda out: None
    try:
        ledger.fence_rank(3, 1)
        assert not ledger.ack(0, np.zeros(2), epoch=0, src=3)   # stale
        assert ledger.stale_dropped == 1
        assert [i for i, _ in ledger.pending()] == [0, 1]       # unacked
        # the NEW incarnation's replay of the same microbatch lands
        assert ledger.ack(0, np.zeros(2), epoch=1, src=3)
        # other sources are not fenced by rank 3's floor
        assert ledger.ack(1, np.ones(2), epoch=0, src=2)
        assert ledger.done.is_set()
    finally:
        rt.handle_results = orig
        while not rt.label_queue.empty():
            rt.label_queue.get()


# -- survivor re-scheduling --------------------------------------------

_LAYERS = [(1, 4), (5, 8)]


def test_failover_substitutes_spare_rank():
    planned = failover.plan_failover(_LAYERS, [8, 0], [0, 1],
                                     world_size=4, dead_ranks={1})
    assert planned is not None
    layers, quant, ranks = planned
    assert layers == _LAYERS and quant == [8, 0]
    assert ranks == [0, 2]           # stage 1 moved to the lowest spare


def test_failover_no_spare_returns_none():
    assert failover.plan_failover(_LAYERS, [0, 0], [0, 1],
                                  world_size=2, dead_ranks={1}) is None


def test_failover_dead_idle_rank_keeps_schedule():
    planned = failover.plan_failover(_LAYERS, [0, 0], [0, 1],
                                     world_size=4, dead_ranks={3})
    assert planned == (_LAYERS, [0, 0], [0, 1])


def test_failover_multiple_deaths_multiple_spares():
    planned = failover.plan_failover(_LAYERS, [0, 0], [0, 1],
                                     world_size=5, dead_ranks={0, 1})
    assert planned is not None
    assert planned[2] == [2, 3]


def test_failover_scheduler_fn_ranks_remap_to_survivors():
    def scheduler_fn(n_survivors):
        assert n_survivors == 3
        return [(1, 8)], [0], [2]    # index 2 INTO the survivor list
    planned = failover.plan_failover(_LAYERS, [0, 0], [0, 1],
                                     world_size=4, dead_ranks={1},
                                     scheduler_fn=scheduler_fn)
    # survivors are [0, 2, 3]; survivor index 2 is fleet rank 3
    assert planned == ([(1, 8)], [0], [3])


def test_failover_scheduler_fn_failure_falls_through_to_spares():
    def scheduler_fn(_n):
        raise RuntimeError("no profiles")
    planned = failover.plan_failover(_LAYERS, [0, 0], [0, 1],
                                     world_size=3, dead_ranks={1},
                                     scheduler_fn=scheduler_fn)
    assert planned is not None and planned[2] == [0, 2]


def test_failover_benched_rank_loses_stage_prefers_fresh_spare():
    """A benched rank (rejoined under --on-peer-rejoin spare) is alive
    but must not keep its scheduled stage; a fresh spare takes it."""
    planned = failover.plan_failover(_LAYERS, [0, 0], [0, 1],
                                     world_size=4, dead_ranks=set(),
                                     benched={1})
    assert planned is not None and planned[2] == [0, 2]


def test_failover_benched_rank_is_last_resort_spare():
    """When the benched rank is the ONLY idle capacity it is used anyway
    — running on a benched rank beats aborting."""
    planned = failover.plan_failover(_LAYERS, [0, 0], [0, 1],
                                     world_size=2, dead_ranks=set(),
                                     benched={1})
    assert planned is not None and planned[2] == [0, 1]


# -- rejoin / heal planning --------------------------------------------

def test_plan_rejoin_restores_pre_failure_schedule():
    """The original rank rejoined: the pre-failure schedule comes back
    verbatim (partition + quant + placement -> bit-identical numerics)."""
    pre = (_LAYERS, [8, 0], [0, 1])
    cur = (_LAYERS, [8, 0], [0, 2])      # failed over onto the spare
    planned = failover.plan_rejoin(cur, pre, world_size=4,
                                   dead_ranks=set())
    assert planned == (_LAYERS, [8, 0], [0, 1])


def test_plan_rejoin_waits_while_pre_failure_rank_still_dead():
    pre = (_LAYERS, [0, 0], [0, 1])
    cur = (_LAYERS, [0, 0], [0, 2])
    # rank 1 is still dead and no contraction happened: nothing to heal
    assert failover.plan_rejoin(cur, pre, world_size=3,
                                dead_ranks={1}) is None


def test_plan_rejoin_expands_contracted_partition():
    """The failover contracted to fewer stages (scheduler re-solve); the
    rejoined capacity re-expands the span via the rebalance DP."""
    pre = (_LAYERS, [0, 0], [0, 1])
    cur = ([(1, 8)], [0], [0])           # contracted to one stage
    planned = failover.plan_rejoin(cur, pre, world_size=2,
                                   dead_ranks=set())
    # restore path applies (both pre-failure ranks alive)
    assert planned == (_LAYERS, [0, 0], [0, 1])
    # without a restorable pre-failure schedule: genuine re-expansion
    planned = failover.plan_rejoin(cur, None, world_size=2,
                                   dead_ranks=set())
    assert planned is not None
    layers, quant, ranks = planned
    assert len(layers) == 2 and layers[0][0] == 1 and layers[-1][1] == 8
    assert layers[0][1] + 1 == layers[1][0]      # contiguous cut
    assert ranks == [0, 1] and quant == [0, 0]


def test_plan_rejoin_no_spares_returns_none():
    cur = ([(1, 8)], [0], [0])
    assert failover.plan_rejoin(cur, None, world_size=2,
                                dead_ranks={1}) is None


# -- chaos spec grammar ------------------------------------------------

def test_chaos_spec_parse():
    spec = chaos.ChaosSpec.parse("kill@3; delay@1:250; drop@7")
    kinds = [(a.kind, a.at_send) for a in spec.actions]
    assert kinds == [("kill", 3), ("delay", 1), ("drop", 7)]
    assert spec.actions[1].delay_ms == 250.0


def test_chaos_spec_parse_restart_and_flap():
    spec = chaos.ChaosSpec.parse("restart@3:2000; flap@2:500")
    assert [(a.kind, a.at_send, a.delay_ms) for a in spec.actions] == [
        ("restart", 3, 2000.0), ("flap", 2, 500.0)]


@pytest.mark.parametrize("bad", ["explode@3", "kill@x", "delay@2:abc",
                                 "restart@x:5", "flap@2:zz"])
def test_chaos_spec_rejects_bad_clauses(bad):
    with pytest.raises(ValueError, match="DCN_CHAOS"):
        chaos.ChaosSpec.parse(bad)


def test_chaos_drop_and_delay_wrap(monkeypatch):
    """The wrapper swallows exactly the dropped send and delays from the
    armed index on, forwarding everything else untouched."""
    sent = []

    class _Ctx:
        def send_tensors(self, dst, tensors, channel=0, trace=None):
            sent.append((dst, channel))

    ctx = _Ctx()
    monkeypatch.setenv(chaos.ENV_CHAOS, "drop@2")
    spec = chaos.maybe_install(ctx)
    assert spec is not None
    for i in range(4):
        ctx.send_tensors(1, [np.zeros(1)], channel=i)
    assert [c for _, c in sent] == [0, 2, 3]      # send #2 swallowed


def test_chaos_env_unset_is_noop(monkeypatch):
    monkeypatch.delenv(chaos.ENV_CHAOS, raising=False)

    class _Ctx:
        send_tensors = staticmethod(lambda *a, **k: None)

    assert chaos.maybe_install(_Ctx()) is None
