"""Orbax checkpoint utilities: round-trip, sharded restore, per-stage
slicing parity with the npz loader."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipeedge_tpu.models import registry
from pipeedge_tpu.utils import checkpoint as ckpt


@pytest.fixture(scope="module")
def tiny_params():
    name = "pipeedge/test-tiny-vit"
    entry = registry.get_model_entry(name)
    sc = registry.make_shard_config(name, 1, registry.get_model_layers(name))
    return name, entry.family.init_params(entry.config, sc, seed=5)


def _assert_trees_equal(a, b):
    la = jax.tree_util.tree_leaves_with_path(a)
    lb = dict(jax.tree_util.tree_leaves_with_path(b))
    assert len(la) == len(lb)
    for path, leaf in la:
        np.testing.assert_array_equal(np.asarray(leaf),
                                      np.asarray(lb[path]), err_msg=str(path))


def test_roundtrip(tmp_path, tiny_params):
    _, params = tiny_params
    ckpt.save_params(str(tmp_path / "ck"), params)
    restored = ckpt.load_params(str(tmp_path / "ck"))
    _assert_trees_equal(params, restored)


def test_restore_with_sharding(tmp_path, tiny_params):
    """Replicated NamedSharding restore across the 8 fake devices."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    _, params = tiny_params
    ckpt.save_params(str(tmp_path / "ck"), params)
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("x",))
    sharding = NamedSharding(mesh, P())
    restored = ckpt.load_params(str(tmp_path / "ck"), shardings=sharding)
    leaf = jax.tree_util.tree_leaves(restored)[0]
    assert leaf.sharding == sharding
    _assert_trees_equal(params, restored)


@pytest.mark.fleet
def test_stage_checkpoints_match_npz_loader(tmp_path):
    """Per-stage orbax checkpoints hold exactly what module_shard_factory
    loads from the npz for the same partition."""
    name = "pipeedge/test-tiny-vit"
    npz = tmp_path / "w.npz"
    # random-init weights in the reference npz format
    import subprocess
    import sys
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "save_model_weights.py"),
         "--random", "-m", name, "-o", str(tmp_path)],
        capture_output=True, text=True, env=dict(os.environ, PYTHONPATH=repo))
    assert r.returncode == 0, r.stdout + r.stderr
    npz = tmp_path / registry.get_model_default_weights_file(name)
    assert npz.exists()

    partition = [(1, 4), (5, 8)]
    dirs = ckpt.save_stage_checkpoints(name, str(npz), str(tmp_path / "st"),
                                       partition)
    assert len(dirs) == 2
    entry = registry.get_model_entry(name)
    with np.load(npz) as weights:
        for i, (l, r) in enumerate(partition):
            sc = registry.make_shard_config(name, l, r)
            expect = entry.family.load_params(entry.config, sc, weights)
            got = ckpt.load_stage_checkpoint(str(tmp_path / "st"), i)
            _assert_trees_equal(expect, got)

    # manifest guards against restoring under a different schedule
    root = str(tmp_path / "st")
    assert ckpt.read_manifest(root)["partition"] == [[1, 4], [5, 8]]
    ckpt.check_stage_compatible(root, name, 0, (1, 4))  # ok
    with pytest.raises(ValueError, match="does not match"):
        ckpt.check_stage_compatible(root, name, 0, (1, 5))
    with pytest.raises(ValueError, match="model"):
        ckpt.check_stage_compatible(root, "pipeedge/test-tiny-bert", 0, (1, 4))
