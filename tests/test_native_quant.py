"""Native quant codec vs the XLA ops.

Interop contract (native_quant.py docstring): wire payloads (packed words,
scale, shift) are BIT-IDENTICAL to the XLA encoder for the wire bitwidths
(<= 16); decodes agree to f32 rounding — the quantization error itself
(scale / 2^bit) is orders of magnitude larger than 1-ulp differences from
XLA's fused multiply-adds."""
import jax.numpy as jnp
import numpy as np
import pytest

from pipeedge_tpu.ops import native_quant
from pipeedge_tpu.ops import quant as quant_ops

# Availability triggers an on-demand cmake build; checking it at collection
# time would build the codec for every unrelated test run, so gate via a
# module-scoped autouse fixture that only runs when these tests are selected.
@pytest.fixture(scope="module", autouse=True)
def _require_native_codec():
    if not native_quant.available():
        pytest.skip("native quant codec not built")

BITS = [2, 3, 4, 6, 8, 16]


def _assert_decodes_agree(a, b, scale):
    # f32-rounding agreement: tiny relative to the quantization step
    np.testing.assert_allclose(a, b, rtol=2e-6,
                               atol=2e-6 * float(np.max(scale)) + 1e-12)


@pytest.mark.parametrize("bit", BITS)
@pytest.mark.parametrize("shape", [(4, 33), (2, 7, 5), (8, 13, 48)])
def test_native_encode_matches_xla_bitwise(bit, shape):
    x = np.random.default_rng(bit).normal(size=shape).astype(np.float32)
    ref = quant_ops.tensor_encode_outerdim(jnp.asarray(x), bit)
    packed, scale, shift = native_quant.encode_outerdim(x, bit)
    np.testing.assert_array_equal(packed, np.asarray(ref.data))
    np.testing.assert_array_equal(scale, np.asarray(ref.scale))
    np.testing.assert_array_equal(shift, np.asarray(ref.shift))


@pytest.mark.parametrize("bit", BITS)
def test_cross_decode(bit):
    """XLA-encoded payloads decode natively and vice versa."""
    shape = (4, 6, 9)
    x = np.random.default_rng(7).normal(size=shape).astype(np.float32)
    enc = quant_ops.tensor_encode_outerdim(jnp.asarray(x), bit)
    native_dec = native_quant.decode_outerdim(
        np.asarray(enc.data), np.asarray(enc.scale), np.asarray(enc.shift),
        shape, bit)
    xla_dec = np.asarray(quant_ops.tensor_decode_outerdim(enc))
    _assert_decodes_agree(native_dec, xla_dec, enc.scale)

    packed, scale, shift = native_quant.encode_outerdim(x, bit)
    enc2 = quant_ops.QuantizedTensor(
        data=jnp.asarray(packed), scale=jnp.asarray(scale),
        shift=jnp.asarray(shift), shape=shape, bit=bit)
    _assert_decodes_agree(np.asarray(quant_ops.tensor_decode_outerdim(enc2)),
                          native_quant.decode_outerdim(packed, scale, shift,
                                                       shape, bit), scale)


def test_roundtrip_error_bound():
    x = np.random.default_rng(0).normal(size=(4, 257)).astype(np.float32)
    for bit in BITS:
        packed, scale, shift = native_quant.encode_outerdim(x, bit)
        dec = native_quant.decode_outerdim(packed, scale, shift, x.shape, bit)
        rng = float(scale.max())
        # uniform quantization: error <= scale / (2^bit - 1) / 2 + fp slack
        bound = rng / ((1 << bit) - 1) / 2 + 1e-5 * rng
        assert np.abs(dec - x).max() <= bound


def test_constant_input_zero_range():
    x = np.full((3, 17), 2.5, np.float32)
    packed, scale, shift = native_quant.encode_outerdim(x, 4)
    assert (scale == 0).all() and (shift == 2.5).all()
    dec = native_quant.decode_outerdim(packed, scale, shift, x.shape, 4)
    np.testing.assert_array_equal(dec, x)


def test_zero_size_inner_dim():
    x = np.zeros((4, 0, 5), np.float32)
    packed, scale, shift = native_quant.encode_outerdim(x, 8)
    assert packed.shape == (4, 0) and (scale == 0).all() and (shift == 0).all()
    dec = native_quant.decode_outerdim(packed, scale, shift, x.shape, 8)
    assert dec.shape == x.shape


def test_rejects_out_of_range_bitwidths():
    x = np.zeros((2, 8), np.float32)
    for bad in (0, 17, 32):
        with pytest.raises(ValueError):
            native_quant.encode_outerdim(x, bad)
