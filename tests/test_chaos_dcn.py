"""Chaos acceptance for the fault-tolerance layer: loopback DCN fleets with
deterministic fault injection (DCN_CHAOS, pipeedge_tpu/comm/chaos.py).

The quick (not-slow) pair is the CI chaos smoke: kill a stage rank
mid-round and recover via failover; kill with no spare capacity and abort
naming the dead rank. The full kill/delay/hang matrix — including the
bit-identical replay comparison against a no-fault run — is `slow`."""
import json
import os
import signal
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.fleet   # every test spawns OS-process fleets

_MODEL = "pipeedge/test-tiny-vit"


def _free_ports(n):
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _run_chaos_fleet(tmp_path, world, chaos=None, victim=1, extra=(),
                     batch=24, timeout=240, env_extra=None):
    """Launch a `world`-rank failover-mode fleet, arming `chaos` in the
    victim's env (`env_extra` lands in EVERY rank's env). Returns
    (data rc, data output, [worker outputs])."""
    addrs = ",".join(f"127.0.0.1:{p}" for p in _free_ports(world))
    common = [sys.executable, os.path.join(REPO, "runtime.py")]
    opts = ["-c", "dcn", "--platform", "cpu", "-m", _MODEL,
            "-b", str(batch), "-u", "4", "-pt", "1,4,5,8", "-q", "0,0",
            "-r", "0,1", "--dcn-addrs", addrs, "--sched-timeout", "120",
            "--on-peer-death", "failover", *extra]
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               DCN_CONNECT_TIMEOUT="30", **(env_extra or {}))
    dirs = []
    for r in range(world):
        d = tmp_path / f"rank{r}"
        d.mkdir(parents=True, exist_ok=True)
        dirs.append(d)
    workers = []
    for r in range(1, world):
        wenv = dict(env, DCN_CHAOS=chaos) if (chaos and r == victim) \
            else env
        workers.append(subprocess.Popen(
            common + [str(r), str(world)] + opts, cwd=dirs[r], env=wenv,
            text=True, stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    try:
        data = subprocess.run(common + ["0", str(world)] + opts,
                              cwd=dirs[0], env=env, capture_output=True,
                              text=True, timeout=timeout)
        wouts = []
        for w in workers:
            try:
                wouts.append(w.communicate(timeout=60)[0])
            except subprocess.TimeoutExpired:
                wouts.append("<no output: killed>")
    finally:
        for w in workers:
            try:
                # SIGKILL, not terminate: a hang-chaos victim is SIGSTOPped
                # and ignores everything else
                os.kill(w.pid, signal.SIGKILL)
            except OSError:
                pass
            w.wait()
    return data, wouts, dirs


def test_chaos_smoke_kill_stage_failover(tmp_path):
    """CI chaos smoke: kill the last stage at its 3rd result send; the
    spare rank takes the stage over, unacknowledged microbatches replay,
    and the run completes with every result delivered exactly once."""
    data, wouts, dirs = _run_chaos_fleet(
        tmp_path, world=3, chaos="kill@3",
        extra=["--save-results", "results.npz"])
    assert data.returncode == 0, data.stdout + data.stderr
    out = data.stdout + data.stderr
    assert "entering failover" in out
    assert "moves rank 1 -> 2" in out
    assert "replaying" in out and "unacknowledged" in out
    assert "latency_sec=" in data.stdout
    # the victim died to the chaos kill; the spare rebuilt stage 1
    assert "chaos: killing this process" in wouts[0]
    assert "stage 1: layers [5, 8]" in wouts[1]
    # all 6 microbatches delivered exactly once
    results = np.load(dirs[0] / "results.npz")
    assert len(results.files) == 6


def test_chaos_no_spare_capacity_aborts_naming_rank(tmp_path):
    """Failover mode with nothing to fail over TO: the fleet must still
    abort cleanly, naming the dead rank (the pre-failover semantics)."""
    data, wouts, _ = _run_chaos_fleet(tmp_path, world=2, chaos="kill@2",
                                      batch=16)
    assert data.returncode not in (None, 0)
    out = data.stdout + data.stderr
    assert "no spare capacity" in out and "rank 1 died" in out


def test_chaos_restart_rejoins_and_heals(tmp_path):
    """CI chaos-restart smoke (kill -> failover -> restart -> heal): the
    last stage dies at its 3rd send and re-execs 1.5s later as a new
    incarnation (DCN_EPOCH+1). The fleet fails over to a spare and
    replays; the restarted rank passes the JOIN admission handshake
    (rejoin event at the data rank); and with --on-peer-rejoin heal the
    pre-failure partition is restored at a round boundary — the final
    partition runs on the ORIGINAL ranks, every round's results exactly
    once.

    Was flaky (fails ~1 in 3 on the pristine tree): when detection of
    the death ran late enough that the restarted incarnation's JOIN was
    admitted FIRST, the victim moved dead_ranks -> benched_ranks before
    the round loop's 0.5s poll ever saw a dead scheduled rank, so
    `death_hits_schedule()` stayed false, the failover re-plan never
    ran, and the round waited out the full --sched-timeout for
    microbatches that died with the old incarnation ("pipeline
    delivered 2/16 results within 120.0s"). Fixed in runtime.py:
    `death_hits_schedule` now also counts a SCHEDULED rank that sits in
    benched_ranks while a death episode is open — a freshly rejoined
    incarnation holds no stage state, so the round must fail over to a
    spare either way (the heal then restores it at the boundary)."""
    data, wouts, dirs = _run_chaos_fleet(
        tmp_path, world=4, chaos="restart@3:1500", batch=16,
        extra=["--rounds", "3", "--on-peer-rejoin", "heal",
               "--save-results", "results.npz"])
    assert data.returncode == 0, data.stdout + data.stderr
    out = data.stdout + data.stderr
    # the failover leg ran (spare took the stage over)
    assert "moves rank 1 -> 2" in out
    # the restarted incarnation was admitted exactly once...
    assert out.count("rejoin_rank=1") == 1
    assert "epoch=1" in out
    # ...and the heal restored the pre-failure placement with a finite
    # time-to-full-capacity
    assert "heal_round=" in out
    heal_line = [ln for ln in data.stdout.splitlines()
                 if ln.startswith("heal_round=")][0]
    assert "ranks=0,1" in heal_line
    assert "time_to_full_capacity_s=" in heal_line
    # the victim really died and came back as epoch 1
    assert "chaos: killing this process" in wouts[0]
    assert "re-exec as epoch 1" in wouts[0]
    assert "JOIN announced" in wouts[0]
    # 4 microbatches x 3 rounds, exactly once each
    results = np.load(dirs[0] / "results.npz")
    assert len(results.files) == 12


@pytest.mark.slow
def test_chaos_restart_heal_bit_identical(tmp_path):
    """The healed run's outputs are bit-identical to a fault-free run of
    the same 3 rounds: spare substitution keeps the partition, the heal
    restores the original placement, and the epoch-aware ledger delivers
    every microbatch exactly once."""
    fault, _, fdirs = _run_chaos_fleet(
        tmp_path / "fault", world=4, chaos="restart@3:1500", batch=16,
        extra=["--rounds", "3", "--on-peer-rejoin", "heal",
               "--save-results", "results.npz"])
    clean, _, cdirs = _run_chaos_fleet(
        tmp_path / "clean", world=4, chaos=None, batch=16,
        extra=["--rounds", "3", "--on-peer-rejoin", "heal",
               "--save-results", "results.npz"])
    assert fault.returncode == 0, fault.stdout + fault.stderr
    assert clean.returncode == 0, clean.stdout + clean.stderr
    got = np.load(fdirs[0] / "results.npz")
    want = np.load(cdirs[0] / "results.npz")
    assert sorted(got.files) == sorted(want.files)
    for k in got.files:
        assert got[k].dtype == want[k].dtype
        np.testing.assert_array_equal(got[k], want[k])


@pytest.mark.slow
def test_chaos_restart_spare_mode_keeps_substitution(tmp_path):
    """--on-peer-rejoin spare: the restarted rank is re-admitted as idle
    capacity but its old stage STAYS on the substitute — no heal line,
    later rounds keep the failed-over placement, results exactly once."""
    data, _, dirs = _run_chaos_fleet(
        tmp_path, world=4, chaos="restart@3:1500", batch=16,
        extra=["--rounds", "3", "--on-peer-rejoin", "spare",
               "--save-results", "results.npz"])
    assert data.returncode == 0, data.stdout + data.stderr
    out = data.stdout + data.stderr
    assert "rejoin_rank=1" in out
    assert "heal_round=" not in out
    assert "moves rank 1 -> 2" in out
    results = np.load(dirs[0] / "results.npz")
    assert len(results.files) == 12


@pytest.mark.slow
def test_chaos_flap_survived_with_grace(tmp_path):
    """flap@K:MS inside every rank's reconnect-grace window: a network
    blip, not a death — the run completes with no failover and no
    rejoin (same incarnation throughout), results exactly once."""
    data, _, dirs = _run_chaos_fleet(
        tmp_path, world=3, chaos="flap@2:400", batch=16,
        extra=["--save-results", "results.npz"],
        env_extra={"DCN_RECONNECT_GRACE": "5", "DCN_SEND_RETRIES": "3"})
    assert data.returncode == 0, data.stdout + data.stderr
    out = data.stdout + data.stderr
    assert "chaos: flapping" not in out          # victim's log, not data's
    assert "entering failover" not in out
    assert "rejoin_rank=" not in out
    results = np.load(dirs[0] / "results.npz")
    assert len(results.files) == 4


@pytest.mark.slow
def test_chaos_kill_replay_bit_identical(tmp_path):
    """The exactly-once guarantee, bitwise: a killed-and-failed-over run's
    results are identical to a no-fault run's (same partition on the
    substituted rank, dedupe by microbatch id, in-order delivery)."""
    fault, _, fdirs = _run_chaos_fleet(
        tmp_path / "fault", world=3, chaos="kill@3",
        extra=["--save-results", "results.npz"])
    clean, _, cdirs = _run_chaos_fleet(
        tmp_path / "clean", world=3, chaos=None,
        extra=["--save-results", "results.npz"])
    assert fault.returncode == 0, fault.stdout + fault.stderr
    assert clean.returncode == 0, clean.stdout + clean.stderr
    got = np.load(fdirs[0] / "results.npz")
    want = np.load(cdirs[0] / "results.npz")
    assert sorted(got.files) == sorted(want.files)
    for k in got.files:
        assert got[k].dtype == want[k].dtype
        np.testing.assert_array_equal(got[k], want[k])


@pytest.mark.slow
def test_chaos_hang_detected_by_heartbeat(tmp_path):
    """SIGSTOP a stage rank: its sockets stay open, so only the liveness
    plane (missed heartbeats) can detect it — then failover proceeds as
    for a closed-socket death."""
    data, wouts, _ = _run_chaos_fleet(
        tmp_path, world=3, chaos="hang@3",
        extra=["--heartbeat-interval", "0.5", "--heartbeat-miss", "8"])
    assert data.returncode == 0, data.stdout + data.stderr
    out = data.stdout + data.stderr
    assert "latency_sec=" in data.stdout
    assert "moves rank 1 -> 2" in out
    # SOME survivor detected the hang via missed beats (the hung process
    # never closed a socket)
    fleet_out = out + "".join(wouts)
    assert "missed" in fleet_out and "heartbeats" in fleet_out


@pytest.mark.slow
def test_chaos_delay_is_survived_without_failover(tmp_path):
    """A slow link (every send delayed) is degradation, not death: the
    run completes with no failover."""
    data, _, _ = _run_chaos_fleet(tmp_path, world=3, chaos="delay@1:150",
                                  batch=16)
    assert data.returncode == 0, data.stdout + data.stderr
    assert "latency_sec=" in data.stdout
    assert "entering failover" not in data.stdout + data.stderr


@pytest.mark.slow
def test_chaos_tool_records_time_to_full_capacity(tmp_path):
    """tools/chaos_dcn.py restart experiment end to end: the JSON record
    carries the healing timeline (detect -> rejoin -> healed) with a
    finite time_to_full_capacity_s."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_dcn.py"),
         "--world", "4", "--victim", "1", "--chaos", "restart@3:1500",
         "--rounds", "3", "--on-peer-rejoin", "heal", "--expect", "heal",
         "-b", "16"],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, PYTHONPATH=REPO), cwd=tmp_path)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    assert record["completed"] and not record["timed_out"]
    assert record["rejoin_s"] is not None and record["rejoin_s"] > 0
    assert record["heal_s"] is not None
    assert record["time_to_full_capacity_s"] is not None
    assert record["time_to_full_capacity_s"] > 0
    assert record["rejoin_mode"] == "heal"


@pytest.mark.slow
def test_chaos_tool_records_latencies(tmp_path):
    """tools/chaos_dcn.py end to end: runs the kill experiment, asserts
    recovery, and emits the detection/recovery-latency JSON record."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_dcn.py"),
         "--world", "3", "--victim", "1", "--chaos", "kill@3"],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, PYTHONPATH=REPO), cwd=tmp_path)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    assert record["completed"] and not record["timed_out"]
    assert record["detect_s"] is not None and record["detect_s"] > 0
    assert record["recover_s"] is not None and record["recover_s"] > 0
    assert record["replayed"] >= 1
